"""Query-shaped benchmarks (BASELINE.json configs #2/#3 scaffolding).

Supplementary to the driver-run bench.py (which stays single-metric):
measures TPC-H q1 (filter -> projected arithmetic -> groupby -> sort) and a
fact-dim inner join + agg at 4M fact rows on the current default device,
with the tunnel-safe protocol from BASELINE.md (chained data dependencies,
host-read fencing, exact-composition warmup).

Run: python benchmarks/bench_queries.py

``--metrics-out PATH`` tees every emitted JSON line (bench metrics,
stream/dist_stream/recovery/dist_recovery records, the regress report) to ``PATH``
as JSONL in addition to stdout — the machine-readable artifact a CI lane
archives.  ``--regress`` appends a ``regress`` JSON line comparing the
freshest ``SRT_METRICS_HISTORY`` record per plan fingerprint against the
per-metric best of the earlier records (obs/regress.py) and exits
nonzero on any breach beyond ``SRT_REGRESS_TOL``.

``--live`` additionally times the ETL stream shape with the live
telemetry stack fully on (``SRT_METRICS=1``, exporter scraped at 20 Hz)
against the same stream with telemetry off and appends a
``live_overhead`` JSON line (base/live wall seconds, overhead fraction)
— the record pinning the registry's near-zero hot-path cost.

``--flight`` additionally times the same ETL stream shape with the
flight recorder live against a metered baseline whose recorder feed is
a no-op (both passes ``SRT_METRICS=1``, so the line isolates the ring
appends themselves), then times one postmortem bundle dump.  Appends a
``flight_recorder`` JSON line (base/flight wall seconds, overhead
fraction, sustained events/sec, bundle write seconds) and exits nonzero
when the measured overhead busts the recorder's 2% budget.

``--capacity`` additionally times the same ETL stream shape with the
capacity accountant live against a metered baseline whose ``feed_*``
hooks are no-ops (both passes ``SRT_METRICS=1``, so the line isolates
the window appends themselves), then runs one advisor evaluation over
the window those runs fed.  Appends a ``capacity`` JSON line (base/
capacity wall seconds, overhead fraction, busy fraction, effective
concurrency, advisor verdict) and exits nonzero when the measured
overhead busts the accountant's 2% budget.

``--workload`` additionally times a deliberately-overlapping two-plan
mini-bank (shared filter+project prefix, divergent aggregations) with
the workload analyzer's completion feed live against a metered baseline
whose ``feed_*`` hooks are no-ops, then runs one workload evaluation
over the window those runs fed.  Appends a ``workload`` JSON line (top
op hotspot, top subplan overlap candidate, muted-vs-live overhead) and
exits nonzero when the measured overhead busts the analyzer's 2% budget.

``--faults`` additionally arms a deterministic HBM-OOM injection
(``SRT_FAULT=oom:materialize:1`` unless the env already sets a spec),
runs one mesh join+agg with a shard-targeted dist-dispatch OOM recovered
by the mesh ladder (``dist_recovery`` JSON line: shards, recovered
wall), and appends a ``recovery`` JSON line (retries / splits /
evictions / backoff / faults injected, plus the ``dist`` block) — the
bench-trajectory proof that the resilience ladder engages and costs
what it claims.

``--plan-opt`` replaces the default lanes with the adaptive-optimizer
lane: the whole TPC-DS bank runs against the ``SRT_PLAN_OPT=0`` oracle
and the optimized pass, and ONE ``plan_opt`` JSON line records wall
seconds, bound input columns, traced step counts, per-rule rewrite
totals, bit-identity, and whether the history-warmed rerun closed the
telemetry feedback loop.  Exits nonzero on any parity divergence.

``--kernels`` replaces the default lanes with the Pallas-kernel lane:
each registered kernel (join, groupby, decode, rows) runs its
representative workload against the ``SRT_KERNELS``-off jnp oracle and
ONE ``kernels`` JSON line records per-kernel oracle/kernel wall
seconds, delta, measured speedup (fed to the kernel registry, hence
the workload advisor), parity, and invocation counts — exits nonzero
on any parity loss or a kernel that never fired.  Off-TPU the kernels
run in Pallas interpret mode (path coverage, not a speedup claim).

``--serving`` replaces the default lanes with the concurrent-serving
lane: a closed-loop mixed 40-query load (one-shot + streaming plans,
repeated fingerprints) over TPC-DS data through ``serve.submit``, each
result checked bit-identical to the sequential executors, emitting ONE
``serving`` JSON line (sustained qps, p50/p99 latency, result-cache hit
rate, admission rejects).  Exits nonzero on any parity failure.

``--spill`` replaces the default lanes with the out-of-core lane: a
streaming combine group-by runs once unconstrained (the ``SRT_SPILL=0``
oracle) and once under a deliberately tiny ``SRT_SERVE_HBM_BUDGET``
with ``SRT_SPILL=1`` forcing every paged partition through the Parquet
disk tier, and ONE ``spill`` JSON line records both wall times, bytes
paged out/in, page counts, spill files, and page-in seconds.  Exits
nonzero on parity loss or when nothing actually paged (a lane that
silently measures the oracle twice is a lane failure).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N = 4_000_000
N_DIM = 10_000
REPS = 5

#: ``--metrics-out`` sink (an open text file), or None for stdout-only.
_METRICS_OUT = None


def emit(line) -> None:
    """Print one bench JSON line, teeing it to ``--metrics-out``.

    Accepts a pre-serialized JSON string (the ``bench_line`` helpers) or
    a dict (serialized here with sorted keys).  The tee is flushed per
    line so a killed bench still leaves every completed record on disk.
    """
    if not isinstance(line, str):
        line = json.dumps(line, sort_keys=True)
    print(line)
    if _METRICS_OUT is not None:
        _METRICS_OUT.write(line + "\n")
        _METRICS_OUT.flush()


def main():
    import jax
    import jax.numpy as jnp

    import spark_rapids_tpu as srt
    from spark_rapids_tpu import dtypes as dt
    from spark_rapids_tpu import ops
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.ops.binary import binary_op

    rng = np.random.default_rng(7)
    lineitem = srt.Table([
        ("flag", Column.from_numpy(rng.integers(0, 3, N).astype(np.int8))),
        ("status", Column.from_numpy(rng.integers(0, 2, N).astype(np.int8))),
        ("qty", Column.from_numpy(rng.integers(1, 51, N).astype(np.int64))),
        ("price", Column.from_numpy(rng.uniform(900, 105000, N))),
        ("disc", Column.from_numpy(np.round(rng.uniform(0, 0.1, N), 2))),
        ("tax", Column.from_numpy(np.round(rng.uniform(0, 0.08, N), 2))),
        ("shipdate", Column.from_numpy(rng.integers(8000, 11000, N).astype(np.int32))),
    ])

    def q1(table, bump):
        t = srt.Table(list(table.items())).with_column(
            "qty", binary_op(table["qty"], bump, "add"))
        pred = binary_op(t["shipdate"], 10_500, "le")
        t = ops.apply_boolean_mask(t, pred)
        disc_price = binary_op(t["price"], binary_op(1.0, t["disc"], "sub"), "mul")
        charge = binary_op(disc_price, binary_op(1.0, t["tax"], "add"), "mul")
        t = t.with_column("disc_price", disc_price).with_column("charge", charge)
        agg = ops.groupby_agg(t, ["flag", "status"],
                              [("qty", "sum", "sum_qty"),
                               ("price", "sum", "sum_price"),
                               ("disc_price", "sum", "sum_disc_price"),
                               ("charge", "sum", "sum_charge"),
                               ("qty", "mean", "avg_qty"),
                               ("disc", "mean", "avg_disc"),
                               ("qty", "count", "n")])
        return ops.sort_by(agg, ["flag", "status"])

    # warm exact composition, then chained reps
    out = q1(lineitem, 0)
    bump = int(np.asarray(out["n"].data)[0]) & 1
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = q1(lineitem, bump)
        bump = int(np.asarray(out["n"].data)[0]) & 1
    dt_q1 = (time.perf_counter() - t0) / REPS
    emit(json.dumps({"metric": "tpch_q1_4M", "value": round(N / dt_q1, 1),
                      "unit": "rows/sec"}))

    fact_key = rng.integers(0, N_DIM, N).astype(np.int64)
    fact = srt.Table([
        ("k", Column.from_numpy(fact_key)),
        ("rev", Column.from_numpy(rng.uniform(1, 1000, N))),
    ])
    dim = srt.Table([
        ("k", Column.from_numpy(np.arange(N_DIM, dtype=np.int64))),
        ("cat", Column.from_numpy(rng.integers(0, 100, N_DIM).astype(np.int32))),
    ])

    def join_agg(f, bump):
        f2 = srt.Table(list(f.items())).with_column(
            "rev", binary_op(f["rev"], float(bump), "add"))
        j = ops.join(f2, dim, on=["k"], how="inner")
        return ops.groupby_agg(j, ["cat"], [("rev", "sum", "rev_sum"),
                                            ("rev", "count", "n")])
    out = join_agg(fact, 0)
    bump = int(np.asarray(out["n"].data)[0]) & 1
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = join_agg(fact, bump)
        bump = int(np.asarray(out["n"].data)[0]) & 1
    dt_j = (time.perf_counter() - t0) / REPS
    emit(json.dumps({"metric": "fact_dim_join_agg_4M",
                      "value": round(N / dt_j, 1), "unit": "rows/sec"}))

    bench_plans(lineitem, fact, dim)
    bench_stream(lineitem)
    bench_dist_stream(lineitem)
    if "--live" in sys.argv:
        bench_live(lineitem)
    if "--flight" in sys.argv:
        bench_flight(lineitem)
    if "--capacity" in sys.argv:
        bench_capacity(lineitem)
    if "--workload" in sys.argv:
        bench_workload(lineitem)

    from spark_rapids_tpu.config import metrics_enabled
    if metrics_enabled():
        from spark_rapids_tpu.obs import bench_line
        emit(bench_line("metrics"))
        emit(bench_line("cache"))
    if "--faults" in sys.argv:
        from spark_rapids_tpu.obs import bench_line
        bench_dist_recovery(fact, dim)
        emit(bench_line("recovery"))
    timeline_path = _timeline_arg()
    if timeline_path is not None:
        from spark_rapids_tpu.obs import timeline
        payload = timeline.export_chrome_trace(timeline_path)
        emit(json.dumps({"metric": "timeline", "path": timeline_path,
                          "events": len(payload["traceEvents"])},
                         sort_keys=True))


def bench_dist_recovery(fact, dim, n=200_000):
    """``--faults`` only: one mesh join+agg with a shard-targeted HBM-OOM
    armed at the dist dispatch, recovered by the mesh ladder — proves the
    dist rungs engage (and what they cost) on whatever mesh the bench
    runs on, and moves the ``dist`` block of the recovery JSON line."""
    import os

    from spark_rapids_tpu import Column, Table
    from spark_rapids_tpu.exec import plan
    from spark_rapids_tpu.parallel import make_mesh, shard_table
    from spark_rapids_tpu.resilience import recovery_stats, reset_faults

    mesh = make_mesh()
    P = mesh.devices.size
    sub = Table([(nm, Column(data=c.data[:n],
                             validity=None if c.validity is None
                             else c.validity[:n], dtype=c.dtype))
                 for nm, c in fact.items()])
    p = (plan()
         .join_broadcast(dim.rename({"k": "dk"}), left_on="k",
                         right_on="dk")
         .groupby_agg(["cat"], [("rev", "sum", "rev_sum"),
                                ("rev", "count", "cnt")],
                      domains={"cat": (0, 99)}))
    d = shard_table(sub, mesh)
    want = p.run_dist(d, mesh).to_pydict()       # no-fault golden (warm)

    saved = os.environ.get("SRT_FAULT")
    os.environ["SRT_FAULT"] = f"oom:dist-dispatch:1:shard={P - 1}"
    reset_faults()
    before = recovery_stats().snapshot()
    t0 = time.perf_counter()
    try:
        got = p.run_dist(d, mesh).to_pydict()
    finally:
        if saved is None:
            os.environ.pop("SRT_FAULT", None)
        else:
            os.environ["SRT_FAULT"] = saved
        reset_faults()
    elapsed = time.perf_counter() - t0
    assert got == want, "faulted dist run diverged from the golden"
    delta = recovery_stats().delta(before)
    emit(json.dumps({"metric": "dist_recovery", "rows": n, "shards": P,
                      "recovered_seconds": round(elapsed, 6),
                      "dist_retries": int(delta["dist_retries"]),
                      "dist_evictions": int(delta["dist_evictions"])},
                     sort_keys=True))


def _path_arg(flag: str):
    """``<flag> PATH``: the path following ``flag`` in argv, or None."""
    if flag not in sys.argv:
        return None
    i = sys.argv.index(flag)
    if i + 1 >= len(sys.argv):
        raise SystemExit(f"{flag} requires an output path")
    return sys.argv[i + 1]


def _timeline_arg():
    """``--timeline out.json``: Chrome-trace export path, or None."""
    return _path_arg("--timeline")


def run_regress_gate():
    """``--regress``: emit the regress JSON line and exit nonzero on any
    tolerance breach (obs/regress.py over ``SRT_METRICS_HISTORY``)."""
    from spark_rapids_tpu.obs import bench_line
    line = bench_line("regress")
    emit(line)
    report = json.loads(line)
    breaches = report.get("breaches") or []
    if breaches:
        raise SystemExit(
            f"perf regression: {len(breaches)} breach(es) beyond "
            f"tolerance {report.get('tolerance')} — see the regress "
            f"JSON line above")


def _bench_compiled(name, p, table, chain_col, leaf_col, reps=10):
    """Device-chained throughput of a compiled plan (zero host syncs in
    the loop: each iteration's input derives from the previous output on
    device) plus the materializing ``run`` form (one sync)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.exec.compile import _bind, _compiled_for

    n = table.num_rows
    # _bind routes through the shape-bucketing layer (exec/bucketing.py),
    # so the chained loop exercises the padded program exactly as plan
    # runs do and the cache/bucketing JSON line reflects the bench.
    bound = _bind(p, table)
    fn = _compiled_for(bound)

    @jax.jit
    def perturb(x, leaf):
        return x + (leaf.ravel()[-1:].astype(x.dtype) * 0 +
                    (leaf.ravel()[-1:] != 0).astype(x.dtype))

    cols = dict(bound.exec_cols)
    out_cols, _ = fn(cols, bound.side_inputs, bound.init_sel)
    leaf = out_cols[leaf_col].data
    cols[chain_col] = Column(data=perturb(cols[chain_col].data, leaf),
                             dtype=cols[chain_col].dtype)
    out_cols, _ = fn(cols, bound.side_inputs, bound.init_sel)
    leaf = out_cols[leaf_col].data
    _ = np.asarray(leaf[-1:])
    t0 = time.perf_counter()
    for _ in range(reps):
        cols[chain_col] = Column(data=perturb(cols[chain_col].data, leaf),
                                 dtype=cols[chain_col].dtype)
        out_cols, _ = fn(cols, bound.side_inputs, bound.init_sel)
        leaf = out_cols[leaf_col].data
    _ = np.asarray(leaf[-1:])
    dt = (time.perf_counter() - t0) / reps
    emit(json.dumps({"metric": f"{name}_plan_chained",
                      "value": round(n / dt, 1), "unit": "rows/sec"}))

    p.run(table)
    t0 = time.perf_counter()
    for _ in range(3):
        p.run(table)
    dt = (time.perf_counter() - t0) / 3
    emit(json.dumps({"metric": f"{name}_plan_run",
                      "value": round(n / dt, 1), "unit": "rows/sec"}))


def bench_stream(lineitem, n_batches=8):
    """Streaming executor over the q1 ETL prefix (filter + projected
    arithmetic — row-shaped outputs, so same-bucket donation recycles
    HBM).  Each batch is constructed from host numpy slices inside the
    feed, so real H2D decode overlaps device compute; the stream_exec
    JSON line (wall vs. serial phase sum, overlap ratio, donation hits)
    is the pipeline-efficiency record future PRs diff."""
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.exec import col, plan, run_plan_stream
    from spark_rapids_tpu.obs import bench_stream_line, last_stream_metrics

    host = {n: np.asarray(c.data) for n, c in lineitem.items()}
    rows = lineitem.num_rows
    step = rows // n_batches

    def feed():
        for i in range(n_batches):
            lo, hi = i * step, min((i + 1) * step, rows)
            yield srt.Table([
                (n, Column.from_numpy(v[lo:hi])) for n, v in host.items()])

    p = (plan()
         .filter(col("shipdate") <= 10_500)
         .with_columns(disc_price=col("price") * (1 - col("disc")))
         .with_columns(charge=col("disc_price") * (1 + col("tax"))))

    for _ in run_plan_stream(p, feed(), prefetch=True):   # warm compile
        pass
    t0 = time.perf_counter()
    for _ in run_plan_stream(p, feed(), prefetch=True):
        pass
    dt_s = time.perf_counter() - t0
    emit(json.dumps({"metric": "tpch_q1_etl_stream_4M",
                      "value": round(rows / dt_s, 1), "unit": "rows/sec"}))
    emit(bench_stream_line())


def bench_live(lineitem, n_batches=8):
    """``--live``: wall-clock cost of the live-telemetry stack on the ETL
    stream shape — registry counters + live-query heartbeats + an
    exporter being scraped, against the same stream with everything off.
    Emits the ``live_overhead`` JSON line the acceptance gate reads
    (overhead_frac stays within a few percent); the stricter
    zero-extra-work-when-off contract is structural (NULL_LIVE identity)
    and pinned by tests/test_live.py rather than timed here."""
    import os
    import threading
    import urllib.request

    import spark_rapids_tpu as srt
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.exec import col, plan, run_plan_stream

    host = {n: np.asarray(c.data) for n, c in lineitem.items()}
    rows = lineitem.num_rows
    step = rows // n_batches

    def feed():
        for i in range(n_batches):
            lo, hi = i * step, min((i + 1) * step, rows)
            yield srt.Table([
                (n, Column.from_numpy(v[lo:hi])) for n, v in host.items()])

    p = (plan()
         .filter(col("shipdate") <= 10_500)
         .with_columns(disc_price=col("price") * (1 - col("disc")))
         .with_columns(charge=col("disc_price") * (1 + col("tax"))))

    def run():
        for _ in run_plan_stream(p, feed(), prefetch=True):
            pass

    def timed(reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            run()
            best = min(best, time.perf_counter() - t0)
        return best

    had = os.environ.pop("SRT_METRICS", None)
    try:
        run()                        # warm compile, telemetry off
        base_s = timed()
    finally:
        if had is not None:
            os.environ["SRT_METRICS"] = had

    from spark_rapids_tpu.obs import server
    os.environ["SRT_METRICS"] = "1"
    srv = server.start(port=0)
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            try:
                with urllib.request.urlopen(srv.url + "/metrics",
                                            timeout=5) as r:
                    r.read()
                with urllib.request.urlopen(srv.url + "/queries",
                                            timeout=5) as r:
                    r.read()
            except Exception:
                pass
            stop.wait(0.05)

    th = threading.Thread(target=scraper, daemon=True)
    th.start()
    try:
        run()                        # warm the metered path
        live_s = timed()
    finally:
        stop.set()
        th.join(timeout=5)
        server.stop()
        if had is None:
            os.environ.pop("SRT_METRICS", None)
        else:
            os.environ["SRT_METRICS"] = had

    emit(json.dumps({
        "metric": "live_overhead",
        "base_seconds": round(base_s, 6),
        "live_seconds": round(live_s, 6),
        "overhead_frac": round(max(live_s - base_s, 0.0) / base_s, 6)},
        sort_keys=True))


#: The flight recorder's measured-overhead budget (fraction of a
#: metered run) — the contract obs/flight.py documents and CI enforces.
FLIGHT_OVERHEAD_BUDGET = 0.02


def bench_flight(lineitem, n_batches=8):
    """``--flight``: marginal wall-clock cost of the flight recorder on
    the metered ETL stream shape.  Both passes run with ``SRT_METRICS=1``
    — the baseline swaps the recorder feed (``flight.record`` /
    ``flight.trace_span``) for no-ops so the comparison isolates the
    ring appends from the rest of the telemetry stack.  Also reports the
    ring's sustained events/sec and the latency of one postmortem
    ``bundle.dump`` (the write a failing query pays).  Emits the
    ``flight_recorder`` JSON line and exits nonzero when the overhead
    busts :data:`FLIGHT_OVERHEAD_BUDGET`."""
    import os
    import shutil
    import tempfile

    import spark_rapids_tpu as srt
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.exec import col, plan, run_plan_stream
    from spark_rapids_tpu.obs import bundle, flight, last_stream_metrics

    host = {n: np.asarray(c.data) for n, c in lineitem.items()}
    rows = lineitem.num_rows
    step = rows // n_batches

    def feed():
        for i in range(n_batches):
            lo, hi = i * step, min((i + 1) * step, rows)
            yield srt.Table([
                (n, Column.from_numpy(v[lo:hi])) for n, v in host.items()])

    p = (plan()
         .filter(col("shipdate") <= 10_500)
         .with_columns(disc_price=col("price") * (1 - col("disc")))
         .with_columns(charge=col("disc_price") * (1 + col("tax"))))

    def run():
        for _ in run_plan_stream(p, feed(), prefetch=True):
            pass

    def timed(reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            run()
            best = min(best, time.perf_counter() - t0)
        return best

    had = os.environ.get("SRT_METRICS")
    os.environ["SRT_METRICS"] = "1"
    real_record, real_span = flight.record, flight.trace_span

    def noop(*a, **k):
        return None

    try:
        flight.record = flight.trace_span = noop
        run()                        # warm metered compile, recorder mute
        base_s = timed()

        flight.record, flight.trace_span = real_record, real_span
        flight.reset()
        run()                        # warm the recorder-live path
        flight_s = timed()

        # Events/sec from one dedicated run: the timed() best-of keeps
        # only a wall number, so measure the ring fill against its own
        # wall (each stream run is its own query id / ring).
        t0 = time.perf_counter()
        run()
        ev_dt = time.perf_counter() - t0
        qm = last_stream_metrics()
        ring = flight.ring_for(qm.query_id, create=False)
        st = ring.stats() if ring is not None else {
            "events_recorded": 0, "events_dropped": 0}
        events = st["events_recorded"] + st["events_dropped"]

        # One postmortem dump against a throwaway dir: the write latency
        # a failing query pays on top of its failure.
        tmp = tempfile.mkdtemp(prefix="srt-flight-bench-")
        had_dir = os.environ.get("SRT_BUNDLE_DIR")
        try:
            os.environ["SRT_BUNDLE_DIR"] = tmp
            t0 = time.perf_counter()
            path = bundle.dump("failure", qm=qm,
                               error=RuntimeError("bench probe"))
            bundle_s = time.perf_counter() - t0
            assert path is not None, "bench bundle dump wrote nothing"
        finally:
            if had_dir is None:
                os.environ.pop("SRT_BUNDLE_DIR", None)
            else:
                os.environ["SRT_BUNDLE_DIR"] = had_dir
            shutil.rmtree(tmp, ignore_errors=True)
    finally:
        flight.record, flight.trace_span = real_record, real_span
        if had is None:
            os.environ.pop("SRT_METRICS", None)
        else:
            os.environ["SRT_METRICS"] = had

    over = max(flight_s - base_s, 0.0)
    frac = over / base_s
    emit(json.dumps({
        "metric": "flight_recorder",
        "base_seconds": round(base_s, 6),
        "flight_seconds": round(flight_s, 6),
        "overhead_frac": round(frac, 6),
        "events": events,
        "events_per_sec": round(events / ev_dt, 1) if ev_dt else 0.0,
        "bundle_write_seconds": round(bundle_s, 6)},
        sort_keys=True))
    # Gate like live_overhead, with an absolute floor so sub-10ms timer
    # jitter on a fast baseline cannot flake the lane.
    if frac > FLIGHT_OVERHEAD_BUDGET and over > 0.01:
        raise SystemExit(
            f"flight recorder overhead {frac:.2%} "
            f"({over * 1e3:.1f} ms on a {base_s:.3f}s baseline) exceeds "
            f"the {FLIGHT_OVERHEAD_BUDGET:.0%} budget")


#: The capacity accountant's measured-overhead budget (fraction of a
#: metered run) — the contract obs/capacity.py documents and CI
#: enforces, same shape as the flight recorder's.
CAPACITY_OVERHEAD_BUDGET = 0.02


def bench_capacity(lineitem, n_batches=8):
    """``--capacity``: marginal wall-clock cost of the capacity
    accountant on the metered ETL stream shape, plus one advisor
    evaluation over the window the runs just fed.  Both passes run with
    ``SRT_METRICS=1`` — the baseline swaps every ``capacity.feed_*``
    for no-ops so the comparison isolates the window appends from the
    rest of the telemetry stack.  Emits the ``capacity`` JSON line
    (busy fraction, effective concurrency, advisor verdict, overhead)
    and exits nonzero past :data:`CAPACITY_OVERHEAD_BUDGET`."""
    import os

    import spark_rapids_tpu as srt
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.config import capacity_targets
    from spark_rapids_tpu.exec import col, plan, run_plan_stream
    from spark_rapids_tpu.obs import capacity

    host = {n: np.asarray(c.data) for n, c in lineitem.items()}
    rows = lineitem.num_rows
    step = rows // n_batches

    def feed():
        for i in range(n_batches):
            lo, hi = i * step, min((i + 1) * step, rows)
            yield srt.Table([
                (n, Column.from_numpy(v[lo:hi])) for n, v in host.items()])

    p = (plan()
         .filter(col("shipdate") <= 10_500)
         .with_columns(disc_price=col("price") * (1 - col("disc")))
         .with_columns(charge=col("disc_price") * (1 + col("tax"))))

    def run():
        for _ in run_plan_stream(p, feed(), prefetch=True):
            pass

    def timed_once():
        t0 = time.perf_counter()
        run()
        return time.perf_counter() - t0

    feed_names = [n for n in dir(capacity) if n.startswith("feed_")]
    real_feeds = {n: getattr(capacity, n) for n in feed_names}

    def noop(*a, **k):
        return None

    def mute():
        for n in feed_names:
            setattr(capacity, n, noop)

    def unmute():
        for n, f in real_feeds.items():
            setattr(capacity, n, f)

    had = os.environ.get("SRT_METRICS")
    os.environ["SRT_METRICS"] = "1"
    try:
        mute()
        run()                       # warm metered compile, accountant mute
        unmute()
        capacity.reset()
        run()                       # warm the accountant-live path

        # Interleave muted/live rounds and keep each side's min: the
        # accountant's true cost is a handful of deque appends, far
        # below this workload's run-to-run jitter, and sequential
        # best-of-N passes let slow drift (CPU frequency, cache state,
        # noisy neighbors) land entirely on whichever side ran second.
        base_s = cap_s = float("inf")
        t_loop0 = time.perf_counter()
        for _ in range(7):
            mute()
            base_s = min(base_s, timed_once())
            unmute()
            cap_s = min(cap_s, timed_once())

        # One advisor evaluation over the window the live rounds fed —
        # one-shot (confirm=1): a bench lane has no repeated windows to
        # confirm hysteresis against.
        window = max(time.perf_counter() - t_loop0 + 1.0, 10.0)
        snap = capacity.snapshot(window_s=window)
        candidates = capacity.recommend(snap, capacity_targets())
        recs = capacity.Advisor(confirm=1, clear=1).observe(candidates)
        verdict = capacity.verdict_for(recs if recs else candidates)
    finally:
        for n, f in real_feeds.items():
            setattr(capacity, n, f)
        if had is None:
            os.environ.pop("SRT_METRICS", None)
        else:
            os.environ["SRT_METRICS"] = had

    over = max(cap_s - base_s, 0.0)
    frac = over / base_s
    emit(json.dumps({
        "metric": "capacity",
        "base_seconds": round(base_s, 6),
        "capacity_seconds": round(cap_s, 6),
        "overhead_frac": round(frac, 6),
        "busy_fraction": round(snap["busy"]["dispatch_fraction"], 6),
        "effective_concurrency": round(
            snap["littles_law"]["effective_concurrency"], 6),
        "dispatch_spans": snap["busy"]["dispatch_spans"],
        "advisor_verdict": verdict,
        "recommendations": [r["action"] for r in recs]},
        sort_keys=True))
    # Gate like the flight lane, with the same absolute floor so
    # sub-10ms timer jitter on a fast baseline cannot flake the lane.
    if frac > CAPACITY_OVERHEAD_BUDGET and over > 0.01:
        raise SystemExit(
            f"capacity accountant overhead {frac:.2%} "
            f"({over * 1e3:.1f} ms on a {base_s:.3f}s baseline) exceeds "
            f"the {CAPACITY_OVERHEAD_BUDGET:.0%} budget")


#: The workload analyzer's measured-overhead budget (fraction of a
#: metered run) — the contract obs/workload.py documents and CI
#: enforces, same shape as the capacity accountant's.
WORKLOAD_OVERHEAD_BUDGET = 0.02


def bench_workload(lineitem, rows=1_000_000):
    """``--workload``: marginal wall-clock cost of the workload
    analyzer's completion feed on a deliberately-overlapping mini-bank
    (two one-shot plans sharing a filter+project prefix with divergent
    aggregations — the fragment-cache motivating shape), plus one
    workload evaluation over the window the live rounds fed.  Both
    passes run with ``SRT_METRICS=1`` — the baseline swaps every
    ``workload.feed_*`` for no-ops so the comparison isolates the
    normalize+append feed from the rest of the telemetry stack.  Emits
    the ``workload`` JSON line (top hotspot, top overlap candidate,
    muted-vs-live overhead) and exits nonzero past
    :data:`WORKLOAD_OVERHEAD_BUDGET`."""
    import os

    import spark_rapids_tpu as srt
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.config import workload_topk
    from spark_rapids_tpu.exec import col, plan
    from spark_rapids_tpu.obs import workload

    sub = srt.Table([(nm, Column(data=c.data[:rows],
                                 validity=None if c.validity is None
                                 else c.validity[:rows], dtype=c.dtype))
                     for nm, c in lineitem.items()])

    # Shared filter+project prefix, divergent tails: the canonical
    # overlap-candidate shape the miner must surface.
    prefix = (plan()
              .filter(col("shipdate") <= 10_500)
              .with_columns(disc_price=col("price") * (1 - col("disc"))))
    # Both tails consume the same column set so the optimizer's pruning
    # projection is identical and the shared prefix keeps one
    # fingerprint across both plans (plans=2 in the overlap evidence).
    pa = prefix.groupby_agg(["flag", "status"],
                            [("disc_price", "sum", "rev"),
                             ("qty", "count", "n")])
    pb = prefix.groupby_agg(["status", "flag"],
                            [("disc_price", "max", "top_rev"),
                             ("qty", "sum", "sum_qty")])

    def run():
        pa.run(sub)
        pb.run(sub)

    def timed_once():
        t0 = time.perf_counter()
        run()
        return time.perf_counter() - t0

    feed_names = [n for n in dir(workload) if n.startswith("feed_")]
    real_feeds = {n: getattr(workload, n) for n in feed_names}

    def noop(*a, **k):
        return []

    def mute():
        for n in feed_names:
            setattr(workload, n, noop)

    def unmute():
        for n, f in real_feeds.items():
            setattr(workload, n, f)

    had = os.environ.get("SRT_METRICS")
    os.environ["SRT_METRICS"] = "1"
    try:
        mute()
        run()                       # warm metered compile, analyzer mute
        unmute()
        workload.reset()
        run()                       # warm the analyzer-live path

        # Interleave muted/live rounds and keep each side's min (same
        # discipline as the flight/capacity lanes: the feed's true cost
        # is step normalization + a deque append, far below run jitter).
        base_s = wl_s = float("inf")
        t_loop0 = time.perf_counter()
        for _ in range(5):
            mute()
            base_s = min(base_s, timed_once())
            unmute()
            wl_s = min(wl_s, timed_once())

        # One workload evaluation over the window the live rounds fed —
        # one-shot (confirm=1): a bench lane has no repeated windows.
        window = max(time.perf_counter() - t_loop0 + 1.0, 10.0)
        snap = workload.snapshot(window_s=window)
        candidates = workload.recommend(snap)
        recs = workload.Advisor(confirm=1, clear=1).observe(candidates)
        verdict = workload.verdict_for(recs if recs else candidates)
    finally:
        for n, f in real_feeds.items():
            setattr(workload, n, f)
        if had is None:
            os.environ.pop("SRT_METRICS", None)
        else:
            os.environ["SRT_METRICS"] = had

    hotspots = snap.get("hotspots") or []
    overlaps = snap.get("overlaps") or []
    over = max(wl_s - base_s, 0.0)
    frac = over / base_s
    emit(json.dumps({
        "metric": "workload",
        "base_seconds": round(base_s, 6),
        "workload_seconds": round(wl_s, 6),
        "overhead_frac": round(frac, 6),
        "queries": snap["queries"],
        "plans": snap["plans"],
        "topk": workload_topk(),
        "top_hotspot": hotspots[0] if hotspots else None,
        "top_overlap": overlaps[0] if overlaps else None,
        "advisor_verdict": verdict,
        "recommendations": [r["action"] for r in recs]},
        sort_keys=True))
    # Gate like the flight/capacity lanes, with the same absolute floor
    # so sub-10ms timer jitter on a fast baseline cannot flake the lane.
    if frac > WORKLOAD_OVERHEAD_BUDGET and over > 0.01:
        raise SystemExit(
            f"workload analyzer overhead {frac:.2%} "
            f"({over * 1e3:.1f} ms on a {base_s:.3f}s baseline) exceeds "
            f"the {WORKLOAD_OVERHEAD_BUDGET:.0%} budget")


def bench_dist_stream(lineitem, n_batches=8, batch_rows=200_000):
    """Sharded streaming executor: the q1 group-by prefix driven over the
    mesh with one in-flight window per shard, per-shard partial
    accumulators, and ONE merge collective at stream end.  Emits the
    ``dist_stream`` JSON line (shards, merge collectives, ICI bytes,
    syncs avoided) plus a wall/host-sync comparison against the per-batch
    ``run_plan_dist`` loop over the same batches — the record that pins
    the executor's ICI-O(1), sync-once economics for future PRs to diff."""
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.config import metrics_enabled
    from spark_rapids_tpu.exec import col, plan, run_plan_dist_stream
    from spark_rapids_tpu.exec.dist import run_plan_dist
    from spark_rapids_tpu.obs import bench_line, registry
    from spark_rapids_tpu.parallel import make_mesh, shard_table

    mesh = make_mesh()
    P = mesh.devices.size
    rows = n_batches * batch_rows
    host = {n: np.asarray(c.data)[:rows] for n, c in lineitem.items()}

    def batch(i):
        lo = i * batch_rows
        return srt.Table([(n, Column.from_numpy(v[lo:lo + batch_rows]))
                          for n, v in host.items()])

    p = (plan()
         .filter(col("shipdate") <= 10_500)
         .with_columns(disc_price=col("price") * (1 - col("disc")))
         .groupby_agg(["flag", "status"],
                      [("qty", "sum", "sum_qty"),
                       ("disc_price", "sum", "revenue"),
                       ("qty", "count", "n")],
                      domains={"flag": (0, 2), "status": (0, 1)}))

    def per_batch_loop():
        for i in range(n_batches):
            run_plan_dist(p, shard_table(batch(i), mesh), mesh)

    def stream():
        return list(run_plan_dist_stream(
            p, (batch(i) for i in range(n_batches)), mesh, combine=True))

    meter = metrics_enabled()

    def syncs():
        # Snapshot delta, not reset(): the metrics/cache lines emitted at
        # the end of main() must keep the whole bench's counters.
        return registry().snapshot().get("host.sync", 0) if meter else 0

    per_batch_loop()                  # warm: per-batch dist programs
    stream()                          # warm: stream partial + merge programs

    base = syncs()
    t0 = time.perf_counter()
    per_batch_loop()
    dt_loop = time.perf_counter() - t0
    loop_syncs = syncs() - base

    base = syncs()
    t0 = time.perf_counter()
    out = stream()
    dt_stream = time.perf_counter() - t0
    stream_syncs = syncs() - base
    assert len(out) == 1 and out[0].num_rows > 0

    emit(json.dumps({"metric": "dist_stream_vs_loop", "rows": rows,
                      "shards": P, "batches": n_batches,
                      "loop_seconds": round(dt_loop, 6),
                      "stream_seconds": round(dt_stream, 6),
                      "loop_host_syncs": loop_syncs,
                      "stream_host_syncs": stream_syncs},
                     sort_keys=True))
    emit(bench_line("dist_stream"))


def bench_plans(lineitem, fact, dim):
    """Whole-plan-compiler forms of the same two query shapes."""
    from spark_rapids_tpu.exec import col, plan

    q1 = (plan()
          .filter(col("shipdate") <= 10_500)
          .with_columns(disc_price=col("price") * (1 - col("disc")))
          .with_columns(charge=col("disc_price") * (1 + col("tax")))
          .groupby_agg(["flag", "status"],
                       [("qty", "sum", "sum_qty"),
                        ("price", "sum", "sum_price"),
                        ("disc_price", "sum", "sum_disc_price"),
                        ("charge", "sum", "sum_charge"),
                        ("qty", "mean", "avg_qty"),
                        ("disc", "mean", "avg_disc"),
                        ("qty", "count", "n")])
          .sort_by(["flag", "status"]))
    _bench_compiled("tpch_q1_4M", q1, lineitem,
                    chain_col="qty", leaf_col="sum_qty")

    pj = (plan()
          .join_broadcast(dim.rename({"k": "dk"}), left_on="k",
                          right_on="dk")
          .groupby_agg(["cat"], [("rev", "sum", "rev_sum"),
                                 ("rev", "count", "n")])
          .sort_by(["cat"]))
    _bench_compiled("fact_dim_join_agg_4M", pj, fact,
                    chain_col="rev", leaf_col="rev_sum")


def bench_plan_opt(sf_rows=200_000):
    """``--plan-opt``: the TPC-DS bank under the adaptive plan optimizer
    vs the ``SRT_PLAN_OPT=0`` oracle.

    Runs every bank query twice per mode (warm compile + timed rep),
    checks the optimized results are **bit-identical** to the oracle,
    aggregates the optimizer's registry counters (rewrites per rule,
    pruned input columns), and closes the telemetry feedback loop with a
    history-warmed rerun whose reorder must report ``history_informed``.
    Emits ONE ``plan_opt`` JSON line (teed by ``--metrics-out``); the
    metered runs also append per-fingerprint history records, so a
    follow-up ``--regress`` gates the optimized walls like any other
    lane.
    """
    import os
    import tempfile

    from spark_rapids_tpu.exec import col, plan
    from spark_rapids_tpu.models import tpcds
    from spark_rapids_tpu.models.tpcds_queries import QUERIES
    from spark_rapids_tpu.obs import last_query_metrics, registry

    os.environ["SRT_METRICS"] = "1"
    t0 = time.perf_counter()
    d = tpcds.generate(sf_rows, seed=7)
    print(f"# plan-opt: generated sf_rows={sf_rows} in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    def sweep(opt_on):
        os.environ["SRT_PLAN_OPT"] = "1" if opt_on else "0"
        registry().reset()
        outs, walls = {}, {}
        steps_before = steps_after = bound_cols = pruned = 0
        for nm, fn in QUERIES.items():
            fn(d)                        # warm: compile off the clock
            t1 = time.perf_counter()
            out = fn(d)
            walls[nm] = time.perf_counter() - t1
            outs[nm] = out.to_pydict()
            qm = last_query_metrics()
            if qm is not None:
                od = qm.to_dict()       # per-query counter deltas
                bound_cols += (od["input"]["columns"]
                               - od["opt"]["pruned_columns"])
                pruned += od["opt"]["pruned_columns"]
                steps_before += od["opt"]["steps_before"]
                steps_after += od["opt"]["steps_after"]
        snap = registry().counters_snapshot()
        return outs, walls, steps_before, steps_after, bound_cols, \
            pruned, snap

    o_outs, o_walls, _, _, o_cols, _, _ = sweep(False)
    outs, walls, sb, sa, cols, pruned, snap = sweep(True)

    mismatched = sorted(nm for nm in QUERIES if outs[nm] != o_outs[nm])
    rewrites = {k.rsplit(".", 1)[1]: int(v) for k, v in snap.items()
                if k.startswith("plan.opt.rewrites.")}

    # History-feedback demo: a cold analyze run records per-conjunct
    # selectivity; the warm rerun's reorder must consume it.  The wide
    # conjunct deliberately leads so only history can demote it.
    hist = os.environ.get("SRT_METRICS_HISTORY")
    if hist is None:
        fd, hist = tempfile.mkstemp(suffix=".jsonl", prefix="srt-hist-")
        os.close(fd)
        os.environ["SRT_METRICS_HISTORY"] = hist
    p = (plan()
         .filter(col("ss_quantity") > -1)
         .filter(col("ss_store_sk").eq(1))
         .groupby_agg(["ss_store_sk"], [("ss_quantity", "sum", "q")]))
    p.explain_analyze(d.store_sales)
    p.run(d.store_sales)
    warm_opt = last_query_metrics().to_dict()["opt"]

    emit(json.dumps({
        "metric": "plan_opt",
        "queries": len(QUERIES),
        "bit_identical": not mismatched,
        "mismatched": mismatched,
        "wall_oracle_s": round(sum(o_walls.values()), 4),
        "wall_opt_s": round(sum(walls.values()), 4),
        "bound_columns": {"oracle": o_cols, "optimized": cols},
        "pruned_columns": pruned,
        "traced_steps": {"oracle": sb, "optimized": sa},
        "rewrites": rewrites,
        "history_informed": bool(warm_opt["history_informed"]),
    }, sort_keys=True))
    if mismatched:
        raise SystemExit(
            f"plan-opt parity failure: {len(mismatched)} quer"
            f"{'y' if len(mismatched) == 1 else 'ies'} diverged from the "
            f"SRT_PLAN_OPT=0 oracle: {', '.join(mismatched)}")


def bench_serving(sf_rows=120_000, n_queries=40, n_clients=4):
    """``--serving``: a mixed closed-loop load over the TPC-DS data
    through ``serve.submit`` — ``n_clients`` client threads pull from a
    40-submission mix (one-shot and streaming plans, fingerprints
    repeated so the result cache engages) and block on each ticket.

    Every serving result is checked **bit-identical** to the same plan
    run sequentially on the bare executors; emits ONE ``serving`` JSON
    line (sustained qps, p50/p99 latency, result-cache hit rate,
    admission rejects — teed by ``--metrics-out``) and exits nonzero on
    any parity failure.
    """
    import os
    import threading

    import spark_rapids_tpu as srt
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.exec import col, plan, run_plan_stream
    from spark_rapids_tpu.models import tpcds
    from spark_rapids_tpu.obs.query import _serving_payload
    from spark_rapids_tpu.serve import QuerySession

    os.environ["SRT_METRICS"] = "1"
    t0 = time.perf_counter()
    d = tpcds.generate(sf_rows, seed=7)
    print(f"# serving: generated sf_rows={sf_rows} in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    ss = d.store_sales
    host = {n: np.asarray(c.data) for n, c in ss.items()}
    n_batches, step = 4, ss.num_rows // 4
    batches = [srt.Table([(n, Column.from_numpy(v[i * step:(i + 1) * step]))
                          for n, v in host.items()])
               for i in range(n_batches)]

    # Five distinct shapes; cycling them through 40 submissions repeats
    # each fingerprint 8x — the result-cache's bread and butter.
    shapes = [
        ("agg", plan().filter(col("ss_quantity") > 10)
         .groupby_agg(["ss_store_sk"],
                      [("ss_ext_sales_price", "sum", "revenue")]), ss),
        ("filter", plan().filter(col("ss_quantity") > 40)
         .with_columns(net=col("ss_ext_sales_price")
                       * (1 + col("ss_ext_tax"))), ss),
        ("topk", plan().filter(col("ss_store_sk").eq(1))
         .groupby_agg(["ss_item_sk"], [("ss_quantity", "sum", "q")]), ss),
        ("stream_etl", plan().filter(col("ss_quantity") > 25)
         .with_columns(net=col("ss_ext_sales_price")
                       - col("ss_ext_discount_amt")), batches),
        ("stream_agg", plan().filter(col("ss_quantity") > 5)
         .groupby_agg(["ss_store_sk"], [("ss_quantity", "sum", "q")]),
         batches),
    ]

    # Sequential oracle on the bare executors (also warms the compile
    # caches, so serving measures serving — not first-compile walls).
    oracle = {}
    for name, p, inp in shapes:
        if isinstance(inp, list):
            oracle[name] = [t.to_pydict()
                            for t in run_plan_stream(p, list(inp))]
        else:
            oracle[name] = p.run(inp).to_pydict()

    session = QuerySession(max_concurrent=n_clients,
                           result_cache_cap=256 << 20)
    work = [shapes[i % len(shapes)] for i in range(n_queries)]
    latencies = [None] * n_queries
    failures = []
    next_i = [0]
    pick = threading.Lock()

    def client():
        while True:
            with pick:
                i = next_i[0]
                if i >= n_queries:
                    return
                next_i[0] += 1
            name, p, inp = work[i]
            t1 = time.perf_counter()
            if isinstance(inp, list):
                ticket = session.submit(p, inp)
                got = [t.to_pydict() for t in ticket.result()]
            else:
                ticket = session.submit(p, table=inp)
                got = ticket.result().to_pydict()
            latencies[i] = time.perf_counter() - t1
            if got != oracle[name]:
                failures.append(name)

    t0 = time.perf_counter()
    clients = [threading.Thread(target=client) for _ in range(n_clients)]
    for c in clients:
        c.start()
    for c in clients:
        c.join()
    wall = time.perf_counter() - t0
    session.close()

    lat = sorted(latencies)
    payload = _serving_payload()
    payload.update({
        "queries": n_queries,
        "clients": n_clients,
        "bit_identical": not failures,
        "mismatched": sorted(set(failures)),
        "wall_seconds": round(wall, 4),
        "qps": round(n_queries / wall, 2) if wall else 0.0,
        "latency_p50_s": round(lat[len(lat) // 2], 6),
        "latency_p99_s": round(lat[min(len(lat) - 1,
                                       int(len(lat) * 0.99))], 6),
    })
    emit(json.dumps(payload, sort_keys=True))
    if failures:
        raise SystemExit(
            f"serving parity failure: {sorted(set(failures))} diverged "
            f"from the sequential oracle")


def bench_semantic(sf_rows=120_000, n_queries=40, n_clients=4,
                   n_batches=6):
    """``--semantic``: the semantic subplan cache + materialized views
    under a workload-representative load (``SRT_SEMANTIC_CACHE=1``,
    ``SRT_VIEWS=1``).

    Two measurements, one ``semantic_cache`` JSON line:

    * an overlapping broadcast-join bank (shared filter+join prefix,
      divergent aggregation tails) driven through ``serve.submit`` by
      ``n_clients`` closed-loop clients — every served result is
      checked **bit-identical** to the bare-executor oracle computed
      with the cache off, and the line reports sustained qps,
      p50/p99 latency, and the subplan cache's hit rate;
    * one materialized view folded batch-by-batch — the incremental
      ``refresh()`` after the last fold is timed against a full
      streaming-combine recompute over the whole history, checked
      bit-identical, and reported as the refresh delta.

    Exits nonzero on any parity loss (CSE splice or view maintenance).
    """
    import os
    import threading

    import spark_rapids_tpu as srt
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.exec import col, plan, run_plan_stream
    from spark_rapids_tpu.models import tpcds
    from spark_rapids_tpu.serve import QuerySession
    from spark_rapids_tpu.serve import semantic
    from spark_rapids_tpu import views as views_pkg

    os.environ["SRT_METRICS"] = "1"
    saved = {k: os.environ.get(k)
             for k in ("SRT_SEMANTIC_CACHE", "SRT_VIEWS")}
    t0 = time.perf_counter()
    d = tpcds.generate(sf_rows, seed=7)
    print(f"# semantic: generated sf_rows={sf_rows} in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    ss = d.store_sales
    stores = srt.Table([
        ("s_store_sk", d.store["s_store_sk"]),
        ("s_number_employees", d.store["s_number_employees"]),
    ])
    smax = int(np.asarray(d.store["s_store_sk"].data).max())

    # Shared filter+broadcast-join prefix; the tails aggregate the SAME
    # column set so the optimizer's pruning projection (and with it the
    # prefix fingerprint) is identical across the bank.
    def bank_plan(aggs):
        return (plan()
                .filter(col("ss_quantity") > 10)
                .join_broadcast(stores, left_on="ss_store_sk",
                                right_on="s_store_sk")
                .groupby_agg(["ss_store_sk"], aggs))

    shapes = [
        ("sum", bank_plan([("ss_ext_sales_price", "sum", "rev"),
                           ("ss_quantity", "sum", "qty")])),
        ("minmax", bank_plan([("ss_ext_sales_price", "min", "lo"),
                              ("ss_ext_sales_price", "max", "hi"),
                              ("ss_quantity", "count", "n")])),
        ("mean", bank_plan([("ss_ext_sales_price", "mean", "avg"),
                            ("ss_quantity", "max", "qmax")])),
    ]

    # Oracle with the cache OFF — the bare executor is the bit-identity
    # reference (and warms the compile caches off the clock).
    os.environ["SRT_SEMANTIC_CACHE"] = "0"
    os.environ["SRT_VIEWS"] = "0"
    semantic.reset()
    views_pkg.reset()
    oracle = {name: p.run(ss).to_pydict() for name, p in shapes}

    os.environ["SRT_SEMANTIC_CACHE"] = "1"
    os.environ["SRT_VIEWS"] = "1"
    session = QuerySession(max_concurrent=n_clients,
                           register_queued=False)
    work = [shapes[i % len(shapes)] for i in range(n_queries)]
    latencies = [None] * n_queries
    failures = []
    next_i = [0]
    pick = threading.Lock()

    def client():
        while True:
            with pick:
                i = next_i[0]
                if i >= n_queries:
                    return
                next_i[0] += 1
            name, p = work[i]
            t1 = time.perf_counter()
            got = session.submit(p, table=ss).result().to_pydict()
            latencies[i] = time.perf_counter() - t1
            if got != oracle[name]:
                failures.append(name)

    try:
        # Warm-up: two sequential passes over the bank materialize the
        # shared prefix (interest threshold 2) and compile the spliced
        # program off the clock — otherwise the one cold splice compile
        # outlives every other query in the bank and the timed window
        # closes with the entry still in flight.  The timed closed-loop
        # below measures steady-state hit traffic.
        for _ in range(2):
            for name, p in shapes:
                got = session.submit(p, table=ss).result().to_pydict()
                if got != oracle[name]:
                    failures.append(name)
        t1 = time.perf_counter()
        clients = [threading.Thread(target=client)
                   for _ in range(n_clients)]
        for c in clients:
            c.start()
        for c in clients:
            c.join()
        wall = time.perf_counter() - t1
        session.close()
        cse = semantic.stats()

        # Materialized view: fold batch-by-batch, time the incremental
        # refresh after the last fold against a full recompute.
        host = {n: np.asarray(c.data) for n, c in ss.items()}
        step = max(1, ss.num_rows // n_batches)
        batches = [srt.Table([(n, Column.from_numpy(
            v[i * step:(i + 1) * step])) for n, v in host.items()])
            for i in range(n_batches)]
        batches = [b for b in batches if b.num_rows]
        pv = (plan()
              .filter(col("ss_quantity") > 10)
              .groupby_agg(["ss_store_sk"],
                           [("ss_ext_sales_price", "sum", "rev"),
                            ("ss_quantity", "sum", "qty")],
                           domains={"ss_store_sk": (0, smax)}))
        view = views_pkg.register("bench:rev_by_store", pv)
        for b in batches[:-1]:
            view.fold(b)
        view.refresh()                       # steady state: fresh view
        view.fold(batches[-1])               # one new batch arrives
        t2 = time.perf_counter()
        incr = view.result()                 # incremental refresh
        refresh_s = time.perf_counter() - t2
        list(run_plan_stream(pv, list(batches), combine=True))  # warm
        t3 = time.perf_counter()
        full = list(run_plan_stream(pv, list(batches), combine=True))[0]
        full_s = time.perf_counter() - t3
        view_identical = incr.to_pydict() == full.to_pydict()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        semantic.reset()
        views_pkg.reset()

    lat = sorted(t for t in latencies if t is not None)
    emit(json.dumps({
        "metric": "semantic_cache",
        "queries": n_queries,
        "clients": n_clients,
        "bit_identical": not failures,
        "mismatched": sorted(set(failures)),
        "wall_seconds": round(wall, 4),
        "qps": round(n_queries / wall, 2) if wall else 0.0,
        "latency_p50_s": round(lat[len(lat) // 2], 6),
        "latency_p99_s": round(lat[min(len(lat) - 1,
                                       int(len(lat) * 0.99))], 6),
        "subplan_hit_rate": cse["hit_rate"],
        "subplan_hits": cse["hits"],
        "subplan_misses": cse["misses"],
        "materializations": cse["materializations"],
        "evictions": cse["evictions"],
        "cache_bytes": cse["bytes"],
        "view_batches": len(batches),
        "view_identical": view_identical,
        "view_refresh_s": round(refresh_s, 6),
        "view_full_recompute_s": round(full_s, 6),
        "view_refresh_delta_s": round(full_s - refresh_s, 6),
    }, sort_keys=True))
    if failures:
        raise SystemExit(
            f"semantic-cache parity failure: {sorted(set(failures))} "
            f"diverged from the cache-off oracle")
    if not view_identical:
        raise SystemExit(
            "materialized-view parity failure: incremental refresh "
            "diverged from the full streaming-combine recompute")


def _pydict_eq(x, y):
    """Structural equality over ``to_pydict`` payloads with NaN == NaN
    (list equality treats two NaN floats as different)."""
    if isinstance(x, float) and isinstance(y, float):
        return x == y or (x != x and y != y)
    if isinstance(x, list):
        return (isinstance(y, list) and len(x) == len(y)
                and all(_pydict_eq(a, b) for a, b in zip(x, y)))
    if isinstance(x, dict):
        return (isinstance(y, dict) and sorted(x) == sorted(y)
                and all(_pydict_eq(x[k], y[k]) for k in x))
    return x == y


def bench_kernels(rows=60_000, reps=3):
    """``--kernels``: per-kernel oracle-vs-kernel wall delta + parity.

    For each registered Pallas kernel (join, groupby, decode, rows) a
    representative workload runs twice — once with ``SRT_KERNELS``
    empty (the jnp oracle) and once with only that kernel enabled —
    and the two results must agree exactly (NaN-aware).  Wall deltas
    feed the kernel registry via ``record_speedup`` so the measured
    ratios are what the workload advisor would consume.  Emits ONE
    ``kernels`` JSON line (per-kernel oracle/kernel wall seconds,
    delta, speedup, parity, invocation count; decode additionally pins
    ``scan.bytes_skipped`` identical across passes — the kernel must
    not change what the page walk skips).  Exits nonzero on any parity
    loss or any kernel that never fired (a lane that silently measures
    the oracle twice is a lane failure).  Off-TPU the kernels run in
    Pallas interpret mode, so deltas there are a path-coverage signal,
    not a speedup claim.
    """
    import os
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    import spark_rapids_tpu as srt
    from spark_rapids_tpu import dtypes as dt
    from spark_rapids_tpu import kernels, ops
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.exec import plan
    from spark_rapids_tpu.io.parquet_native import read_parquet_native
    from spark_rapids_tpu.obs import registry
    from spark_rapids_tpu.rows.image import pack_image, unpack_image
    from spark_rapids_tpu.rows.layout import compute_fixed_width_layout

    os.environ["SRT_METRICS"] = "1"
    rng = np.random.default_rng(11)

    fact = srt.Table([
        ("k", Column.from_numpy(rng.integers(0, 4000, rows)
                                .astype(np.int64))),
        ("rev", Column.from_numpy(rng.uniform(1, 100, rows))),
    ])
    dim = srt.Table([
        ("k", Column.from_numpy(np.arange(4000, dtype=np.int64))),
        ("cat", Column.from_numpy(rng.integers(0, 100, 4000)
                                  .astype(np.int32))),
    ])

    gb_table = srt.Table([
        ("k", Column.from_numpy(rng.integers(0, 64, rows)
                                .astype(np.int32))),
        ("v", Column.from_numpy(rng.uniform(-10, 10, rows))),
    ])
    gb_plan = plan().groupby_agg(
        ["k"], [("v", "sum", "s"), ("v", "count", "n")],
        domains={"k": (0, 63)})

    tmpdir = tempfile.mkdtemp(prefix="srt-kernels-")
    pq_path = os.path.join(tmpdir, "kernels.parquet")
    pq.write_table(
        pa.table({"g": rng.integers(0, 8, rows).astype(np.int32),
                  "x": np.arange(rows, dtype=np.int64)}),
        pq_path, use_dictionary=True, data_page_size=4096,
        row_group_size=max(rows // 8, 1024))
    pred = [("x", "<", rows // 4)]        # skips most row groups

    row_schema = (dt.INT64, dt.FLOAT64, dt.INT32)
    layout = compute_fixed_width_layout(row_schema)
    row_datas = [np.arange(rows, dtype=np.int64),
                 rng.normal(size=rows),
                 rng.integers(-50, 50, rows).astype(np.int32)]
    row_masks = [rng.random(rows) > 0.1 for _ in row_schema]

    def run_join():
        return ops.join(fact, dim, on=["k"], how="inner").to_pydict()

    def run_groupby():
        return gb_plan.run(gb_table).to_pydict()

    def run_decode():
        return read_parquet_native(pq_path, predicate=pred).to_pydict()

    def run_rows():
        image = pack_image(layout, row_datas, row_masks)
        datas, valids = unpack_image(layout, image)
        out = {}
        for i, (d, v) in enumerate(zip(datas, valids)):
            out[f"c{i}"] = np.where(np.asarray(v)[:rows],
                                    np.asarray(d)[:rows], 0).tolist()
        return out

    lanes = {"join": run_join, "groupby": run_groupby,
             "decode": run_decode, "rows": run_rows}

    def timed(fn):
        fn()                              # warm: compile off the clock
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn()
        return (time.perf_counter() - t0) / reps, out

    def skipped_bytes():
        return float(registry().counter("scan.bytes_skipped").value)

    had_kernels = os.environ.get("SRT_KERNELS")
    had_rows = os.environ.pop("SRT_ROWS_IMPL", None)
    per_kernel, failures = {}, []
    try:
        for name, fn in lanes.items():
            kernels.reset()
            os.environ["SRT_KERNELS"] = ""
            sk0 = skipped_bytes()
            oracle_s, oracle_out = timed(fn)
            sk_oracle = skipped_bytes() - sk0

            os.environ["SRT_KERNELS"] = name
            sk1 = skipped_bytes()
            kernel_s, kernel_out = timed(fn)
            sk_kernel = skipped_bytes() - sk1

            fired = kernels.stats()["per_kernel"][name]["invocations"]
            parity = _pydict_eq(oracle_out, kernel_out)
            if name == "decode":
                parity = parity and sk_oracle == sk_kernel
            if parity and fired:
                kernels.record_speedup(name, oracle_s, kernel_s)
            else:
                failures.append(name)
            entry = {
                "oracle_s": round(oracle_s, 6),
                "kernel_s": round(kernel_s, 6),
                "delta_s": round(oracle_s - kernel_s, 6),
                "speedup": round(oracle_s / kernel_s, 4)
                if kernel_s > 0 else 0.0,
                "parity": parity,
                "invocations": int(fired),
            }
            if name == "decode":
                entry["bytes_skipped_oracle"] = sk_oracle
                entry["bytes_skipped_kernel"] = sk_kernel
            per_kernel[name] = entry
    finally:
        if had_kernels is None:
            os.environ.pop("SRT_KERNELS", None)
        else:
            os.environ["SRT_KERNELS"] = had_kernels
        if had_rows is not None:
            os.environ["SRT_ROWS_IMPL"] = had_rows

    emit(json.dumps({
        "metric": "kernels",
        "rows": rows,
        "interpret": kernels.interpret_mode(),
        "per_kernel": per_kernel,
        "parity": not failures,
        "failed": sorted(failures),
    }, sort_keys=True))
    if failures:
        raise SystemExit(
            f"kernel lane failure: {sorted(failures)} — parity loss or "
            f"kernel never fired (see the `kernels` line)")


def bench_spill(n_batches=8, batch_rows=40_000):
    """``--spill``: out-of-core lane — oracle vs spill-forced wall + parity.

    A streaming combine group-by (5 aggregates over a dense key domain)
    runs twice: once with spill off (the ``SRT_SPILL=0`` oracle) and
    once under ``SRT_SPILL=1`` with a deliberately tiny
    ``SRT_SERVE_HBM_BUDGET`` and ``SRT_SPILL_HOST_BYTES=0``, so the
    watermark pages every cold combine level all the way through the
    Parquet disk tier and back.  The two results must agree exactly
    (NaN-aware).  Emits ONE ``spill`` JSON line (oracle/spilled wall
    seconds, pages + bytes out/in, spill files, page-in seconds).
    Exits nonzero on parity loss or when ``bytes_out`` stayed zero —
    a lane that never pages is measuring the oracle twice.
    """
    import os
    import tempfile

    import spark_rapids_tpu as srt
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.exec import plan
    from spark_rapids_tpu.resilience import recovery_stats, reset_spill

    rng = np.random.default_rng(23)
    batches = [srt.Table([
        ("k", Column.from_numpy(rng.integers(0, 64, batch_rows)
                                .astype(np.int32))),
        ("v", Column.from_numpy(rng.uniform(-10, 10, batch_rows))),
    ]) for _ in range(n_batches)]
    gb_plan = plan().groupby_agg(
        ["k"], [("v", "sum", "s"), ("v", "count", "n"),
                ("v", "mean", "m"), ("v", "min", "lo"),
                ("v", "max", "hi")],
        domains={"k": (0, 63)})

    def run_combine():
        t0 = time.perf_counter()
        outs = list(gb_plan.run_stream(iter(batches), inflight=2,
                                       combine=True))
        wall = time.perf_counter() - t0
        assert len(outs) == 1
        return wall, outs[0].to_pydict()

    knobs = ("SRT_SPILL", "SRT_SPILL_DIR", "SRT_SPILL_HOST_BYTES",
             "SRT_SPILL_WATERMARK", "SRT_SERVE_HBM_BUDGET")
    saved = {k: os.environ.get(k) for k in knobs}
    for k in knobs:
        os.environ.pop(k, None)
    reset_spill()
    try:
        oracle_s, oracle_out = run_combine()

        spill_dir = tempfile.mkdtemp(prefix="srt-bench-spill-")
        os.environ["SRT_SPILL"] = "1"
        os.environ["SRT_SPILL_DIR"] = spill_dir
        os.environ["SRT_SPILL_HOST_BYTES"] = "0"   # force the disk tier
        os.environ["SRT_SERVE_HBM_BUDGET"] = "64"  # tiny: accumulators
        os.environ["SRT_SPILL_WATERMARK"] = "0.5"  # must page out
        reset_spill()
        before = recovery_stats().snapshot()
        spilled_s, spilled_out = run_combine()
        d = recovery_stats().delta(before)
        leftovers = os.listdir(spill_dir)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        reset_spill()

    parity = _pydict_eq(oracle_out, spilled_out)
    emit(json.dumps({
        "metric": "spill",
        "batches": n_batches,
        "rows_per_batch": batch_rows,
        "oracle_s": round(oracle_s, 6),
        "spilled_s": round(spilled_s, 6),
        "overhead_s": round(spilled_s - oracle_s, 6),
        "pages_out": d["spill_pages_out"],
        "pages_in": d["spill_pages_in"],
        "bytes_out": d["spill_bytes_out"],
        "bytes_in": d["spill_bytes_in"],
        "files": d["spill_files"],
        "page_in_seconds": round(d["spill_page_in_seconds"], 6),
        "parity": parity,
        "leaked_files": len(leftovers),
    }, sort_keys=True))
    if not parity:
        raise SystemExit(
            "spill lane failure: spilled result diverged from the "
            "SRT_SPILL=0 oracle (see the `spill` line)")
    if d["spill_bytes_out"] <= 0:
        raise SystemExit(
            "spill lane failure: nothing paged out — the lane measured "
            "the oracle twice (see the `spill` line)")
    if leftovers:
        raise SystemExit(
            f"spill lane failure: {len(leftovers)} page files leaked in "
            f"the spill directory after the run")


if __name__ == "__main__":
    import os
    if "--faults" in sys.argv:
        os.environ.setdefault("SRT_FAULT", "oom:materialize:1")
    if "--timeline" in sys.argv:
        # Arm the recorder before any engine work so the whole bench —
        # stream lanes included — lands in the export.
        _timeline_arg()                       # validate the argument early
        os.environ["SRT_TRACE_TIMELINE"] = "1"
    metrics_out = _path_arg("--metrics-out")
    if metrics_out is not None:
        _METRICS_OUT = open(metrics_out, "a")
    try:
        if "--plan-opt" in sys.argv:
            bench_plan_opt()
        elif "--serving" in sys.argv:
            bench_serving()
        elif "--semantic" in sys.argv:
            bench_semantic()
        elif "--kernels" in sys.argv:
            bench_kernels()
        elif "--spill" in sys.argv:
            bench_spill()
        else:
            main()
        if "--regress" in sys.argv:
            run_regress_gate()
    finally:
        if _METRICS_OUT is not None:
            _METRICS_OUT.close()
            _METRICS_OUT = None
