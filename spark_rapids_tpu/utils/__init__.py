"""Utilities: tracing/profiling scopes and device-memory management."""

from .memory import (MemoryScope, device_get_counted, device_memory_stats,
                     donating_jit, free, no_implicit_transfers,
                     record_host_sync)
from .tracing import start_server, trace, traced

__all__ = [
    "MemoryScope",
    "device_get_counted",
    "device_memory_stats",
    "donating_jit",
    "free",
    "no_implicit_transfers",
    "record_host_sync",
    "start_server",
    "trace",
    "traced",
]
