"""Live-query telemetry contracts (obs/live.py, obs/server.py).

Four contracts:

1. **Zero-cost when off** — with ``SRT_METRICS`` unset and nobody
   observing, every execution path gets the shared ``NULL_LIVE`` record
   (identity-checked) and the registry stays empty.
2. **Heartbeats when on** — metered runs and streams appear in the
   in-flight registry while executing and move to the recent ring at
   finish; ``on_progress`` / ``progress=`` callbacks fire even without
   ``SRT_METRICS``; recovery rungs and per-shard progress publish live.
3. **Valid exposition** — ``/metrics`` is parseable Prometheus text
   0.0.4 under label escaping, NaN/±Inf values, and concurrent scrapes
   mid-stream; counters stay monotonic across device-cache evictions.
4. **Correlation** — timeline span args and history JSONL rows carry the
   same ``query_id`` the live snapshot uses.
"""

import json
import math
import re
import threading
import urllib.request

import numpy as np
import pytest

from spark_rapids_tpu import Table
from spark_rapids_tpu.exec import col, plan
from spark_rapids_tpu.exec.stream import run_plan_stream
from spark_rapids_tpu.obs import live, server
from spark_rapids_tpu.obs.metrics import counter, gauge, registry


@pytest.fixture(autouse=True)
def _fresh_live(monkeypatch):
    monkeypatch.delenv("SRT_LIVE_SERVER", raising=False)
    monkeypatch.delenv("SRT_LIVE_PORT", raising=False)
    live.reset()
    server.reset_histograms()
    yield
    server.stop()
    live.reset()
    server.reset_histograms()
    registry().reset()


@pytest.fixture
def metrics_on(monkeypatch):
    monkeypatch.setenv("SRT_METRICS", "1")
    registry().reset()
    yield
    registry().reset()


@pytest.fixture
def metrics_off(monkeypatch):
    monkeypatch.delenv("SRT_METRICS", raising=False)


def _table(prefix, n=400):
    return Table.from_pydict({
        f"{prefix}_k": (np.arange(n) % 5).astype(np.int32),
        f"{prefix}_v": np.arange(n, dtype=np.float32),
    })


def _query(prefix):
    return (plan()
            .filter(col(f"{prefix}_v") > 10.0)
            .with_columns(**{f"{prefix}_d": col(f"{prefix}_v") * 2.0}))


def _batches(prefix, n=4, rows=128):
    for i in range(n):
        yield Table.from_pydict({
            f"{prefix}_k": (np.arange(rows) % 5).astype(np.int32),
            f"{prefix}_v": np.arange(rows, dtype=np.float32) + i,
        })


# ---------------------------------------------------------------------------
# 1. zero-cost-off contract
# ---------------------------------------------------------------------------

def test_start_returns_null_record_when_off(metrics_off):
    assert live.start("run") is live.NULL_LIVE
    # the null record swallows the whole publishing API
    live.NULL_LIVE.set_phase("x")
    live.NULL_LIVE.batch_out(5)
    live.NULL_LIVE.rung("retry", site="bind")
    live.NULL_LIVE.finish()
    assert live.NULL_LIVE.snapshot() == {}
    assert live.snapshot_all()["in_flight"] == []


def test_disabled_run_leaves_registry_empty(metrics_off):
    t = _table("loff")
    _query("loff").run(t)
    snap = live.snapshot_all()
    assert snap["in_flight"] == [] and snap["recent"] == []


def test_ambient_publishers_noop_without_record(metrics_off):
    # must not raise (the recovery ladder calls these unconditionally)
    live.phase("bind")
    live.rung("retry", site="dispatch")
    live.add_ici(1024)
    live.note_hbm(1 << 20)
    assert live.current() is None


# ---------------------------------------------------------------------------
# 2. heartbeats when on
# ---------------------------------------------------------------------------

def test_metered_run_lands_in_recent_ring(metrics_on):
    t = _table("lrec")
    _query("lrec").run(t)
    snap = live.snapshot_all()
    assert snap["in_flight"] == []
    assert len(snap["recent"]) == 1
    q = snap["recent"][0]
    assert q["status"] == "done" and q["mode"] == "run"
    assert q["fingerprint"] and q["query_id"] > 0
    assert q["rows_out"] > 0


def test_stream_progress_callback_without_metrics(metrics_off):
    snaps = []
    outs = list(run_plan_stream(_query("lprog"), _batches("lprog"),
                                on_progress=snaps.append))
    assert len(outs) == 4
    assert snaps, "observer must fire even when SRT_METRICS is unset"
    last = snaps[-1]
    assert last["status"] == "done"
    assert last["batches_done"] == 4
    assert last["rows_in"] == 4 * 128
    # still zero-cost for everyone else: the registry stayed empty
    assert registry().counters_snapshot() == {}


def test_plan_run_progress_callback(metrics_off):
    snaps = []
    t = _table("lrun")
    _query("lrun").run(t, progress=snaps.append)
    assert snaps and snaps[-1]["status"] == "done"
    assert {s["phase"] for s in snaps} >= {"bind", "dispatch", "done"}


def test_in_flight_snapshot_mid_stream(metrics_on):
    seen = []

    def observe(snap):
        if snap["status"] == "running" and not seen:
            inflight = live.snapshot_all()["in_flight"]
            seen.append((snap["query_id"], [q["query_id"]
                                            for q in inflight]))

    list(run_plan_stream(_query("lmid"), _batches("lmid"),
                         on_progress=observe))
    assert seen, "no running heartbeat observed"
    qid, inflight_ids = seen[0]
    assert qid in inflight_ids


def test_recovery_rung_publishes_live(metrics_on, monkeypatch):
    monkeypatch.setenv("SRT_FAULT", "oom:dispatch:1")
    monkeypatch.setenv("SRT_RETRY_BACKOFF", "0")
    from spark_rapids_tpu.resilience import reset_faults
    reset_faults()
    try:
        t = _table("lrung")
        _query("lrung").run(t)
    finally:
        monkeypatch.delenv("SRT_FAULT")
        reset_faults()
    q = live.snapshot_all()["recent"][-1]
    assert q["recovery"]["count"] >= 1
    assert any(r.endswith(":retry") for r in q["recovery"]["rungs"])


# ---------------------------------------------------------------------------
# 3. Prometheus text exposition
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? '
    r'(-?\d+(\.\d+)?([eE][+-]?\d+)?|NaN|\+Inf|-Inf)$')


def _assert_valid_exposition(text):
    families = {}
    current = None
    for line in text.strip().split("\n"):
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ")
            assert name not in families, f"duplicate TYPE for {name}"
            families[name] = kind
            current = name
            continue
        assert _SAMPLE_RE.match(line), f"unparseable sample line: {line!r}"
        name = line.split("{", 1)[0].split(" ", 1)[0]
        # histogram families expose suffixed samples under the base name
        allowed = {current}
        if families.get(current) == "histogram":
            allowed = {current + s for s in ("_bucket", "_sum", "_count")}
        assert name in allowed, (
            f"sample {name} outside its TYPE block (current={current})")
    return families


def test_metrics_endpoint_is_valid_exposition(metrics_on):
    t = _table("lexp")
    _query("lexp").run(t)
    families = _assert_valid_exposition(server.prometheus_text())
    assert any(k == "counter" for k in families.values())
    assert families.get("srt_live_queries") == "gauge"


def test_counter_names_are_mangled_and_suffixed(metrics_on):
    counter("weird.name-with/chars").inc(3)
    text = server.prometheus_text()
    assert "srt_weird_name_with_chars_total 3" in text


def test_timers_become_two_counter_families(metrics_on):
    from spark_rapids_tpu.obs.metrics import timer
    with timer("lt.timer").time():
        pass
    text = server.prometheus_text()
    assert "# TYPE srt_lt_timer_seconds_total counter" in text
    assert "# TYPE srt_lt_timer_calls_total counter" in text


def test_nan_and_inf_gauges_render(metrics_on):
    gauge("lt.nan").set(float("nan"))
    gauge("lt.posinf").set(float("inf"))
    gauge("lt.neginf").set(float("-inf"))
    text = server.prometheus_text()
    assert "srt_lt_nan NaN" in text
    assert "srt_lt_posinf +Inf" in text
    assert "srt_lt_neginf -Inf" in text
    _assert_valid_exposition(text)


def test_label_escaping(metrics_on):
    lq = live.start('we"ird\\mo\nde', force=True)
    try:
        text = server.prometheus_text()
    finally:
        lq.finish()
    assert 'mode="we\\"ird\\\\mo\\nde"' in text


def test_counters_monotonic_across_cache_eviction(metrics_on):
    from spark_rapids_tpu.resilience.recovery import evict_device_caches
    t = _table("lmono")
    q = _query("lmono")
    q.run(t)

    def counters(text):
        out = {}
        for line in text.split("\n"):
            if line.startswith("#") or not line:
                continue
            name, value = line.rsplit(" ", 1)
            if name.endswith("_total") and "{" not in name:
                out[name] = float(value)
        return out

    before = counters(server.prometheus_text())
    evict_device_caches()
    q.run(t)
    after = counters(server.prometheus_text())
    for name, value in before.items():
        assert after.get(name, 0) >= value, (
            f"{name} went backwards across eviction: "
            f"{value} -> {after.get(name)}")


def test_concurrent_scrape_during_stream(metrics_on):
    srv = server.start(port=0)
    stop = threading.Event()
    errors = []

    def scraper():
        while not stop.is_set():
            try:
                with urllib.request.urlopen(srv.url + "/metrics",
                                            timeout=5) as resp:
                    assert resp.status == 200
                    _assert_valid_exposition(resp.read().decode())
                with urllib.request.urlopen(srv.url + "/queries",
                                            timeout=5) as resp:
                    json.loads(resp.read().decode())
            except Exception as exc:       # pragma: no cover
                errors.append(exc)
                return

    th = threading.Thread(target=scraper, daemon=True)
    th.start()
    try:
        outs = list(run_plan_stream(_query("lconc"), _batches("lconc", n=6)))
    finally:
        stop.set()
        th.join(timeout=10)
    assert len(outs) == 6
    assert not errors, f"scrape failed mid-stream: {errors[0]!r}"


# ---------------------------------------------------------------------------
# 3a. SLO latency histograms
# ---------------------------------------------------------------------------

def _hist_samples(text, family):
    """{(suffix, labels-string): float} for one histogram family."""
    out = {}
    for line in text.split("\n"):
        if not line.startswith(family):
            continue
        rest = line[len(family):]
        for suffix in ("_bucket", "_sum", "_count"):
            if rest.startswith(suffix):
                sample, value = line.rsplit(" ", 1)
                labels = sample.split("{", 1)[1][:-1] if "{" in sample else ""
                out[(suffix, labels)] = float(value)
    return out


def test_query_seconds_histogram_per_mode(metrics_on):
    t = _table("lhist")
    _query("lhist").run(t)
    text = server.prometheus_text()
    families = _assert_valid_exposition(text)
    assert families.get("srt_query_seconds") == "histogram"
    assert families.get("srt_query_phase_seconds") == "histogram"
    assert 'srt_query_seconds_bucket{le="+Inf",mode="run"} 1' in text
    assert 'srt_query_seconds_count{mode="run"} 1' in text
    for phase in ("bind", "compile", "execute", "materialize"):
        assert f'phase="{phase}"' in text


def test_histogram_buckets_cumulative_inf_equals_count(metrics_on):
    for v in (0.003, 0.02, 0.02, 0.2, 7.0, 1e9):
        server.observe_hist("lt_hist_demo", v)
    text = "\n".join(server.histogram_text())
    samples = _hist_samples(text, "srt_lt_hist_demo")
    bounds = [(float(labels.split('"')[1].replace("+Inf", "inf")), v)
              for (suffix, labels), v in samples.items()
              if suffix == "_bucket"]
    bounds.sort()
    counts = [v for _, v in bounds]
    assert counts == sorted(counts), f"non-cumulative buckets: {bounds}"
    assert bounds[-1][0] == float("inf")
    assert bounds[-1][1] == samples[("_count", "")] == 6
    # the out-of-range observation lands only in +Inf
    assert bounds[-2][1] == 5
    assert samples[("_sum", "")] == pytest.approx(
        0.003 + 0.02 + 0.02 + 0.2 + 7.0 + 1e9)


def test_histogram_observation_on_its_bucket_boundary(metrics_on):
    server.observe_hist("lt_hist_edge", 0.25)
    text = "\n".join(server.histogram_text())
    assert 'srt_lt_hist_edge_bucket{le="0.25"} 1' in text
    assert 'srt_lt_hist_edge_bucket{le="0.1"} 0' in text


def test_histogram_label_escaping(metrics_on):
    server.observe_hist("lt_hist_esc", 0.1, {"mode": 'we"ird\\mo\nde'})
    text = "\n".join(server.histogram_text())
    assert 'mode="we\\"ird\\\\mo\\nde"' in text
    _assert_valid_exposition(text)


def test_histogram_noop_when_metrics_off(metrics_off):
    server.observe_hist("lt_hist_off", 1.0)
    assert server.histogram_text() == []


def test_histogram_concurrent_scrape_while_recording(metrics_on):
    stop = threading.Event()
    errors = []

    def recorder():
        i = 0
        while not stop.is_set():
            server.observe_hist("lt_hist_conc", (i % 100) / 10.0,
                                {"mode": "run"})
            i += 1

    th = threading.Thread(target=recorder, daemon=True)
    th.start()
    try:
        for _ in range(50):
            text = server.prometheus_text()
            _assert_valid_exposition(text)
            samples = _hist_samples(text, "srt_lt_hist_conc")
            inf = samples.get(("_bucket", 'le="+Inf",mode="run"'))
            count = samples.get(("_count", 'mode="run"'))
            if count is not None:
                assert inf == count, (
                    f"torn histogram snapshot: +Inf={inf} count={count}")
    except Exception as exc:       # pragma: no cover
        errors.append(exc)
    finally:
        stop.set()
        th.join(timeout=10)
    assert not errors, f"scrape failed while recording: {errors[0]!r}"


# ---------------------------------------------------------------------------
# 3b. HTTP endpoints
# ---------------------------------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


def test_queries_endpoint_round_trips(metrics_on):
    srv = server.start(port=0)
    t = _table("lhttp")
    _query("lhttp").run(t)
    status, body = _get(srv.url + "/queries")
    assert status == 200
    snap = json.loads(body)
    assert snap["recent"][-1]["mode"] == "run"
    assert snap["pid"] > 0


def test_timeline_endpoint_404_for_unknown_query(metrics_on):
    srv = server.start(port=0)
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(srv.url + "/queries/999999/timeline")
    assert exc.value.code == 404


def test_timeline_endpoint_serves_mid_run_spans(metrics_on, monkeypatch):
    monkeypatch.setenv("SRT_TRACE_TIMELINE", "1")
    from spark_rapids_tpu.obs import timeline
    timeline.reset()
    srv = server.start(port=0)
    grabbed = []

    def observe(snap):
        if (snap["status"] == "running" and snap["batches_done"] >= 1
                and not grabbed):
            status, body = _get(
                srv.url + f"/queries/{snap['query_id']}/timeline")
            grabbed.append((status, json.loads(body)))

    list(run_plan_stream(_query("ltl"), _batches("ltl"),
                         on_progress=observe, trace_timeline=True))
    timeline.reset()
    assert grabbed, "no mid-run timeline scrape happened"
    status, payload = grabbed[0]
    assert status == 200
    evs = payload["traceEvents"]
    assert any(e.get("ph") == "X" for e in evs)
    for e in evs:
        if e.get("ph") != "M":
            assert isinstance(e["args"]["query_id"], int)


def test_server_start_is_idempotent_and_stoppable():
    a = server.start(port=0)
    b = server.start(port=0)
    assert a is b
    server.stop()
    assert server.get() is None


def test_maybe_start_respects_flag(monkeypatch):
    monkeypatch.delenv("SRT_LIVE_SERVER", raising=False)
    assert server.maybe_start() is None
    monkeypatch.setenv("SRT_LIVE_SERVER", "1")
    monkeypatch.setenv("SRT_LIVE_PORT", "0")
    assert server.maybe_start() is not None


def test_live_port_knob_validation(monkeypatch):
    from spark_rapids_tpu.config import live_server_port
    monkeypatch.delenv("SRT_LIVE_PORT", raising=False)
    assert live_server_port() == 9465
    monkeypatch.setenv("SRT_LIVE_PORT", "0")
    assert live_server_port() == 0
    monkeypatch.setenv("SRT_LIVE_PORT", "70000")
    with pytest.raises(ValueError):
        live_server_port()


def test_recent_ring_bounded_by_live_recent_knob(monkeypatch):
    monkeypatch.setenv("SRT_LIVE_RECENT", "5")
    ids = []
    for _ in range(12):
        lq = live.start("run", force=True)
        ids.append(lq.query_id)
        lq.finish()
    recent = live.snapshot_all()["recent"]
    assert len(recent) == 5
    # LRU: only the five newest finishes survive, oldest-first order kept
    assert [q["query_id"] for q in recent] == ids[-5:]


def test_live_recent_knob_validation(monkeypatch):
    from spark_rapids_tpu.config import live_recent_keep
    monkeypatch.delenv("SRT_LIVE_RECENT", raising=False)
    assert live_recent_keep() == 256
    monkeypatch.setenv("SRT_LIVE_RECENT", "3")
    assert live_recent_keep() == 3
    for bad in ("0", "-1", "lots"):
        monkeypatch.setenv("SRT_LIVE_RECENT", bad)
        with pytest.raises(ValueError, match="SRT_LIVE_RECENT"):
            live_recent_keep()


# ---------------------------------------------------------------------------
# 4. correlation: one query_id across live / timeline / history
# ---------------------------------------------------------------------------

def test_query_id_threads_into_timeline_and_history(metrics_on,
                                                    monkeypatch, tmp_path):
    hist = tmp_path / "hist.jsonl"
    monkeypatch.setenv("SRT_TRACE_TIMELINE", "1")
    monkeypatch.setenv("SRT_METRICS_HISTORY", str(hist))
    from spark_rapids_tpu.obs import history, timeline
    timeline.reset()
    t = _table("lcorr")
    _query("lcorr").run(t)
    q = live.snapshot_all()["recent"][-1]
    qid = q["query_id"]
    spans = [e for e in timeline.events()
             if e.get("ph") == "X" and e.get("args", {}).get("query_id")]
    timeline.reset()
    assert spans and all(e["args"]["query_id"] == qid for e in spans)
    rows = history.load(path=hist, query_id=qid)
    assert len(rows) == 1
    assert rows[0]["fingerprint"] == q["fingerprint"]


def test_top_renderer_draws_shard_bars():
    from spark_rapids_tpu.obs.__main__ import render_top
    lq = live.start("dist_stream", force=True)
    lq.set_shards(4)
    lq.batch_in(100)
    lq.batch_in(100)
    lq.shard_batches_done(4)
    lq.rung("retry", site="dist-dispatch")
    try:
        frame = render_top(live.snapshot_all(), source="test")
    finally:
        lq.finish()
    assert "dist_stream" in frame
    assert frame.count("shard ") == 4
    assert "dist-dispatch:retry" in frame
    done_frame = render_top(live.snapshot_all(), source="test")
    assert "recent:" in done_frame


def test_rows_per_sec_and_eta_are_finite():
    lq = live.start("stream", force=True)
    lq.set_total_batches(10)
    lq.batch_in(500)
    lq.batch_out(500)
    snap = lq.snapshot()
    lq.finish()
    assert math.isfinite(snap["rows_per_sec"])
    assert snap["eta_seconds"] is None or snap["eta_seconds"] >= 0
