"""Named profiler scopes — the NVTX-ranges analog.

The reference's tracing story is NVTX ranges in the cudf Java layer behind
``-Dai.rapids.cudf.nvtx.enabled`` (pom.xml:84, :366-369) plus ``-lineinfo``
device compiles for profiler introspection (ConfigureCUDA.cmake:33-37).  The
TPU equivalents are ``jax.profiler`` trace annotations (visible in
TensorBoard/XPlane captures and Perfetto) and jitted-function naming.

Everything here is a no-op unless ``SRT_TRACE=1`` (config.trace_enabled), so
instrumented code pays nothing in production — the same opt-in contract as
the NVTX toggle.

Usage::

    with trace("convert_to_rows"):
        ...
    @traced
    def shuffle(...): ...

``start_server(port)`` re-exports the on-demand profiler server so a running
job can be attached to (the TPU replacement for attaching nsys to a live
process).
"""

from __future__ import annotations

import functools
from typing import Callable, TypeVar

from ..config import trace_enabled

_F = TypeVar("_F", bound=Callable)


class _NullScope:
    """Shared disabled-tracing context (no generator machinery on the
    cold path — instrumented hot loops enter/exit two empty methods)."""
    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc) -> None:
        return None


_NULL_SCOPE = _NullScope()


def trace(name: str, **attrs):
    """Named scope visible in jax profiler captures (NVTX push/pop analog).

    ``attrs`` pass through as annotation metadata (profiler-visible metric
    labels, e.g. ``trace("shuffle", partitions=8)``).  When tracing is off
    this returns a shared null context: no profiler import, no annotation
    construction, no attr formatting."""
    if not trace_enabled():
        return _NULL_SCOPE
    import jax.profiler
    return jax.profiler.TraceAnnotation(name, **attrs)


def traced(fn: _F) -> _F:
    """Decorator form of :func:`trace`, scope named after the function
    (name computed once at decoration time; the disabled path is a single
    flag check before a plain call — no contextmanager entry)."""
    name = f"{fn.__module__.rsplit('.', 1)[-1]}.{fn.__qualname__}"

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if not trace_enabled():
            return fn(*args, **kwargs)
        with trace(name):
            return fn(*args, **kwargs)

    return wrapper  # type: ignore[return-value]


def start_server(port: int = 9012):
    """Start the on-demand jax profiler server (attach via TensorBoard)."""
    import jax.profiler
    return jax.profiler.start_server(port)
