"""Group-by aggregation, sort-based.

TPU-first redesign of the hash-groupby a GPU engine uses (cuDF's groupby is
part of the reference's capability envelope; BASELINE.json names groupby
throughput as a headline metric): hash tables need scatter-to-random-address,
which the TPU memory system punishes, so groups are formed by the native
multi-key sort (:mod:`.sort`), adjacent-difference boundaries, and
segment reductions over sorted runs.

One host sync materializes the group count; segment reductions run with the
group count bucketed to a power of two so jit caches stay small.

Null semantics follow cuDF/Spark: null keys form their own group (null ==
null for grouping); null *values* are excluded from aggregations; an
all-null group aggregates to null (except counts).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..column import Column
from ..dtypes import (DType, FLOAT64, INT64, TypeId, UINT64)
from ..table import Table
from .common import (compact_indices, grouping_columns,
                     null_safe_equal_adjacent, pow2_bucket)
from .sort import sorted_order

#: Aggregations supported (cuDF basic set).
AGGS = ("count", "count_all", "sum", "min", "max", "mean", "first", "last",
        "var", "std")


def _sum_dtype(dtype: DType) -> DType:
    """Accumulation/result type for sums (Spark semantics: widen)."""
    if dtype.is_floating:
        return FLOAT64
    if dtype.type_id in (TypeId.UINT8, TypeId.UINT16, TypeId.UINT32, TypeId.UINT64):
        return UINT64
    if dtype.type_id == TypeId.DECIMAL32 or dtype.type_id == TypeId.DECIMAL64:
        return DType(TypeId.DECIMAL64, dtype.scale)
    return INT64


def _minmax_identity(dtype: DType, for_min: bool):
    np_dt = dtype.np_dtype
    if dtype.is_floating:
        return np_dt.type(np.inf if for_min else -np.inf)
    info = np.iinfo(np_dt)
    return np_dt.type(info.max if for_min else info.min)


class GroupByResult:
    """Carrier so ``groupby(t, keys).agg(...)`` reads naturally."""

    def __init__(self, table: Table, keys: Sequence[str]):
        self._table = table
        self._keys = list(keys)

    def agg(self, aggs: dict[str, Sequence[str] | str]) -> Table:
        spec = []
        for col, hows in aggs.items():
            if isinstance(hows, str):
                hows = [hows]
            for how in hows:
                out_name = col if len(hows) == 1 else f"{col}_{how}"
                spec.append((col, how, out_name))
        return groupby_agg(self._table, self._keys, spec)


def groupby(table: Table, keys: Sequence[str] | str) -> GroupByResult:
    if isinstance(keys, str):
        keys = [keys]
    return GroupByResult(table, keys)


def groupby_agg(table: Table, keys: Sequence[str],
                aggs: Sequence[tuple[str, str, str]]) -> Table:
    """Aggregate ``aggs`` = [(value_col, how, out_name), ...] grouped by ``keys``.

    Output: one row per group, key columns first (group order = sorted key
    order), then aggregate columns.
    """
    for _, how, _ in aggs:
        if how not in AGGS:
            raise ValueError(f"unsupported aggregation {how!r} (have {AGGS})")

    if table.num_rows == 0:
        return _empty_result(table, keys, aggs)

    # Encode keys once (strings -> dictionary codes), sort, find boundaries.
    key_cols = grouping_columns([table[k] for k in keys])
    perm = sorted_order(key_cols)
    sorted_tbl = table.gather(perm)

    # Group boundaries over the sorted keys (null == null, NaN == NaN).
    boundary = jnp.zeros(table.num_rows, jnp.bool_)
    for kc in key_cols:
        boundary = boundary | null_safe_equal_adjacent(kc.gather(perm))
    group_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    starts = compact_indices(boundary)          # host sync: group count
    num_groups = int(starts.shape[0])
    seg_count = pow2_bucket(num_groups)

    out: list[tuple[str, Column]] = []
    for k in keys:
        out.append((k, sorted_tbl[k].gather(starts)))

    ends = None
    for value_name, how, out_name in aggs:
        col = sorted_tbl[value_name]
        if how in ("first", "last"):
            if ends is None:
                n = table.num_rows
                ends = jnp.concatenate([starts[1:] - 1,
                                        jnp.array([n - 1], starts.dtype)])
            idx = starts if how == "first" else ends
            out.append((out_name, col.gather(idx)))
            continue
        out.append((out_name, _segment_agg(col, group_id, seg_count,
                                           num_groups, how)))
    return Table(out)


def _empty_result(table: Table, keys: Sequence[str],
                  aggs: Sequence[tuple[str, str, str]]) -> Table:
    out: list[tuple[str, Column]] = []
    for k in keys:
        out.append((k, table[k]))
    for value_name, how, out_name in aggs:
        src = table[value_name]
        if how in ("count", "count_all"):
            dtype = INT64
        elif how == "sum":
            dtype = _sum_dtype(src.dtype)
        elif how in ("mean", "var", "std"):
            dtype = FLOAT64
        else:
            dtype = src.dtype
        out.append((out_name, Column(data=jnp.zeros(0, dtype.jnp_dtype),
                                     dtype=dtype)))
    return Table(out)


def _segment_agg(col: Column, group_id: jax.Array, seg_count: int,
                 num_groups: int, how: str) -> Column:
    valid = col.valid_mask()
    counts = jax.ops.segment_sum(valid.astype(jnp.int64), group_id,
                                 num_segments=seg_count)[:num_groups]
    if how == "count":
        return Column(data=counts, dtype=INT64)
    if how == "count_all":
        ones = jnp.ones(col.size, jnp.int64)
        all_counts = jax.ops.segment_sum(ones, group_id,
                                         num_segments=seg_count)[:num_groups]
        return Column(data=all_counts, dtype=INT64)

    data = col.data
    has_valid = counts > 0

    if how in ("sum", "mean", "var", "std"):
        acc_dtype = _sum_dtype(col.dtype)
        vals = jnp.where(valid, data, data.dtype.type(0)).astype(acc_dtype.jnp_dtype)
        sums = jax.ops.segment_sum(vals, group_id,
                                   num_segments=seg_count)[:num_groups]
        if how == "sum":
            return Column(data=sums, validity=has_valid, dtype=acc_dtype)
        # mean/var/std return logical FLOAT64 values: decimals apply 10**scale.
        scale_factor = 10.0 ** col.dtype.scale if col.dtype.is_decimal else 1.0
        fsums = sums.astype(jnp.float64) * scale_factor
        fcounts = counts.astype(jnp.float64)
        if how == "mean":
            mean = fsums / jnp.maximum(fcounts, 1.0)
            return Column(data=mean, validity=has_valid, dtype=FLOAT64)
        # var/std (ddof=1, Spark sample variance)
        sq = jnp.where(valid, data.astype(jnp.float64) * scale_factor, 0.0) ** 2
        sumsq = jax.ops.segment_sum(sq, group_id,
                                    num_segments=seg_count)[:num_groups]
        denom = jnp.maximum(fcounts - 1.0, 1.0)
        var = (sumsq - fsums * fsums / jnp.maximum(fcounts, 1.0)) / denom
        var = jnp.maximum(var, 0.0)             # clamp fp round-off
        ok = counts > 1
        if how == "var":
            return Column(data=var, validity=ok, dtype=FLOAT64)
        return Column(data=jnp.sqrt(var), validity=ok, dtype=FLOAT64)

    # min / max
    for_min = how == "min"
    ident = _minmax_identity(col.dtype, for_min)
    vals = jnp.where(valid, data, ident)
    seg = jax.ops.segment_min if for_min else jax.ops.segment_max
    res = seg(vals, group_id, num_segments=seg_count)[:num_groups]
    return Column(data=res, validity=has_valid, dtype=col.dtype)
