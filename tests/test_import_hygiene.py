"""Import-time behavior contracts.

``import spark_rapids_tpu`` must not initialize the XLA backend: a
multi-host user has to be able to call ``jax.distributed.initialize``
(via ``parallel.init_cluster``) AFTER importing the package, and backend
init forecloses that (jax raises).  The persistent-compile-cache setup is
therefore import-time only for explicitly-configured accelerator
platforms and otherwise deferred to the engine's first compile.
"""

import json
import subprocess
import sys


def test_import_does_not_initialize_backend():
    code = (
        "import jax\n"
        "import spark_rapids_tpu\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge.backends_are_initialized(), \\\n"
        "    'importing spark_rapids_tpu initialized the XLA backend'\n"
        "print('clean')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "clean" in out.stdout


def test_obs_imports_without_jax():
    """``spark_rapids_tpu.obs`` must stay importable without jax: metrics
    post-processing (reading benchmark JSON on a laptop, rendering a
    QueryMetrics) must not drag in the XLA stack.

    The package __init__ itself imports jax, so graft ``obs`` onto a stub
    parent package and import it alone.
    """
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    code = (
        "import sys, types\n"
        f"pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "import spark_rapids_tpu.obs as obs\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing spark_rapids_tpu.obs pulled in jax'\n"
        "qm = obs.QueryMetrics(query_id=1, input_rows=10, input_columns=2)\n"
        "assert 'query_metrics' in qm.to_json()\n"
        "assert obs.counter('x') is obs.NULL_METRIC  # SRT_METRICS unset\n"
        "print('jaxfree')\n"
    )
    import os
    env = dict(os.environ)
    env.pop("SRT_METRICS", None)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout


def test_bucketing_imports_without_jax():
    """``exec.bucketing`` must stay importable without jax: the bucket
    schedule math (capacity planning, waste estimation) is plain integer
    arithmetic that diagnostic tooling runs on hosts without the XLA
    stack.  ``exec/__init__`` itself pulls in jax, so graft both the
    package and an ``exec`` stub and import the module alone."""
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    code = (
        "import sys, types\n"
        "pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "ex = types.ModuleType('spark_rapids_tpu.exec')\n"
        f"ex.__path__ = [{str(pkg_dir / 'spark_rapids_tpu' / 'exec')!r}]\n"
        "sys.modules['spark_rapids_tpu.exec'] = ex\n"
        "import spark_rapids_tpu.exec.bucketing as bk\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing exec.bucketing pulled in jax'\n"
        "assert bk.bucket_capacity(100) == 112\n"
        "assert bk.bucket_capacity(9, floor=8, growth=2.0) == 16\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'bucket_capacity pulled in jax'\n"
        "print('jaxfree')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout


def test_stream_imports_without_jax():
    """``exec.stream`` must stay importable without jax (the config.py
    lazy-import rule): a scheduler deciding whether a plan can
    stream-combine, or validating knob values, must not pay for the XLA
    stack.  Argument validation runs before any engine import, so bad
    arguments raise ValueError while jax stays unloaded."""
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    code = (
        "import sys, types\n"
        "pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "ex = types.ModuleType('spark_rapids_tpu.exec')\n"
        f"ex.__path__ = [{str(pkg_dir / 'spark_rapids_tpu' / 'exec')!r}]\n"
        "sys.modules['spark_rapids_tpu.exec'] = ex\n"
        "import spark_rapids_tpu.exec.stream as st\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing exec.stream pulled in jax'\n"
        "assert 'sum' in st.COMBINABLE_AGGS\n"
        "try:\n"
        "    st.run_plan_stream(None, [], inflight=0)\n"
        "except ValueError:\n"
        "    pass\n"
        "else:\n"
        "    raise AssertionError('inflight=0 did not raise')\n"
        "try:\n"
        "    st.run_plan_stream(None, [], combine='bogus')\n"
        "except ValueError:\n"
        "    pass\n"
        "else:\n"
        "    raise AssertionError(\"combine='bogus' did not raise\")\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'run_plan_stream validation pulled in jax'\n"
        "print('jaxfree')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout


def test_timeline_records_without_jax(tmp_path):
    """``obs.timeline`` must record spans and export Chrome-trace JSON
    without jax (the timeline-off/-on import contract of ISSUE 6): the
    recorder is host-side bookkeeping and the export is plain JSON, so a
    laptop can capture and inspect a timeline with no XLA stack."""
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    out_path = tmp_path / "trace.json"
    code = (
        "import sys, types\n"
        "pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "import spark_rapids_tpu.obs.timeline as tl\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing obs.timeline pulled in jax'\n"
        "assert tl.enabled()  # SRT_TRACE_TIMELINE=1 below\n"
        "with tl.span('work', cat='test', lane='lane-0', batch=0):\n"
        "    tl.instant('tick', cat='test', lane='lane-0')\n"
        f"payload = tl.export_chrome_trace({str(out_path)!r})\n"
        "phases = sorted(e['ph'] for e in payload['traceEvents'])\n"
        "assert phases == ['M', 'X', 'i'], phases\n"
        "assert 'jax' not in sys.modules, 'recording pulled in jax'\n"
        "print('jaxfree')\n"
    )
    import json
    import os
    env = dict(os.environ)
    env["SRT_TRACE_TIMELINE"] = "1"
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout
    # The exported file is loadable JSON in the pinned Chrome-trace shape.
    payload = json.loads(out_path.read_text())
    assert payload["displayTimeUnit"] == "ms"
    assert len(payload["traceEvents"]) == 3


def test_profile_and_regress_import_without_jax(tmp_path):
    """``obs.profile`` and ``obs.regress`` must work without jax: the
    cost ledger's bucket math and the regression gate are exactly the
    post-processing a laptop runs over benchmark JSONL artifacts."""
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    hist = tmp_path / "hist.jsonl"
    code = (
        "import sys, types\n"
        "pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "import spark_rapids_tpu.obs.profile as pf\n"
        "import spark_rapids_tpu.obs.regress as rg\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing obs.profile/regress pulled in jax'\n"
        "b = pf.attribute(1.0, 0.1, 0.6, 0.2, ici_seconds=0.1,\n"
        "                 host_sync_seconds=0.05)\n"
        "total = sum(v for k, v in b.items() if k.endswith('_seconds'))\n"
        "assert abs(total - 1.0) < 1e-6, b\n"
        "assert b['compute_seconds'] == 0.5, b\n"
        "import json\n"
        "rec = {'fingerprint': 'f1', 'timings': {'total_seconds': 1.0},\n"
        "       'host': {'syncs': 2}}\n"
        f"with open({str(hist)!r}, 'w') as f:\n"
        "    f.write(json.dumps(rec) + '\\n')\n"
        "    rec2 = dict(rec, timings={'total_seconds': 9.0})\n"
        "    f.write(json.dumps(rec2) + '\\n')\n"
        f"report = rg.check_history(path={str(hist)!r}, tolerance=0.5)\n"
        "assert report['breaches'], report\n"
        "try:\n"
        f"    rg.gate(path={str(hist)!r}, tolerance=0.5)\n"
        "except rg.RegressionError as err:\n"
        "    assert err.breaches\n"
        "else:\n"
        "    raise AssertionError('9x slowdown did not trip the gate')\n"
        "assert 'jax' not in sys.modules, 'the gate pulled in jax'\n"
        "print('jaxfree')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout


def test_live_and_server_import_without_jax():
    """``obs.live`` and ``obs.server`` must work without jax: the live
    registry is host-side bookkeeping and the exporter renders text/JSON,
    so a monitoring sidecar (or ``python -m spark_rapids_tpu.obs top``)
    never pays for the XLA stack.  With ``SRT_METRICS`` unset and nobody
    observing, ``live.start`` must hand back the shared null record."""
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    code = (
        "import sys, types\n"
        "pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "import spark_rapids_tpu.obs.live as live\n"
        "import spark_rapids_tpu.obs.server as server\n"
        "import spark_rapids_tpu.obs.__main__ as top\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing obs.live/server pulled in jax'\n"
        "assert live.start('run') is live.NULL_LIVE  # SRT_METRICS unset\n"
        "assert live.snapshot_all()['in_flight'] == []\n"
        "lq = live.start('run', force=True)\n"
        "lq.batch_out(10)\n"
        "text = server.prometheus_text()\n"
        "assert 'srt_live_queries 1' in text, text\n"
        "frame = top.render_top(live.snapshot_all(), source='test')\n"
        "assert 'running=1' in frame, frame\n"
        "lq.finish()\n"
        "assert 'jax' not in sys.modules, 'live telemetry pulled in jax'\n"
        "print('jaxfree')\n"
    )
    import os
    env = dict(os.environ)
    env.pop("SRT_METRICS", None)
    env.pop("SRT_LIVE_SERVER", None)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout


def test_flight_bundle_doctor_import_without_jax(tmp_path):
    """The postmortem surface (obs.flight, obs.bundle, obs.doctor) must
    work without jax: the flight ring is host-side bookkeeping, bundles
    are plain JSON, and the doctor is exactly the tool an operator runs
    on a laptop against a bundle scp'd out of an incident."""
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    bdir = tmp_path / "bundles"
    code = (
        "import sys, types\n"
        "pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "import spark_rapids_tpu.obs.flight as flight\n"
        "import spark_rapids_tpu.obs.bundle as bundle\n"
        "import spark_rapids_tpu.obs.doctor as doctor\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing obs.flight/bundle/doctor pulled in jax'\n"
        "assert flight.trace_span('x', {}) is None  # SRT_METRICS unset\n"
        "ring = flight.FlightRing(7, capacity=4)\n"
        "ring.append('step', 'flight', 1.0, 2.0, 'lane-0', {'batch': 0})\n"
        "assert ring.stats()['events_recorded'] == 1\n"
        "path = bundle.dump('failure', query_id=7,\n"
        "                   error=ValueError('boom'))  # SRT_BUNDLE_DIR set\n"
        "assert path is not None, 'bundle not written'\n"
        "import json\n"
        "payload = json.load(open(path))\n"
        "report = doctor.diagnose(payload)\n"
        "assert report['findings'], report\n"
        "assert doctor.main(path) == 0\n"
        "assert 'jax' not in sys.modules, 'the postmortem path pulled jax'\n"
        "print('jaxfree')\n"
    )
    import os
    env = dict(os.environ)
    for k in ("SRT_METRICS", "SRT_SLO_MS", "SRT_METRICS_HISTORY"):
        env.pop(k, None)
    env["SRT_BUNDLE_DIR"] = str(bdir)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout


def test_capacity_advisor_import_without_jax(tmp_path):
    """The capacity accountant + advisor (obs.capacity) must work
    without jax: saturation math and autoscaling advice are exactly what
    a fleet-controller sidecar evaluates, and it never runs queries.
    The offline CLI path over a metrics-history JSONL is jax-free too."""
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    hist = tmp_path / "hist.jsonl"
    hist.write_text(json.dumps({
        "fingerprint": "fpA", "mode": "table", "total_seconds": 1.0,
        "timings": {"execute_seconds": 0.9},
        "serve": {"queue_wait_seconds": 0.5, "admission": "queued"},
        "cost": {"hbm": {"peak_bytes": 1048576}}}) + "\n")
    code = (
        "import sys, types\n"
        "pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "import spark_rapids_tpu.obs.capacity as capacity\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing obs.capacity pulled in jax'\n"
        "capacity.feed_completion('table', 0.1, 'fp')  # SRT_METRICS unset\n"
        "snap = capacity.snapshot(window_s=60)\n"
        "assert snap['littles_law']['completions'] == 0\n"
        "assert capacity.recommend(snap) == []\n"
        "import spark_rapids_tpu.obs.__main__ as cli\n"
        f"payload = cli._advise_history({str(hist)!r}, last=16)\n"
        "assert payload['snapshot']['littles_law']['completions'] == 1\n"
        "assert 'jax' not in sys.modules, 'the advisor path pulled jax'\n"
        "print('jaxfree')\n"
    )
    import os
    env = dict(os.environ)
    for k in ("SRT_METRICS", "SRT_CAPACITY_WINDOW_S",
              "SRT_CAPACITY_TARGETS"):
        env.pop(k, None)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout


def test_workload_import_without_jax(tmp_path):
    """The workload analyzer (obs.workload) must work without jax: a
    fleet sidecar mines hotspots and subplan overlaps from history
    JSONL and scheduler feeds, never running a query.  The gated feeds,
    the pure derive/recommend core, and the offline ``obs workload
    --history`` replay are all jax-free."""
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    hist = tmp_path / "hist.jsonl"
    with open(hist, "w") as f:
        for fp in ("fpA", "fpB"):
            f.write(json.dumps({
                "fingerprint": fp, "mode": "table", "total_seconds": 1.0,
                "timings": {"execute_seconds": 0.8},
                "input": {"rows": 1000},
                "steps": [{"kind": "Filter", "describe": "Filter[v>10]",
                           "seconds": 0.6, "rows_in": 1000,
                           "rows_out": 500}]}) + "\n")
    code = (
        "import sys, types\n"
        "pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "import spark_rapids_tpu.obs.workload as workload\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing obs.workload pulled in jax'\n"
        "assert workload.feed_query(None, object()) == []  # metrics off\n"
        "workload.feed_ticket('fp', object())\n"
        "snap = workload.snapshot(window_s=60)\n"
        "assert snap['queries'] == 0 and snap['tickets'] == 0\n"
        "assert workload.recommend(snap) == []\n"
        "assert workload.verdict_for([]) == 'quiet'\n"
        "import spark_rapids_tpu.obs.__main__ as cli\n"
        f"payload = cli._workload_history({str(hist)!r}, last=16)\n"
        "hot = payload['snapshot']['hotspots']\n"
        "assert hot and hot[0]['kind'] == 'Filter', hot\n"
        "assert payload['snapshot']['overlaps'], payload\n"
        "assert 'jax' not in sys.modules, 'the workload path pulled jax'\n"
        "print('jaxfree')\n"
    )
    import os
    env = dict(os.environ)
    for k in ("SRT_METRICS", "SRT_WORKLOAD_WINDOW_S", "SRT_WORKLOAD_TOPK",
              "SRT_METRICS_HISTORY"):
        env.pop(k, None)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout


def test_cold_import_does_not_load_obs():
    """A plain ``import spark_rapids_tpu`` must not pay for the metrics
    subsystem (it is lazy-imported at the first metered region)."""
    code = (
        "import sys\n"
        "import spark_rapids_tpu\n"
        "assert 'spark_rapids_tpu.obs' not in sys.modules, \\\n"
        "    'cold import loaded the obs subsystem'\n"
        "print('lazy')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "lazy" in out.stdout


def test_serve_imports_without_jax():
    """The serving layer (``spark_rapids_tpu.serve``) must work without
    jax at import AND for everything short of executing a plan: knob
    validation, admission math over history estimates, result-cache
    keying, and the fairness gate are host-side scheduling a control
    plane runs with no XLA stack."""
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    code = (
        "import sys, types\n"
        "pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "import spark_rapids_tpu.serve as serve\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing spark_rapids_tpu.serve pulled in jax'\n"
        "from spark_rapids_tpu import config\n"
        "assert config.serve_max_concurrent() == 4  # env unset below\n"
        "assert config.serve_hbm_budget() is None\n"
        "assert config.serve_policy() == 'rr'\n"
        "assert config.result_cache_bytes() is None\n"
        "a = serve.AdmissionController(budget=100)\n"
        "try:\n"
        "    a.check(200)\n"
        "except serve.AdmissionRejected:\n"
        "    pass\n"
        "else:\n"
        "    raise AssertionError('over-budget estimate not rejected')\n"
        "assert a.acquire(1, 60) is False and a.claimed_bytes() == 60\n"
        "a.release(1)\n"
        "assert a.claimed_bytes() == 0\n"
        "c = serve.ResultCache(cap_bytes=None)\n"
        "assert c.get(('k',)) == (None, False)  # disabled: always miss\n"
        "c.put(('k',), object())\n"
        "assert c.stats()['entries'] == 0\n"
        "assert serve.input_digest(iter([])) is None  # iterators unkeyed\n"
        "from spark_rapids_tpu.serve.scheduler import _FairGate\n"
        "g = _FairGate('rr')\n"
        "g.register(1, 1.0)\n"
        "g.turn(1)  # lone waiter never blocks\n"
        "g.unregister(1)\n"
        "assert 'jax' not in sys.modules, 'serving logic pulled in jax'\n"
        "print('jaxfree')\n"
    )
    import os
    env = dict(os.environ)
    for k in ("SRT_METRICS", "SRT_SERVE_MAX_CONCURRENT",
              "SRT_SERVE_HBM_BUDGET", "SRT_SERVE_POLICY",
              "SRT_RESULT_CACHE"):
        env.pop(k, None)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout


def test_semantic_and_views_import_without_jax():
    """The semantic subplan cache (serve/semantic.py) and the
    materialized-view registry (views/) must stay jax-free at import
    AND for their control-plane logic: stats, the bundle block,
    knob-gated registration errors, and the ``/views`` payload are
    operator surfaces a monitoring process uses with no XLA stack."""
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    code = (
        "import sys, types\n"
        "pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "from spark_rapids_tpu.serve import semantic\n"
        "from spark_rapids_tpu import views\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing serve.semantic/views pulled in jax'\n"
        "from spark_rapids_tpu import config\n"
        "assert config.semantic_cache_enabled() is False  # env unset\n"
        "assert config.semantic_cache_bytes() == 256 << 20\n"
        "assert config.views_enabled() is False\n"
        "assert config.views_auto() is False\n"
        "s = semantic.stats()\n"
        "assert s['enabled'] is False and s['entries'] == 0\n"
        "assert s['hit_rate'] == 0.0\n"
        "b = semantic.bundle_block(None)\n"
        "assert b == {'enabled': False, 'used': False,\n"
        "             'prefix_fingerprints': [],\n"
        "             'hot_prefix_recompute': False}\n"
        "c = semantic.SemanticCache(cap_bytes=1024)\n"
        "assert c.get('missing') is None\n"
        "assert c.stats()['entries'] == 0\n"
        "try:\n"
        "    views.register('v', object())\n"
        "except ValueError as e:\n"
        "    assert 'SRT_VIEWS' in str(e)\n"
        "else:\n"
        "    raise AssertionError('SRT_VIEWS off did not refuse')\n"
        "p = views.views_payload()\n"
        "assert p['schema_version'] == 1 and p['views'] == []\n"
        "assert p['views_enabled'] is False\n"
        "assert 'jax' not in sys.modules, 'semantic logic pulled in jax'\n"
        "print('jaxfree')\n"
    )
    import os
    env = dict(os.environ)
    for k in ("SRT_METRICS", "SRT_SEMANTIC_CACHE",
              "SRT_SEMANTIC_CACHE_BYTES", "SRT_VIEWS", "SRT_VIEWS_AUTO"):
        env.pop(k, None)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout


def test_watchdog_imports_without_jax():
    """The mesh stall watchdog (resilience.watchdog) must stay jax-free
    at import: the guard is plain threading, and the dist-resilience
    surface (DistStallError, dist_guard, the fault grammar) is part of
    the resilience package's jax-free contract."""
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    code = (
        "import sys, types\n"
        "pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "import spark_rapids_tpu.resilience.watchdog as wd\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing resilience.watchdog pulled in jax'\n"
        "assert wd.dist_guard('x', lambda: 7, timeout=5.0) == 7\n"
        "import threading\n"
        "ev = threading.Event()\n"
        "try:\n"
        "    wd.dist_guard('x', lambda: ev.wait(30), timeout=0.1)\n"
        "except wd.DistStallError:\n"
        "    ev.set()\n"
        "else:\n"
        "    raise AssertionError('stalled guard did not raise')\n"
        "assert 'jax' not in sys.modules, 'dist_guard pulled in jax'\n"
        "print('jaxfree')\n"
    )
    import os
    env = dict(os.environ)
    env.pop("SRT_DIST_TIMEOUT", None)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout
