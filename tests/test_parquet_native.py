"""Native Parquet page decoder tests (pyarrow as writer and oracle).

Mirrors the reference's oracle strategy (SURVEY.md §4: round-trip equality
against a known-good implementation) for the decode direction: files written
by pyarrow across the encoding/codec/page-version matrix must decode to
tables equal to what the Arrow reader produces.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import assert_tables_equal
from spark_rapids_tpu.io import from_arrow, read_parquet, read_parquet_native
from spark_rapids_tpu.io.parquet_native import decode_rle_bp, parse_rle_runs

#: compile-heavy module: full tier only (smoke = -m 'not full').
pytestmark = pytest.mark.full


def _mixed_arrow_table(n=1000, seed=3, with_nulls=True):
    rng = np.random.default_rng(seed)
    def maybe_null(arr):
        if not with_nulls:
            return arr
        mask = rng.random(n) < 0.25
        return pa.array(arr, mask=mask)
    cols = {
        "i32": maybe_null(rng.integers(-1 << 20, 1 << 20, n).astype(np.int32)),
        "i64": maybe_null(rng.integers(-1 << 40, 1 << 40, n).astype(np.int64)),
        "f32": maybe_null(rng.normal(size=n).astype(np.float32)),
        "f64": maybe_null(rng.normal(size=n)),
        "b": maybe_null(rng.integers(0, 2, n).astype(np.bool_)),
        "u32": maybe_null(rng.integers(0, 1 << 31, n).astype(np.uint32)),
        "s": pa.array(
            [None if with_nulls and rng.random() < 0.2
             else f"row-{rng.integers(0, 50)}" for _ in range(n)],
            pa.string()),
    }
    return pa.table(cols)


def _check_file(tmp_path, at, **write_kwargs):
    path = tmp_path / "t.parquet"
    pq.write_table(at, path, **write_kwargs)
    got = read_parquet_native(path)
    want = from_arrow(pq.read_table(path))
    assert_tables_equal(got, want)
    return got


class TestDecodeMatrix:
    @pytest.mark.parametrize("compression", [None, "snappy", "zstd", "gzip"])
    def test_codecs(self, tmp_path, compression):
        _check_file(tmp_path, _mixed_arrow_table(),
                    compression=compression)

    @pytest.mark.parametrize("version", ["1.0", "2.0"])
    def test_data_page_versions(self, tmp_path, version):
        _check_file(tmp_path, _mixed_arrow_table(),
                    data_page_version=version)

    @pytest.mark.parametrize("use_dictionary", [True, False])
    def test_dictionary_toggle(self, tmp_path, use_dictionary):
        _check_file(tmp_path, _mixed_arrow_table(),
                    use_dictionary=use_dictionary)

    def test_no_nulls(self, tmp_path):
        _check_file(tmp_path, _mixed_arrow_table(with_nulls=False))

    def test_multiple_row_groups_and_pages(self, tmp_path):
        _check_file(tmp_path, _mixed_arrow_table(n=5000),
                    row_group_size=700, data_page_size=1024)

    def test_plain_fallback_after_dict_overflow(self, tmp_path):
        # A tiny dictionary page limit forces pyarrow to fall back to PLAIN
        # data pages mid-chunk: both encodings must coexist in one chunk.
        rng = np.random.default_rng(0)
        at = pa.table({"s": pa.array([f"unique-string-{i}-{rng.integers(1<<30)}"
                                      for i in range(2000)])})
        _check_file(tmp_path, at, dictionary_pagesize_limit=1024,
                    data_page_size=2048)

    def test_decimal_and_date(self, tmp_path):
        import datetime
        import decimal as pydec
        at = pa.table({
            "d32": pa.array([pydec.Decimal("1.23"), None,
                             pydec.Decimal("-99.01")],
                            pa.decimal128(7, 2)),
            "d64": pa.array([pydec.Decimal("123456.789"), None,
                             pydec.Decimal("-1.001")],
                            pa.decimal128(15, 3)),
            "day": pa.array([datetime.date(2026, 7, 30), None,
                             datetime.date(1969, 12, 31)]),
        })
        _check_file(tmp_path, at)

    def test_decimal_stored_as_integer(self, tmp_path):
        # Spec allows narrow decimals in INT32/INT64 physical lanes; the
        # dtype must follow precision (arrow-engine mapping), not the lanes.
        import decimal as pydec
        at = pa.table({
            "d32": pa.array([pydec.Decimal("1.23"), None],
                            pa.decimal128(7, 2)),
            "d64": pa.array([pydec.Decimal("1.001"), None],
                            pa.decimal128(15, 3)),
        })
        try:
            _check_file(tmp_path, at, store_decimal_as_integer=True)
        except TypeError:
            pytest.skip("pyarrow without store_decimal_as_integer")

    def test_timestamps(self, tmp_path):
        at = pa.table({
            "ts_us": pa.array([1_700_000_000_000_000, None, 12345],
                              pa.timestamp("us")),
            "ts_ms": pa.array([1_700_000_000_000, None, -5],
                              pa.timestamp("ms")),
        })
        _check_file(tmp_path, at)

    def test_column_pruning(self, tmp_path):
        at = _mixed_arrow_table()
        path = tmp_path / "t.parquet"
        pq.write_table(at, path)
        got = read_parquet_native(path, columns=["i64", "s"])
        assert list(got.names) == ["i64", "s"]
        want = from_arrow(pq.read_table(path, columns=["i64", "s"]))
        assert_tables_equal(got, want)

    def test_missing_column_raises(self, tmp_path):
        path = tmp_path / "t.parquet"
        pq.write_table(_mixed_arrow_table(n=10), path)
        with pytest.raises(KeyError):
            read_parquet_native(path, columns=["nope"])

    def test_empty_file(self, tmp_path):
        at = pa.table({"a": pa.array([], pa.int64()),
                       "s": pa.array([], pa.string())})
        got = _check_file(tmp_path, at)
        assert got.num_rows == 0

    def test_incompressible_page_roundtrips(self, tmp_path):
        # Page whose compressed size ~= uncompressed size must still be
        # decompressed (no size-equality shortcut).
        rng = np.random.default_rng(11)
        at = pa.table({"x": rng.integers(-1 << 60, 1 << 60, 500)})
        _check_file(tmp_path, at, compression="snappy",
                    use_dictionary=False)

    def test_native_flat_filters_supported(self, tmp_path):
        # Flat (col, op, val) conjunctions route to the native reader
        # (statistics pruning + exact device-side re-filter) and must
        # match Arrow's filtered read exactly.
        path = tmp_path / "t.parquet"
        pq.write_table(_mixed_arrow_table(n=200), path)
        filt = [("i32", ">", 0), ("s", "!=", "row-7")]
        got = read_parquet(path, engine="native", filters=filt)
        want = from_arrow(pq.read_table(path, filters=filt))
        assert_tables_equal(got, want)

    def test_native_rejects_nested_dnf_filters(self, tmp_path):
        # OR-of-conjunctions (list of lists) stays outside the native
        # envelope: engine="native" raises, engine="auto" falls to Arrow.
        path = tmp_path / "t.parquet"
        pq.write_table(_mixed_arrow_table(n=10), path)
        dnf = [[("i32", ">", 0)], [("i64", "<", 0)]]
        with pytest.raises(ValueError):
            read_parquet(path, engine="native", filters=dnf)
        got = read_parquet(path, engine="auto", filters=dnf)
        want = from_arrow(pq.read_table(path, filters=dnf))
        assert_tables_equal(got, want)

    def test_all_null_column(self, tmp_path):
        at = pa.table({"x": pa.array([None, None, None], pa.int64())})
        _check_file(tmp_path, at)

    def test_all_null_string_column(self, tmp_path):
        at = pa.table({"s": pa.array([None, None, None], pa.string())})
        got = _check_file(tmp_path, at)
        assert got["s"].to_pylist() == [None, None, None]

    def test_tz_aware_timestamp_rejected(self, tmp_path):
        path = tmp_path / "t.parquet"
        pq.write_table(pa.table({"ts": pa.array([1, 2],
                                                pa.timestamp("us", tz="UTC"))}),
                       path)
        with pytest.raises(NotImplementedError):
            read_parquet_native(path)

    def test_empty_strings_and_unicode(self, tmp_path):
        at = pa.table({"s": pa.array(["", "wörld", None, "", "日本語", "x"])})
        _check_file(tmp_path, at)


class TestEngineDispatch:
    def test_auto_uses_native_result(self, tmp_path):
        path = tmp_path / "t.parquet"
        pq.write_table(_mixed_arrow_table(n=100), path)
        assert_tables_equal(read_parquet(path, engine="auto"),
                            read_parquet(path, engine="arrow"))

    def test_native_reads_lists_rejects_structs(self, tmp_path):
        # LIST schemas are in-envelope now (repetition levels,
        # tests/test_nested.py); STRUCT groups still fall back to Arrow.
        path = tmp_path / "t.parquet"
        pq.write_table(pa.table({"l": pa.array([[1, 2], [3]])}), path)
        assert read_parquet(path, engine="native")["l"].to_pylist() == \
            [[1, 2], [3]]
        spath = tmp_path / "s.parquet"
        pq.write_table(pa.table({"r": pa.array(
            [{"a": 1}], pa.struct([("a", pa.int64())]))}), spath)
        with pytest.raises(NotImplementedError):
            read_parquet(spath, engine="native")

    def test_auto_falls_back_on_delta_encoding(self, tmp_path):
        path = tmp_path / "t.parquet"
        pq.write_table(pa.table({"x": pa.array(range(100), pa.int64())}),
                       path, use_dictionary=False, version="2.6",
                       column_encoding={"x": "DELTA_BINARY_PACKED"})
        with pytest.raises(NotImplementedError):
            read_parquet(path, engine="native")
        t = read_parquet(path, engine="auto")        # silent Arrow fallback
        assert t["x"].to_pylist() == list(range(100))

    def test_bad_engine(self, tmp_path):
        with pytest.raises(ValueError):
            read_parquet(tmp_path / "x.parquet", engine="gpu")


class TestRleKernel:
    """Direct unit tests of the RLE/bit-packed hybrid decoder against a
    pure-python encoder (the format spec, independently re-implemented)."""

    @staticmethod
    def _encode(values, bit_width, runs):
        """Encode ``values`` as the given (kind, count) run plan."""
        out = bytearray()
        pos = 0
        def varint(v):
            while True:
                b = v & 0x7F
                v >>= 7
                out.append(b | (0x80 if v else 0))
                if not v:
                    break
        for kind, count in runs:
            if kind == "rle":
                varint(count << 1)
                out.extend(int(values[pos]).to_bytes((bit_width + 7) // 8,
                                                     "little"))
                pos += count
            else:
                assert count % 8 == 0
                varint(((count // 8) << 1) | 1)
                acc = 0
                nbits = 0
                for v in values[pos:pos + count]:
                    acc |= int(v) << nbits
                    nbits += bit_width
                    while nbits >= 8:
                        out.append(acc & 0xFF)
                        acc >>= 8
                        nbits -= 8
                if nbits:
                    out.append(acc & 0xFF)
                pos += count
        assert pos == len(values)
        return bytes(out)

    @pytest.mark.parametrize("bit_width", [1, 2, 3, 5, 7, 8, 12, 17, 20])
    def test_mixed_runs(self, bit_width):
        rng = np.random.default_rng(bit_width)
        hi = (1 << bit_width) - 1
        plan = [("rle", 7), ("bp", 16), ("rle", 300), ("bp", 64), ("rle", 1)]
        n = sum(c for _, c in plan)
        values = np.zeros(n, np.int64)
        pos = 0
        for kind, count in plan:
            if kind == "rle":
                values[pos:pos + count] = rng.integers(0, hi + 1)
            else:
                values[pos:pos + count] = rng.integers(0, hi + 1, count)
            pos += count
        buf = self._encode(values, bit_width, plan)
        got = np.asarray(decode_rle_bp(buf, bit_width, n))
        np.testing.assert_array_equal(got, values)

    def test_bit_packed_tail_overrun(self):
        # Bit-packed runs cover multiples of 8; the decoder must clamp to
        # the requested count.
        values = np.arange(8) % 4
        buf = self._encode(values, 2, [("bp", 8)])
        got = np.asarray(decode_rle_bp(buf, 2, 5))
        np.testing.assert_array_equal(got, values[:5])

    def test_exhausted_stream_raises(self):
        values = np.ones(4, np.int64)
        buf = self._encode(values, 1, [("rle", 4)])
        with pytest.raises(ValueError):
            parse_rle_runs(buf, 1, 100)

    def test_width_zero(self):
        got = np.asarray(decode_rle_bp(b"", 0, 17))
        np.testing.assert_array_equal(got, np.zeros(17, np.int32))

    @pytest.mark.parametrize("bit_width", [1, 3, 8, 17])
    def test_native_parser_matches_python(self, bit_width):
        ffi = pytest.importorskip("spark_rapids_tpu.ffi")
        try:
            ffi.load()
        except Exception:
            pytest.skip("native host library unavailable")
        from spark_rapids_tpu.io.parquet_native import count_rle_ones
        rng = np.random.default_rng(bit_width)
        hi = (1 << bit_width) - 1
        plan = [("bp", 24), ("rle", 100), ("bp", 8), ("rle", 3), ("rle", 7)]
        n = sum(c for _, c in plan)
        values = rng.integers(0, hi + 1, n)
        pos = 0
        for kind, cnt in plan:          # RLE spans must be constant
            if kind == "rle":
                values[pos:pos + cnt] = values[pos]
            pos += cnt
        buf = self._encode(values, bit_width, plan)
        py = parse_rle_runs(buf, bit_width, n)
        nat, ones = ffi.parse_rle_runs(buf, bit_width, n)
        for key in ("out_start", "count", "rle_value", "bp_bit_base",
                    "is_rle"):
            np.testing.assert_array_equal(nat[key], py[key], err_msg=key)
        if bit_width == 1:
            assert ones == count_rle_ones(buf, py, n) == int(values.sum())
        else:
            assert ones is None

    def test_native_parser_exhausted_stream(self):
        ffi = pytest.importorskip("spark_rapids_tpu.ffi")
        try:
            ffi.load()
        except Exception:
            pytest.skip("native host library unavailable")
        buf = self._encode(np.ones(4, np.int64), 1, [("rle", 4)])
        with pytest.raises(ValueError):
            ffi.parse_rle_runs(buf, 1, 100)
