"""Oracle tests for the TPC-DS logistics family (tpcds_q_logistics.py).

Same contract as tests/test_tpcds.py: every query is checked against an
independent pandas re-implementation of the same semantics at a small
scale (the bank must not be its own oracle, SURVEY.md §4).
"""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.models import tpcds
from spark_rapids_tpu.models.tpcds_queries import QUERIES

from test_tpcds import _assert_frame

#: compile-heavy module: full tier only (smoke = -m 'not full').
pytestmark = pytest.mark.full

SF_ROWS = 20_000


@pytest.fixture(scope="module")
def data():
    return tpcds.generate(SF_ROWS, seed=7)


@pytest.fixture(scope="module")
def pdf(data):
    out = {}
    for nm in data.names():
        t = getattr(data, nm)
        out[nm] = pd.DataFrame(
            {c: pd.array(t[c].to_pylist()) for c in t.names})
    return out


def _lag_oracle(pdf, fact, pfx, wh_key, site_dim, site_key, site_fk,
                site_name):
    dd, sm, wh = pdf["date_dim"], pdf["ship_mode"], pdf["warehouse"]
    dds = dd[dd.d_month_seq.between(0, 11)].d_date_sk
    j = (fact[fact[f"{pfx}_ship_date_sk"].isin(dds)]
         .merge(sm[["sm_ship_mode_sk", "sm_type_id"]],
                left_on=f"{pfx}_ship_mode_sk",
                right_on="sm_ship_mode_sk"))
    lag = (j[f"{pfx}_ship_date_sk"]
           - j[f"{pfx}_sold_date_sk"]).to_numpy(dtype=float)
    j = j.assign(
        d30=((lag <= 30)).astype("int64"),
        d60=((lag > 30) & (lag <= 60)).astype("int64"),
        d90=((lag > 60) & (lag <= 90)).astype("int64"),
        d120=((lag > 90) & (lag <= 120)).astype("int64"),
        dmore=(lag > 120).astype("int64"))
    keys = [wh_key, "sm_type_id", site_fk]
    g = (j.groupby(keys, dropna=False)
         [["d30", "d60", "d90", "d120", "dmore"]].sum().reset_index()
         .rename(columns={"d30": "days_30", "d60": "days_60",
                          "d90": "days_90", "d120": "days_120",
                          "dmore": "days_more"}))
    for c in ("days_30", "days_60", "days_90", "days_120", "days_more"):
        g[c] = g[c].astype("int64")
    g = (g.merge(wh[["w_warehouse_sk", "w_warehouse_name"]],
                 left_on=wh_key, right_on="w_warehouse_sk")
         .drop(columns=["w_warehouse_sk"]))
    g["sm_type"] = [tpcds.SHIP_MODE_TYPES[i - 1] for i in g.sm_type_id]
    g = (g.merge(site_dim[[site_key, site_name]],
                 left_on=site_fk, right_on=site_key)
         .drop(columns=[site_key] if site_key != site_fk else []))
    return g.sort_values(keys).head(100)


def test_q62(data, pdf):
    got = QUERIES["q62"](data)
    want = _lag_oracle(pdf, pdf["web_sales"], "ws", "ws_warehouse_sk",
                       pdf["web_site"], "web_site_sk", "ws_web_site_sk",
                       "web_name")
    _assert_frame(got, want)


def test_q99(data, pdf):
    got = QUERIES["q99"](data)
    want = _lag_oracle(pdf, pdf["catalog_sales"], "cs", "cs_warehouse_sk",
                       pdf["call_center"], "cc_call_center_sk",
                       "cs_call_center_sk", "cc_name")
    _assert_frame(got, want)


def test_q21(data, pdf):
    got = QUERIES["q21"](data)
    inv, it, wh = pdf["inventory"], pdf["item"], pdf["warehouse"]
    pivot = tpcds.DATE_SK0 + 360
    items = it[it.i_current_price.between(20.0, 60.0)].i_item_sk
    j = inv[inv.inv_item_sk.isin(items)
            & inv.inv_date_sk.between(pivot - 30, pivot + 30)].copy()
    j["before"] = j.inv_quantity_on_hand.where(j.inv_date_sk < pivot, 0)
    j["after"] = j.inv_quantity_on_hand.where(j.inv_date_sk >= pivot, 0)
    g = (j.groupby(["inv_warehouse_sk", "inv_item_sk"], dropna=False)
         .agg(inv_before=("before", lambda s: s.sum(min_count=1)),
              inv_after=("after", lambda s: s.sum(min_count=1)))
         .reset_index())
    before = g.inv_before.to_numpy(dtype=float)
    after = g.inv_after.to_numpy(dtype=float)
    with np.errstate(invalid="ignore", divide="ignore"):
        ratio = after / before
    keep = (np.nan_to_num(before) > 0) & (ratio >= 2.0 / 3.0) \
        & (ratio <= 3.0 / 2.0)
    g = g[keep]
    g = (g.merge(wh[["w_warehouse_sk", "w_warehouse_name"]],
                 left_on="inv_warehouse_sk", right_on="w_warehouse_sk")
         .drop(columns=["w_warehouse_sk"])
         .merge(it[["i_item_sk", "i_item_id"]],
                left_on="inv_item_sk", right_on="i_item_sk")
         .drop(columns=["i_item_sk"]))
    g = g.sort_values(["inv_warehouse_sk", "inv_item_sk"]).head(100)
    for c in ("inv_before", "inv_after"):
        g[c] = g[c].astype("int64")
    _assert_frame(got, g)


def _in_stock_oracle(pdf, fact, date_col, item_col, price_lo, price_hi,
                     lo_d, hi_d):
    inv, it = pdf["inventory"], pdf["item"]
    qoh = inv.inv_quantity_on_hand.to_numpy(dtype=float)
    inv_items = set(inv[(qoh >= 100) & (qoh <= 500)
                        & inv.inv_date_sk.between(lo_d, hi_d)
                        .to_numpy(dtype=bool)].inv_item_sk)
    dts = fact[date_col].to_numpy(dtype=float)
    sold = set(fact[(dts >= lo_d) & (dts <= hi_d)][item_col].dropna())
    price = it.i_current_price.to_numpy(dtype=float)
    want = it[(price >= price_lo) & (price <= price_hi)
              & it.i_item_sk.isin(inv_items).to_numpy(dtype=bool)
              & it.i_item_sk.isin(sold).to_numpy(dtype=bool)]
    return (want[["i_item_sk", "i_item_id", "i_current_price"]]
            .sort_values("i_item_sk").head(100))


def test_q37(data, pdf):
    got = QUERIES["q37"](data)
    want = _in_stock_oracle(pdf, pdf["catalog_sales"], "cs_sold_date_sk",
                            "cs_item_sk", 20.0, 50.0,
                            tpcds.DATE_SK0 + 300, tpcds.DATE_SK0 + 360)
    _assert_frame(got, want, float_cols=("i_current_price",))


def test_q82(data, pdf):
    got = QUERIES["q82"](data)
    want = _in_stock_oracle(pdf, pdf["store_sales"], "ss_sold_date_sk",
                            "ss_item_sk", 30.0, 60.0,
                            tpcds.DATE_SK0 + 60, tpcds.DATE_SK0 + 120)
    _assert_frame(got, want, float_cols=("i_current_price",))


def test_q22(data, pdf):
    got = QUERIES["q22"](data)
    inv, it = pdf["inventory"], pdf["item"]
    base = (inv[inv.inv_date_sk.between(tpcds.DATE_SK0,
                                        tpcds.DATE_SK0 + 330)]
            .merge(it[["i_item_sk", "i_category_id", "i_brand_id"]],
                   left_on="inv_item_sk", right_on="i_item_sk"))
    rows = []
    leaf = (base.groupby(["i_category_id", "i_brand_id"], dropna=False)
            ["inv_quantity_on_hand"].mean().reset_index())
    for c, b, q in leaf.itertuples(index=False):
        rows.append((int(c), int(b), float(q)))
    cat = (base.groupby("i_category_id", dropna=False)
           ["inv_quantity_on_hand"].mean().reset_index())
    for c, q in cat.itertuples(index=False):
        rows.append((int(c), None, float(q)))
    rows.append((None, None, float(base.inv_quantity_on_hand.mean())))
    rows.sort(key=lambda r: (round(r[2], 6) if r[2] is not None
                             else float("inf"),
                             r[0] if r[0] is not None else -1,
                             r[1] if r[1] is not None else -1))
    rows = rows[:100]
    want = pd.DataFrame({
        "i_category": pd.array(
            [None if r[0] is None else tpcds.CATEGORIES[r[0] - 1]
             for r in rows]),
        "i_brand": pd.array(
            [None if r[1] is None else tpcds.BRANDS[r[1] - 1]
             for r in rows]),
        "qoh": pd.array([r[2] for r in rows]),
    })
    _assert_frame(got, want, float_cols=("qoh",))
