"""Rule-based plan optimizer — rewrites between Plan construction and
bind/compile.

The engine records per-step live rows, selection density, and a per-plan
cost ledger keyed by a stable fingerprint, but (ROADMAP item 3) never
acted on any of it.  This pass closes the loop: every executor entry
point (``run_plan`` / ``analyze_plan`` / ``run_plan_stream`` /
``run_plan_dist`` / dist-stream) calls :func:`optimize` ONCE on the
user's plan, and the rewritten copy is what binds and compiles.  The
rules — each independently toggleable via ``SRT_PLAN_OPT_RULES`` and
logged — are classical relational rewrites restricted to forms that are
*bit-identical* under the engine's selection-mask semantics:

``pushdown``   Hoist filters above projections (substituting renamed
               references) and above UNION ALL branches, toward the
               scan.  A longer leading filter run means
               ``Plan.scan_predicates()`` hands more conjuncts to
               parquet row-group/page pruning.  Sound because a filter
               only ANDs the selection mask and a projection never
               reads it; never hoists past window functions (their
               frames depend on the mask) or joins.
``reorder``    Flatten each maximal run of FilterSteps into its Kleene
               conjuncts, order them by observed selectivity from the
               metrics history (most selective first; unknowns keep
               their position), and fuse back into one FilterStep —
               Kleene AND of keep-masks is order/associativity
               invariant bitwise.  Under ``analyze`` the conjuncts stay
               split one-per-step so per-conjunct selectivity lands in
               the history for later runs.  Adjacent projections fuse
               the same way (substitution through the first project's
               definitions), so ``_step_closures`` traces fewer ops.
``topk``       Sort followed by Limit(k) becomes one :class:`TopKStep`:
               the same mask-leading sort, then a static ``[:k]`` slice
               instead of the limit's argsort/gather pass.
``prune``      Backward liveness over the step list; when the plan's
               input needs only a known column subset, a leading
               narrow pass-through projection is inserted so unused
               payload columns are never bound, padded, or shipped
               over ICI (the bind layer subsets the table before
               padding — see compile._Bound / dist_stream).
``join``       (``run_plan_dist`` only) Rewrite a shuffled join whose
               build side is provably small, unique-keyed, non-null
               and fixed-width into a broadcast join — replicating a
               dimension table beats ``all_to_all``-ing the fact table.
               Probe cardinality comes from ``SRT_METRICS_HISTORY``
               (:func:`..obs.history.lookup_latest`) when the plan ran
               before, else from the live DistTable.  Applied only
               when a following group-by makes the row-order change
               unobservable (order-insensitive exact aggregates).

``SRT_PLAN_OPT=0`` disables the whole pass: the plan runs verbatim —
the bit-identity oracle every rewrite is tested against.
"""

from __future__ import annotations

from dataclasses import dataclass, field as _field
from typing import Optional

from ..config import get_logger, plan_opt, plan_opt_rules
from ..io.pushdown import split_conjuncts
from .expr import BinOp, Col, expr_size, references, render, substitute
from .plan import (CachedSourceStep, FilterStep, GroupAggStep,
                   JoinShuffledStep, JoinStep, LimitStep, Plan,
                   ProjectStep, SortStep, TopKStep, UnionAllStep,
                   WindowStep)

_LOG = get_logger("spark_rapids_tpu.optimize")

#: Build sides beyond this row count never broadcast, whatever the cost
#: model says — replicating more is an HBM bet the optimizer won't make.
BROADCAST_MAX_BUILD_ROWS = 65536

#: Fused-expression node budget: past this, fusing projections stops
#: paying (trace time grows, XLA CSE has more to undo).
FUSE_NODE_BUDGET = 256

#: Aggregations whose result is exact regardless of input row order —
#: the precondition for swapping a shuffled join (which repartitions
#: rows by key hash) for a broadcast join (which keeps probe order).
_ORDER_FREE_AGGS = frozenset({"count", "count_all", "min", "max",
                              "nunique"})
#: ... and these are order-free only over integer inputs (float
#: accumulation order changes low bits).
_ORDER_FREE_INT_AGGS = frozenset({"sum", "mean"})


@dataclass
class OptInfo:
    """What the optimizer did to one plan — attached to the rewritten
    Plan as ``plan.opt`` and folded into QueryMetrics' ``opt`` block."""
    enabled: bool
    rules: tuple
    rewrites: dict = _field(default_factory=dict)
    steps_before: int = 0
    steps_after: int = 0
    history_informed: bool = False
    #: one-line step texts, for the explain() before/after diff
    before: tuple = ()
    after: tuple = ()
    #: the user's original (un-rewritten) Plan — fingerprints, history
    #: records, and oracle comparisons key on THIS object.
    source: object = None

    def render_diff(self) -> str:
        """The explain() before/after step diff."""
        if not self.rewrites:
            return "  == Optimizer == no rewrites applied"
        rw = " ".join(f"{k}={v}" for k, v in sorted(self.rewrites.items()))
        lines = [f"  == Optimizer == {rw}"
                 + (" (history-informed)" if self.history_informed else "")]
        lines += [f"  - {t}" for t in self.before]
        lines += [f"  + {t}" for t in self.after]
        return "\n".join(lines)


def source_plan(plan) -> Plan:
    """The pre-optimization plan (identity when never optimized) — the
    object history records and bit-identity oracles key on."""
    info = getattr(plan, "opt", None)
    return info.source if info is not None and info.source is not None \
        else plan


def live_input_names(plan) -> Optional[tuple]:
    """The input-column subset a pruned plan actually reads, or None.

    Non-None exactly when the plan leads with an all-pass-through
    narrow projection (what the ``prune`` rule inserts): the bind
    layers subset the input table to these names BEFORE padding /
    sharding, which is where the pruned columns' cost would have been
    paid."""
    if plan.steps and _is_passthrough_narrow(plan.steps[0]):
        return tuple(nm for nm, _ in plan.steps[0].cols)
    return None


def _is_passthrough_narrow(step) -> bool:
    return (isinstance(step, ProjectStep) and step.narrow
            and all(isinstance(ex, Col) and ex.name == nm
                    for nm, ex in step.cols))


# -- step text (plan-level; the bound _step_descriptions needs a table) --

def _step_text(step) -> str:
    if isinstance(step, FilterStep):
        return f"Filter[{render(step.pred)}]"
    if isinstance(step, ProjectStep):
        kind = "Select" if step.narrow else "Project"
        return f"{kind}[{', '.join(nm for nm, _ in step.cols)}]"
    if isinstance(step, GroupAggStep):
        return f"GroupBy[{', '.join(step.keys)}]"
    if isinstance(step, JoinStep):
        return f"BroadcastJoin[{', '.join(step.left_on)} {step.how}]"
    if isinstance(step, JoinShuffledStep):
        return f"ShuffledJoin[{', '.join(step.left_on)} {step.how}]"
    if isinstance(step, UnionAllStep):
        return "UnionAll"
    if isinstance(step, WindowStep):
        return f"Window[{step.out}={step.func}]"
    if isinstance(step, SortStep):
        return f"Sort[{', '.join(step.by)}]"
    if isinstance(step, TopKStep):
        return f"TopK[{', '.join(step.by)} k={step.k}]"
    if isinstance(step, LimitStep):
        return f"Limit[{step.k}]"
    if isinstance(step, CachedSourceStep):
        return f"CachedSource[{step.key[:16]}]"
    return type(step).__name__


def plan_step_texts(plan) -> tuple:
    return tuple(_step_text(s) for s in plan.steps)


#: Step types a materializable subplan prefix may consist of: the
#: leading scan(+filter/project/join) pipeline before any aggregation,
#: sort, window, or union changes the row population's identity.  The
#: workload analyzer (obs/workload.py) mines cross-query recurrence of
#: these prefixes as fragment-materialization candidates.
PREFIX_STEP_TYPES = (FilterStep, ProjectStep, JoinStep, JoinShuffledStep)


def prefix_step_texts(plan) -> tuple:
    """Canonical step texts of every leading scan/filter/project/join
    prefix of ``plan``, shortest first: ``((t1,), (t1, t2), ...)`` up to
    the maximal leading run of :data:`PREFIX_STEP_TYPES` steps.  Hash
    each entry with ``obs.history.subplan_fingerprint`` to get the
    subplan fingerprints the overlap miner counts."""
    texts = []
    for step in plan.steps:
        if not isinstance(step, PREFIX_STEP_TYPES):
            break
        texts.append(_step_text(step))
    return tuple(tuple(texts[:i + 1]) for i in range(len(texts)))


def prefix_plan(plan: Plan, depth: int) -> Plan:
    """The standalone sub-plan of ``plan``'s first ``depth`` steps, ready
    to run as-is: it carries its own OptInfo (so ``optimize``'s re-entry
    check skips it — the steps were already rewritten as part of the
    parent) with ``source=None``, so its fingerprint / history records
    key on the prefix itself, never on the full plan it was cut from.
    This is what the semantic cache (serve/semantic.py) executes once to
    materialize a shared fragment."""
    if not (0 < depth <= len(plan.steps)):
        raise ValueError(f"prefix depth must be in 1..{len(plan.steps)}, "
                         f"got {depth}")
    sub = Plan(tuple(plan.steps[:depth]))
    info = getattr(plan, "opt", None)
    sub_info = OptInfo(
        enabled=info.enabled if info is not None else True,
        rules=info.rules if info is not None else (),
        steps_before=depth, steps_after=depth,
        before=plan_step_texts(sub), after=plan_step_texts(sub))
    object.__setattr__(sub, "opt", sub_info)
    return sub


def resume_prefix_steps(names: tuple, sel_name) -> tuple:
    """Steps that re-enter the executor's ``(columns, selection)`` state
    from a *position-preserving* materialized prefix (a table padded at
    the source's logical length, carrying the prefix's live-row
    selection as a ``sel_name`` column): a filter on the stored
    selection restores the mask, and a narrow select drops the carrier
    column and restores the boundary column order.  Without this, a
    compacted prefix result re-orders float accumulation in downstream
    aggregations (last-ulp drift vs the fused run) — the fused executor
    never compacts between steps, so neither may the splice."""
    from .plan import Col, FilterStep, ProjectStep
    steps = []
    if sel_name is not None:
        steps.append(FilterStep(Col(sel_name)))
    steps.append(ProjectStep(tuple((nm, Col(nm)) for nm in names),
                             narrow=True))
    return tuple(steps)


def splice_prefix(plan: Plan, depth: int, key: str) -> Plan:
    """``plan`` resuming AFTER its first ``depth`` steps, sourced from a
    :class:`~.plan.CachedSourceStep` leaf carrying ``key`` — the
    semantic cache's splice.  The parent's OptInfo rides along unchanged
    (``source`` still names the user's original plan, so fingerprints,
    history records, and bit-identity oracles are untouched, and
    ``optimize``'s re-entry check runs the spliced plan verbatim)."""
    if not (0 < depth < len(plan.steps)):
        raise ValueError(f"splice depth must be in 1..{len(plan.steps) - 1}"
                         f" (a strict prefix), got {depth}")
    spliced = Plan((CachedSourceStep(key),) + tuple(plan.steps[depth:]))
    info = getattr(plan, "opt", None)
    if info is not None:
        object.__setattr__(spliced, "opt", info)
    return spliced


# -- rule: predicate pushdown --------------------------------------------

def _hoist_over_project(pred, proj: ProjectStep):
    """The predicate as seen BELOW ``proj``, or None when the hoist is
    unsound (a referenced column is computed by the projection)."""
    defined = dict(proj.cols)
    mapping = {}
    for ref in references(pred):
        ex = defined.get(ref)
        if ex is not None:
            if not isinstance(ex, Col):
                return None               # computed column: can't hoist
            if ex.name != ref:
                mapping[ref] = ex         # pure rename: substitute
        elif proj.narrow:
            return None                   # not produced — leave alone
    return substitute(pred, mapping) if mapping else pred


def _rule_pushdown(steps: tuple) -> tuple[tuple, int]:
    out = list(steps)
    count = 0
    budget = len(out) * len(out) + 8
    changed = True
    while changed and budget > 0:
        changed = False
        budget -= 1
        for i in range(len(out) - 1):
            above, flt = out[i], out[i + 1]
            if not isinstance(flt, FilterStep):
                continue
            if isinstance(above, ProjectStep):
                pred = _hoist_over_project(flt.pred, above)
                if pred is None:
                    continue
                out[i], out[i + 1] = FilterStep(pred), above
                count += 1
                changed = True
                break
            if isinstance(above, UnionAllStep):
                # Filtering after UNION ALL == filtering each side: the
                # union concatenates data and selection mask per side,
                # and the filter ANDs the mask row-locally.
                branch = Plan(above.plan.steps + (FilterStep(flt.pred),))
                out[i] = FilterStep(flt.pred)
                out[i + 1] = UnionAllStep(above.table, branch)
                count += 1
                changed = True
                break
    return tuple(out), count


# -- rule: filter reorder / fusion ---------------------------------------

def _history_selectivities(rec: Optional[dict]) -> dict:
    """describe-text -> observed selectivity (rows_out / rows_in) from
    one history record's measured steps."""
    sel: dict = {}
    if not rec:
        return sel
    for s in rec.get("steps", ()):
        if not isinstance(s, dict) or s.get("kind") != "Filter":
            continue
        rows_in, rows_out = s.get("rows_in", -1), s.get("rows_out", -1)
        if isinstance(rows_in, (int, float)) and rows_in > 0 \
                and isinstance(rows_out, (int, float)) and rows_out >= 0:
            sel[s.get("describe")] = rows_out / rows_in
    return sel


def _filter_describe(conjunct) -> str:
    # Must match compile._step_descriptions' FilterStep text — that is
    # what analyze runs record into the history.
    return f"Filter[{render(conjunct)}] -> selection mask"


def _rule_reorder(steps: tuple, mode: str,
                  hist_sel: dict) -> tuple[tuple, int, bool]:
    out: list = []
    count = 0
    hist_used = False
    i = 0
    while i < len(steps):
        if not isinstance(steps[i], FilterStep):
            out.append(steps[i])
            i += 1
            continue
        j = i
        while j < len(steps) and isinstance(steps[j], FilterStep):
            j += 1
        run = list(steps[i:j])
        conjuncts: list = []
        for f in run:
            conjuncts.extend(split_conjuncts(f.pred))
        found = [hist_sel.get(_filter_describe(c)) for c in conjuncts]
        # Stable sort on observed selectivity: unknown conjuncts keep
        # their relative position at selectivity 1.0 (run last).
        order = sorted(range(len(conjuncts)),
                       key=lambda k: 1.0 if found[k] is None else found[k])
        ordered = [conjuncts[k] for k in order]
        if mode == "analyze":
            # One step per conjunct: the analyze run measures each
            # conjunct's selectivity separately, which is what feeds
            # this very rule on the next run.
            new_run = [FilterStep(c) for c in ordered]
        else:
            pred = ordered[0]
            for c in ordered[1:]:
                pred = BinOp("and_kleene", pred, c)
            new_run = [FilterStep(pred)]
        if new_run != run:
            count += 1
            if any(found[k] is not None for k in order):
                hist_used = True
            out.extend(new_run)
        else:
            out.extend(run)
        i = j
    return tuple(out), count, hist_used


def _fuse_projects(p1: ProjectStep, p2: ProjectStep):
    """One ProjectStep equal to ``p1`` then ``p2``, or None when the
    fusion blows the node budget.  Both projects evaluate against their
    own input state, so ``p2``'s references to ``p1``-defined names are
    substituted through ``p1``'s definitions."""
    p1map = dict(p1.cols)
    if p2.narrow:
        cols = tuple((nm, substitute(ex, p1map)) for nm, ex in p2.cols)
        fused = ProjectStep(cols, True)
    else:
        redefined = {nm: substitute(ex, p1map) for nm, ex in p2.cols}
        cols = []
        for nm, ex in p1.cols:
            cols.append((nm, redefined.pop(nm)) if nm in redefined
                        else (nm, ex))
        for nm, _ in p2.cols:
            if nm in redefined:            # genuinely new name: append
                cols.append((nm, redefined.pop(nm)))
        fused = ProjectStep(tuple(cols), p1.narrow)
    if any(expr_size(ex) > FUSE_NODE_BUDGET for _, ex in fused.cols):
        return None
    return fused


def _rule_fuse_projects(steps: tuple) -> tuple[tuple, int]:
    out: list = []
    count = 0
    for step in steps:
        if out and isinstance(out[-1], ProjectStep) \
                and isinstance(step, ProjectStep):
            fused = _fuse_projects(out[-1], step)
            if fused is not None:
                out[-1] = fused
                count += 1
                continue
        out.append(step)
    return tuple(out), count


# -- rule: limit-through-sort (top-k) ------------------------------------

def _rule_topk(steps: tuple) -> tuple[tuple, int]:
    out: list = []
    count = 0
    i = 0
    while i < len(steps):
        s = steps[i]
        if isinstance(s, SortStep) and i + 1 < len(steps) \
                and isinstance(steps[i + 1], LimitStep):
            out.append(TopKStep(s.by, s.ascending, s.nulls_first,
                                steps[i + 1].k))
            count += 1
            i += 2
        else:
            out.append(s)
            i += 1
    return tuple(out), count


# -- rule: projection pruning --------------------------------------------

def _live_before(step, live: Optional[set]) -> Optional[set]:
    """Column liveness at a step's INPUT, given liveness at its output
    (None = every column is (or may be) live)."""
    if isinstance(step, FilterStep):
        return None if live is None else live | references(step.pred)
    if isinstance(step, ProjectStep):
        if step.narrow:
            entries = step.cols if live is None else \
                [e for e in step.cols if e[0] in live]
            need: set = set()
            for _, ex in entries:
                need |= references(ex)
            return need
        if live is None:
            return None                   # pass-through keeps everything
        defined = {nm for nm, _ in step.cols}
        need = set(live - defined)
        for nm, ex in step.cols:
            if nm in live:
                need |= references(ex)
        return need
    if isinstance(step, GroupAggStep):
        need = set(step.keys)
        for c, _how, _ in step.aggs:
            if c:
                need.add(c)
        return need
    if isinstance(step, (JoinStep, JoinShuffledStep)):
        if step.how in ("inner", "left"):
            if live is None:
                return None
            payload = {n for n in step.table.names
                       if n not in set(step.right_on)}
            return (live - payload) | set(step.left_on)
        # semi/anti: probe schema passes through unchanged
        return None if live is None else live | set(step.left_on)
    if isinstance(step, WindowStep):
        if live is None:
            return None
        need = (live - {step.out}) | set(step.partition_by) \
            | set(step.order_by)
        if step.value:
            need.add(step.value)
        return need
    if isinstance(step, (SortStep, TopKStep)):
        return None if live is None else live | set(step.by)
    if isinstance(step, LimitStep):
        return live
    # UnionAllStep (branch schema must match the FULL current schema)
    # and anything unknown: every input column stays live.
    return None


def _rule_prune(steps: tuple) -> tuple[tuple, int]:
    live: Optional[set] = None            # plan output: all columns live
    for step in reversed(steps):
        live = _live_before(step, live)
    if live is None or not live:
        return steps, 0
    lead = ProjectStep(tuple((nm, Col(nm)) for nm in sorted(live)), True)
    if steps and _is_passthrough_narrow(steps[0]):
        if {nm for nm, _ in steps[0].cols} == live:
            return steps, 0               # already exactly pruned
        return (lead,) + steps[1:], 1
    return (lead,) + steps, 1


# -- rule: cost-based join strategy (dist) -------------------------------

def _keys_unique_nonnull(table, keys: tuple) -> bool:
    """Host-side check over a SMALL build table: every key column fully
    valid and the (possibly composite) key combination unique — the
    broadcast-join build-side contract."""
    import numpy as np
    arrs = []
    for k in keys:
        if k not in table:
            return False
        vals, mask = table[k].to_numpy()
        if mask is not None and not bool(np.all(mask)):
            return False
        arrs.append(np.asarray(vals))
    if not arrs:
        return False
    stacked = np.stack(arrs, axis=1) if len(arrs) > 1 else arrs[0]
    uniq = np.unique(stacked, axis=0) if len(arrs) > 1 \
        else np.unique(stacked)
    return len(uniq) == int(table.num_rows)


def _int_dtype(name: str, *tables) -> bool:
    for t in tables:
        if t is not None and name in t:
            dt = t[name].dtype
            return bool(dt is not None and dt.is_integer)
    return False


def _order_free_tail(steps: tuple, i: int, build, probe) -> bool:
    """True when everything after the join at ``i`` makes row order
    unobservable: only row-local steps up to a GroupAggStep whose
    aggregates are exact regardless of input order."""
    computed: set = set()
    for s in steps[i + 1:]:
        if isinstance(s, FilterStep):
            continue
        if isinstance(s, ProjectStep):
            for nm, ex in s.cols:
                if not (isinstance(ex, Col) and ex.name == nm):
                    computed.add(nm)
            continue
        if isinstance(s, GroupAggStep):
            for c, how, _ in s.aggs:
                if how in _ORDER_FREE_AGGS:
                    continue
                if how in _ORDER_FREE_INT_AGGS and c not in computed \
                        and _int_dtype(c, build, probe):
                    continue
                return False
            return True
        return False                      # sort/window/... before the agg
    return False


def _rule_join(steps: tuple, probe_rows, mesh_size, probe_table,
               rec: Optional[dict]) -> tuple[tuple, int, bool]:
    out = list(steps)
    count = 0
    hist_used = False
    shards = max(int(mesh_size or 1), 1)
    for i, step in enumerate(out):
        if not isinstance(step, JoinShuffledStep):
            continue
        if step.how not in ("inner", "left"):
            continue
        build = step.table
        build_rows = int(getattr(build, "num_rows", 0) or 0)
        if build_rows == 0 or build_rows > BROADCAST_MAX_BUILD_ROWS:
            continue
        if any(c.offsets is not None for c in build.columns):
            continue                      # broadcast build must be fixed-width
        probe = probe_rows
        if rec:
            hist_rows = rec.get("input", {}).get("rows", 0)
            if isinstance(hist_rows, (int, float)) and hist_rows > 0:
                probe = int(hist_rows)
                hist_used = True
        if not probe:
            continue                      # no cardinality evidence: keep
        # Broadcast replicates the build on every shard; the shuffle
        # moves both sides across ICI once.  Model both in rows.
        if build_rows * shards >= probe + build_rows:
            continue
        if not _order_free_tail(tuple(out), i, build, probe_table):
            continue
        if not _keys_unique_nonnull(build, step.right_on):
            continue
        out[i] = JoinStep(build, step.left_on, step.right_on, step.how)
        count += 1
        _LOG.debug("plan-opt join: shuffled->broadcast at step %d "
                   "(build=%d rows, probe~%d, shards=%d)",
                   i, build_rows, probe, shards)
    return tuple(out), count, hist_used


# -- entry point ---------------------------------------------------------

_MODES = ("run", "analyze", "stream", "dist", "dist_stream")


def optimize(plan: Plan, *, mode: str = "run", probe_rows=None,
             mesh_size=None, probe_table=None) -> Plan:
    """The ONE optimize entry point every executor goes through.

    Returns ``plan`` itself when the pass is off (``SRT_PLAN_OPT=0``)
    or the plan was already optimized; otherwise a NEW Plan (the
    original is never mutated) carrying an :class:`OptInfo` as
    ``plan.opt`` — even when no rule fired, so QueryMetrics always
    knows the optimizer ran.  ``mode`` shapes rule behavior (analyze
    keeps conjuncts split for per-step measurement; ``join`` fires only
    under ``dist``); ``probe_rows`` / ``mesh_size`` / ``probe_table``
    feed the join cost model from the live DistTable."""
    if mode not in _MODES:
        raise ValueError(f"optimize mode must be one of {_MODES}, "
                         f"got {mode!r}")
    if getattr(plan, "opt", None) is not None:
        return plan                       # already optimized (re-entry)
    if not plan_opt():
        return plan
    rules = plan_opt_rules()
    steps = tuple(plan.steps)
    rewrites: dict = {}
    history_informed = False

    rec = None
    hist_sel: dict = {}
    if "reorder" in rules or "join" in rules:
        from ..obs.history import lookup_latest, plan_fingerprint
        rec = lookup_latest(plan_fingerprint(plan))
        hist_sel = _history_selectivities(rec)

    if "pushdown" in rules:
        steps, n = _rule_pushdown(steps)
        if n:
            rewrites["pushdown"] = n
    if "reorder" in rules:
        steps, n, used = _rule_reorder(steps, mode, hist_sel)
        history_informed = history_informed or used
        if mode != "analyze":             # keep steps 1:1 for analyze
            steps, n2 = _rule_fuse_projects(steps)
            n += n2
        if n:
            rewrites["reorder"] = n
    if "topk" in rules:
        steps, n = _rule_topk(steps)
        if n:
            rewrites["topk"] = n
    if "prune" in rules:
        steps, n = _rule_prune(steps)
        if n:
            rewrites["prune"] = n
    if "join" in rules and mode == "dist":
        steps, n, used = _rule_join(steps, probe_rows, mesh_size,
                                    probe_table, rec)
        history_informed = history_informed or used
        if n:
            rewrites["join"] = n

    new_plan = Plan(steps)
    info = OptInfo(enabled=True, rules=rules, rewrites=rewrites,
                   steps_before=len(plan.steps), steps_after=len(steps),
                   history_informed=history_informed,
                   before=plan_step_texts(plan),
                   after=plan_step_texts(new_plan), source=plan)
    object.__setattr__(new_plan, "opt", info)
    if rewrites:
        from ..obs.metrics import counter
        for rule, n in rewrites.items():
            counter(f"plan.opt.rewrites.{rule}").inc(n)
        _LOG.debug("plan-opt (%s): %s  steps %d -> %d%s", mode,
                   " ".join(f"{k}={v}"
                            for k, v in sorted(rewrites.items())),
                   info.steps_before, info.steps_after,
                   " [history]" if history_informed else "")
    return new_plan
