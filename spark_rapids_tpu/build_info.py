"""Build-provenance access (the reference's build-info properties, at runtime).

The reference stamps ``*-version-info.properties`` files (version, user,
revision, branch, date, url — build/build-info:27-43) into the jar
(pom.xml:273-298) so any artifact can answer "what exactly am I running?".
The wheel analog: ``setup.py`` runs ``buildtools/build-info`` and packages the
result as ``spark-rapids-tpu-version-info.properties`` next to this module;
:func:`properties` reads it, falling back to live ``git`` queries in a dev
tree so the answer is always available.

:func:`native_build_info` reports the provenance compiled into the native
host library (native/CMakeLists.txt stamps ``SRT_VERSION``/``SRT_GIT_REV``/
``SRT_BUILD_DATE`` as compile definitions) — the two can legitimately differ
when a stale native build is loaded, and comparing them is the supported way
to detect that.
"""

from __future__ import annotations

import subprocess
from datetime import datetime, timezone
from pathlib import Path
from typing import Dict

PROPERTIES_FILE = "spark-rapids-tpu-version-info.properties"

_PKG_DIR = Path(__file__).resolve().parent


def _git(args, cwd) -> str:
    try:
        out = subprocess.run(["git", *args], cwd=cwd, capture_output=True,
                             text=True, check=False).stdout.strip()
        return out or "unknown"
    except OSError:
        return "unknown"


def _live_properties() -> Dict[str, str]:
    """Dev-tree fallback: compute the same fields buildtools/build-info emits."""
    import getpass

    from . import __version__

    cwd = _PKG_DIR.parent
    try:
        user = getpass.getuser()
    except (KeyError, OSError):
        user = "unknown"
    return {
        "version": __version__,
        "user": user,
        "revision": _git(["rev-parse", "HEAD"], cwd),
        "branch": _git(["rev-parse", "--abbrev-ref", "HEAD"], cwd),
        "date": datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
        "url": _git(["config", "--get", "remote.origin.url"], cwd),
    }


def properties() -> Dict[str, str]:
    """Provenance of the installed Python package.

    Packaged wheel: parsed from the stamped properties resource.  Source
    checkout: computed live (marked ``"source": "git"`` so callers can tell).
    """
    path = _PKG_DIR / PROPERTIES_FILE
    if path.is_file():
        props: Dict[str, str] = {}
        for line in path.read_text().splitlines():
            line = line.strip()
            if line and not line.startswith("#") and "=" in line:
                k, v = line.split("=", 1)
                props[k] = v
        props["source"] = "wheel"
        return props
    props = _live_properties()
    props["source"] = "git"
    return props


def native_build_info() -> Dict[str, str]:
    """Provenance stamped into the loaded native host library."""
    from . import ffi
    return ffi.build_info()


def banner() -> str:
    """One-line human-readable provenance summary."""
    p = properties()
    return (f"spark-rapids-tpu {p['version']} "
            f"(rev {p['revision'][:12]}, branch {p['branch']}, "
            f"built {p['date']} by {p['user']}, from {p['source']})")
