#!/bin/bash
# Dispatch to the requested operator; extra args are word-split on purpose.
set -e
OPERATOR="$1"
shift || true
case "$OPERATOR" in
    deps-sync|auto-merge|cleanup-bot-branch)
        exec python "/opt/action-helper/$OPERATOR" $* ;;
    *)
        echo "unknown operator: $OPERATOR" >&2
        exit 2 ;;
esac
