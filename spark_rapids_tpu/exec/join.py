"""Broadcast join inside compiled plans.

The TPU re-architecture of the Spark broadcast hash join (probe side
streams, build side is small and replicated).  A hash table is the wrong
tool on TPU — random scatters to build, random gathers to probe; instead
the binder turns the build side into one of two probe structures, chosen
statically at bind time and cached per build-key buffer identity:

* **direct** — build keys span a small static range: an int32 slot array
  of size (hi-lo+1) maps key-lo → build row (-1 = absent).  Probing is a
  single vectorized gather; O(1) per probe row, no hashing.
* **search** — general integer keys: the build keys are pre-sorted and the
  probe runs a vectorized binary search (``jnp.searchsorted``, log2(D)
  small-table gathers).

Both run sync-free inside the plan program.  Build keys must be unique
(dimension-table contract — checked at bind); many-to-many joins with
data-dependent expansion stay in the eager layer (ops.join, which the
reference's cuDF hash join envelope maps to).

Null semantics: null probe keys and null build keys never match
(Spark/cuDF equi-join); a left join nulls the build payloads of unmatched
rows, inner/semi drop them via the selection mask, anti keeps exactly
them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..column import Column
from ..dtypes import BOOL8, INT32
from .plan import JoinStep

#: Max slot-array cells for the direct probe (int32 => 16 MB at the cap).
DIRECT_PROBE_MAX = 1 << 22


@dataclass(frozen=True)
class JoinMeta:
    """Static join description (part of the compile-cache key)."""
    index: int
    how: str
    left_on: str
    mode: str                            # "direct" | "search"
    lo: int
    hi: int
    dim_rows: int
    #: build rows with a non-null key (0 => nothing can ever match)
    valid_keys: int
    #: build key type id (probe key must match exactly)
    key_type_id: int
    key_scale: int
    #: fixed-width build payloads: (side-input name, output name)
    pays: tuple[tuple[str, str], ...]
    #: string build payloads: (build column name, output name)
    str_pays: tuple[tuple[str, str], ...]
    #: hidden state column carrying matched build row ids (None when no
    #: string payloads need late gathering)
    rowid_name: Optional[str]


# probe-structure cache: build key column buffers -> (mode, lo, hi, arrays)
_PROBE_CACHE: dict = {}


def _build_probe(key: Column):
    """(mode, lo, hi, side arrays) for a build-side key column; cached."""
    from .stats import _guarded_cache_get, _guarded_cache_put
    buffers = ((key.data,) if key.validity is None
               else (key.data, key.validity))
    cache_key = tuple(id(b) for b in buffers)
    hit = _guarded_cache_get(_PROBE_CACHE, cache_key, buffers)
    if hit is not None:
        return hit

    np_keys = np.asarray(key.data)
    rows = np.arange(np_keys.shape[0], dtype=np.int32)
    if key.validity is not None:
        m = np.asarray(key.validity)
        np_keys, rows = np_keys[m], rows[m]
    if np_keys.size == 0:
        result = ("search", 0, 0,
                  {"keys": jnp.zeros(0, key.data.dtype),
                   "rows": jnp.zeros(0, jnp.int32)})
        _guarded_cache_put(_PROBE_CACHE, cache_key, buffers, result)
        return result
    if np.unique(np_keys).size != np_keys.size:
        raise ValueError(
            "broadcast join requires unique build-side keys "
            "(use the eager ops.join for many-to-many joins)")
    lo, hi = int(np_keys.min()), int(np_keys.max())
    span = hi - lo + 1
    if span <= DIRECT_PROBE_MAX:
        lookup = np.full(span, -1, np.int32)
        lookup[(np_keys - lo).astype(np.int64)] = rows
        result = ("direct", lo, hi, {"lookup": jnp.asarray(lookup)})
    else:
        order = np.argsort(np_keys, kind="stable")
        result = ("search", lo, hi,
                  {"keys": jnp.asarray(np_keys[order]),
                   "rows": jnp.asarray(rows[order].astype(np.int32))})
    _guarded_cache_put(_PROBE_CACHE, cache_key, buffers, result)
    return result


def bind_join(bound, step: JoinStep, index: int,
              current_names: list[str]) -> JoinMeta:
    """Register side inputs on ``bound`` and produce the static meta."""
    dim = step.table
    if (step.left_on in bound.string_cols
            or step.left_on in bound.dictionaries):
        raise TypeError(
            f"broadcast join probe key {step.left_on!r} is a string column; "
            f"dictionary-encode both sides or use the eager ops.join")
    if step.right_on not in dim:
        raise KeyError(f"build-side key {step.right_on!r} not in "
                       f"{list(dim.names)}")
    key = dim[step.right_on]
    if key.offsets is not None or key.dtype.is_floating:
        raise TypeError(
            f"broadcast join keys must be integer-typed "
            f"({step.right_on!r} is {key.dtype.type_id.name}); "
            f"dictionary-encode strings or use the eager ops.join")

    mode, lo, hi, arrays = _build_probe(key)
    valid_keys = (dim.num_rows if key.validity is None
                  else int(np.asarray(key.validity).sum()))
    prefix = f"__join{index}__"
    for nm, arr in arrays.items():
        bound.side_inputs[prefix + nm] = Column(
            data=arr, dtype=INT32 if arr.dtype == jnp.int32 else key.dtype)

    pays: list[tuple[str, str]] = []
    str_pays: list[tuple[str, str]] = []
    rowid_name = None
    if step.how in ("inner", "left"):
        for name, c in dim.items():
            if name == step.right_on:
                continue
            if name in current_names:
                raise ValueError(
                    f"join output column {name!r} collides with an existing "
                    f"column; rename one side first")
            if c.offsets is None:
                side_name = prefix + "pay__" + name
                bound.side_inputs[side_name] = c
                pays.append((side_name, name))
            else:
                str_pays.append((name, name))
        if str_pays:
            rowid_name = prefix + "rowid"
            bound.join_string_srcs[rowid_name] = [
                (dim[src], out) for src, out in str_pays]

    return JoinMeta(index, step.how, step.left_on, mode, lo, hi,
                    dim.num_rows, valid_keys, int(key.dtype.type_id),
                    key.dtype.scale, tuple(pays), tuple(str_pays),
                    rowid_name)


def trace_join(cols, sel, side, meta: JoinMeta):
    """Traced probe + payload attach (runs inside the plan program)."""
    k = cols[meta.left_on]
    if (int(k.dtype.type_id) != meta.key_type_id
            or k.dtype.scale != meta.key_scale):
        raise TypeError(
            f"join key dtype mismatch: probe {meta.left_on!r} is "
            f"{k.dtype!r}, build key type id is {meta.key_type_id} "
            f"(cast first)")
    kd = k.data
    in_range = (kd >= jnp.asarray(meta.lo, kd.dtype)) & \
               (kd <= jnp.asarray(meta.hi, kd.dtype))
    if k.validity is not None:
        in_range = in_range & k.validity
    prefix = f"__join{meta.index}__"

    if meta.valid_keys == 0:
        dimrow = jnp.zeros(kd.shape[0], jnp.int32)
        found = jnp.zeros(kd.shape[0], jnp.bool_)
    elif meta.mode == "direct":
        lookup = side[prefix + "lookup"].data
        span = meta.hi - meta.lo + 1
        slot = jnp.clip((kd - jnp.asarray(meta.lo, kd.dtype)).astype(jnp.int32),
                        0, span - 1)
        dimrow = jnp.take(lookup, slot)
        found = in_range & (dimrow >= 0)
    else:
        skeys = side[prefix + "keys"].data
        srows = side[prefix + "rows"].data
        d = skeys.shape[0]
        pos = jnp.clip(jnp.searchsorted(skeys, kd).astype(jnp.int32),
                       0, d - 1)
        found = in_range & (jnp.take(skeys, pos) == kd)
        dimrow = jnp.take(srows, pos)
    dimrow = jnp.clip(dimrow, 0, max(meta.dim_rows - 1, 0))

    if meta.how == "semi":
        return cols, found if sel is None else (sel & found)
    if meta.how == "anti":
        return cols, (~found) if sel is None else (sel & ~found)

    new = dict(cols)
    for side_name, out_name in meta.pays:
        pay = side[side_name]
        data = jnp.take(pay.data, dimrow, axis=0)
        validity = (None if pay.validity is None
                    else jnp.take(pay.validity, dimrow))
        if meta.how == "left":
            validity = found if validity is None else (validity & found)
        new[out_name] = Column(data=data, validity=validity, dtype=pay.dtype)
    if meta.rowid_name is not None:
        new[meta.rowid_name] = Column(data=dimrow, validity=found,
                                      dtype=INT32)
    if meta.how == "inner":
        sel = found if sel is None else (sel & found)
    return new, sel
