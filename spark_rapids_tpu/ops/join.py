"""Equi-joins, sort-based (the reference envelope's "hash join", re-architected).

BASELINE.json names hash-join throughput as a headline metric, but hash
probes scatter to random addresses — hostile to TPU memory.  Idiomatic
replacement (SURVEY.md §7): factorize the join keys over the *union* of both
sides with one multi-key sort (key equality becomes dense int32 group-id
equality), then merge with vectorized ``searchsorted`` + prefix-sum
expansion.  Every step is a sort, scan, gather, or segmented arithmetic —
all TPU-native patterns.

Null join keys never match (Spark/cuDF equi-join semantics): null-key rows
get side-distinct sentinel group ids.

Output-size materialization: one host sync for the total match count
(inherent — the result shape is data dependent), then fixed-shape gathers.

``SRT_KERNELS=join`` swaps the factorize+probe for the Pallas
hash-table build/probe (`kernels/join.py`) — the sort path below stays
in-tree as its bit-identity oracle and automatic fallback.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..column import Column, all_null_column
from ..table import Table
from .common import grouping_columns, pow2_bucket


def _factorize_union(left: Table, right: Table, left_on: Sequence[str],
                     right_on: Sequence[str]):
    """Factorize + probe: returns (rorder, lo, counts, rmatched) from the
    fused kernel; rows with any null key get a non-matching sentinel
    (-1 left, -2 right) so nulls never join."""
    n_left = left.num_rows
    merged_cols = []
    for lname, rname in zip(left_on, right_on):
        lc, rc = left[lname], right[rname]
        if lc.dtype != rc.dtype:
            raise ValueError(
                f"join key dtype mismatch: {lname}={lc.dtype!r} vs "
                f"{rname}={rc.dtype!r} (cast first)")
        if lc.offsets is not None:
            from .strings import concat_columns
            merged_cols.append(concat_columns([lc, rc]))
            continue
        data = jnp.concatenate([lc.data, rc.data])
        validity = None
        if lc.validity is not None or rc.validity is not None:
            validity = jnp.concatenate([lc.valid_mask(), rc.valid_mask()])
        merged_cols.append(Column(data=data, validity=validity, dtype=lc.dtype))
    merged_cols = grouping_columns(merged_cols)   # strings -> dictionary codes
    datas = tuple(c.data for c in merged_cols)
    valids = tuple(c.validity for c in merged_cols)

    def _oracle():
        return _factorize_probe_kernel(datas, valids, n_left=n_left)

    from ..kernels import registry as _kernels
    if _kernels.enabled("join"):
        from ..kernels.join import hash_factorize_probe, supported
        if supported(datas, n_left=n_left):
            return _kernels.dispatch(
                "join",
                lambda: hash_factorize_probe(
                    datas, valids, n_left=n_left,
                    interpret=_kernels.interpret_mode()),
                _oracle)
    return _oracle()


@functools.partial(jax.jit, static_argnames=("n_left",))
def _factorize_probe_kernel(key_datas, key_valids, *, n_left):
    """ONE program: factorize both sides' key tuples to dense group ids
    (sort + boundary + inverse scatter, null rows masked and sentineled),
    then probe the right side (argsort + two searchsorteds).  The eager
    form paid a dispatch per step; fused it is one device execution per
    join schema.  Returns (rorder, lo, counts, rmatched) — ``rmatched``
    (does any left row share this right row's key?) feeds the
    unmatched-right tail of full/right outer joins.
    """
    from .common import adjacent_differs, grouping_sort_operands
    n = key_datas[0].shape[0]
    ops_list = grouping_sort_operands(key_datas, key_valids)
    iota = jnp.arange(n, dtype=jnp.int32)
    sorted_all = jax.lax.sort(ops_list + [iota], dimension=0, is_stable=True,
                              num_keys=len(ops_list))
    perm = sorted_all[-1]
    boundary = jnp.zeros(n, jnp.bool_)
    for op in sorted_all[:-1]:
        boundary = boundary | adjacent_differs(op)
    gid_sorted = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    gid = jnp.zeros(n, jnp.int32).at[perm].set(gid_sorted)

    any_null = jnp.zeros(n, jnp.bool_)
    for v in key_valids:
        if v is not None:
            any_null = any_null | ~v
    gid = jnp.where(any_null, jnp.where(iota < n_left, -1, -2), gid)

    lgid, rgid = gid[:n_left], gid[n_left:]
    rorder = jnp.argsort(rgid, stable=True).astype(jnp.int32)
    rgid_sorted = jnp.take(rgid, rorder)
    lo = jnp.searchsorted(rgid_sorted, lgid, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(rgid_sorted, lgid, side="right").astype(jnp.int32)
    counts = (hi - lo).astype(jnp.int64)
    # Reverse probe: the -2 sentinel of null-key right rows never appears
    # in lgid (left nulls are -1), so null right keys are never matched.
    lgid_sorted = jax.lax.sort([lgid], dimension=0, num_keys=1)[0]
    r_lo = jnp.searchsorted(lgid_sorted, rgid, side="left")
    r_hi = jnp.searchsorted(lgid_sorted, rgid, side="right")
    rmatched = r_hi > r_lo
    return rorder, lo, counts, rmatched


def _suffix_overlaps(left: Table, right: Table, drop_right: set[str],
                     suffixes: tuple[str, str]) -> tuple[Table, list[tuple[str, str]]]:
    """Resolve output column names; returns (renamed left, right name pairs)."""
    right_names = [(n, n) for n in right.names if n not in drop_right]
    overlap = set(left.names) & {n for n, _ in right_names}
    if overlap:
        left = left.rename({n: n + suffixes[0] for n in overlap})
        right_names = [(n, n + suffixes[1] if n in overlap else n)
                       for n, _ in right_names]
    return left, right_names


def join(left: Table, right: Table, on: Optional[Sequence[str] | str] = None,
         left_on: Optional[Sequence[str]] = None,
         right_on: Optional[Sequence[str]] = None,
         how: str = "inner", suffixes: tuple[str, str] = ("_x", "_y")) -> Table:
    """Equi-join two tables.

    ``how``: "inner", "left", "right", "full" (alias "outer"), "semi"
    (left rows with a match), or "anti" (left rows without a match).

    Full/right outer append the unmatched right rows after the expansion
    rows, with all-null left columns; when ``on=`` names shared keys, the
    deduplicated key column is coalesced from the right side for those
    rows (Spark USING-join / pandas merge semantics).  Null keys never
    match on either side (they surface as unmatched rows in outer joins).
    """
    if how == "outer":
        how = "full"
    if how not in ("inner", "left", "right", "full", "semi", "anti"):
        raise ValueError(f"unsupported join type {how!r}")
    if on is not None:
        if isinstance(on, str):
            on = [on]
        left_on = right_on = list(on)
    if not left_on or not right_on or len(left_on) != len(right_on):
        raise ValueError("join keys: pass `on=` or matching left_on/right_on")

    rorder, lo, counts, rmatched = _factorize_union(left, right,
                                                    left_on, right_on)

    if how == "semi":
        from .filter import _compact_table
        return _compact_table(left, counts > 0)
    if how == "anti":
        from .filter import _compact_table
        return _compact_table(left, counts == 0)

    keep_right_gid_cols = set()
    if on is not None:
        keep_right_gid_cols = set(on)   # de-dup shared key columns
    left_out, right_names = _suffix_overlaps(left, right, keep_right_gid_cols,
                                             suffixes)
    #: output name of each deduplicated key column -> right source name
    #: (outer tails coalesce these from the right side)
    key_coalesce = ({ln: rn for ln, rn in zip(left_on, right_on)}
                    if on is not None else {})

    left_join = how in ("left", "full")
    with_tail = how in ("right", "full")
    if left_join and right.num_rows == 0:   # degenerate: all-null right side
        cols = [(n, c) for n, c in left_out.items()]
        for src_name, out_name in right_names:
            cols.append((out_name,
                         all_null_column(right[src_name].dtype, left.num_rows)))
        return Table(cols)

    out_counts = jnp.maximum(counts, 1) if left_join else counts
    if with_tail:
        total, n_tail = (int(x) for x in jax.device_get(
            (out_counts.sum(), (~rmatched).sum())))   # the one host sync
    else:
        total, n_tail = int(out_counts.sum()), 0      # the one host sync

    if total == 0 and n_tail == 0:
        cols = [(n, Column(data=jnp.zeros(0, c.dtype.jnp_dtype), dtype=c.dtype)
                 if c.offsets is None else c.gather(jnp.zeros(0, jnp.int32)))
                for n, c in left_out.items()]
        for src_name, out_name in right_names:
            c = right[src_name]
            cols.append((out_name, c.gather(jnp.zeros(0, jnp.int32))))
        return Table(cols)

    pieces = []
    if total:
        pieces.append(_expand_segment(left_out, right, right_names, rorder,
                                      lo, counts, total, left_join))
    if n_tail:
        pieces.append(_unmatched_right_tail(left_out, right, right_names,
                                            rmatched, n_tail, key_coalesce))
    if len(pieces) == 1:
        return pieces[0]
    from .common import concat_tables
    return concat_tables(pieces)


def _expand_segment(left_out: Table, right: Table, right_names, rorder, lo,
                    counts, total: int, left_join: bool) -> Table:
    """The match-expansion rows (plus unmatched-left rows when
    ``left_join``): the original inner/left join body."""
    bucket = pow2_bucket(total)
    lfixed = [(n, c) for n, c in left_out.items() if c.offsets is None]
    rfixed = [(s, o) for s, o in right_names
              if right[s].offsets is None]
    lrow, rrow, matched, ldatas, lvalids, rdatas, rvalids = _expand_kernel(
        lo, counts, rorder,
        tuple(c.data for _, c in lfixed),
        tuple(c.validity for _, c in lfixed),
        tuple(right[s].data for s, _ in rfixed),
        tuple(right[s].validity for s, _ in rfixed),
        bucket=bucket, left_join=left_join)

    cols_by_name: dict[str, Column] = {}
    for (name, col), d, v in zip(lfixed, ldatas, lvalids):
        cols_by_name[name] = Column(
            data=d[:total], validity=None if v is None else v[:total],
            dtype=col.dtype)
    for (src_name, out_name), d, v in zip(rfixed, rdatas, rvalids):
        validity = v[:total] if v is not None else None
        if left_join:
            m = matched[:total]
            validity = m if validity is None else (validity & m)
        cols_by_name[out_name] = Column(data=d[:total], validity=validity,
                                        dtype=right[src_name].dtype)

    lrow_t = rrow_t = None
    cols: list[tuple[str, Column]] = []
    for name, col in left_out.items():
        if col.offsets is None:
            cols.append((name, cols_by_name[name]))
        else:
            if lrow_t is None:
                lrow_t = lrow[:total]
            cols.append((name, col.gather(lrow_t)))
    for src_name, out_name in right_names:
        col = right[src_name]
        if col.offsets is None:
            cols.append((out_name, cols_by_name[out_name]))
        else:
            if rrow_t is None:
                rrow_t = rrow[:total]
            g = col.gather(rrow_t)
            if left_join:
                g = g.with_validity(g.valid_mask() & matched[:total])
            cols.append((out_name, g))
    return Table(cols)


def _unmatched_right_tail(left_out: Table, right: Table, right_names,
                          rmatched, n_tail: int,
                          key_coalesce: dict[str, str]) -> Table:
    """Full/right outer tail: right rows with no left match, left columns
    all-null except ``on=``-deduplicated keys (coalesced from the right)."""
    from .filter import _compact_kernel
    bucket = min(pow2_bucket(n_tail), int(rmatched.shape[0]))
    idx, _, _ = _compact_kernel(~rmatched, (), (), bucket=bucket)
    idx = idx[:n_tail]
    cols: list[tuple[str, Column]] = []
    for name, col in left_out.items():
        rn = key_coalesce.get(name)
        if rn is not None:
            cols.append((name, right[rn].gather(idx)))
        else:
            cols.append((name, all_null_column(col.dtype, n_tail)))
    for src_name, out_name in right_names:
        cols.append((out_name, right[src_name].gather(idx)))
    return Table(cols)


@functools.partial(jax.jit, static_argnames=("bucket", "left_join"))
def _expand_kernel(lo, counts, rorder, ldatas, lvalids, rdatas, rvalids, *,
                   bucket, left_join):
    """Match expansion + every fixed-width output gather in ONE program.

    The per-output left row id is recovered with the scatter-indicator +
    prefix-sum trick (O(output) instead of a log-factor searchsorted);
    output arrays are padded to the pow2 ``bucket`` so one compile serves
    many match totals.
    """
    n_left = counts.shape[0]
    out_counts = jnp.maximum(counts, 1) if left_join else counts
    out_starts = (jnp.cumsum(out_counts) - out_counts).astype(jnp.int32)
    pos = jnp.arange(bucket, dtype=jnp.int32)
    # Scatter EVERY row's start (zero-output rows stack on the next start);
    # the prefix count - 1 then yields the LAST row starting at or before
    # each position — exactly the owning row (same trick as the strings
    # engine's _row_ids).
    indicator = jnp.zeros(bucket, jnp.int32).at[
        jnp.clip(out_starts, 0, bucket - 1)].add(
            jnp.where(out_starts < bucket, 1, 0).astype(jnp.int32))
    lrow = jnp.clip(jnp.cumsum(indicator) - 1, 0, n_left - 1)
    k = pos - jnp.take(out_starts, lrow)
    rpos = jnp.take(lo, lrow) + k
    matched = jnp.take(counts, lrow) > 0
    nr = max(rorder.shape[0], 1)
    rrow = jnp.take(rorder, jnp.clip(rpos, 0, nr - 1))
    out_l = tuple(jnp.take(d, lrow, axis=0) for d in ldatas)
    out_lv = tuple(None if v is None else jnp.take(v, lrow) for v in lvalids)
    out_r = tuple(jnp.take(d, rrow, axis=0) for d in rdatas)
    out_rv = tuple(None if v is None else jnp.take(v, rrow) for v in rvalids)
    return lrow, rrow, matched, out_l, out_lv, out_r, out_rv
