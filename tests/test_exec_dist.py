"""Distributed plan execution tests (8 virtual CPU devices, conftest).

Oracle: a distributed plan over a sharded table must produce exactly the
same result as the same plan run locally on the unsharded table (which is
itself oracle-checked against the eager ops layer in test_exec.py).
"""

import numpy as np
import pytest

from spark_rapids_tpu import Column, Table, assert_tables_equal
from spark_rapids_tpu.exec import col, plan
from spark_rapids_tpu.parallel import make_flat_mesh, shard_table


def _table(rng, n=4003):
    return Table([
        ("k1", Column.from_numpy(rng.integers(0, 5, n).astype(np.int8),
                                 validity=rng.random(n) > 0.1)),
        ("k2", Column.from_numpy(rng.integers(0, 2, n).astype(np.bool_))),
        ("v", Column.from_numpy(rng.integers(-100, 100, n).astype(np.int64),
                                validity=rng.random(n) > 0.2)),
        ("f", Column.from_numpy(rng.normal(size=n))),
    ])


@pytest.fixture(scope="module")
def mesh():
    return make_flat_mesh()


class TestDistPlans:
    def test_dense_groupby_matches_local(self, rng, mesh):
        t = _table(rng)
        dist = shard_table(t, mesh)
        p = (plan().filter(col("v") > 0)
             .groupby_agg(["k1", "k2"],
                          [("v", "sum", "vs"), ("v", "count", "n"),
                           ("f", "mean", "fm"), ("v", "min", "vmin"),
                           ("v", "max", "vmax"), ("f", "var", "fv"),
                           ("f", "std", "fs"), ("v", "count_all", "ca")])
             .sort_by(["k1", "k2"]))
        got = p.run_dist(dist, mesh)
        want = p.run(t)
        assert_tables_equal(want, got, rtol=1e-9, atol=1e-9)

    def test_projection_and_join(self, rng, mesh):
        t = _table(rng)
        d = Table([("dk", Column.from_numpy(np.arange(5, dtype=np.int8))),
                   ("w", Column.from_numpy(rng.normal(size=5)))])
        p = (plan()
             .join_broadcast(d, left_on="k1", right_on="dk", how="left")
             .with_columns(z=col("f") * col("w").fill_null(1.0))
             .groupby_agg(["k1"], [("z", "sum", "zs")])
             .sort_by(["k1"]))
        got = p.run_dist(shard_table(t, mesh), mesh)
        want = p.run(t)
        assert_tables_equal(want, got, rtol=1e-9, atol=1e-9)

    def test_filter_only_returns_disttable(self, rng, mesh):
        from spark_rapids_tpu.parallel import collect
        from spark_rapids_tpu.parallel.mesh import DistTable
        t = _table(rng)
        p = plan().filter(col("v") > 0).with_columns(g=col("f") * 2.0)
        out = p.run_dist(shard_table(t, mesh), mesh)
        assert isinstance(out, DistTable)
        got = collect(out)
        want = p.run(t)
        # Shard padding permutes nothing: row order is preserved within
        # the contiguous deal-out, so direct equality applies.
        assert_tables_equal(want, got, rtol=1e-12, atol=1e-12)

    def test_sharded_sort_raises(self, rng, mesh):
        t = _table(rng)
        p = plan().sort_by(["v"])
        with pytest.raises(TypeError, match="sort"):
            p.run_dist(shard_table(t, mesh), mesh)

    def test_sharded_wide_groupby_raises(self, rng, mesh):
        n = 1000
        t = Table([
            ("k", Column.from_numpy(
                rng.integers(0, 1_000_000, n).astype(np.int64))),
            ("v", Column.from_numpy(rng.normal(size=n))),
        ])
        p = plan().groupby_agg(["k"], [("v", "sum", "s")])
        with pytest.raises(TypeError, match="dense-domain"):
            p.run_dist(shard_table(t, mesh), mesh)

    def test_padding_does_not_widen_domain(self, rng, mesh):
        # Keys in [300, 400]: the zero-filled padding slots must not drag
        # the probed domain down to [0, 400] (which would overflow
        # DENSE_MAX_CELLS and wrongly reject the distributed plan).
        n = 4003                                   # pads 5 zero slots
        t = Table([
            ("k", Column.from_numpy(
                (rng.integers(0, 101, n) + 300).astype(np.int64))),
            ("v", Column.from_numpy(rng.normal(size=n))),
        ])
        p = (plan().groupby_agg(["k"], [("v", "sum", "s")])
             .sort_by(["k"]))
        got = p.run_dist(shard_table(t, mesh), mesh)
        want = p.run(t)
        assert_tables_equal(want, got, rtol=1e-9, atol=1e-9)

    def test_mesh_identity_in_cache(self, rng, mesh):
        import jax
        from spark_rapids_tpu.parallel import make_flat_mesh
        devs = jax.devices()
        m1 = make_flat_mesh(devs[:4])
        m2 = make_flat_mesh(devs[4:8])
        t = _table(rng, n=400)
        p = plan().groupby_agg(["k1"], [("v", "sum", "s")]).sort_by(["k1"])
        got1 = p.run_dist(shard_table(t, m1), m1)
        got2 = p.run_dist(shard_table(t, m2), m2)
        want = p.run(t)
        assert_tables_equal(want, got1)
        assert_tables_equal(want, got2)

    def test_empty_dist_table(self, rng, mesh):
        # shard_table pads an empty table to capacity with zero live rows;
        # the runner must fall back to the eager empty result, not raise.
        t = _table(rng, n=16).gather(np.zeros(0, np.int32))
        d0 = shard_table(t, mesh, capacity=2)
        p = plan().groupby_agg(["k1"], [("v", "sum", "s")])
        out = p.run_dist(d0, mesh)
        assert out.num_rows == 0

    def test_first_across_shards_raises(self, rng, mesh):
        t = _table(rng)
        p = plan().groupby_agg(["k1"], [("v", "first", "vf")])
        with pytest.raises(TypeError, match="first/last"):
            p.run_dist(shard_table(t, mesh), mesh)


def _row_multiset(t):
    from spark_rapids_tpu.parallel import collect
    from spark_rapids_tpu.parallel.mesh import DistTable
    if isinstance(t, DistTable):
        t = collect(t)
    d = t.to_pydict()
    names = sorted(d)
    return sorted(zip(*[d[nm] for nm in names]),
                  key=lambda r: tuple((x is None, x) for x in r))


class TestDistShuffledJoin:
    """Big-big join over the mesh: both sides hash-shuffled with
    all_to_all, merge-joined per shard (the q95 shape distributed)."""

    def _facts(self, rng, n=4003, m=3001, hi=300):
        left = Table([
            ("k", Column.from_numpy(rng.integers(0, hi, n).astype(np.int64),
                                    validity=rng.random(n) > 0.05)),
            ("lv", Column.from_numpy(
                rng.integers(-100, 100, n).astype(np.int64))),
        ])
        right = Table([
            ("rk", Column.from_numpy(rng.integers(0, hi, m).astype(np.int64),
                                     validity=rng.random(m) > 0.05)),
            ("rv", Column.from_numpy(rng.integers(0, 40, m).astype(np.int64),
                                     validity=rng.random(m) > 0.1)),
        ])
        return left, right

    def test_join_groupby_matches_local(self, rng, mesh):
        left, right = self._facts(rng)
        p = (plan()
             .filter(col("lv") > -50)
             .join_shuffled(right, left_on="k", right_on="rk")
             .groupby_agg(["rv"], [("lv", "sum", "s"), ("lv", "count", "c")])
             .sort_by(["rv"]))
        got = p.run_dist(shard_table(left, mesh), mesh)
        want = p.run(left)
        assert_tables_equal(want, got, rtol=1e-9, atol=1e-9)

    def test_join_only_multiset(self, rng, mesh):
        from spark_rapids_tpu.parallel import collect
        left, right = self._facts(rng)
        for how in ("inner", "left"):
            p = plan().join_shuffled(right, left_on="k", right_on="rk",
                                     how=how)
            got = collect(p.run_dist(shard_table(left, mesh), mesh))
            want = p.run(left)
            assert _row_multiset(got) == _row_multiset(want), how

    def test_shared_key_name(self, rng, mesh):
        left, right = self._facts(rng, n=1200, m=900)
        right = right.rename({"rk": "k"})
        p = (plan().join_shuffled(right, on="k")
             .groupby_agg(["rv"], [("lv", "sum", "s")])
             .sort_by(["rv"]))
        got = p.run_dist(shard_table(left, mesh), mesh)
        want = p.run(left)
        assert_tables_equal(want, got)

    def test_semi_raises_dist(self, rng, mesh):
        left, right = self._facts(rng, n=400, m=300)
        p = plan().join_shuffled(right, left_on="k", right_on="rk",
                                 how="semi")
        with pytest.raises(TypeError, match="inner/left"):
            p.run_dist(shard_table(left, mesh), mesh)

    def test_join_after_groupby_raises_dist(self, rng, mesh):
        left, right = self._facts(rng, n=400, m=300)
        p = (plan().groupby_agg(["k"], [("lv", "sum", "s")],
                                domains={"k": (0, 299)})
             .join_shuffled(right, left_on="k", right_on="rk"))
        with pytest.raises(TypeError, match="join first"):
            p.run_dist(shard_table(left, mesh), mesh)

    def test_empty_left_falls_back_eager(self, rng, mesh):
        left, right = self._facts(rng, n=16, m=8)
        empty = left.gather(np.zeros(0, np.int32))
        d0 = shard_table(empty, mesh, capacity=2)
        p = (plan().join_shuffled(right, left_on="k", right_on="rk")
             .groupby_agg(["rv"], [("lv", "sum", "s")]))
        out = p.run_dist(d0, mesh)
        assert out.num_rows == 0

    def test_empty_right_falls_back_eager(self, rng, mesh):
        left, right = self._facts(rng, n=400, m=8)
        right0 = right.gather(np.zeros(0, np.int32))
        for how in ("inner", "left"):
            p = plan().join_shuffled(right0, left_on="k", right_on="rk",
                                     how=how)
            got = p.run_dist(shard_table(left, mesh), mesh)
            want = p.run(left)
            assert _row_multiset(got) == _row_multiset(want), how

    def test_prefix_filters_all_rows(self, rng, mesh):
        left, right = self._facts(rng, n=400, m=300)
        p = (plan().filter(col("lv") > 10_000)      # drops every row
             .join_shuffled(right, left_on="k", right_on="rk"))
        got = p.run_dist(shard_table(left, mesh), mesh)
        want = p.run(left)
        assert _row_multiset(got) == _row_multiset(want)

    def test_empty_input_keeps_disttable_contract(self, rng, mesh):
        from spark_rapids_tpu.parallel.mesh import DistTable
        left, _ = self._facts(rng, n=16, m=8)
        empty = left.gather(np.zeros(0, np.int32))
        d0 = shard_table(empty, mesh, capacity=2)
        # Row-sharded-ending plan over an empty input: still a DistTable.
        out = plan().filter(col("lv") > 0).run_dist(d0, mesh)
        assert isinstance(out, DistTable)
        assert out.num_rows() == 0
