"""``python -m spark_rapids_tpu.obs`` — console tooling over obs state.

``top``
    htop-style live query view: polls the in-process live registry
    (obs/live.py) or, with ``--url``, a remote exporter's ``/queries``
    endpoint (obs/server.py) and redraws a console table of in-flight
    queries: phase, batches done / in-flight, rows/sec, ICI bytes, last
    recovery rung, and one progress bar per shard.  ``--once`` prints a
    single frame (scripts, CI, docs); default is a 1 Hz refresh until
    Ctrl-C.
``doctor <bundle.json | fingerprint>``
    postmortem analysis (obs/doctor.py): rank what failed or got slow
    in one bundle — or a plan fingerprint's newest history record —
    against the same-fingerprint history baseline, and print the
    verdict.  Exits 0 whenever a verdict was produced.
``advisor``
    one capacity-advisor evaluation (obs/capacity.py): the saturation
    snapshot plus ranked, evidence-cited recommendations.  Reads the
    local in-process window by default, a remote exporter's
    ``/capacity`` with ``--url``, or — with ``--history`` — replays a
    metrics-history JSONL offline (newest ``--last`` records via the
    tail-seeking reverse reader).  Exits 0 whenever a verdict was
    produced.

Rendering is a pure function of the ``/queries`` JSON payload
(:func:`render_top`) / the advisor payload (:func:`render_advisor`), so
tests drive them with synthetic snapshots and the remote and local paths
share one code path.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import List, Optional

_BAR_WIDTH = 24


def _human(n: float) -> str:
    for unit in ("", "K", "M", "G", "T"):
        if abs(n) < 1000:
            return f"{n:.0f}{unit}" if unit else f"{n:.0f}"
        n /= 1000.0
    return f"{n:.0f}P"


def _bar(done: int, total: int, width: int = _BAR_WIDTH) -> str:
    if total <= 0:
        return "[" + "·" * width + "]"
    filled = min(width, int(round(width * done / total)))
    return "[" + "#" * filled + "-" * (width - filled) + "]"


def _fmt_query(q: dict) -> List[str]:
    eta = q.get("eta_seconds")
    lines = [
        "  q{qid:<5} {mode:<12} {phase:<12} {elapsed:>8.1f}s "
        "{done:>5}/{total:<5} inflight={inflight:<2} "
        "{rps:>9} rows/s  ici={ici:>6}B  hbm={hbm:>6}B{eta}".format(
            qid=q["query_id"], mode=q["mode"], phase=q["phase"],
            elapsed=q["elapsed_seconds"], done=q["batches_done"],
            total=q["total_batches"] or "?", inflight=q["inflight"],
            rps=_human(q["rows_per_sec"]), ici=_human(q["ici_bytes"]),
            hbm=_human(q["hbm_peak_bytes"]),
            eta=f"  eta={eta:.0f}s" if eta else "")]
    rung = q["recovery"]["last_rung"]
    if rung:
        lines.append(f"         recovery: {rung} "
                     f"({q['recovery']['count']} rungs)")
    shard_batches = q.get("shard_batches") or {}
    if shard_batches:
        total = max(q["batches_in"], max(shard_batches.values()), 1)
        for shard, done in sorted(shard_batches.items(),
                                  key=lambda kv: int(kv[0])):
            lines.append(f"         shard {int(shard):>2} "
                         f"{_bar(done, total)} {done}/{total}")
    return lines


def render_top(snap: dict, source: str = "local") -> str:
    """One frame of the ``top`` view from a ``/queries`` payload."""
    in_flight = snap.get("in_flight", [])
    queued = snap.get("queued", [])
    recent = snap.get("recent", [])
    ts = time.strftime("%H:%M:%S",
                       time.localtime(snap.get("unix_time", time.time())))
    lines = [f"srt top — {source} pid={snap.get('pid', '?')} {ts}  "
             f"running={len(in_flight)} queued={len(queued)} "
             f"recent={len(recent)}"]
    if in_flight:
        lines.append("in-flight:")
        for q in in_flight:
            lines.extend(_fmt_query(q))
    else:
        lines.append("in-flight: (none)")
    if queued:
        lines.append("queued:")
        for q in queued[:8]:
            lines.append(
                "  q{qid:<5} {mode:<12} {status:<8} waiting "
                "{waited:>6.1f}s  est_hbm={est} fp={fp}".format(
                    qid=q.get("query_id", "?"), mode=q.get("mode", "?"),
                    status=q.get("status", "?"),
                    waited=q.get("queued_seconds", 0.0),
                    est=q.get("estimate_hbm_bytes", 0),
                    fp=q.get("fingerprint", "")))
    if recent:
        lines.append("recent:")
        for q in recent[-8:]:
            lines.append(
                "  q{qid:<5} {mode:<12} {status:<8} {elapsed:>8.1f}s "
                "{batches:>5} batches {rows:>10} rows out".format(
                    qid=q["query_id"], mode=q["mode"], status=q["status"],
                    elapsed=q["elapsed_seconds"],
                    batches=q["batches_done"], rows=q["rows_out"]))
    return "\n".join(lines)


def render_advisor(payload: dict, source: str = "local") -> str:
    """Console rendering of one ``/capacity`` advisor payload — pure."""
    snap = payload.get("snapshot") or {}
    busy = snap.get("busy", {})
    queue = snap.get("queue", {})
    ll = snap.get("littles_law", {})
    adm = snap.get("admission", {})
    lines = [
        f"srt advisor — {source}  verdict={payload.get('verdict', '?')}",
        "window={w:.0f}s  busy={b:.2f}  eff_concurrency={l:.2f}/{cap}  "
        "util_of_cap={u:.2f}  qps={qps:.2f}".format(
            w=snap.get("window_seconds", 0.0),
            b=busy.get("dispatch_fraction", 0.0),
            l=ll.get("effective_concurrency", 0.0),
            cap=ll.get("max_concurrent", "?"),
            u=ll.get("utilization_of_cap", 0.0),
            qps=ll.get("arrival_rate_qps", 0.0)),
        "queue: waits={n} p95={p95:.3f}s depth={d}   admission: "
        "hbm_waits={hw} rejected={rj}".format(
            n=queue.get("waits", 0), p95=queue.get("wait_p95_s", 0.0),
            d=queue.get("depth", 0), hw=adm.get("hbm_waits", 0),
            rj=adm.get("rejected", 0)),
    ]
    recs = payload.get("recommendations") or []
    cands = payload.get("candidates") or []
    shown = recs if recs else cands
    tag = "recommendations" if recs else "candidates (unconfirmed)"
    if not shown:
        lines.append("recommendations: (none — capacity looks healthy)")
        return "\n".join(lines)
    lines.append(f"{tag}:")
    for rec in shown:
        lines.append(f"  [{rec['severity']:>3}] {rec['action']}: "
                     f"{rec['reason']}")
        ev = rec.get("evidence") or {}
        if ev:
            detail = ", ".join(f"{k}={ev[k]}" for k in sorted(ev))
            lines.append(f"        evidence: {detail}")
    return "\n".join(lines)


def _capacity_pane(url: Optional[str]) -> List[str]:
    """Capacity summary lines appended under a ``top`` frame —
    best-effort (an older exporter without ``/capacity`` just yields
    nothing)."""
    try:
        if url is not None:
            with urllib.request.urlopen(
                    url.rstrip("/") + "/capacity", timeout=5) as resp:
                payload = json.loads(resp.read().decode())
        else:
            from . import capacity
            payload = capacity.advise()
    except Exception:
        return []
    return ["", render_advisor(payload, source="capacity")]


def _advisor_payload(url: Optional[str], history: Optional[str],
                     last: int) -> dict:
    """The advisor payload from one of the three sources: a remote
    exporter's ``/capacity``, an offline metrics-history replay, or the
    local in-process window."""
    if url is not None:
        with urllib.request.urlopen(url.rstrip("/") + "/capacity",
                                    timeout=5) as resp:
            return json.loads(resp.read().decode())
    if history is not None:
        return _advise_history(history, last)
    from . import capacity
    return capacity.advise()


def _advise_history(path: str, last: int) -> dict:
    """Offline advisor: replay the newest ``last`` metrics-history
    records (tail-seeking reverse reader, so a multi-GB JSONL costs one
    tail read) through the same pure derive/recommend core.  One-shot
    evaluation — hysteresis needs repeated windows — so a fresh
    ``Advisor(confirm=1)`` folds the single window."""
    from ..config import capacity_targets
    from . import capacity
    from .history import _iter_lines_reversed
    records: List[dict] = []
    for line in _iter_lines_reversed(path):
        if len(records) >= max(last, 1):
            break
        try:
            rec = json.loads(line)
        except (ValueError, TypeError):
            continue
        if isinstance(rec, dict):
            records.append(rec)
    records.reverse()           # oldest first for the serialized replay
    events, w0, w1 = capacity.events_from_history(records)
    from ..config import (result_cache_bytes, serve_hbm_budget,
                          serve_max_concurrent)
    snap = capacity.derive(
        events, w0, w1, max_concurrent=serve_max_concurrent(),
        hbm_budget=serve_hbm_budget(),
        result_cache_on=result_cache_bytes() is not None)
    candidates = capacity.recommend(snap, capacity_targets())
    recs = capacity.Advisor(confirm=1, clear=1).observe(candidates)
    return {"snapshot": snap, "candidates": candidates,
            "recommendations": recs,
            "verdict": capacity.verdict_for(recs if recs else candidates)}


def _fetch(url: str) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/queries",
                                timeout=5) as resp:
        return json.loads(resp.read().decode())


def _snapshot(url: Optional[str]) -> dict:
    if url is not None:
        return _fetch(url)
    from . import live
    return live.snapshot_all()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.obs",
        description="Console views over the live-query registry.")
    sub = parser.add_subparsers(dest="command")
    top = sub.add_parser("top", help="htop-style live query table")
    top.add_argument("--url", default=None,
                     help="remote exporter base URL (e.g. "
                          "http://127.0.0.1:9465); default: the local "
                          "in-process registry")
    top.add_argument("--interval", type=float, default=1.0,
                     help="refresh period in seconds (default 1.0)")
    top.add_argument("--once", action="store_true",
                     help="print one frame and exit")
    doctor = sub.add_parser(
        "doctor", help="explain a failed/slow query from its postmortem "
                       "bundle or plan fingerprint")
    doctor.add_argument("target",
                        help="path to a postmortem bundle JSON "
                             "(SRT_BUNDLE_DIR) or a plan fingerprint "
                             "with history records")
    doctor.add_argument("--history", default=None,
                        help="metrics-history JSONL for the baseline "
                             "(default: SRT_METRICS_HISTORY)")
    advisor = sub.add_parser(
        "advisor", help="capacity snapshot + ranked autoscaling advice")
    advisor.add_argument("--url", default=None,
                         help="remote exporter base URL (fetches its "
                              "/capacity); default: the local in-process "
                              "event window")
    advisor.add_argument("--history", default=None,
                         help="replay a metrics-history JSONL offline "
                              "instead of a live window")
    advisor.add_argument("--last", type=int, default=256,
                         help="history records to replay (newest first, "
                              "default 256)")
    advisor.add_argument("--json", action="store_true",
                         help="print the raw advisor payload as JSON")
    args = parser.parse_args(argv)
    if args.command == "doctor":
        from .doctor import main as doctor_main
        return doctor_main(args.target, history_path=args.history)
    if args.command == "advisor":
        payload = _advisor_payload(args.url, args.history, args.last)
        if args.json:
            print(json.dumps(payload, sort_keys=True))
        else:
            print(render_advisor(
                payload, source=args.url or args.history or "local"))
        return 0
    if args.command != "top":
        parser.print_help()
        return 2
    source = args.url or "local"
    try:
        while True:
            frame = render_top(_snapshot(args.url), source=source)
            frame += "\n".join(_capacity_pane(args.url))
            if args.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
