"""Window functions, datetime extraction, search, and compaction ops."""

import datetime as pydt

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu import ops
from spark_rapids_tpu.column import Column
from spark_rapids_tpu.dtypes import DType, TypeId
from spark_rapids_tpu.ops import window
from spark_rapids_tpu.ops import datetime as sdt

#: compile-heavy module: full tier only (smoke = -m 'not full').
pytestmark = pytest.mark.full


def sample_table():
    return srt.Table.from_pydict({
        "p": ["a", "a", "b", "a", "b", "b", "a"],
        "o": [3, 1, 5, 1, 2, 2, None],
        "v": [10, 20, 30, None, 50, 60, 70],
    }, dtypes={"p": dt.STRING, "o": dt.INT64, "v": dt.INT64})


class TestWindow:
    # Sorted views (nulls first, stable):
    #   partition a: row6(o=None,v=70), row1(o=1,v=20), row3(o=1,v=None),
    #                row0(o=3,v=10)
    #   partition b: row4(o=2,v=50), row5(o=2,v=60), row2(o=5,v=30)

    def test_row_number(self):
        t = sample_table()
        rn = window.row_number(t, ["p"], ["o"]).to_pylist()
        assert rn == [4, 2, 3, 3, 1, 2, 1]

    def test_rank_and_dense_rank(self):
        t = sample_table()
        r = window.rank(t, ["p"], ["o"]).to_pylist()
        d = window.dense_rank(t, ["p"], ["o"]).to_pylist()
        assert r == [4, 2, 3, 2, 1, 1, 1]
        assert d == [3, 2, 2, 2, 1, 1, 1]

    def test_lag_lead(self):
        t = sample_table()
        lagv = window.lag(t, "v", ["p"], ["o"]).to_pylist()
        leadv = window.lead(t, "v", ["p"], ["o"]).to_pylist()
        assert lagv == [None, 70, 60, 20, None, 50, None]
        assert leadv == [None, None, None, 10, 60, 30, 20]

    def test_lag_fill(self):
        # fill applies where the offset leaves the partition; null VALUES
        # inside the partition stay null.
        t = sample_table()
        lagv = window.lag(t, "v", ["p"], ["o"], fill=-1).to_pylist()
        assert lagv == [None, 70, 60, 20, -1, 50, -1]

    def test_cumulative_sum_and_count(self):
        t = sample_table()
        s = window.window_agg(t, "v", "sum", ["p"], ["o"]).to_pylist()
        c = window.window_agg(t, "v", "count", ["p"], ["o"]).to_pylist()
        assert s == [100, 90, 140, 90, 50, 110, 70]
        assert c == [3, 2, 3, 2, 1, 2, 1]

    def test_cumulative_min_max(self):
        t = sample_table()
        mn = window.window_agg(t, "v", "min", ["p"], ["o"]).to_pylist()
        mx = window.window_agg(t, "v", "max", ["p"], ["o"]).to_pylist()
        assert mn == [10, 20, 30, 20, 50, 50, 70]
        assert mx == [70, 70, 60, 70, 50, 60, 70]

    def test_partition_frame(self):
        t = sample_table()
        s = window.window_agg(t, "v", "sum", ["p"],
                              frame="partition").to_pylist()
        assert s == [100, 100, 140, 100, 140, 140, 100]
        mx = window.window_agg(t, "v", "max", ["p"],
                               frame="partition").to_pylist()
        assert mx == [70, 70, 60, 70, 60, 60, 70]

    def test_all_null_partition_value(self):
        t = srt.Table.from_pydict({
            "p": [1, 1, 2], "v": [None, None, 5],
        }, dtypes={"p": dt.INT64, "v": dt.INT64})
        s = window.window_agg(t, "v", "sum", ["p"],
                              frame="partition").to_pylist()
        assert s == [None, None, 5]

    def test_errors(self):
        t = sample_table()
        with pytest.raises(ValueError):
            window.window_agg(t, "v", "median", ["p"])
        with pytest.raises(ValueError):
            window.window_agg(t, "v", "sum", ["p"], frame="rows")
        with pytest.raises(ValueError):
            window.row_number(t, [])


class TestDatetime:
    def _ts_col(self, dts, unit):
        tid = {"s": TypeId.TIMESTAMP_SECONDS,
               "ms": TypeId.TIMESTAMP_MILLISECONDS,
               "us": TypeId.TIMESTAMP_MICROSECONDS}[unit]
        scale = {"s": 1, "ms": 10**3, "us": 10**6}[unit]
        epoch = pydt.datetime(1970, 1, 1)
        vals = [int((d - epoch).total_seconds() * scale) for d in dts]
        return Column.from_numpy(np.asarray(vals, np.int64),
                                 dtype=DType(tid))

    def test_civil_fields_vs_python(self):
        rng = np.random.default_rng(4)
        dts = [pydt.datetime(1970, 1, 1)
               + pydt.timedelta(days=int(d), seconds=int(s))
               for d, s in zip(rng.integers(-40000, 40000, 300),
                               rng.integers(0, 86400, 300))]
        col = self._ts_col(dts, "s")
        for field, want in [
            ("year", [d.year for d in dts]),
            ("month", [d.month for d in dts]),
            ("day", [d.day for d in dts]),
            ("hour", [d.hour for d in dts]),
            ("minute", [d.minute for d in dts]),
            ("second", [d.second for d in dts]),
            ("weekday", [d.isoweekday() for d in dts]),
            ("day_of_year", [d.timetuple().tm_yday for d in dts]),
        ]:
            got = sdt.extract(col, field).to_pylist()
            assert got == want, f"{field}: first diff at " \
                f"{next(i for i in range(len(got)) if got[i] != want[i])}"

    def test_subsecond_fields(self):
        us = 3 * 10**6 + 123_456
        col = Column.from_numpy(np.asarray([us], np.int64),
                                dtype=DType(TypeId.TIMESTAMP_MICROSECONDS))
        assert sdt.extract(col, "second").to_pylist() == [3]
        assert sdt.extract(col, "millisecond").to_pylist() == [123]
        assert sdt.extract(col, "microsecond").to_pylist() == [456]

    def test_days_dtype(self):
        col = Column.from_numpy(np.asarray([0, 19000, -1], np.int32),
                                dtype=DType(TypeId.TIMESTAMP_DAYS))
        assert sdt.year(col).to_pylist() == [1970, 2022, 1969]
        assert sdt.extract(col, "day").to_pylist() == [1, 8, 31]
        with pytest.raises(TypeError):
            sdt.extract(col, "hour")

    def test_non_timestamp_raises(self):
        col = Column.from_numpy(np.arange(3, dtype=np.int64))
        with pytest.raises(TypeError):
            sdt.year(col)


class TestSearchAndCompaction:
    def test_is_in_ints(self):
        col = Column.from_pylist([1, 5, None, 7, 2], dt.INT64)
        got = ops.is_in(col, [2, 5, 99]).to_pylist()
        assert got == [False, True, None, False, True]

    def test_is_in_strings(self):
        col = Column.from_pylist(["a", "b", None, "c"], dt.STRING)
        got = ops.is_in(col, ["c", "a", "zz"]).to_pylist()
        assert got == [True, False, None, True]

    def test_is_in_empty_values(self):
        col = Column.from_pylist([1, None], dt.INT64)
        assert ops.is_in(col, []).to_pylist() == [False, None]

    def test_bounds(self):
        hay = Column.from_numpy(np.asarray([1, 3, 3, 7], np.int64))
        needles = Column.from_numpy(np.asarray([0, 3, 8], np.int64))
        assert ops.lower_bound(hay, needles).to_pylist() == [0, 1, 4]
        assert ops.upper_bound(hay, needles).to_pylist() == [0, 3, 4]

    def test_distinct_keeps_first_in_order(self):
        t = srt.Table.from_pydict({
            "k": [3, 1, 3, None, 1, None],
            "v": [10, 20, 30, 40, 50, 60],
        }, dtypes={"k": dt.INT64, "v": dt.INT64})
        out = ops.distinct(t, subset=["k"])
        assert out["k"].to_pylist() == [3, 1, None]
        assert out["v"].to_pylist() == [10, 20, 40]

    def test_distinct_all_columns(self):
        t = srt.Table.from_pydict({
            "a": [1, 1, 1], "b": [2, 2, 3],
        }, dtypes={"a": dt.INT64, "b": dt.INT64})
        out = ops.distinct(t)
        assert out["a"].to_pylist() == [1, 1]
        assert out["b"].to_pylist() == [2, 3]

    def test_concat_tables(self):
        t1 = srt.Table.from_pydict({"x": [1, 2], "s": ["a", "b"]},
                                   dtypes={"x": dt.INT64, "s": dt.STRING})
        t2 = srt.Table.from_pydict({"x": [None, 4], "s": [None, "d"]},
                                   dtypes={"x": dt.INT64, "s": dt.STRING})
        out = ops.concat_tables([t1, t2])
        assert out["x"].to_pylist() == [1, 2, None, 4]
        assert out["s"].to_pylist() == ["a", "b", None, "d"]

    def test_concat_tables_schema_mismatch(self):
        t1 = srt.Table.from_pydict({"x": [1]}, dtypes={"x": dt.INT64})
        t2 = srt.Table.from_pydict({"y": [1]}, dtypes={"y": dt.INT64})
        with pytest.raises(ValueError):
            ops.concat_tables([t1, t2])
