"""Streaming plan executor contracts (exec/stream.py).

Four contracts:

1. **Bit-identity** — per-batch mode yields exactly what ``run_plan``
   produces on each batch (same programs, same materialization), across
   bucket-boundary-straddling sizes, null/string columns, and empty
   batches mid-stream; streaming combine mode's one output equals
   ``run_plan`` over the concatenated stream.
2. **Donation safety** — only engine-owned bucket-pad copies are ever
   consumed; the user's tables always survive, exact-capacity binds are
   never donated, and a donated (deleted) pad-cache entry is re-padded
   on the next sequential run, never served.
3. **Overlap** — on a feed with real decode latency the pipeline's wall
   time beats the serial phase sum (overlap_ratio > 0).
4. **Observability** — stream counters land in ``QueryMetrics.to_json()``
   and in the registry under SRT_METRICS, and knobs parse/validate.
"""

import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu import Column, Table, assert_tables_equal
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.exec import col, plan, run_plan_stream
from spark_rapids_tpu.exec.compile import run_plan
from spark_rapids_tpu.obs import (bench_stream_line, counter,
                                  last_stream_metrics, registry)
from spark_rapids_tpu.ops import concat_tables


@pytest.fixture
def metrics_on(monkeypatch):
    monkeypatch.setenv("SRT_METRICS", "1")
    registry().reset()
    yield
    registry().reset()


def _mk(n, seed, prefix="", hi=3):
    r = np.random.default_rng(seed)
    return Table.from_pydict({
        f"{prefix}k": r.integers(0, hi, n),
        f"{prefix}v": r.integers(0, 100, n),
    })


def _rowset(t: Table):
    """Order-insensitive exact row multiset (values and nulls)."""
    cols = [t[n].to_pylist() for n in t.names]
    return sorted(zip(*cols), key=repr)


# ---------------------------------------------------------------------------
# 1. bit-identity
# ---------------------------------------------------------------------------

class TestPerBatchIdentity:
    # 60/65/89 pad to a bucket; 64/88 sit exactly on a capacity boundary
    SIZES = [60, 64, 65, 88, 89, 1]

    def test_bit_identical_across_bucket_boundaries(self):
        p = (plan().filter(col("v") > 10)
                   .with_columns(w=col("v") * 2)
                   .sort_by(["v"]))
        batches = [_mk(n, seed) for seed, n in enumerate(self.SIZES)]
        outs = list(run_plan_stream(p, iter(batches), inflight=2))
        assert len(outs) == len(batches)
        for out, batch in zip(outs, batches):
            assert_tables_equal(out, run_plan(p, batch))

    def test_plan_run_stream_method(self):
        p = plan().filter(col("v") > 50)
        batches = [_mk(70, s) for s in range(3)]
        outs = list(p.run_stream(iter(batches)))
        for out, batch in zip(outs, batches):
            assert_tables_equal(out, run_plan(p, batch))

    def test_null_and_string_columns(self):
        def batch(seed, n=75):
            r = np.random.default_rng(seed)
            return Table([
                ("k", Column.from_pylist(
                    [None if i % 11 == 0 else int(r.integers(0, 5))
                     for i in range(n)], dt.INT64)),
                ("v", Column.from_numpy(r.normal(size=n),
                                        validity=r.random(n) > 0.2)),
                ("s", Column.from_pylist(
                    [None if i % 7 == 0 else f"s{i % 4}"
                     for i in range(n)], dt.STRING)),
            ])
        p = plan().filter(col("v") > 0.0)
        batches = [batch(s) for s in range(4)]
        outs = list(run_plan_stream(p, iter(batches), inflight=2))
        for out, b in zip(outs, batches):
            assert_tables_equal(out, run_plan(p, b))

    def test_empty_batch_mid_stream_preserves_order(self):
        p = plan().with_columns(w=col("v") + 1)
        batches = [_mk(60, 0), _mk(0, 1), _mk(70, 2)]
        outs = list(run_plan_stream(p, iter(batches), inflight=2))
        assert [o.num_rows for o in outs] == [60, 0, 70]
        for out, b in zip(outs, batches):
            assert_tables_equal(out, run_plan(p, b))

    def test_zero_batches_yields_nothing(self):
        assert list(run_plan_stream(plan().filter(col("v") > 0),
                                    iter([]))) == []

    def test_groupby_terminated_plan_per_batch(self):
        # no domains hint -> combine="auto" falls back to per-batch mode
        p = plan().groupby_agg(["k"], [("v", "sum", "vs")])
        batches = [_mk(n, s) for s, n in enumerate([60, 64, 89])]
        outs = list(run_plan_stream(p, iter(batches), inflight=2))
        assert len(outs) == len(batches)
        for out, b in zip(outs, batches):
            assert_tables_equal(out, run_plan(p, b))


# ---------------------------------------------------------------------------
# 2. donation safety
# ---------------------------------------------------------------------------

class TestDonation:
    # row-shaped outputs: XLA can alias the donated input buffers
    P = plan().filter(col("v") > 10).with_columns(w=col("v") * 2)

    def test_padded_copies_consumed_user_tables_survive(self):
        batches = [_mk(100, s) for s in range(6)]     # all pad 100 -> 112
        oracles = [run_plan(self.P, b) for b in batches]
        outs = list(run_plan_stream(self.P, iter(batches), inflight=3))
        qm = last_stream_metrics()
        assert qm.stream_donation_hits == 6
        assert qm.stream_donation_misses == 0
        for b in batches:
            assert not b.is_deleted()
        for out, want in zip(outs, oracles):
            assert_tables_equal(out, want)

    def test_deleted_pad_cache_entry_is_repadded(self):
        t = _mk(100, 7, prefix="rp_")
        p = plan().filter(col("rp_v") > 10).with_columns(w=col("rp_v") * 2)
        oracle = run_plan(p, t)
        outs = list(run_plan_stream(p, iter([t]), inflight=1))
        assert last_stream_metrics().stream_donation_hits == 1
        assert_tables_equal(outs[0], oracle)
        # the pad cache now holds a deleted (donated) copy for t; the
        # sequential path must re-pad instead of serving it
        assert_tables_equal(run_plan(p, t), oracle)

    def test_same_table_object_twice(self):
        t = _mk(100, 3)
        oracle = run_plan(self.P, t)
        outs = list(run_plan_stream(self.P, iter([t, t, t]), inflight=2))
        assert len(outs) == 3
        for out in outs:
            assert_tables_equal(out, oracle)
        assert not t.is_deleted()

    def test_no_donation_at_exact_bucket_capacity(self):
        # 64 rows bind at exact capacity: pad_to returns the user's table
        # itself, so donating would destroy caller-owned buffers
        batches = [_mk(64, s) for s in range(3)]
        outs = list(run_plan_stream(self.P, iter(batches), inflight=2))
        qm = last_stream_metrics()
        assert qm.stream_donation_hits == 0
        assert qm.stream_donation_misses == 3
        for b in batches:
            assert not b.is_deleted()
        for out, b in zip(outs, batches):
            assert_tables_equal(out, run_plan(self.P, b))

    def test_agg_outputs_cannot_alias_counted_as_miss(self):
        # a group-by program emits cells-shaped outputs, so the n-sized
        # donated buffers are never consumed — the hit counter must not lie
        p = plan().groupby_agg(["k"], [("v", "sum", "vs")])
        outs = list(run_plan_stream(p, iter([_mk(100, s) for s in range(4)]),
                                    inflight=2, combine=False))
        qm = last_stream_metrics()
        assert qm.stream_donation_hits == 0
        assert qm.stream_donation_misses == 4
        assert len(outs) == 4

    def test_outputs_never_read_donated_buffers(self):
        # with K batches in flight the donated inputs of batch N are dead
        # while N+1..N+K dispatch over recycled HBM; every output must
        # still equal its oracle after the whole stream drains
        batches = [_mk(100, 40 + s) for s in range(8)]
        oracles = [run_plan(self.P, b) for b in batches]
        outs = list(run_plan_stream(self.P, iter(batches), inflight=4))
        for out, want in zip(outs, oracles):
            assert_tables_equal(out, want)

    def test_inflight_depth_bounded(self):
        batches = [_mk(100, s) for s in range(7)]
        list(run_plan_stream(self.P, iter(batches), inflight=2))
        qm = last_stream_metrics()
        assert 1 <= qm.stream_peak_inflight <= 2


# ---------------------------------------------------------------------------
# combine mode
# ---------------------------------------------------------------------------

class TestCombine:
    AGGS = [("v", "sum", "vs"), ("v", "count", "vc"), ("v", "mean", "vm"),
            ("v", "min", "vlo"), ("v", "max", "vhi")]

    def _plan(self):
        return plan().groupby_agg(["k"], self.AGGS, domains={"k": (0, 2)})

    def test_combine_matches_concat_oracle(self):
        batches = [_mk(n, s) for s, n in enumerate([60, 64, 89, 100, 33])]
        outs = list(run_plan_stream(self._plan(), iter(batches), inflight=2,
                                    combine=True))
        assert len(outs) == 1
        oracle = run_plan(self._plan(), concat_tables(batches))
        assert _rowset(outs[0]) == _rowset(oracle)
        assert outs[0].names == oracle.names

    def test_combine_with_filter_project_prefix(self):
        p = (plan().filter(col("v") > 20)
                   .with_columns(w=col("v") * 3)
                   .groupby_agg(["k"], [("w", "sum", "ws"),
                                        ("w", "var", "wv")],
                                domains={"k": (0, 2)}))
        batches = [_mk(n, 10 + s) for s, n in enumerate([80, 100, 64])]
        outs = list(run_plan_stream(p, iter(batches), combine=True))
        oracle = run_plan(p, concat_tables(batches))
        assert _rowset(outs[0]) == _rowset(oracle)

    def test_combine_bool_key_needs_no_hint(self):
        def b(seed):
            r = np.random.default_rng(seed)
            return Table.from_pydict({
                "flag": r.integers(0, 2, 90).astype(np.bool_),
                "v": r.integers(0, 50, 90)})
        p = plan().groupby_agg(["flag"], [("v", "sum", "vs")])
        batches = [b(s) for s in range(3)]
        outs = list(run_plan_stream(p, iter(batches), combine=True))
        oracle = run_plan(p, concat_tables(batches))
        assert _rowset(outs[0]) == _rowset(oracle)

    def test_combine_with_null_keys(self):
        def b(seed, n=77):
            r = np.random.default_rng(seed)
            return Table([
                ("k", Column.from_numpy(r.integers(0, 3, n),
                                        validity=r.random(n) > 0.2)),
                ("v", Column.from_numpy(r.integers(0, 9, n)))])
        p = plan().groupby_agg(["k"], [("v", "sum", "vs")],
                               domains={"k": (0, 2)})
        batches = [b(s) for s in range(4)]
        outs = list(run_plan_stream(p, iter(batches), combine=True))
        oracle = run_plan(p, concat_tables(batches))
        assert _rowset(outs[0]) == _rowset(oracle)

    def test_combine_empty_batches(self):
        batches = [_mk(0, 0), _mk(80, 1), _mk(0, 2), _mk(64, 3), _mk(0, 4)]
        outs = list(run_plan_stream(self._plan(), iter(batches),
                                    combine=True))
        assert len(outs) == 1
        oracle = run_plan(self._plan(),
                          concat_tables([b for b in batches if b.num_rows]))
        assert _rowset(outs[0]) == _rowset(oracle)

    def test_combine_all_empty_stream(self):
        outs = list(run_plan_stream(self._plan(), iter([_mk(0, 0)]),
                                    combine=True))
        assert len(outs) == 1
        assert outs[0].num_rows == 0

    def test_strict_raises_on_non_groupby_plan(self):
        p = plan().sort_by(["v"])
        with pytest.raises(TypeError, match="does not end in a group-by"):
            run_plan_stream(p, iter([]), combine=True)

    def test_strict_raises_without_static_domain(self):
        p = plan().groupby_agg(["k"], [("v", "sum", "vs")])  # no hint
        it = run_plan_stream(p, iter([_mk(60, 0)]), combine=True)
        with pytest.raises(TypeError, match="static domain"):
            list(it)

    def test_auto_falls_back_to_per_batch(self):
        p = plan().groupby_agg(["k"], [("v", "sum", "vs")])  # no hint
        batches = [_mk(60, s) for s in range(3)]
        outs = list(run_plan_stream(p, iter(batches), combine="auto"))
        assert len(outs) == 3
        for out, b in zip(outs, batches):
            assert_tables_equal(out, run_plan(p, b))

    def test_combine_false_forces_per_batch(self):
        batches = [_mk(60, s) for s in range(2)]
        outs = list(run_plan_stream(self._plan(), iter(batches),
                                    combine=False))
        assert len(outs) == 2


# ---------------------------------------------------------------------------
# 3. overlap on a delayed feed
# ---------------------------------------------------------------------------

class TestOverlap:
    def test_overlap_ratio_positive_with_prefetch(self):
        # fresh column names force a compile miss, so the stream overlaps
        # real work (compile + dispatch) with the feed's decode latency
        p = (plan().filter(col("ov_v") > 10)
                   .with_columns(ov_w=col("ov_v") * 2))

        def feed():
            for i in range(8):
                time.sleep(0.02)
                yield _mk(100, i, prefix="ov_")

        outs = list(run_plan_stream(p, feed(), inflight=3, prefetch=4))
        assert len(outs) == 8
        qm = last_stream_metrics()
        assert qm.stream_source_seconds > 0.1
        assert qm.stream_overlap_ratio > 0
        assert qm.total_seconds < qm.stream_serial_seconds

    def test_abandoned_stream_shuts_down_prefetch(self):
        p = plan().filter(col("v") > 0)

        def feed():
            for i in range(1000):
                yield _mk(60, i)

        it = run_plan_stream(p, feed(), inflight=1, prefetch=1)
        next(it)
        it.close()
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if not [t for t in threading.enumerate()
                    if t.name == "srt-prefetch"]:
                break
            time.sleep(0.01)
        assert not [t for t in threading.enumerate()
                    if t.name == "srt-prefetch"]


# ---------------------------------------------------------------------------
# 4. observability + knobs
# ---------------------------------------------------------------------------

class TestStreamMetrics:
    P = plan().filter(col("v") > 10).with_columns(w=col("v") * 2)

    def test_stream_block_in_to_json(self):
        import json
        batches = [_mk(100, s) for s in range(5)]
        list(run_plan_stream(self.P, iter(batches), inflight=2))
        payload = json.loads(last_stream_metrics().to_json())
        assert payload["mode"] == "stream"
        assert payload["schema_version"] == 11
        s = payload["stream"]
        assert s["batches"] == 5
        assert s["inflight"] == 2
        assert 1 <= s["peak_inflight"] <= 2
        assert s["donation_hits"] == 5
        assert s["donation_misses"] == 0
        assert s["serial_seconds"] >= 0

    def test_registry_counters_fire(self, metrics_on):
        batches = [_mk(100, s) for s in range(4)]
        list(run_plan_stream(self.P, iter(batches), inflight=2))
        assert counter("stream.batches").value >= 4
        assert counter("stream.donation.hit").value >= 4

    def test_bench_stream_line(self):
        import json
        list(run_plan_stream(self.P, iter([_mk(100, 0)])))
        line = json.loads(bench_stream_line())
        assert line["metric"] == "stream_exec"
        assert line["runs"] == 1
        assert line["batches"] == 1
        assert "overlap_ratio" in line and "donation_hits" in line


class TestKnobs:
    def test_stream_inflight_default_and_env(self, monkeypatch):
        from spark_rapids_tpu.config import stream_inflight
        monkeypatch.delenv("SRT_STREAM_INFLIGHT", raising=False)
        assert stream_inflight() == 2
        monkeypatch.setenv("SRT_STREAM_INFLIGHT", "5")
        assert stream_inflight() == 5
        monkeypatch.setenv("SRT_STREAM_INFLIGHT", "0")
        with pytest.raises(ValueError):
            stream_inflight()

    def test_prefetch_depth_default_and_env(self, monkeypatch):
        from spark_rapids_tpu.config import prefetch_depth
        monkeypatch.delenv("SRT_PREFETCH_DEPTH", raising=False)
        assert prefetch_depth() == 2
        monkeypatch.setenv("SRT_PREFETCH_DEPTH", "7")
        assert prefetch_depth() == 7
        monkeypatch.setenv("SRT_PREFETCH_DEPTH", "-1")
        with pytest.raises(ValueError):
            prefetch_depth()

    def test_inflight_env_reaches_stream(self, monkeypatch):
        monkeypatch.setenv("SRT_STREAM_INFLIGHT", "3")
        p = plan().filter(col("v") > 0)
        list(run_plan_stream(p, iter([_mk(60, s) for s in range(2)])))
        assert last_stream_metrics().stream_inflight == 3

    @pytest.mark.parametrize("kwargs", [
        {"inflight": 0}, {"inflight": "2"}, {"combine": "always"},
        {"prefetch": 0}, {"prefetch": -3},
    ])
    def test_bad_arguments_raise_eagerly(self, kwargs):
        with pytest.raises(ValueError):
            run_plan_stream(plan().filter(col("v") > 0), iter([]), **kwargs)
