"""TPC-DS bank, reporting family: single-channel filter/join/agg shapes.

Same conventions as :mod:`.tpcds_queries` (dimension pre-filtering,
group-by-id/decode-after, FLOAT64 money); every query here reuses the
plan-compiler pipeline and is oracle-checked in tests/test_tpcds_report.py.
This module is imported by :mod:`.tpcds_queries` for the registry merge;
shared helpers live in :mod:`.tpcds_lib` to keep that merge acyclic.
"""

from __future__ import annotations

import numpy as np

from ..column import Column
from ..table import Table
from ..exec import col, lit, plan, when
from .tpcds import TpcdsData
from .tpcds_lib import _city_map, _class_map, _dim, _scalar_table


def q9(d: TpcdsData) -> Table:
    """TPC-DS q9: per quantity-bucket, report avg(ss_ext_discount_amt)
    when the bucket is populous else avg(ss_net_paid) — five scalar
    subqueries folded into one dense group-by plus a host-side CASE."""
    buckets = [(1, 20), (21, 40), (41, 60), (61, 80), (81, 100)]
    thresholds = [3000, 3000, 3000, 3000, 3000]
    e = None
    for i, (lo, hi) in enumerate(buckets):
        cond = col("ss_quantity").between(lo, hi)
        e = when(cond, i) if e is None else e.when(cond, i)
    p = (plan()
         .with_columns(bucket=e)
         .filter(col("bucket").between(0, 4))
         .groupby_agg(["bucket"],
                      [("ss_quantity", "count", "cnt"),
                       ("ss_ext_discount_amt", "mean", "avg_disc"),
                       ("ss_net_paid", "mean", "avg_paid")],
                      domains={"bucket": (0, 4)})
         .sort_by(["bucket"]))
    out = p.run(d.store_sales).to_pydict()
    by_bucket = {b: (c, ad, ap) for b, c, ad, ap in
                 zip(out["bucket"], out["cnt"], out["avg_disc"],
                     out["avg_paid"])}
    chosen = []
    for i in range(5):
        cnt, ad, ap = by_bucket.get(i, (0, None, None))
        chosen.append(ad if cnt > thresholds[i] else ap)
    return Table([
        ("bucket", Column.from_numpy(np.arange(5, dtype=np.int64))),
        ("chosen_avg", Column.from_numpy(
            np.asarray([np.nan if v is None else v for v in chosen]),
            validity=np.asarray([v is not None for v in chosen]))),
    ])


def q13(d: TpcdsData) -> Table:
    """TPC-DS q13: average sales stats under OR'd (demographic, price,
    household) and (state, profit) condition triples — the q48 shape plus
    a household-demographics leg."""
    cd = (plan()
          .with_columns(cd_tag=when(
              col("cd_marital_status").eq("M")
              & col("cd_education_status").eq("Advanced Degree"), 1)
              .when(col("cd_marital_status").eq("S")
                    & col("cd_education_status").eq("College"), 2)
              .when(col("cd_marital_status").eq("W")
                    & col("cd_education_status").eq("2 yr Degree"), 3)
              .otherwise(0))
          .select("cd_demo_sk", "cd_tag")
          .run(d.customer_demographics))
    addr = (plan()
            .with_columns(ca_tag=when(
                col("ca_state").isin(["TX", "OH"]), 1)
                .when(col("ca_state").isin(["OR", "NY", "WA"]), 2)
                .when(col("ca_state").isin(["GA", "TN", "IL"]), 3)
                .otherwise(0))
            .select("ca_address_sk", "ca_tag")
            .run(d.customer_address))
    hd = d.household_demographics.select(["hd_demo_sk", "hd_dep_count"])
    dates = _dim(d.date_dim, col("d_year").eq(1998), ["d_date_sk"])
    p = (plan()
         .join_broadcast(dates, left_on="ss_sold_date_sk",
                         right_on="d_date_sk", how="semi")
         .join_broadcast(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
         .join_broadcast(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
         .join_broadcast(addr, left_on="ss_addr_sk",
                         right_on="ca_address_sk")
         .filter(((col("cd_tag").eq(1)
                   & col("ss_sales_price").between(100.0, 150.0)
                   & col("hd_dep_count").eq(3))
                  | (col("cd_tag").eq(2)
                     & col("ss_sales_price").between(50.0, 100.0)
                     & col("hd_dep_count").eq(1))
                  | (col("cd_tag").eq(3)
                     & col("ss_sales_price").between(150.0, 200.0)
                     & col("hd_dep_count").eq(1)))
                 & ((col("ca_tag").eq(1)
                     & col("ss_net_profit").between(100.0, 200.0))
                    | (col("ca_tag").eq(2)
                       & col("ss_net_profit").between(150.0, 300.0))
                    | (col("ca_tag").eq(3)
                       & col("ss_net_profit").between(50.0, 250.0))))
         .with_columns(one=lit(1))
         .groupby_agg(["one"],
                      [("ss_quantity", "mean", "avg_qty"),
                       ("ss_ext_sales_price", "mean", "avg_esp"),
                       ("ss_ext_wholesale_cost", "mean", "avg_ewc"),
                       ("ss_ext_wholesale_cost", "sum", "sum_ewc")],
                      domains={"one": (1, 1)}))
    out = p.run(d.store_sales).to_pydict()

    def pick(name, default=None):
        vals = out[name]
        return vals[0] if vals else default
    return Table([
        ("avg_qty", Column.from_numpy(
            np.asarray([float(pick("avg_qty") or 0.0)]))),
        ("avg_esp", Column.from_numpy(
            np.asarray([float(pick("avg_esp") or 0.0)]))),
        ("avg_ewc", Column.from_numpy(
            np.asarray([float(pick("avg_ewc") or 0.0)]))),
        ("sum_ewc", Column.from_numpy(
            np.asarray([float(pick("sum_ewc") or 0.0)]))),
    ])


def q20(d: TpcdsData) -> Table:
    """TPC-DS q20: q12's class-revenue-share shape over the catalog
    channel."""
    from .tpcds import DATE_SK0
    items = _dim(d.item, col("i_category_id").isin([2, 5, 8]),
                 ["i_item_sk", "i_class_id"])
    p = (plan()
         .filter(col("cs_sold_date_sk").between(DATE_SK0 + 200,
                                                DATE_SK0 + 230))
         .join_broadcast(items, left_on="cs_item_sk",
                         right_on="i_item_sk")
         .groupby_agg(["i_class_id", "cs_item_sk"],
                      [("cs_ext_sales_price", "sum", "itemrevenue")])
         .window("classrevenue", "sum", partition_by=["i_class_id"],
                 value="itemrevenue", frame="partition")
         .with_columns(revenueratio=col("itemrevenue") * 100.0
                       / col("classrevenue"))
         .join_broadcast(_class_map(), left_on="i_class_id",
                         right_on="__class_id")
         .sort_by(["i_class_id", "cs_item_sk"])
         .limit(100))
    return p.run(d.catalog_sales)


def _deviation_query(d: TpcdsData, group_key: str, time_key: str,
                     item_pred) -> Table:
    """Shared q53/q63 shape: sum(ss_sales_price) per (group_key,
    time_key), partition average over the group, keep rows deviating
    more than 10%."""
    dates = _dim(d.date_dim, col("d_year").eq(1999),
                 ["d_date_sk", time_key])
    items = _dim(d.item, item_pred, ["i_item_sk", group_key])
    p = (plan()
         .join_broadcast(dates, left_on="ss_sold_date_sk",
                         right_on="d_date_sk")
         .join_broadcast(items, left_on="ss_item_sk",
                         right_on="i_item_sk")
         .groupby_agg([group_key, time_key],
                      [("ss_sales_price", "sum", "sum_sales")])
         .window("__psum", "sum", partition_by=[group_key],
                 value="sum_sales", frame="partition")
         .window("__pcnt", "count", partition_by=[group_key],
                 value="sum_sales", frame="partition")
         .with_columns(avg_quarterly_sales=col("__psum") / col("__pcnt"))
         .filter(when(col("avg_quarterly_sales") > 0.0,
                      abs(col("sum_sales") - col("avg_quarterly_sales"))
                      / col("avg_quarterly_sales")).otherwise(0.0) > 0.1)
         .select(group_key, "sum_sales", "avg_quarterly_sales", time_key)
         .sort_by(["avg_quarterly_sales", "sum_sales", group_key,
                   time_key])
         .limit(100))
    return p.run(d.store_sales)


def q53(d: TpcdsData) -> Table:
    """TPC-DS q53: manufacturers whose quarterly sales deviate >10% from
    their yearly average."""
    return _deviation_query(d, "i_manufact_id", "d_qoy",
                            col("i_manufact_id").between(1, 40))


def q63(d: TpcdsData) -> Table:
    """TPC-DS q63: q53's deviation shape per manager by month."""
    return _deviation_query(d, "i_manager_id", "d_moy",
                            col("i_manager_id").between(1, 40))


def q45(d: TpcdsData) -> Table:
    """TPC-DS q45: web revenue by customer zip/city where the zip is in
    a list OR the item is in a chosen item-id set (the OR of a column
    predicate and a subquery membership)."""
    zips = [85669, 86197, 88274, 83405, 86475]
    item_sks = [2, 3, 5, 7, 11, 13, 17, 19, 23, 29]
    dates = _dim(d.date_dim, col("d_qoy").eq(2) & col("d_year").eq(1999),
                 ["d_date_sk"])
    cust = d.customer.select(["c_customer_sk", "c_current_addr_sk"])
    addr = d.customer_address.select(["ca_address_sk", "ca_zip5",
                                      "ca_city_id"])
    p = (plan()
         .join_broadcast(dates, left_on="ws_sold_date_sk",
                         right_on="d_date_sk", how="semi")
         .join_broadcast(cust, left_on="ws_bill_customer_sk",
                         right_on="c_customer_sk")
         .join_broadcast(addr, left_on="c_current_addr_sk",
                         right_on="ca_address_sk")
         .filter(col("ca_zip5").isin(zips)
                 | col("ws_item_sk").isin(item_sks))
         .groupby_agg(["ca_zip5", "ca_city_id"],
                      [("ws_sales_price", "sum", "total_price")])
         .join_broadcast(_city_map(), left_on="ca_city_id",
                         right_on="__city_id")
         .sort_by(["ca_zip5", "ca_city_id"])
         .limit(100))
    return p.run(d.web_sales)


def q90(d: TpcdsData) -> Table:
    """TPC-DS q90: ratio of morning to evening web sales for one page
    char-count band and dependent count — one dense two-cell group-by
    instead of two scalar subqueries."""
    demos = _dim(d.household_demographics, col("hd_dep_count").eq(6),
                 ["hd_demo_sk"])
    pages = _dim(d.web_page, col("wp_char_count").between(4000, 5200),
                 ["wp_web_page_sk"])
    times = (plan()
             .with_columns(slot=when(col("t_hour").between(8, 9), 0)
                           .when(col("t_hour").between(19, 20), 1)
                           .otherwise(-1))
             .filter(col("slot").between(0, 1))
             .select("t_time_sk", "slot")
             .run(d.time_dim))
    # web_sales has no hdemo column in the synthetic schema; the
    # demographic leg rides the bill customer's household instead
    cust = d.customer.select(["c_customer_sk", "c_current_hdemo_sk"])
    p = (plan()
         .join_broadcast(pages, left_on="ws_web_page_sk",
                         right_on="wp_web_page_sk", how="semi")
         .join_broadcast(cust, left_on="ws_bill_customer_sk",
                         right_on="c_customer_sk")
         .join_broadcast(demos, left_on="c_current_hdemo_sk",
                         right_on="hd_demo_sk", how="semi")
         .join_broadcast(times, left_on="ws_sold_time_sk",
                         right_on="t_time_sk")
         .groupby_agg(["slot"], [("slot", "count", "cnt")],
                      domains={"slot": (0, 1)})
         .sort_by(["slot"]))
    out = p.run(d.web_sales).to_pydict()
    counts = dict(zip(out["slot"], out["cnt"]))
    am, pm = counts.get(0, 0), counts.get(1, 0)
    ratio = (am / pm) if pm else 0.0
    return _scalar_table(am_count=int(am), pm_count=int(pm),
                         am_pm_ratio=float(ratio))


def _per_ticket_count_query(d: TpcdsData, dom_pred, hd_pred,
                            county_list, lo: int, hi: int) -> Table:
    """Shared q34/q73 shape: tickets with between ``lo`` and ``hi``
    items, decorated with the buyer's name."""
    dates = _dim(d.date_dim,
                 dom_pred & col("d_year").isin([1998, 1999]),
                 ["d_date_sk"])
    stores = _dim(d.store, col("s_county").isin(county_list),
                  ["s_store_sk"])
    demos = _dim(d.household_demographics, hd_pred, ["hd_demo_sk"])
    cust = d.customer.select(["c_customer_sk", "c_salutation",
                              "c_first_name", "c_last_name",
                              "c_preferred_cust_flag"])
    p = (plan()
         .join_broadcast(dates, left_on="ss_sold_date_sk",
                         right_on="d_date_sk", how="semi")
         .join_broadcast(stores, left_on="ss_store_sk",
                         right_on="s_store_sk", how="semi")
         .join_broadcast(demos, left_on="ss_hdemo_sk",
                         right_on="hd_demo_sk", how="semi")
         .groupby_agg(["ss_ticket_number", "ss_customer_sk"],
                      [("ss_ticket_number", "count", "cnt")])
         .filter(col("cnt").between(lo, hi))
         .join_broadcast(cust, left_on="ss_customer_sk",
                         right_on="c_customer_sk")
         .sort_by(["ss_customer_sk", "cnt", "ss_ticket_number"],
                  ascending=[True, False, True])
         .limit(100))
    return p.run(d.store_sales)


def q34(d: TpcdsData) -> Table:
    """TPC-DS q34: customers buying 15-20 items on one ticket around the
    month turn, for big households in chosen counties."""
    return _per_ticket_count_query(
        d, col("d_dom").between(1, 3) | col("d_dom").between(25, 28),
        col("hd_vehicle_count") > 0,
        ["Fair County 0", "Rich County 1", "Walker County 0",
         "Ziebach County 1"], 15, 20)


def q73(d: TpcdsData) -> Table:
    """TPC-DS q73: q34's shape for 1-5 item tickets early in the
    month."""
    return _per_ticket_count_query(
        d, col("d_dom").between(1, 2),
        (col("hd_dep_count") > 0) | (col("hd_vehicle_count") > 1),
        ["Fair County 1", "Rich County 0", "Ziebach County 0"], 1, 5)


def q46(d: TpcdsData) -> Table:
    """TPC-DS q46: weekend shoppers' per-ticket coupon/profit when they
    bought in a city other than their home city (q68's shape with the
    weekend date cut)."""
    dates = _dim(d.date_dim,
                 col("d_dow").isin([0, 6])
                 & col("d_year").isin([1998, 1999]),
                 ["d_date_sk"])
    stores = _dim(d.store,
                  col("s_city").isin(["Midway", "Fairview"]),
                  ["s_store_sk"])
    demos = _dim(d.household_demographics,
                 col("hd_dep_count").eq(5) | col("hd_vehicle_count").eq(2),
                 ["hd_demo_sk"])
    addr = d.customer_address.select(["ca_address_sk", "ca_city_id"])
    cur_addr = (d.customer_address.select(["ca_address_sk", "ca_city_id"])
                .rename({"ca_address_sk": "__cur_addr",
                         "ca_city_id": "cur_city_id"}))
    cust = d.customer.select(["c_customer_sk", "c_current_addr_sk",
                              "c_first_name", "c_last_name"])
    p = (plan()
         .join_broadcast(dates, left_on="ss_sold_date_sk",
                         right_on="d_date_sk", how="semi")
         .join_broadcast(stores, left_on="ss_store_sk",
                         right_on="s_store_sk", how="semi")
         .join_broadcast(demos, left_on="ss_hdemo_sk",
                         right_on="hd_demo_sk", how="semi")
         .join_broadcast(addr, left_on="ss_addr_sk",
                         right_on="ca_address_sk")
         .groupby_agg(["ss_ticket_number", "ss_customer_sk",
                       "ca_city_id"],
                      [("ss_coupon_amt", "sum", "amt"),
                       ("ss_net_profit", "sum", "profit")])
         .join_broadcast(cust, left_on="ss_customer_sk",
                         right_on="c_customer_sk")
         .join_broadcast(cur_addr, left_on="c_current_addr_sk",
                         right_on="__cur_addr")
         .filter(col("cur_city_id").ne(col("ca_city_id")))
         .join_broadcast(_city_map(), left_on="ca_city_id",
                         right_on="__city_id")
         .sort_by(["ss_customer_sk", "ss_ticket_number", "ca_city_id"])
         .limit(100))
    return p.run(d.store_sales)


QUERIES = {
    "q9": q9, "q13": q13, "q20": q20, "q34": q34, "q45": q45,
    "q46": q46, "q53": q53, "q63": q63, "q73": q73, "q90": q90,
}
