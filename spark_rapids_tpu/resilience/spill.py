"""Spill manager — out-of-core paging for the OOM ladder's terminal rung.

When the recovery ladder's evict/backoff/split rungs are exhausted (and
proactively, when serving admission sees claimed bytes crossing the
``SRT_SPILL_WATERMARK`` fraction of ``SRT_SERVE_HBM_BUDGET``), this
module pages cold partitions OUT of HBM — first into a byte-capped
host-RAM LRU (``SRT_SPILL_HOST_BYTES``), overflowing oldest-first to
Parquet spill files (io/spill.py, ``SRT_SPILL_DIR``) — and pages them
back on demand, so a working set larger than HBM completes instead of
failing.  Paged values are arbitrary jax pytrees (streaming-combine
partial accumulators, bucket buffers, Tables): flatten → ``device_get``
→ free the device buffers, and the reverse on page-in, so a paged-back
value is bit-identical to the one paged out and folds through exactly
the same compute (the ``SRT_SPILL=0`` oracle contract).

Two integration surfaces:

  * **pages** — :meth:`SpillManager.page_out` / :meth:`page_in`, used by
    holders of cold state (exec/stream.py parks idle combine levels);
  * **victims** — :meth:`register_victim` callbacks the ladder's
    ``spill`` rung (:mod:`.recovery`) and admission's proactive path
    drive via :meth:`reclaim`: each callback frees device bytes it owns
    (the bucketing pad cache's last-touch LRU, a streaming driver's
    idle levels) and returns how many.

Everything lands in the ``recovery.spill.*`` stats/counters
(:mod:`.retry`) — pages/bytes out and in, files, page-in seconds — the
receipts QueryMetrics, the capacity advisor's ``spill_pressure`` rule,
and the doctor's thrash finding are built from.

jax-free at module import (the package rule): jax/numpy/pyarrow load
only inside paging methods, at which point the engine is necessarily
live.  With ``SRT_SPILL`` unset everything here is inert — the ladder
keeps its old fail-with-named-rungs behavior.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

from .retry import recovery_stats


class _Page:
    """One paged-out pytree: host leaves (or a disk path) + treedef."""
    __slots__ = ("key", "leaves", "treedef", "nbytes", "path")

    def __init__(self, key, leaves, treedef, nbytes):
        self.key = key
        self.leaves = leaves            # numpy leaves, or None once on disk
        self.treedef = treedef
        self.nbytes = nbytes
        self.path: Optional[str] = None  # spill-file path once flushed


class SpillManager:
    """Process-wide two-tier (host RAM → Parquet) page store + victim
    registry.  All methods are thread-safe; the serving scheduler's
    workers and the recovery ladder share one instance."""

    def __init__(self):
        self._lock = threading.RLock()
        self._pages: "OrderedDict[Any, _Page]" = OrderedDict()
        self._victims: "OrderedDict[str, Callable]" = OrderedDict()
        self._store = None
        self._host_bytes = 0

    # -- config reads (live, so tests can flip knobs per-case) -----------

    @property
    def enabled(self) -> bool:
        from ..config import spill_enabled
        return spill_enabled()

    def over_watermark(self, live_bytes: int,
                       budget: Optional[int] = None) -> bool:
        """True when ``live_bytes`` crosses the proactive-spill
        watermark of the serving HBM budget (both knobs must be set)."""
        if not self.enabled:
            return False
        if budget is None:
            from ..config import serve_hbm_budget
            budget = serve_hbm_budget()
        if not budget:
            return False
        from ..config import spill_watermark
        return live_bytes > spill_watermark() * budget

    def _file_store(self):
        if self._store is None:
            from ..io.spill import SpillFileStore
            self._store = SpillFileStore()
        return self._store

    # -- paging ----------------------------------------------------------

    def page_out(self, key: Any, value: Any) -> int:
        """Move ``value`` (any jax pytree) out of HBM under ``key``;
        returns device bytes freed.  The caller must treat ``value`` as
        gone until :meth:`page_in` hands back its bit-identical twin."""
        import jax
        import numpy as np
        from ..utils.memory import free
        leaves, treedef = jax.tree_util.tree_flatten(value)
        np_leaves = [np.asarray(jax.device_get(leaf)) for leaf in leaves]
        free(*leaves)
        nbytes = sum(int(leaf.nbytes) for leaf in np_leaves)
        page = _Page(key, np_leaves, treedef, nbytes)
        with self._lock:
            old = self._pages.pop(key, None)
            if old is not None:
                self._drop_page_storage(old)
            self._pages[key] = page
            self._host_bytes += nbytes
            self._flush_over_cap_locked()
        stats = recovery_stats()
        stats.add_spill_page_out(nbytes)
        from ..obs.metrics import gauge
        gauge("spill.host_bytes").set(self._host_bytes)
        from ..obs.timeline import instant
        instant("spill.page_out", cat="resilience", key=str(key),
                nbytes=nbytes)
        return nbytes

    def page_in(self, key: Any) -> Any:
        """Bring a page back as device arrays; removes the page (and its
        spill file).  Raises ``KeyError`` for an unknown key."""
        t0 = time.perf_counter()
        with self._lock:
            page = self._pages.pop(key)
            if page.leaves is None:
                # On disk: read outside the lock would be nicer, but the
                # page is already ours (popped) — only the store syncs.
                pass
            else:
                self._host_bytes -= page.nbytes
        leaves = page.leaves
        if leaves is None:
            leaves = self._file_store().read(page.path)
            self._file_store().remove(page.path)
        import jax.numpy as jnp
        device_leaves = [jnp.asarray(leaf) for leaf in leaves]
        value = page.treedef.unflatten(device_leaves)
        seconds = time.perf_counter() - t0
        stats = recovery_stats()
        stats.add_spill_page_in(page.nbytes, seconds)
        from ..obs.metrics import gauge
        gauge("spill.host_bytes").set(self._host_bytes)
        from ..obs.timeline import instant
        instant("spill.page_in", cat="resilience", key=str(key),
                nbytes=page.nbytes, seconds=round(seconds, 6))
        return value

    def has_page(self, key: Any) -> bool:
        with self._lock:
            return key in self._pages

    def drop_page(self, key: Any) -> None:
        """Discard a page without reviving it (owner abandoned the
        value — e.g. a streaming driver torn down mid-query)."""
        with self._lock:
            page = self._pages.pop(key, None)
            if page is not None:
                self._drop_page_storage(page)

    def _flush_over_cap_locked(self) -> None:
        """Overflow oldest host pages to Parquet until under the
        ``SRT_SPILL_HOST_BYTES`` cap (0 = disk-only: everything
        flushes).  Caller holds the lock."""
        from ..config import spill_host_bytes
        cap = spill_host_bytes()
        if self._host_bytes <= cap:
            return
        stats = recovery_stats()
        for page in list(self._pages.values()):
            if self._host_bytes <= cap:
                break
            if page.leaves is None:
                continue
            path, _ = self._file_store().write(page.leaves)
            page.path = path
            page.leaves = None
            self._host_bytes -= page.nbytes
            stats.add_spill_file()

    def _drop_page_storage(self, page: _Page) -> None:
        if page.leaves is not None:
            self._host_bytes -= page.nbytes
            page.leaves = None
        elif page.path is not None:
            self._file_store().remove(page.path)

    # -- victims (the ladder's spill rung drives these) ------------------

    def register_victim(self, name: str, fn: Callable[[], int]) -> None:
        """Register a callback that frees device bytes it owns (pages
        its cold state out through this manager, or drops recomputable
        buffers) and returns how many it freed."""
        with self._lock:
            self._victims[name] = fn

    def unregister_victim(self, name: str) -> None:
        with self._lock:
            self._victims.pop(name, None)

    def reclaim(self, target_bytes: Optional[int] = None) -> int:
        """The spill rung's body: free device bytes by dropping the
        bucketing layer's last-touch pad/resident caches and running
        every registered victim, until ``target_bytes`` is met (None =
        free everything reclaimable).  Returns bytes freed."""
        freed = 0
        try:
            from ..exec.bucketing import spill_pad_victims
            freed += spill_pad_victims(target_bytes)
        except ImportError:                      # pragma: no cover
            pass
        with self._lock:
            victims = list(self._victims.items())
        for name, fn in victims:
            if target_bytes is not None and freed >= target_bytes:
                break
            try:
                freed += int(fn() or 0)
            except Exception:
                # A broken victim must not turn one OOM into two
                # failures; it just contributes nothing.
                self.unregister_victim(name)
        if freed:
            from ..obs.metrics import counter
            counter("spill.reclaimed_bytes").inc(freed)
        return freed

    # -- accounting / lifecycle ------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            on_disk = sum(1 for p in self._pages.values()
                          if p.leaves is None)
            return {"pages": len(self._pages),
                    "pages_on_disk": on_disk,
                    "host_bytes": self._host_bytes,
                    "victims": len(self._victims)}

    def reset(self) -> None:
        """Drop all pages (removing their files) and victims — test and
        bench isolation.  Parked state referencing dropped pages is the
        caller's to forget."""
        with self._lock:
            pages = list(self._pages.values())
            self._pages.clear()
            self._host_bytes = 0
            self._victims.clear()
            store, self._store = self._store, None
        if store is not None:
            for page in pages:
                if page.path is not None:
                    store.remove(page.path)


_MANAGER = SpillManager()


def spill_manager() -> SpillManager:
    """The process-wide spill manager."""
    return _MANAGER


def reset_spill() -> None:
    """Reset the process-wide manager (test isolation)."""
    _MANAGER.reset()


def maybe_proactive_spill(projected_bytes: int,
                          budget: Optional[int]) -> int:
    """Admission's proactive hook: when ``projected_bytes`` (claimed +
    the incoming estimate) crosses the watermark fraction of the
    budget, reclaim enough to get back under it BEFORE the claim has to
    wait.  Returns bytes freed (0 when spill is off or under the
    watermark)."""
    mgr = spill_manager()
    if not mgr.over_watermark(projected_bytes, budget):
        return 0
    from ..config import spill_watermark
    target = projected_bytes - int(spill_watermark() * budget)
    freed = mgr.reclaim(target)
    if freed:
        from ..obs.metrics import counter
        counter("spill.proactive").inc()
        from ..obs import live as _live
        _live.rung("spill-proactive", site="admission")
    return freed


__all__ = ["SpillManager", "maybe_proactive_spill", "reset_spill",
           "spill_manager"]
