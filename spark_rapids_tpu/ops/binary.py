"""Elementwise binary/unary operations with null propagation.

cuDF binary-ops surface, null semantics: result is null where either input
is null (and-masks compose for free in XLA — the mask ops fuse into the
arithmetic).  Scalars broadcast.  Decimal add/sub require matching scales
(callers rescale via :func:`..ops.cast.cast`); decimal mul adds scales.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..column import Column
from ..dtypes import BOOL8, DType, FLOAT64, INT64, TypeId

Operand = Union[Column, int, float, bool]


def _combine_validity(a: Column, b: Optional[Column]) -> Optional[jax.Array]:
    masks = [c.validity for c in (a, b) if isinstance(c, Column) and c.validity is not None]
    if not masks:
        return None
    out = masks[0]
    for m in masks[1:]:
        out = out & m
    return out


def _payload(x: Operand):
    return x.data if isinstance(x, Column) else x


def _check_decimal_operands(a: Column, b: Operand, op: str) -> None:
    """Decimal ops are only defined decimal-to-decimal; add/sub/compare need
    matching scales (cast first).  Anything else silently misinterprets the
    unscaled payload, so reject it."""
    a_dec = a.dtype.is_decimal
    b_dec = isinstance(b, Column) and b.dtype.is_decimal
    if not a_dec and not b_dec:
        return
    if not (a_dec and b_dec):
        raise ValueError(
            f"decimal {op}: both operands must be decimal columns "
            f"(cast the other operand into a decimal first)")
    if op == "mul" or op == "truediv":
        return
    if a.dtype.scale != b.dtype.scale:
        raise ValueError(
            f"decimal {op} requires matching scales "
            f"({a.dtype.scale} vs {b.dtype.scale}): rescale via ops.cast")


def _result_dtype(a: Column, b: Operand, op: str) -> DType:
    if op in ("eq", "ne", "lt", "le", "gt", "ge", "and", "or"):
        return BOOL8
    if isinstance(b, Column):
        if a.dtype.is_decimal and b.dtype.is_decimal:
            if op in ("add", "sub"):
                return a.dtype
            if op == "mul":
                return DType(a.dtype.type_id, a.dtype.scale + b.dtype.scale)
            if op in ("div", "truediv"):
                return FLOAT64
        if a.dtype.itemsize >= b.dtype.itemsize:
            return a.dtype if not b.dtype.is_floating or a.dtype.is_floating else b.dtype
        return b.dtype if not a.dtype.is_floating or b.dtype.is_floating else a.dtype
    return a.dtype


_OPS = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "truediv": jnp.true_divide, "floordiv": jnp.floor_divide, "mod": jnp.mod,
    "pow": jnp.power,
    "eq": jnp.equal, "ne": jnp.not_equal, "lt": jnp.less, "le": jnp.less_equal,
    "gt": jnp.greater, "ge": jnp.greater_equal,
    "and": jnp.logical_and, "or": jnp.logical_or,
}


#: scalar-op-column forms: how to express `scalar OP col` as `col OP' ...`
_REFLECT = {"add": "add", "mul": "mul", "and": "and", "or": "or",
            "and_kleene": "and_kleene", "or_kleene": "or_kleene",
            "eq": "eq", "ne": "ne",
            "lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}


def binary_op(a: Operand, b: Operand, op: str) -> Column:
    if not isinstance(a, Column):
        # Literal-first expressions (Spark plans emit them, e.g. `1 - disc`).
        if not isinstance(b, Column):
            raise TypeError("binary_op needs at least one Column operand")
        if op in _REFLECT:
            return binary_op(b, a, _REFLECT[op])
        if op == "sub":                  # s - x  ==  (-x) + s
            return binary_op(unary_op(b, "neg"), a, "add")
        if op in ("truediv", "floordiv", "mod", "pow"):
            # Materialize the literal as a column; the normal path handles
            # promotion and null propagation.
            lit = Column.all_valid(
                jnp.full(b.data.shape, a,
                         jnp.float64 if isinstance(a, float) else jnp.int64),
                FLOAT64 if isinstance(a, float) else INT64)
            return binary_op(lit, b, op)
        raise ValueError(f"unsupported binary op {op!r} with scalar left operand")
    if op in ("or_kleene", "and_kleene"):
        return _kleene(a, b, op)
    if op not in _OPS:
        raise ValueError(f"unsupported binary op {op!r}")
    _check_decimal_operands(a, b, op)
    out_dtype = _result_dtype(a, b, op)
    x, y = _payload(a), _payload(b)
    if op in ("and", "or"):
        x = x != 0
        if isinstance(y, jax.Array):
            y = y != 0
    if op == "truediv":
        if a.dtype.is_decimal:
            # divide logical values: scale both payloads
            x = x.astype(jnp.float64) * (10.0 ** a.dtype.scale)
            y = y.astype(jnp.float64) * (10.0 ** b.dtype.scale)
            out_dtype = FLOAT64
        elif not a.dtype.is_floating:
            x = x.astype(jnp.float64)
            out_dtype = FLOAT64
    res = _OPS[op](x, y)
    if out_dtype == BOOL8:
        res = res.astype(jnp.uint8)
    else:
        res = res.astype(out_dtype.jnp_dtype)
    return Column(data=res,
                  validity=_combine_validity(a, b if isinstance(b, Column) else None),
                  dtype=out_dtype)


def _kleene(a: Column, b: Operand, op: str) -> Column:
    """SQL three-valued AND/OR (Spark semantics; cudf's NULL_LOGICAL_AND/
    NULL_LOGICAL_OR): ``true OR null = true``, ``false AND null = false``,
    unlike the plain ``and``/``or`` ops which propagate nulls
    unconditionally.  Plan expressions (exec.expr ``&``/``|``) lower to
    these so compiled queries match Spark's WHERE-clause logic."""
    xa = _payload(a) != 0
    yb = _payload(b)
    if isinstance(yb, jax.Array):
        xb = yb != 0
        vb = b.validity if isinstance(b, Column) else None
    else:
        xb = jnp.full(xa.shape, bool(yb))
        vb = None
    va = a.validity
    ones = None
    ma = va if va is not None else (ones := jnp.ones(xa.shape, jnp.bool_))
    mb = vb if vb is not None else (ones if ones is not None
                                    else jnp.ones(xa.shape, jnp.bool_))
    at = ma & xa                     # definitely true
    bt = mb & xb
    af = ma & ~xa                    # definitely false
    bf = mb & ~xb
    if op == "or_kleene":
        data = at | bt
        validity = at | bt | (af & bf)
    else:
        data = ~(af | bf) & (at & bt)
        validity = af | bf | (at & bt)
    if va is None and vb is None:
        validity = None
    return Column(data=data.astype(jnp.uint8), validity=validity,
                  dtype=BOOL8)


# -- unary --------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs, "neg": jnp.negative, "not": lambda x: (x == 0),
    "sqrt": jnp.sqrt, "floor": jnp.floor, "ceil": jnp.ceil,
    "exp": jnp.exp, "log": jnp.log, "sin": jnp.sin, "cos": jnp.cos,
    "rint": jnp.rint,
}


def unary_op(a: Column, op: str) -> Column:
    if op not in _UNARY:
        raise ValueError(f"unsupported unary op {op!r}")
    res = _UNARY[op](a.data)
    out_dtype = a.dtype
    if op == "not":
        res = res.astype(jnp.uint8)
        out_dtype = BOOL8
    else:
        res = res.astype(a.dtype.jnp_dtype)
    return Column(data=res, validity=a.validity, dtype=out_dtype)


def is_null(a: Column) -> Column:
    mask = (~a.valid_mask()).astype(jnp.uint8)
    return Column(data=mask, dtype=BOOL8)


def is_valid(a: Column) -> Column:
    return Column(data=a.valid_mask().astype(jnp.uint8), dtype=BOOL8)


def fill_null(a: Column, value) -> Column:
    """Replace nulls with a scalar (cudf ``replace_nulls``)."""
    if a.validity is None:
        return a
    if a.dtype.is_string:
        from .strings import fill_null_strings
        return fill_null_strings(a, value)
    data = jnp.where(a.validity, a.data, a.data.dtype.type(value))
    return Column(data=data, dtype=a.dtype)


def if_else(cond: Column, a: Operand, b: Operand) -> Column:
    """Row-wise select (cudf ``copy_if_else``): where cond true -> a else b."""
    pred = cond.data != 0
    if cond.validity is not None:
        pred = pred & cond.validity
    xa, xb = _payload(a), _payload(b)
    dtype = a.dtype if isinstance(a, Column) else b.dtype
    data = jnp.where(pred, xa, xb).astype(dtype.jnp_dtype)
    validity = None
    va = a.validity if isinstance(a, Column) else None
    vb = b.validity if isinstance(b, Column) else None
    if va is not None or vb is not None:
        ma = va if va is not None else jnp.ones(cond.size, jnp.bool_)
        mb = vb if vb is not None else jnp.ones(cond.size, jnp.bool_)
        validity = jnp.where(pred, ma, mb)
    return Column(data=data, validity=validity, dtype=dtype)
