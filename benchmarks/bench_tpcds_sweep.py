"""TPC-DS query-bank sweep: queries/hr on one chip.

BASELINE.json's north-star metric is "TPC-DS SF1000 queries/hr"; this
bench runs the implemented bank (spark_rapids_tpu/models/tpcds_queries)
end to end — generation excluded, compile included only in the warm-up
pass — and reports steady-state queries/hr, the compile-once execution
model a Spark plan cache gives the reference system.

Protocol per the repo's tunneled-TPU measurement rules (BASELINE.md):
each query materializes its result (host sync) every iteration, so the
timed loop is fence-accurate by construction; the warm-up pass absorbs
per-program tunnel load cost (~30s/program first time, ~0 after).

Usage: python benchmarks/bench_tpcds_sweep.py [sf_rows] [passes]
Prints one JSON line {"metric", "value", "unit", "per_query"}.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> None:
    sf_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    passes = int(sys.argv[2]) if len(sys.argv) > 2 else 3

    from spark_rapids_tpu.models import tpcds
    from spark_rapids_tpu.models.tpcds_queries import QUERIES

    t0 = time.time()
    d = tpcds.generate(sf_rows)
    print(f"# generated sf_rows={sf_rows} in {time.time() - t0:.1f}s",
          file=sys.stderr)

    # Warm-up: compile + load every program once.
    t0 = time.time()
    for nm, fn in QUERIES.items():
        t1 = time.time()
        fn(d)
        print(f"# warm {nm}: {time.time() - t1:.2f}s", file=sys.stderr)
    print(f"# warm pass total {time.time() - t0:.1f}s", file=sys.stderr)

    per_query: dict[str, float] = {}
    t_all = time.time()
    n_runs = 0
    for _ in range(passes):
        for nm, fn in QUERIES.items():
            t1 = time.time()
            fn(d)
            per_query[nm] = per_query.get(nm, 0.0) + (time.time() - t1)
            n_runs += 1
    wall = time.time() - t_all
    qph = n_runs / wall * 3600.0

    print(json.dumps({
        "metric": "tpcds_bank_queries_per_hour",
        "value": round(qph, 1),
        "unit": "queries/hr",
        "sf_rows": sf_rows,
        "queries": len(QUERIES),
        "per_query_s": {k: round(v / passes, 3)
                        for k, v in sorted(per_query.items())},
    }))


if __name__ == "__main__":
    main()
