"""The HBM-OOM recovery ladder: evict → bounded retry → (caller splits).

The engine's memory consumers are the whole-plan program cache
(exec/compile.py ``_COMPILED`` — live executables pin HBM for constants
and donated scratch) and the bucket pad cache (exec/bucketing.py
``_PAD_CACHE`` — full padded copies of recent input tables).  On a
``RESOURCE_EXHAUSTED`` both are dropped wholesale before each retry:
reruns recompile/re-pad (the persistent XLA cache keeps recompiles
cheap), but the device gets its memory back.

:func:`oom_ladder` runs the evict-and-retry rungs and raises
:class:`ExecutionRecoveryError` (chained to the ORIGINAL error) when the
budget is spent; batch *splitting* — the last rung — lives with the
callers (exec/compile.py ``_split_batch``, exec/stream.py) because only
they know how to recombine the pieces (concat for row-local plans,
accumulator merge for streaming combine).  They catch the ladder's error
and append their split outcome to its step list.

This module is jax-free at import; jax is only touched inside the
eviction path at recovery time, when the engine is necessarily live.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from .classify import (CATEGORY_COMPILE, CATEGORY_OOM,
                       ExecutionRecoveryError, RecoverySummary, classify)
from .retry import RetryPolicy, recovery_stats

#: Recursion bound for the split rung: each level halves the batch, so 4
#: levels shrink it 16x — past that the OOM is not batch-size-driven.
MAX_SPLIT_DEPTH = 4


class SplitUnavailable(RuntimeError):
    """Internal signal from a split callback: this plan/batch cannot be
    split (single row, non-row-local and non-combinable plan, depth
    exhausted).  The caller appends the reason to the ladder's error."""


def evict_device_caches() -> int:
    """Rung 1: drop every engine-owned device-buffer cache — the
    whole-plan program LRU, the bucket pad cache, the decoded dictionary
    table, the encoded-residency registry (scan-built dictionary codes,
    SRT_ENCODED_EXEC), and (when the dist layer is loaded) the
    sharded-program LRU, the live-count memo, and the parallel-op program
    cache.  Returns entries dropped (recorded in
    ``recovery.cache_evictions``).

    The dist caches are looked up via ``sys.modules`` instead of
    imported: a single-chip process that never touched the mesh must not
    pay the dist-layer import (and has nothing to evict there anyway);
    same for ops.strings — its residency registry only fills when a scan
    ran with encoded execution on.
    """
    import sys
    from ..exec import compile as _compile
    from ..exec.bucketing import clear_pad_cache
    # The program LRUs are shared with concurrent serving threads mid
    # get-or-insert; take the cache lock so a wholesale clear never
    # interleaves with a lookup's insert/move-to-end.
    with _compile._CACHE_LOCK:
        dropped = len(_compile._COMPILED) + len(_compile._DECODED_DICTS)
        _compile._COMPILED.clear()
        _compile._DECODED_DICTS.clear()
        dropped += clear_pad_cache()
        root = __package__.rsplit(".", 1)[0]
        strings_mod = sys.modules.get(f"{root}.ops.strings")
        if strings_mod is not None:
            dropped += strings_mod.clear_resident_encodings()
        dist_mod = sys.modules.get(f"{root}.exec.dist")
        if dist_mod is not None:
            dropped += (len(dist_mod._DIST_COMPILED)
                        + len(dist_mod._LIVE_COUNT))
            dist_mod._DIST_COMPILED.clear()
            dist_mod._LIVE_COUNT.clear()
        mesh_mod = sys.modules.get(f"{root}.parallel.mesh")
        if mesh_mod is not None:
            dropped += len(mesh_mod._DIST_PROGRAMS)
            mesh_mod._DIST_PROGRAMS.clear()
    recovery_stats().add_evictions(dropped)
    return dropped


def oom_ladder(site: str, fn: Callable,
               policy: Optional[RetryPolicy] = None,
               drain: Optional[Callable] = None,
               dist: bool = False):
    """Run ``fn()`` under the evict-and-retry rungs of the recovery
    ladder for OOM/compile-classified failures.

    On the first qualifying failure: ``drain()`` once (the streaming
    executor materializes its in-flight batches here, freeing their
    output buffers), then up to ``policy.max_retries`` rounds of cache
    evict + backoff + retry.  Exhaustion raises
    :class:`ExecutionRecoveryError` chained to the ORIGINAL error; the
    caller may catch it and attempt the split rung.  Non-OOM errors
    propagate untouched.

    ``dist=True`` marks a mesh-ladder run (exec/dist.py): every rung
    ALSO bumps the ``dist_*`` recovery stats so the ``recovery.dist``
    block of QueryMetrics isolates the mesh share of the totals.
    """
    try:
        return fn()
    except Exception as exc:
        category = classify(exc)
        if category not in (CATEGORY_OOM, CATEGORY_COMPILE):
            raise
        original = exc
    from ..obs import live as _live
    from ..obs.timeline import instant, span
    if policy is None:
        policy = RetryPolicy.from_env()
    stats = recovery_stats()
    summary = RecoverySummary(site=site, category=category)
    if drain is not None:
        with span("recovery.drain", cat="resilience", site=site):
            drain()
        summary.steps.append("drain-inflight")
        _live.rung("drain-inflight", site=site)
    for attempt in range(policy.max_retries):
        dropped = evict_device_caches()
        if dist:
            stats.add_dist_evictions(dropped)
        summary.cache_evictions += dropped
        summary.steps.append(f"evict-caches[{dropped}]")
        instant("recovery.evict_caches", cat="resilience", site=site,
                dropped=dropped, attempt=attempt)
        _live.rung("evict-caches", site=site)
        delay = policy.delay(attempt)
        if delay > 0:
            with span("recovery.backoff", cat="resilience", site=site,
                      seconds=delay):
                time.sleep(delay)
        summary.backoff_seconds += delay
        stats.add_backoff(delay)
        stats.add_retry()
        if dist:
            stats.add_dist_retry()
        summary.retries += 1
        summary.steps.append("retry")
        instant("recovery.retry", cat="resilience", site=site,
                category=category, attempt=attempt)
        _live.rung("retry", site=site)
        try:
            return fn()
        except Exception as exc:
            if classify(exc) not in (CATEGORY_OOM, CATEGORY_COMPILE):
                raise
    # Terminal rung: spill-and-continue (SRT_SPILL).  Evict/backoff/retry
    # is spent; before declaring exhaustion, page cold device state out
    # through the spill manager (bucketing's last-touch pad caches plus
    # any registered victims — e.g. a streaming driver's idle combine
    # levels) and re-run ONCE against the freed HBM.  Default-off keeps
    # the old fail-with-named-rungs behavior bit-for-bit.
    from .spill import spill_manager
    mgr = spill_manager()
    if mgr.enabled:
        with span("recovery.spill", cat="resilience", site=site):
            freed = mgr.reclaim()
        if freed > 0:
            summary.steps.append(f"spill[{freed}]")
            instant("recovery.spill", cat="resilience", site=site,
                    freed=freed)
            _live.rung("spill", site=site)
            try:
                return fn()
            except Exception as exc:
                if classify(exc) not in (CATEGORY_OOM, CATEGORY_COMPILE):
                    raise
        else:
            summary.steps.append("spill-unavailable")
    err = ExecutionRecoveryError(site, summary)
    # The ladder is out of rungs: capture the postmortem HERE, while the
    # ring still holds the events leading up to the original OOM.  The
    # caller may still attempt the split rung; a later bundle for the
    # same (query, reason) is deduplicated, and a successful split just
    # leaves this bundle as the record of a near-miss.
    from ..obs import bundle as _bundle
    from ..obs.timeline import current_query_id
    _bundle.dump("recovery_exhausted", query_id=current_query_id(),
                 error=original, recovery=summary)
    raise err from original
