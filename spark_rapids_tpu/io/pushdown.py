"""Leaf-predicate pushdown and statistics pruning for parquet scans.

The scan path decodes every row group into fully materialized columns
before a single predicate runs, even though parquet footers and page
headers already carry min/max/null-count statistics.  This module is the
shared vocabulary between the plan layer and the native reader:

* :class:`LeafPred` — a single column-vs-literal predicate in a small
  closed op set, extractable from a plan's leading filter conjunction or
  from the pandas-style ``filters=[(col, op, val), ...]`` tuples.
* :class:`ColumnStats` — decoded min/max/null-count bounds for one
  chunk or page (parquet_native decodes the physical bytes; this module
  only compares).
* :func:`may_match` — the conservative three-valued pruning test: False
  means *no row in this unit can satisfy the predicate* (safe to skip);
  True means "must read".  Missing or unusable statistics always answer
  True — pruning can never change results, only skip work, because the
  full predicate re-runs downstream over whatever was read.

Pruning soundness leans on one invariant: pushdown never *removes* the
plan's filter step.  Row-group pruning drops whole rows consistently
across all columns (trivially safe); page pruning replaces a pruned
page's rows with nulls (see parquet_native._walk_pages), which is safe
only because every op here except ``is_null`` is null-rejecting — a
placeholder null can never flip a downstream predicate to true.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable, Optional, Sequence

#: The closed op vocabulary.  ``isin`` carries a tuple of literals;
#: ``is_null`` / ``is_valid`` carry no value.
PRED_OPS = frozenset({"eq", "ne", "lt", "le", "gt", "ge",
                      "isin", "is_null", "is_valid"})

#: Ops for which a null operand row evaluates to null/false — i.e. a row
#: forced to null by a pruned page can never newly satisfy the predicate.
#: Page-level pruning is restricted to these (everything but ``is_null``).
NULL_REJECTING_OPS = PRED_OPS - {"is_null"}

#: pandas/pyarrow-style filter-tuple op spellings → LeafPred ops.
TUPLE_OPS = {"=": "eq", "==": "eq", "!=": "ne", "<": "lt", "<=": "le",
             ">": "gt", ">=": "ge", "in": "isin"}


@dataclass(frozen=True)
class LeafPred:
    """One pushdown-eligible predicate: ``column <op> value``."""
    column: str
    op: str
    value: Any = None

    def __post_init__(self):
        if self.op not in PRED_OPS:
            raise ValueError(f"unknown pushdown op {self.op!r} "
                             f"(expected one of {sorted(PRED_OPS)})")


@dataclass(frozen=True)
class ColumnStats:
    """Decoded statistics for one column chunk or data page.

    ``min``/``max`` are python comparables in the column's logical
    domain (int/float/bool, or raw utf-8 ``bytes`` for strings — UTF-8
    byte order equals code-point order, so byte comparison is correct
    for string predicates).  Any field may be None (writer omitted it);
    ``num_values`` counts rows INCLUDING nulls when known.
    """
    min: Any = None
    max: Any = None
    null_count: Optional[int] = None
    num_values: Optional[int] = None


def _usable_bound(b) -> bool:
    if b is None:
        return False
    if isinstance(b, float) and b != b:        # NaN bound: unordered, unusable
        return False
    return True


def _coerce_literal(value, bound):
    """Make a predicate literal comparable to a stats bound, or None if
    the domains don't line up (→ caller must answer "read")."""
    if isinstance(bound, bytes):
        if isinstance(value, str):
            return value.encode("utf-8")
        return value if isinstance(value, bytes) else None
    if isinstance(value, (str, bytes)):
        return None
    if isinstance(value, float) and value != value:   # NaN literal never prunes
        return None
    return value


def may_match(pred: LeafPred, stats: Optional[ColumnStats]) -> bool:
    """Conservative test: can ANY row described by ``stats`` satisfy
    ``pred``?  False is a proof (skip is safe); True means read."""
    if stats is None:
        return True
    all_null = (stats.null_count is not None
                and stats.num_values is not None
                and stats.num_values > 0
                and stats.null_count >= stats.num_values)
    if pred.op == "is_null":
        return stats.null_count != 0           # None (unknown) → True
    if pred.op == "is_valid":
        return not all_null
    if all_null:
        return False                           # null rows fail every cmp/isin
    lo, hi = stats.min, stats.max
    if not (_usable_bound(lo) and _usable_bound(hi)):
        return True
    if pred.op == "isin":
        vals = [_coerce_literal(v, lo) for v in pred.value]
        if any(v is None for v in vals):
            return True
        try:
            return any(lo <= v <= hi for v in vals)
        except TypeError:
            return True
    v = _coerce_literal(pred.value, lo)
    if v is None:
        return True
    try:
        if pred.op == "eq":
            return lo <= v <= hi
        if pred.op == "ne":
            return not (lo == hi == v)
        if pred.op == "lt":
            return lo < v
        if pred.op == "le":
            return lo <= v
        if pred.op == "gt":
            return hi > v
        if pred.op == "ge":
            return hi >= v
    except TypeError:
        return True
    return True


def group_may_match(stats_by_column, preds: Sequence[LeafPred]) -> bool:
    """AND over a conjunction: False iff some predicate's column has
    statistics proving no row in the unit can match."""
    for p in preds:
        if not may_match(p, stats_by_column.get(p.column)):
            return False
    return True


# -- extraction -----------------------------------------------------------

def _split_conjuncts(expr):
    from ..exec.expr import BinOp
    if isinstance(expr, BinOp) and expr.op == "and_kleene":
        yield from _split_conjuncts(expr.left)
        yield from _split_conjuncts(expr.right)
    else:
        yield expr


def split_conjuncts(expr) -> tuple:
    """Top-level Kleene-AND conjuncts of an expression, left to right.

    The plan optimizer's filter reordering works over this list; Kleene
    AND of the per-conjunct keep-masks is order- and associativity-
    invariant, so any reassembly of the same conjuncts is
    bit-identical."""
    return tuple(_split_conjuncts(expr))


def _leaf_from_expr(expr) -> Optional[LeafPred]:
    from ..exec.expr import FLIP_CMP, BinOp, Col, IsIn, Lit, UnOp
    if isinstance(expr, BinOp) and expr.op in FLIP_CMP:
        if isinstance(expr.left, Col) and isinstance(expr.right, Lit):
            return LeafPred(expr.left.name, expr.op, expr.right.value)
        if isinstance(expr.left, Lit) and isinstance(expr.right, Col):
            return LeafPred(expr.right.name, FLIP_CMP[expr.op],
                            expr.left.value)
        return None
    if isinstance(expr, IsIn) and isinstance(expr.operand, Col):
        if all(isinstance(v, (bool, int, float, str, bytes))
               for v in expr.values):
            return LeafPred(expr.operand.name, "isin", tuple(expr.values))
        return None
    if isinstance(expr, UnOp) and expr.op in ("is_null", "is_valid") \
            and isinstance(expr.operand, Col):
        return LeafPred(expr.operand.name, expr.op)
    return None


def extract_scan_predicates(obj) -> tuple[LeafPred, ...]:
    """Extract the pushdown-eligible leaves of a filter.

    Accepts an :class:`~..exec.expr.Expr` (split on top-level Kleene
    AND; non-extractable conjuncts are simply ignored — they still run
    downstream), an iterable of ``(col, op, val)`` filter tuples
    (pandas/pyarrow spelling; unknown ops raise), an iterable of
    :class:`LeafPred`, or None.  The result is a conjunction: a scan
    unit is skipped only when some ONE leaf proves it empty.
    """
    if obj is None:
        return ()
    from ..exec.expr import Expr
    if isinstance(obj, LeafPred):
        return (obj,)
    if isinstance(obj, Expr):
        leaves = (_leaf_from_expr(c) for c in _split_conjuncts(obj))
        return tuple(p for p in leaves if p is not None)
    preds: list[LeafPred] = []
    for item in obj:
        if isinstance(item, LeafPred):
            preds.append(item)
            continue
        column, op, value = item
        if op not in TUPLE_OPS:
            raise ValueError(
                f"unsupported filter op {op!r} for column {column!r} "
                f"(native filters support {sorted(TUPLE_OPS)})")
        mapped = TUPLE_OPS[op]
        if mapped == "isin":
            if isinstance(value, (str, bytes)) or not isinstance(
                    value, Iterable):
                raise ValueError(
                    f"'in' filter on {column!r} needs a list of values")
            value = tuple(value)
        preds.append(LeafPred(column, mapped, value))
    return tuple(preds)


def predicates_for_column(preds: Sequence[LeafPred],
                          column: str) -> tuple[LeafPred, ...]:
    """The subset of a conjunction that constrains one column."""
    return tuple(p for p in preds if p.column == column)
