"""Datetime component extraction — cuDF ``datetime`` ops equivalent.

The engine stores timestamps as integer counts since the Unix epoch in the
unit carried by the dtype (TIMESTAMP_DAYS/SECONDS/MILLISECONDS/MICROSECONDS/
NANOSECONDS — :mod:`spark_rapids_tpu.dtypes`), matching both Arrow and the
cudf type ids the reference's JNI layer reconstructs
(reference: src/main/cpp/src/RowConversionJni.cpp:56-61).

Extraction is pure integer arithmetic (no calendars, no host loops): the
days→civil conversion is the standard era-based algorithm expressed in
vector ops, exact over the full int range, negatives included (floor
division semantics).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..column import Column
from ..dtypes import INT16, INT32, TypeId
from ..table import Table  # noqa: F401  (re-exported convenience typing)

#: ticks per day for each timestamp unit
_PER_DAY = {
    TypeId.TIMESTAMP_DAYS: 1,
    TypeId.TIMESTAMP_SECONDS: 86_400,
    TypeId.TIMESTAMP_MILLISECONDS: 86_400_000,
    TypeId.TIMESTAMP_MICROSECONDS: 86_400_000_000,
    TypeId.TIMESTAMP_NANOSECONDS: 86_400_000_000_000,
}

#: ticks per second (None for DAYS: no intra-day component)
_PER_SECOND = {
    TypeId.TIMESTAMP_SECONDS: 1,
    TypeId.TIMESTAMP_MILLISECONDS: 1_000,
    TypeId.TIMESTAMP_MICROSECONDS: 1_000_000,
    TypeId.TIMESTAMP_NANOSECONDS: 1_000_000_000,
}

FIELDS = ("year", "month", "day", "weekday", "day_of_year",
          "hour", "minute", "second", "millisecond", "microsecond",
          "nanosecond")


def _require_timestamp(col: Column):
    if col.dtype.type_id not in _PER_DAY:
        raise TypeError(f"expected a timestamp column, got {col.dtype!r}")


def _days_and_ticks(col: Column):
    """(days since epoch, intra-day ticks, ticks/second) — floor semantics
    so pre-epoch instants land on the correct civil day."""
    tid = col.dtype.type_id
    per_day = _PER_DAY[tid]
    data = col.data
    if per_day == 1:
        return data.astype(jnp.int32), None, None
    days = jnp.floor_divide(data, per_day)
    ticks = data - days * per_day
    return days.astype(jnp.int32), ticks, _PER_SECOND[tid]


def _civil_from_days(days):
    """days since 1970-01-01 → (year, month, day), era-based, vectorized."""
    z = days.astype(jnp.int64) + 719_468
    era = jnp.floor_divide(z, 146_097)
    doe = z - era * 146_097                                  # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36_524 - doe // 146_096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)          # [0, 365]
    mp = (5 * doy + 2) // 153                                # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                        # [1, 31]
    m = mp + 3 - 12 * (mp // 10)                             # [1, 12]
    y = y + (m <= 2)
    return y.astype(jnp.int16), m.astype(jnp.int16), d.astype(jnp.int16)


def extract(col: Column, field: str) -> Column:
    """Extract one civil/time field (cuDF ``extract_datetime_component``).

    ``weekday`` is ISO: Monday=1 … Sunday=7.  Sub-second fields report the
    value within the next-larger unit (cudf semantics): ``millisecond`` in
    [0, 999], ``microsecond`` in [0, 999], ``nanosecond`` in [0, 999].
    """
    _require_timestamp(col)
    if field not in FIELDS:
        raise ValueError(f"field must be one of {FIELDS}, got {field!r}")
    days, ticks, per_second = _days_and_ticks(col)

    if field in ("year", "month", "day", "weekday", "day_of_year"):
        if field == "weekday":
            # 1970-01-01 was a Thursday (ISO 4).
            out = ((days.astype(jnp.int64) + 3) % 7 + 1).astype(jnp.int16)
        elif field == "day_of_year":
            y, m, d = _civil_from_days(days)
            jan1 = _days_from_civil(y.astype(jnp.int64), 1, 1)
            out = (days.astype(jnp.int64) - jan1 + 1).astype(jnp.int16)
        else:
            y, m, d = _civil_from_days(days)
            out = {"year": y, "month": m, "day": d}[field]
        return Column(data=out, validity=col.validity, dtype=INT16)

    if ticks is None:
        raise TypeError(f"{field!r} undefined for TIMESTAMP_DAYS")
    tid = col.dtype.type_id
    second_of_day = ticks // per_second
    if field == "hour":
        out = (second_of_day // 3600).astype(jnp.int16)
        return Column(data=out, validity=col.validity, dtype=INT16)
    if field == "minute":
        out = (second_of_day // 60 % 60).astype(jnp.int16)
        return Column(data=out, validity=col.validity, dtype=INT16)
    if field == "second":
        out = (second_of_day % 60).astype(jnp.int16)
        return Column(data=out, validity=col.validity, dtype=INT16)
    sub = ticks % per_second          # ticks within the current second
    scale = {TypeId.TIMESTAMP_SECONDS: 1,
             TypeId.TIMESTAMP_MILLISECONDS: 1,
             TypeId.TIMESTAMP_MICROSECONDS: 1_000,
             TypeId.TIMESTAMP_NANOSECONDS: 1_000_000}[tid]
    if field == "millisecond":
        out = (sub // scale) if tid != TypeId.TIMESTAMP_SECONDS \
            else jnp.zeros_like(sub)
    elif field == "microsecond":
        if tid in (TypeId.TIMESTAMP_SECONDS, TypeId.TIMESTAMP_MILLISECONDS):
            out = jnp.zeros_like(sub)
        else:
            out = sub // (scale // 1_000) % 1_000
    else:                             # nanosecond
        out = (sub % 1_000) if tid == TypeId.TIMESTAMP_NANOSECONDS \
            else jnp.zeros_like(sub)
    if field == "millisecond":
        out = out % 1_000
    return Column(data=out.astype(jnp.int32), validity=col.validity,
                  dtype=INT32)


def _days_from_civil(y, m, d):
    """(year, month, day) → days since 1970-01-01 (inverse of
    :func:`_civil_from_days`)."""
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = (m + 9) % 12
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146_097 + doe - 719_468


def year(col: Column) -> Column:
    return extract(col, "year")


def month(col: Column) -> Column:
    return extract(col, "month")


def day(col: Column) -> Column:
    return extract(col, "day")


def weekday(col: Column) -> Column:
    return extract(col, "weekday")


def hour(col: Column) -> Column:
    return extract(col, "hour")


def minute(col: Column) -> Column:
    return extract(col, "minute")


def second(col: Column) -> Column:
    return extract(col, "second")
