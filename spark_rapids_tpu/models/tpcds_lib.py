"""Shared helpers for the TPC-DS query-bank family modules.

Lives below :mod:`.tpcds_queries` and the per-family modules so the
registry merge at the bottom of ``tpcds_queries`` stays acyclic whichever
module is imported first.
"""

from __future__ import annotations

import numpy as np

from ..column import Column
from ..dtypes import STRING
from ..table import Table
from ..exec import plan
from .tpcds import BRANDS, CATEGORIES, CITIES, CLASSES, STATES


def _dim(table: Table, pred=None, select=None) -> Table:
    """Pre-filter + narrow a dimension table (predicate pushdown below
    the join, as Spark's optimizer does)."""
    p = plan()
    if pred is not None:
        p = p.filter(pred)
    if select is not None:
        p = p.select(*select)
    if not p.steps:
        return table
    return p.run(table)


_MAPS: dict = {}


def _vocab_map(id_name: str, name_name: str, vocab) -> Table:
    """A unique-key (id, name) decode table for a vocabulary, memoized by
    (names, vocab) so repeated queries rebind the same Table object (the
    plan compile cache is keyed on build-table identity)."""
    key = (id_name, name_name, tuple(vocab))
    hit = _MAPS.get(key)
    if hit is None:
        hit = Table([
            (id_name, Column.from_numpy(
                np.arange(1, len(vocab) + 1, dtype=np.int64))),
            (name_name, Column.from_pylist(list(vocab), STRING)),
        ])
        _MAPS[key] = hit
    return hit


def _brand_map() -> Table:
    return _vocab_map("__brand_id", "i_brand", BRANDS)


def _category_map() -> Table:
    return _vocab_map("__category_id", "i_category", CATEGORIES)


def _class_map() -> Table:
    return _vocab_map("__class_id", "i_class", CLASSES)


def _city_map() -> Table:
    return _vocab_map("__city_id", "city", CITIES)


def _state_map() -> Table:
    return _vocab_map("__state_id", "state", STATES)


def _lag_buckets(p, lag):
    """Annotate a plan with the five 30-day lag indicator columns of the
    q62/q99/q50 report shapes (0/1 ints that a group-by sums)."""
    from ..exec import when
    return p.with_columns(
        d30=when(lag <= 30, 1).otherwise(0),
        d60=when((lag > 30) & (lag <= 60), 1).otherwise(0),
        d90=when((lag > 60) & (lag <= 90), 1).otherwise(0),
        d120=when((lag > 90) & (lag <= 120), 1).otherwise(0),
        dmore=when(lag > 120, 1).otherwise(0))


def _scalar_table(**vals) -> Table:
    cols = []
    for k, v in vals.items():
        arr = np.asarray([v])
        if arr.dtype.kind == "i":
            arr = arr.astype(np.int64)
        cols.append((k, Column.from_numpy(arr)))
    return Table(cols)
