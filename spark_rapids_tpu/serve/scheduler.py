"""Run-queue scheduler: many independent plans served concurrently over
one device/mesh.

:class:`QuerySession` owns a pool of ``SRT_SERVE_MAX_CONCURRENT``
worker threads; :meth:`~QuerySession.submit` enqueues a plan with its
input (a Table for one-shot execution, a batch list/iterator for the
streaming executors, a DistTable+mesh for sharded execution) and hands
back a :class:`Ticket` future.  Workers pop tickets FIFO, pass HBM
admission (serve/admission.py), and run the ordinary executors — the
only serving-specific hook in the execution path is the streaming
drivers' ``on_dispatch`` callback, which blocks at the session's
fairness gate so per-batch dispatches from concurrent queries
interleave into the device's in-flight windows (round-robin by default,
weighted-fair under ``SRT_SERVE_POLICY=wfair``).  The gate reorders
only WHICH query dispatches next, never what a query dispatches, so
every result is bit-identical to running the same plans sequentially —
including when the recovery ladder is mid-rescue on a neighboring
query.

Cross-query state the session layers on top of the executors:

* the result cache (serve/result_cache.py): repeated fingerprints over
  identical inputs short-circuit at submit;
* the admission controller's HBM budget, fed by cost-ledger history;
* the queued-queries pane: the session registers a provider with
  obs/live.py so ``/queries``, ``/metrics`` and ``obs top`` show the
  run queue next to the in-flight registry;
* the always-present ``serve`` block of QueryMetrics, populated through
  a thread-local serve context (obs/query.py) set around each worker's
  executor call.

jax-free at module load; executors import lazily inside workers.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from collections import deque
from typing import Any, Iterable, List, Optional

from .admission import AdmissionController, AdmissionRejected
from .result_cache import ResultCache, input_digest

_SUBMISSION_IDS = itertools.count(1)
_AUTO = object()        # "resolve from config" sentinel (None means OFF)


class Ticket:
    """One submission's future: resolves to the executor's result (a
    Table, or a list of Tables for streaming modes)."""

    __slots__ = ("id", "fingerprint", "mode", "weight", "status",
                 "submitted_unix", "queue_wait_seconds", "run_seconds",
                 "admission", "result_cache", "estimate", "metrics",
                 "_t_submit", "_event", "_result", "_error", "_thunk",
                 "_cache_key", "_session", "_finalizer", "__weakref__")

    def __init__(self, sub_id: int, fingerprint: str, mode: str,
                 weight: float):
        self.id = sub_id
        self.fingerprint = fingerprint
        self.mode = mode
        self.weight = weight
        self.status = "queued"
        self.submitted_unix = time.time()
        self.queue_wait_seconds = 0.0
        self.run_seconds = 0.0
        self.admission = "queued"
        self.result_cache = ""
        self.estimate = 0
        self.metrics = None
        self._t_submit = time.perf_counter()
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self._thunk = None
        self._cache_key = None
        self._session = None        # weakref.ref set by submit
        self._finalizer = None      # claim-release guard set at acquire

    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> bool:
        """Withdraw a still-queued submission: the ticket resolves to a
        cancellation error, its admission claim (if any) is freed, and
        the worker pool never sees it.  Returns False when the query
        already started running (or finished) — a running executor is
        not interruptible."""
        session = self._session() if self._session is not None else None
        if session is None or self.done():
            return False
        return session._cancel_ticket(self)

    def result(self, timeout: Optional[float] = None) -> Any:
        """Block until the query finishes; re-raises its error (an
        :class:`AdmissionRejected` for rejected submissions)."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"query {self.id} still {self.status} after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    def snapshot(self) -> dict:
        """JSON-safe entry for the queued-queries pane."""
        return {
            "query_id": self.id,
            "fingerprint": self.fingerprint,
            "mode": self.mode,
            "status": self.status,
            "weight": self.weight,
            "estimate_hbm_bytes": self.estimate,
            "queued_seconds": round(
                max(time.perf_counter() - self._t_submit, 0.0), 3),
        }


class _FairGate:
    """The per-batch dispatch turnstile.  ``turn(tid)`` blocks only
    while OTHER queries are simultaneously waiting; among waiters the
    policy picks who goes next (``rr``: least recently served;
    ``wfair``: least credits spent per unit weight).  A lone waiter
    always proceeds, so the gate can never deadlock a stream."""

    def __init__(self, policy: str):
        self.policy = policy
        self._cond = threading.Condition()
        self._waiting: dict = {}        # tid -> arrival seq
        self._last_served: dict = {}    # tid -> service seq
        self._credits: dict = {}        # tid -> credits spent
        self._weights: dict = {}        # tid -> weight
        self._seq = 0

    def register(self, tid: int, weight: float) -> None:
        with self._cond:
            self._weights[tid] = max(float(weight), 1e-9)
            self._credits.setdefault(tid, 0.0)

    def unregister(self, tid: int) -> None:
        with self._cond:
            self._waiting.pop(tid, None)
            self._last_served.pop(tid, None)
            self._credits.pop(tid, None)
            self._weights.pop(tid, None)
            self._cond.notify_all()

    def _chosen(self):
        if not self._waiting:
            return None
        if self.policy == "wfair":
            return min(self._waiting,
                       key=lambda tid: (self._credits.get(tid, 0.0), tid))
        return min(self._waiting,
                   key=lambda tid: (self._last_served.get(tid, -1), tid))

    def turn(self, tid: int) -> None:
        with self._cond:
            self._seq += 1
            self._waiting[tid] = self._seq
            self._cond.notify_all()     # arrival may change the choice
            while self._chosen() != tid:
                self._cond.wait(0.05)
            del self._waiting[tid]
            self._seq += 1
            self._last_served[tid] = self._seq
            self._credits[tid] = (self._credits.get(tid, 0.0)
                                  + 1.0 / self._weights.get(tid, 1.0))
            self._cond.notify_all()


def _is_table(obj: Any) -> bool:
    return hasattr(obj, "items") and hasattr(obj, "num_rows")


class QuerySession:
    """A serving session: worker pool + admission + fairness gate +
    result cache.  One session per process is the normal shape
    (:func:`default_session`); independent sessions only share the
    process-global compile caches."""

    def __init__(self, max_concurrent: Optional[int] = None,
                 hbm_budget: Any = _AUTO, policy: Optional[str] = None,
                 result_cache_cap: Any = _AUTO,
                 register_queued: bool = True):
        from ..config import (result_cache_bytes, serve_hbm_budget,
                              serve_max_concurrent, serve_policy)
        self.max_concurrent = (serve_max_concurrent()
                               if max_concurrent is None
                               else int(max_concurrent))
        if self.max_concurrent < 1:
            raise ValueError(
                f"max_concurrent must be >= 1, got {self.max_concurrent}")
        self.policy = serve_policy() if policy is None else str(policy)
        if self.policy not in ("rr", "wfair"):
            raise ValueError(
                f"policy must be 'rr' or 'wfair', got {self.policy!r}")
        self.admission = AdmissionController(
            serve_hbm_budget() if hbm_budget is _AUTO else hbm_budget)
        self.cache = ResultCache(
            result_cache_bytes() if result_cache_cap is _AUTO
            else result_cache_cap)
        self._gate = _FairGate(self.policy)
        self._cond = threading.Condition()
        self._queue: "deque[Ticket]" = deque()
        self._workers: List[threading.Thread] = []
        self._running = 0
        self._closed = False
        if register_queued:
            from ..obs import live as _live
            _live.set_queued_provider(self.queued)

    # -- submission ------------------------------------------------------

    def submit(self, plan, batches: Optional[Iterable] = None, *,
               table=None, dist=None, mesh=None, combine="auto",
               inflight: Optional[int] = None,
               weight: float = 1.0) -> Ticket:
        """Enqueue one query; returns its :class:`Ticket` immediately.

        Exactly one input shape applies:

        * ``table=Table`` — one-shot ``run_plan`` (with ``mesh`` +
          ``dist=DistTable``: ``run_plan_dist``);
        * ``batches=`` list/iterator of Tables — the streaming executor
          (``run_plan_stream``; sharded when ``mesh`` is given), result
          is the list of yielded Tables;

        ``weight`` feeds the ``wfair`` policy (higher = more dispatch
        turns).  Repeated fingerprints over identical (re-hashable)
        inputs resolve from the result cache without touching the
        device."""
        if (table is None) == (batches is None) and dist is None:
            raise ValueError(
                "submit needs exactly one of table=, batches=, or "
                "dist=+mesh=")
        if dist is not None and mesh is None:
            raise ValueError("dist= needs mesh=")
        if not (isinstance(weight, (int, float)) and weight > 0):
            raise ValueError(f"weight must be > 0, got {weight!r}")
        with self._cond:
            if self._closed:
                raise RuntimeError("session is closed")
        from ..obs.history import plan_fingerprint
        from ..obs.metrics import counter, gauge
        fingerprint = plan_fingerprint(plan)
        # Workload intelligence: a submitted ticket's subplan prefixes
        # are in-flight recurrence evidence for the overlap miner (one
        # env read when metrics are off).
        from ..obs import workload as _workload
        _workload.feed_ticket(fingerprint, plan)
        if dist is not None:
            mode = "dist"
        elif table is not None:
            mode = "run"
        else:
            mode = "dist_stream" if mesh is not None else "stream"
        t = Ticket(next(_SUBMISSION_IDS), fingerprint, mode, float(weight))
        t._session = weakref.ref(self)
        counter("serve.submitted").inc()

        # Result cache: only identity-checkable inputs participate.
        if self.cache.enabled and dist is None:
            digest = input_digest(table if table is not None else batches)
            if digest is not None:
                t._cache_key = (fingerprint, mode, combine, digest)
                cached, hit = self.cache.get(t._cache_key)
                if hit:
                    t.result_cache = "hit"
                    t.admission = "admitted"
                    t.status = "done"
                    t._result = cached
                    t._event.set()
                    counter("serve.completed").inc()
                    return t
                t.result_cache = "miss"

        # Admission pre-check: an estimate that can never fit rejects
        # now, with the error delivered through the ticket.
        t.estimate = self.admission.estimate(fingerprint)
        try:
            self.admission.check(t.estimate)
        except AdmissionRejected as err:
            t.admission = "rejected"
            t.status = "rejected"
            t._error = err
            t._event.set()
            from ..obs import bundle as _bundle
            _bundle.dump("admission_rejected", fingerprint=fingerprint,
                         mode=mode, error=err, plan=plan)
            return t

        t._thunk = self._make_thunk(plan, table, batches, dist, mesh,
                                    combine, inflight)
        with self._cond:
            # Admitted straight through when a worker is free AND
            # nothing is queued ahead; otherwise the ticket waited.
            t.admission = ("admitted"
                           if (self._running < self.max_concurrent
                               and not self._queue) else "queued")
            if t.admission == "queued":
                counter("serve.queued").inc()
            self._queue.append(t)
            gauge("serve.queue_depth").set(len(self._queue))
            from ..obs import capacity as _capacity
            _capacity.feed_queue_depth(len(self._queue))
            self._spawn_locked()
            self._cond.notify()
        return t

    def _make_thunk(self, plan, table, batches, dist, mesh, combine,
                    inflight):
        if dist is not None:
            def thunk(gate):
                from ..exec.dist import run_plan_dist
                return run_plan_dist(plan, dist, mesh)
        elif table is not None:
            def thunk(gate):
                # Cross-ticket prefix CSE (SRT_SEMANTIC_CACHE); a plain
                # run_plan pass-through when the cache is off.
                from .semantic import run_table_plan
                return run_table_plan(plan, table,
                                      admission=self.admission)
        else:
            def thunk(gate):
                from ..exec.stream import run_plan_stream
                return list(run_plan_stream(
                    plan, batches, inflight=inflight, combine=combine,
                    mesh=mesh, on_dispatch=gate))
        return thunk

    # -- worker pool -----------------------------------------------------

    def _spawn_locked(self) -> None:
        want = min(self.max_concurrent,
                   len(self._queue) + self._running)
        while len(self._workers) < want:
            w = threading.Thread(target=self._worker, daemon=True,
                                 name=f"srt-serve-{len(self._workers)}")
            self._workers.append(w)
            w.start()

    def _worker(self) -> None:
        from ..obs.metrics import gauge
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(0.1)
                if not self._queue:
                    return          # closed and drained
                t = self._queue.popleft()
                gauge("serve.queue_depth").set(len(self._queue))
                from ..obs import capacity as _capacity
                _capacity.feed_queue_depth(len(self._queue))
                self._running += 1
                gauge("serve.running").set(self._running)
            try:
                self._run_ticket(t)
            finally:
                with self._cond:
                    self._running -= 1
                    gauge("serve.running").set(self._running)
                    self._cond.notify_all()

    def _run_ticket(self, t: Ticket) -> None:
        from ..obs import query as _oq
        from ..obs.metrics import counter, timer
        t.queue_wait_seconds = max(
            time.perf_counter() - t._t_submit, 0.0)
        timer("serve.queue_wait").observe(t.queue_wait_seconds)
        from ..obs import server as _server
        _server.observe_hist("serve_queue_wait_seconds",
                             t.queue_wait_seconds)
        from ..obs import capacity as _capacity
        _capacity.feed_queue_wait(t.queue_wait_seconds)
        counter("serve.admitted").inc()
        t.status = "running"
        gate = None
        if t.mode in ("stream", "dist_stream"):
            self._gate.register(t.id, t.weight)
            gate = lambda: self._gate.turn(t.id)  # noqa: E731
        info = {"queue_wait_seconds": t.queue_wait_seconds,
                "admission": t.admission,
                "result_cache": t.result_cache,
                "policy": self.policy}
        # The HBM claim: blocks this worker until running claims fit.
        if self.admission.acquire(t.id, t.estimate):
            t.admission = info["admission"] = "queued"
        # Ledger-leak guard: if the caller abandons the ticket (never
        # re-joins ``result(timeout=)``) and it becomes garbage before a
        # release ran, GC frees the claim.  ``release`` is idempotent,
        # so the normal finally-path release below makes this a no-op.
        t._finalizer = weakref.finalize(t, self.admission.release, t.id)
        _oq.set_serve_context(info)
        t0 = time.perf_counter()
        try:
            result = t._thunk(gate)
        except BaseException as err:
            t._error = err
            t.status = "error"
            counter("serve.errors").inc()
            # The executor-side hook usually dumped already (dedup by
            # query id); this catches failures that never reached a
            # metered region (e.g. optimizer/bind errors).
            from ..obs import bundle as _bundle
            _bundle.dump("failure", qm=info.get("qm"),
                         fingerprint=t.fingerprint, mode=t.mode,
                         error=err)
        else:
            t._result = result
            t.status = "done"
            self.cache.put(t._cache_key, result)
        finally:
            _oq.set_serve_context(None)
            if gate is not None:
                self._gate.unregister(t.id)
            self.admission.release(t.id)
            if t._finalizer is not None:
                t._finalizer.detach()
            t.run_seconds = time.perf_counter() - t0
            timer("serve.run").observe(t.run_seconds)
            t.metrics = info.get("qm")
            counter("serve.completed").inc()
            t._event.set()

    def _cancel_ticket(self, t: Ticket) -> bool:
        from ..obs.metrics import counter, gauge
        with self._cond:
            try:
                self._queue.remove(t)
            except ValueError:
                return False        # a worker already claimed it
            gauge("serve.queue_depth").set(len(self._queue))
            from ..obs import capacity as _capacity
            _capacity.feed_queue_depth(len(self._queue))
        self.admission.release(t.id)
        t.status = "cancelled"
        t._error = RuntimeError(f"query {t.id} cancelled")
        counter("serve.cancelled").inc()
        t._event.set()
        return True

    # -- introspection / lifecycle ---------------------------------------

    def queued(self) -> List[dict]:
        """Queued-ticket snapshots (the obs/live.py provider)."""
        with self._cond:
            return [t.snapshot() for t in self._queue]

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until the queue is empty and no ticket is running."""
        deadline = (None if timeout is None
                    else time.perf_counter() + timeout)
        with self._cond:
            while self._queue or self._running:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"{len(self._queue)} queued / "
                            f"{self._running} running after {timeout}s")
                self._cond.wait(remaining if remaining is not None
                                else 0.1)

    def close(self, wait: bool = True) -> None:
        """Stop accepting submissions; with ``wait`` drain first."""
        if wait:
            self.drain()
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        from ..obs import live as _live
        if _live._QUEUED_PROVIDER == self.queued:
            _live.set_queued_provider(None)


_DEFAULT: Optional[QuerySession] = None
_DEFAULT_LOCK = threading.Lock()


def default_session() -> QuerySession:
    """The process-wide session :func:`submit` uses, created on first
    use from the ``SRT_SERVE_*`` knobs."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None or _DEFAULT._closed:
            _DEFAULT = QuerySession()
        return _DEFAULT


def submit(plan, batches: Optional[Iterable] = None, **kw) -> Ticket:
    """Module-level convenience: ``default_session().submit(...)``."""
    return default_session().submit(plan, batches, **kw)
