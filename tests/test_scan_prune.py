"""Statistics-driven scan pruning + encoded-execution contracts.

Five contracts:

1. **Conservative truth table** — ``may_match`` answers False only when
   statistics PROVE emptiness; missing, NaN, or domain-mismatched stats
   always answer "read".  Pruning can skip work, never change results.
2. **Extraction** — pushdown leaves come out of plan filter Exprs,
   pandas-style filter tuples, and ``Plan.scan_predicates`` (leading
   filters only); unknown tuple ops fail loudly; ``SRT_SCAN_PRUNE=0``
   kills extraction at the scan boundary.
3. **Bit-identity** — pruned reads equal the decode-everything oracle
   after the full predicate re-runs: row-group pruning end-to-end
   (sorted keys, min==max groups, all-null groups, NaN data, files
   written without statistics), page pruning via all-null placeholders
   (synthetic page stats — pyarrow omits page-header statistics).
4. **Encoded residency** — under ``SRT_ENCODED_EXEC=1`` the scan
   registers (codes, sorted vocab) for dictionary string columns;
   ``dictionary_encode_cached`` hits it (no host re-factorize), results
   match the decode-everything oracle, and residency survives feed
   coalescing.
5. **Feed integration** — ``scan_parquet(predicate=...)`` skips row
   groups and sizes its bucket coalesce target over the SURVIVING
   groups, not the raw file layout.
"""

import math

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import assert_tables_equal
from spark_rapids_tpu.exec import col, plan
from spark_rapids_tpu.io import read_parquet
from spark_rapids_tpu.io.pushdown import (ColumnStats, LeafPred,
                                          extract_scan_predicates,
                                          group_may_match, may_match,
                                          predicates_for_column)
from spark_rapids_tpu.obs import registry

pytestmark = pytest.mark.full


@pytest.fixture
def metrics_on(monkeypatch):
    monkeypatch.setenv("SRT_METRICS", "1")
    registry().reset()
    yield
    registry().reset()


@pytest.fixture
def encoded_on(monkeypatch):
    monkeypatch.setenv("SRT_ENCODED_EXEC", "1")


def _snap():
    return registry().counters_snapshot()


# ---------------------------------------------------------------------------
# 1. may_match truth table
# ---------------------------------------------------------------------------

CMP_OPS = ("eq", "ne", "lt", "le", "gt", "ge")


class TestMayMatch:
    def test_missing_stats_always_read(self):
        for op in CMP_OPS:
            assert may_match(LeafPred("x", op, 5), None)
        assert may_match(LeafPred("x", "isin", (1, 2)), None)
        assert may_match(LeafPred("x", "is_null"), None)
        assert may_match(LeafPred("x", "is_valid"), None)
        # stats object with nothing usable in it behaves the same
        empty = ColumnStats()
        for op in CMP_OPS:
            assert may_match(LeafPred("x", op, 5), empty)
        assert may_match(LeafPred("x", "is_null"), empty)
        assert may_match(LeafPred("x", "is_valid"), empty)

    def test_all_null_unit(self):
        s = ColumnStats(null_count=10, num_values=10)
        for op in CMP_OPS:
            assert not may_match(LeafPred("x", op, 5), s)
        assert not may_match(LeafPred("x", "isin", (1, 2)), s)
        assert may_match(LeafPred("x", "is_null"), s)
        assert not may_match(LeafPred("x", "is_valid"), s)
        # a single valid row flips everything back to "read"
        s2 = ColumnStats(min=3, max=3, null_count=9, num_values=10)
        assert may_match(LeafPred("x", "is_valid"), s2)
        assert may_match(LeafPred("x", "eq", 3), s2)

    def test_is_null_needs_zero_null_count(self):
        assert not may_match(LeafPred("x", "is_null"),
                             ColumnStats(min=1, max=2, null_count=0,
                                         num_values=5))
        assert may_match(LeafPred("x", "is_null"),
                         ColumnStats(min=1, max=2, null_count=None,
                                     num_values=5))

    def test_comparison_bounds(self):
        s = ColumnStats(min=10, max=20, null_count=0, num_values=5)
        assert not may_match(LeafPred("x", "eq", 9), s)
        assert may_match(LeafPred("x", "eq", 10), s)
        assert may_match(LeafPred("x", "eq", 20), s)
        assert not may_match(LeafPred("x", "eq", 21), s)
        assert not may_match(LeafPred("x", "lt", 10), s)
        assert may_match(LeafPred("x", "lt", 11), s)
        assert not may_match(LeafPred("x", "le", 9), s)
        assert may_match(LeafPred("x", "le", 10), s)
        assert not may_match(LeafPred("x", "gt", 20), s)
        assert may_match(LeafPred("x", "gt", 19), s)
        assert not may_match(LeafPred("x", "ge", 21), s)
        assert may_match(LeafPred("x", "ge", 20), s)

    def test_ne_prunes_only_constant_groups(self):
        const = ColumnStats(min=7, max=7, null_count=0, num_values=4)
        assert not may_match(LeafPred("x", "ne", 7), const)
        assert may_match(LeafPred("x", "ne", 8), const)
        spread = ColumnStats(min=1, max=9, null_count=0, num_values=4)
        assert may_match(LeafPred("x", "ne", 5), spread)

    def test_isin(self):
        s = ColumnStats(min=10, max=20, null_count=0, num_values=5)
        assert not may_match(LeafPred("x", "isin", (1, 2, 30)), s)
        assert may_match(LeafPred("x", "isin", (1, 15)), s)
        assert not may_match(LeafPred("x", "isin", ()), s)
        # one un-coercible literal poisons the whole list → read
        assert may_match(LeafPred("x", "isin", (1, "a")), s)

    def test_nan_bounds_and_literals_never_prune(self):
        nan = float("nan")
        s = ColumnStats(min=nan, max=nan, null_count=0, num_values=4)
        for op in CMP_OPS:
            assert may_match(LeafPred("x", op, 5.0), s)
        ok = ColumnStats(min=1.0, max=2.0, null_count=0, num_values=4)
        for op in CMP_OPS:
            assert may_match(LeafPred("x", op, nan), ok)
        assert may_match(LeafPred("x", "isin", (nan,)), ok)

    def test_string_bounds_coerce_utf8(self):
        s = ColumnStats(min=b"apple", max=b"mango", null_count=0,
                        num_values=3)
        assert may_match(LeafPred("s", "eq", "kiwi"), s)
        assert not may_match(LeafPred("s", "eq", "zebra"), s)
        assert not may_match(LeafPred("s", "eq", b"zebra"), s)
        assert not may_match(LeafPred("s", "lt", "apple"), s)
        assert may_match(LeafPred("s", "isin", ("zzz", "banana")), s)
        # numeric literal against byte bounds: domains don't line up → read
        assert may_match(LeafPred("s", "eq", 5), s)
        # and the reverse: string literal against numeric bounds
        n = ColumnStats(min=1, max=2, null_count=0, num_values=3)
        assert may_match(LeafPred("x", "eq", "a"), n)

    def test_group_conjunction(self):
        stats = {"a": ColumnStats(min=0, max=9, null_count=0, num_values=5),
                 "b": ColumnStats(min=0, max=9, null_count=0, num_values=5)}
        keep = (LeafPred("a", "gt", 3), LeafPred("b", "lt", 5))
        assert group_may_match(stats, keep)
        assert not group_may_match(stats, keep + (LeafPred("a", "gt", 9),))
        # predicate on a column with no stats (or not in the file) → read
        assert group_may_match(stats, (LeafPred("zzz", "eq", 1),))
        assert group_may_match({"a": None}, (LeafPred("a", "eq", 1),))

    def test_unknown_op_rejected_at_construction(self):
        with pytest.raises(ValueError, match="unknown pushdown op"):
            LeafPred("x", "like", "a%")


# ---------------------------------------------------------------------------
# 2. extraction
# ---------------------------------------------------------------------------

class TestExtraction:
    def test_none_and_leaves_pass_through(self):
        assert extract_scan_predicates(None) == ()
        p = LeafPred("x", "gt", 1)
        assert extract_scan_predicates(p) == (p,)
        assert extract_scan_predicates([p, LeafPred("y", "eq", 2)]) == \
            (p, LeafPred("y", "eq", 2))

    def test_expr_conjunction_splits(self):
        e = (col("a") > 3) & col("b").is_null() & col("a").isin([1, 2])
        got = extract_scan_predicates(e)
        assert got == (LeafPred("a", "gt", 3), LeafPred("b", "is_null"),
                       LeafPred("a", "isin", (1, 2)))

    def test_flipped_literal_comparison(self):
        from spark_rapids_tpu.exec.expr import BinOp, Col, Lit
        got = extract_scan_predicates(BinOp("gt", Lit(5), Col("x")))
        assert got == (LeafPred("x", "lt", 5),)

    def test_non_leaf_conjuncts_ignored_not_fatal(self):
        e = ((col("a") + 1) > 3) & (col("b") <= 7)
        assert extract_scan_predicates(e) == (LeafPred("b", "le", 7),)
        # a filter with NO extractable leaf extracts nothing
        assert extract_scan_predicates((col("a") * 2) > col("b")) == ()

    def test_filter_tuples(self):
        got = extract_scan_predicates(
            [("a", ">", 1), ("s", "in", ["x", "y"]), ("b", "=", 2)])
        assert got == (LeafPred("a", "gt", 1),
                       LeafPred("s", "isin", ("x", "y")),
                       LeafPred("b", "eq", 2))

    def test_bad_tuples_raise(self):
        with pytest.raises(ValueError, match="unsupported filter op"):
            extract_scan_predicates([("a", "~", 1)])
        with pytest.raises(ValueError, match="needs a list"):
            extract_scan_predicates([("a", "in", "xy")])

    def test_plan_scan_predicates_leading_filters_only(self):
        p = (plan()
             .filter(col("a") > 1)
             .filter(col("b").eq(2))
             .with_columns(d=col("a") * 2.0)
             .filter(col("d") < 9))
        assert p.scan_predicates() == (LeafPred("a", "gt", 1),
                                       LeafPred("b", "eq", 2))
        assert plan().with_columns(d=col("a")).scan_predicates() == ()

    def test_kill_switch_empties_scan_leaves(self, monkeypatch):
        from spark_rapids_tpu.io.parquet_native import scan_predicate_leaves
        assert scan_predicate_leaves([("a", ">", 1)]) == \
            (LeafPred("a", "gt", 1),)
        monkeypatch.setenv("SRT_SCAN_PRUNE", "0")
        assert scan_predicate_leaves([("a", ">", 1)]) == ()
        monkeypatch.setenv("SRT_SCAN_PRUNE", "1")
        assert len(scan_predicate_leaves([("a", ">", 1)])) == 1

    def test_predicates_for_column(self):
        preds = (LeafPred("a", "gt", 1), LeafPred("b", "eq", 2),
                 LeafPred("a", "lt", 9))
        assert predicates_for_column(preds, "a") == (preds[0], preds[2])
        assert predicates_for_column(preds, "zzz") == ()


# ---------------------------------------------------------------------------
# 3a. row-group pruning end to end
# ---------------------------------------------------------------------------

def _write_sorted(path, n=4000, group=1000, vocab=8, **write_kw):
    """Sorted int64 key + nullable float + dictionary strings, several
    row groups; the sorted key gives each group a disjoint [min, max]."""
    rng = np.random.default_rng(42)
    words = [f"w-{i:02d}" for i in range(vocab)]
    at = pa.table({
        "k": np.arange(n, dtype=np.int64),
        "v": pa.array(rng.normal(size=n), mask=rng.random(n) < 0.15),
        "s": pa.array([words[i % vocab] for i in range(n)]),
    })
    pq.write_table(at, path, row_group_size=group, **write_kw)
    return at


def _both_engines(path, filt):
    native = read_parquet(path, filters=filt, engine="native")
    arrow = read_parquet(path, filters=filt, engine="arrow")
    return native, arrow


class TestRowGroupPruning:
    def test_sorted_key_prunes_and_matches_oracle(self, tmp_path,
                                                  metrics_on):
        p = tmp_path / "sorted.parquet"
        _write_sorted(p)
        filt = [("k", ">", 3499)]          # only the last of 4 groups survives
        native, arrow = _both_engines(p, filt)
        assert_tables_equal(native, arrow)
        assert native.num_rows == 500
        snap = _snap()
        assert snap.get("scan.row_groups_skipped", 0) == 3
        assert snap.get("scan.bytes_skipped", 0) > 0
        # moved bytes exclude the skipped groups' chunks entirely
        assert snap.get("io.parquet.row_groups", 0) == 1

    def test_kill_switch_is_the_oracle_path(self, tmp_path, metrics_on,
                                            monkeypatch):
        p = tmp_path / "killed.parquet"
        _write_sorted(p)
        monkeypatch.setenv("SRT_SCAN_PRUNE", "0")
        native, arrow = _both_engines(p, [("k", ">", 3499)])
        assert_tables_equal(native, arrow)
        snap = _snap()
        assert snap.get("scan.row_groups_skipped", 0) == 0
        assert snap.get("scan.bytes_skipped", 0) == 0
        assert snap.get("io.parquet.row_groups", 0) == 4

    def test_min_eq_max_groups_keep_exactly_one(self, tmp_path,
                                                metrics_on):
        # Constant key per row group: eq hits exactly one group, every
        # other group's min==max bound proves it empty.
        p = tmp_path / "const.parquet"
        n, group = 4000, 1000
        at = pa.table({
            "g": (np.arange(n) // group).astype(np.int64),
            "v": np.arange(n, dtype=np.float64),
        })
        pq.write_table(at, p, row_group_size=group)
        native, arrow = _both_engines(p, [("g", "==", 2)])
        assert_tables_equal(native, arrow)
        assert native.num_rows == group
        assert _snap().get("scan.row_groups_skipped", 0) == 3

    def test_all_null_groups_pruned_for_null_rejecting_pred(
            self, tmp_path, metrics_on):
        p = tmp_path / "allnull.parquet"
        n = 2000
        at = pa.table({
            "x": pa.array([None] * n, type=pa.int64()),
            "k": np.arange(n, dtype=np.int64),
        })
        pq.write_table(at, p, row_group_size=500)
        native, arrow = _both_engines(p, [("x", ">", 0)])
        assert_tables_equal(native, arrow)
        assert native.num_rows == 0
        assert list(native.names) == ["x", "k"]
        assert _snap().get("scan.row_groups_skipped", 0) == 4

    def test_no_statistics_reads_everything_correctly(self, tmp_path,
                                                      metrics_on):
        p = tmp_path / "nostats.parquet"
        _write_sorted(p, write_statistics=False)
        native, arrow = _both_engines(p, [("k", ">", 3499)])
        assert_tables_equal(native, arrow)
        assert native.num_rows == 500
        snap = _snap()
        assert snap.get("scan.row_groups_skipped", 0) == 0
        assert snap.get("scan.pages_skipped", 0) == 0

    def test_nan_data_never_wrong(self, tmp_path):
        p = tmp_path / "nan.parquet"
        n = 2000
        f = np.linspace(-1.0, 1.0, n)
        f[::7] = np.nan
        pq.write_table(pa.table({"f": f, "k": np.arange(n)}), p,
                       row_group_size=500)
        native, arrow = _both_engines(p, [("f", ">", 0.5)])
        assert_tables_equal(native, arrow)
        assert all(x is not None and x > 0.5 and not math.isnan(x)
                   for x in native["f"].to_pylist())

    def test_string_predicate_prunes_groups(self, tmp_path, metrics_on):
        # Sorted strings: byte-order bounds per group are disjoint.
        p = tmp_path / "str.parquet"
        n, group = 2000, 500
        at = pa.table({"s": pa.array([f"id-{i:06d}" for i in range(n)]),
                       "v": np.arange(n, dtype=np.float64)})
        pq.write_table(at, p, row_group_size=group)
        native, arrow = _both_engines(p, [("s", ">=", "id-001500")])
        assert_tables_equal(native, arrow)
        assert native.num_rows == 500
        assert _snap().get("scan.row_groups_skipped", 0) == 3


# ---------------------------------------------------------------------------
# 3b. page pruning (synthetic page statistics: pyarrow writes footer
# stats but omits page-header stats, so the page walk is driven with a
# patched _decode_stats and exercised chunk-by-chunk)
# ---------------------------------------------------------------------------

def _one_group_file(path, n=600, nullable=True, pages=True):
    arr = pa.array(list(range(n)), type=pa.int64(),
                   mask=np.zeros(n, bool) if nullable else None)
    fields = [pa.field("x", pa.int64(), nullable=nullable)]
    at = pa.table({"x": arr}).cast(pa.schema(fields))
    # data_page_size is only checked every write_batch_size values: a
    # small batch size forces real multi-page chunks at this row count.
    pq.write_table(at, path, row_group_size=n, use_dictionary=False,
                   data_page_size=512 if pages else None,
                   write_batch_size=64, compression="none")
    return at


def _chunk_blob(path, chunk):
    with open(path, "rb") as f:
        f.seek(chunk.start_offset)
        return f.read(chunk.total_compressed)


class TestPagePruning:
    def test_all_pages_pruned_become_all_null_rows(self, tmp_path,
                                                   metrics_on,
                                                   monkeypatch):
        from spark_rapids_tpu.io import parquet_native as pn
        p = tmp_path / "pages.parquet"
        n = 600
        _one_group_file(p, n=n)
        _, rgs = pn.read_metadata(p)          # footer decoded BEFORE patch
        chunk = rgs[0][0]
        blob = _chunk_blob(p, chunk)
        calls = []

        def fake_stats(sd, info, num_values, exact_nulls=None):
            calls.append(num_values)
            return ColumnStats(min=0, max=n - 1, null_count=0,
                               num_values=num_values)

        monkeypatch.setattr(pn, "_decode_stats", fake_stats)
        out = pn._materialize_piece(pn._decode_chunk(
            blob, chunk, (LeafPred("x", "gt", n * 10),)))
        assert len(calls) > 1                  # really multiple pages
        assert sum(calls) == n
        assert out.size == n
        assert out.to_pylist() == [None] * n   # placeholders, not dropped rows
        snap = _snap()
        assert snap.get("scan.pages_skipped", 0) == len(calls)
        assert snap.get("scan.bytes_skipped", 0) > 0

    def test_mixed_pruned_and_real_pages(self, tmp_path, monkeypatch):
        # Alternate pages pruned: pruned pages' rows surface as nulls in
        # place, real pages' rows are bit-identical to the oracle — the
        # full predicate re-run downstream then sees no false positives.
        from spark_rapids_tpu.io import parquet_native as pn
        p = tmp_path / "mixed.parquet"
        n = 600
        _one_group_file(p, n=n)
        _, rgs = pn.read_metadata(p)
        chunk = rgs[0][0]
        blob = _chunk_blob(p, chunk)
        oracle = pn._materialize_piece(pn._decode_chunk(blob, chunk)) \
            .to_pylist()
        calls = []

        def fake_stats(sd, info, num_values, exact_nulls=None):
            pruned = len(calls) % 2 == 0
            calls.append((num_values, pruned))
            if pruned:                        # bounds that fail the pred
                return ColumnStats(min=0, max=1, null_count=0,
                                   num_values=num_values)
            return None                       # unusable → page is read

        monkeypatch.setattr(pn, "_decode_stats", fake_stats)
        got = pn._materialize_piece(pn._decode_chunk(
            blob, chunk, (LeafPred("x", "gt", n * 10),))).to_pylist()
        assert len(calls) > 2
        expected, row = list(oracle), 0
        for nv, pruned in calls:
            if pruned:
                expected[row:row + nv] = [None] * nv
            row += nv
        assert row == n
        assert got == expected
        assert any(pr for _, pr in calls) and not all(pr for _, pr in calls)

    def test_required_column_never_page_pruned(self, tmp_path,
                                               metrics_on, monkeypatch):
        # A required column can't represent placeholder nulls: even with
        # stats proving emptiness, pages are read (row-group pruning
        # still covers this case from the footer).
        from spark_rapids_tpu.io import parquet_native as pn
        p = tmp_path / "req.parquet"
        n = 600
        _one_group_file(p, n=n, nullable=False)
        _, rgs = pn.read_metadata(p)
        chunk = rgs[0][0]
        assert not chunk.column.optional
        blob = _chunk_blob(p, chunk)
        monkeypatch.setattr(
            pn, "_decode_stats",
            lambda sd, info, nv, exact_nulls=None: ColumnStats(
                min=0, max=1, null_count=0, num_values=nv))
        out = pn._materialize_piece(pn._decode_chunk(
            blob, chunk, (LeafPred("x", "gt", n * 10),)))
        assert out.to_pylist() == list(range(n))
        assert _snap().get("scan.pages_skipped", 0) == 0

    def test_is_null_pred_disables_page_pruning(self, tmp_path,
                                                metrics_on, monkeypatch):
        # is_null is NOT null-rejecting: placeholder nulls would newly
        # match it, so its presence turns page pruning off for the column.
        from spark_rapids_tpu.io import parquet_native as pn
        p = tmp_path / "isnull.parquet"
        n = 600
        _one_group_file(p, n=n)
        _, rgs = pn.read_metadata(p)
        chunk = rgs[0][0]
        blob = _chunk_blob(p, chunk)
        monkeypatch.setattr(
            pn, "_decode_stats",
            lambda sd, info, nv, exact_nulls=None: ColumnStats(
                min=0, max=1, null_count=0, num_values=nv))
        out = pn._materialize_piece(pn._decode_chunk(
            blob, chunk,
            (LeafPred("x", "gt", n * 10), LeafPred("x", "is_null"))))
        assert out.to_pylist() == list(range(n))
        assert _snap().get("scan.pages_skipped", 0) == 0


# ---------------------------------------------------------------------------
# 4. encoded residency (SRT_ENCODED_EXEC)
# ---------------------------------------------------------------------------

class TestEncodedResidency:
    def test_scan_registers_sorted_vocab_codes(self, tmp_path, metrics_on,
                                               encoded_on):
        from spark_rapids_tpu.io.parquet_native import read_parquet_native
        from spark_rapids_tpu.ops.strings import (dictionary_encode_cached,
                                                  resident_encoding)
        p = tmp_path / "enc.parquet"
        at = _write_sorted(p, n=2000, group=500)
        t = read_parquet_native(p)
        res = resident_encoding(t["s"])
        assert res is not None
        codes, uniq = res
        values = t["s"].to_pylist()
        assert list(uniq) == sorted({v for v in values if v is not None})
        np_codes = np.asarray(codes.data)
        assert all(uniq[np_codes[i]] == v
                   for i, v in enumerate(values) if v is not None)
        assert _snap().get("scan.encoded_cols", 0) >= 1
        # the binder-side encode is a registry hit, not a host factorize
        codes2, uniq2 = dictionary_encode_cached(t["s"])
        assert uniq2 == uniq and codes2 is codes
        snap = _snap()
        assert snap.get("strings.dict_encode.resident_hit", 0) == 1
        assert snap.get("strings.dict_encode.miss", 0) == 0
        assert at.num_rows == t.num_rows

    def test_off_by_default_no_residency(self, tmp_path, metrics_on):
        from spark_rapids_tpu.io.parquet_native import read_parquet_native
        from spark_rapids_tpu.ops.strings import resident_encoding
        p = tmp_path / "plainenc.parquet"
        _write_sorted(p, n=1000, group=500)
        t = read_parquet_native(p)
        assert resident_encoding(t["s"]) is None
        assert _snap().get("scan.encoded_cols", 0) == 0

    def test_code_domain_predicate_equals_oracle(self, tmp_path,
                                                 monkeypatch):
        from spark_rapids_tpu.io.parquet_native import read_parquet_native
        from spark_rapids_tpu.ops.strings import compare_scalar
        p = tmp_path / "cmp.parquet"
        _write_sorted(p, n=2000, group=500, vocab=11)
        monkeypatch.setenv("SRT_ENCODED_EXEC", "0")
        oracle_col = read_parquet_native(p)["s"]
        monkeypatch.setenv("SRT_ENCODED_EXEC", "1")
        enc_col = read_parquet_native(p)["s"]
        for op, lit in (("gt", "w-04"), ("eq", "w-07"), ("le", "w-00"),
                        ("ne", "zzz")):
            assert compare_scalar(enc_col, lit, op).to_pylist() == \
                compare_scalar(oracle_col, lit, op).to_pylist()

    def test_encoded_plan_run_equals_oracle(self, tmp_path, monkeypatch):
        # Whole pipeline parity: scan → filter (string + float) →
        # group-by on the string key, encoded+pruned vs oracle env.
        from spark_rapids_tpu.exec.compile import run_plan
        p = tmp_path / "pipe.parquet"
        _write_sorted(p, n=3000, group=750, vocab=6)
        q = (plan()
             .filter(col("k") > 1499)
             .filter(col("s") > "w-01")
             .groupby_agg(["s"], [("v", "sum", "vs"), ("v", "count", "vc")]))

        def rows(env_val):
            monkeypatch.setenv("SRT_ENCODED_EXEC", env_val)
            monkeypatch.setenv("SRT_SCAN_PRUNE", env_val)
            t = read_parquet(p, engine="native",
                             filters=[("k", ">", 1499)])
            out = run_plan(q, t)
            return sorted(zip(*(out[n].to_pylist() for n in out.names)),
                          key=repr)

        assert rows("1") == rows("0")

    def test_coalesce_keeps_residency(self, tmp_path, encoded_on):
        from spark_rapids_tpu.io import scan_parquet
        from spark_rapids_tpu.ops.strings import resident_encoding
        p = tmp_path / "coal.parquet"
        at = _write_sorted(p, n=2000, group=500, vocab=5)
        batches = list(scan_parquet(p, coalesce_rows="bucket"))
        assert sum(b.num_rows for b in batches) == 2000
        assert any(b.num_rows > 500 for b in batches)  # coalescing happened
        got = []
        for b in batches:
            res = resident_encoding(b["s"])
            assert res is not None, "coalesce dropped scan residency"
            codes, uniq = res
            np_codes = np.asarray(codes.data)
            valid = np.ones(b.num_rows, bool) if codes.validity is None \
                else np.asarray(codes.validity)
            got.extend(uniq[c] if ok else None
                       for c, ok in zip(np_codes, valid))
        assert got == at.column("s").to_pylist()

    def test_bucket_pad_carries_residency(self, tmp_path, encoded_on):
        from spark_rapids_tpu.exec.bucketing import enabled, prepare_input
        from spark_rapids_tpu.io.parquet_native import read_parquet_native
        from spark_rapids_tpu.ops.strings import resident_encoding
        if not enabled():
            pytest.skip("shape bucketing disabled in this environment")
        p = tmp_path / "pad.parquet"
        _write_sorted(p, n=300, group=300, vocab=5)
        t = read_parquet_native(p)
        assert resident_encoding(t["s"]) is not None
        bi = prepare_input(plan().filter(col("k") > 10), t)
        assert bi is not None
        res = resident_encoding(bi.table["s"])
        assert res is not None, "bucket pad dropped scan residency"
        codes, uniq = res
        assert codes.data.shape[0] == bi.capacity
        # pad rows are null in the codes, exactly like the padded column
        assert np.asarray(codes.validity)[300:].sum() == 0


# ---------------------------------------------------------------------------
# 5. feed integration: scan_parquet(predicate=...)
# ---------------------------------------------------------------------------

class TestScanFeedPruning:
    def test_stream_skips_groups_and_matches_oracle(self, tmp_path,
                                                    metrics_on):
        from spark_rapids_tpu.io import scan_parquet
        p = tmp_path / "feed.parquet"
        at = _write_sorted(p, n=4000, group=1000)
        preds = [("k", ">", 2999)]
        batches = list(scan_parquet(p, predicate=preds))
        assert sum(b.num_rows for b in batches) == 1000   # one group survives
        ks = [k for b in batches for k in b["k"].to_pylist()]
        assert ks == at.column("k").to_pylist()[3000:]
        snap = _snap()
        assert snap.get("scan.row_groups_skipped", 0) == 3
        assert snap.get("scan.bytes_skipped", 0) > 0

    def test_bucket_target_sized_to_survivors(self, tmp_path):
        # Layout: one 4000-row group then three 100-row groups.  The
        # predicate keeps only the small groups; the "bucket" coalesce
        # target must size to THEM (capacity(100) < 200), so the three
        # survivors do not all collapse into one batch as sizing to the
        # 4000-row group would force.
        from spark_rapids_tpu.exec.bucketing import bucket_capacity
        from spark_rapids_tpu.io import scan_parquet
        p = tmp_path / "target.parquet"
        ns = [4000, 100, 100, 100]
        base = 0
        schema = pa.schema([pa.field("k", pa.int64()),
                            pa.field("v", pa.float64())])
        with pq.ParquetWriter(p, schema) as w:
            for n in ns:
                w.write_table(pa.table(
                    {"k": np.arange(base, base + n, dtype=np.int64),
                     "v": np.zeros(n)}, schema=schema))
                base += n
        assert bucket_capacity(100) < 200      # the layout's premise
        preds = [("k", ">=", 4000)]
        batches = list(scan_parquet(p, coalesce_rows="bucket",
                                    predicate=preds))
        assert sum(b.num_rows for b in batches) == 300
        assert len(batches) > 1, \
            "coalesce target ignored pruning (sized to the 4000-row group)"
        ks = [k for b in batches for k in b["k"].to_pylist()]
        assert ks == list(range(4000, 4300))
