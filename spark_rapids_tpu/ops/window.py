"""Window functions over partitions — sort-based, TPU-first.

Spark's window functions (the workload the reference system accelerates via
cuDF's rolling/window kernels, part of the capability envelope, SURVEY.md
§2.3) reduce to a handful of primitives once rows are sorted by
(partition keys, order keys):

  * segment boundaries — adjacent-difference over the sorted partition
    keys (shared with groupby, :mod:`.common`),
  * per-segment positions/prefixes — global ``cumsum`` / running-max of
    marked positions; no per-partition loops,
  * whole-partition aggregates — one scatter-reduce keyed by segment id,
  * intra-segment shifts — a global shift masked at segment boundaries.

Every function returns results in the TABLE'S ORIGINAL row order (Spark
semantics): the sort permutation is inverted with one scatter.

Supported: ``row_number``, ``rank``, ``dense_rank``, ``lag``, ``lead``,
and ``window_agg`` ("sum"/"min"/"max"/"count") over the running frame
(unbounded preceding → current row) or the whole partition
(``frame="partition"``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..column import Column
from ..dtypes import INT32, INT64
from ..table import Table
from .common import (chunked_cumsum, chunked_segmented_scan,
                     grouping_columns, null_safe_equal_adjacent)
from .groupby import _sum_dtype
from .sort import sorted_order


def _window_order(table: Table, partition_by: Sequence[str],
                  order_by: Optional[Sequence[str]] = None,
                  ascending: Optional[Sequence[bool]] = None):
    """(perm, inverse-perm, partition-start bool, encoded order cols).

    String keys are dictionary-encoded ONCE here (the host-side O(n) cost)
    and the encoded columns are reused for the sort, the partition
    boundaries, and — via the returned list — the order-change masks in
    rank/dense_rank.
    """
    if not partition_by:
        raise ValueError("partition_by must name at least one column")
    part_cols = grouping_columns([table[name] for name in partition_by])
    raw_order = [table[name] for name in (order_by or [])]
    if ascending is not None and len(ascending) != len(raw_order):
        raise ValueError("ascending must match order_by length")
    from .common import grouping_columns_with
    order_cols, asc_order = grouping_columns_with(
        raw_order, list(ascending or [True] * len(raw_order)))
    asc = [True] * len(part_cols) + asc_order
    perm = sorted_order(part_cols + order_cols, ascending=asc)
    n = perm.shape[0]
    inv = jnp.zeros(n, jnp.int32).at[perm].set(jnp.arange(n, dtype=jnp.int32))
    starts = jnp.zeros(n, jnp.bool_)
    for col in part_cols:
        starts = starts | null_safe_equal_adjacent(col.gather(perm))
    return perm, inv, starts, order_cols


def _segment_base(starts: jax.Array) -> jax.Array:
    """Per sorted row: position of its partition's first row.

    ``starts[0]`` is always True, so a running max of marked positions is
    exactly the latest partition start at or before each row.
    """
    pos = jnp.arange(starts.shape[0], dtype=jnp.int32)
    return chunked_segmented_scan(
        {"b": (jnp.where(starts, pos, 0), "max")}, starts)["b"]


def row_number(table: Table, partition_by: Sequence[str],
               order_by: Optional[Sequence[str]] = None,
               ascending: Optional[Sequence[bool]] = None) -> Column:
    """1-based position within the partition (Spark ``row_number()``)."""
    _, inv, starts, _ = _window_order(table, partition_by, order_by,
                                      ascending)
    base = _segment_base(starts)
    pos = jnp.arange(starts.shape[0], dtype=jnp.int32)
    return Column(data=jnp.take(pos - base + 1, inv), dtype=INT32)


def _order_change(order_cols, perm) -> jax.Array:
    """Sorted-view mask: the ORDER key differs from the previous row's.
    ``order_cols`` are the already-encoded columns from _window_order."""
    change = jnp.zeros(perm.shape[0], jnp.bool_)
    for col in order_cols:
        change = change | null_safe_equal_adjacent(col.gather(perm))
    return change


def rank(table: Table, partition_by: Sequence[str],
         order_by: Sequence[str],
         ascending: Optional[Sequence[bool]] = None) -> Column:
    """Spark ``rank()``: 1-based, ties share, gaps after ties."""
    perm, inv, starts, order_cols = _window_order(table, partition_by,
                                                  order_by, ascending)
    n = starts.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    base = _segment_base(starts)
    # rank = position of the latest order-change (or partition start) + 1,
    # relative to the partition base.
    marker = starts | _order_change(order_cols, perm)
    latest = chunked_segmented_scan(
        {"m": (jnp.where(marker, pos, 0), "max")}, starts)["m"]
    return Column(data=jnp.take(latest - base + 1, inv), dtype=INT32)


def dense_rank(table: Table, partition_by: Sequence[str],
               order_by: Sequence[str],
               ascending: Optional[Sequence[bool]] = None) -> Column:
    """Spark ``dense_rank()``: 1-based, ties share, no gaps."""
    perm, inv, starts, order_cols = _window_order(table, partition_by,
                                                  order_by, ascending)
    distinct = (starts | _order_change(order_cols, perm)).astype(jnp.int32)
    cum = chunked_cumsum(distinct)
    base = _segment_base(starts)
    return Column(data=jnp.take(cum - jnp.take(cum, base) + 1, inv),
                  dtype=INT32)


def _shift(table: Table, value: str, partition_by, order_by, ascending,
           offset: int, fill) -> Column:
    col = table[value]
    if col.offsets is not None:
        raise NotImplementedError("lag/lead over string columns")
    perm, inv, starts, _ = _window_order(table, partition_by, order_by,
                                         ascending)
    n = perm.shape[0]
    sorted_col = col.gather(perm)
    seg_id = chunked_cumsum(starts.astype(jnp.int32)) - 1
    pos = jnp.arange(n, dtype=jnp.int32)
    src = pos - offset
    src_safe = jnp.clip(src, 0, n - 1)
    ok = (src >= 0) & (src < n) & (jnp.take(seg_id, src_safe) == seg_id)
    data = jnp.take(sorted_col.data, src_safe, axis=0)
    src_valid = jnp.ones(n, jnp.bool_) if sorted_col.validity is None \
        else jnp.take(sorted_col.validity, src_safe)
    if fill is not None:
        data = jnp.where(ok, data, jnp.asarray(fill, data.dtype))
        validity = jnp.where(ok, src_valid, True)
    else:
        data = jnp.where(ok, data, jnp.zeros((), data.dtype))
        validity = ok & src_valid
    validity = None if bool(jnp.all(validity)) else validity
    return Column(data=jnp.take(data, inv, axis=0),
                  validity=None if validity is None
                  else jnp.take(validity, inv),
                  dtype=col.dtype)


def lag(table: Table, value: str, partition_by: Sequence[str],
        order_by: Sequence[str], offset: int = 1,
        ascending: Optional[Sequence[bool]] = None, fill=None) -> Column:
    """Value ``offset`` rows earlier in the partition (null/fill outside)."""
    return _shift(table, value, partition_by, order_by, ascending, offset,
                  fill)


def lead(table: Table, value: str, partition_by: Sequence[str],
         order_by: Sequence[str], offset: int = 1,
         ascending: Optional[Sequence[bool]] = None, fill=None) -> Column:
    """Value ``offset`` rows later in the partition (null/fill outside)."""
    return _shift(table, value, partition_by, order_by, ascending, -offset,
                  fill)


_WINDOW_AGGS = ("sum", "min", "max", "count")


def window_agg(table: Table, value: str, how: str,
               partition_by: Sequence[str],
               order_by: Optional[Sequence[str]] = None,
               ascending: Optional[Sequence[bool]] = None,
               frame: str = "cumulative") -> Column:
    """Windowed aggregation per partition.

    ``frame="cumulative"``: unbounded preceding → current row, in order.
    ``frame="partition"``: the whole partition's aggregate broadcast to
    every row.  Null values never contribute; sum/min/max are null while
    the frame holds no valid value (count is never null).
    """
    if how not in _WINDOW_AGGS:
        raise ValueError(f"how must be one of {_WINDOW_AGGS}, got {how!r}")
    if frame not in ("cumulative", "partition"):
        raise ValueError(f"frame must be cumulative|partition, got {frame!r}")
    col = table[value]
    if col.offsets is not None:
        raise NotImplementedError("window_agg over string columns")
    perm, inv, starts, _ = _window_order(table, partition_by, order_by,
                                         ascending)
    n = perm.shape[0]
    sorted_col = col.gather(perm)
    valid = sorted_col.valid_mask()
    seg_id = chunked_cumsum(starts.astype(jnp.int32)) - 1

    if how == "count":
        out_dtype = INT64
        contrib = valid.astype(jnp.int64)
    elif how == "sum":
        out_dtype = _sum_dtype(col.dtype)
        contrib = jnp.where(valid, sorted_col.data, 0).astype(
            out_dtype.jnp_dtype)
    else:
        out_dtype = col.dtype
        if col.dtype.is_floating:
            ident = np.inf if how == "min" else -np.inf
        else:
            info = np.iinfo(col.dtype.np_dtype)
            ident = info.max if how == "min" else info.min
        ident = jnp.asarray(ident, col.dtype.jnp_dtype)
        contrib = jnp.where(valid, sorted_col.data, ident)

    if frame == "partition":
        if how in ("sum", "count"):
            per_seg = jnp.zeros(n, contrib.dtype).at[seg_id].add(contrib)
        elif how == "min":
            per_seg = jnp.full(n, ident).at[seg_id].min(contrib)
        else:
            per_seg = jnp.full(n, ident).at[seg_id].max(contrib)
        run = jnp.take(per_seg, seg_id)
        seen = jnp.zeros(n, jnp.int32).at[seg_id].add(
            valid.astype(jnp.int32))
        seen = jnp.take(seen, seg_id)
    else:
        kind = "add" if how in ("sum", "count") else how
        scans = chunked_segmented_scan(
            {"v": (contrib, kind),
             "seen": (valid.astype(jnp.int32), "add")}, starts)
        run, seen = scans["v"], scans["seen"]

    if how == "count":
        validity = None
    else:
        validity = None if bool(jnp.all(seen > 0)) else (seen > 0)
        if validity is not None:
            run = jnp.where(validity, run, jnp.zeros((), run.dtype))

    return Column(data=jnp.take(run.astype(out_dtype.jnp_dtype), inv),
                  validity=None if validity is None
                  else jnp.take(validity, inv),
                  dtype=out_dtype)
