"""String/regex + decimal-cast kernel benchmark (BASELINE.json config #4).

TPC-DS q28/q88 shape: predicate-heavy scans where the per-row work is
string matching (LIKE / regex) and decimal arithmetic over a wide fact
table.  Measures each kernel family standalone plus the fused
filter→cast→aggregate pipeline, with the tunnel-safe protocol from
BASELINE.md (chained data dependencies, host-read fence, exact-composition
warmup).

Run: python benchmarks/bench_strings.py
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N = 2_000_000
REPS = 5


def _bench(label, fn, state0, n=N, reps=REPS):
    """Chained-reps timing: fn(state) -> (result_col, next_state)."""
    out, state = fn(state0)                  # warm the exact composition
    out, state = fn(state)
    _ = np.asarray(out.data[-1:])            # fence
    t0 = time.perf_counter()
    for _ in range(reps):
        out, state = fn(state)
    _ = np.asarray(out.data[-1:])            # fence
    dt = (time.perf_counter() - t0) / reps
    print(json.dumps({"metric": label, "value": round(n / dt, 1),
                      "unit": "rows/sec"}))
    return out


def main():
    import jax.numpy as jnp

    import spark_rapids_tpu as srt
    from spark_rapids_tpu import dtypes as dt
    from spark_rapids_tpu import ops
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.ops import strings
    from spark_rapids_tpu.ops.binary import binary_op

    rng = np.random.default_rng(13)

    # Dictionary-shaped string column (realistic: bounded distinct values).
    vocab = [f"item-{i:04d}-{'promo' if i % 7 == 0 else 'base'}"
             for i in range(500)]
    codes = rng.integers(0, len(vocab), N)
    names = strings.strings_from_pylist([vocab[c] for c in codes])

    unscaled = rng.integers(-10**7, 10**7, N)
    price = Column.from_numpy(unscaled.astype(np.int64)).data
    price_col = Column(data=price, dtype=dt.decimal64(-2))

    # -- LIKE scan (q88-style predicate) -------------------------------------
    def like_scan(state):
        # Shift the char domain by a data-dependent bump so runs chain.
        col = Column(data=names.data + state, offsets=names.offsets,
                     validity=names.validity, dtype=names.dtype)
        m = strings.like(col, "%promo%")
        nxt = (m.data[-1]).astype(jnp.uint8)
        return m, nxt

    _bench("strings_like_2M", like_scan, jnp.uint8(0))

    # -- regex scan (q28-style) ----------------------------------------------
    def regex_scan(state):
        col = Column(data=names.data + state, offsets=names.offsets,
                     validity=names.validity, dtype=names.dtype)
        m = strings.contains_re(col, "item-0*[1-3][0-9]-(promo|base)")
        nxt = (m.data[-1]).astype(jnp.uint8)
        return m, nxt

    _bench("strings_regex_2M", regex_scan, jnp.uint8(0))

    # -- decimal cast + rescale ----------------------------------------------
    def cast_chain(state):
        col = Column(data=price_col.data + state, dtype=dt.decimal64(-2))
        wide = ops.cast(col, dt.decimal64(-4))       # rescale x100
        back = ops.cast(wide, dt.FLOAT64)
        nxt = (back.data[-1] > 0).astype(price_col.data.dtype)
        return back, nxt

    _bench("decimal_cast_2M", cast_chain, np.int64(0))

    # -- fused pipeline: LIKE filter -> decimal cast -> grouped sum ----------
    group = Column.from_numpy(rng.integers(0, 64, N).astype(np.int32))
    table = srt.Table([("name", names), ("price", price_col), ("g", group)])

    def q28ish(state):
        t = srt.Table(list(table.items())).with_column(
            "price", Column(data=table["price"].data + state,
                            dtype=dt.decimal64(-2)))
        pred = strings.like(t["name"], "%promo%")
        t = ops.apply_boolean_mask(t, pred)
        t = t.with_column("pricef", ops.cast(t["price"], dt.FLOAT64))
        agg = ops.groupby_agg(t, ["g"], [("pricef", "sum", "rev"),
                                         ("pricef", "count", "n")])
        nxt = (agg["n"].data[0] & 1).astype(np.int64)
        return agg["rev"], nxt

    _bench("q28_like_cast_groupby_2M", q28ish, np.int64(0))

    # -- same pipeline through the LazyTable facade: the eager LIKE mask
    # fuses with filter -> cast -> grouped sum as ONE compiled program
    # (exec/lazy.py); no plan() in the pipeline code, one host sync.
    from spark_rapids_tpu.exec import col as C, lazy

    def q28_lazy(state):
        t = srt.Table(list(table.items())).with_column(
            "price", Column(data=table["price"].data + state,
                            dtype=dt.decimal64(-2)))
        pred = strings.like(t["name"], "%promo%")
        agg = (lazy(t)
               .filter(pred)
               .with_columns(pricef=C("price").cast(dt.FLOAT64))
               .groupby_agg(["g"], [("pricef", "sum", "rev"),
                                    ("pricef", "count", "n")])
               .collect())
        nxt = (agg["n"].data[0] & 1).astype(np.int64)
        return agg["rev"], nxt

    _bench("q28_lazy_fused_2M", q28_lazy, np.int64(0))

    # -- device-chained form: collect_padded() keeps the whole iteration
    # sync-free (the materializing count is the ONE remaining sync of the
    # lazy path; this isolates the program cost the way the other
    # whole-plan numbers in BASELINE.md are recorded).
    def q28_lazy_chained(state):
        t = srt.Table(list(table.items())).with_column(
            "price", Column(data=table["price"].data + state,
                            dtype=dt.decimal64(-2)))
        pred = strings.like(t["name"], "%promo%")
        agg, sel = (lazy(t)
                    .filter(pred)
                    .with_columns(pricef=C("price").cast(dt.FLOAT64))
                    .groupby_agg(["g"], [("pricef", "sum", "rev"),
                                         ("pricef", "count", "n")])
                    .collect_padded())
        nxt = (agg["n"].data[0] & 1).astype(np.int64)
        return agg["rev"], nxt

    _bench("q28_lazy_chained_2M", q28_lazy_chained, np.int64(0))


if __name__ == "__main__":
    main()
