"""Observability: query metrics, counters, and host-sync accounting.

The reference stack (spark-rapids-jni) inherits Spark's SQL-metrics UI —
every exec node reports rows/bytes/time for free.  This engine's
whole-plan XLA programs are opaque by construction, so :mod:`.metrics`
provides the substrate (named counters/gauges/timers, no-op unless
``SRT_METRICS=1``) and :mod:`.query` the per-plan record populated by
exec/compile.py and surfaced through ``Plan.explain_analyze`` and the
benchmarks' JSON output.  :mod:`.timeline` adds the fourth pillar —
span events on per-batch/per-shard lanes exported as Chrome-trace JSON
(``SRT_TRACE_TIMELINE=1``) — and :mod:`.history` persists finished
``QueryMetrics`` as JSONL keyed by plan fingerprint
(``SRT_METRICS_HISTORY=path``).  :mod:`.profile` turns all of the above
into the per-plan **cost ledger** (compute/ici/host_sync/
dispatch_overhead buckets + HBM footprint — the ``cost`` block of every
QueryMetrics), and :mod:`.regress` gates fresh ledgers against the
history baseline (``SRT_REGRESS_TOL``).

Import hygiene: nothing under ``obs`` imports jax at module load (tested
by tests/test_import_hygiene.py) — metrics post-processing must not drag
in the XLA stack.
"""

from . import history, profile, regress, timeline
from .history import load as load_history, plan_fingerprint
from .metrics import (NULL_METRIC, Counter, Gauge, MetricsRegistry, Timer,
                      counter, counters_delta, gauge, registry, timer)
from .profile import cost_block
from .regress import RegressionError
from .query import (QueryMetrics, StepMetrics, bench_cache_line, bench_line,
                    bench_metrics_line, bench_recovery_line,
                    bench_stream_line, last_query_metrics,
                    last_stream_metrics, set_last_query_metrics,
                    set_last_stream_metrics)

__all__ = [
    "NULL_METRIC",
    "Counter",
    "Gauge",
    "MetricsRegistry",
    "QueryMetrics",
    "StepMetrics",
    "Timer",
    "bench_cache_line",
    "bench_line",
    "bench_metrics_line",
    "bench_recovery_line",
    "bench_stream_line",
    "RegressionError",
    "cost_block",
    "counter",
    "counters_delta",
    "gauge",
    "history",
    "last_query_metrics",
    "last_stream_metrics",
    "load_history",
    "plan_fingerprint",
    "profile",
    "regress",
    "registry",
    "set_last_query_metrics",
    "set_last_stream_metrics",
    "timeline",
    "timer",
]
