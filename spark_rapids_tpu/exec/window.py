"""Window functions inside compiled plans (Spark OVER clauses).

Same primitives as the eager window layer (:mod:`..ops.window` — sorted
partitions, segment boundaries, running scans) re-expressed for the plan
program's constraints:

* the selection mask participates — filtered-out rows sort to the end,
  never contribute, and never break a live partition (Spark computes
  windows after WHERE);
* all running reductions use the shared chunked segmented scan
  (:func:`...ops.common.chunked_segmented_scan`) — whole-array
  ``associative_scan``/``cumsum`` are compile-time cliffs at millions of
  rows;
* the original row order is restored with a second ``lax.sort`` keyed on
  the carried row ids (the eager layer's inverse-permutation scatter is
  hostile to TPU inside a fused program).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..column import Column
from ..dtypes import INT32, INT64
from ..ops.common import adjacent_differs, chunked_cumsum, \
    chunked_segmented_scan, grouping_sort_operands
from ..ops.groupby import _sum_dtype
from .plan import WindowStep


def _sorted_view(cols, sel, step: WindowStep):
    """Sort by (selection, partition keys, order keys); returns the pieces
    every window function needs, in sorted space."""
    from ..ops.sort import sort_operands
    n = next(iter(cols.values())).size
    part_cols = [cols[k] for k in step.partition_by]
    part_ops = grouping_sort_operands(
        tuple(c.data for c in part_cols),
        tuple(c.validity for c in part_cols))
    order_ops = sort_operands([cols[k] for k in step.order_by],
                              list(step.ascending),
                              list(step.ascending))   # Spark null default
    ops_list = list(part_ops) + list(order_ops)
    if sel is not None:
        ops_list = [jnp.where(sel, jnp.uint8(0), jnp.uint8(1))] + ops_list

    iota = jnp.arange(n, dtype=jnp.int32)
    payload = [iota]
    vcol = cols[step.value] if step.value is not None else None
    if vcol is not None:
        payload.append(vcol.data)
        if vcol.validity is not None:
            payload.append(vcol.validity)
    sorted_all = jax.lax.sort(ops_list + payload, dimension=0,
                              is_stable=True, num_keys=len(ops_list))
    off = 1 if sel is not None else 0
    live = (sorted_all[0] == 0) if sel is not None else jnp.ones(n, jnp.bool_)
    part_sorted = sorted_all[off:off + len(part_ops)]
    order_sorted = sorted_all[off + len(part_ops):len(ops_list)]
    rest = sorted_all[len(ops_list):]
    row_ids = rest[0]
    svalue = svalid = None
    if vcol is not None:
        svalue = rest[1]
        svalid = rest[2] if vcol.validity is not None else None

    starts = jnp.zeros(n, jnp.bool_)
    for op in part_sorted:
        starts = starts | adjacent_differs(op)
    starts = starts & live
    order_change = starts
    for op in order_sorted:
        order_change = order_change | adjacent_differs(op)
    order_change = order_change & live
    return (n, live, starts, order_change, row_ids, svalue, svalid,
            iota, vcol)


def _seg_base(starts, pos):
    """Per sorted row: position of its partition's first row."""
    return chunked_segmented_scan(
        {"b": (jnp.where(starts, pos, 0), "max")}, starts)["b"]


def trace_window(cols, sel, step: WindowStep):
    (n, live, starts, order_change, row_ids, svalue, svalid, pos,
     vcol) = _sorted_view(cols, sel, step)

    out_validity_sorted = None
    if step.func == "row_number":
        base = _seg_base(starts, pos)
        data = (pos - base + 1).astype(jnp.int32)
        out_dtype = INT32
    elif step.func == "rank":
        base = _seg_base(starts, pos)
        latest = chunked_segmented_scan(
            {"m": (jnp.where(order_change, pos, 0), "max")}, starts)["m"]
        data = (latest - base + 1).astype(jnp.int32)
        out_dtype = INT32
    elif step.func == "dense_rank":
        data = chunked_segmented_scan(
            {"d": (order_change.astype(jnp.int32), "add")},
            starts)["d"]
        out_dtype = INT32
    elif step.func in ("lag", "lead"):
        offset = step.offset if step.func == "lag" else -step.offset
        seg_id = chunked_cumsum(starts.astype(jnp.int32)) - 1
        src = pos - jnp.int32(offset)
        src_safe = jnp.clip(src, 0, n - 1)
        ok = ((src >= 0) & (src < n)
              & (jnp.take(seg_id, src_safe) == seg_id)
              & jnp.take(live, src_safe))
        data = jnp.take(svalue, src_safe)
        src_valid = (jnp.ones(n, jnp.bool_) if svalid is None
                     else jnp.take(svalid, src_safe))
        if step.fill is not None:
            data = jnp.where(ok, data,
                             jnp.asarray(step.fill, data.dtype))
            out_validity_sorted = jnp.where(ok, src_valid, True)
        else:
            data = jnp.where(ok, data, jnp.zeros((), data.dtype))
            out_validity_sorted = ok & src_valid
        out_dtype = vcol.dtype
    else:                                  # sum / min / max / count
        valid = live if svalid is None else (live & svalid)
        how = step.func
        if how == "count":
            out_dtype = INT64
            contrib = valid.astype(jnp.int64)
            kind = "add"
        elif how == "sum":
            out_dtype = _sum_dtype(vcol.dtype)
            contrib = jnp.where(valid, svalue, 0).astype(out_dtype.jnp_dtype)
            kind = "add"
        else:
            out_dtype = vcol.dtype
            if vcol.dtype.is_floating:
                ident = np.inf if how == "min" else -np.inf
            else:
                info = np.iinfo(vcol.dtype.np_dtype)
                ident = info.max if how == "min" else info.min
            ident = jnp.asarray(ident, vcol.dtype.jnp_dtype)
            contrib = jnp.where(valid, svalue, ident)
            kind = how
        fields = {"v": (contrib, kind),
                  "seen": (valid.astype(jnp.int64), "add")}
        scans = chunked_segmented_scan(fields, starts)
        run, seen = scans["v"], scans["seen"]
        if step.frame == "partition":
            # Broadcast the value at each partition's END back to all its
            # rows: end position via a reversed-space segment base.
            ends_marker = jnp.concatenate(
                [starts[1:], jnp.ones(1, jnp.bool_)])
            rev_starts = jnp.flip(ends_marker)
            rev_base = _seg_base(rev_starts, pos)
            end_pos = (n - 1) - jnp.flip(rev_base)
            run = jnp.take(run, end_pos)
            seen = jnp.take(seen, end_pos)
        data = run
        if how == "count":
            out_validity_sorted = None
        else:
            out_validity_sorted = seen > 0

    # Restore original row order: one sort keyed on the carried row ids.
    back = [row_ids, data]
    if out_validity_sorted is not None:
        back.append(out_validity_sorted)
    restored = jax.lax.sort(back, dimension=0, is_stable=False, num_keys=1)
    out_data = restored[1]
    out_valid = restored[2] if out_validity_sorted is not None else None

    new = dict(cols)
    new[step.out] = Column(data=out_data.astype(out_dtype.jnp_dtype),
                           validity=out_valid, dtype=out_dtype)
    return new, sel
