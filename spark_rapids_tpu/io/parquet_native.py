"""Native Parquet page decoder with device-side, chunk-fused value decode.

The reference's Parquet decode lives in the vendored cuDF GPU reader
(SURVEY.md §2.3; BASELINE.json lists "Parquet decode" on the op set).  This
is the TPU-native equivalent, split the way the hardware wants:

  * **Host (cheap, metadata-scale):** Thrift metadata/page-header walk
    (:mod:`.thriftc`), codec decompression (pyarrow's C++ codecs), and an
    O(#runs) parse of RLE/bit-packed run *headers* — runs are few (a
    bit-packed run covers up to 2^31 values), so this is not the hot path.
  * **Device (value-scale):** everything proportional to the number of
    values — RLE/bit-packed expansion of definition levels and dictionary
    indices via vectorized bit-extraction over ``uint32`` word images (the
    same word-major design as :mod:`spark_rapids_tpu.rows.image`),
    dictionary gathers, boolean bit-unpack, and null scatter — all jitted
    XLA.

**Chunk fusion** is the central design decision: per-page decode would cost
~8 device dispatches + a host sync per page (measured ≈35 ms/page through
the tunneled TPU), so instead all pages of a column chunk are merged on the
host into ONE run table (out-positions rebased per page, bit offsets
rebased into one concatenated byte stream) and the chunk decodes with a
constant number of device kernels: one run expansion for definition
levels, one for dictionary indices (or one reinterpret for PLAIN), one
gather, one null scatter.  Definition-level counts are computed host-side
by popcount over the run structure, so no device→host sync happens inside
the page walk.  Kernels specialize on pow2-bucketed shapes, bounding TPU
recompiles at O(log pages · widths) per schema.

Supported: flat schemas; BOOLEAN/INT32/INT64/FLOAT/DOUBLE/BYTE_ARRAY and
≤8-byte FIXED_LEN_BYTE_ARRAY decimals; PLAIN, PLAIN_DICTIONARY /
RLE_DICTIONARY, RLE booleans; RLE definition levels; data pages v1 and v2;
UNCOMPRESSED/SNAPPY/GZIP/BROTLI/ZSTD/LZ4_RAW codecs; DECIMAL / DATE /
TIMESTAMP / INTEGER logical types.  Out-of-envelope files raise
``NotImplementedError`` from the footer walk — before any data-page IO —
so ``engine="auto"`` (:mod:`.parquet`) falls back to the Arrow reader
cheaply.
"""

from __future__ import annotations

import functools
import struct as _struct
import time as _time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..column import Column
from ..dtypes import (BOOL8, DType, FLOAT32, FLOAT64, INT32, INT64, STRING,
                      TypeId, decimal32, decimal64)
from ..table import Table
from .pushdown import (ColumnStats, LeafPred, NULL_REJECTING_OPS, may_match)
from .thriftc import ThriftReader

MAGIC = b"PAR1"

# parquet.thrift physical types.
T_BOOLEAN, T_INT32, T_INT64, T_INT96, T_FLOAT, T_DOUBLE, T_BYTE_ARRAY, \
    T_FIXED_LEN_BYTE_ARRAY = range(8)

# parquet.thrift encodings.
E_PLAIN = 0
E_PLAIN_DICTIONARY = 2
E_RLE = 3
E_BIT_PACKED = 4
E_RLE_DICTIONARY = 8

# parquet.thrift page types.
P_DATA = 0
P_INDEX = 1
P_DICTIONARY = 2
P_DATA_V2 = 3

_CODEC_NAMES = {0: None, 1: "snappy", 2: "gzip", 4: "brotli", 6: "zstd",
                7: "lz4_raw"}

# ConvertedType values that matter for flat columns.
_CT_DECIMAL = 5
_CT_DATE = 6
_CT_TIMESTAMP_MILLIS = 9
_CT_TIMESTAMP_MICROS = 10
_CT_INTS = {11: TypeId.UINT8, 12: TypeId.UINT16, 13: TypeId.UINT32,
            14: TypeId.UINT64, 15: TypeId.INT8, 16: TypeId.INT16,
            17: TypeId.INT32, 18: TypeId.INT64}
# LogicalType union field ids (SchemaElement field 10).
_LT_DECIMAL = 5
_LT_DATE = 6
_LT_TIMESTAMP = 8
_LT_INTEGER = 10
# TimeUnit union field ids → cudf timestamp type per unit.
_TIMESTAMP_UNITS = {1: TypeId.TIMESTAMP_MILLISECONDS,
                    2: TypeId.TIMESTAMP_MICROSECONDS,
                    3: TypeId.TIMESTAMP_NANOSECONDS}

# Encodings outside the decoder's envelope; checked against footer metadata
# BEFORE any data-page IO so engine="auto" can reject cheaply.  BIT_PACKED is
# absent on purpose: writers list it for legacy *level* encoding and listing
# it does not imply the values use it (rejected at page decode if they do).
_UNSUPPORTED_ENCODINGS = {5, 6, 7, 9}   # DELTA_* family, BYTE_STREAM_SPLIT


@dataclass(frozen=True)
class ColumnInfo:
    """Schema leaf column: physical + logical type and level widths.

    ``max_rep > 0`` marks a LIST column (one repetition level — the
    standard 3-level list encoding); ``max_def`` then distinguishes null
    list / empty list / null element / present element."""
    name: str
    physical: int
    dtype: DType
    optional: bool          # max definition level is 1 iff optional (flat)
    type_length: int = 0    # FIXED_LEN_BYTE_ARRAY width (bytes)
    max_rep: int = 0        # 1 for LIST columns
    max_def: int = 0        # full definition-level depth (lists)
    element_optional: bool = False


@dataclass(frozen=True)
class ChunkInfo:
    column: ColumnInfo
    codec: Optional[str]
    num_values: int
    start_offset: int       # min(data_page_offset, dictionary_page_offset)
    total_compressed: int
    stats: Optional[ColumnStats] = None   # footer Statistics, decoded


def _stat_bound(raw, info: ColumnInfo):
    """Decode one Statistics min/max payload into a python comparable in
    the column's logical domain, or None when undecodable.

    BYTE_ARRAY bounds stay raw utf-8 bytes (byte order == code-point
    order); INT32/INT64 lanes decode per the logical signedness (UINT
    converted types order unsigned); decimal lanes hold unscaled ints —
    the same domain the engine's Column data uses, so comparisons against
    pushed-down literals stay consistent.
    """
    if raw is None:
        return None
    phys = info.physical
    if phys == T_BYTE_ARRAY:
        return bytes(raw) if info.dtype == STRING else None
    if phys == T_BOOLEAN:
        return bool(raw[0]) if len(raw) >= 1 else None
    try:
        kind = np.dtype(info.dtype.jnp_dtype).kind
    except Exception:
        return None
    fmts = {T_INT32: ("<u4" if kind == "u" else "<i4", 4),
            T_INT64: ("<u8" if kind == "u" else "<i8", 8),
            T_FLOAT: ("<f4", 4), T_DOUBLE: ("<f8", 8)}
    if phys not in fmts:
        return None
    fmt, width = fmts[phys]
    if len(raw) < width:
        return None
    val = np.frombuffer(raw[:width], dtype=fmt)[0]
    return float(val) if fmt[1] == "f" else int(val)


def _decode_stats(sd, info: ColumnInfo, num_values: int,
                  exact_nulls: Optional[int] = None
                  ) -> Optional[ColumnStats]:
    """Parquet ``Statistics`` thrift struct → :class:`ColumnStats`, or
    None when nothing usable was written.  min/max are only used as a
    PAIR (a lone bound can't drive the two-sided truth table safely
    against buggy writers)."""
    if not isinstance(sd, dict):
        sd = {}
    null_count = sd.get(3)
    if exact_nulls is not None:
        null_count = exact_nulls
    mn_raw, mx_raw = sd.get(6), sd.get(5)
    if mn_raw is None and mx_raw is None:
        # Legacy min/max (fields 2/1) were written under SIGNED comparison;
        # trust them only where the logical order IS the signed physical
        # order — plain signed ints and floats, never BYTE_ARRAY
        # (PARQUET-251) and never UINT converted types.
        legacy_ok = info.physical in (T_INT32, T_INT64, T_FLOAT, T_DOUBLE)
        if legacy_ok:
            try:
                legacy_ok = np.dtype(info.dtype.jnp_dtype).kind != "u"
            except Exception:
                legacy_ok = False
        if legacy_ok:
            mn_raw, mx_raw = sd.get(2), sd.get(1)
    mn = _stat_bound(mn_raw, info)
    mx = _stat_bound(mx_raw, info)
    if mn is None or mx is None:
        mn = mx = None
    if mn is None and null_count is None:
        return None
    return ColumnStats(min=mn, max=mx, null_count=null_count,
                       num_values=num_values)


def _logical_dtype(phys: int, elem: Dict[int, Any], name: str) -> DType:
    """Map (physical type, ConvertedType, LogicalType) → engine DType.

    Mirrors the Arrow-reader mapping (:mod:`.arrow` ``_PA_TO_TYPEID``) so
    both engines produce identical schemas for the same file.
    """
    converted = elem.get(6)
    logical = elem.get(10) or {}
    if converted == _CT_DECIMAL or _LT_DECIMAL in logical:
        scale = elem.get(7)
        if scale is None:
            scale = logical.get(_LT_DECIMAL, {}).get(1, 0)
        precision = elem.get(8)
        if precision is None:
            precision = logical.get(_LT_DECIMAL, {}).get(
                2, 9 if phys == T_INT32 else 18)
        if phys in (T_INT32, T_INT64, T_FIXED_LEN_BYTE_ARRAY) \
                and precision <= 18:
            # Width follows PRECISION, not the physical lanes (the spec
            # allows storing a narrow decimal in wider lanes) — this is the
            # Arrow engine's mapping (io/arrow.py: precision<=9 → DECIMAL32),
            # kept identical so both engines agree on schemas.
            return decimal32(-scale) if precision <= 9 else decimal64(-scale)
        raise NotImplementedError(
            f"column {name!r}: DECIMAL physical type {phys} at precision "
            f"{precision} (decimal128 needs the Arrow reader)")
    if converted == _CT_DATE or _LT_DATE in logical:
        return DType(TypeId.TIMESTAMP_DAYS)
    if _LT_TIMESTAMP in logical:
        if logical[_LT_TIMESTAMP].get(1):
            # isAdjustedToUTC: the Arrow engine rejects tz-aware timestamps
            # (no device representation of the zone); match it rather than
            # silently dropping the UTC flag.
            raise NotImplementedError(
                f"column {name!r}: UTC-adjusted (tz-aware) timestamp")
        unit = next(iter(logical[_LT_TIMESTAMP].get(2, {1: {}}).keys()))
        return DType(_TIMESTAMP_UNITS[unit])
    if converted == _CT_TIMESTAMP_MILLIS:
        return DType(TypeId.TIMESTAMP_MILLISECONDS)
    if converted == _CT_TIMESTAMP_MICROS:
        return DType(TypeId.TIMESTAMP_MICROSECONDS)
    if converted in _CT_INTS:
        return DType(_CT_INTS[converted])
    if _LT_INTEGER in logical:
        width = logical[_LT_INTEGER].get(1, 32)
        signed = logical[_LT_INTEGER].get(2, True)
        tid = TypeId[("INT" if signed else "UINT") + str(width)]
        return DType(tid)
    if phys == T_BOOLEAN:
        return BOOL8
    if phys == T_INT32:
        return INT32
    if phys == T_INT64:
        return INT64
    if phys == T_FLOAT:
        return FLOAT32
    if phys == T_DOUBLE:
        return FLOAT64
    if phys == T_BYTE_ARRAY:
        return STRING
    raise NotImplementedError(
        f"column {name!r}: unsupported physical type {phys} "
        "(INT96/FIXED_LEN_BYTE_ARRAY need the Arrow reader)")


def read_metadata(path) -> Tuple[List[ColumnInfo], List[List[ChunkInfo]]]:
    """Parse footer metadata: per-leaf columns and per-row-group chunks.

    Only the footer is read (via tail seeks), and the schema/encoding
    envelope is validated here — so out-of-envelope files cost one footer
    read and no data IO.  Data bytes are fetched later as per-chunk range
    reads (:func:`read_parquet_native`), so column pruning prunes IO too.
    """
    with open(path, "rb") as f:
        f.seek(0, 2)
        fsize = f.tell()
        if fsize < 12:
            raise ValueError(f"{path}: not a Parquet file")
        f.seek(fsize - 8)
        tail = f.read(8)
        if tail[4:] != MAGIC:
            raise ValueError(f"{path}: not a Parquet file")
        (meta_len,) = _struct.unpack_from("<I", tail, 0)
        meta_start = fsize - 8 - meta_len
        f.seek(meta_start)
        fmeta = ThriftReader(f.read(meta_len)).read_struct()

    schema_elems = fmeta[2]
    root = schema_elems[0]
    n_children = root.get(5, 0)
    columns: List[ColumnInfo] = []
    idx = 1
    for _ in range(n_children):
        elem = schema_elems[idx]
        idx += 1
        if elem.get(5):     # group node
            # Standard 3-level LIST: optional group X (LIST=3) {
            #   repeated group list { <element> } }.  Anything else
            # (MAP, structs, multi-level nesting) -> Arrow reader.
            name = elem[4].decode()
            if elem.get(6) != 3 or elem.get(5) != 1:
                raise NotImplementedError(
                    f"nested group {name!r} is not a standard LIST; "
                    f"MAP/STRUCT schemas need the Arrow reader")
            mid = schema_elems[idx]
            idx += 1
            if mid.get(3) != 2 or mid.get(5, 0) != 1:
                raise NotImplementedError(
                    f"column {name!r}: non-standard (2-level) list "
                    f"encoding needs the Arrow reader")
            leaf = schema_elems[idx]
            idx += 1
            if leaf.get(5):
                raise NotImplementedError(
                    f"column {name!r}: nested list elements need the "
                    f"Arrow reader")
            from ..dtypes import list_
            phys = leaf[1]
            list_optional = elem.get(3, 0) == 1
            element_optional = leaf.get(3, 0) == 1
            elem_dtype = _logical_dtype(phys, leaf, name)
            columns.append(ColumnInfo(
                name=name, physical=phys, dtype=list_(elem_dtype),
                optional=list_optional, type_length=leaf.get(2, 0),
                max_rep=1,
                max_def=(1 if list_optional else 0) + 1
                + (1 if element_optional else 0),
                element_optional=element_optional))
            continue
        name = elem[4].decode()
        phys = elem[1]
        repetition = elem.get(3, 0)   # 0 required, 1 optional, 2 repeated
        if repetition == 2:
            raise NotImplementedError(f"column {name!r}: repeated field")
        columns.append(ColumnInfo(
            name=name, physical=phys,
            dtype=_logical_dtype(phys, elem, name),
            optional=(repetition == 1),
            type_length=elem.get(2, 0)))

    row_groups: List[List[ChunkInfo]] = []
    for rg in fmeta.get(4, []):
        chunks = []
        for cc, col in zip(rg[1], columns):
            md = cc.get(3)
            if md is None:
                # meta_data is optional in parquet.thrift: absent for
                # column-encrypted or external-file chunks.
                raise NotImplementedError(
                    f"column {col.name!r}: chunk without inline metadata "
                    "(encrypted/external chunks need the Arrow reader)")
            codec_id = md[4]
            if codec_id not in _CODEC_NAMES:
                raise NotImplementedError(f"codec id {codec_id}")
            bad = _UNSUPPORTED_ENCODINGS.intersection(md.get(2, []))
            if bad:
                raise NotImplementedError(
                    f"column {col.name!r} uses encoding(s) {sorted(bad)} "
                    "(DELTA_*/BYTE_STREAM_SPLIT need the Arrow reader)")
            start = md[9]
            dict_off = md.get(11)
            # Some writers put dictionary_page_offset after data_page_offset
            # erroneously; the chunk always starts at the smallest offset.
            if dict_off is not None and 0 < dict_off < start:
                start = dict_off
            try:
                stats = _decode_stats(md.get(12), col, md[5])
            except Exception:
                stats = None            # malformed stats never fail a read
            chunks.append(ChunkInfo(
                column=col, codec=_CODEC_NAMES[codec_id],
                num_values=md[5], start_offset=start,
                total_compressed=md[7], stats=stats))
        row_groups.append(chunks)
    return columns, row_groups


def _decompress(codec: Optional[str], data: bytes, out_size: int) -> bytes:
    # No size-equality shortcut: v1 pages are always compressed when the
    # chunk codec is set (equal sizes can legitimately happen on
    # incompressible data); v2's is_compressed flag is handled by callers.
    if codec is None:
        return data
    import pyarrow as pa
    return pa.Codec(codec).decompress(data, out_size).to_pybytes()


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid: host run parse/merge + device expansion
# ---------------------------------------------------------------------------

def parse_rle_runs(buf: bytes, bit_width: int,
                   num_values: int) -> Dict[str, np.ndarray]:
    """Walk run headers, returning the run table the device kernel expands.

    Output arrays (one slot per run): ``out_start`` — first output index the
    run covers; ``count`` — values the run encodes (bit-packed runs encode
    multiples of 8 and may overrun ``num_values`` at the tail);
    ``rle_value`` — the run's value for RLE runs, else 0; ``bp_bit_base`` —
    absolute bit offset of the run's packed data for bit-packed runs, else
    0; ``is_rle`` — run kind.  O(#runs) host work.
    """
    starts: List[int] = []
    counts: List[int] = []
    values: List[int] = []
    bases: List[int] = []
    kinds: List[bool] = []
    pos = 0
    out = 0
    vbytes = (bit_width + 7) // 8
    n = len(buf)
    while out < num_values and pos < n:
        header = 0
        shift = 0
        while True:
            b = buf[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        if header & 1:                          # bit-packed groups of 8
            count = (header >> 1) * 8
            starts.append(out)
            counts.append(count)
            values.append(0)
            bases.append(pos * 8)
            kinds.append(False)
            pos += (header >> 1) * bit_width
            out += count
        else:                                   # RLE run
            count = header >> 1
            v = int.from_bytes(buf[pos:pos + vbytes], "little")
            pos += vbytes
            starts.append(out)
            counts.append(count)
            values.append(v)
            bases.append(0)
            kinds.append(True)
            out += count
    if out < num_values:
        raise ValueError(
            f"RLE stream exhausted at {out}/{num_values} values")
    return {
        "out_start": np.asarray(starts, np.int32),
        "count": np.asarray(counts, np.int64),
        "rle_value": np.asarray(values, np.int32),
        "bp_bit_base": np.asarray(bases, np.int64),
        "is_rle": np.asarray(kinds, np.bool_),
    }


_native_parse = None
_native_checked = False


def _parse_runs_and_ones(buf: bytes, bit_width: int, num_values: int
                         ) -> Tuple[Dict[str, np.ndarray], Optional[int]]:
    """Run-table parse + width-1 popcount, native C++ when available.

    Null-dense definition-level streams carry ~100k runs per chunk; the
    single-pass C++ walk (native/src/rle_decode.cpp) is ~100x the Python
    loop there.  Falls back to the pure-Python parser (kept as the
    behavioral reference; tests assert parity) when the host library is
    unavailable.
    """
    global _native_parse, _native_checked
    if not _native_checked:
        _native_checked = True
        try:
            from .. import ffi
            ffi.load()
            _native_parse = ffi.parse_rle_runs
        except Exception:
            _native_parse = None
    if _native_parse is not None:
        return _native_parse(buf, bit_width, num_values)
    runs = parse_rle_runs(buf, bit_width, num_values)
    ones = count_rle_ones(buf, runs, num_values) if bit_width == 1 else None
    return runs, ones


def _expand_levels_host(buf: Optional[bytes], bit_width: int,
                        num_values: int) -> np.ndarray:
    """Expand an RLE/bit-packed LEVEL stream to int8 values on the host.

    Levels are metadata-scale (<= 2 bits for lists) and drive offset/
    validity construction, which is host work anyway; element VALUES stay
    on the device path.  O(#runs) + O(num_values) numpy."""
    if bit_width == 0 or buf is None:
        return np.zeros(num_values, np.int8)
    runs = parse_rle_runs(buf, bit_width, num_values)
    total = num_values
    if runs["out_start"].size:
        total = max(total,
                    int((runs["out_start"] + runs["count"]).max()))
    out = np.zeros(total, np.int8)
    allbits = None
    for start, count, value, base, is_rle in zip(
            runs["out_start"], runs["count"], runs["rle_value"],
            runs["bp_bit_base"], runs["is_rle"]):
        if is_rle:
            out[start:start + count] = value
        else:
            if allbits is None:
                allbits = np.unpackbits(np.frombuffer(buf, np.uint8),
                                        bitorder="little")
            nbits = int(count) * bit_width
            seg = allbits[base:base + nbits]
            if seg.size < nbits:
                seg = np.pad(seg, (0, nbits - seg.size))
            vals = seg.reshape(int(count), bit_width) @ \
                (1 << np.arange(bit_width, dtype=np.int16))
            out[start:start + count] = vals.astype(np.int8)
    return out[:num_values]


def count_rle_ones(buf: bytes, runs: Dict[str, np.ndarray],
                   num_values: int) -> int:
    """Host popcount of a width-1 RLE/bit-packed stream (definition levels).

    Lets the page walk know each page's defined-value count without a
    device→host sync: RLE runs contribute ``count * value``; bit-packed
    runs a byte-level popcount clamped to the stream's logical length.
    """
    total = 0
    for start, count, value, base, is_rle in zip(
            runs["out_start"], runs["count"], runs["rle_value"],
            runs["bp_bit_base"], runs["is_rle"]):
        covered = min(int(count), num_values - int(start))
        if covered <= 0:
            continue
        if is_rle:
            total += covered * int(value)
        else:
            byte0 = int(base) // 8              # width-1 runs are byte-aligned
            full, rem = divmod(covered, 8)
            seg = np.frombuffer(buf, np.uint8, count=full, offset=byte0)
            total += int(np.unpackbits(seg).sum())
            if rem:
                total += bin(buf[byte0 + full] & ((1 << rem) - 1)).count("1")
    return total


class RunMerger:
    """Accumulates run tables from many pages into one device expansion.

    Pages append their (rebased) runs and byte streams; ``expand`` pads the
    merged table and word image to pow2 buckets and launches ONE kernel for
    the whole chunk.  This is what makes decode cost per-chunk, not
    per-page.
    """

    def __init__(self):
        self._bufs: List[bytes] = []
        self._tables: List[Dict[str, np.ndarray]] = []
        self._bit_base = 0
        self._max_width = 1

    def add_stream(self, buf: bytes, bit_width: int, num_values: int,
                   out_base: int,
                   runs: Optional[Dict[str, np.ndarray]] = None
                   ) -> Dict[str, np.ndarray]:
        """Append one RLE/bit-packed stream whose output lands at
        ``out_base``; returns the parsed (un-rebased) run table.  Pass
        ``runs`` when the stream was already parsed (avoids a re-walk)."""
        if runs is None:
            runs, _ = _parse_runs_and_ones(buf, bit_width, num_values)
        self._tables.append({
            "out_start": runs["out_start"] + np.int32(out_base),
            "rle_value": runs["rle_value"],
            "bp_bit_base": np.where(runs["is_rle"], 0,
                                    runs["bp_bit_base"] + self._bit_base),
            "is_rle": runs["is_rle"],
            # Per-run width: streams of DIFFERENT widths fuse into one
            # expansion (dictionary bit widths grow page-over-page as the
            # writer's dictionary fills; a per-page fallback cost ~120
            # kernel dispatches on a 4M-row scan).
            "width": np.full(runs["is_rle"].shape[0], bit_width, np.int32),
        })
        self._bufs.append(buf)
        self._bit_base += len(buf) * 8
        self._max_width = max(self._max_width, bit_width)
        return runs

    def add_raw_bits(self, buf: bytes, out_base: int) -> None:
        """Append a raw bit span (PLAIN BOOLEAN page) as one synthetic
        bit-packed run — fuses boolean pages into the same expansion."""
        self._tables.append({
            "out_start": np.asarray([out_base], np.int32),
            "rle_value": np.zeros(1, np.int32),
            "bp_bit_base": np.asarray([self._bit_base], np.int64),
            "is_rle": np.zeros(1, np.bool_),
            "width": np.ones(1, np.int32),
        })
        self._bufs.append(buf)
        self._bit_base += len(buf) * 8

    def expand(self, bit_width: int, num_values: int) -> jax.Array:
        """One device kernel: merged runs → ``num_values`` int32 values."""
        if num_values == 0 or not self._tables:
            return jnp.zeros(num_values, jnp.int32)
        from ..ops.common import pow2_bucket
        out_start = np.concatenate([t["out_start"] for t in self._tables])
        rle_value = np.concatenate([t["rle_value"] for t in self._tables])
        bp_bit_base = np.concatenate([t["bp_bit_base"] for t in self._tables])
        is_rle = np.concatenate([t["is_rle"] for t in self._tables])
        width = np.concatenate([t["width"] for t in self._tables])
        # Bit indices fit int32 whenever the merged stream is < 256 MB (the
        # practical case: level/index streams are a fraction of a <=2 GB
        # chunk) — int64 index math would run in emulated x64 on TPU.
        # Worst-case index: a run base plus (pow2-padded) run-local offset.
        max_w = max(self._max_width, bit_width, 1)
        if self._bit_base + 2 * num_values * max_w + 64 < 2**31:
            bp_bit_base = bp_bit_base.astype(np.int32)
        n_runs = out_start.shape[0]
        pad = pow2_bucket(n_runs) - n_runs
        n_pad = pow2_bucket(num_values)
        if pad:
            # Sentinel runs start past every real output index, so the
            # searchsorted in the kernel never selects them.
            out_start = np.concatenate(
                [out_start, np.full(pad, n_pad, np.int32)])
            rle_value = np.concatenate([rle_value, np.zeros(pad, np.int32)])
            bp_bit_base = np.concatenate(       # keep the int32 downcast
                [bp_bit_base, np.zeros(pad, bp_bit_base.dtype)])
            is_rle = np.concatenate([is_rle, np.ones(pad, np.bool_)])
            width = np.concatenate([width, np.ones(pad, np.int32)])
        words = _bytes_to_words(b"".join(self._bufs), bucket=True)
        args = (words, jnp.asarray(out_start), jnp.asarray(rle_value),
                jnp.asarray(bp_bit_base), jnp.asarray(is_rle),
                jnp.asarray(width))
        from ..kernels import registry as _kernels
        if _kernels.enabled("decode"):
            # Same run table, same page-walk accounting (scan.bytes_skipped
            # is host-side and untouched) — only the expansion is Pallas.
            from ..kernels.decode import expand_runs as _pallas_expand
            out = _kernels.dispatch(
                "decode",
                lambda: _pallas_expand(*args, n=n_pad,
                                       interpret=_kernels.interpret_mode()),
                lambda: _expand_runs(*args, n=n_pad))
        else:
            out = _expand_runs(*args, n=n_pad)
        return out[:num_values]


def _bytes_to_words(buf: bytes, bucket: bool = False) -> jax.Array:
    """Byte stream → device ``uint32`` little-endian word image (+1 pad word
    so the two-word bit-extract below never reads out of bounds).

    ``bucket=True`` zero-pads the word count to a power of two so kernels
    parameterized on the word-image shape compile O(log sizes) times across
    a many-page scan instead of once per distinct page size.
    """
    pad = (-len(buf)) % 4 + 4
    arr = np.frombuffer(buf + b"\x00" * pad, dtype="<u4")
    if bucket:
        from ..ops.common import pow2_bucket
        target = pow2_bucket(arr.shape[0])
        if target != arr.shape[0]:
            arr = np.concatenate([arr, np.zeros(target - arr.shape[0], "<u4")])
    return jnp.asarray(arr)


@functools.partial(jax.jit, static_argnames=("n",))
def _expand_runs(words: jax.Array, out_start: jax.Array, rle_value: jax.Array,
                 bp_bit_base: jax.Array, is_rle: jax.Array,
                 width: jax.Array, *, n: int) -> jax.Array:
    """Device expansion of an RLE/bit-packed run table to ``n`` int32 values.

    Each output position finds its run with a vectorized ``searchsorted``
    (runs are start-sorted), then either takes the run's RLE value or
    gathers ``width[run]`` bits from the word image — two u32 loads plus
    shifts, the TPU replacement for cuDF's per-thread run cursors.  The
    bit width is a PER-RUN operand, not a static parameter, so streams of
    different widths (growing dictionary codes) share one kernel and the
    compile cache keys only on shapes.
    """
    idx = jnp.arange(n, dtype=jnp.int32)
    run = jnp.searchsorted(out_start, idx, side="right").astype(jnp.int32) - 1
    w = width[run]
    # bp_bit_base arrives int32 when the stream is small enough (the common
    # case) so the index math stays in native 32-bit lanes on TPU; int64
    # (emulated) only for >256 MB merged streams.
    # Multiply in the base dtype: the int64 fallback path (merged streams
    # >= 2^31 bits) must not wrap the product in int32 lanes first.
    base = bp_bit_base[run] + \
        (idx - out_start[run]).astype(bp_bit_base.dtype) * \
        w.astype(bp_bit_base.dtype)
    word_idx = jnp.minimum((base >> 5).astype(jnp.int32),
                           words.shape[0] - 2)     # pad rows read zeros
    shift = (base & 31).astype(jnp.uint32)
    w0 = words[word_idx]
    w1 = words[word_idx + 1]
    # (w1 << (31-s)) << 1 == w1 << (32-s) without an undefined shift-by-32.
    packed = (w0 >> shift) | ((w1 << (31 - shift)) << 1)
    # ((1 << w) - 1) in uint32 lanes: at w == 32 the shift wraps to 0 and
    # 0 - 1 wraps to the full mask — exactly what width-32 needs — but the
    # explicit where keeps the intent (and the lowering) well-defined.
    wmask = jnp.where(w >= 32, jnp.uint32(0xFFFFFFFF),
                      (jnp.uint32(1) << jnp.clip(w, 0, 31).astype(jnp.uint32))
                      - jnp.uint32(1))
    packed = packed & wmask
    return jnp.where(is_rle[run], rle_value[run],
                     packed.astype(jnp.int32))


def decode_rle_bp(buf: bytes, bit_width: int, num_values: int) -> jax.Array:
    """Single-stream RLE/bit-packed hybrid decode → device int32 values."""
    if bit_width == 0:
        return jnp.zeros(num_values, jnp.int32)
    m = RunMerger()
    m.add_stream(buf, bit_width, num_values, 0)
    return m.expand(bit_width, num_values)


@jax.jit
def _scatter_defined_kernel(dense: jax.Array, valid: jax.Array):
    rank = jnp.cumsum(valid.astype(jnp.int32)) - 1
    safe = jnp.clip(rank, 0, max(dense.shape[0] - 1, 0))
    out = dense[safe] if dense.shape[0] else \
        jnp.zeros(valid.shape[0], dense.dtype)
    zero = jnp.zeros((), dense.dtype)
    return jnp.where(valid, out, zero)


def _scatter_defined(dense: jax.Array, valid: jax.Array, *, n: int):
    """Spread ``dense`` non-null values to their row slots per ``valid``.

    ``out[i] = dense[rank(i)]`` where rank counts valid rows before ``i`` —
    a prefix-sum + gather, the deterministic TPU replacement for cuDF's
    atomically-compacted scatter.  Null slots get payload 0.  Both inputs
    are zero-padded to pow2 buckets (padding is invalid, so ranks are
    unchanged) to bound per-shape recompiles.
    """
    from ..ops.common import pow2_bucket
    nd = int(dense.shape[0])
    dpad = pow2_bucket(nd) - nd if nd else 0
    if dpad:
        dense = jnp.concatenate([dense, jnp.zeros(dpad, dense.dtype)])
    vpad = pow2_bucket(n) - n
    if vpad:
        valid = jnp.concatenate([valid, jnp.zeros(vpad, jnp.bool_)])
    return _scatter_defined_kernel(dense, valid)[:n]


# ---------------------------------------------------------------------------
# Page walk + chunk-fused decode
# ---------------------------------------------------------------------------

def _plain_fixed(values: bytes, phys: int, count: int,
                 type_length: int = 0) -> np.ndarray:
    if phys == T_FIXED_LEN_BYTE_ARRAY:
        # ≤8-byte FLBA decimals: big-endian two's-complement fold.
        raw = np.frombuffer(values, np.uint8,
                            count=count * type_length).reshape(count,
                                                               type_length)
        out = raw[:, 0].astype(np.int8).astype(np.int64)
        for i in range(1, type_length):
            out = (out << 8) | raw[:, i]
        return out
    np_dt = {T_INT32: "<i4", T_INT64: "<i8", T_FLOAT: "<f4",
             T_DOUBLE: "<f8"}[phys]
    return np.frombuffer(values, dtype=np_dt, count=count)


def _plain_byte_array(values: bytes, count: int) -> Tuple[np.ndarray, np.ndarray]:
    """PLAIN BYTE_ARRAY: [u32 len][bytes]... → (chars, offsets).

    Inherently sequential (each length depends on the previous end); done
    host-side.  Dictionary pages are small by construction; large PLAIN
    string chunks should use dictionary encoding (the writers' default).
    """
    offsets = np.zeros(count + 1, np.int32)
    chunks = []
    pos = 0
    for i in range(count):
        (ln,) = _struct.unpack_from("<I", values, pos)
        pos += 4
        chunks.append(values[pos:pos + ln])
        pos += ln
        offsets[i + 1] = offsets[i] + ln
    chars = np.frombuffer(b"".join(chunks), np.uint8)
    return chars, offsets


@dataclass
class _Dict:
    """Decoded dictionary page, device-resident, ready to gather from."""
    column: Optional[Column] = None     # STRING dictionaries
    values: Optional[jax.Array] = None  # fixed-width dictionaries
    raw: bytes = b""                    # decompressed page payload (identity
                                        # check for cross-chunk code fusion)
    np_chars: Optional[np.ndarray] = None    # host copies (STRING dicts):
    np_offsets: Optional[np.ndarray] = None  # cross-chunk union building


def _decode_dict_page(payload: bytes, info: ColumnInfo, count: int) -> _Dict:
    if info.physical == T_BYTE_ARRAY:
        chars, offsets = _plain_byte_array(payload, count)
        return _Dict(column=Column(data=jnp.asarray(chars),
                                   offsets=jnp.asarray(offsets),
                                   dtype=STRING), raw=payload,
                     np_chars=chars, np_offsets=offsets)
    if info.physical == T_BOOLEAN:
        raise ValueError("BOOLEAN columns are never dictionary-encoded")
    vals = _plain_fixed(payload, info.physical, count, info.type_length)
    return _Dict(values=jnp.asarray(vals), raw=payload)


@dataclass
class _PageSlice:
    """One data page, decompressed and located within its chunk."""
    row_base: int           # first row index within the chunk
    num_values: int         # rows this page covers (incl. nulls)
    def_base: int           # first defined-value index within the chunk
    n_defined: int          # non-null values in this page
    def_buf: Optional[bytes]
    encoding: int
    values: bytes
    def_runs: Optional[Dict[str, np.ndarray]] = None   # parsed def levels
    rep_levels: Optional[np.ndarray] = None   # LIST: expanded rep levels
    def_levels: Optional[np.ndarray] = None   # LIST: expanded def levels
    pruned: bool = False    # stats-skipped page: rows present, all null


def _all_null_runs(num_values: int) -> Dict[str, np.ndarray]:
    """Synthetic definition-level run table — one RLE run of value 0
    covering the whole page — so a stats-pruned page contributes all-null
    rows to the chunk's fused validity expansion without ever being
    decompressed."""
    return {"out_start": np.zeros(1, np.int32),
            "count": np.asarray([num_values], np.int64),
            "rle_value": np.zeros(1, np.int32),
            "bp_bit_base": np.zeros(1, np.int64),
            "is_rle": np.ones(1, np.bool_)}


def _page_kind(p: _PageSlice) -> str:
    if p.encoding in (E_PLAIN_DICTIONARY, E_RLE_DICTIONARY):
        return "dict"
    if p.encoding == E_PLAIN:
        return "plain"
    if p.encoding == E_RLE:
        return "rle_bool"
    raise NotImplementedError(
        f"value encoding {p.encoding} (DELTA_* need the Arrow reader)")


def _walk_pages(blob: bytes, chunk: ChunkInfo,
                preds: Sequence[LeafPred] = ()
                ) -> Tuple[Optional[_Dict], List[_PageSlice], int]:
    """Host pass over a chunk: headers, decompression, defined counts.

    Returns (dictionary, pages, total_rows).  The only value-scale work
    here is decompression and the width-1 popcount — both O(bytes) host
    passes with no device involvement.

    ``preds`` are the pushed-down leaf predicates constraining THIS
    column.  A page whose header statistics prove no row can match is
    never decompressed or uploaded — it enters the page list as an
    all-null placeholder (pruning one column's page cannot drop rows,
    because sibling columns' page boundaries don't align).  That is only
    sound for null-rejecting predicates on nullable flat columns: the
    placeholder nulls fail the full predicate when it re-runs downstream,
    so survivors are bit-identical to an unpruned read.
    """
    info = chunk.column
    # Page pruning requires: the column is optional (nulls are
    # representable) and flat, and every predicate on it is
    # null-rejecting (an ``is_null`` pushdown could newly match the
    # placeholder rows).  Required columns still get row-group pruning.
    prune_pages = bool(preds) and info.optional and not info.max_rep \
        and all(p.op in NULL_REJECTING_OPS for p in preds)
    pos = 0                     # blob is the chunk's own byte range
    remaining = chunk.num_values
    dictionary: Optional[_Dict] = None
    pages: List[_PageSlice] = []
    row_base = 0
    def_base = 0
    while remaining > 0:
        r = ThriftReader(blob, pos)
        header = r.read_struct()
        payload_start = r.pos
        ptype = header[1]
        comp_size = header[3]
        payload = blob[payload_start:payload_start + comp_size]
        pos = payload_start + comp_size
        if ptype == P_DICTIONARY:
            dph = header[7]
            body = _decompress(chunk.codec, payload, header[2])
            dictionary = _decode_dict_page(body, info, dph[1])
            continue
        if ptype == P_INDEX:
            continue
        if prune_pages and ptype in (P_DATA, P_DATA_V2):
            dph = header[5] if ptype == P_DATA else header[8]
            num_values = dph[1]
            try:
                st = _decode_stats(
                    dph.get(5 if ptype == P_DATA else 8), info, num_values,
                    exact_nulls=dph.get(2) if ptype == P_DATA_V2 else None)
            except Exception:
                st = None               # malformed stats: read the page
            if st is not None and not all(may_match(p, st) for p in preds):
                from ..obs.metrics import counter
                counter("scan.pages_skipped").inc()
                counter("scan.bytes_skipped").inc(comp_size)
                pages.append(_PageSlice(
                    row_base=row_base, num_values=num_values,
                    def_base=def_base, n_defined=0, def_buf=b"",
                    encoding=E_RLE_DICTIONARY, values=b"",
                    def_runs=_all_null_runs(num_values), pruned=True))
                row_base += num_values
                remaining -= num_values
                continue
        rep_buf = None
        if ptype == P_DATA:
            dph = header[5]
            num_values = dph[1]
            encoding = dph[2]
            def_enc = dph[3]
            body = _decompress(chunk.codec, payload, header[2])
            bpos = 0
            def_buf = None
            if info.max_rep:
                (rep_len,) = _struct.unpack_from("<I", body, bpos)
                bpos += 4
                rep_buf = body[bpos:bpos + rep_len]
                bpos += rep_len
            if info.optional or info.max_rep:
                if def_enc != E_RLE:
                    raise NotImplementedError(
                        f"definition-level encoding {def_enc} "
                        "(legacy BIT_PACKED)")
                (def_len,) = _struct.unpack_from("<I", body, bpos)
                bpos += 4
                def_buf = body[bpos:bpos + def_len]
                bpos += def_len
            values = body[bpos:]
        elif ptype == P_DATA_V2:
            dph = header[8]
            num_values = dph[1]
            encoding = dph[4]
            def_len = dph[5]
            rep_len = dph[6]
            if rep_len and not info.max_rep:
                raise NotImplementedError("repetition levels (nested data)")
            rep_buf = payload[:rep_len] if rep_len else None
            def_buf = payload[rep_len:rep_len + def_len] \
                if (info.optional or info.max_rep) else None
            rest = payload[rep_len + def_len:]
            is_compressed = dph.get(7, True)
            values = _decompress(chunk.codec, rest,
                                 header[2] - def_len - rep_len) \
                if is_compressed else rest
        else:
            raise NotImplementedError(f"page type {ptype}")

        def_runs = None
        rep_levels = def_levels = None
        if info.max_rep:
            # LIST column: expand both level streams on the host (levels
            # are <= 2-bit metadata; offsets/validity are host-built).
            rep_levels = _expand_levels_host(rep_buf, 1, num_values)
            def_bits = max(int(info.max_def).bit_length(), 1)
            def_levels = _expand_levels_host(def_buf, def_bits, num_values)
            n_defined = int((def_levels == info.max_def).sum())
        elif info.optional:
            if ptype == P_DATA_V2:
                n_defined = num_values - dph[2]     # num_nulls is exact in v2
            else:
                def_runs, n_defined = _parse_runs_and_ones(def_buf, 1,
                                                           num_values)
        else:
            n_defined = num_values
        pages.append(_PageSlice(row_base=row_base, num_values=num_values,
                                def_base=def_base, n_defined=n_defined,
                                def_buf=def_buf, encoding=encoding,
                                values=values, def_runs=def_runs,
                                rep_levels=rep_levels,
                                def_levels=def_levels))
        row_base += num_values
        def_base += n_defined
        remaining -= num_values
    return dictionary, pages, row_base


def _expand_dict_codes(pages: List[_PageSlice]) -> jax.Array:
    """Fuse a run of dictionary pages' RLE/bit-packed code streams into one
    device expansion (shared by the flat dict path and the deferred
    string-chunk path)."""
    base0 = pages[0].def_base
    n_dense = sum(p.n_defined for p in pages)
    m = RunMerger()
    for p in pages:
        m.add_stream(p.values[1:], p.values[0], p.n_defined,
                     p.def_base - base0)
    return m.expand(pages[0].values[0], n_dense)


def _chunk_validity(pages: List[_PageSlice], total_rows: int) -> jax.Array:
    """All pages' definition levels → one fused device expansion → bools."""
    m = RunMerger()
    for p in pages:
        m.add_stream(p.def_buf, 1, p.num_values, p.row_base, runs=p.def_runs)
    return m.expand(1, total_rows) != 0


def _dense_group(pages: List[_PageSlice], kind: str, info: ColumnInfo,
                 dictionary: Optional[_Dict]) -> Column:
    """Decode one contiguous run of same-kind pages into dense values.

    All pages of the group feed a single device expansion/gather (for the
    common single-kind chunk this is the whole chunk in one shot).
    """
    base0 = pages[0].def_base
    n_dense = sum(p.n_defined for p in pages)

    if kind == "dict":
        if dictionary is None:
            raise ValueError("dictionary-encoded page with no dictionary page")
        indices = _expand_dict_codes(pages)
        if dictionary.column is not None:
            return dictionary.column.gather(indices)
        return Column(data=dictionary.values[indices], dtype=info.dtype)

    if kind == "rle_bool":
        m = RunMerger()
        for p in pages:
            (rle_len,) = _struct.unpack_from("<I", p.values, 0)
            m.add_stream(p.values[4:4 + rle_len], 1, p.n_defined,
                         p.def_base - base0)
        return Column(data=m.expand(1, n_dense) != 0, dtype=BOOL8)

    # kind == "plain"
    if info.physical == T_BOOLEAN:
        m = RunMerger()
        for p in pages:
            m.add_raw_bits(p.values, p.def_base - base0)
        return Column(data=m.expand(1, n_dense) != 0, dtype=BOOL8)
    if info.physical == T_BYTE_ARRAY:
        char_parts = []
        offset_parts = [np.zeros(1, np.int32)]
        base = 0
        for p in pages:
            chars, offsets = _plain_byte_array(p.values, p.n_defined)
            char_parts.append(chars)
            offset_parts.append(offsets[1:] + base)
            base += int(offsets[-1])
        return Column(data=jnp.asarray(np.concatenate(char_parts)),
                      offsets=jnp.asarray(np.concatenate(offset_parts)),
                      dtype=STRING)
    blob = b"".join(p.values for p in pages)
    dense = jnp.asarray(_plain_fixed(blob, info.physical, n_dense,
                                     info.type_length))
    return Column(data=dense, dtype=info.dtype)


@dataclass
class _DictStrChunk:
    """A string chunk kept dictionary-ENCODED: int32 codes (+validity) and
    the dictionary.  The expensive string gather (one host sync for char
    totals inside strings_gather) is deferred to the whole-column level:
    when every chunk of a column shares one dictionary — the overwhelmingly
    common writer behavior — codes concatenate on device and ONE gather
    materializes the column, instead of a sync per chunk plus a host-side
    string concat (profiled at ~8.6 s of a 13.8 s 4M-row read through the
    tunneled device)."""
    codes: Column               # INT32 (+validity), chunk-length
    dict_: _Dict


def _decode_chunk(blob: bytes, chunk: ChunkInfo,
                  preds: Sequence[LeafPred] = ()):
    """One column chunk → one device Column (or a deferred
    :class:`_DictStrChunk` for single-dictionary string chunks).

    ``preds`` (this column's pushed-down predicates) drive page-level
    stats pruning in the page walk: pruned pages surface as all-null
    rows, never as dropped rows — see :func:`_walk_pages`."""
    info = chunk.column
    dictionary, pages, total_rows = _walk_pages(blob, chunk, preds)
    if not pages:
        return _empty_column(info.dtype)
    # Pruned placeholders contribute rows (all null) to validity/offsets
    # but no dense values — only real pages feed the value decode.
    real = [p for p in pages if not p.pruned]

    if info.max_rep:
        return _decode_list_chunk(info, dictionary, pages)

    if (info.dtype == STRING and dictionary is not None
            and all(_page_kind(p) == "dict" for p in real)):
        n_dense = sum(p.n_defined for p in pages)
        dense_codes = _expand_dict_codes(real).astype(jnp.int32) if real \
            else jnp.zeros(0, jnp.int32)
        codes = Column(data=dense_codes, dtype=INT32)
        if info.optional and n_dense != total_rows:
            valid = _chunk_validity(pages, total_rows)
            codes = Column(data=_scatter_defined(codes.data, valid,
                                                 n=total_rows),
                           validity=valid, dtype=INT32)
        return _DictStrChunk(codes=codes, dict_=dictionary)

    # Group contiguous same-kind pages (a chunk is a single group unless the
    # writer fell back from dictionary to PLAIN mid-chunk).
    groups: List[Tuple[str, List[_PageSlice]]] = []
    for p in real:
        kind = _page_kind(p)
        if groups and groups[-1][0] == kind:
            groups[-1][1].append(p)
        else:
            groups.append((kind, [p]))
    parts = [_dense_group(ps, kind, info, dictionary) for kind, ps in groups]
    if not parts:                       # every page of the chunk pruned
        dense_col = _empty_column(info.dtype)
    else:
        dense_col = parts[0] if len(parts) == 1 else _concat_columns(parts)

    # Physical → logical representation (uint/timestamp converted types are
    # stored in the signed physical lanes; same-width casts reinterpret).
    if dense_col.offsets is None:
        target = info.dtype.jnp_dtype
        if dense_col.data.dtype != target:
            dense_col = Column(data=dense_col.data.astype(target),
                               dtype=info.dtype)
        elif dense_col.dtype != info.dtype:
            dense_col = Column(data=dense_col.data, dtype=info.dtype)

    if not info.optional:
        return dense_col
    if sum(p.n_defined for p in pages) == total_rows:
        # No nulls anywhere in the chunk — known host-side from the page
        # walk, so the def-level expansion and null scatter are skipped
        # entirely (and the column carries validity=None, matching the
        # Arrow reader, with no device sync needed downstream).
        return dense_col
    valid = _chunk_validity(pages, total_rows)

    if dense_col.offsets is not None:
        if dense_col.size == 0:             # all rows null
            return Column(data=dense_col.data, validity=valid,
                          offsets=jnp.zeros(total_rows + 1, jnp.int32),
                          dtype=STRING)
        # Valid rows take successive dense rows IN ORDER, so their extents
        # tile the dense char buffer exactly: the buffer is reused as-is and
        # only the offsets are rebuilt, with zero-length extents at nulls.
        rank = jnp.cumsum(valid.astype(jnp.int32)) - 1
        safe = jnp.clip(rank, 0, max(dense_col.size - 1, 0))
        dense_lens = dense_col.offsets[1:] - dense_col.offsets[:-1]
        lens = jnp.where(valid, dense_lens[safe], 0)
        offsets = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                   jnp.cumsum(lens, dtype=jnp.int32)])
        return Column(data=dense_col.data, validity=valid, offsets=offsets,
                      dtype=STRING)
    data = _scatter_defined(dense_col.data, valid, n=total_rows)
    return Column(data=data, validity=valid, dtype=info.dtype)


def _decode_list_chunk(info: ColumnInfo, dictionary: Optional[_Dict],
                       pages: List[_PageSlice]) -> Column:
    """LIST column chunk: element values decode through the same fused
    device machinery as flat columns; offsets and validity come from the
    host-expanded repetition/definition levels (rep == 0 starts a row;
    def distinguishes null list / empty list / null element / value)."""
    from dataclasses import replace as _dc_replace
    elem_dt = info.dtype.element
    einfo = _dc_replace(info, dtype=elem_dt, optional=info.element_optional,
                        max_rep=0, max_def=0)

    groups: List[Tuple[str, List[_PageSlice]]] = []
    for pg in pages:
        kind = _page_kind(pg)
        if groups and groups[-1][0] == kind:
            groups[-1][1].append(pg)
        else:
            groups.append((kind, [pg]))
    parts = [_dense_group(ps, kind, einfo, dictionary)
             for kind, ps in groups]
    dense = parts[0] if len(parts) == 1 else _concat_columns(parts)
    if dense.offsets is None:
        target = elem_dt.jnp_dtype
        if dense.data.dtype != target:
            dense = Column(data=dense.data.astype(target), dtype=elem_dt)
        elif dense.dtype != elem_dt:
            dense = Column(data=dense.data, dtype=elem_dt)

    rep = np.concatenate([pg.rep_levels for pg in pages])
    deff = np.concatenate([pg.def_levels for pg in pages])
    base = 1 if info.optional else 0
    is_row = rep == 0
    n_rows = int(is_row.sum())
    row_ids = np.cumsum(is_row) - 1
    elem_slot = deff >= base + 1
    lens = np.bincount(row_ids[elem_slot],
                       minlength=max(n_rows, 1))[:max(n_rows, 1)]
    if n_rows == 0:
        lens = lens[:0]
    offsets = np.concatenate([np.zeros(1, np.int64),
                              np.cumsum(lens)]).astype(np.int32)

    validity = None
    if info.optional:
        row_def = deff[is_row]
        vr = row_def >= base
        if not vr.all():
            validity = jnp.asarray(vr)

    if info.element_optional:
        edef = deff[elem_slot]
        if (edef != info.max_def).any():
            if dense.offsets is not None:
                raise NotImplementedError(
                    "lists of strings with null elements need the "
                    "Arrow reader")
            evalid = jnp.asarray(edef == info.max_def)
            n_slots = int(elem_slot.sum())
            data = _scatter_defined(dense.data, evalid, n=n_slots)
            dense = Column(data=data, validity=evalid, dtype=elem_dt)

    return Column(offsets=jnp.asarray(offsets), validity=validity,
                  dtype=info.dtype, children=(dense,))


def _empty_column(dtype: DType) -> Column:
    if dtype == STRING:
        return Column(data=jnp.zeros(0, jnp.uint8),
                      offsets=jnp.zeros(1, jnp.int32), dtype=STRING)
    return Column(data=jnp.zeros(0, dtype.jnp_dtype), dtype=dtype)


def _concat_columns(pieces: Sequence[Column]) -> Column:
    from ..ops.common import concat_columns
    return concat_columns(list(pieces))


def row_group_row_counts(path) -> List[int]:
    """Per-row-group row counts from the footer alone (no page IO).

    Scan drivers use this to pick a bucket-aligned coalesce target for
    :func:`spark_rapids_tpu.io.feed.scan_parquet`: coalescing row groups
    up to ``exec.bucketing.bucket_capacity`` of the typical group length
    makes consecutive batches land in one shape bucket, so the whole scan
    executes under a single compiled program.  Raises
    ``NotImplementedError`` outside the native envelope (callers fall back
    to the Arrow reader's metadata).
    """
    _, row_groups = read_metadata(path)
    out = []
    for rg in row_groups:
        # A flat chunk's num_values (nulls included) equals the group's
        # row count; LIST chunks count elements, so prefer a flat one.
        flat = [c for c in rg if c.column.max_rep == 0]
        chunk = flat[0] if flat else rg[0]
        out.append(chunk.num_values)
    return out


def scan_predicate_leaves(predicate) -> Tuple[LeafPred, ...]:
    """Normalize any accepted ``predicate`` argument (Expr, filter
    tuples, LeafPreds, None) to the leaf conjunction, honoring the
    ``SRT_SCAN_PRUNE`` kill switch (off → no leaves → no pruning)."""
    if predicate is None:
        return ()
    from ..config import scan_prune
    if not scan_prune():
        return ()
    from .pushdown import extract_scan_predicates
    return extract_scan_predicates(predicate)


def group_stats(rg: List[ChunkInfo]) -> Dict[str, Optional[ColumnStats]]:
    """Footer statistics of one row group, keyed by column name (flat
    columns only — LIST chunk stats describe elements, not rows)."""
    return {c.column.name: c.stats for c in rg if c.column.max_rep == 0}


def read_parquet_native(path, columns: Optional[Sequence[str]] = None,
                        predicate=None) -> Table:
    """Read a Parquet file via the native page decoder into a device Table.

    Column pruning prunes IO: only the selected chunks' byte ranges are
    read from the file.  ``predicate`` (an ``exec.expr`` tree, pandas-style
    filter tuples, or :class:`~.pushdown.LeafPred` leaves) prunes further:
    row groups whose footer statistics prove no match are never read, and
    non-qualifying pages are never decompressed or uploaded.  Pruning is
    group/page granular and page-pruned rows surface as nulls, so the
    CALLER MUST still apply the full predicate to the result — the engine's
    plan layer always does (pushdown never removes the filter step).
    Raises ``NotImplementedError`` for shapes outside the supported
    envelope (nested schemas, INT96, DELTA encodings) — callers fall back
    to the Arrow-backed :func:`spark_rapids_tpu.io.parquet.read_parquet`.
    """
    from ..obs.metrics import counter, timer
    from .pushdown import group_may_match, predicates_for_column
    preds = scan_predicate_leaves(predicate)
    with timer("io.parquet.read").time():
        cols, row_groups = read_metadata(path)
        want = (list(columns) if columns is not None
                else [c.name for c in cols])
        missing = set(want) - {c.name for c in cols}
        if missing:
            raise KeyError(f"columns not in file: {sorted(missing)}")
        col_preds = {name: predicates_for_column(preds, name)
                     for name in want}
        per_name: Dict[str, List] = {name: [] for name in want}
        bytes_read = 0
        bytes_skipped = 0
        groups_read = 0
        groups_skipped = 0
        decode_s = 0.0
        with open(path, "rb") as f:
            for rg in row_groups:
                if preds and not group_may_match(group_stats(rg), preds):
                    groups_skipped += 1
                    bytes_skipped += sum(c.total_compressed for c in rg
                                         if c.column.name in per_name)
                    continue
                groups_read += 1
                for chunk in rg:
                    if chunk.column.name not in per_name:
                        continue
                    f.seek(chunk.start_offset)
                    chunk_bytes = f.read(chunk.total_compressed)
                    bytes_read += len(chunk_bytes)
                    t0 = _time.perf_counter()
                    piece = _decode_chunk(chunk_bytes, chunk,
                                          col_preds[chunk.column.name])
                    decode_s += _time.perf_counter() - t0
                    per_name[chunk.column.name].append(piece)
        dtypes_by_name = {c.name: c.dtype for c in cols}
        out = []
        for name in want:
            pieces = per_name[name]
            if not pieces:       # zero row groups in (or surviving) the file
                col = _empty_column(dtypes_by_name[name])
            elif all(isinstance(x, _DictStrChunk) for x in pieces):
                col = _fuse_dict_str_chunks(pieces)
            else:
                mats = [_materialize_piece(x) for x in pieces]
                col = mats[0] if len(mats) == 1 else _concat_columns(mats)
            out.append((name, col))
        t = Table(out)
        counter("io.parquet.files").inc()
        counter("io.parquet.row_groups").inc(groups_read)
        counter("io.parquet.rows").inc(t.num_rows)
        counter("io.parquet.columns").inc(t.num_columns)
        counter("io.parquet.bytes_read").inc(bytes_read)
        if groups_skipped:
            counter("scan.row_groups_skipped").inc(groups_skipped)
        if bytes_skipped:
            counter("scan.bytes_skipped").inc(bytes_skipped)
        if decode_s > 0:
            counter("scan.decode.us").inc(int(decode_s * 1e6))
    return t


def _dict_words(d: _Dict) -> List[bytes]:
    """A string dictionary's entries, in file (first-occurrence) order."""
    n_entries = 0 if d.np_offsets is None else len(d.np_offsets) - 1
    return [d.np_chars[d.np_offsets[i]:d.np_offsets[i + 1]].tobytes()
            for i in range(n_entries)]


def _sorted_rank(words: List[bytes]) -> Optional[np.ndarray]:
    """Old-code → sorted-code remap for a vocabulary, or None when the
    vocabulary is already ascending (identity remap)."""
    order = sorted(range(len(words)), key=words.__getitem__)
    if order == list(range(len(words))):
        return None
    rank = np.empty(len(words), np.int32)
    rank[np.asarray(order)] = np.arange(len(words), dtype=np.int32)
    return rank


def _strings_from_words(words: List[bytes]) -> Column:
    chars = np.concatenate([np.frombuffer(w, np.uint8) for w in words]
                           or [np.zeros(0, np.uint8)])
    lens = np.asarray([len(w) for w in words], np.int64)
    offsets = np.concatenate([np.zeros(1, np.int64),
                              np.cumsum(lens)]).astype(np.int32)
    return Column(data=jnp.asarray(chars), offsets=jnp.asarray(offsets),
                  dtype=STRING)


def _register_scan_encoding(col: Column, codes: Column,
                            words: List[bytes]) -> None:
    """Hand a scan-built (codes, sorted vocab) pair to the encoded-
    residency registry (ops/strings.py) keyed on the materialized
    column's buffers, so the plan binder's ``dictionary_encode_cached``
    reuses the scan's encoding instead of a host np.unique pass.

    The vocabulary must already be ascending (``dictionary_encode``'s
    contract — ``scalar_cut`` bisects it).  Non-UTF-8 entries (spec
    violation) simply skip registration; results are unaffected.
    """
    from ..obs.metrics import counter
    from ..ops.strings import register_resident_encoding
    try:
        uniq = tuple(w.decode("utf-8") for w in words)
    except UnicodeDecodeError:
        return
    register_resident_encoding(col, codes, uniq)
    counter("scan.encoded_cols").inc()


def _fuse_dict_str_chunks(pieces: List["_DictStrChunk"]) -> Column:
    """Whole-column string materialization from per-chunk codes.

    Row groups write independent dictionaries (same vocabulary, but entry
    order follows each group's first-occurrence order), so chunk codes are
    NOT directly comparable.  The dictionaries are host-resident and tiny
    (O(vocabulary)), so a union dictionary + per-chunk int32 remap is
    built on the host; each chunk's codes remap with one small device
    gather, the remapped codes concatenate on device, and ONE string
    gather (the single host sync of the whole column) materializes the
    result.  Before this fusion the reader paid a sync per chunk plus a
    host-side string concat — profiled at ~10 s of a 4M-row read.

    Under ``SRT_ENCODED_EXEC`` the union vocabulary is additionally
    ranked into ascending byte order (== code-point order) and the
    (codes, vocab) pair is registered with the encoded-residency
    registry, keyed on the materialized column — downstream code-domain
    execution then starts from the scan's encoding for free.
    """
    from ..config import encoded_exec
    from ..obs.metrics import counter
    encoded = encoded_exec()
    same_raw = len({x.dict_.raw for x in pieces}) == 1
    vocab: Dict[bytes, int] = {}
    remaps: List[Optional[np.ndarray]] = []
    words_all: Optional[List[bytes]] = None
    if same_raw:
        # Fast path: identical dictionaries need no vocab/remap at all —
        # only emptiness matters (all-null column).
        d0 = pieces[0].dict_
        if d0.np_offsets is None or len(d0.np_offsets) <= 1:
            from ..column import all_null_column
            return all_null_column(STRING,
                                   sum(x.codes.size for x in pieces))
        remaps = [np.zeros(0, np.int32)] * len(pieces)   # unused markers
        if encoded:
            words_all = _dict_words(d0)
    else:
        for x in pieces:
            words = _dict_words(x.dict_)
            if not words:
                remaps.append(None)
                continue
            remaps.append(np.asarray(
                [vocab.setdefault(w, len(vocab)) for w in words], np.int32))
        if not vocab:                    # every chunk all-null
            from ..column import all_null_column
            return all_null_column(STRING,
                                   sum(x.codes.size for x in pieces))
        words_all = list(vocab)

    rank = None
    if encoded and words_all is not None:
        # Ascending vocabulary for the residency registry: compose every
        # chunk remap with the sort ranking (identity when the writer
        # already sorted — then the original codes are reused as-is).
        rank = _sorted_rank(words_all)
        if rank is not None:
            words_all = sorted(words_all)
            if same_raw:
                remaps = [rank] * len(pieces)
            else:
                remaps = [None if r is None else rank[r] for r in remaps]

    code_cols = []
    for x, remap in zip(pieces, remaps):
        c = x.codes
        if remap is None:                # all-null chunk: any in-range code
            code_cols.append(Column(data=jnp.zeros(c.size, jnp.int32),
                                    validity=c.validity, dtype=INT32))
        elif same_raw and (rank is None or remap is not rank):
            code_cols.append(c)          # identical dicts: codes line up
        elif remap.size == 0:
            code_cols.append(c)
        else:
            code_cols.append(Column(
                data=jnp.take(jnp.asarray(remap), c.data, mode="clip"),
                validity=c.validity, dtype=INT32))

    codes = code_cols[0] if len(code_cols) == 1 \
        else _concat_columns(code_cols)
    if same_raw and rank is None:
        union_col = pieces[0].dict_.column
    else:
        union_col = _strings_from_words(words_all)
    t0 = _time.perf_counter()
    col = union_col.gather(codes.data)
    if codes.validity is not None:
        col = col.with_validity(codes.validity if col.validity is None
                                else (col.validity & codes.validity))
    counter("scan.gather.us").inc(int((_time.perf_counter() - t0) * 1e6))
    if encoded and words_all is not None:
        _register_scan_encoding(col, codes, words_all)
    return col


def _materialize_piece(piece) -> Column:
    """Per-chunk string gather for the rare multi-dictionary column."""
    if isinstance(piece, Column):
        return piece
    return _gather_dict_strings(piece.dict_, piece.codes)


def _gather_dict_strings(d: _Dict, codes: Column) -> Column:
    """Codes -> strings; an empty dictionary (all-null chunk) cannot be
    gathered from and yields an all-null column directly.

    Under ``SRT_ENCODED_EXEC`` the chunk's dictionary is ranked into
    ascending order and the (codes, vocab) pair registered with the
    encoded-residency registry, same as the whole-column fusion path —
    this is what the row-group-streaming feed (io/feed.py) hits.
    """
    from ..obs.metrics import counter
    if d.column.size == 0:
        from ..column import all_null_column
        return all_null_column(STRING, codes.size)
    from ..config import encoded_exec
    encoded = encoded_exec() and d.np_offsets is not None
    words = _dict_words(d) if encoded else None
    rank = _sorted_rank(words) if encoded else None
    if rank is not None:
        words = sorted(words)
        codes = Column(data=jnp.take(jnp.asarray(rank), codes.data,
                                     mode="clip"),
                       validity=codes.validity, dtype=INT32)
        dict_col = _strings_from_words(words)
    else:
        dict_col = d.column
    t0 = _time.perf_counter()
    col = dict_col.gather(codes.data)
    if codes.validity is not None:
        col = col.with_validity(codes.validity if col.validity is None
                                else (col.validity & codes.validity))
    counter("scan.gather.us").inc(int((_time.perf_counter() - t0) * 1e6))
    if encoded:
        _register_scan_encoding(col, codes, words)
    return col
