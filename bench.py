"""Benchmark: fixed-width row <-> columnar transpose throughput.

BASELINE.json config #1: "row<->columnar transpose microbench (1M-row int64
column) — CPU baseline via Spark UnsafeRow".  Measures the flagship path
(the reference's row_conversion.cu:458-575 equivalent) as a chained
pack->unpack round trip and compares against an in-process CPU baseline
packing the same table the way Spark's UnsafeRow writer does
(vectorized-numpy upper bound).  Deliberate deviation from the config's 1M
qualifier: 4M rows — at 1M the measurement is dominated by the ~2ms
per-dispatch latency of the tunneled TPU, not the kernels; both sides (TPU
and CPU baseline) use the same 4M-row table so the ratio stays meaningful.
BASELINE.md records the protocol and history.

Measurement discipline (learned the hard way on the tunneled TPU):

  * pack and unpack run as SEPARATE jitted programs — fusing them in one
    program lets XLA algebraically cancel the round trip into a copy,
  * every iteration's input depends on the previous iteration's output (a
    data-dependent scalar perturbation), so no execution can be served from
    any repeated-computation cache and the chain is truly serialized,
  * the clock stops only after a device->host read of the final result
    (``block_until_ready`` alone under-waits through the remote tunnel).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

N_ROWS = 4_000_000
REPS = 48


def _make_inputs(rng):
    import jax.numpy as jnp

    from spark_rapids_tpu.dtypes import (BOOL8, FLOAT32, FLOAT64, INT8, INT32,
                                         INT64, decimal32, decimal64)

    schema = (INT64, FLOAT64, INT32, BOOL8, FLOAT32, INT8,
              decimal32(-3), decimal64(-8))
    np_datas = (
        rng.integers(-1 << 40, 1 << 40, N_ROWS).astype(np.int64),
        rng.normal(size=N_ROWS),
        rng.integers(-1 << 20, 1 << 20, N_ROWS).astype(np.int32),
        rng.integers(0, 2, N_ROWS).astype(np.bool_),
        rng.normal(size=N_ROWS).astype(np.float32),
        rng.integers(-128, 128, N_ROWS).astype(np.int8),
        rng.integers(-1 << 20, 1 << 20, N_ROWS).astype(np.int32),
        rng.integers(-1 << 40, 1 << 40, N_ROWS).astype(np.int64),
    )
    np_masks = tuple(rng.integers(0, 4, N_ROWS) > 0 for _ in schema)
    datas = tuple(jnp.asarray(d) for d in np_datas)
    masks = tuple(jnp.asarray(m) for m in np_masks)
    return schema, np_datas, np_masks, datas, masks


def bench_device(schema, datas, masks):
    """Chained pack->unpack round trips (separate jitted programs).

    Two dispatches per iteration: the data-dependent perturbation (+0/+1
    derived from the previous words) is FUSED into the pack program — a
    separate perturb jit measured ~2.2 ms of pure dispatch latency per
    iteration through the tunneled device.  REPS is sized to amortize the
    fixed end-of-chain host-read fence, measured ~95-120 ms through the
    tunnel (BASELINE.md "transpose roofline analysis"): at 8 reps the
    fence alone halves the reported throughput; at 48 it costs ~10%.
    """
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.rows.layout import compute_fixed_width_layout
    from spark_rapids_tpu.rows.image import pack_image, unpack_image

    layout = compute_fixed_width_layout(schema)

    @jax.jit
    def pack_chained(d, v, prev_words):
        bump = (prev_words[0, -1] & jnp.uint32(1)).astype(d[0].dtype)
        return pack_image(layout, (d[0] + bump,) + tuple(d[1:]), v)

    @jax.jit
    def unpack_step(words):
        return unpack_image(layout, words)

    W = layout.row_size // 4
    words = jnp.zeros((W, N_ROWS), jnp.uint32)
    d, v = datas, masks
    # Warm the EXACT loop composition (in-loop calls see the unpack
    # outputs' buffer layouts; a re-specialized compile must happen
    # outside the timed region).
    for _ in range(2):
        words = pack_chained(d, v, words)
        d, v = unpack_step(words)
    _ = np.asarray(d[0][-1:])                             # force completion

    t0 = time.perf_counter()
    for _ in range(REPS):
        words = pack_chained(d, v, words)
        d, v = unpack_step(words)
    _ = np.asarray(d[0][-1:])                             # host read = fence
    dt = (time.perf_counter() - t0) / REPS
    return N_ROWS / dt


def bench_cpu_baseline(schema, np_datas, np_masks):
    """CPU UnsafeRow-style pack+unpack: per-field stores into a row image.

    Vectorized numpy structured-array formulation — per-column strided
    stores into the row-major buffer plus bit-packed validity — which is
    the optimistic upper bound on Spark's row-at-a-time UnsafeRow writer.
    """
    from spark_rapids_tpu.rows.layout import compute_fixed_width_layout

    layout = compute_fixed_width_layout(schema)

    def round_trip():
        image = np.zeros((N_ROWS, layout.row_size), np.uint8)
        for d, start, size in zip(np_datas, layout.column_starts,
                                  layout.column_sizes):
            image[:, start:start + size] = (
                d.view((np.uint8, d.dtype.itemsize))
                if d.dtype != np.bool_ else d[:, None].astype(np.uint8))
        valid = np.stack(np_masks, axis=1)
        packed = np.packbits(valid, axis=1, bitorder="little")
        image[:, layout.validity_offset:
              layout.validity_offset + layout.validity_bytes] = packed
        # Unpack back to columns.
        outs = []
        for dt, start, size in zip(schema, layout.column_starts,
                                   layout.column_sizes):
            raw = np.ascontiguousarray(image[:, start:start + size])
            outs.append(raw.view(dt.np_dtype)[:, 0])
        vb = image[:, layout.validity_offset:
                   layout.validity_offset + layout.validity_bytes]
        np.unpackbits(vb, axis=1, bitorder="little", count=len(schema))
        return outs

    round_trip()   # warm caches
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        round_trip()
    dt = (time.perf_counter() - t0) / reps
    return N_ROWS / dt


def main():
    rng = np.random.default_rng(20260729)
    schema, np_datas, np_masks, datas, masks = _make_inputs(rng)
    device_rps = bench_device(schema, datas, masks)
    cpu_rps = bench_cpu_baseline(schema, np_datas, np_masks)
    print(json.dumps({
        "metric": "row_columnar_transpose_roundtrip_4M",
        "value": round(device_rps, 1),
        "unit": "rows/sec",
        "vs_baseline": round(device_rps / cpu_rps, 3),
    }))
    from spark_rapids_tpu.config import metrics_enabled
    if metrics_enabled():
        from spark_rapids_tpu.obs import bench_cache_line, bench_metrics_line
        print(bench_metrics_line())
        print(bench_cache_line())


if __name__ == "__main__":
    main()
