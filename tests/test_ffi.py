"""Native C++ bridge parity tests.

The native host library (native/src/) must agree byte-for-byte with the
JAX/device path: same layout (rows/layout.py), same pack bytes, same
round-trip semantics, same error behavior (the JNI contract of the
reference's RowConversionJni.cpp re-expressed over a C ABI).
"""

import numpy as np
import pytest

from spark_rapids_tpu import Table, ffi
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.rows import to_rows
from spark_rapids_tpu.rows.layout import compute_fixed_width_layout

from test_row_conversion import reference_test_table

SCHEMAS = [
    (dt.INT8,),
    (dt.INT64, dt.INT8, dt.INT16, dt.INT32),
    (dt.BOOL8, dt.FLOAT64, dt.UINT16),
    (dt.INT64, dt.FLOAT64, dt.INT32, dt.BOOL8, dt.FLOAT32, dt.INT8,
     dt.decimal32(-3), dt.decimal64(-8)),
    tuple([dt.INT8] * 9),                      # >8 cols -> 2 validity bytes
    (dt.TIMESTAMP_MICROSECONDS, dt.DURATION_DAYS, dt.UINT64),
    tuple([dt.FLOAT32] * 17),                  # 3 validity bytes
]


def table_buffers(table):
    schema = tuple(table.schema())
    datas, valids = [], []
    for _name, col in table.items():
        vals, mask = col.to_numpy()
        datas.append(np.ascontiguousarray(vals))
        valids.append(None if mask is None else np.ascontiguousarray(mask))
    return schema, datas, valids


@pytest.mark.parametrize("schema", SCHEMAS)
def test_layout_parity(schema):
    py = compute_fixed_width_layout(schema)
    nat = ffi.compute_fixed_width_layout(schema)
    assert nat["column_starts"] == py.column_starts
    assert nat["column_sizes"] == py.column_sizes
    assert nat["validity_offset"] == py.validity_offset
    assert nat["validity_bytes"] == py.validity_bytes
    assert nat["row_size"] == py.row_size


def test_pack_bytes_match_device_path():
    table = reference_test_table()
    schema, datas, valids = table_buffers(table)
    native = ffi.pack_rows(schema, datas, valids)
    [blob] = to_rows(table)
    device = np.asarray(blob.data)
    assert native.tobytes() == device.tobytes()


def test_pack_unpack_round_trip():
    table = reference_test_table()
    schema, datas, valids = table_buffers(table)
    rows = ffi.pack_rows(schema, datas, valids)
    out_datas, out_valids = ffi.unpack_rows(schema, rows, table.num_rows)
    for dtp, src, valid, out, out_valid in zip(schema, datas, valids,
                                               out_datas, out_valids):
        np.testing.assert_array_equal(np.asarray(src).view(out.dtype), out)
        expect = np.ones(table.num_rows, bool) if valid is None else valid
        np.testing.assert_array_equal(expect.astype(bool), out_valid)


def test_pack_parity_random_wide(rng):
    n = 1000
    schema = (dt.INT64, dt.INT16, dt.FLOAT32, dt.UINT8, dt.FLOAT64, dt.BOOL8,
              dt.INT32, dt.UINT32, dt.INT8, dt.UINT64, dt.decimal64(2))
    datas = [
        rng.integers(-1 << 40, 1 << 40, n).astype(np.int64),
        rng.integers(-1 << 10, 1 << 10, n).astype(np.int16),
        rng.normal(size=n).astype(np.float32),
        rng.integers(0, 256, n).astype(np.uint8),
        rng.normal(size=n),
        rng.integers(0, 2, n).astype(np.bool_),
        rng.integers(-1 << 20, 1 << 20, n).astype(np.int32),
        rng.integers(0, 1 << 20, n).astype(np.uint32),
        rng.integers(-128, 128, n).astype(np.int8),
        rng.integers(0, 1 << 40, n).astype(np.uint64),
        rng.integers(-1 << 40, 1 << 40, n).astype(np.int64),
    ]
    valids = [rng.integers(0, 4, n) > 0 for _ in schema]
    valids[3] = None  # one all-valid column exercises the nullptr mask path

    native = ffi.pack_rows(schema, datas, valids)

    cols = {}
    for i, (dtp, data, valid) in enumerate(zip(schema, datas, valids)):
        from spark_rapids_tpu import Column
        import jax.numpy as jnp
        cols[f"c{i}"] = Column(
            data=jnp.asarray(data), dtype=dtp,
            validity=None if valid is None else jnp.asarray(valid))
    [blob] = to_rows(Table(list(cols.items())))
    assert native.tobytes() == np.asarray(blob.data).tobytes()


def test_convert_to_rows_batching():
    n = 257
    schema = (dt.INT64, dt.INT32)
    rng = np.random.default_rng(3)
    datas = [rng.integers(0, 1 << 30, n).astype(np.int64),
             rng.integers(0, 1 << 20, n).astype(np.int32)]
    valids = [rng.integers(0, 2, n).astype(np.bool_), None]
    layout = compute_fixed_width_layout(schema)

    # Cap small enough to force splitting: 64 rows per blob (multiple of 32).
    cap = layout.row_size * 70
    blobs = ffi.convert_to_rows(schema, datas, valids, max_batch_bytes=cap)
    rows_per_blob = [b.size // layout.row_size for b in blobs]
    assert sum(rows_per_blob) == n
    assert all(r % 32 == 0 for r in rows_per_blob[:-1])
    assert all(r * layout.row_size <= cap for r in rows_per_blob)

    whole = ffi.pack_rows(schema, datas, valids)
    assert b"".join(b.tobytes() for b in blobs) == whole.tobytes()


def test_convert_to_rows_empty():
    schema = (dt.INT64,)
    blobs = ffi.convert_to_rows(schema, [np.zeros(0, np.int64)], [None])
    assert len(blobs) == 1 and blobs[0].size == 0


def test_errors():
    with pytest.raises(ValueError, match="fixed width"):
        ffi.compute_fixed_width_layout((dt.STRING,))
    schema = (dt.INT64,)
    with pytest.raises(ValueError, match="layout of the data"):
        ffi.unpack_rows(schema, np.zeros(7, np.uint8), 1)
    wide = tuple([dt.FLOAT64] * 200)  # row_size > 1 KB
    datas = [np.zeros(4) for _ in wide]
    with pytest.raises(ValueError, match="1 KB"):
        ffi.convert_to_rows(wide, datas, [None] * len(wide))
    # liftable, as in the device path
    blobs = ffi.convert_to_rows(wide, datas, [None] * len(wide),
                                check_row_width=False)
    assert len(blobs) == 1


def test_buffer_validation():
    schema = (dt.INT64, dt.INT64)
    a = np.zeros(8, np.int64)
    with pytest.raises(ValueError, match="expected shape"):
        ffi.pack_rows(schema, [a, np.zeros(5, np.int64)], [None, None])
    with pytest.raises(ValueError, match="does not match"):
        ffi.pack_rows(schema, [a, np.zeros(8, np.int32)], [None, None])
    with pytest.raises(ValueError, match="validity shape"):
        ffi.pack_rows(schema, [a, a], [None, np.zeros(3, np.uint8)])
    with pytest.raises(ValueError, match="buffers for"):
        ffi.convert_to_rows(schema, [a], [None])


def test_build_info():
    info = ffi.build_info()
    assert "version" in info and "revision" in info
    assert ffi.load().srt_version().decode() == info["version"]
