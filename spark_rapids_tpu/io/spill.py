"""Parquet spill-file store — the disk tier of out-of-core execution.

The spill manager (resilience/spill.py) pages cold device partitions to
host RAM first; when the host tier's ``SRT_SPILL_HOST_BYTES`` cap
overflows, the oldest pages land here as Parquet files in
``SRT_SPILL_DIR``.  Each page is an arbitrary pytree's leaves: one
Parquet row per leaf, carrying the raw little-endian bytes, the dtype
string, and the shape — enough to reconstruct every numpy array exactly
(bit-identical round trip) without the store knowing anything about
Tables or accumulators.

Robustness contract (mirrors io/feed.py's ``_read_retry``):

  * every write/read runs under :func:`~..resilience.with_retries`
    against transient-IO classification, with seeded fault sites
    ``spill-write`` / ``spill-read`` (``SRT_FAULT=io:spill-write:N``);
  * when ``SRT_STREAM_TIMEOUT`` is set, each attempt additionally runs
    under the stall watchdog (:func:`~..resilience.dist_guard`), so a
    wedged disk raises a named ``DistStallError`` instead of hanging
    the ladder;
  * writes are atomic (tmp + ``os.replace``) — a crash mid-write leaves
    a ``.tmp`` orphan, never a truncated page;
  * the directory is count- and byte-capped; overflow raises
    :class:`SpillCapacityError` (fatal-classified), the honest-failure
    path;
  * filenames embed the owning pid (``srt-spill-<pid>-<n>.parquet``)
    and startup sweeps only orphans whose pid is DEAD, so concurrent
    processes share one spill directory safely.

Heavy imports (pyarrow, numpy) are function-local: importing this module
costs nothing on hosts that never spill.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..config import spill_dir, stream_timeout
from ..resilience import CATEGORY_IO, dist_guard, fault_point, with_retries

#: Most spill files the store keeps before refusing (honest failure
#: instead of filling a disk); constructor-overridable.
MAX_SPILL_FILES = 1024

#: Byte cap across all live spill files; constructor-overridable.
MAX_SPILL_BYTES = 16 << 30

_FILE_PREFIX = "srt-spill-"
_FILE_SUFFIX = ".parquet"


class SpillCapacityError(ValueError):
    """The spill directory's count/byte cap is exhausted — deliberately
    a ``ValueError`` (fatal-classified): retrying cannot free disk, so
    the ladder fails honestly naming the cap."""


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True            # alive, owned by someone else
    except OSError:
        return True            # unknowable: never delete a maybe-live file
    return True


def _guarded_io(site: str, fn):
    """One spill IO attempt: fault site + stall watchdog inside a
    transient-IO retry loop.  The watchdog sits INSIDE the retry so a
    stall-injected attempt raises the fatal ``DistStallError`` straight
    through ``with_retries`` (no retry into the same wedge), while
    io-classified flakes are retried with backoff."""
    def attempt():
        def body():
            fault_point(site)
            return fn()
        return dist_guard(site, body, timeout=stream_timeout())
    return with_retries(attempt, retryable=(CATEGORY_IO,), site=site)


class SpillFileStore:
    """Capped, atomic, crash-safe Parquet page files in one directory."""

    def __init__(self, directory: Optional[str] = None,
                 max_files: int = MAX_SPILL_FILES,
                 max_bytes: int = MAX_SPILL_BYTES):
        self.directory = directory or spill_dir()
        self.max_files = int(max_files)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._seq = 0
        self._live: Dict[str, int] = {}          # path -> nbytes on disk
        os.makedirs(self.directory, exist_ok=True)
        self.orphans_swept = self._sweep_orphans()

    # -- startup hygiene -------------------------------------------------

    def _sweep_orphans(self) -> int:
        """Remove spill files (and ``.tmp`` partials) left by DEAD
        processes; live pids' files are never touched."""
        swept = 0
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        for name in names:
            if not name.startswith(_FILE_PREFIX):
                continue
            stem = name
            for suffix in (_FILE_SUFFIX + ".tmp", _FILE_SUFFIX):
                if stem.endswith(suffix):
                    stem = stem[len(_FILE_PREFIX):-len(suffix)]
                    break
            else:
                continue
            try:
                pid = int(stem.split("-", 1)[0])
            except ValueError:
                continue
            if pid == os.getpid() or _pid_alive(pid):
                continue
            try:
                os.unlink(os.path.join(self.directory, name))
                swept += 1
            except OSError:
                pass
        if swept:
            from ..obs.metrics import counter
            counter("spill.orphans_swept").inc(swept)
        return swept

    # -- page IO ---------------------------------------------------------

    def write(self, np_leaves: List) -> Tuple[str, int]:
        """Persist one page's leaves; returns ``(path, disk_bytes)``.

        Atomic: the page file either exists complete or not at all.
        Raises :class:`SpillCapacityError` when the directory caps are
        exhausted (fatal — counted on ``spill.cap_refusals``).
        """
        payload_bytes = sum(int(leaf.nbytes) for leaf in np_leaves)
        with self._lock:
            if (len(self._live) >= self.max_files
                    or sum(self._live.values()) + payload_bytes
                    > self.max_bytes):
                from ..obs.metrics import counter
                counter("spill.cap_refusals").inc()
                raise SpillCapacityError(
                    f"spill directory {self.directory!r} is full "
                    f"({len(self._live)} files / "
                    f"{sum(self._live.values())} bytes; caps "
                    f"{self.max_files} files / {self.max_bytes} bytes) — "
                    f"cannot page out {payload_bytes} more bytes")
            self._seq += 1
            name = f"{_FILE_PREFIX}{os.getpid()}-{self._seq}{_FILE_SUFFIX}"
            path = os.path.join(self.directory, name)
            # Reserve the slot before the (retryable) IO so concurrent
            # writers never race the caps.
            self._live[path] = payload_bytes

        def _write():
            import pyarrow as pa
            import pyarrow.parquet as pq
            table = pa.table({
                "data": pa.array([leaf.tobytes() for leaf in np_leaves],
                                 type=pa.binary()),
                "dtype": pa.array([str(leaf.dtype) for leaf in np_leaves]),
                "shape": pa.array([json.dumps(list(leaf.shape))
                                   for leaf in np_leaves]),
            })
            tmp = path + ".tmp"
            pq.write_table(table, tmp, compression="snappy")
            os.replace(tmp, path)
            return os.path.getsize(path)

        try:
            disk_bytes = _guarded_io("spill-write", _write)
        except BaseException:
            with self._lock:
                self._live.pop(path, None)
            try:
                os.unlink(path + ".tmp")
            except OSError:
                pass
            raise
        with self._lock:
            self._live[path] = int(disk_bytes)
        self._publish_gauges()
        return path, int(disk_bytes)

    def read(self, path: str) -> List:
        """Reconstruct one page's numpy leaves exactly as written."""
        def _read():
            import numpy as np
            import pyarrow.parquet as pq
            table = pq.read_table(path)
            datas = table.column("data").to_pylist()
            dtypes = table.column("dtype").to_pylist()
            shapes = table.column("shape").to_pylist()
            return [np.frombuffer(d, dtype=np.dtype(t))
                    .reshape(json.loads(s))
                    for d, t, s in zip(datas, dtypes, shapes)]
        return _guarded_io("spill-read", _read)

    def remove(self, path: str) -> None:
        """Drop a page file (after page-in, or on reset)."""
        with self._lock:
            self._live.pop(path, None)
        try:
            os.unlink(path)
        except OSError:
            pass
        self._publish_gauges()

    # -- accounting ------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"files": len(self._live),
                    "bytes": sum(self._live.values()),
                    "orphans_swept": self.orphans_swept}

    def _publish_gauges(self) -> None:
        from ..obs.metrics import gauge
        s = self.stats()
        gauge("spill.files").set(s["files"])
        gauge("spill.file_bytes").set(s["bytes"])


__all__ = ["MAX_SPILL_BYTES", "MAX_SPILL_FILES", "SpillCapacityError",
           "SpillFileStore"]
