"""Pipelined storage→device feed — the GPUDirect-Storage analog.

The reference optionally DMA-streams files straight into GPU memory via
cuFile/GDS (reference: CMakeLists.txt:177-199, the ``USE_GDS`` knob,
pom.xml:83).  TPU hosts have no DMA path from storage to HBM, so the
idiomatic equivalent is a **double-buffered background pipeline**: a worker
thread does storage IO + host decode for batch N+1 while the device
computes on batch N, hiding IO latency behind compute exactly the way GDS
hides it behind DMA.

Two layers:

  * :func:`prefetch` — generic iterator pipelining with a bounded queue
    (depth 2 by default: one batch in compute, one in flight).
  * :func:`scan_parquet` — a row-group-granular Parquet scan built on it:
    each row group is decoded (native decoder when in envelope, Arrow
    otherwise) off-thread and arrives as a device-resident ``Table``.

Worker exceptions propagate to the consumer at the point of ``next()``;
the worker is a daemon thread and shuts down when the consumer drops the
generator (or exhausts it).
"""

from __future__ import annotations

import queue
import threading
import time as _time
from typing import Callable, Iterable, Iterator, Optional, Sequence

from ..table import Table

_SENTINEL = object()


def prefetch(iterable: Iterable, depth: Optional[int] = None,
             transform: Optional[Callable] = None) -> Iterator:
    """Run ``iter(iterable)`` (and ``transform``) in a background thread,
    keeping up to ``depth`` results ready ahead of the consumer.

    ``depth`` defaults to ``SRT_PREFETCH_DEPTH`` (config.prefetch_depth,
    2 = classic double buffering).  Exceptions raised by the producer
    re-raise at the consumer's ``next()`` call as the original exception
    object (original type and traceback intact — a decode error three
    frames deep in the worker reads exactly as it would inline).

    The worker starts lazily at the consumer's first ``next()`` and every
    put is a timeout-put that rechecks the stop flag: a generator that is
    closed (or garbage-collected) while the queue is full cannot leave the
    worker wedged in a blocking ``q.put`` — close drains until the worker
    exits.
    """
    if depth is None:
        from ..config import prefetch_depth
        depth = prefetch_depth()
    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put(item) -> bool:
        """Enqueue unless the consumer is gone; True when delivered."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        # Spans land on the "srt-prefetch" thread's own timeline lane, so
        # the Perfetto view shows IO/decode overlapping device compute.
        from ..obs.timeline import span as _tspan
        try:
            it = iter(iterable)
            while True:
                with _tspan("io.prefetch.next", cat="io"):
                    try:
                        item = next(it)
                    except StopIteration:
                        break
                if stop.is_set():
                    return
                if transform is not None:
                    with _tspan("io.prefetch.transform", cat="io"):
                        item = transform(item)
                if not put(item):
                    return
            put(_SENTINEL)
        except BaseException as e:          # propagate to the consumer
            put(e)

    thread = threading.Thread(target=worker, daemon=True,
                              name="srt-prefetch")

    def generator():
        from ..config import stream_timeout
        thread.start()
        try:
            while True:
                timeout = stream_timeout()
                if timeout is None:
                    item = q.get()
                else:
                    # Stall watchdog (SRT_STREAM_TIMEOUT): a producer
                    # wedged in IO leaves q.get() blocked forever; bound
                    # the wait so the pipeline fails loudly instead.
                    deadline = _time.monotonic() + timeout
                    while True:
                        try:
                            item = q.get(timeout=0.05)
                            break
                        except queue.Empty:
                            if _time.monotonic() >= deadline:
                                from ..resilience import StreamStallError
                                raise StreamStallError(
                                    f"prefetch source produced nothing "
                                    f"for {timeout:.1f}s "
                                    f"(SRT_STREAM_TIMEOUT); worker "
                                    f"alive={thread.is_alive()}")
                if item is _SENTINEL:
                    return
                if isinstance(item, BaseException):
                    # Re-raise the worker's exception itself: python
                    # attaches the worker-side traceback to the object, so
                    # the consumer sees the real failure frames instead of
                    # an opaque RuntimeError wrapper.
                    raise item
                yield item
        finally:
            stop.set()
            # Unblock a producer mid-put and wait for it to exit; the
            # timeout-put rechecks ``stop`` so bounded draining suffices
            # (no race against items landing after a q.empty() check).
            deadline = _time.monotonic() + 2.0
            while thread.is_alive() and _time.monotonic() < deadline:
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                thread.join(0.02)

    return generator()


def _arrow_row_group(path, i, columns):
    import pyarrow.parquet as pq
    from .arrow import from_arrow
    return from_arrow(pq.ParquetFile(path).read_row_group(
        i, columns=list(columns) if columns is not None else None))


def _read_retry(fn, site: str = "read"):
    """Run one row-group read/decode under the transient-IO retry policy
    (resilience.with_retries, ``SRT_RETRY_MAX``/``SRT_RETRY_BACKOFF``).
    Only IO-classified errors retry — decode bugs and missing files
    surface on the first raise — and exhaustion re-raises the ORIGINAL
    exception (worker-side traceback and chain intact) with the
    attempted-recovery summary attached.  ``site`` is the fault-injection
    hook: ``SRT_FAULT=io:read:...`` flakes exactly here."""
    from ..obs.timeline import span as _tspan
    from ..resilience import fault_point, with_retries
    from ..resilience.classify import CATEGORY_IO

    def attempt():
        with _tspan("io.read", cat="io", site=site):
            fault_point(site)
            return fn()

    return with_retries(attempt, retryable=(CATEGORY_IO,), site=site)


def _row_group_reader(path, columns, preds=()):
    """Yield one decoded device Table per row group of one file.

    Fallback to the Arrow reader is **row-group granular**: a footer-level
    envelope rejection switches the whole file, and a page-level rejection
    (e.g. legacy BIT_PACKED levels the footer cannot reveal) switches just
    that row group — matching ``read_parquet(engine="auto")`` semantics
    without re-yielding rows already produced.

    ``preds`` is a conjunction of :class:`~.pushdown.LeafPred`: row groups
    whose footer statistics prove no row can match are skipped (never
    read), and page statistics prune inside surviving groups.  The caller
    MUST still apply the full predicate — surviving groups can contain
    non-matching rows (and page-pruned rows read as null).
    """
    from .parquet_native import (group_stats, read_metadata, _decode_chunk,
                                 _materialize_piece)
    from .pushdown import group_may_match, predicates_for_column

    try:
        cols, row_groups = read_metadata(path)
    except NotImplementedError:
        import pyarrow.parquet as pq
        for i in range(pq.ParquetFile(path).num_row_groups):
            yield _read_retry(
                lambda i=i: _arrow_row_group(path, i, columns))
        return

    want = list(columns) if columns is not None else [c.name for c in cols]
    missing = set(want) - {c.name for c in cols}
    if missing:
        raise KeyError(f"columns not in file: {sorted(missing)}")
    col_preds = {name: predicates_for_column(preds, name) for name in want}
    with open(path, "rb") as f:
        for i, rg in enumerate(row_groups):
            if preds and not group_may_match(group_stats(rg), preds):
                from ..obs.metrics import counter
                counter("scan.row_groups_skipped").inc()
                counter("scan.bytes_skipped").inc(
                    sum(c.total_compressed for c in rg
                        if c.column.name in col_preds))
                continue

            def decode_group(i=i, rg=rg):
                by_name = {}
                for chunk in rg:
                    if chunk.column.name in want:
                        f.seek(chunk.start_offset)
                        raw = f.read(chunk.total_compressed)
                        # Row-group streaming materializes per chunk (the
                        # whole-column dictionary fusion needs all chunks;
                        # a stream hands each group on as it decodes).
                        by_name[chunk.column.name] = _materialize_piece(
                            _decode_chunk(raw, chunk,
                                          col_preds[chunk.column.name]))
                return Table([(n, by_name[n]) for n in want])
            try:
                # Seek + read restart inside the closure, so a transient
                # IO failure mid-group retries from the group's start.
                table = _read_retry(decode_group)
            except NotImplementedError:
                table = _read_retry(
                    lambda i=i: _arrow_row_group(path, i, columns))
            yield table


def coalesce_to_buckets(tables: Iterable[Table],
                        target_rows: int) -> Iterator[Table]:
    """Merge consecutive same-schema tables until each batch reaches at
    least ``target_rows`` rows (the tail batch may be smaller).

    The shape-bucketing layer (exec/bucketing.py) pads every bound batch
    up to a bucket capacity; tiny trailing row groups would each pay a
    near-total pad waste and, worse, land in *different* small buckets.
    Coalescing feed batches to one target first makes consecutive row
    groups share a single bucket — one XLA program for the whole scan.
    A schema change (different names/dtypes mid-stream) flushes the
    pending batch rather than erroring.
    """
    from ..obs.metrics import counter
    from ..ops.common import concat_tables
    pending: list[Table] = []
    pending_rows = 0

    def schema_of(t: Table):
        return (t.names, tuple(t.schema()))

    def flush():
        nonlocal pending, pending_rows
        if not pending:
            return None
        out = pending[0] if len(pending) == 1 else concat_tables(pending)
        if len(pending) > 1:
            counter("io.feed.coalesced_batches").inc(len(pending))
            _propagate_residency(pending, out)
        pending, pending_rows = [], 0
        return out

    for t in tables:
        if pending and schema_of(t) != schema_of(pending[0]):
            merged = flush()
            if merged is not None:
                yield merged
        pending.append(t)
        pending_rows += t.num_rows
        if pending_rows >= target_rows:
            yield flush()
    merged = flush()
    if merged is not None:
        yield merged


def _propagate_residency(pieces: list[Table], out: Table) -> None:
    """Carry scan-registered dictionary encodings across a coalesce.

    When every coalesced piece of a string column holds a resident
    encoding over the same vocabulary (the common case: one file's row
    groups share a dictionary), the concatenated codes are registered for
    the merged column so downstream code-domain execution survives the
    batch merge.  Vocabulary mismatches just fall back silently."""
    from ..config import encoded_exec
    if not encoded_exec():
        return
    from ..dtypes import STRING
    from ..ops.strings import resident_concat
    for name, col in out.items():
        if col.dtype is STRING:
            resident_concat([p[name] for p in pieces], col)


def _bucket_coalesce_target(paths, columns, preds=()) -> int:
    """Footer-only pass over ``paths``: the bucket capacity of the largest
    *surviving* row group — coalescing to it lands every non-tail batch in
    one shape bucket (exec/bucketing.py), so the scan runs under one
    program.  With pushdown predicates the target is computed over the
    groups that survive statistics pruning, not the raw file layout:
    skipped groups never yield rows, so sizing buckets to them would only
    inflate pad waste."""
    from ..exec.bucketing import bucket_capacity
    counts: list[int] = []
    for p in paths:
        try:
            if preds:
                from .parquet_native import group_stats, read_metadata
                from .pushdown import group_may_match
                _, row_groups = read_metadata(p)
                for rg in row_groups:
                    if not rg or not group_may_match(group_stats(rg),
                                                     preds):
                        continue
                    flat = [c for c in rg if c.column.max_rep == 0]
                    counts.append((flat[0] if flat else rg[0]).num_values)
            else:
                from .parquet_native import row_group_row_counts
                counts.extend(row_group_row_counts(p))
        except NotImplementedError:
            import pyarrow.parquet as pq
            md = pq.ParquetFile(p).metadata
            counts.extend(md.row_group(i).num_rows
                          for i in range(md.num_row_groups))
    return bucket_capacity(max(counts) if counts else 1)


def scan_parquet(paths, columns: Optional[Sequence[str]] = None,
                 depth: Optional[int] = None,
                 coalesce_rows: Optional[object] = None,
                 predicate: Optional[object] = None) -> Iterator[Table]:
    """Stream device Tables row-group by row-group across ``paths``.

    IO + host decode for the next row group overlap with the caller's
    device compute on the current one (the GDS-analog pipeline).  ``paths``
    may be one path or a sequence.  ``depth`` defaults to
    ``SRT_PREFETCH_DEPTH`` (config.prefetch_depth).

    ``coalesce_rows`` merges consecutive row groups until each yielded
    batch holds at least that many rows (see :func:`coalesce_to_buckets`).
    Pass an int target, or ``"bucket"`` to derive one from the files'
    footers (the bucket capacity of the largest *surviving* row group,
    ``exec.bucketing.bucket_capacity``) so a many-file scan executes as
    one compiled program instead of one per distinct row-group length.

    ``predicate`` is a pushdown hint — an :class:`~..exec.expr.Expr`, a
    list of ``(col, op, val)`` tuples, or LeafPreds (see
    ``io.pushdown.extract_scan_predicates``).  Statistics-qualifying row
    groups and pages are skipped before any byte is read or uploaded
    (``scan.bytes_skipped`` / ``scan.pages_skipped``), and the
    ``coalesce_rows="bucket"`` target is derived from surviving groups
    only.  Pruning is a pure optimization: batches can still contain
    non-matching rows (and pruned pages read as null), so the CALLER MUST
    apply the full predicate to every yielded batch.  Honors
    ``SRT_SCAN_PRUNE`` (off → no pruning).
    """
    if isinstance(paths, (str, bytes)) or hasattr(paths, "__fspath__"):
        paths = [paths]
    from .parquet_native import scan_predicate_leaves
    preds = scan_predicate_leaves(predicate)

    def all_groups():
        from ..obs.metrics import counter
        for p in paths:
            for t in _row_group_reader(p, columns, preds):
                counter("io.feed.row_groups").inc()
                counter("io.feed.rows").inc(t.num_rows)
                yield t

    groups = all_groups()
    if coalesce_rows is not None:
        if coalesce_rows == "bucket":
            coalesce_rows = _bucket_coalesce_target(paths, columns, preds)
        if not isinstance(coalesce_rows, int) or coalesce_rows < 1:
            raise ValueError(
                f"coalesce_rows must be a positive int or 'bucket', "
                f"got {coalesce_rows!r}")
        groups = coalesce_to_buckets(groups, coalesce_rows)
    return prefetch(groups, depth=depth)
