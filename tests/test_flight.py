"""Flight recorder, postmortem bundles, and the doctor (obs/flight.py,
obs/bundle.py, obs/doctor.py) plus the tail-first history lookup.

Five contracts:

1. **Bounded always-on recording** — with ``SRT_METRICS=1`` every
   ``trace()`` scope lands in a fixed-size per-query ring
   (``SRT_FLIGHT_EVENTS`` slots) that overwrites oldest-first and
   drains as a golden-valid Chrome trace; off and query-less spans
   record nothing.
2. **One incident, one bundle** — terminal failures, recovery
   exhaustion, and SLO breaches each write exactly one self-contained
   JSON bundle to ``SRT_BUNDLE_DIR`` matching the golden-pinned schema
   (tests/golden/postmortem_bundle_schema.json), count-capped, and
   ``dump`` never raises into the failing query.
3. **The doctor explains it** — ``diagnose`` ranks the classified
   error, the recovery chain, SLO overrun, cache regressions, and
   cost-bucket growth against the same-fingerprint history baseline;
   the CLI exits 0 whenever a verdict was produced.
4. **Knob hygiene** — the four new knobs raise knob-named ValueErrors.
5. **O(tail) history lookup** — ``lookup_latest`` reads block-wise from
   EOF and survives a torn final line.
"""

import json
import os
import pathlib
import threading

import numpy as np
import pytest

from spark_rapids_tpu import Table
from spark_rapids_tpu.exec import col, plan
from spark_rapids_tpu.obs import bundle, flight, history, timeline
from spark_rapids_tpu.obs.doctor import diagnose, render
from spark_rapids_tpu.obs.metrics import registry

GOLDEN = pathlib.Path(__file__).parent / "golden"


def _golden(name):
    with open(GOLDEN / name) as f:
        return json.load(f)


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for knob in ("SRT_BUNDLE_DIR", "SRT_SLO_MS", "SRT_FLIGHT_EVENTS",
                 "SRT_LIVE_RECENT"):
        monkeypatch.delenv(knob, raising=False)
    flight.reset()
    bundle.reset()
    registry().reset()
    yield
    flight.reset()
    bundle.reset()
    registry().reset()


@pytest.fixture
def metrics_on(monkeypatch):
    monkeypatch.setenv("SRT_METRICS", "1")
    yield


@pytest.fixture
def metrics_off(monkeypatch):
    monkeypatch.delenv("SRT_METRICS", raising=False)


def _table(prefix, n=300):
    return Table.from_pydict({
        f"{prefix}_k": (np.arange(n) % 5).astype(np.int32),
        f"{prefix}_v": np.arange(n, dtype=np.float32),
    })


def _query(prefix):
    return (plan()
            .filter(col(f"{prefix}_v") > 10.0)
            .with_columns(**{f"{prefix}_d": col(f"{prefix}_v") * 2.0}))


def _bundles(dirpath, reason=None):
    out = []
    for name in sorted(os.listdir(dirpath)):
        if reason is not None and not name.startswith(f"postmortem-{reason}"):
            continue
        with open(os.path.join(dirpath, name)) as f:
            out.append((os.path.join(dirpath, name), json.load(f)))
    return out


# ---------------------------------------------------------------------------
# 1. the ring
# ---------------------------------------------------------------------------

def test_ring_drains_in_timestamp_order():
    ring = flight.FlightRing(7, capacity=8)
    for ts in (30.0, 10.0, 20.0):
        ring.append("step", "flight", ts, 1.0, "lane-0", {})
    assert [e[0] for e in ring.events()] == [10.0, 20.0, 30.0]


def test_ring_overwrites_oldest_and_counts_drops():
    ring = flight.FlightRing(7, capacity=4)
    for i in range(10):
        ring.append(f"e{i}", "flight", float(i), 1.0, "lane-0", {"i": i})
    stats = ring.stats()
    assert stats == {"capacity": 4, "events_recorded": 4,
                     "events_dropped": 6}
    # only the newest <capacity> events survive
    assert [e[0] for e in ring.events()] == [6.0, 7.0, 8.0, 9.0]


def test_ring_capacity_from_knob(monkeypatch):
    monkeypatch.setenv("SRT_FLIGHT_EVENTS", "16")
    assert flight.FlightRing(1).capacity == 16


def test_concurrent_appends_never_lose_the_ring(metrics_on):
    # the lock-free contract: racing appenders corrupt nothing — every
    # retained slot is a whole event and stats stay bounded
    ring = flight.FlightRing(9, capacity=64)

    def worker(base):
        for i in range(500):
            ring.append("w", "flight", float(base + i), 1.0,
                        f"lane-{base}", {"i": i})

    threads = [threading.Thread(target=worker, args=(k * 1000,))
               for k in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    evs = ring.events()
    assert len(evs) == 64
    assert all(len(e) == 6 for e in evs)
    stats = ring.stats()
    assert stats["events_recorded"] == 64
    assert stats["events_dropped"] == 2000 - 64


def test_ring_chrome_trace_matches_golden():
    ring = flight.FlightRing(42, capacity=8)
    ring.append("dispatch", "flight", 100.0, 5.0, "main", {"batch": 0})
    ring.append("materialize", "flight", 110.0, 2.0, "worker-1",
                {"rows": 99, "odd": object()})
    payload = ring.chrome_trace()
    errors = timeline.validate_chrome_trace(
        payload, _golden("chrome_trace_schema.json"))
    assert errors == [], errors
    xs = [e for e in payload["traceEvents"] if e["ph"] == "X"]
    ms = [e for e in payload["traceEvents"] if e["ph"] == "M"]
    assert len(xs) == 2 and len(ms) == 2        # one M per lane
    assert all(e["args"]["query_id"] == 42 for e in xs)
    assert isinstance(xs[1]["args"]["odd"], str)     # coerced, not raw


def test_ring_registry_is_lru_bounded():
    for qid in range(flight.MAX_RINGS + 5):
        flight.ring_for(qid)
    assert flight.ring_for(0, create=False) is None       # evicted
    assert flight.ring_for(flight.MAX_RINGS + 4,
                           create=False) is not None


def test_trace_span_off_without_metrics(metrics_off):
    with timeline.query_scope(5):
        assert flight.trace_span("x", {}) is None


def test_trace_span_needs_ambient_query(metrics_on):
    assert flight.trace_span("x", {}) is None
    with timeline.query_scope(5):
        span = flight.trace_span("x", {"k": 1})
        assert span is not None
        with span:
            pass
    snap = flight.snapshot(5)
    assert snap["events_recorded"] == 1


def test_trace_feeds_the_ring(metrics_on):
    from spark_rapids_tpu.utils.tracing import trace
    with timeline.query_scope(77):
        with trace("flight-step", batch=3):
            pass
    snap = flight.snapshot(77)
    assert snap is not None and snap["events_recorded"] == 1
    [ev] = [e for e in snap["trace"]["traceEvents"] if e["ph"] == "X"]
    assert ev["name"] == "flight-step"
    assert ev["args"] == {"batch": 3, "query_id": 77}


def test_metered_run_populates_flight_ring(metrics_on):
    from spark_rapids_tpu.obs import last_query_metrics
    t = _table("fr")
    _query("fr").run(t)
    qid = last_query_metrics().query_id
    snap = flight.snapshot(qid)
    assert snap is not None and snap["events_recorded"] > 0
    errors = timeline.validate_chrome_trace(
        snap["trace"], _golden("chrome_trace_schema.json"))
    assert errors == [], errors


def test_unmetered_run_records_nothing(metrics_off):
    t = _table("froff")
    _query("froff").run(t)
    with flight._LOCK:
        assert not flight._RINGS


# ---------------------------------------------------------------------------
# 2. bundles
# ---------------------------------------------------------------------------

def test_build_matches_golden_schema(metrics_on):
    from spark_rapids_tpu.obs import last_query_metrics
    t = _table("bg")
    _query("bg").run(t)
    payload = bundle.build("failure", qm=last_query_metrics(),
                           error=ValueError("boom"))
    errors = bundle.validate_bundle(
        payload, _golden("postmortem_bundle_schema.json"))
    assert errors == [], errors
    assert payload["error"]["type"] == "ValueError"
    assert payload["metrics"]["metric"] == "query_metrics"
    assert payload["config"].get("SRT_FLIGHT_EVENTS")


def test_embedded_chrome_schema_pins_the_standalone_golden():
    # the bundle golden embeds the chrome-trace schema verbatim so the
    # two files cannot drift apart silently
    assert (_golden("postmortem_bundle_schema.json")["chrome_trace"]
            == _golden("chrome_trace_schema.json"))


def test_bundle_rejects_unknown_reason():
    with pytest.raises(ValueError, match="reason"):
        bundle.build("mystery")


def test_dump_noop_without_bundle_dir():
    assert bundle.dump("failure", query_id=1,
                       error=ValueError("x")) is None


def test_dump_writes_validates_and_dedups(tmp_path, monkeypatch):
    monkeypatch.setenv("SRT_BUNDLE_DIR", str(tmp_path))
    path = bundle.dump("failure", query_id=123, error=ValueError("boom"))
    assert path is not None and os.path.exists(path)
    with open(path) as f:
        payload = json.load(f)
    errors = bundle.validate_bundle(
        payload, _golden("postmortem_bundle_schema.json"))
    assert errors == [], errors
    # same (query, reason): deduped; other reason: a second bundle
    assert bundle.dump("failure", query_id=123,
                       error=ValueError("boom")) is None
    assert bundle.dump("slo_breach", query_id=123) is not None
    assert len(os.listdir(tmp_path)) == 2


def test_dump_never_raises(tmp_path, monkeypatch):
    monkeypatch.setenv("SRT_BUNDLE_DIR",
                       str(tmp_path / "file-not-a-dir" / "x"))
    (tmp_path / "file-not-a-dir").write_text("in the way")
    assert bundle.dump("failure", query_id=5,
                       error=ValueError("x")) is None


def test_bundle_dir_is_count_capped(tmp_path, monkeypatch):
    monkeypatch.setenv("SRT_BUNDLE_DIR", str(tmp_path))
    monkeypatch.setattr(bundle, "MAX_BUNDLES", 5)
    for qid in range(9):
        assert bundle.dump("failure", query_id=qid,
                           error=ValueError("x")) is not None
    assert len(os.listdir(tmp_path)) == 5


def test_failed_run_writes_postmortem_bundles(tmp_path, monkeypatch,
                                              metrics_on):
    from spark_rapids_tpu.resilience import (ExecutionRecoveryError,
                                             reset_faults)
    monkeypatch.setenv("SRT_BUNDLE_DIR", str(tmp_path))
    monkeypatch.setenv("SRT_FAULT", "oom:dispatch:99")
    monkeypatch.setenv("SRT_RETRY_MAX", "1")
    monkeypatch.setenv("SRT_RETRY_BACKOFF", "0")
    reset_faults()
    t = _table("fb")
    p = plan().sort_by("fb_v")       # unsplittable: the ladder exhausts
    try:
        with pytest.raises(ExecutionRecoveryError):
            p.run(t)
    finally:
        monkeypatch.delenv("SRT_FAULT")
        reset_faults()
    schema = _golden("postmortem_bundle_schema.json")
    exhausted = _bundles(tmp_path, "recovery_exhausted")
    failures = _bundles(tmp_path, "failure")
    assert len(exhausted) == 1 and len(failures) == 1
    for path, payload in exhausted + failures:
        errors = bundle.validate_bundle(payload, schema)
        assert errors == [], (path, errors)
    _, ex = exhausted[0]
    assert ex["error"]["category"] == "oom"
    assert ex["recovery"]["site"] == "dispatch"
    assert ex["recovery"]["steps"], "recovery chain missing its rungs"
    assert ex["flight"]["events_recorded"] > 0
    assert any(e["ph"] == "X"
               for e in ex["flight"]["trace"]["traceEvents"])
    # the later failure dump carries the final recovery chain: the same
    # rungs the exhaustion bundle saw, plus whatever the ladder added on
    # the way out (e.g. the split-unavailable verdict)
    _, fl = failures[0]
    assert fl["query_id"] == ex["query_id"]
    n = len(ex["recovery"]["steps"])
    assert fl["recovery"]["steps"][:n] == ex["recovery"]["steps"]


def test_slo_breach_writes_bundle(tmp_path, monkeypatch, metrics_on):
    monkeypatch.setenv("SRT_BUNDLE_DIR", str(tmp_path))
    monkeypatch.setenv("SRT_SLO_MS", "0.001")      # everything breaches
    t = _table("slo")
    out = _query("slo").run(t)
    assert out.num_rows > 0                        # the query succeeded
    breaches = _bundles(tmp_path, "slo_breach")
    assert len(breaches) == 1
    _, payload = breaches[0]
    errors = bundle.validate_bundle(
        payload, _golden("postmortem_bundle_schema.json"))
    assert errors == [], errors
    assert payload["slo"]["slo_ms"] == 0.001
    assert payload["slo"]["elapsed_seconds"] * 1000.0 > 0.001
    assert payload["metrics"]["timings"]["total_seconds"] > 0


def test_no_slo_bundle_when_within_budget(tmp_path, monkeypatch,
                                          metrics_on):
    monkeypatch.setenv("SRT_BUNDLE_DIR", str(tmp_path))
    monkeypatch.setenv("SRT_SLO_MS", "3600000")     # one hour
    t = _table("sok")
    _query("sok").run(t)
    assert _bundles(tmp_path, "slo_breach") == []


# ---------------------------------------------------------------------------
# 3. the doctor
# ---------------------------------------------------------------------------

def _mk_qm(query_id=1, fingerprint="f1", total=1.0, compute=0.8,
           compile_cache="hit", queue_wait=0.0, counters=None):
    return {
        "metric": "query_metrics", "query_id": query_id,
        "fingerprint": fingerprint, "mode": "run",
        "compile_cache": compile_cache,
        "timings": {"total_seconds": total, "compile_seconds": 0.2},
        "cost": {"compute_seconds": compute, "ici_seconds": 0.0,
                 "host_sync_seconds": 0.1,
                 "dispatch_overhead_seconds": 0.1,
                 "unattributed_seconds": total - compute - 0.2},
        "caches": {"dict_encode_hits": 5, "dict_encode_misses": 0},
        "serve": {"queue_wait_seconds": queue_wait, "result_cache": None},
        "recovery": {"retries": 0, "splits": 0, "cache_evictions": 0,
                     "backoff_seconds": 0.0},
        "counters": counters or {},
    }


def test_doctor_names_the_fault_site(tmp_path, monkeypatch, metrics_on):
    from spark_rapids_tpu.resilience import (ExecutionRecoveryError,
                                             reset_faults)
    monkeypatch.setenv("SRT_BUNDLE_DIR", str(tmp_path))
    monkeypatch.setenv("SRT_FAULT", "oom:dispatch:99")
    monkeypatch.setenv("SRT_RETRY_MAX", "1")
    monkeypatch.setenv("SRT_RETRY_BACKOFF", "0")
    reset_faults()
    try:
        with pytest.raises(ExecutionRecoveryError):
            plan().sort_by("dm_v").run(_table("dm"))
    finally:
        monkeypatch.delenv("SRT_FAULT")
        reset_faults()
    [(path, payload)] = _bundles(tmp_path, "recovery_exhausted")
    report = diagnose(payload)
    assert "oom" in report["verdict"] and "dispatch" in report["verdict"]
    titles = [f["title"] for f in report["findings"]]
    assert any("recovery ladder" in t for t in titles)
    text = render(report)
    assert "== Doctor ==" in text and "dispatch" in text
    # severities are sorted most-damning-first
    sevs = [f["severity"] for f in report["findings"]]
    assert sevs == sorted(sevs, reverse=True)


def test_doctor_explains_slowdown_against_baseline():
    payload = _mk_qm(query_id=9, total=3.0, compute=2.5,
                     compile_cache="miss")
    baseline = _mk_qm(query_id=3, total=1.0, compute=0.6)
    report = diagnose(payload, baseline=baseline)
    assert report["baseline_used"]
    assert "3.0x slower" in report["verdict"]
    titles = [f["title"] for f in report["findings"]]
    assert any("compute_seconds grew most" in t for t in titles)
    assert any("compile cache miss (the baseline run hit)" == t
               for t in titles)


def test_doctor_flags_queue_wait_and_pad_waste():
    payload = _mk_qm(total=2.0, queue_wait=1.5,
                     counters={"plan.bucket.pad_rows": 900,
                               "plan.bucket.rows_total": 1000})
    report = diagnose(payload, baseline=None)
    titles = [f["title"] for f in report["findings"]]
    assert any("queue wait dominated" in t for t in titles)
    assert any("padding wasted 90%" in t for t in titles)


def test_doctor_refuses_self_baseline():
    payload = _mk_qm(query_id=9, total=3.0)
    report = diagnose(payload, baseline=_mk_qm(query_id=9, total=1.0))
    assert not report["baseline_used"]
    assert "no anomalies" in report["verdict"]


def test_doctor_cli_on_bundle_file(tmp_path, capsys):
    payload = bundle.build("failure", query_id=4,
                           error=RuntimeError("kaput"))
    path = tmp_path / "b.json"
    path.write_text(json.dumps(payload))
    from spark_rapids_tpu.obs.doctor import main
    assert main(str(path)) == 0
    out = capsys.readouterr().out
    assert "== Doctor ==" in out and "RuntimeError" in out


def test_doctor_cli_unknown_target_exits_2(tmp_path, capsys):
    from spark_rapids_tpu.obs.doctor import main
    assert main("nosuchfingerprint",
                history_path=str(tmp_path / "none.jsonl")) == 2
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert main(str(bad)) == 2


def test_doctor_cli_fingerprint_mode(tmp_path, monkeypatch, metrics_on,
                                     capsys):
    hist = tmp_path / "hist.jsonl"
    monkeypatch.setenv("SRT_METRICS_HISTORY", str(hist))
    t = _table("dfp")
    q = _query("dfp")
    q.run(t)
    q.run(t)
    recs = history.load(path=str(hist))
    assert len(recs) == 2
    fp = recs[-1]["fingerprint"]
    from spark_rapids_tpu.obs.doctor import main
    assert main(fp, history_path=str(hist)) == 0
    out = capsys.readouterr().out
    assert "== Doctor ==" in out and fp in out


def test_obs_cli_doctor_subcommand(tmp_path, capsys):
    payload = bundle.build("admission_rejected", fingerprint="fp9",
                           mode="run")
    path = tmp_path / "adm.json"
    path.write_text(json.dumps(payload))
    from spark_rapids_tpu.obs.__main__ import main
    assert main(["doctor", str(path)]) == 0
    out = capsys.readouterr().out
    assert "rejected at admission" in out


# ---------------------------------------------------------------------------
# 4. knob hygiene
# ---------------------------------------------------------------------------

def test_flight_events_knob(monkeypatch):
    from spark_rapids_tpu.config import flight_events
    assert flight_events() == 4096
    monkeypatch.setenv("SRT_FLIGHT_EVENTS", "128")
    assert flight_events() == 128
    for bad in ("0", "-4", "many"):
        monkeypatch.setenv("SRT_FLIGHT_EVENTS", bad)
        with pytest.raises(ValueError, match="SRT_FLIGHT_EVENTS"):
            flight_events()


def test_slo_ms_knob(monkeypatch):
    from spark_rapids_tpu.config import slo_ms
    assert slo_ms() is None
    monkeypatch.setenv("SRT_SLO_MS", "250")
    assert slo_ms() == 250.0
    for off in ("0", "off", ""):
        monkeypatch.setenv("SRT_SLO_MS", off)
        assert slo_ms() is None
    monkeypatch.setenv("SRT_SLO_MS", "fast")
    with pytest.raises(ValueError, match="SRT_SLO_MS"):
        slo_ms()


def test_bundle_dir_knob(monkeypatch):
    from spark_rapids_tpu.config import bundle_dir
    assert bundle_dir() is None
    monkeypatch.setenv("SRT_BUNDLE_DIR", "  ")
    assert bundle_dir() is None
    monkeypatch.setenv("SRT_BUNDLE_DIR", "/tmp/bundles")
    assert bundle_dir() == "/tmp/bundles"


def test_new_knobs_in_knob_table(monkeypatch):
    from spark_rapids_tpu.config import knob_table
    table = knob_table()
    for knob in ("SRT_FLIGHT_EVENTS", "SRT_BUNDLE_DIR", "SRT_SLO_MS",
                 "SRT_LIVE_RECENT"):
        assert knob in table


# ---------------------------------------------------------------------------
# 5. tail-first history lookup
# ---------------------------------------------------------------------------

def _hist_line(fingerprint, query_id, measured=True, total=1.0):
    rec = {"fingerprint": fingerprint, "query_id": query_id,
           "timings": {"total_seconds": total},
           "steps": [{"step": "Filter",
                      "rows_out": 10 if measured else None}]}
    return json.dumps(rec)


def test_iter_lines_reversed_roundtrip(tmp_path):
    path = tmp_path / "x.jsonl"
    lines = [f"line-{i}-" + "p" * (40 + i % 37) for i in range(4000)]
    path.write_text("\n".join(lines) + "\n")
    assert path.stat().st_size > 2 * history._REVERSE_BLOCK
    got = [raw.decode() for raw in history._iter_lines_reversed(str(path))]
    assert got == lines[::-1]


def test_iter_lines_reversed_no_trailing_newline(tmp_path):
    path = tmp_path / "x.jsonl"
    path.write_text("a\nb\nc")
    got = [raw.decode() for raw in history._iter_lines_reversed(str(path))]
    assert got == ["c", "b", "a"]


def test_lookup_latest_returns_newest_measured_record(tmp_path):
    path = tmp_path / "hist.jsonl"
    with open(path, "w") as f:
        f.write(_hist_line("aaa", 1, total=1.0) + "\n")
        f.write(_hist_line("bbb", 2) + "\n")
        f.write(_hist_line("aaa", 3, total=2.0) + "\n")
        f.write(_hist_line("aaa", 4, measured=False) + "\n")
    rec = history.lookup_latest("aaa", path=str(path))
    # newest MEASURED record wins; the unmeasured newer one is skipped
    assert rec["query_id"] == 3
    assert history.lookup_latest("zzz", path=str(path)) is None
    assert history.lookup_latest("aaa",
                                 path=str(tmp_path / "no.jsonl")) is None


def test_lookup_latest_survives_corrupt_tail(tmp_path, monkeypatch):
    monkeypatch.setenv("SRT_METRICS", "1")
    registry().reset()
    path = tmp_path / "hist.jsonl"
    with open(path, "w") as f:
        f.write(_hist_line("ct1", 7) + "\n")
        f.write('{"fingerprint": "ct1", "torn mid-wri')     # no newline
    rec = history.lookup_latest("ct1", path=str(path))
    assert rec is not None and rec["query_id"] == 7
    assert registry().counters_snapshot().get(
        "history.corrupt_lines") == 1


def test_lookup_latest_is_tail_first_on_big_files(tmp_path):
    path = tmp_path / "hist.jsonl"
    pad = "x" * 200
    with open(path, "w") as f:
        for i in range(2000):
            rec = {"fingerprint": "big", "query_id": i, "pad": pad,
                   "steps": [{"rows_out": 1}]}
            f.write(json.dumps(rec) + "\n")
    assert path.stat().st_size > 4 * history._REVERSE_BLOCK
    rec = history.lookup_latest("big", path=str(path))
    assert rec["query_id"] == 1999
