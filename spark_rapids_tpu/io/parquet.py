"""Parquet scan/write.

The reference envelope's Parquet decode lives in cuDF's GPU decoder
(BASELINE.json: "Parquet decode" is on the op list).  Current TPU design:
host-side decode via Arrow (pyarrow's vectorized C++ reader) feeding
device-resident columns — the decode itself is IO/CPU-bound and overlaps
with device compute in a pipeline; predicate/column pushdown happens in the
reader.  A device-side decoder for PLAIN/RLE/dictionary pages (decompressed
bytes shipped to HBM, unpacked with the same word-image machinery as
:mod:`..rows`) is the planned next step for scan-bound queries.

Row-group filtering: ``filters`` accepts pyarrow dataset filter
expressions.  A flat conjunction of ``(col, op, val)`` tuples routes to
the native reader, which prunes statistics-disqualified row groups and
pages before any byte is read and re-applies the exact predicate on
device; nested DNF (list-of-lists) falls back to Arrow.
"""

from __future__ import annotations

from typing import Optional, Sequence

import pyarrow.parquet as pq

from ..table import Table
from .arrow import from_arrow, to_arrow


def _flat_filter_tuples(filters) -> bool:
    """True for the pandas-style flat AND form ``[(col, op, val), ...]``
    — the shape the native reader's pushdown understands.  Nested DNF
    (``[[...], [...]]``, an OR of conjunctions) is not."""
    try:
        items = list(filters)
    except TypeError:
        return False
    return bool(items) and all(
        isinstance(t, tuple) and len(t) == 3 and isinstance(t[0], str)
        for t in items)


def _filters_to_expr(filters):
    """The exact predicate the filter tuples denote, as an Expr tree —
    re-applied on device after the native scan so pruning stays a pure
    optimization (group/page granularity can keep non-matching rows)."""
    from ..exec.expr import BinOp, Col, IsIn, Lit
    from .pushdown import TUPLE_OPS
    pred = None
    for column, op, value in filters:
        if TUPLE_OPS[op] == "isin":
            leaf = IsIn(Col(column), tuple(value))
        else:
            leaf = BinOp(TUPLE_OPS[op], Col(column), Lit(value))
        pred = leaf if pred is None else BinOp("and_kleene", pred, leaf)
    return pred


def _read_native_filtered(path, columns, filters) -> Table:
    """Native scan with statistics pruning + exact device-side re-filter.

    Filter columns are read even when not requested (the mask needs
    them), then projected away.  Raises ValueError for filter shapes the
    native path cannot express and NotImplementedError outside the
    decoder's envelope — ``engine="auto"`` catches both into Arrow.
    """
    from ..exec.expr import evaluate
    from ..ops.filter import apply_boolean_mask
    from .parquet_native import read_parquet_native
    from .pushdown import extract_scan_predicates

    preds = extract_scan_predicates(filters)   # validates ops; may raise
    expr = _filters_to_expr(filters)
    want = None
    if columns is not None:
        want = list(columns) + [p.column for p in preds
                                if p.column not in columns]
    table = read_parquet_native(path, want, predicate=preds)
    if expr is not None:
        table = apply_boolean_mask(
            table, evaluate(expr, dict(table.items())))
    if columns is not None and list(columns) != table.names:
        table = Table([(n, table[n]) for n in columns])
    return table


def read_parquet(path, columns: Optional[Sequence[str]] = None,
                 filters=None, engine: str = "auto") -> Table:
    """Read a Parquet file into a device Table.

    ``engine="native"`` decodes pages with the device-side decoder
    (:mod:`.parquet_native`: RLE/bit-packed expansion, dictionary gather,
    boolean unpack and null scatter all run as jitted XLA on device);
    ``engine="arrow"`` uses pyarrow's host reader; ``engine="auto"``
    (default) picks native when the file is inside its envelope (flat
    schema; filters either absent or a flat tuple conjunction) and falls
    back to Arrow otherwise.

    With a flat ``[(col, op, val), ...]`` conjunction the native path
    additionally prunes row groups and pages from footer/page-header
    statistics before reading (``scan.bytes_skipped``), then re-applies
    the exact predicate on device — results are identical to Arrow's.

    Routing rationale (measured, BASELINE.md): on a quiet host the two
    engines are within ~15% of each other (interleaved medians); on a
    loaded host — the shared-Spark-executor case this reader exists
    for — the native path is unaffected while Arrow's multithreaded host
    decode loses ~30%, so native is the safer default wherever it can
    read the file.
    """
    if engine not in ("auto", "native", "arrow"):
        raise ValueError(f"engine must be auto|native|arrow, got {engine!r}")
    if engine == "native" and filters is not None \
            and not _flat_filter_tuples(filters):
        raise ValueError("engine='native' supports only a flat list of "
                         "(col, op, val) filter tuples; "
                         "use engine='auto' or 'arrow'")
    if engine != "arrow":
        try:
            if filters is None:
                from .parquet_native import read_parquet_native
                return read_parquet_native(path, columns)
            if _flat_filter_tuples(filters):
                return _read_native_filtered(path, columns, filters)
        except NotImplementedError:
            if engine == "native":
                raise
        except ValueError:
            if engine == "native":
                raise
    tbl = pq.read_table(path,
                        columns=list(columns) if columns is not None else None,
                        filters=filters)
    return from_arrow(tbl)


def write_parquet(table: Table, path, compression: str = "snappy") -> None:
    """Write a device Table to Parquet."""
    pq.write_table(to_arrow(table), path, compression=compression)
