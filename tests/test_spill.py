"""Out-of-core spill: the OOM ladder's terminal rung pages cold
partitions to host RAM / Parquet and back (resilience/spill.py +
io/spill.py), so a working set larger than the HBM budget completes
bit-identical to the unspilled oracle (``SRT_SPILL=0``).

Covers: the four ``SRT_SPILL*`` knobs (knob-named ``ValueError``\\ s),
manager paging round trips through both tiers, the spill-file store's
atomic capped Parquet pages + dead-pid orphan sweep, the ladder's named
``spill`` rung (engaged, exhausted, and default-off), postmortem bundles
naming the rung, seeded spill-IO faults (``io:spill-write`` /
``io:spill-read`` retried bit-identical; ``stall`` fails honestly via
the watchdog instead of hanging), the end-to-end streaming group-by
oracle parity with ``recovery.spill.*`` receipts, admission's
spill-instead-of-reject + proactive watermark, and the two satellite
bugfixes (donated-Table cache refusals; ticket cancel / GC releasing
the admission claim ledger).
"""

import gc
import json
import os
import subprocess
import threading
import weakref

import numpy as np
import pytest

from spark_rapids_tpu import Table
from spark_rapids_tpu.exec import col, plan
from spark_rapids_tpu.io.spill import (SpillCapacityError, SpillFileStore)
from spark_rapids_tpu.obs import last_stream_metrics, registry
from spark_rapids_tpu.resilience import (DistStallError, classify,
                                         fault_point, recovery_stats,
                                         reset_faults, reset_spill,
                                         spill_manager)
from spark_rapids_tpu.resilience.recovery import oom_ladder
from spark_rapids_tpu.serve.admission import (AdmissionController,
                                              AdmissionRejected)


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for knob in ("SRT_FAULT", "SRT_SPILL", "SRT_SPILL_DIR",
                 "SRT_SPILL_HOST_BYTES", "SRT_SPILL_WATERMARK",
                 "SRT_SERVE_HBM_BUDGET", "SRT_STREAM_TIMEOUT"):
        monkeypatch.delenv(knob, raising=False)
    monkeypatch.setenv("SRT_RETRY_BACKOFF", "0")
    # Pad-cache leftovers from earlier test files are legitimate spill
    # victims — clear them so byte-exact reclaim assertions hold.
    from spark_rapids_tpu.exec.bucketing import clear_pad_cache
    clear_pad_cache()
    reset_faults()
    reset_spill()
    yield
    reset_faults()
    reset_spill()


@pytest.fixture
def metrics_on(monkeypatch):
    monkeypatch.setenv("SRT_METRICS", "1")
    registry().reset()
    yield
    registry().reset()


@pytest.fixture
def spill_on(monkeypatch, tmp_path):
    monkeypatch.setenv("SRT_SPILL", "1")
    monkeypatch.setenv("SRT_SPILL_DIR", str(tmp_path / "spill"))
    yield tmp_path / "spill"


def _mk(n, seed=0, hi=3):
    r = np.random.default_rng(seed)
    return Table.from_pydict({"k": r.integers(0, hi, n),
                              "v": r.integers(0, 100, n)})


def _value(seed=0):
    import jax.numpy as jnp
    r = np.random.default_rng(seed)
    return {"a": jnp.asarray(r.integers(0, 1000, 500)),
            "b": jnp.asarray(r.random((20, 30), dtype=np.float32))}


def _np_eq(a, b):
    fa = [np.asarray(x) for x in _leaves(a)]
    fb = [np.asarray(x) for x in _leaves(b)]
    return len(fa) == len(fb) and all(
        x.dtype == y.dtype and x.shape == y.shape and np.array_equal(x, y)
        for x, y in zip(fa, fb))


def _leaves(v):
    import jax
    return jax.tree_util.tree_leaves(v)


AGGS = [("v", "sum", "vs"), ("v", "count", "vc"), ("v", "mean", "vm"),
        ("v", "min", "vlo"), ("v", "max", "vhi")]


def _agg_plan():
    return plan().groupby_agg(["k"], AGGS, domains={"k": (0, 2)})


def _combine(sizes=(60, 64, 89, 100, 33, 77, 55, 120)):
    batches = [_mk(n, s) for s, n in enumerate(sizes)]
    outs = list(_agg_plan().run_stream(iter(batches), inflight=2,
                                       combine=True))
    assert len(outs) == 1
    return outs[0]


# ---------------------------------------------------------------------------
# 1. knobs
# ---------------------------------------------------------------------------

class TestKnobs:
    def test_defaults(self):
        from spark_rapids_tpu.config import (spill_dir, spill_enabled,
                                             spill_host_bytes,
                                             spill_watermark)
        assert spill_enabled() is False
        assert spill_dir().endswith("srt_spill")
        assert spill_host_bytes() == 256 << 20
        assert spill_watermark() == 0.8

    @pytest.mark.parametrize("raw", ["x", "-1", "1.5"])
    def test_host_bytes_rejects_garbage(self, monkeypatch, raw):
        from spark_rapids_tpu.config import spill_host_bytes
        monkeypatch.setenv("SRT_SPILL_HOST_BYTES", raw)
        with pytest.raises(ValueError, match="SRT_SPILL_HOST_BYTES"):
            spill_host_bytes()

    def test_host_bytes_off_means_disk_only(self, monkeypatch):
        from spark_rapids_tpu.config import spill_host_bytes
        for raw in ("0", "off"):
            monkeypatch.setenv("SRT_SPILL_HOST_BYTES", raw)
            assert spill_host_bytes() == 0

    @pytest.mark.parametrize("raw", ["x", "0", "-0.2", "1.5"])
    def test_watermark_rejects_out_of_range(self, monkeypatch, raw):
        from spark_rapids_tpu.config import spill_watermark
        monkeypatch.setenv("SRT_SPILL_WATERMARK", raw)
        with pytest.raises(ValueError, match="SRT_SPILL_WATERMARK"):
            spill_watermark()

    def test_knob_table_lists_spill_knobs(self):
        from spark_rapids_tpu.config import knob_table
        names = set(knob_table())
        assert {"SRT_SPILL", "SRT_SPILL_DIR", "SRT_SPILL_HOST_BYTES",
                "SRT_SPILL_WATERMARK"} <= names


# ---------------------------------------------------------------------------
# 2. manager paging, both tiers
# ---------------------------------------------------------------------------

class TestManagerPaging:
    def test_host_tier_round_trip_bit_identical(self, spill_on):
        mgr = spill_manager()
        val = _value(1)
        oracle = [np.asarray(x).copy() for x in _leaves(val)]
        before = recovery_stats().snapshot()
        freed = mgr.page_out("k", val)
        assert freed > 0 and mgr.stats()["pages"] == 1
        assert mgr.stats()["pages_on_disk"] == 0   # fits the host LRU
        back = mgr.page_in("k")
        assert all(np.array_equal(o, np.asarray(l))
                   for o, l in zip(oracle, _leaves(back)))
        d = recovery_stats().delta(before)
        assert d["spill_pages_out"] == 1 and d["spill_pages_in"] == 1
        assert d["spill_bytes_out"] == freed == d["spill_bytes_in"]
        assert d["spill_files"] == 0
        assert mgr.stats() == {"pages": 0, "pages_on_disk": 0,
                               "host_bytes": 0, "victims": 0}

    def test_disk_tier_round_trip_and_file_cleanup(self, spill_on,
                                                   monkeypatch):
        monkeypatch.setenv("SRT_SPILL_HOST_BYTES", "0")
        mgr = spill_manager()
        val = _value(2)
        oracle = [np.asarray(x).copy() for x in _leaves(val)]
        before = recovery_stats().snapshot()
        mgr.page_out("k", val)
        assert mgr.stats()["pages_on_disk"] == 1
        files = os.listdir(spill_on)
        assert len(files) == 1 and files[0].endswith(".parquet")
        back = mgr.page_in("k")
        assert all(np.array_equal(o, np.asarray(l))
                   for o, l in zip(oracle, _leaves(back)))
        assert os.listdir(spill_on) == []          # page-in removed it
        d = recovery_stats().delta(before)
        assert d["spill_files"] == 1
        assert d["spill_page_in_seconds"] > 0

    def test_host_lru_overflows_oldest_to_disk(self, spill_on,
                                               monkeypatch):
        mgr = spill_manager()
        nbytes = mgr.page_out("a", _value(1))
        monkeypatch.setenv("SRT_SPILL_HOST_BYTES", str(nbytes + 16))
        mgr.page_out("b", _value(2))   # over cap -> oldest ("a") flushes
        s = mgr.stats()
        assert s["pages"] == 2 and s["pages_on_disk"] == 1
        assert _np_eq(mgr.page_in("a"), _value(1))   # disk tier
        assert _np_eq(mgr.page_in("b"), _value(2))   # host tier

    def test_page_in_unknown_key_raises(self, spill_on):
        with pytest.raises(KeyError):
            spill_manager().page_in("nope")

    def test_reclaim_runs_victims_and_pad_cache(self, spill_on):
        mgr = spill_manager()
        mgr.register_victim("v1", lambda: 100)
        calls = []
        mgr.register_victim("v2", lambda: calls.append(1) or 50)
        assert mgr.reclaim() == 150 and calls
        mgr.unregister_victim("v1")
        mgr.unregister_victim("v2")

    def test_broken_victim_is_dropped_not_fatal(self, spill_on):
        mgr = spill_manager()
        def boom():
            raise RuntimeError("victim broke")
        mgr.register_victim("bad", boom)
        mgr.register_victim("good", lambda: 7)
        assert mgr.reclaim() == 7
        assert mgr.stats()["victims"] == 1         # "bad" dropped


# ---------------------------------------------------------------------------
# 3. spill-file store: caps, atomicity, orphan sweep
# ---------------------------------------------------------------------------

class TestSpillFileStore:
    def test_cap_refusal_is_fatal_and_names_caps(self, tmp_path,
                                                 metrics_on):
        store = SpillFileStore(str(tmp_path), max_files=1)
        leaves = [np.arange(10)]
        store.write(leaves)
        with pytest.raises(SpillCapacityError, match="1 files"):
            store.write(leaves)
        assert classify(SpillCapacityError("full")) == "fatal"
        assert registry().snapshot().get("spill.cap_refusals", 0) == 1

    def test_byte_cap(self, tmp_path):
        store = SpillFileStore(str(tmp_path), max_bytes=8)
        with pytest.raises(SpillCapacityError, match="bytes"):
            store.write([np.arange(100)])

    def test_orphan_sweep_dead_pid_only(self, tmp_path):
        proc = subprocess.Popen(["sleep", "0"])
        proc.wait()
        dead = proc.pid
        live = os.getpid()
        (tmp_path / f"srt-spill-{dead}-1.parquet").write_bytes(b"x")
        (tmp_path / f"srt-spill-{dead}-2.parquet.tmp").write_bytes(b"x")
        (tmp_path / f"srt-spill-{live}-1.parquet").write_bytes(b"x")
        (tmp_path / "unrelated.parquet").write_bytes(b"x")
        store = SpillFileStore(str(tmp_path))
        assert store.orphans_swept == 2
        left = sorted(os.listdir(tmp_path))
        assert left == sorted([f"srt-spill-{live}-1.parquet",
                               "unrelated.parquet"])

    def test_round_trip_preserves_dtype_and_shape(self, tmp_path):
        store = SpillFileStore(str(tmp_path))
        leaves = [np.arange(24, dtype=np.int16).reshape(2, 3, 4),
                  np.array([1.5, np.nan], dtype=np.float64),
                  np.array([True, False])]
        path, disk_bytes = store.write(leaves)
        assert disk_bytes > 0 and os.path.exists(path)
        back = store.read(path)
        for a, b in zip(leaves, back):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert np.array_equal(a, b, equal_nan=True)
        store.remove(path)
        assert store.stats()["files"] == 0


# ---------------------------------------------------------------------------
# 4. the ladder's spill rung
# ---------------------------------------------------------------------------

class TestSpillRung:
    def test_rung_saves_the_run(self, spill_on, monkeypatch):
        # default budget = initial + 3 evict-retries; the 4 injected
        # OOMs burn all of them and only the spill-rung retry succeeds.
        monkeypatch.setenv("SRT_FAULT", "oom:lad:4")
        reset_faults()
        mgr = spill_manager()
        mgr.register_victim("t", lambda: 512)
        before = recovery_stats().snapshot()
        out = oom_ladder("lad", lambda: (fault_point("lad"), "ok")[1])
        assert out == "ok"
        assert recovery_stats().delta(before)["retries"] == 3

    def test_exhaustion_names_spill_rung(self, spill_on, monkeypatch,
                                         tmp_path):
        from spark_rapids_tpu.resilience import ExecutionRecoveryError
        monkeypatch.setenv("SRT_BUNDLE_DIR", str(tmp_path / "bundles"))
        monkeypatch.setenv("SRT_FAULT", "oom:lad2:99")
        reset_faults()
        spill_manager().register_victim("t", lambda: 256)
        with pytest.raises(ExecutionRecoveryError) as ei:
            oom_ladder("lad2", lambda: (fault_point("lad2"), None)[1])
        steps = ei.value.summary.steps
        assert steps[-1] == "spill[256]"
        assert "evict-caches" in steps[0] and "retry" in steps
        # the postmortem bundle carries the same chain, rung included
        bdir = tmp_path / "bundles"
        bundles = [json.loads((bdir / f).read_text())
                   for f in os.listdir(bdir)]
        rungs = [b["recovery"]["steps"] for b in bundles
                 if b.get("reason") == "recovery_exhausted"]
        assert rungs and any("spill[256]" in s for s in rungs)

    def test_enabled_but_nothing_to_free_is_named(self, spill_on,
                                                  monkeypatch):
        from spark_rapids_tpu.resilience import ExecutionRecoveryError
        monkeypatch.setenv("SRT_FAULT", "oom:lad3:99")
        reset_faults()
        with pytest.raises(ExecutionRecoveryError) as ei:
            oom_ladder("lad3", lambda: (fault_point("lad3"), None)[1])
        assert ei.value.summary.steps[-1] == "spill-unavailable"

    def test_default_off_keeps_old_chain(self, monkeypatch):
        from spark_rapids_tpu.resilience import ExecutionRecoveryError
        monkeypatch.setenv("SRT_FAULT", "oom:lad4:99")
        reset_faults()
        spill_manager().register_victim("t", lambda: 256)
        with pytest.raises(ExecutionRecoveryError) as ei:
            oom_ladder("lad4", lambda: (fault_point("lad4"), None)[1])
        assert not any("spill" in s for s in ei.value.summary.steps)


# ---------------------------------------------------------------------------
# 5. end-to-end: larger-than-budget group-by, bit-identical to the oracle
# ---------------------------------------------------------------------------

class TestOutOfCoreOracleParity:
    def _force_spill(self, monkeypatch, spill_dir):
        monkeypatch.setenv("SRT_SPILL_HOST_BYTES", "0")   # disk tier
        monkeypatch.setenv("SRT_SERVE_HBM_BUDGET", "64")  # tiny budget
        monkeypatch.setenv("SRT_SPILL_WATERMARK", "0.5")

    def test_combine_bit_identical_with_receipts(self, spill_on,
                                                 monkeypatch, metrics_on):
        monkeypatch.delenv("SRT_SPILL", raising=False)
        oracle = _combine()                         # SRT_SPILL=0 oracle
        monkeypatch.setenv("SRT_SPILL", "1")
        self._force_spill(monkeypatch, spill_on)
        before = recovery_stats().snapshot()
        spilled = _combine()
        d = recovery_stats().delta(before)
        assert d["spill_bytes_out"] > 0, "no pages went out"
        assert d["spill_bytes_in"] == d["spill_bytes_out"]
        assert d["spill_pages_in"] == d["spill_pages_out"]
        assert d["spill_files"] > 0                 # through the disk tier
        assert spilled.to_pydict() == oracle.to_pydict()
        assert os.listdir(spill_on) == []           # no files leaked
        # the receipts land in QueryMetrics' recovery.spill block
        payload = json.loads(last_stream_metrics().to_json())
        assert payload["schema_version"] == 11
        spill_block = payload["recovery"]["spill"]
        assert spill_block["bytes_out"] > 0
        assert spill_block["bytes_in"] == spill_block["bytes_out"]
        assert "recovery.spill:" in last_stream_metrics().render()

    @pytest.mark.parametrize("fault", ["io:spill-write:1",
                                       "io:spill-read:1"])
    def test_faulted_spill_io_stays_bit_identical(self, spill_on,
                                                  monkeypatch, fault):
        monkeypatch.delenv("SRT_SPILL", raising=False)
        oracle = _combine()
        monkeypatch.setenv("SRT_SPILL", "1")
        self._force_spill(monkeypatch, spill_on)
        monkeypatch.setenv("SRT_FAULT", fault)
        reset_faults()
        before = recovery_stats().snapshot()
        spilled = _combine()
        d = recovery_stats().delta(before)
        assert d["faults_injected"] >= 1, "fault never fired"
        assert d["spill_bytes_out"] > 0
        assert spilled.to_pydict() == oracle.to_pydict()

    def test_spill_write_stall_fails_honestly(self, spill_on,
                                              monkeypatch):
        # A wedged disk must raise the named watchdog error, not hang:
        # the stall is fatal-classified, so with_retries re-raises it
        # straight through instead of retrying into the same wedge.
        monkeypatch.setenv("SRT_STREAM_TIMEOUT", "0.2")
        monkeypatch.setenv("SRT_FAULT", "stall:spill-write:1")
        reset_faults()
        store = SpillFileStore(str(spill_on))
        with pytest.raises(DistStallError, match="spill-write"):
            store.write([np.arange(10)])
        monkeypatch.delenv("SRT_FAULT")  # else reset_faults re-arms it
        reset_faults()                  # release the parked stall thread
        # the store works again (roomy timeout: cold Parquet writer)
        monkeypatch.setenv("SRT_STREAM_TIMEOUT", "30")
        path, _ = store.write([np.arange(10)])
        assert os.path.exists(path)


# ---------------------------------------------------------------------------
# 6. admission: spill instead of reject + proactive watermark
# ---------------------------------------------------------------------------

class TestAdmissionSpill:
    def test_oversize_estimate_rejected_without_spill(self):
        with pytest.raises(AdmissionRejected, match="SRT_SERVE_HBM_BUDGET"):
            AdmissionController(budget=100).check(1000)

    def test_oversize_estimate_admitted_with_spill(self, spill_on,
                                                   metrics_on):
        AdmissionController(budget=100).check(1000)   # no raise
        snap = registry().snapshot()
        assert snap.get("serve.admission.spill_admitted", 0) == 1

    def test_acquire_triggers_proactive_reclaim(self, spill_on,
                                                monkeypatch):
        monkeypatch.setenv("SRT_SPILL_WATERMARK", "0.5")
        freed = []
        mgr = spill_manager()
        mgr.register_victim("t", lambda: freed.append(64) or 64)
        adm = AdmissionController(budget=100)
        adm.acquire(1, 80)              # 80 > 0.5 * 100 -> reclaim
        assert freed == [64]
        adm.release(1)
        assert adm.claimed_bytes() == 0


# ---------------------------------------------------------------------------
# 7. satellite: donated Tables must never be cached
# ---------------------------------------------------------------------------

class TestRefusedDeleted:
    def _donated_table(self):
        import jax
        from spark_rapids_tpu.utils.memory import free
        t = _mk(64, seed=9)
        t = plan().with_columns(w=col("v") * 2).run(t)
        free(*[leaf for leaf in jax.tree_util.tree_leaves(t)
               if leaf is not None])
        assert t.is_deleted()
        return t

    def test_result_cache_refuses_deleted(self, metrics_on):
        from spark_rapids_tpu.serve.result_cache import ResultCache
        cache = ResultCache(1 << 20)
        cache.put(("k",), self._donated_table())
        assert cache.stats()["entries"] == 0
        _, hit = cache.get(("k",))
        assert not hit
        snap = registry().snapshot()
        assert snap.get("serve.cache.refused_deleted", 0) == 1

    def test_result_cache_refuses_deleted_in_list(self, metrics_on):
        from spark_rapids_tpu.serve.result_cache import ResultCache
        cache = ResultCache(1 << 20)
        cache.put(("k",), [_mk(8, 1), self._donated_table()])
        assert cache.stats()["entries"] == 0

    def test_semantic_cache_refuses_deleted(self, metrics_on):
        from spark_rapids_tpu.serve.semantic import SemanticCache
        cache = SemanticCache(1 << 20)
        assert cache.put("fp/dig", "fp", self._donated_table()) is False
        assert cache.peek("fp/dig") is None
        snap = registry().snapshot()
        assert snap.get("serve.cache.refused_deleted", 0) == 1


# ---------------------------------------------------------------------------
# 8. satellite: the admission ledger survives abandoned tickets
# ---------------------------------------------------------------------------

class TestTicketLedger:
    def test_gc_of_abandoned_ticket_releases_claim(self):
        from spark_rapids_tpu.serve.scheduler import Ticket
        adm = AdmissionController(budget=1000)
        t = Ticket(7, "fp", "run", 1.0)
        adm.acquire(t.id, 400)
        t._finalizer = weakref.finalize(t, adm.release, t.id)
        assert adm.claimed_bytes() == 400
        del t
        gc.collect()
        assert adm.claimed_bytes() == 0

    def test_cancel_queued_ticket(self):
        from spark_rapids_tpu.serve.scheduler import QuerySession
        session = QuerySession(max_concurrent=1, register_queued=False)
        gate = threading.Event()

        def slow_batches():
            gate.wait(30)
            yield _mk(64, 0)

        t1 = session.submit(plan().with_columns(w=col("v") + 1),
                            batches=slow_batches())
        t2 = session.submit(plan().with_columns(w=col("v") + 2),
                            table=_mk(64, 1))
        assert t2.cancel() is True
        assert t2.status == "cancelled"
        with pytest.raises(RuntimeError, match="cancelled"):
            t2.result(timeout=5)
        gate.set()
        t1.result(timeout=120)
        assert t1.status == "done"
        assert t2.cancel() is False     # already resolved
        assert t1.cancel() is False     # already done
        assert session.admission.claimed_bytes() == 0
        session.close()

    def test_ledger_zero_after_full_run(self):
        from spark_rapids_tpu.serve.scheduler import QuerySession
        session = QuerySession(max_concurrent=1, register_queued=False)
        t = session.submit(plan().with_columns(w=col("v") + 1),
                           table=_mk(32, 2))
        t.result(timeout=120)
        assert session.admission.claimed_bytes() == 0
        session.close()


# ---------------------------------------------------------------------------
# 9. obs: advisor rule + doctor finding + bench line
# ---------------------------------------------------------------------------

class TestSpillObservability:
    def test_capacity_snapshot_and_rule(self, spill_on):
        from spark_rapids_tpu.obs import capacity
        spill_manager().page_out("k", _value(3))
        snap = capacity.snapshot(window_s=60.0)
        assert snap["spill"]["bytes_out"] > 0
        recs = capacity.recommend(snap)
        actions = {r["action"]: r for r in recs}
        assert "spill_pressure" in actions
        assert actions["spill_pressure"]["evidence"]["spill_bytes_out"] > 0
        spill_manager().page_in("k")

    def test_recommend_without_spill_block_is_quiet(self):
        # derive() stays pure: unit-style snapshots carry no spill block
        # and must not trip the rule.
        from spark_rapids_tpu.obs import capacity
        snap = capacity.snapshot(window_s=60.0)
        snap.pop("spill", None)
        assert all(r["action"] != "spill_pressure"
                   for r in capacity.recommend(snap))

    def test_doctor_flags_spill_thrash(self):
        from spark_rapids_tpu.obs.doctor import diagnose
        qm = {"metric": "query_metrics", "recovery": {
            "spill": {"pages_out": 2, "pages_in": 5, "bytes_out": 4096,
                      "bytes_in": 10240, "files": 3,
                      "page_in_seconds": 0.5}}}
        titles = [f["title"] for f in diagnose(qm)["findings"]]
        assert any("thrashed the spill cache" in t for t in titles)

    def test_doctor_notes_plain_out_of_core(self):
        from spark_rapids_tpu.obs.doctor import diagnose
        qm = {"metric": "query_metrics", "recovery": {
            "spill": {"pages_out": 2, "pages_in": 2, "bytes_out": 4096,
                      "bytes_in": 4096, "files": 0,
                      "page_in_seconds": 0.1}}}
        titles = [f["title"] for f in diagnose(qm)["findings"]]
        assert any("ran out-of-core" in t for t in titles)

    def test_bench_line_spill(self, spill_on):
        from spark_rapids_tpu.obs import bench_line
        spill_manager().page_out("k", _value(4))
        spill_manager().page_in("k")
        payload = json.loads(bench_line("spill"))
        assert payload["metric"] == "spill"
        assert payload["bytes_out"] > 0
        assert payload["bytes_in"] == payload["bytes_out"]

    def test_metrics_counters_mirror(self, spill_on, metrics_on):
        spill_manager().page_out("k", _value(5))
        spill_manager().page_in("k")
        snap = registry().snapshot()
        assert snap.get("recovery.spill.pages_out", 0) == 1
        assert snap.get("recovery.spill.pages_in", 0) == 1
        assert snap.get("recovery.spill.bytes_out", 0) > 0
        assert snap.get("recovery.spill.page_in_seconds", 0) == 1
