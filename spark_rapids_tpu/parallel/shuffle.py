"""Hash shuffle over the mesh: the engine's repartition primitive.

TPU-native equivalent of the RAPIDS Shuffle Manager's UCX/NCCL transport
(SURVEY.md §2.4): rows move between shards with one ``lax.all_to_all`` over
the mesh axis — ICI bandwidth within a slice, DCN across slices — inside a
single jitted ``shard_map``.  No host round-trips, no dynamic shapes:

  1. per shard, order local rows by target partition (one small sort),
  2. slice the ordered rows into P fixed-capacity buckets (padding marked
     in the bucket mask; per-target overflow detected, not silently dropped),
  3. ``all_to_all`` the bucket slabs (the only cross-chip step),
  4. the received P slabs *are* the new shard: capacity P * bucket_size,
     live rows marked in the new row mask.

Overflow handling is cooperative: the op returns an overflow flag (psum of
per-target overruns) plus the observed max bucket occupancy (pmax across
shards); the driver re-runs with a larger ``bucket_size``, jumping straight
to the occupancy the mesh actually reported.  The default ``bucket_size``
is derived from the *live*-row distribution (the busiest sender's rows
spread over P buckets, 2x slack for hash skew) — not from the input's
padded capacity — so chained distributed ops keep output capacity
proportional to real rows.  Both the initial size and the overflow retry
snap onto the shared geometric bucket schedule (exec/bucketing.py), so
hot-key skew is absorbed by stepping up the same capacity ladder every
other stage compiles against, not by drifting into fresh doubled shapes.

The retry loop is BOUNDED (``SRT_SHUFFLE_RETRY_MAX``, default 3): a
pathological key distribution raises
:class:`~spark_rapids_tpu.resilience.ShuffleOverflowError` naming the
observed occupancy instead of recursing until HBM gives out.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from ..column import Column
from ..table import Table
from .hashing import partition_ids
from .mesh import AXIS, DistTable, _DIST_PROGRAMS, mesh_cache_key, shard_map


def shuffle(dist: DistTable, mesh: Mesh, keys: Sequence[str],
            bucket_size: Optional[int] = None, seed: int = 42) -> DistTable:
    """Redistribute rows so equal key tuples land on the same shard.

    Output capacity is ``P * bucket_size`` slots per shard.  The default
    ``bucket_size`` is sized from the *live* row distribution (one
    host-synced P-element reduction), not from the input's padded capacity —
    chained distributed ops (join -> groupby) therefore keep capacity
    proportional to real rows instead of doubling it at every stage.
    """
    from ..config import shuffle_retry_max
    from ..exec.bucketing import bucket_capacity
    from ..obs.metrics import counter, gauge
    from ..resilience import ShuffleOverflowError, dist_guard, fault_point
    from ..utils.memory import record_host_sync
    P = mesh.devices.size
    capacity = dist.capacity_total // P
    if bucket_size is None:
        # Worst sender must fit its rows in P buckets; 2x slack for hash
        # skew, floor of 8 so tiny shards don't thrash the overflow retry.
        import time as _time
        t_sz = _time.perf_counter()
        per_shard_live = jnp.sum(dist.row_mask.reshape(P, capacity), axis=1)
        max_live = int(jnp.max(per_shard_live))   # host sync (P scalars)
        record_host_sync("shuffle.sizing", 8,
                         seconds=_time.perf_counter() - t_sz)
        # Snap to the shared geometric bucket schedule (exec/bucketing.py)
        # so the shard_map's static shapes — and every downstream kernel
        # keyed off capacity_total — recompile once per bucket instead of
        # once per slightly-different live-row count, and chained
        # distributed ops land on capacities other stages already compiled.
        bucket_size = bucket_capacity(2 * (-(-max_live // P)), floor=8)

    pids = partition_ids([dist.table[k] for k in keys], P, seed)
    retries_left = shuffle_retry_max()

    while True:
        counter("shuffle.invocations").inc()
        gauge("shuffle.partitions").set(P)
        # Cross-chip traffic: every shard all_to_alls its P*bucket_size
        # slots of every column (data + validity + mask), so the mesh-wide
        # payload is the full slab set regardless of how many slots are
        # live.
        slab_rows = P * P * bucket_size
        data_bytes = sum(slab_rows * c.data.dtype.itemsize
                         for c in dist.table.columns)
        mask_bytes = slab_rows * (len(dist.table.columns) + 1)
        counter("shuffle.bytes_moved").inc(data_bytes + mask_bytes)

        from ..config import metrics_enabled
        from ..obs import timeline as _tl
        import time as _time
        tl_on = _tl.enabled()
        meter = metrics_enabled()
        t0 = _tl.now_us() if tl_on else 0.0
        t_wall = _time.perf_counter()

        def exchange(bs=bucket_size):
            # Named fault site INSIDE the guarded body: an armed
            # SRT_FAULT "shuffle" spec (optionally shard-targeted) fails
            # here — the mesh ladder of the caller (exec/dist.py
            # dist-join rung) recovers OOMs, and an injected stall parks
            # this worker so the watchdog fires.  The overflow bool is a
            # host sync that blocks on the all_to_all itself, so a
            # wedged exchange raises DistStallError instead of hanging.
            for s in range(P):
                fault_point("shuffle", shard=s)
            o, overflow, occ = _shuffle_arrays(
                dist, mesh, pids, P, capacity, bs)
            return o, bool(overflow), occ
        out, ov, occupancy = dist_guard("shuffle.exchange", exchange)
        record_host_sync("shuffle.overflow_check", 1)
        if meter:
            # The overflow check blocked on the all_to_all, so the wall
            # here covers the exchange — the shuffle's whole ICI story.
            from .mesh import record_ici
            record_ici(data_bytes + mask_bytes,
                       seconds=_time.perf_counter() - t_wall)
        if tl_on:
            # The overflow check above already blocked on the shuffled
            # slabs, so the interval covers the collective's device wall;
            # emit it on every shard lane — the all_to_all is the one
            # all-shards ICI exchange of the shuffle.
            dur = _tl.now_us() - t0
            for s in range(P):
                _tl.add_complete("ici.all_to_all", "ici", t0, dur,
                                 lane=f"shard-{s}", shard=s,
                                 collective="all_to_all",
                                 bucket_size=bucket_size)
        if not ov:
            return out
        occ = int(occupancy)  # mesh-wide max rows any one bucket needed
        if retries_left <= 0:
            raise ShuffleOverflowError(
                f"shuffle overflow persists after {shuffle_retry_max()} "
                f"retry attempt(s) (SRT_SHUFFLE_RETRY_MAX): observed max "
                f"bucket occupancy {occ} rows > bucket_size {bucket_size} "
                f"across {P} partitions; pass bucket_size >= "
                f"{bucket_capacity(occ, floor=8)} explicitly")
        retries_left -= 1
        counter("shuffle.retries").inc()
        # Jump straight to what the mesh reported it needs (at least a
        # doubling), snapped onto the bucket schedule: hot-key skew lands
        # back on a capacity other shuffles (and the compile cache)
        # already know instead of a fresh 2^k * initial.
        bucket_size = bucket_capacity(max(occ, 2 * bucket_size), floor=8)


def _shuffle_arrays(dist: DistTable, mesh: Mesh, pids: jax.Array, P: int,
                    capacity: int, bucket_size: int):
    axis = mesh.axis_names[0]
    names = dist.table.names
    datas = tuple(c.data for c in dist.table.columns)
    valids = tuple(c.valid_mask() for c in dist.table.columns)
    ncols = len(datas)
    fn = _shuffle_program(mesh, axis, P, ncols, capacity, bucket_size)

    results = fn(pids, dist.row_mask, *datas, *valids)
    new_mask = results[0]
    new_datas = results[1:1 + ncols]
    new_valids = results[1 + ncols:-2]
    overflow, occupancy = results[-2], results[-1]

    cols = []
    for name, old, data, valid in zip(names, dist.table.columns, new_datas,
                                      new_valids):
        validity = None if old.validity is None else valid
        cols.append((name, Column(data=data, validity=validity, dtype=old.dtype)))
    return DistTable(table=Table(cols), row_mask=new_mask), overflow, occupancy


def _shuffle_program(mesh: Mesh, axis: str, P: int, ncols: int,
                     capacity: int, bucket_size: int):
    """The shard_map shuffle body, cached in the bounded parallel-program
    LRU (mesh._DIST_PROGRAMS): the closure depends only on the mesh, the
    column count, and the static capacities — jit re-specializes per
    dtype, so one entry serves every same-arity shuffle on the mesh."""
    from ..exec.compile import _lru_lookup
    key = ("shuffle", mesh_cache_key(mesh), ncols, capacity, bucket_size)
    return _lru_lookup(_DIST_PROGRAMS, key,
                       lambda: _build_shuffle_body(mesh, axis, P, ncols,
                                                   capacity, bucket_size),
                       "dist.programs")[0]


def _build_shuffle_body(mesh: Mesh, axis: str, P: int, ncols: int,
                        capacity: int, bucket_size: int):
    @partial(shard_map, mesh=mesh,
             in_specs=(PartitionSpec(axis),) * (2 + 2 * ncols),
             out_specs=((PartitionSpec(axis),) * (1 + 2 * ncols)
                        + (PartitionSpec(), PartitionSpec())))
    def body(pids_l, mask_l, *cols_l):
        datas_l = cols_l[:ncols]
        valids_l = cols_l[ncols:]
        # Dead slots route to a virtual partition P (sorts last, never sent).
        eff_pid = jnp.where(mask_l, pids_l, P)
        order = jnp.argsort(eff_pid, stable=True)
        sorted_pid = eff_pid[order]
        # Bucket boundaries within the sorted local rows.
        starts = jnp.searchsorted(sorted_pid, jnp.arange(P, dtype=jnp.int32))
        ends = jnp.searchsorted(sorted_pid, jnp.arange(P, dtype=jnp.int32),
                                side="right")
        counts = ends - starts                          # (P,)
        overflow = jnp.any(counts > bucket_size)
        # Gather rows into (P * bucket_size,) bucket-major layout.
        slot = jnp.arange(P * bucket_size, dtype=jnp.int32)
        b_target = slot // bucket_size
        b_idx = slot % bucket_size
        src_pos = jnp.take(starts, b_target) + b_idx
        live = b_idx < jnp.take(counts, b_target)
        src = jnp.take(order, jnp.clip(src_pos, 0, capacity - 1))

        def exchange(x, mask_with_live=False):
            bucketed = jnp.take(x, src, axis=0)
            if mask_with_live:
                bucketed = bucketed & live
            return jax.lax.all_to_all(bucketed, axis, split_axis=0,
                                      concat_axis=0, tiled=True)

        new_mask = exchange(mask_l, mask_with_live=True)
        new_datas = tuple(exchange(d) for d in datas_l)
        new_valids = tuple(exchange(v, mask_with_live=True) for v in valids_l)
        overflow_any = jax.lax.psum(overflow.astype(jnp.int32), axis) > 0
        # Mesh-wide max bucket occupancy: what bucket_size would have
        # sufficed.  The bounded retry loop jumps straight to it, and the
        # overflow error names it so a manual rerun needs no bisection.
        occupancy = jax.lax.pmax(jnp.max(counts), axis)
        return (new_mask,) + new_datas + new_valids + (overflow_any,
                                                       occupancy)

    return jax.jit(body)
