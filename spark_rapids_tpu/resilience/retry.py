"""Bounded retry with capped exponential backoff + recovery accounting.

:func:`with_retries` is the generic retry helper (transient IO, flaky
readers); the HBM-OOM ladder in :mod:`.recovery` builds on the same
policy and stats.  All recovery activity in the process accumulates in
ONE :class:`RecoveryStats` (global, locked): executions snapshot before
and delta after to fill the per-query ``recovery`` block of QueryMetrics
(obs/query.py), and registry counters mirror every increment under
``SRT_METRICS=1`` so CI lanes can assert on them.  jax-free at import.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence

from .classify import (CATEGORY_COMPILE, CATEGORY_IO, CATEGORY_OOM,
                       RecoverySummary, classify)


@dataclass(frozen=True)
class RetryPolicy:
    """Retry budget + backoff shape.  ``max_retries`` counts RE-attempts
    (0 = try once, never retry); sleep before retry k (0-based) is
    ``backoff * 2**k`` capped at ``backoff_cap`` seconds."""
    max_retries: int = 3
    backoff: float = 0.05
    backoff_cap: float = 2.0

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        from ..config import retry_backoff, retry_max
        return cls(max_retries=retry_max(), backoff=retry_backoff())

    def delay(self, attempt: int) -> float:
        return min(self.backoff * (2 ** attempt), self.backoff_cap)


class RecoveryStats:
    """Process-wide recovery accounting (single instance, locked).

    Mutators mirror into the metrics registry (no-ops unless
    ``SRT_METRICS=1``); ``snapshot``/``delta`` give executions their
    per-query view without a reset that would race concurrent streams.
    """

    _FIELDS = ("retries", "splits", "cache_evictions", "backoff_seconds",
               "faults_injected", "dist_retries", "dist_splits",
               "dist_fallbacks", "dist_evictions", "spill_pages_out",
               "spill_pages_in", "spill_bytes_out", "spill_bytes_in",
               "spill_files", "spill_page_in_seconds")

    #: Float-seconds fields whose mirrored counter counts OCCURRENCES,
    #: not the (fractional) amount added to the stat.
    _SECONDS_FIELDS = ("backoff_seconds", "spill_page_in_seconds")

    def __init__(self):
        self._lock = threading.Lock()
        self.retries = 0
        self.splits = 0
        self.cache_evictions = 0
        self.backoff_seconds = 0.0
        self.faults_injected = 0
        # Mesh-ladder view: dist rungs ALSO bump the totals above (a dist
        # retry is a retry); these isolate the mesh share for the
        # ``recovery.dist`` block of QueryMetrics.
        self.dist_retries = 0
        self.dist_splits = 0
        self.dist_fallbacks = 0
        self.dist_evictions = 0
        # Out-of-core view (resilience/spill.py): pages/bytes that left
        # HBM and came back, spill files written, and page-in wall — the
        # ``recovery.spill`` block of QueryMetrics.
        self.spill_pages_out = 0
        self.spill_pages_in = 0
        self.spill_bytes_out = 0
        self.spill_bytes_in = 0
        self.spill_files = 0
        self.spill_page_in_seconds = 0.0

    def _bump(self, name: str, amount, counter_name: str) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + amount)
        from ..obs.metrics import counter
        counter(counter_name).inc(amount if name not in self._SECONDS_FIELDS
                                  else 1)

    def add_retry(self) -> None:
        self._bump("retries", 1, "recovery.retries")

    def add_split(self) -> None:
        self._bump("splits", 1, "recovery.splits")

    def add_evictions(self, n: int) -> None:
        self._bump("cache_evictions", n, "recovery.cache_evictions")

    def add_backoff(self, seconds: float) -> None:
        if seconds > 0:
            self._bump("backoff_seconds", seconds, "recovery.backoffs")

    def add_injection(self) -> None:
        self._bump("faults_injected", 1, "resilience.faults_injected")

    def add_dist_retry(self) -> None:
        self._bump("dist_retries", 1, "recovery.dist.retries")

    def add_dist_split(self) -> None:
        self._bump("dist_splits", 1, "recovery.dist.splits")

    def add_dist_fallback(self) -> None:
        self._bump("dist_fallbacks", 1, "recovery.dist.fallbacks")

    def add_dist_evictions(self, n: int) -> None:
        self._bump("dist_evictions", n, "recovery.dist.cache_evictions")

    def add_spill_page_out(self, nbytes: int) -> None:
        self._bump("spill_pages_out", 1, "recovery.spill.pages_out")
        self._bump("spill_bytes_out", nbytes, "recovery.spill.bytes_out")

    def add_spill_page_in(self, nbytes: int, seconds: float) -> None:
        self._bump("spill_pages_in", 1, "recovery.spill.pages_in")
        self._bump("spill_bytes_in", nbytes, "recovery.spill.bytes_in")
        if seconds > 0:
            self._bump("spill_page_in_seconds", seconds,
                       "recovery.spill.page_in_seconds")

    def add_spill_file(self) -> None:
        self._bump("spill_files", 1, "recovery.spill.files")

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {f: getattr(self, f) for f in self._FIELDS}

    def delta(self, before: Dict[str, float]) -> Dict[str, float]:
        now = self.snapshot()
        return {f: now[f] - before.get(f, 0) for f in self._FIELDS}


_STATS = RecoveryStats()


def recovery_stats() -> RecoveryStats:
    """The process-wide recovery accounting object."""
    return _STATS


#: Categories :func:`with_retries` retries by default; ``"fatal"`` is
#: structurally excluded (classify never lands a retryable on it).
DEFAULT_RETRYABLE = (CATEGORY_IO, CATEGORY_OOM, CATEGORY_COMPILE)


def with_retries(fn: Callable, policy: Optional[RetryPolicy] = None,
                 retryable: Sequence[str] = DEFAULT_RETRYABLE,
                 on_retry: Optional[Callable] = None,
                 site: str = ""):
    """Call ``fn()`` with up to ``policy.max_retries`` re-attempts when
    the raised error classifies into ``retryable``.

    On budget exhaustion the ORIGINAL (first) error re-raises with a
    :class:`RecoverySummary` attached as ``exc.recovery_summary`` — the
    caller sees the real failure, annotated with what recovery was
    attempted.  ``on_retry(attempt, exc)`` runs before each sleep (the
    OOM ladder hooks cache eviction here).  Non-retryable errors
    propagate untouched on the first raise.
    """
    if policy is None:
        policy = RetryPolicy.from_env()
    stats = recovery_stats()
    original: Optional[BaseException] = None
    backoff_total = 0.0
    for attempt in range(policy.max_retries + 1):
        try:
            return fn()
        except Exception as exc:
            category = classify(exc)
            if category not in retryable:
                raise
            if original is None:
                original = exc
            if attempt >= policy.max_retries:
                summary = RecoverySummary(
                    site=site, category=classify(original),
                    steps=["retry"] * attempt, retries=attempt,
                    backoff_seconds=backoff_total)
                original.recovery_summary = summary
                raise original
            if on_retry is not None:
                on_retry(attempt, exc)
            from ..obs.timeline import instant, span
            instant("recovery.retry", cat="resilience", site=site,
                    category=category, attempt=attempt)
            delay = policy.delay(attempt)
            if delay > 0:
                with span("recovery.backoff", cat="resilience", site=site,
                          seconds=delay):
                    time.sleep(delay)
            backoff_total += delay
            stats.add_backoff(delay)
            stats.add_retry()
