"""Native host bridge loader + ctypes wrappers.

Python half of the C ABI defined in native/src/bridge.cpp.  Plays the role of
the reference's ``NativeDepsLoader`` (RowConversion.java:23-25: locate the
packaged native library, load it once, lazily) with a dev-tree fallback that
builds the library on demand via g++ (the configure-once semantics of
build-libcudf.xml:22-59).

The wrappers expose the same two entry points as the reference's JNI layer
(convert to/from rows) operating on host numpy buffers, plus the layout
query.  Errors surface as Python exceptions carrying the native message (the
CATCH_STD reverse mapping).
"""

from __future__ import annotations

import atexit
import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

_LIB_NAME = "libspark_rapids_tpu_host.so"
_PKG_DIR = Path(__file__).resolve().parent
_REPO_NATIVE = _PKG_DIR.parent.parent / "native"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class NativeError(RuntimeError):
    """A C++-side failure, message propagated via srt_last_error()."""


def _compile_module():
    """Load native/compile.py (the shared g++ build logic) by path."""
    import importlib.util
    path = _REPO_NATIVE / "compile.py"
    spec = importlib.util.spec_from_file_location("srt_native_compile", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _build_from_source() -> Path:
    """Dev-tree fallback: compile the native library via native/compile.py.

    CMake (native/CMakeLists.txt) is the official build for packagers; the
    shared g++ path keeps a source checkout self-bootstrapping with the same
    flags and provenance definitions as the wheel build (setup.py).
    """
    src = _REPO_NATIVE / "src"
    if not src.is_dir():
        raise NativeError(
            f"{_LIB_NAME} not found in {_PKG_DIR} and no source tree at {src}")
    from .. import __version__
    try:
        return _compile_module().build(src, _PKG_DIR / _LIB_NAME, __version__)
    except RuntimeError as e:
        raise NativeError(str(e)) from e


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i32, i64 = ctypes.c_int32, ctypes.c_int64
    p = ctypes.POINTER
    lib.srt_last_error.restype = ctypes.c_char_p
    lib.srt_version.restype = ctypes.c_char_p
    lib.srt_build_info.restype = ctypes.c_char_p
    lib.srt_compute_fixed_width_layout.restype = i32
    lib.srt_compute_fixed_width_layout.argtypes = [
        i32, p(i32), p(i32), p(i32), p(i32), p(i32), p(i32), p(i32)]
    lib.srt_pack_rows.restype = i32
    lib.srt_pack_rows.argtypes = [
        i32, p(i32), p(i32), i64, p(ctypes.c_void_p), p(ctypes.c_void_p),
        ctypes.c_void_p]
    lib.srt_unpack_rows.restype = i32
    lib.srt_unpack_rows.argtypes = [
        i32, p(i32), p(i32), i64, ctypes.c_void_p, i64, p(ctypes.c_void_p),
        p(ctypes.c_void_p)]
    lib.srt_convert_to_rows.restype = i64
    lib.srt_convert_to_rows.argtypes = [
        i32, p(i32), p(i32), i64, p(ctypes.c_void_p), p(ctypes.c_void_p),
        i64, i32, p(i32), p(i32)]
    lib.srt_blobs_count.restype = i32
    lib.srt_blobs_count.argtypes = [i64]
    lib.srt_blob_num_rows.restype = i64
    lib.srt_blob_num_rows.argtypes = [i64, i32]
    lib.srt_blob_row_size.restype = i32
    lib.srt_blob_row_size.argtypes = [i64, i32]
    lib.srt_blob_data.restype = ctypes.c_void_p
    lib.srt_blob_data.argtypes = [i64, i32]
    lib.srt_blobs_free.restype = None
    lib.srt_blobs_free.argtypes = [i64]
    u8p = p(ctypes.c_uint8)
    lib.srt_rle_count_runs.restype = i32
    lib.srt_rle_count_runs.argtypes = [u8p, i64, i32, i64, p(i64)]
    lib.srt_rle_parse_runs.restype = i32
    lib.srt_rle_parse_runs.argtypes = [
        u8p, i64, i32, i64, i64, p(i32), p(i64), p(i32), p(i64), u8p,
        p(i64), p(i64)]
    return lib


def _stale(lib_path: Path) -> bool:
    """True when any native source is newer than the built library."""
    src = _REPO_NATIVE / "src"
    if not src.is_dir():
        return False
    built = lib_path.stat().st_mtime
    return any(f.stat().st_mtime > built
               for f in src.iterdir() if f.suffix in (".cpp", ".hpp"))


def load() -> ctypes.CDLL:
    """Locate (or build) and load the native library, once per process.

    Resolution order: explicit ``SPARK_RAPIDS_TPU_NATIVE_LIB`` override, then
    the packaged/previously-built library (rebuilt if the native sources are
    newer — the configure-once-but-track-changes semantics of
    build-libcudf.xml:22-30), then a fresh source build.
    """
    global _lib
    with _lock:
        if _lib is None:
            from ..config import native_lib_override
            env = native_lib_override()
            if env:
                path = Path(env)
            else:
                path = _PKG_DIR / _LIB_NAME
                if not path.exists() or _stale(path):
                    path = _build_from_source()
            _lib = _bind(ctypes.CDLL(str(path)))
        return _lib


def _check(lib: ctypes.CDLL, status: int) -> None:
    if status != 0:
        msg = lib.srt_last_error().decode()
        raise ValueError(msg) if status == 1 else NativeError(msg)


def build_info() -> dict:
    """Provenance stamped into the native artifact (build/build-info analog)."""
    lib = load()
    pairs = (kv.split("=", 1) for kv in lib.srt_build_info().decode().split(";"))
    return {k: v for k, v in pairs}


def _schema_arrays(schema) -> tuple:
    ids = np.asarray([int(dt.type_id) for dt in schema], np.int32)
    scales = np.asarray([int(getattr(dt, "scale", 0) or 0) for dt in schema],
                        np.int32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    # Keep the numpy arrays alive alongside the pointers.
    return (len(schema), ids.ctypes.data_as(i32p), scales.ctypes.data_as(i32p),
            ids, scales)


def compute_fixed_width_layout(schema) -> dict:
    """Native layout query; must agree byte-for-byte with rows/layout.py."""
    lib = load()
    ncols, ids_p, scales_p, *_keep = _schema_arrays(schema)
    starts = np.zeros(ncols, np.int32)
    sizes = np.zeros(ncols, np.int32)
    voff, vbytes, rsize = ctypes.c_int32(), ctypes.c_int32(), ctypes.c_int32()
    i32p = ctypes.POINTER(ctypes.c_int32)
    _check(lib, lib.srt_compute_fixed_width_layout(
        ncols, ids_p, scales_p, starts.ctypes.data_as(i32p),
        sizes.ctypes.data_as(i32p), ctypes.byref(voff), ctypes.byref(vbytes),
        ctypes.byref(rsize)))
    return {
        "column_starts": tuple(int(x) for x in starts),
        "column_sizes": tuple(int(x) for x in sizes),
        "validity_offset": voff.value,
        "validity_bytes": vbytes.value,
        "row_size": rsize.value,
    }


def _buffer_array(arrays: Sequence[Optional[np.ndarray]]):
    ptrs = (ctypes.c_void_p * len(arrays))()
    for i, a in enumerate(arrays):
        ptrs[i] = None if a is None else a.ctypes.data_as(ctypes.c_void_p).value
    return ptrs


def _checked_buffers(schema, datas, valids):
    """Validate + coerce caller buffers against the schema before they cross
    the FFI boundary (lengths and physical dtypes must match or native code
    would read out of bounds / pack garbage)."""
    if len(datas) != len(schema) or len(valids) != len(schema):
        raise ValueError(
            f"{len(datas)} data / {len(valids)} validity buffers for "
            f"{len(schema)} schema columns")
    num_rows = int(np.asarray(datas[0]).shape[0]) if datas else 0
    out_d, out_v = [], []
    for i, (dt, d, v) in enumerate(zip(schema, datas, valids)):
        d = np.ascontiguousarray(d)
        want = dt.np_dtype
        # Same width AND compatible kind: integer/bool buffers may view each
        # other (timestamps/decimals travel as int64), but float-for-int or
        # int-for-float of the same width is a caller bug, not a view.
        compatible = d.dtype == want or (
            d.dtype.itemsize == want.itemsize
            and d.dtype.kind in "iub" and want.kind in "iub")
        if not compatible:
            raise ValueError(
                f"column {i}: buffer dtype {d.dtype} does not match {dt!r}")
        if d.ndim != 1 or d.shape[0] != num_rows:
            raise ValueError(
                f"column {i}: expected shape ({num_rows},), got {d.shape}")
        if v is not None:
            v = np.ascontiguousarray(v, np.uint8)
            if v.ndim != 1 or v.shape[0] != num_rows:
                raise ValueError(
                    f"column {i}: validity shape {v.shape} != ({num_rows},)")
        out_d.append(d)
        out_v.append(v)
    return num_rows, out_d, out_v


def pack_rows(schema, datas: Sequence[np.ndarray],
              valids: Sequence[Optional[np.ndarray]]) -> np.ndarray:
    """Columnar numpy buffers -> one contiguous row-format byte buffer."""
    lib = load()
    ncols, ids_p, scales_p, *_keep = _schema_arrays(schema)
    # Size the output via the pure-Python layout engine (byte-identical by
    # test contract) — no extra FFI round trip on the hot path.
    from ..rows.layout import compute_fixed_width_layout as _py_layout
    row_size = _py_layout(schema).row_size
    num_rows, datas, valids = _checked_buffers(schema, datas, valids)
    # np.empty, not zeros: the native pack memsets the whole range itself
    # (its deterministic-zeros contract), so pre-zeroing is a wasted pass.
    out = np.empty(num_rows * row_size, np.uint8)
    _check(lib, lib.srt_pack_rows(
        ncols, ids_p, scales_p, num_rows, _buffer_array(datas),
        _buffer_array(valids), out.ctypes.data_as(ctypes.c_void_p)))
    return out


def unpack_rows(schema, rows: np.ndarray, num_rows: int):
    """Row-format byte buffer -> (list of column arrays, list of bool arrays).

    Validates the buffer size against the schema layout, as the reference does
    (row_conversion.cu:541).
    """
    lib = load()
    ncols, ids_p, scales_p, *_keep = _schema_arrays(schema)
    rows = np.ascontiguousarray(rows, np.uint8)
    datas = [np.zeros(num_rows, dt.np_dtype) for dt in schema]
    valids = [np.zeros(num_rows, np.uint8) for _ in schema]
    _check(lib, lib.srt_unpack_rows(
        ncols, ids_p, scales_p, num_rows, rows.ctypes.data_as(ctypes.c_void_p),
        rows.size, _buffer_array(datas), _buffer_array(valids)))
    return datas, [v.astype(np.bool_) for v in valids]


class RowBlobs:
    """Caller-owned native blob set — the reference's handle contract.

    The reference returns *released* native column pointers across the JNI
    boundary and the Java caller owns closing them (RowConversionJni.cpp:33-38,
    RowConversionTest.java:53-57), with opt-in leak diagnostics under
    ``-Dai.rapids.refcount.debug``.  This class is that contract for Python:
    it wraps the ``srt_convert_to_rows`` handle, exposes zero-copy views into
    native memory, must be :meth:`close`\\ d (or used as a context manager),
    and — when ``SRT_LEAK_DEBUG=1`` — records its creation stack and reports
    any still-open handle at interpreter exit.
    """

    def __init__(self, lib: ctypes.CDLL, handle: int, count: int):
        self._lib = lib
        self._handle = handle
        self._count = count
        self._creation_stack: Optional[str] = None
        from ..config import leak_debug_enabled
        if leak_debug_enabled():
            import traceback
            self._creation_stack = "".join(traceback.format_stack(limit=16))
            _live_blobs[id(self)] = self

    @property
    def closed(self) -> bool:
        return self._handle == 0

    def _require_open(self) -> int:
        if self._handle == 0:
            raise NativeError("RowBlobs used after close()")
        return self._handle

    def __len__(self) -> int:
        return self._count

    def num_rows(self, i: int) -> int:
        return int(self._lib.srt_blob_num_rows(self._require_open(), i))

    def row_size(self, i: int) -> int:
        return int(self._lib.srt_blob_row_size(self._require_open(), i))

    def data(self, i: int) -> np.ndarray:
        """Zero-copy uint8 view into the native blob (valid until close)."""
        handle = self._require_open()
        nbytes = self.num_rows(i) * self.row_size(i)
        addr = self._lib.srt_blob_data(handle, i)
        if nbytes == 0 or addr is None:
            return np.zeros(0, np.uint8)
        buf = (ctypes.c_uint8 * nbytes).from_address(addr)
        return np.frombuffer(buf, np.uint8)

    def to_arrays(self) -> list[np.ndarray]:
        """Python-owned copies of every blob."""
        return [self.data(i).copy() for i in range(self._count)]

    def close(self) -> None:
        if self._handle != 0:
            self._lib.srt_blobs_free(self._handle)
            self._handle = 0
            _live_blobs.pop(id(self), None)

    def __enter__(self) -> "RowBlobs":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover - GC timing dependent
        # Deliberately NOT freeing here: the contract is caller-owns-close,
        # and silently freeing on GC would mask lifetime bugs the leak
        # debugger exists to catch.  Native memory is reclaimed at process
        # exit by the OS; the leak report names the allocation site.
        pass


# Live handle registry for SRT_LEAK_DEBUG (populated by RowBlobs.__init__).
_live_blobs: dict = {}


def _report_leaks() -> None:  # pragma: no cover - exercised via subprocess test
    if not _live_blobs:
        return
    import sys
    print(f"[spark_rapids_tpu] LEAK: {len(_live_blobs)} RowBlobs handle(s) "
          "never closed:", file=sys.stderr)
    for blobs in _live_blobs.values():
        stack = blobs._creation_stack or "<creation stack not recorded>"
        print(f"  - {len(blobs)} blob(s), created at:\n{stack}",
              file=sys.stderr)


atexit.register(_report_leaks)


def convert_to_rows_handle(schema, datas: Sequence[np.ndarray],
                           valids: Sequence[Optional[np.ndarray]],
                           max_batch_bytes: int = 0,
                           check_row_width: bool = True) -> RowBlobs:
    """Batched conversion returning a caller-owned :class:`RowBlobs` handle.

    Applies the reference's output contract (blobs capped at 2 GB, batch row
    counts in 32-row multiples, optional 1 KB row-width gate —
    row_conversion.cu:458-517).
    """
    lib = load()
    ncols, ids_p, scales_p, *_keep = _schema_arrays(schema)
    num_rows, datas, valids = _checked_buffers(schema, datas, valids)
    nblobs = ctypes.c_int32()
    status = ctypes.c_int32()
    handle = lib.srt_convert_to_rows(
        ncols, ids_p, scales_p, num_rows, _buffer_array(datas),
        _buffer_array(valids), max_batch_bytes, 1 if check_row_width else 0,
        ctypes.byref(nblobs), ctypes.byref(status))
    if handle == 0:
        _check(lib, status.value or 2)
    return RowBlobs(lib, handle, nblobs.value)


def convert_to_rows(schema, datas: Sequence[np.ndarray],
                    valids: Sequence[Optional[np.ndarray]],
                    max_batch_bytes: int = 0,
                    check_row_width: bool = True) -> list[np.ndarray]:
    """Copying convenience over :func:`convert_to_rows_handle`."""
    with convert_to_rows_handle(schema, datas, valids, max_batch_bytes,
                                check_row_width) as blobs:
        return blobs.to_arrays()


def parse_rle_runs(buf: bytes, bit_width: int, num_values: int):
    """Native single-pass RLE/bit-packed run parse (+ width-1 popcount).

    Returns ``(runs, ones)`` where ``runs`` has the same keys as the Python
    reference parser (``spark_rapids_tpu.io.parquet_native.parse_rle_runs``)
    and ``ones`` is the count of 1-values for width-1 streams (``None``
    otherwise).  Raises ``ValueError`` on truncated/exhausted streams.
    """
    lib = load()
    i64 = ctypes.c_int64
    n = len(buf)
    # Zero-copy view: `view` must stay referenced across both native calls.
    view = np.frombuffer(buf, np.uint8) if n else None
    cbuf = ctypes.cast(view.ctypes.data,
                       ctypes.POINTER(ctypes.c_uint8)) if n else None
    n_runs = i64(0)
    _check(lib, lib.srt_rle_count_runs(cbuf, n, bit_width, num_values,
                                       ctypes.byref(n_runs)))
    r = n_runs.value
    out_start = np.empty(r, np.int32)
    count = np.empty(r, np.int64)
    rle_value = np.empty(r, np.int32)
    bp_bit_base = np.empty(r, np.int64)
    is_rle = np.empty(r, np.uint8)
    ones = i64(0)
    as_p = ctypes.cast
    _check(lib, lib.srt_rle_parse_runs(
        cbuf, n, bit_width, num_values, r,
        as_p(out_start.ctypes.data, ctypes.POINTER(ctypes.c_int32)),
        as_p(count.ctypes.data, ctypes.POINTER(ctypes.c_int64)),
        as_p(rle_value.ctypes.data, ctypes.POINTER(ctypes.c_int32)),
        as_p(bp_bit_base.ctypes.data, ctypes.POINTER(ctypes.c_int64)),
        as_p(is_rle.ctypes.data, ctypes.POINTER(ctypes.c_uint8)),
        ctypes.byref(n_runs), ctypes.byref(ones)))
    runs = {
        "out_start": out_start,
        "count": count,
        "rle_value": rle_value,
        "bp_bit_base": bp_bit_base,
        "is_rle": is_rle.astype(np.bool_),
    }
    return runs, (ones.value if bit_width == 1 else None)


__all__ = [
    "NativeError",
    "RowBlobs",
    "build_info",
    "compute_fixed_width_layout",
    "convert_to_rows",
    "convert_to_rows_handle",
    "load",
    "pack_rows",
    "parse_rle_runs",
    "unpack_rows",
]
