"""Shape-bucketed execution contracts (exec/bucketing.py).

Three guarantees, in order of importance:

1. **Result identity** — bucketed execution (the default) is bit-for-bit
   identical to the eager oracle across row counts straddling bucket
   boundaries, including null-laden columns, string/dict columns, and
   inputs that filter down to zero rows.  Pad rows are NULL and masked
   out from bind time, so no aggregate, join, sort, or vocab may ever
   observe them.
2. **One compile per bucket** — two different row counts landing in the
   same bucket bind to the same signature: exactly one whole-plan
   compile-cache miss then a hit (the acceptance criterion, observable
   through the SRT_METRICS counters and the benchmarks' JSON line).
3. **Schedule + knobs** — the geometric capacity schedule is deterministic
   and 8-aligned, ``SRT_SHAPE_BUCKETS=0`` restores exact-shape binding,
   and ``SRT_COMPILE_CACHE_CAP`` LRU-bounds the program cache.
"""

import json
from collections import OrderedDict

import numpy as np
import pytest

from spark_rapids_tpu import Column, Table, assert_tables_equal
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.config import shape_buckets
from spark_rapids_tpu.exec import col, plan
from spark_rapids_tpu.exec import compile as compile_mod
from spark_rapids_tpu.exec.bucketing import (bucket_capacity, bucket_stats,
                                             enabled, prepare_input,
                                             plan_bucketable)
from spark_rapids_tpu.exec.compile import run_plan_eager
from spark_rapids_tpu.obs import registry


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def metrics_on(monkeypatch):
    monkeypatch.setenv("SRT_METRICS", "1")
    registry().reset()
    yield
    registry().reset()


def _table(prefix, n, with_strings=False, rng=None):
    """Null-laden mixed table; value domains depend only on ``prefix`` and
    row position (NOT on ``n``), so two lengths in one bucket probe the
    same key domains / string vocab and share one bound signature."""
    rng = rng or np.random.default_rng(7)
    cols = [
        (f"{prefix}_k", Column.from_numpy(
            (np.arange(n) % 7).astype(np.int32),
            validity=(np.arange(n) % 11) != 0)),
        (f"{prefix}_v", Column.from_numpy(
            np.arange(n, dtype=np.int64) - n // 2,
            validity=(np.arange(n) % 13) != 0)),
        (f"{prefix}_f", Column.from_numpy(rng.normal(size=n))),
    ]
    if with_strings:
        words = ["alpha", "beta", "gamma", "", "delta"]
        vals = [None if i % 9 == 0 else words[i % 5] for i in range(n)]
        cols.append((f"{prefix}_s", Column.from_pylist(vals, dt.STRING)))
    return Table(cols)


def _query(prefix):
    """filter -> project -> groupby -> sort.  Aggregates are chosen to be
    reduction-order independent (int sums, max, count) so the eager oracle
    comparison is exact: float mean/sum over unordered reductions differs
    in the last ulp between the compiled and eager paths regardless of
    bucketing (see test_bit_for_bit_vs_exact_shape for that case)."""
    return (plan()
            .filter(col(f"{prefix}_v") > -10_000)
            .with_columns(**{f"{prefix}_w": col(f"{prefix}_f") * 2.0})
            .groupby_agg([f"{prefix}_k"],
                         [(f"{prefix}_v", "sum", "vs"),
                          (f"{prefix}_w", "max", "wx"),
                          (f"{prefix}_v", "mean", "vm"),
                          (f"{prefix}_v", "count", "n")])
            .sort_by([f"{prefix}_k"]))


class TestBucketCapacity:
    def test_default_schedule_values(self):
        # Pinned observations of the default floor=64 growth=1.3 schedule.
        for n, cap in [(1, 64), (64, 64), (65, 88), (88, 88), (89, 112),
                       (100, 112), (110, 112), (120, 144), (1000, 1152)]:
            assert bucket_capacity(n) == cap, n

    def test_schedule_invariants(self):
        prev = 0
        for n in range(1, 5000, 17):
            cap = bucket_capacity(n)
            assert cap >= n
            assert cap % 8 == 0
            assert cap >= prev          # monotone in n
            prev = cap

    def test_explicit_floor_growth(self):
        assert bucket_capacity(1, floor=8, growth=2.0) == 8
        assert bucket_capacity(9, floor=8, growth=2.0) == 16
        assert bucket_capacity(17, floor=8, growth=2.0) == 32

    def test_env_schedule(self, monkeypatch):
        monkeypatch.setenv("SRT_SHAPE_BUCKETS", "32:2.0")
        assert shape_buckets() == (32, 2.0)
        assert bucket_capacity(1) == 32
        assert bucket_capacity(33) == 64
        assert bucket_capacity(65) == 128

    @pytest.mark.parametrize("raw", ["abc", "64:0.9", "0:2", "64:1.0"])
    def test_env_schedule_invalid(self, monkeypatch, raw):
        monkeypatch.setenv("SRT_SHAPE_BUCKETS", raw)
        with pytest.raises(ValueError, match="SRT_SHAPE_BUCKETS"):
            shape_buckets()

    @pytest.mark.parametrize("raw", ["0", "off", "false", "no"])
    def test_env_disable(self, monkeypatch, raw):
        monkeypatch.setenv("SRT_SHAPE_BUCKETS", raw)
        assert shape_buckets() is None
        assert not enabled()


class TestResultIdentity:
    """Bucketed run == eager oracle, across bucket-boundary row counts."""

    # Straddles the 64 | 88 | 112 boundaries plus a deep interior point.
    BOUNDARY_NS = [1, 63, 64, 65, 88, 89, 112, 113, 200]

    @pytest.mark.parametrize("n", BOUNDARY_NS)
    def test_mixed_nulls(self, rng, n):
        t = _table("bi", n, rng=rng)
        p = _query("bi")
        assert_tables_equal(run_plan_eager(p, t), p.run(t))

    @pytest.mark.parametrize("n", [63, 64, 65, 100])
    def test_strings_dict_columns(self, rng, n):
        t = _table("bs", n, with_strings=True, rng=rng)
        p = (plan()
             .filter(col("bs_v") > -10_000)
             .groupby_agg(["bs_s"], [("bs_v", "sum", "vs"),
                                     ("bs_v", "count", "cnt")])
             .sort_by(["bs_s"]))
        assert_tables_equal(run_plan_eager(p, t), p.run(t))

    @pytest.mark.parametrize("n", [65, 100])
    def test_empty_after_filter(self, rng, n):
        t = _table("be", n, rng=rng)
        p = (plan().filter(col("be_v") > 10_000_000)
             .groupby_agg(["be_k"], [("be_v", "sum", "vs")])
             .sort_by(["be_k"]))
        got = p.run(t)
        assert got.num_rows == 0
        assert_tables_equal(run_plan_eager(p, t), got)

    @pytest.mark.parametrize("n", [63, 65, 100])
    def test_bit_for_bit_vs_exact_shape(self, monkeypatch, rng, n):
        """The acceptance criterion proper: bucketed output is bit-for-bit
        identical to exact-shape compiled output, including float means
        (pad rows are masked zeros — they must not perturb reductions)."""
        t = _table("bb", n, rng=rng)
        p = (plan()
             .filter(col("bb_v") > -10_000)
             .groupby_agg(["bb_k"], [("bb_f", "mean", "fm"),
                                     ("bb_f", "sum", "fs")])
             .sort_by(["bb_k"]))
        monkeypatch.setenv("SRT_SHAPE_BUCKETS", "0")
        exact = p.run(t)
        monkeypatch.setenv("SRT_SHAPE_BUCKETS", "1")
        bucketed = p.run(t)
        assert_tables_equal(exact, bucketed)

    def test_run_padded_capacity_and_live_count(self, rng):
        t = _table("bp", 100, rng=rng)
        p = plan().filter(col("bp_v") > 0)
        padded, sel = p.run_padded(t)
        assert padded.num_rows == bucket_capacity(100)  # 112 slots
        keep = np.asarray(sel.data).astype(bool)
        assert int(keep.sum()) == run_plan_eager(p, t).num_rows
        # Pad slots are never live.
        assert not keep[100:].any()


class TestOneCompilePerBucket:
    """The acceptance criterion: two row counts in one bucket -> exactly
    one whole-plan compile-cache miss, then a hit."""

    def test_one_miss_one_hit(self, metrics_on):
        n1, n2 = 90, 100
        cap = bucket_capacity(n1)
        assert bucket_capacity(n2) == cap   # same bucket by construction
        p = _query("b1")
        out1 = p.run(_table("b1", n1))
        out2 = p.run(_table("b1", n2))
        snap = registry().snapshot()
        assert snap.get("plan.compile_cache.miss", 0) == 1
        assert snap.get("plan.compile_cache.hit", 0) == 1
        # Both results still match the oracle, padded or not.
        assert_tables_equal(run_plan_eager(p, _table("b1", n1)), out1)
        assert_tables_equal(run_plan_eager(p, _table("b1", n2)), out2)

    def test_bucket_counters(self, metrics_on):
        n = 90
        cap = bucket_capacity(n)
        p = _query("b2")
        p.run(_table("b2", n))
        snap = registry().snapshot()
        assert snap.get("plan.bucket.pad_rows", 0) == cap - n
        assert snap.get("plan.bucket.rows_total", 0) == cap
        assert snap.get("plan.bucket.waste_frac") == pytest.approx(
            (cap - n) / cap, abs=1e-5)

    def test_bench_cache_line_payload(self, metrics_on):
        from spark_rapids_tpu.obs import bench_cache_line
        p = _query("b3")
        p.run(_table("b3", 90))
        p.run(_table("b3", 100))
        payload = json.loads(bench_cache_line())
        assert payload["metric"] == "compile_cache"
        assert payload["hits"] == 1 and payload["misses"] == 1
        assert payload["hit_rate"] == pytest.approx(0.5)
        b = payload["bucketing"]
        assert b["enabled"] is True
        assert b["pad_rows"] > 0 and b["rows_total"] > 0
        assert 0.0 < b["pad_waste_frac"] < 1.0
        assert b["distinct_input_shapes"] >= 2
        assert b["recompiles_avoided"] >= 1


class TestDisableKnob:
    def test_exact_shape_when_off(self, monkeypatch, rng):
        monkeypatch.setenv("SRT_SHAPE_BUCKETS", "0")
        t = _table("bd", 100, rng=rng)
        p = plan().filter(col("bd_v") > 0)
        assert prepare_input(p, t) is None
        padded, _sel = p.run_padded(t)
        assert padded.num_rows == t.num_rows     # pre-bucketing behavior
        assert_tables_equal(run_plan_eager(p, t), p.run(t))

    def test_gates(self, rng):
        # Empty tables take the eager path.
        empty = Table([("g_k", Column.from_numpy(
            np.array([], dtype=np.int32)))])
        assert prepare_input(plan(), empty) is None
        # JoinShuffledStep plans bind row-aligned probes: never bucketed.
        dim = Table([("g_d", Column.from_numpy(
            np.arange(4, dtype=np.int64)))])
        pj = plan().join_shuffled(dim, left_on="g_k", right_on="g_d")
        assert not plan_bucketable(pj)


class TestCompileCacheLRU:
    def test_eviction_respects_cap(self, monkeypatch, rng):
        monkeypatch.setenv("SRT_COMPILE_CACHE_CAP", "2")
        # Fresh cache for the test so the process-global one (and the
        # other tests' entries) survives untouched.
        monkeypatch.setattr(compile_mod, "_COMPILED", OrderedDict())
        tables = [(_query(f"lru{i}"), _table(f"lru{i}", 64, rng=rng))
                  for i in range(3)]
        for p, t in tables:
            p.run(t)
        assert len(compile_mod._COMPILED) == 2
        # The evicted (oldest) program re-binds and still runs correctly.
        p0, t0 = tables[0]
        assert_tables_equal(run_plan_eager(p0, t0), p0.run(t0))
        assert len(compile_mod._COMPILED) == 2

    def test_lru_order_hit_refreshes(self, monkeypatch, rng):
        monkeypatch.setenv("SRT_COMPILE_CACHE_CAP", "2")
        monkeypatch.setattr(compile_mod, "_COMPILED", OrderedDict())
        pa, ta = _query("lra"), _table("lra", 64, rng=rng)
        pb, tb = _query("lrb"), _table("lrb", 64, rng=rng)
        pc, tc = _query("lrc"), _table("lrc", 64, rng=rng)
        pa.run(ta)
        pb.run(tb)
        pa.run(ta)                       # refresh A: B becomes LRU
        keys_before = list(compile_mod._COMPILED)
        pc.run(tc)                       # evicts B, not A
        assert keys_before[1] in compile_mod._COMPILED   # A survived
        assert keys_before[0] not in compile_mod._COMPILED

    def test_eviction_counter_and_size_gauge(self, metrics_on, monkeypatch,
                                             rng):
        monkeypatch.setenv("SRT_COMPILE_CACHE_CAP", "1")
        monkeypatch.setattr(compile_mod, "_COMPILED", OrderedDict())
        for i in range(2):
            p = _query(f"lrg{i}")
            p.run(_table(f"lrg{i}", 64, rng=rng))
        snap = registry().snapshot()
        assert snap.get("plan.compile_cache.evictions", 0) == 1
        assert snap.get("plan.compile_cache.size") == 1


class TestPadMemoization:
    def test_rerun_reuses_padded_buffers(self, rng):
        t = _table("pm", 90, rng=rng)
        p = plan().filter(col("pm_v") > 0)
        b1 = prepare_input(p, t)
        b2 = prepare_input(p, t)
        assert b1 is not None and b2 is not None
        # Identity (not just equality): the stats-probe and dict-encode
        # caches key on buffer ids, so reruns must hand the binder the
        # same padded objects to stay sync-free.
        assert b1.table is b2.table
        assert b1.live_mask is b2.live_mask
        assert b1.pad_rows == bucket_capacity(90) - 90

    def test_bucket_stats_shape(self):
        s = bucket_stats()
        assert set(s) == {"enabled", "distinct_input_shapes",
                          "distinct_capacities", "recompiles_avoided"}
