"""Columnar core tests: dtypes, Column, Table, pytree behavior."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.column import Column
from spark_rapids_tpu.table import Table, assert_tables_equal


class TestDtypes:
    def test_type_ids_match_cudf_numbering(self):
        # Wire contract: ids must match cudf 22.06 (RowConversionJni.cpp:56-61).
        assert dt.TypeId.INT8 == 1
        assert dt.TypeId.INT64 == 4
        assert dt.TypeId.FLOAT64 == 10
        assert dt.TypeId.BOOL8 == 11
        assert dt.TypeId.STRING == 23
        assert dt.TypeId.DECIMAL32 == 25
        assert dt.TypeId.DECIMAL64 == 26

    def test_itemsizes(self):
        assert dt.INT8.itemsize == 1
        assert dt.INT16.itemsize == 2
        assert dt.INT32.itemsize == 4
        assert dt.INT64.itemsize == 8
        assert dt.FLOAT32.itemsize == 4
        assert dt.FLOAT64.itemsize == 8
        assert dt.BOOL8.itemsize == 1
        assert dt.decimal32(-2).itemsize == 4
        assert dt.decimal64(-4).itemsize == 8
        assert dt.TIMESTAMP_DAYS.itemsize == 4
        assert dt.TIMESTAMP_MICROSECONDS.itemsize == 8

    def test_decimal_scale_round_trips_through_wire_format(self):
        schema = dt.from_type_ids([4, 25, 26], [0, -2, -5])
        assert schema == [dt.INT64, dt.decimal32(-2), dt.decimal64(-5)]

    def test_scale_rejected_for_non_decimal(self):
        with pytest.raises(ValueError):
            dt.DType(dt.TypeId.INT32, scale=-2)

    def test_variable_width_has_no_itemsize(self):
        with pytest.raises(ValueError):
            dt.STRING.itemsize


class TestColumn:
    def test_from_pylist_with_nulls(self):
        c = Column.from_pylist([1, None, 3], dt.INT32)
        assert c.size == 3
        assert c.null_count() == 1
        assert c.to_pylist() == [1, None, 3]

    def test_all_valid_has_no_mask(self):
        c = Column.from_pylist([1, 2, 3], dt.INT64)
        assert c.validity is None
        assert c.null_count() == 0

    def test_bool8_stored_as_bytes(self):
        c = Column.from_pylist([True, None, False], dt.BOOL8)
        assert c.data.dtype == jnp.uint8
        assert c.to_pylist() == [True, None, False]

    def test_int64_precision_preserved(self):
        big = 2**62 + 12345
        c = Column.from_pylist([big, -big], dt.INT64)
        assert c.to_pylist() == [big, -big]

    def test_gather(self):
        c = Column.from_pylist([10, None, 30, 40], dt.INT32)
        g = c.gather(jnp.array([3, 1, 0]))
        assert g.to_pylist() == [40, None, 10]

    def test_column_is_pytree(self):
        c = Column.from_pylist([1.5, None, 2.5], dt.FLOAT64)
        leaves, treedef = jax.tree_util.tree_flatten(c)
        c2 = jax.tree_util.tree_unflatten(treedef, leaves)
        assert c2.dtype == dt.FLOAT64
        assert c2.to_pylist() == c.to_pylist()

    def test_jit_over_column(self):
        c = Column.from_pylist([1, 2, None, 4], dt.INT32)

        @jax.jit
        def double(col: Column) -> Column:
            return Column(data=col.data * 2, validity=col.validity, dtype=col.dtype)

        assert double(c).to_pylist() == [2, 4, None, 8]


class TestStrings:
    def test_pylist_roundtrip_with_nulls(self):
        c = Column.from_pylist(["hello", None, "", "wörld"], dt.STRING)
        assert c.size == 4
        assert c.to_pylist() == ["hello", None, "", "wörld"]

    def test_inferred_from_pydict(self):
        t = Table.from_pydict({"s": ["a", "bc", None]})
        assert t.schema() == [dt.STRING]
        assert t.to_pydict() == {"s": ["a", "bc", None]}

    def test_gather(self):
        c = Column.from_pylist(["aa", "b", None, "dddd"], dt.STRING)
        g = c.gather(jnp.array([3, 0, 2]))
        assert g.to_pylist() == ["dddd", "aa", None]


class TestGatherBounds:
    def test_fill_invalid_nullifies_out_of_range(self):
        c = Column.from_pylist([10, 20], dt.INT32)
        g = c.gather(jnp.array([0, 5, -1, 1]), fill_invalid=True)
        assert g.to_pylist() == [10, None, None, 20]

    def test_nan_survives_oracle(self):
        t = Table.from_pydict({"x": [1.0, float("nan")]}, dtypes={"x": dt.FLOAT64})
        assert_tables_equal(t, t)


class TestTable:
    def make(self):
        return Table.from_pydict(
            {"a": [1, None, 3], "b": [1.0, 2.0, None]},
            dtypes={"a": dt.INT64, "b": dt.FLOAT64},
        )

    def test_basic_structure(self):
        t = self.make()
        assert t.num_rows == 3
        assert t.num_columns == 2
        assert t.names == ("a", "b")
        assert t.schema() == [dt.INT64, dt.FLOAT64]

    def test_mismatched_lengths_rejected(self):
        with pytest.raises(ValueError):
            Table.from_pydict({"a": [1, 2], "b": [1]})

    def test_duplicate_names_rejected(self):
        c = Column.from_pylist([1], dt.INT32)
        with pytest.raises(ValueError):
            Table([("x", c), ("x", c)])

    def test_select_drop_rename_with_column(self):
        t = self.make()
        assert t.select(["b"]).names == ("b",)
        assert t.drop(["a"]).names == ("b",)
        assert t.rename({"a": "z"}).names == ("z", "b")
        t2 = t.with_column("c", Column.from_pylist([7, 8, 9], dt.INT32))
        assert t2.names == ("a", "b", "c")
        # Replacing an existing column must keep schema order (positional
        # type-id schemas depend on it).
        t3 = t.with_column("a", Column.from_pylist([7, 8, 9], dt.INT32))
        assert t3.names == ("a", "b")
        assert t3.schema() == [dt.INT32, dt.FLOAT64]

    def test_table_jit_roundtrip(self):
        t = self.make()

        @jax.jit
        def ident(tbl: Table) -> Table:
            return tbl

        assert_tables_equal(ident(t), t)

    def test_gather(self):
        t = self.make()
        g = t.gather(jnp.array([2, 0]))
        assert g.to_pydict() == {"a": [3, 1], "b": [None, 1.0]}

    def test_version(self):
        assert srt.__version__


class TestHarness:
    def test_eight_virtual_devices(self):
        if jax.default_backend() != "cpu":
            pytest.skip("virtual device count only applies to the CPU harness")
        assert len(jax.devices()) == 8
