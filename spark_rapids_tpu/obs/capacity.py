"""Capacity accountant — rolling-window saturation math and an advisor.

The serving layer already *emits* every signal an operator needs to
answer "is this process saturated, and what should change?" — queue-wait
histograms (obs/server.py), the admission controller's claimed-bytes
ledger (serve/admission.py), dispatch/materialize span walls (the
flight/timeline path), and per-query completions (obs/query.py).  What
it lacks is a place that *consumes* them over a rolling window and turns
them into decisions.  This module is that place:

  * an **event window** — bounded deques of timestamped observations fed
    from the hot paths (one gate check + one deque append when metrics
    are on, nothing when off);
  * **pure derivations** over a window snapshot: device-busy fraction
    (union-merged dispatch wall over wall-clock, so the dist path's
    fan-out of identical spans does not double-count), queue depth/wait
    trends, admission pressure vs ``SRT_SERVE_HBM_BUDGET``, HBM headroom
    percentiles, and Little's-law effective concurrency (L = λ·W) vs the
    ``SRT_SERVE_MAX_CONCURRENT`` cap;
  * an **advisor**: :func:`recommend` maps a snapshot to ranked,
    evidence-cited actions (raise/lower the worker pool, grow the HBM
    budget, enable the result cache, shed load), and :class:`Advisor`
    applies hysteresis so a recommendation only surfaces after
    ``confirm`` consecutive supporting windows and only clears after
    ``clear`` consecutive absent ones — scrape-to-scrape flapping never
    reaches the operator.

Contract (mirrors obs/metrics.py, obs/flight.py):

  * jax-free at import (pinned by an import-hygiene test);
  * off unless ``SRT_METRICS=1`` — every ``feed_*`` returns after one
    env read, and :func:`snapshot` over an unfed window is well-defined
    (zero traffic, no recommendations);
  * the derivation/advice layer is pure — ``derive`` and ``recommend``
    take explicit inputs and are deterministic for a fixed window, so
    the math is unit-testable without a device, a server, or a clock.

Surfaces: ``/capacity`` + ``srt_capacity_*`` gauges (obs/server.py), a
capacity pane in ``obs top`` and the ``obs advisor`` CLI
(obs/__main__.py, also offline over a metrics-history JSONL), and a
``capacity`` block in postmortem bundles (obs/bundle.py → obs/doctor.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..config import metrics_enabled

__all__ = [
    "span_step_kind",
    "feed_span", "feed_queue_wait", "feed_queue_depth",
    "feed_admission_wait", "feed_admission_reject", "feed_hbm",
    "feed_completion",
    "merged_busy_seconds", "effective_concurrency", "percentile", "trend",
    "derive", "recommend", "Advisor",
    "window_events", "snapshot", "advise", "bundle_block",
    "events_from_history", "reset",
]

# Spans worth metering for device-busy accounting.  Dispatch-like walls
# cover time the device (or its dist fan-out) is working — the one-shot
# and stream ``.dispatch`` spans, plus the combine-path stream's
# ``.partial`` per-batch aggregation, ``.combine`` merges, and the dist
# ``.merge_collective``.  Materialize-like walls cover device→host
# result transfer: ``.materialize`` and the combine path's
# ``.finalize``.
_DISPATCH_SUFFIXES = (".dispatch", ".partial", ".combine",
                      ".merge_collective")
_MATERIALIZE_SUFFIXES = (".materialize", ".finalize")
_SPAN_SUFFIXES = _DISPATCH_SUFFIXES + _MATERIALIZE_SUFFIXES


def span_step_kind(name: str) -> Optional[str]:
    """Stable busy-classification label for a span name — the ONE
    name→kind mapping capacity accounting and workload hotspot
    attribution share.  The executors stamp the same label into the
    span's ``step_kind`` arg (exec/compile.py, exec/stream.py), so a
    trace reader, this accountant, and the workload analyzer agree on
    what a span was doing; ``None`` means not busy-metered (bind,
    split, backpressure, ...)."""
    if name.endswith(_DISPATCH_SUFFIXES):
        return "dispatch"
    if name.endswith(_MATERIALIZE_SUFFIXES):
        return "materialize"
    return None

# Per-kind event retention.  4096 events at serving rates covers far
# more than any sane SRT_CAPACITY_WINDOW_S; the deques bound memory the
# same way the flight ring does.
_MAXEVENTS = 4096

_LOCK = threading.Lock()
_DISPATCH: "deque[Tuple[float, float]]" = deque(maxlen=_MAXEVENTS)
_MATERIALIZE: "deque[Tuple[float, float]]" = deque(maxlen=_MAXEVENTS)
_QUEUE_WAITS: "deque[Tuple[float, float]]" = deque(maxlen=_MAXEVENTS)
_QUEUE_DEPTHS: "deque[Tuple[float, int]]" = deque(maxlen=_MAXEVENTS)
_ADMISSION: "deque[Tuple[float, str, int]]" = deque(maxlen=_MAXEVENTS)
_HBM: "deque[Tuple[float, int]]" = deque(maxlen=_MAXEVENTS)
_COMPLETIONS: "deque[Tuple[float, str, float, str]]" = deque(
    maxlen=_MAXEVENTS)


def _now() -> float:
    """Window clock in seconds — same base as ``timeline.now_us()``."""
    return time.perf_counter()


# ---------------------------------------------------------------------------
# Event feeds (hot path: one env read when off; gate + append when on)
# ---------------------------------------------------------------------------

def feed_span(name: str, ts_us: float, dur_us: float) -> None:
    """Record one finished span wall.  Called from the flight-recorder
    sinks (both the timeline-on mirror and the timeline-off scope
    path), so dispatch walls are visible whenever metrics are on —
    regardless of whether the opt-in timeline records."""
    kind = span_step_kind(name)
    if kind is None:
        return
    if not metrics_enabled():
        return
    start = ts_us / 1e6
    end = start + max(dur_us, 0.0) / 1e6
    dq = _DISPATCH if kind == "dispatch" else _MATERIALIZE
    with _LOCK:
        dq.append((start, end))


def feed_queue_wait(seconds: float) -> None:
    """One query left the run queue after waiting ``seconds``."""
    if not metrics_enabled():
        return
    with _LOCK:
        _QUEUE_WAITS.append((_now(), max(seconds, 0.0)))


def feed_queue_depth(depth: int) -> None:
    """Run-queue depth sample (taken at submit and at worker pop)."""
    if not metrics_enabled():
        return
    with _LOCK:
        _QUEUE_DEPTHS.append((_now(), int(depth)))


def feed_admission_wait() -> None:
    """The admission controller made a query wait for HBM headroom."""
    if not metrics_enabled():
        return
    with _LOCK:
        _ADMISSION.append((_now(), "wait", 0))


def feed_admission_reject(estimate_bytes: int) -> None:
    """The admission controller rejected an over-budget claim."""
    if not metrics_enabled():
        return
    with _LOCK:
        _ADMISSION.append((_now(), "reject", int(estimate_bytes)))


def feed_hbm(claimed_bytes: int) -> None:
    """Claimed-bytes ledger sample (taken on acquire and release)."""
    if not metrics_enabled():
        return
    with _LOCK:
        _HBM.append((_now(), int(claimed_bytes)))


def feed_completion(mode: str, seconds: float,
                    fingerprint: Optional[str]) -> None:
    """One query finished: latency + plan identity for Little's law and
    repeated-plan (result-cache) detection."""
    if not metrics_enabled():
        return
    with _LOCK:
        _COMPLETIONS.append((_now(), str(mode), max(seconds, 0.0),
                             fingerprint or ""))


def reset() -> None:
    """Drop all window events and advisor state (test/bench isolation —
    mirrors ``registry().reset()`` and ``server.reset_histograms()``)."""
    with _LOCK:
        for dq in (_DISPATCH, _MATERIALIZE, _QUEUE_WAITS, _QUEUE_DEPTHS,
                   _ADMISSION, _HBM, _COMPLETIONS):
            dq.clear()
    _ADVISOR.reset()


# ---------------------------------------------------------------------------
# Pure derivations (no ambient state — unit-testable without a clock)
# ---------------------------------------------------------------------------

def merged_busy_seconds(intervals: Iterable[Tuple[float, float]],
                        w0: float, w1: float) -> float:
    """Union length of ``intervals`` clipped to window ``[w0, w1]``.

    Overlapping spans — concurrent workers, or the dist path's 8-way
    fan-out of one dispatch into identical per-shard spans — count
    once, so the busy fraction derived from this is naturally <= 1.
    """
    clipped = sorted((max(s, w0), min(e, w1))
                     for s, e in intervals if e > w0 and s < w1)
    busy = 0.0
    cur_s = cur_e = None
    for s, e in clipped:
        if cur_e is None:
            cur_s, cur_e = s, e
        elif s <= cur_e:
            cur_e = max(cur_e, e)
        else:
            busy += cur_e - cur_s
            cur_s, cur_e = s, e
    if cur_e is not None:
        busy += cur_e - cur_s
    return busy


def effective_concurrency(service_seconds: Sequence[float],
                          window_seconds: float) -> float:
    """Little's law: L = λ·W.  With λ = n/window and W = mean service
    time, L reduces to total in-window service seconds over the window
    — the average number of queries concurrently in service."""
    if window_seconds <= 0:
        return 0.0
    return sum(service_seconds) / window_seconds


def percentile(values: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]); None for no samples."""
    if not values:
        return None
    xs = sorted(values)
    rank = max(int(round(q / 100.0 * len(xs) + 0.5)), 1)
    return xs[min(rank, len(xs)) - 1]


def trend(samples: Sequence[Tuple[float, float]],
          w0: float, w1: float) -> float:
    """Second-half mean minus first-half mean of timestamped samples in
    ``[w0, w1]`` — positive means the signal is rising."""
    mid = (w0 + w1) / 2.0
    lo = [v for t, v in samples if w0 <= t < mid]
    hi = [v for t, v in samples if mid <= t <= w1]
    if not lo or not hi:
        return 0.0
    return sum(hi) / len(hi) - sum(lo) / len(lo)


def derive(events: Dict[str, Any], w0: float, w1: float, *,
           max_concurrent: int, hbm_budget: Optional[int],
           result_cache_on: bool) -> Dict[str, Any]:
    """Saturation observables for one event window — pure.

    ``events`` is the shape :func:`window_events` returns: lists of the
    feed tuples.  All rate/fraction math is clipped to ``[w0, w1]``.
    """
    window = max(w1 - w0, 1e-9)

    disp = [iv for iv in events.get("dispatch", ())]
    mat = [iv for iv in events.get("materialize", ())]
    disp_busy = merged_busy_seconds(disp, w0, w1)
    mat_busy = merged_busy_seconds(mat, w0, w1)

    waits = [v for t, v in events.get("queue_waits", ()) if w0 <= t <= w1]
    depths = [(t, float(d)) for t, d in events.get("queue_depths", ())
              if w0 <= t <= w1]
    adm = [(t, kind, nb) for t, kind, nb in events.get("admission", ())
           if w0 <= t <= w1]
    hbm = [(t, float(b)) for t, b in events.get("hbm", ())
           if w0 <= t <= w1]
    comps = [(t, m, s, fp) for t, m, s, fp in events.get("completions", ())
             if w0 <= t <= w1]

    lat = [s for _, _, s, _ in comps]
    eff = effective_concurrency(lat, window)
    fps = [fp for _, _, _, fp in comps if fp]
    repeated = sorted({fp for fp in fps if fps.count(fp) > 1})

    hbm_vals = [b for _, b in hbm]
    hbm_now = hbm_vals[-1] if hbm_vals else 0.0
    headroom = None
    if hbm_budget:
        p95 = percentile(hbm_vals, 95.0) or 0.0
        headroom = max(1.0 - p95 / hbm_budget, 0.0)

    rejected = [nb for _, kind, nb in adm if kind == "reject"]
    return {
        "window_seconds": window,
        "busy": {
            "dispatch_seconds": disp_busy,
            "dispatch_fraction": min(disp_busy / window, 1.0),
            "materialize_seconds": mat_busy,
            "materialize_fraction": min(mat_busy / window, 1.0),
            "dispatch_spans": len(disp),
            "materialize_spans": len(mat),
        },
        "queue": {
            "waits": len(waits),
            "wait_mean_s": sum(waits) / len(waits) if waits else 0.0,
            "wait_p95_s": percentile(waits, 95.0) or 0.0,
            "wait_trend_s": trend(events.get("queue_waits", ()), w0, w1),
            "depth": int(depths[-1][1]) if depths else 0,
            "depth_trend": trend(depths, w0, w1),
        },
        "admission": {
            "hbm_waits": sum(1 for _, k, _ in adm if k == "wait"),
            "rejected": len(rejected),
            "rejected_bytes": int(sum(rejected)),
            "budget_bytes": hbm_budget,
        },
        "hbm": {
            "claimed_now_bytes": int(hbm_now),
            "claimed_p50_bytes": int(percentile(hbm_vals, 50.0) or 0),
            "claimed_p95_bytes": int(percentile(hbm_vals, 95.0) or 0),
            "headroom_fraction": headroom,
            "samples": len(hbm_vals),
        },
        "littles_law": {
            "completions": len(comps),
            "arrival_rate_qps": len(comps) / window,
            "latency_mean_s": sum(lat) / len(lat) if lat else 0.0,
            "effective_concurrency": eff,
            "max_concurrent": max_concurrent,
            "utilization_of_cap": min(eff / max_concurrent, 1.0)
            if max_concurrent > 0 else 0.0,
        },
        "result_cache_on": bool(result_cache_on),
        "repeated_fingerprints": repeated,
    }


# ---------------------------------------------------------------------------
# Advisor (pure rules + hysteresis)
# ---------------------------------------------------------------------------

TARGET_DEFAULTS: Dict[str, float] = {
    # Busy-fraction band: above busy_high the device itself is the
    # bottleneck; below busy_low it is idling.
    "busy_high": 0.85,
    "busy_low": 0.20,
    # Concurrency-cap utilization band (Little's-law L over the cap).
    "util_high": 0.85,
    "util_low": 0.25,
    # Queue-wait pain threshold (p95 seconds).
    "wait_s": 0.25,
    # Minimum acceptable HBM headroom fraction.
    "hbm_headroom": 0.10,
}


def recommend(snap: Dict[str, Any],
              targets: Optional[Dict[str, float]] = None
              ) -> List[Dict[str, Any]]:
    """Ranked candidate actions for one snapshot — pure and
    deterministic.  Each candidate cites the observables that triggered
    it so operators (and the doctor) can audit the advice."""
    t = dict(TARGET_DEFAULTS)
    if targets:
        t.update(targets)
    busy = snap["busy"]["dispatch_fraction"]
    queue = snap["queue"]
    adm = snap["admission"]
    hbm = snap["hbm"]
    ll = snap["littles_law"]
    util = ll["utilization_of_cap"]
    waiting = queue["waits"] > 0 or queue["depth"] > 0

    out: List[Dict[str, Any]] = []

    if busy >= t["busy_high"] and queue["wait_p95_s"] >= t["wait_s"] \
            and queue["wait_trend_s"] > 0:
        out.append({
            "action": "shed_load", "severity": 90,
            "reason": "device saturated and queue waits still rising — "
                      "more workers cannot help; shed or defer load",
            "evidence": {
                "busy_fraction": busy,
                "wait_p95_s": queue["wait_p95_s"],
                "wait_trend_s": queue["wait_trend_s"],
                "target_busy_high": t["busy_high"],
                "target_wait_s": t["wait_s"],
            },
        })
    if util >= t["util_high"] and waiting and busy < t["busy_high"]:
        out.append({
            "action": "raise_workers", "severity": 80,
            "reason": "concurrency cap saturated while the device has "
                      "headroom — raise SRT_SERVE_MAX_CONCURRENT",
            "evidence": {
                "utilization_of_cap": util,
                "effective_concurrency": ll["effective_concurrency"],
                "max_concurrent": ll["max_concurrent"],
                "queue_waits": queue["waits"],
                "queue_depth": queue["depth"],
                "busy_fraction": busy,
                "target_util_high": t["util_high"],
            },
        })
    if adm["hbm_waits"] > 0 or adm["rejected"] > 0 or (
            hbm["headroom_fraction"] is not None
            and hbm["headroom_fraction"] < t["hbm_headroom"]):
        out.append({
            "action": "grow_hbm_budget", "severity": 70,
            "reason": "admission pressure against SRT_SERVE_HBM_BUDGET "
                      "— queries wait or are rejected for HBM headroom",
            "evidence": {
                "hbm_waits": adm["hbm_waits"],
                "rejected": adm["rejected"],
                "rejected_bytes": adm["rejected_bytes"],
                "budget_bytes": adm["budget_bytes"],
                "headroom_fraction": hbm["headroom_fraction"],
                "target_hbm_headroom": t["hbm_headroom"],
            },
        })
    # The spill block is attached by the ambient snapshot() wrapper, not
    # by the pure derive() — absent (unit-test snapshots) means no rule.
    spill = snap.get("spill") or {}
    if spill.get("bytes_out", 0) > 0:
        out.append({
            "action": "spill_pressure", "severity": 65,
            "reason": "queries are paging working sets out of HBM "
                      "(SRT_SPILL) — throughput is paying disk/host "
                      "page-in wall; grow SRT_SERVE_HBM_BUDGET or shed "
                      "concurrent heavy queries",
            "evidence": {
                "spill_pages_out": spill.get("pages_out", 0),
                "spill_bytes_out": spill.get("bytes_out", 0),
                "spill_bytes_in": spill.get("bytes_in", 0),
                "spill_files": spill.get("files", 0),
                "page_in_seconds": spill.get("page_in_seconds", 0.0),
                "budget_bytes": adm["budget_bytes"],
            },
        })
    if not snap["result_cache_on"] and snap["repeated_fingerprints"]:
        out.append({
            "action": "enable_result_cache", "severity": 60,
            "reason": "repeated plan fingerprints in the window with the "
                      "result cache off — set SRT_RESULT_CACHE",
            "evidence": {
                "repeated_fingerprints": snap["repeated_fingerprints"],
                "completions": ll["completions"],
            },
        })
    if util <= t["util_low"] and not waiting and busy <= t["busy_low"] \
            and ll["completions"] > 0 and ll["max_concurrent"] > 1:
        out.append({
            "action": "lower_workers", "severity": 30,
            "reason": "serving well under the concurrency cap with no "
                      "queueing — the worker pool can shrink",
            "evidence": {
                "utilization_of_cap": util,
                "busy_fraction": busy,
                "max_concurrent": ll["max_concurrent"],
                "target_util_low": t["util_low"],
            },
        })
    out.sort(key=lambda r: (-r["severity"], r["action"]))
    return out


class Advisor:
    """Hysteresis over :func:`recommend` candidates.

    An action becomes *active* only after ``confirm`` consecutive
    windows propose it, and deactivates only after ``clear``
    consecutive windows do not — a candidate that flaps window-to-
    window never surfaces, and an active recommendation does not
    vanish on one quiet scrape.
    """

    def __init__(self, confirm: int = 2, clear: int = 2):
        self.confirm = max(int(confirm), 1)
        self.clear = max(int(clear), 1)
        self._streak: Dict[str, int] = {}
        self._gone: Dict[str, int] = {}
        self._active: Dict[str, Dict[str, Any]] = {}

    def reset(self) -> None:
        self._streak.clear()
        self._gone.clear()
        self._active.clear()

    def observe(self, candidates: List[Dict[str, Any]]
                ) -> List[Dict[str, Any]]:
        """Fold one window's candidates in; return the stable set."""
        seen = {c["action"]: c for c in candidates}
        for action, cand in seen.items():
            self._streak[action] = self._streak.get(action, 0) + 1
            self._gone[action] = 0
            if self._streak[action] >= self.confirm:
                self._active[action] = cand
            elif action in self._active:
                self._active[action] = cand
        for action in list(self._streak):
            if action in seen:
                continue
            self._gone[action] = self._gone.get(action, 0) + 1
            self._streak[action] = 0
            if action in self._active \
                    and self._gone[action] >= self.clear:
                del self._active[action]
        out = list(self._active.values())
        out.sort(key=lambda r: (-r["severity"], r["action"]))
        return out


def verdict_for(recommendations: List[Dict[str, Any]]) -> str:
    """One-word operator verdict for a recommendation set."""
    if not recommendations:
        return "healthy"
    top = recommendations[0]["severity"]
    if top >= 80:
        return "saturated"
    if top >= 50:
        return "pressured"
    return "underutilized"


# ---------------------------------------------------------------------------
# Ambient wrappers (read knobs + the live window; thin over the pure core)
# ---------------------------------------------------------------------------

_ADVISOR = Advisor()


def window_events(w0: float, w1: float) -> Dict[str, Any]:
    """Copy of the live window's events clipped to ``[w0, w1]`` (span
    intervals are kept when they overlap the window)."""
    with _LOCK:
        return {
            "dispatch": [iv for iv in _DISPATCH
                         if iv[1] > w0 and iv[0] < w1],
            "materialize": [iv for iv in _MATERIALIZE
                            if iv[1] > w0 and iv[0] < w1],
            "queue_waits": [e for e in _QUEUE_WAITS if w0 <= e[0] <= w1],
            "queue_depths": [e for e in _QUEUE_DEPTHS
                             if w0 <= e[0] <= w1],
            "admission": [e for e in _ADMISSION if w0 <= e[0] <= w1],
            "hbm": [e for e in _HBM if w0 <= e[0] <= w1],
            "completions": [e for e in _COMPLETIONS if w0 <= e[0] <= w1],
        }


def snapshot(window_s: Optional[float] = None) -> Dict[str, Any]:
    """Saturation observables for the trailing window (knobs ambient)."""
    from ..config import (capacity_window_s, result_cache_bytes,
                          serve_hbm_budget, serve_max_concurrent)
    window = capacity_window_s() if window_s is None else float(window_s)
    w1 = _now()
    w0 = w1 - window
    snap = derive(window_events(w0, w1), w0, w1,
                  max_concurrent=serve_max_concurrent(),
                  hbm_budget=serve_hbm_budget(),
                  result_cache_on=result_cache_bytes() is not None)
    # Out-of-core view, attached HERE (not in the pure derive()): the
    # spill totals live in the process-wide recovery stats, not in the
    # windowed event rings.  Guarded so a broken stats read never takes
    # the saturation snapshot down with it.
    try:
        from ..resilience import recovery_stats
        s = recovery_stats().snapshot()
        snap["spill"] = {
            "pages_out": int(s["spill_pages_out"]),
            "pages_in": int(s["spill_pages_in"]),
            "bytes_out": int(s["spill_bytes_out"]),
            "bytes_in": int(s["spill_bytes_in"]),
            "files": int(s["spill_files"]),
            "page_in_seconds": round(float(s["spill_page_in_seconds"]), 6),
        }
    except Exception:  # pragma: no cover - defensive
        snap["spill"] = None
    return snap


def advise(window_s: Optional[float] = None,
           advisor: Optional[Advisor] = None) -> Dict[str, Any]:
    """One advisor evaluation over the live window.

    ``candidates`` are this window's raw proposals (immediate — a CI
    scrape sees them on the first evaluation); ``recommendations`` are
    the hysteresis-stable set from ``advisor`` (the module-level one by
    default, so repeated ``/capacity`` scrapes confirm/clear actions).
    """
    from ..config import capacity_targets
    snap = snapshot(window_s)
    candidates = recommend(snap, capacity_targets())
    adv = _ADVISOR if advisor is None else advisor
    recs = adv.observe(candidates)
    return {
        "snapshot": snap,
        "candidates": candidates,
        "recommendations": recs,
        "verdict": verdict_for(recs if recs else candidates),
    }


def bundle_block() -> Dict[str, Any]:
    """Capacity block for a postmortem bundle — never raises (a broken
    accountant must not block an incident bundle)."""
    try:
        payload = advise()
        return {
            "snapshot": payload["snapshot"],
            "recommendations": payload["recommendations"]
            or payload["candidates"],
            "verdict": payload["verdict"],
        }
    except Exception as exc:  # pragma: no cover - defensive
        return {"snapshot": None, "recommendations": [],
                "verdict": f"unavailable: {type(exc).__name__}"}


# ---------------------------------------------------------------------------
# Offline: synthesize a window from metrics-history records
# ---------------------------------------------------------------------------

def events_from_history(records: Sequence[Dict[str, Any]]
                        ) -> Tuple[Dict[str, Any], float, float]:
    """Window events synthesized from metrics-history records
    (obs/history.py JSONL, oldest first).

    History records carry durations but no wall-clock timestamps, so
    the replay is *serialized*: records are laid back-to-back on a
    synthetic clock (each query occupies ``[cursor, cursor +
    total_seconds]``, dispatch wall is the trailing
    ``execute_seconds``).  Busy fractions read as "of serialized
    runtime"; queue/admission/cache signals carry over exactly.
    Returns ``(events, w0, w1)`` for :func:`derive`.
    """
    cursor = 0.0
    ev: Dict[str, List[Any]] = {
        "dispatch": [], "materialize": [], "queue_waits": [],
        "queue_depths": [], "admission": [], "hbm": [], "completions": [],
    }
    for rec in records:
        if not isinstance(rec, dict):
            continue
        timings = rec.get("timings") or {}
        total = float(rec.get("total_seconds") or 0.0)
        execute = float(timings.get("execute_seconds") or 0.0)
        t_end = cursor + total
        if execute > 0:
            ev["dispatch"].append((t_end - min(execute, total), t_end))
        serve = rec.get("serve") or {}
        qw = serve.get("queue_wait_seconds")
        if qw is not None:
            ev["queue_waits"].append((t_end, float(qw)))
        admission = serve.get("admission")
        if admission == "queued":
            ev["admission"].append((t_end, "wait", 0))
        elif admission == "rejected":
            ev["admission"].append((t_end, "reject", 0))
        cost = rec.get("cost") or {}
        hbm = cost.get("hbm") or {}
        peak = hbm.get("peak_bytes")
        if peak:
            ev["hbm"].append((t_end, int(peak)))
        ev["completions"].append((t_end, str(rec.get("mode") or "?"),
                                  total, str(rec.get("fingerprint") or "")))
        cursor = t_end
    return ev, 0.0, max(cursor, 1e-9)
