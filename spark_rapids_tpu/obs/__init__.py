"""Observability: query metrics, counters, and host-sync accounting.

The reference stack (spark-rapids-jni) inherits Spark's SQL-metrics UI —
every exec node reports rows/bytes/time for free.  This engine's
whole-plan XLA programs are opaque by construction, so :mod:`.metrics`
provides the substrate (named counters/gauges/timers, no-op unless
``SRT_METRICS=1``) and :mod:`.query` the per-plan record populated by
exec/compile.py and surfaced through ``Plan.explain_analyze`` and the
benchmarks' JSON output.  :mod:`.timeline` adds the fourth pillar —
span events on per-batch/per-shard lanes exported as Chrome-trace JSON
(``SRT_TRACE_TIMELINE=1``) — and :mod:`.history` persists finished
``QueryMetrics`` as JSONL keyed by plan fingerprint
(``SRT_METRICS_HISTORY=path``).  :mod:`.profile` turns all of the above
into the per-plan **cost ledger** (compute/ici/host_sync/
dispatch_overhead buckets + HBM footprint — the ``cost`` block of every
QueryMetrics), and :mod:`.regress` gates fresh ledgers against the
history baseline (``SRT_REGRESS_TOL``).  :mod:`.live` is the in-flight
side — a live-query registry every execution path heartbeats into —
and :mod:`.server` exports it over HTTP (Prometheus ``/metrics``, JSON
``/queries``, mid-run Chrome traces, SLO latency histograms) behind
``SRT_LIVE_SERVER=1``; ``python -m spark_rapids_tpu.obs top`` renders
it as a console table.  :mod:`.flight` is the always-on
(``SRT_METRICS=1``) per-query flight recorder — a bounded ring of
trace events — which :mod:`.bundle` drains into self-contained
postmortem JSON on failure/SLO breach (``SRT_BUNDLE_DIR``), and
:mod:`.doctor` (``python -m spark_rapids_tpu.obs doctor``) turns a
bundle into a ranked verdict against the history baseline.
:mod:`.capacity` closes the loop at fleet level: a rolling-window
capacity accountant fed from the serving/flight hot paths (busy
fraction, queue trends, admission pressure, Little's-law concurrency)
plus an autoscaling advisor with hysteresis, surfaced on ``/capacity``,
``srt_capacity_*`` gauges, the ``obs top`` capacity pane, and
``python -m spark_rapids_tpu.obs advisor``.  :mod:`.workload` mines the
same telemetry ACROSS queries: an op-hotspot profiler (per-step-kind
cost ledger aggregation naming the next Pallas kernel targets) and a
cross-query subplan overlap miner (recurring optimized plan prefixes
scored for materialization benefit), surfaced on ``/workload``,
``srt_workload_*`` gauges, the ``obs top`` workload pane,
``python -m spark_rapids_tpu.obs workload``, and a ``workload``
postmortem-bundle block the doctor reads.

Import hygiene: nothing under ``obs`` imports jax at module load (tested
by tests/test_import_hygiene.py) — metrics post-processing must not drag
in the XLA stack.  This ``__init__`` resolves submodules and names
LAZILY (PEP 562 ``__getattr__``): ``import spark_rapids_tpu.obs`` loads
none of the pillars until one is touched, so the live server and the
``top`` renderer stay out of processes that never observe anything.
"""

from __future__ import annotations

import importlib

#: exported name -> (submodule, attribute | None).  None means the name
#: IS the submodule.
_LAZY = {
    "bundle": ("bundle", None),
    "capacity": ("capacity", None),
    "doctor": ("doctor", None),
    "flight": ("flight", None),
    "history": ("history", None),
    "live": ("live", None),
    "metrics": ("metrics", None),
    "profile": ("profile", None),
    "query": ("query", None),
    "regress": ("regress", None),
    "server": ("server", None),
    "timeline": ("timeline", None),
    "workload": ("workload", None),
    "load_history": ("history", "load"),
    "plan_fingerprint": ("history", "plan_fingerprint"),
    "subplan_fingerprint": ("history", "subplan_fingerprint"),
    "NULL_METRIC": ("metrics", "NULL_METRIC"),
    "Counter": ("metrics", "Counter"),
    "Gauge": ("metrics", "Gauge"),
    "MetricsRegistry": ("metrics", "MetricsRegistry"),
    "Timer": ("metrics", "Timer"),
    "counter": ("metrics", "counter"),
    "counters_delta": ("metrics", "counters_delta"),
    "gauge": ("metrics", "gauge"),
    "registry": ("metrics", "registry"),
    "timer": ("metrics", "timer"),
    "cost_block": ("profile", "cost_block"),
    "RegressionError": ("regress", "RegressionError"),
    "NULL_LIVE": ("live", "NULL_LIVE"),
    "LiveQuery": ("live", "LiveQuery"),
    "QueryMetrics": ("query", "QueryMetrics"),
    "StepMetrics": ("query", "StepMetrics"),
    "bench_cache_line": ("query", "bench_cache_line"),
    "bench_line": ("query", "bench_line"),
    "bench_metrics_line": ("query", "bench_metrics_line"),
    "bench_recovery_line": ("query", "bench_recovery_line"),
    "bench_stream_line": ("query", "bench_stream_line"),
    "last_query_metrics": ("query", "last_query_metrics"),
    "last_stream_metrics": ("query", "last_stream_metrics"),
    "set_last_query_metrics": ("query", "set_last_query_metrics"),
    "set_last_stream_metrics": ("query", "set_last_stream_metrics"),
    "dump_bundle": ("bundle", "dump"),
    "diagnose": ("doctor", "diagnose"),
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    submodule, attr = entry
    mod = importlib.import_module(f".{submodule}", __name__)
    value = mod if attr is None else getattr(mod, attr)
    globals()[name] = value        # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
