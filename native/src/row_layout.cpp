#include "row_layout.hpp"

namespace spark_rapids_tpu {

int32_t itemsize(TypeId id) {
  switch (id) {
    case TypeId::INT8:
    case TypeId::UINT8:
    case TypeId::BOOL8:
      return 1;
    case TypeId::INT16:
    case TypeId::UINT16:
      return 2;
    case TypeId::INT32:
    case TypeId::UINT32:
    case TypeId::FLOAT32:
    case TypeId::TIMESTAMP_DAYS:
    case TypeId::DURATION_DAYS:
    case TypeId::DECIMAL32:
      return 4;
    case TypeId::INT64:
    case TypeId::UINT64:
    case TypeId::FLOAT64:
    case TypeId::TIMESTAMP_SECONDS:
    case TypeId::TIMESTAMP_MILLISECONDS:
    case TypeId::TIMESTAMP_MICROSECONDS:
    case TypeId::TIMESTAMP_NANOSECONDS:
    case TypeId::DURATION_SECONDS:
    case TypeId::DURATION_MILLISECONDS:
    case TypeId::DURATION_MICROSECONDS:
    case TypeId::DURATION_NANOSECONDS:
    case TypeId::DECIMAL64:
      return 8;
    case TypeId::DECIMAL128:
      // Two little-endian 64-bit words (lo, hi) at 8-byte alignment — the
      // engine's extension to the reference format (dtypes.py _TWO_WORD,
      // rows/layout.py), byte-compatible with Arrow/cudf decimal128.
      return 16;
    default:
      throw std::invalid_argument("Only fixed width types are currently supported");
  }
}

bool is_fixed_width(TypeId id) {
  switch (id) {
    case TypeId::EMPTY:
    case TypeId::DICTIONARY32:
    case TypeId::STRING:
    case TypeId::LIST:
    case TypeId::STRUCT:
      return false;
    default:
      return true;
  }
}

static int32_t align_offset(int32_t offset, int32_t alignment) {
  return (offset + alignment - 1) & ~(alignment - 1);
}

RowLayout compute_fixed_width_layout(const std::vector<DType>& schema) {
  if (schema.empty()) throw std::invalid_argument("schema must have at least one column");
  RowLayout layout;
  layout.column_starts.reserve(schema.size());
  layout.column_sizes.reserve(schema.size());
  int32_t at = 0;
  for (const DType& dt : schema) {
    if (!is_fixed_width(dt.type_id))
      throw std::invalid_argument("Only fixed width types are currently supported");
    int32_t size = itemsize(dt.type_id);
    // Natural alignment capped at 8: DECIMAL128 (16 bytes) sits at 8-byte
    // alignment as two consecutive 64-bit words (rows/layout.py contract).
    at = align_offset(at, size < 8 ? size : 8);
    layout.column_starts.push_back(at);
    layout.column_sizes.push_back(size);
    at += size;
  }
  layout.validity_offset = at;  // validity tail is byte-aligned, no padding
  layout.validity_bytes = (static_cast<int32_t>(schema.size()) + 7) / 8;
  at += layout.validity_bytes;
  layout.row_size = align_offset(at, 8);  // 64-bit row alignment
  return layout;
}

}  // namespace spark_rapids_tpu
