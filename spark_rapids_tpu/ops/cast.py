"""Type casts, including decimal scale arithmetic.

Covers the cast surface of the reference envelope (cuDF ``cast`` +
the decimal semantics the JNI schema wire format carries — scale as a base-10
exponent, value = unscaled * 10**scale; RowConversionJni.cpp:56-61).

Numeric cast semantics follow cuDF: float -> int truncates toward zero;
out-of-range is undefined behavior (we document XLA's saturation on TPU);
bool casts map nonzero -> True.  Decimal rescaling multiplies/divides by
powers of ten with truncation toward zero (cudf fixed_point::rescaled).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..column import Column
from ..dtypes import BOOL8, DType, TypeId


def cast(col: Column, to: DType) -> Column:
    """Cast a column to another dtype (fixed-width both ways, plus the
    Spark string casts: string -> int/float parse with null-on-malformed,
    number -> decimal string format)."""
    if col.dtype == to:
        return col
    from ..dtypes import STRING
    if col.dtype == STRING:
        return _cast_from_string(col, to)
    if to == STRING:
        return _cast_to_string(col)
    if not col.dtype.is_fixed_width or not to.is_fixed_width:
        raise ValueError(f"cast {col.dtype!r} -> {to!r}: both must be fixed width")

    src, dst = col.dtype, to
    data = col.data

    if dst.is_two_word:
        from .decimal128 import cast_to_d128
        return cast_to_d128(col, to)
    if src.is_two_word:
        from .decimal128 import cast_from_d128
        return cast_from_d128(col, to)

    if src.is_decimal and dst.is_decimal:
        data = _rescale(data.astype(dst.jnp_dtype), src.scale, dst.scale)
    elif src.is_decimal:
        # decimal -> numeric: apply the scale
        if dst.is_floating:
            data = data.astype(jnp.float64) * (10.0 ** src.scale)
            data = data.astype(dst.jnp_dtype)
        else:
            data = _rescale(data.astype(jnp.int64), src.scale, 0).astype(dst.jnp_dtype)
    elif dst.is_decimal:
        # numeric -> decimal: quantize into the target scale
        if src.is_floating:
            scaled = data.astype(jnp.float64) * (10.0 ** -dst.scale)
            data = jnp.trunc(scaled).astype(dst.jnp_dtype)
        else:
            data = _rescale(data.astype(dst.jnp_dtype), 0, dst.scale)
    elif dst == BOOL8:
        data = (data != 0).astype(jnp.uint8)
    elif src == BOOL8:
        data = (data != 0).astype(dst.jnp_dtype)
    else:
        data = data.astype(dst.jnp_dtype)

    return Column(data=data, validity=col.validity, dtype=to)


#: widest decimal run a string parse examines (int64 max has 19 digits;
#: longer runs are malformed -> null, the Spark non-ANSI contract)
_PARSE_WINDOW = 24


def _cast_from_string(col: Column, to: DType) -> Column:
    """Parse strings to numbers, null on malformed (Spark CAST with
    ansi=off; cudf ``to_integers``/``to_floats``).

    Vectorized over a (rows, 24) window gather of the leading bytes —
    sign, integer digits, optional '.' + fraction for floats.  Exponent
    forms and strings longer than the window parse to null."""
    import numpy as np

    from ..dtypes import STRING
    from .strings import _gather_window, strip

    if to == STRING:
        return col
    s = strip(col)
    offsets = s.offsets
    lens = (offsets[1:] - offsets[:-1]).astype(jnp.int32)
    w = _PARSE_WINDOW
    win = _gather_window(s, offsets[:-1], w).astype(jnp.int32)
    pos_in = jnp.arange(w, dtype=jnp.int32)[None, :]
    in_row = pos_in < lens[:, None]
    ch = jnp.where(in_row, win, 0)

    sign_byte = ch[:, 0]
    has_sign = (sign_byte == ord("-")) | (sign_byte == ord("+"))
    neg = sign_byte == ord("-")
    digit = (ch >= ord("0")) & (ch <= ord("9")) & in_row
    dval = jnp.clip(ch - ord("0"), 0, 9).astype(jnp.int64)
    is_dot = (ch == ord(".")) & in_row
    body = in_row & (pos_in >= has_sign[:, None].astype(jnp.int32))

    # first dot position (or row length if none)
    big = jnp.full((), w + 1, jnp.int32)
    dot_pos = jnp.min(jnp.where(is_dot, pos_in, big), axis=1)
    n_dots = jnp.sum(is_dot.astype(jnp.int32), axis=1)

    int_part = body & digit & (pos_in < dot_pos[:, None])
    frac_part = body & digit & (pos_in > dot_pos[:, None])
    n_int = jnp.sum(int_part.astype(jnp.int32), axis=1)
    n_frac = jnp.sum(frac_part.astype(jnp.int32), axis=1)

    # every body byte must be a digit or the single dot
    body_ok = jnp.all(~body | digit | is_dot, axis=1)
    fits = lens <= w

    if to.is_floating or to.is_decimal:
        ok = (body_ok & fits & (n_dots <= 1) & (lens > has_sign)
              & ((n_int + n_frac) > 0))
        # place value: the r-th integer digit (1-based from the left, of
        # n_int total) scales by 10^(n_int - r); the r-th fraction digit
        # by 10^-r
        int_rank = jnp.cumsum(int_part.astype(jnp.int32), axis=1)
        frac_rank = jnp.cumsum(frac_part.astype(jnp.int32), axis=1)
        fint = jnp.sum(jnp.where(
            int_part,
            dval.astype(jnp.float64)
            * 10.0 ** (n_int[:, None] - int_rank).astype(jnp.float64),
            0.0), axis=1)
        ffrac = jnp.sum(jnp.where(
            frac_part,
            dval.astype(jnp.float64)
            * 10.0 ** (-frac_rank).astype(jnp.float64),
            0.0), axis=1)
        val = jnp.where(neg, -(fint + ffrac), fint + ffrac)
        validity = ok if s.validity is None else (s.validity & ok)
        if to.is_decimal:
            scaled = jnp.trunc(val * (10.0 ** -to.scale))
            return Column(data=scaled.astype(to.jnp_dtype),
                          validity=validity, dtype=to)
        return Column(data=val.astype(to.jnp_dtype), validity=validity,
                      dtype=to)

    if to == BOOL8:
        # Spark accepts true/false/t/f/y/n/yes/no/0/1 — cover the common
        # true/false/0/1 forms via a round trip through lowercase compare
        raise ValueError("cast string -> bool is not supported; compare "
                         "against literals instead")

    # integer targets: digits only, no dot
    ok = (body_ok & fits & (n_dots == 0) & (n_int > 0)
          & (n_int <= 19) & (lens > has_sign))
    int_rank = jnp.cumsum(int_part.astype(jnp.int32), axis=1)
    pow10 = jnp.asarray(
        np.concatenate([[0], 10 ** np.arange(19, dtype=np.int64)]),
        jnp.int64)
    place = jnp.take(pow10, jnp.clip(n_int[:, None] - int_rank + 1, 0, 19))
    val = jnp.sum(jnp.where(int_part, dval * place, 0), axis=1)
    val = jnp.where(neg, -val, val)
    validity = ok if s.validity is None else (s.validity & ok)
    return Column(data=val.astype(to.jnp_dtype), validity=validity,
                  dtype=to)


def _cast_to_string(col: Column) -> Column:
    """Format numbers as decimal strings, device-side.

    Integers (and bools, and decimals via their unscaled value + scale
    point insertion) format with a digit matrix + pack; floats take a
    host-assisted round trip (shortest round-trip float repr is a
    sequential algorithm — a documented deviation, matching how the
    engine host-assists dictionary encodes)."""
    import numpy as np

    from ..dtypes import STRING
    from .strings import _offsets_from_lens, strings_from_pylist

    if col.dtype.is_floating:
        data, validity = col.to_numpy()
        vals = [repr(float(v)) for v in data]
        out = strings_from_pylist(vals)
        return out.with_validity(
            None if validity is None else jnp.asarray(validity))
    if col.dtype == BOOL8:
        data, validity = col.to_numpy()
        out = strings_from_pylist(
            ["true" if v else "false" for v in data])
        return out.with_validity(
            None if validity is None else jnp.asarray(validity))
    if col.dtype.is_two_word:
        raise ValueError("cast decimal128 -> string: cast to decimal64 "
                         "first")

    scale = col.dtype.scale if col.dtype.is_decimal else 0
    if scale > 0:
        # positive scale multiplies the unscaled value; format the logical
        # integer directly
        v = col.data.astype(jnp.int64) * (10 ** scale)
        scale = 0
    else:
        v = col.data.astype(jnp.int64)
    frac_digits = -scale
    neg = v < 0
    mag = jnp.abs(v)

    # digit count of the magnitude (>= 1)
    pow10 = jnp.asarray(10 ** np.arange(19, dtype=np.int64), jnp.int64)
    ndig = jnp.sum((mag[:, None] >= pow10[None, :]).astype(jnp.int32),
                   axis=1)
    ndig = jnp.maximum(ndig, 1)
    # ensure enough digits to cover the fraction + a leading zero
    ndig = jnp.maximum(ndig, frac_digits + 1)
    out_lens = ndig + neg.astype(jnp.int32) + (1 if frac_digits else 0)
    new_offsets = _offsets_from_lens(out_lens)
    total = int(new_offsets[-1])
    if total == 0:
        return Column(data=jnp.zeros(0, jnp.uint8), validity=col.validity,
                      offsets=new_offsets, dtype=STRING)
    from .strings import _row_ids
    pos = jnp.arange(total, dtype=jnp.int32)
    row = _row_ids(new_offsets, total)
    rel = pos - jnp.take(new_offsets, row)
    rneg = jnp.take(neg, row)
    rnd = jnp.take(ndig, row)
    rmag = jnp.take(mag, row)
    # layout: [-] d ... d [. d ... d]; digit index from the left among
    # ndig digits, skipping the sign and the point
    di = rel - rneg.astype(jnp.int32)
    if frac_digits:
        point_at = rnd - frac_digits + rneg.astype(jnp.int32)
        is_point = rel == point_at
        di = jnp.where(rel > point_at, di - 1, di)
    else:
        is_point = jnp.zeros(total, jnp.bool_)
    # value of digit i (from left): mag // 10^(ndig-1-i) % 10
    exp = jnp.clip(rnd - 1 - di, 0, 18)
    digit = (rmag // jnp.take(pow10, exp)) % 10
    chars = jnp.where(is_point, ord("."), ord("0") + digit)
    chars = jnp.where(rneg & (rel == 0), ord("-"), chars)
    return Column(data=chars.astype(jnp.uint8), validity=col.validity,
                  offsets=new_offsets, dtype=STRING)


def _rescale(unscaled, from_scale: int, to_scale: int):
    """Move a base-10 fixed-point value between scales, truncating toward zero."""
    diff = from_scale - to_scale
    if diff == 0:
        return unscaled
    if diff > 0:
        return unscaled * (10 ** diff)
    factor = 10 ** (-diff)
    # integer division truncating toward zero (jnp // floors)
    q = jnp.abs(unscaled) // factor
    return jnp.where(unscaled < 0, -q, q).astype(unscaled.dtype)
