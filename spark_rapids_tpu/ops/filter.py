"""Row filtering / stream compaction."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..column import Column
from ..table import Table
from .common import compact_indices, pow2_bucket


@functools.partial(jax.jit, static_argnames=("bucket",))
def _compact_kernel(keep, datas, valids, *, bucket):
    """Stable compaction of every fixed-width column in ONE program.

    The order permutation and all gathers fuse into a single dispatch —
    the eager per-column form cost one dispatch + kernel per column
    (measured ~420 ms for a 7-column 4M-row filter through the tunneled
    TPU vs ~80 ms fused).  Output is padded to the pow2 ``bucket`` so one
    compile serves many selectivities; callers slice to the real count.
    """
    order = jnp.argsort(~keep, stable=True)
    idx = order[:bucket]
    out_datas = tuple(jnp.take(d, idx, axis=0) for d in datas)
    out_valids = tuple(None if v is None else jnp.take(v, idx)
                       for v in valids)
    return idx, out_datas, out_valids


def _compact_table(table: Table, keep: jax.Array) -> Table:
    """Shared fused compaction: one host sync (count) + one device program
    (+ eager string gathers, which are host-sized anyway)."""
    count = int(jnp.sum(keep))
    bucket = min(pow2_bucket(count), table.num_rows)

    def needs_gather(col):
        # Strings and nested columns go through Column.gather (which
        # recurses into offsets/children); flat buffers ride the fused
        # compaction kernel.
        return col.offsets is not None or (col.dtype is not None
                                           and col.dtype.is_nested)

    fixed = [(name, col) for name, col in table.items()
             if not needs_gather(col)]
    idx, datas, valids = _compact_kernel(
        keep, tuple(c.data for _, c in fixed),
        tuple(c.validity for _, c in fixed), bucket=bucket)
    out = {}
    for (name, col), d, v in zip(fixed, datas, valids):
        out[name] = Column(data=d[:count],
                           validity=None if v is None else v[:count],
                           dtype=col.dtype)
    sliced_idx = None
    for name, col in table.items():
        if needs_gather(col):
            if sliced_idx is None:
                sliced_idx = idx[:count]
            out[name] = col.gather(sliced_idx)
    return Table([(name, out[name]) for name in table.names])


def apply_boolean_mask(table: Table, mask) -> Table:
    """Keep rows where ``mask`` is True (null mask entries drop the row,
    cudf ``apply_boolean_mask`` semantics)."""
    if isinstance(mask, Column):
        keep = mask.data.astype(jnp.bool_)
        if mask.validity is not None:
            keep = keep & mask.validity
    else:
        keep = jnp.asarray(mask).astype(jnp.bool_)
    if keep.shape[0] != table.num_rows:
        raise ValueError("mask length must equal table row count")
    return _compact_table(table, keep)


def drop_nulls(table: Table, subset=None) -> Table:
    """Drop rows with a null in any of ``subset`` (default: all columns)."""
    names = list(table.names) if subset is None else list(subset)
    keep = jnp.ones(table.num_rows, jnp.bool_)
    for name in names:
        col = table[name]
        if col.validity is not None:
            keep = keep & col.validity
    return _compact_table(table, keep)


def distinct(table: Table, subset=None) -> Table:
    """Drop duplicate rows, keeping each key's FIRST occurrence in the
    original row order (Spark ``dropDuplicates`` semantics; null == null
    and NaN == NaN for key equality, as in grouping).

    Sort-based: a stable multi-key sort clusters duplicates, adjacent
    difference marks each cluster's head (the first original occurrence,
    by stability), and the surviving row ids are re-sorted to restore
    input order.
    """
    from .common import grouping_columns, null_safe_equal_adjacent
    from .sort import sorted_order
    names = list(table.names) if subset is None else list(subset)
    keys = grouping_columns([table[name] for name in names])
    perm = sorted_order(keys)
    boundary = jnp.zeros(table.num_rows, jnp.bool_)
    for col in keys:
        boundary = boundary | null_safe_equal_adjacent(col.gather(perm))
    survivors = jnp.take(perm, compact_indices(boundary))
    return table.gather(jnp.sort(survivors))
