"""Device mesh + sharded (distributed) tables.

TPU-native replacement for the reference system's distribution model (one
Spark executor per GPU, UCX/NCCL shuffle in the spark-rapids plugin —
SURVEY.md §2.4): a 1-D ``jax.sharding.Mesh`` whose axis is the partition
dimension, tables sharded row-wise across it, and XLA collectives over
ICI/DCN for data movement.

**Static-shape representation.** Distributed ops run under ``shard_map``
inside ``jit``, where output shapes must be static, but real partition sizes
are data dependent.  Resolution: every shard holds a fixed ``capacity`` of
row slots plus a ``row_mask`` marking live rows.  All distributed ops
(shuffle/groupby/join) consume and produce this padded form with zero host
round-trips; compaction happens only at :func:`collect` (host materialize).
This replaces the reference world's dynamic buffers + executor-side resizing
with the compile-once discipline TPU wants.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..column import Column
from ..table import Table

AXIS = "x"    #: the partition axis name used throughout the engine

#: Bounded LRU of compiled parallel-op programs (shuffle bodies, local
#: groupby/join kernels — dist_ops.py/shuffle.py), keyed by
#: (op, mesh_cache_key, static shape/arity params).  Shared-cap LRU via
#: exec/compile._lru_lookup (``SRT_COMPILE_CACHE_CAP``); cleared
#: wholesale by resilience/recovery.evict_device_caches on OOM — live
#: executables pin HBM, and the mesh ladder needs them droppable.
_DIST_PROGRAMS: OrderedDict = OrderedDict()


def mesh_cache_key(mesh: Mesh) -> tuple:
    """Identify a mesh by its actual devices for program-cache keys:
    compiled bodies close over the concrete mesh via ``shard_map``, so
    same-shape meshes over different devices must not share entries."""
    return (mesh.axis_names[0],
            tuple(int(d.id) for d in mesh.devices.flat))


def record_ici(nbytes: int, seconds: float = 0.0,
               collectives: int = 1) -> None:
    """Shared ICI-counter accounting for one mesh collective: the
    ``ici.us`` / ``ici.bytes`` / ``ici.collectives`` triple every
    distributed layer (shuffle all_to_all, dist_ops pmax, the sharded
    stream merge) increments identically.  ``seconds`` is the measured
    wall the caller attributes to the exchange; the 1-microsecond floor
    keeps a ran-collective visible in the cost ledger even when the
    caller could not isolate its wall."""
    from ..obs import live as _live
    from ..obs.metrics import counter
    counter("ici.us").inc(max(1, int(seconds * 1e6)))
    counter("ici.bytes").inc(int(nbytes))
    counter("ici.collectives").inc(int(collectives))
    _live.add_ici(int(nbytes))

# ``jax.shard_map`` graduated from jax.experimental in jax 0.6; accept
# both so the distributed layer runs on every jax the engine supports.
try:
    shard_map = jax.shard_map                       # jax >= 0.6
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, **kwargs):
        # check_vma is the jax >= 0.6 name for check_rep.
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_exp(f, **kwargs)


def make_mesh(devices: Optional[Sequence] = None, axis_name: str = AXIS) -> Mesh:
    """A 1-D mesh over all (or the given) devices.

    On a pod slice this is the ICI ring; across slices JAX orders DCN
    transparently (multi-host: pass ``jax.devices()`` spanning hosts).
    """
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis_name,))


def row_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class DistTable:
    """A row-sharded table with padded shards.

    ``table`` columns have global length ``P * capacity`` (``P`` mesh
    devices), sharded on the row axis; ``row_mask`` marks live rows.
    Fixed-width columns only (strings must be dictionary-encoded before
    distribution — device-side global dictionaries are a follow-up).
    """

    table: Table
    row_mask: jax.Array     # bool (P * capacity,)

    def tree_flatten(self):
        return (self.table, self.row_mask), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        table, row_mask = children
        return cls(table=table, row_mask=row_mask)

    @property
    def capacity_total(self) -> int:
        return int(self.row_mask.shape[0])

    def num_rows(self) -> int:
        """Live row count (host sync)."""
        import time as _time
        t0 = _time.perf_counter()
        count = int(jnp.sum(self.row_mask))
        from ..utils.memory import record_host_sync
        record_host_sync("dist.live_count", 8,
                         seconds=_time.perf_counter() - t0)
        return count

    def live_count_device(self) -> jax.Array:
        """Live row count as a device scalar — NO host sync.  The sharded
        streaming executor sums these across batches on device and pays
        one blocking read at stream end instead of one per dispatch."""
        return jnp.sum(self.row_mask, dtype=jnp.int32)


def shard_table(table: Table, mesh: Mesh,
                capacity: Optional[int] = None) -> DistTable:
    """Distribute a host/device table row-wise over the mesh.

    Rows are dealt out contiguously; each shard is padded to ``capacity``
    slots (default: even split, rounded up).
    """
    P = mesh.devices.size
    n = table.num_rows
    if capacity is None:
        capacity = max(1, -(-n // P))
    if n > P * capacity:
        raise ValueError(f"{n} rows exceed mesh capacity {P}x{capacity}")
    total = P * capacity

    cols = []
    for name, col in table.items():
        if col.offsets is not None:
            raise ValueError(
                f"column {name!r} is variable-width: dictionary-encode string "
                f"columns before distributing (ops.strings.dictionary_encode)")
        data = jnp.zeros(total, col.data.dtype).at[:n].set(col.data)
        validity = None
        if col.validity is not None:
            validity = jnp.zeros(total, jnp.bool_).at[:n].set(col.validity)
        cols.append((name, Column(data=data, validity=validity, dtype=col.dtype)))
    row_mask = jnp.zeros(total, jnp.bool_).at[:n].set(True)

    spec = row_spec(mesh)
    sharded_cols = [(name, Column(data=jax.device_put(c.data, spec),
                                  validity=None if c.validity is None
                                  else jax.device_put(c.validity, spec),
                                  dtype=c.dtype))
                    for name, c in cols]
    return DistTable(table=Table(sharded_cols),
                     row_mask=jax.device_put(row_mask, spec))


def collect(dist: DistTable) -> Table:
    """Materialize a DistTable on host, dropping padding slots.

    Every ``np.asarray`` of a device array below is a blocking D2H round
    trip; they are counted so sharded runs report the same host-sync
    totals as the single-chip path (one sync per buffer pulled, plus the
    mask).  The D2H drain blocks on every in-flight device computation
    over these buffers, so it runs under the ``SRT_DIST_TIMEOUT`` stall
    watchdog: a wedged mesh surfaces here as ``DistStallError`` instead
    of an unbounded host hang."""
    from ..resilience import dist_guard
    return dist_guard("dist.collect", lambda: _collect_blocking(dist))


def _collect_blocking(dist: DistTable) -> Table:
    # Fault site INSIDE the guarded body: an injected stall parks this
    # worker, and the watchdog surfaces it as DistStallError.
    from ..resilience import fault_point
    fault_point("collect")
    import time as _time
    from ..utils.memory import record_host_sync
    t0 = _time.perf_counter()
    mask = np.asarray(dist.row_mask)
    record_host_sync("dist.collect", mask.nbytes,
                     seconds=_time.perf_counter() - t0)
    cols = []
    for name, col in dist.table.items():
        t0 = _time.perf_counter()
        data = np.asarray(col.data)[mask]
        nbytes = data.nbytes
        validity = None
        if col.validity is not None:
            v = np.asarray(col.validity)[mask]
            nbytes += v.nbytes
            validity = None if v.all() else v
        record_host_sync("dist.collect", nbytes,
                         seconds=_time.perf_counter() - t0)
        cols.append((name, Column.from_numpy(data, validity, dtype=col.dtype)))
    return Table(cols)
