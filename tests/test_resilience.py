"""Execution-resilience contracts (spark_rapids_tpu/resilience/).

Five contracts:

1. **Classification & retry policy** — ``classify`` is the single
   exception→category mapping; ``with_retries`` retries only retryable
   categories and re-raises the ORIGINAL error with its recovery summary
   on exhaustion.
2. **Deterministic fault injection** — ``SRT_FAULT`` count specs fire on
   exactly the first N passes and probability specs replay bit-identically
   from their seed; bad specs fail loudly.
3. **Bit-identical recovery** — with an OOM injected at every engine site
   (bind / dispatch / materialize / stream-combine), ``run_plan`` and
   ``run_plan_stream`` (both modes) return exactly what a no-fault run
   returns, including across bucket boundaries, null keys, and the
   batch-split last rung; ``QueryMetrics`` records the recovery.
4. **Honest failure** — when recovery is exhausted the surfaced error
   chains the original ``RESOURCE_EXHAUSTED`` and names every attempted
   step; the shuffle overflow loop is bounded and names the observed
   occupancy; the feed watchdog raises instead of hanging.
5. **Import hygiene** — the resilience package never imports jax at
   module load.
"""

import json
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu import Column, Table, assert_tables_equal
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.exec import col, plan, run_plan_stream
from spark_rapids_tpu.exec.compile import run_plan
from spark_rapids_tpu.obs import last_query_metrics, registry
from spark_rapids_tpu.resilience import (
    CATEGORY_COMPILE, CATEGORY_FATAL, CATEGORY_IO, CATEGORY_OOM,
    ExecutionRecoveryError, InjectedFault, RecoveryStats, RetryPolicy,
    ShuffleOverflowError, StreamStallError, classify, fault_point,
    recovery_stats, reset_faults, with_retries)

ALL_SITES = ("bind", "dispatch", "materialize")


@pytest.fixture(autouse=True)
def _fresh_faults(monkeypatch):
    """Every test starts with no armed faults and a permissive, fast
    retry budget; injection state never leaks between tests."""
    monkeypatch.delenv("SRT_FAULT", raising=False)
    monkeypatch.setenv("SRT_RETRY_BACKOFF", "0")
    reset_faults()
    yield
    reset_faults()


@pytest.fixture
def metrics_on(monkeypatch):
    monkeypatch.setenv("SRT_METRICS", "1")
    registry().reset()
    yield
    registry().reset()


def _mk(n, seed=0, khi=5):
    """Int key + float value table with nulls in the value column; float
    values are integer-valued so any re-association (batch splits) sums
    exactly."""
    r = np.random.default_rng(seed)
    return Table({
        "k": Column.from_numpy(r.integers(0, khi, n).astype(np.int64)),
        "v": Column.from_numpy(r.integers(0, 100, n).astype(np.float64),
                               validity=r.random(n) > 0.2),
    })


def _rowset(t: Table):
    cols = [t[n].to_pylist() for n in t.names]
    return sorted(zip(*cols), key=repr)


def _row_local_plan():
    return plan().filter(col("v") > 10).with_columns(v2=col("v") * 2.0)


def _grouped_plan(khi=5):
    return plan().filter(col("v") > 10).groupby_agg(
        ["k"], [("v", "sum", "s"), ("v", "count", "c"), ("v", "max", "m")],
        domains={"k": (0, khi - 1)})


# ---------------------------------------------------------------------------
# 1. classification & retry policy
# ---------------------------------------------------------------------------

class TestClassify:
    def test_oom_by_marker_and_type(self):
        assert classify(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            "1073741824 bytes")) == CATEGORY_OOM
        assert classify(MemoryError()) == CATEGORY_OOM
        assert classify(InjectedFault("oom", "dispatch", "x")) == CATEGORY_OOM

    def test_compile_needs_name_and_marker(self):
        XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
        assert classify(XlaRuntimeError(
            "XLA compilation failed")) == CATEGORY_COMPILE
        # Marker without the jaxlib type name stays fatal: an arbitrary
        # RuntimeError mentioning compilation is not an engine failure.
        assert classify(RuntimeError("XLA compilation")) == CATEGORY_FATAL

    def test_io_vs_fatal_os_errors(self):
        assert classify(ConnectionError("reset")) == CATEGORY_IO
        assert classify(TimeoutError()) == CATEGORY_IO
        assert classify(OSError(5, "EIO")) == CATEGORY_IO
        # Filesystem *state* errors can never be retried away.
        assert classify(FileNotFoundError("gone")) == CATEGORY_FATAL
        assert classify(PermissionError("denied")) == CATEGORY_FATAL
        assert classify(ValueError("bug")) == CATEGORY_FATAL

    def test_injected_fault_category_wins(self):
        assert classify(InjectedFault("io", "read", "x")) == CATEGORY_IO


class TestWithRetries:
    def test_flaky_fn_succeeds_within_budget(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("reset")
            return "ok"

        assert with_retries(flaky, RetryPolicy(3, 0.0)) == "ok"
        assert len(calls) == 3

    def test_exhaustion_reraises_original_with_summary(self):
        errs = [ConnectionError("first"), ConnectionError("second"),
                ConnectionError("third")]

        def failing():
            e = errs[min(failing.n, 2)]
            failing.n += 1
            raise e
        failing.n = 0

        with pytest.raises(ConnectionError) as ei:
            with_retries(failing, RetryPolicy(2, 0.0), site="read")
        # The FIRST error surfaces, not the last attempt's.
        assert ei.value is errs[0]
        summary = ei.value.recovery_summary
        assert summary.retries == 2
        assert summary.site == "read"

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def fatal():
            calls.append(1)
            raise ValueError("bug")

        with pytest.raises(ValueError):
            with_retries(fatal, RetryPolicy(5, 0.0))
        assert len(calls) == 1

    def test_backoff_is_capped_exponential(self):
        p = RetryPolicy(max_retries=10, backoff=0.05, backoff_cap=0.4)
        assert p.delay(0) == pytest.approx(0.05)
        assert p.delay(1) == pytest.approx(0.10)
        assert p.delay(3) == pytest.approx(0.4)       # capped
        assert p.delay(9) == pytest.approx(0.4)

    def test_policy_from_env(self, monkeypatch):
        monkeypatch.setenv("SRT_RETRY_MAX", "7")
        monkeypatch.setenv("SRT_RETRY_BACKOFF", "0.125")
        p = RetryPolicy.from_env()
        assert p.max_retries == 7 and p.backoff == 0.125
        monkeypatch.setenv("SRT_RETRY_MAX", "-1")
        with pytest.raises(ValueError):
            RetryPolicy.from_env()


# ---------------------------------------------------------------------------
# 2. deterministic fault injection
# ---------------------------------------------------------------------------

class TestFaultInjection:
    def test_count_spec_fires_exactly_n_times(self, monkeypatch):
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:2")
        reset_faults()
        for _ in range(2):
            with pytest.raises(InjectedFault) as ei:
                fault_point("dispatch")
            assert "RESOURCE_EXHAUSTED" in str(ei.value)
            assert classify(ei.value) == CATEGORY_OOM
        fault_point("dispatch")                      # 3rd pass: clean
        fault_point("materialize")                   # other sites: clean

    def test_probability_spec_replays_identically(self, monkeypatch):
        monkeypatch.setenv("SRT_FAULT", "io:read:0.5:seed=7")

        def draw(n=64):
            reset_faults()
            fired = []
            for _ in range(n):
                try:
                    fault_point("read")
                    fired.append(False)
                except InjectedFault:
                    fired.append(True)
            return fired

        a, b = draw(), draw()
        assert a == b                      # seeded PRNG: bit-identical
        assert any(a) and not all(a)       # actually probabilistic

    def test_multiple_specs_and_bad_specs(self, monkeypatch):
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:1,io:read:1")
        reset_faults()
        with pytest.raises(InjectedFault):
            fault_point("dispatch")
        with pytest.raises(InjectedFault):
            fault_point("read")
        for bad in ("oom", "oom:dispatch", "boom:dispatch:1",
                    "oom:dispatch:0", "oom:dispatch:1.5",
                    "oom:dispatch:1:tries=2"):
            monkeypatch.setenv("SRT_FAULT", bad)
            reset_faults()
            with pytest.raises(ValueError):
                fault_point("dispatch")

    def test_injections_are_counted(self, monkeypatch):
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:1")
        reset_faults()
        before = recovery_stats().snapshot()
        with pytest.raises(InjectedFault):
            fault_point("dispatch")
        assert recovery_stats().delta(before)["faults_injected"] == 1


# ---------------------------------------------------------------------------
# 3. bit-identical recovery
# ---------------------------------------------------------------------------

class TestRunPlanRecovery:
    @pytest.mark.parametrize("site", ALL_SITES)
    @pytest.mark.parametrize("mk_plan", [_row_local_plan, _grouped_plan],
                             ids=["row_local", "grouped"])
    def test_single_oom_recovers_bit_identical(self, monkeypatch, site,
                                               mk_plan):
        t = _mk(150, seed=3)
        p = mk_plan()
        oracle = run_plan(p, t).to_pydict()
        monkeypatch.setenv("SRT_FAULT", f"oom:{site}:1")
        reset_faults()
        before = recovery_stats().snapshot()
        assert run_plan(p, t).to_pydict() == oracle
        d = recovery_stats().delta(before)
        assert d["retries"] >= 1 and d["cache_evictions"] >= 1

    @pytest.mark.parametrize("site", ALL_SITES)
    def test_recovery_block_lands_in_query_metrics(self, monkeypatch,
                                                   metrics_on, site):
        t = _mk(100, seed=4)
        p = _row_local_plan()
        oracle = run_plan(p, t).to_pydict()
        monkeypatch.setenv("SRT_FAULT", f"oom:{site}:1")
        reset_faults()
        assert run_plan(p, t).to_pydict() == oracle
        payload = json.loads(last_query_metrics().to_json())
        assert payload["schema_version"] == 11
        rec = payload["recovery"]
        assert rec["retries"] >= 1
        assert rec["cache_evictions"] >= 1
        assert "recovery:" in last_query_metrics().render()

    def test_fault_free_run_reports_zero_recovery(self, metrics_on):
        t = _mk(64, seed=5)
        run_plan(_row_local_plan(), t)
        rec = json.loads(last_query_metrics().to_json())["recovery"]
        assert rec == {"retries": 0, "splits": 0, "cache_evictions": 0,
                       "backoff_seconds": 0.0,
                       "dist": {"retries": 0, "splits": 0, "fallbacks": 0,
                                "cache_evictions": 0},
                       "spill": {"pages_out": 0, "pages_in": 0,
                                 "bytes_out": 0, "bytes_in": 0, "files": 0,
                                 "page_in_seconds": 0.0}}

    def test_concat_split_across_bucket_boundary(self, monkeypatch):
        # 150 rows straddles buckets (64/88/120/160): the snapped cut at
        # 88 puts both pieces in already-scheduled buckets.  Two faults
        # against a budget of one retry exhaust the ladder and force the
        # split rung.
        t = _mk(150, seed=6)
        p = _row_local_plan()
        oracle = run_plan(p, t).to_pydict()
        monkeypatch.setenv("SRT_RETRY_MAX", "1")
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:2")
        reset_faults()
        before = recovery_stats().snapshot()
        assert run_plan(p, t).to_pydict() == oracle
        d = recovery_stats().delta(before)
        assert d["splits"] >= 1

    def test_combine_split_with_null_keys(self, monkeypatch):
        # Group keys carry nulls and the values are integer-valued floats:
        # the split path's partial-aggregate merge must neither lose the
        # null group nor change any sum.
        n = 150
        r = np.random.default_rng(7)
        t = Table({
            "k": Column.from_numpy(r.integers(0, 4, n).astype(np.int64),
                                   validity=r.random(n) > 0.15),
            "v": Column.from_numpy(
                r.integers(0, 100, n).astype(np.float64),
                validity=r.random(n) > 0.2),
        })
        p = plan().groupby_agg(
            ["k"], [("v", "sum", "s"), ("v", "count", "c")],
            domains={"k": (0, 3)})
        oracle = _rowset(run_plan(p, t))
        monkeypatch.setenv("SRT_RETRY_MAX", "1")
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:2")
        reset_faults()
        before = recovery_stats().snapshot()
        assert _rowset(run_plan(p, t)) == oracle
        assert recovery_stats().delta(before)["splits"] >= 1

    def test_recursive_split_shrinks_until_it_fits(self, monkeypatch):
        # Enough faults to exhaust the first split level too: pieces
        # re-enter the ladder and split again (depth 2), still exact.
        t = _mk(200, seed=8)
        p = _row_local_plan()
        oracle = run_plan(p, t).to_pydict()
        monkeypatch.setenv("SRT_RETRY_MAX", "1")
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:4")
        reset_faults()
        before = recovery_stats().snapshot()
        assert run_plan(p, t).to_pydict() == oracle
        assert recovery_stats().delta(before)["splits"] >= 2


class TestStreamRecovery:
    def _batches(self, t, size=50):
        import jax.numpy as jnp
        n = t.num_rows
        return [t.gather(jnp.arange(i, min(i + size, n), dtype=jnp.int32))
                for i in range(0, n, size)]

    @pytest.mark.parametrize("site", ALL_SITES)
    def test_per_batch_stream_single_oom(self, monkeypatch, site):
        t = _mk(150, seed=9)
        p = _row_local_plan()
        oracle = [x.to_pydict() for x in
                  run_plan_stream(p, self._batches(t), combine=False)]
        monkeypatch.setenv("SRT_FAULT", f"oom:{site}:1")
        reset_faults()
        got = [x.to_pydict() for x in
               run_plan_stream(p, self._batches(t), combine=False)]
        assert got == oracle

    @pytest.mark.parametrize("site", ALL_SITES + ("stream-combine",))
    def test_combine_stream_single_oom(self, monkeypatch, site):
        t = _mk(150, seed=10)
        p = _grouped_plan()
        [oracle] = run_plan_stream(p, self._batches(t), combine=True)
        oracle = oracle.to_pydict()
        monkeypatch.setenv("SRT_FAULT", f"oom:{site}:1")
        reset_faults()
        [got] = run_plan_stream(p, self._batches(t), combine=True)
        assert got.to_pydict() == oracle

    def test_per_batch_stream_split_preserves_order(self, monkeypatch):
        # Ladder exhaustion mid-stream splits ONE batch; its recombined
        # output must ride the in-flight window in its original slot.
        t = _mk(150, seed=11)
        p = _row_local_plan()
        oracle = [x.to_pydict() for x in
                  run_plan_stream(p, self._batches(t), combine=False)]
        monkeypatch.setenv("SRT_RETRY_MAX", "1")
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:2")
        reset_faults()
        before = recovery_stats().snapshot()
        got = [x.to_pydict() for x in
               run_plan_stream(p, self._batches(t), combine=False)]
        assert got == oracle
        assert recovery_stats().delta(before)["splits"] >= 1

    def test_combine_stream_split_preserves_carry(self, monkeypatch):
        # The split batch folds into the SAME binomial-tree position as
        # its unsplit self, so the final accumulator is unchanged.
        t = _mk(200, seed=12)
        p = _grouped_plan()
        [oracle] = run_plan_stream(p, self._batches(t), combine=True)
        oracle = oracle.to_pydict()
        monkeypatch.setenv("SRT_RETRY_MAX", "1")
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:2")
        reset_faults()
        before = recovery_stats().snapshot()
        [got] = run_plan_stream(p, self._batches(t), combine=True)
        assert got.to_pydict() == oracle
        assert recovery_stats().delta(before)["splits"] >= 1

    def test_stream_metrics_record_recovery(self, monkeypatch, metrics_on):
        from spark_rapids_tpu.obs import last_stream_metrics
        t = _mk(100, seed=13)
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:1")
        reset_faults()
        list(run_plan_stream(_row_local_plan(), self._batches(t),
                             combine=False))
        rec = json.loads(last_stream_metrics().to_json())["recovery"]
        assert rec["retries"] >= 1


# ---------------------------------------------------------------------------
# 4. honest failure
# ---------------------------------------------------------------------------

class TestExhaustion:
    def test_unsplittable_plan_chains_original_error(self, monkeypatch):
        # A sort-terminated plan can neither concat-split nor
        # combine-split; exhaustion must surface ExecutionRecoveryError
        # chaining the original RESOURCE_EXHAUSTED and naming every rung.
        t = _mk(100, seed=14)
        p = plan().sort_by("v")
        monkeypatch.setenv("SRT_RETRY_MAX", "1")
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:99")
        reset_faults()
        with pytest.raises(ExecutionRecoveryError) as ei:
            run_plan(p, t)
        err = ei.value
        assert err.site == "dispatch"
        assert "RESOURCE_EXHAUSTED" in str(err.__cause__)
        msg = str(err)
        assert "evict-caches" in msg and "retry" in msg
        assert "split-unavailable" in msg

    def test_split_depth_is_bounded(self, monkeypatch):
        # Inexhaustible faults: splitting must stop at MAX_SPLIT_DEPTH
        # and fail honestly instead of recursing to single-row batches.
        t = _mk(150, seed=15)
        monkeypatch.setenv("SRT_RETRY_MAX", "0")
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:9999")
        reset_faults()
        with pytest.raises(ExecutionRecoveryError) as ei:
            run_plan(_row_local_plan(), t)
        assert "split" in str(ei.value)

    def test_io_exhaustion_preserves_chain(self, monkeypatch):
        monkeypatch.setenv("SRT_FAULT", "io:read:9999")
        monkeypatch.setenv("SRT_RETRY_MAX", "2")
        reset_faults()

        def read():
            fault_point("read")

        with pytest.raises(InjectedFault) as ei:
            with_retries(read, retryable=(CATEGORY_IO,), site="read")
        assert ei.value.recovery_summary.retries == 2


class TestFeedResilience:
    def test_parquet_scan_survives_seeded_flake(self, monkeypatch,
                                                tmp_path):
        import pyarrow.parquet as pq

        from spark_rapids_tpu.io import scan_parquet
        from spark_rapids_tpu.io.arrow import to_arrow
        t = _mk(300, seed=16)
        path = str(tmp_path / "flaky.parquet")
        pq.write_table(to_arrow(t), path, row_group_size=64)
        clean = [b.to_pydict() for b in scan_parquet(path)]
        monkeypatch.setenv("SRT_RETRY_MAX", "8")
        monkeypatch.setenv("SRT_FAULT", "io:read:0.5:seed=7")
        reset_faults()
        before = recovery_stats().snapshot()
        got = [b.to_pydict() for b in scan_parquet(path)]
        assert got == clean
        assert recovery_stats().delta(before)["retries"] >= 1

    def test_stall_watchdog_raises(self, monkeypatch):
        from spark_rapids_tpu.io.feed import prefetch
        monkeypatch.setenv("SRT_STREAM_TIMEOUT", "0.3")
        release = threading.Event()

        def stalling():
            yield 1
            release.wait(30)               # simulated wedged IO
            yield 2

        gen = prefetch(stalling(), depth=1)
        assert next(gen) == 1
        t0 = time.monotonic()
        with pytest.raises(StreamStallError) as ei:
            next(gen)
        release.set()
        gen.close()
        assert time.monotonic() - t0 < 5.0
        assert "SRT_STREAM_TIMEOUT" in str(ei.value)

    def test_watchdog_off_by_default(self, monkeypatch):
        from spark_rapids_tpu.config import stream_timeout
        monkeypatch.delenv("SRT_STREAM_TIMEOUT", raising=False)
        assert stream_timeout() is None
        for off in ("0", "off", "false", ""):
            monkeypatch.setenv("SRT_STREAM_TIMEOUT", off)
            assert stream_timeout() is None
        monkeypatch.setenv("SRT_STREAM_TIMEOUT", "2.5")
        assert stream_timeout() == 2.5
        monkeypatch.setenv("SRT_STREAM_TIMEOUT", "-1")
        with pytest.raises(ValueError):
            stream_timeout()


def _has_shard_map():
    import jax
    return hasattr(jax, "shard_map")


class TestShuffleBounds:
    @pytest.mark.skipif(not _has_shard_map(),
                        reason="jax.shard_map unavailable")
    def test_overflow_error_names_occupancy(self, monkeypatch):
        from spark_rapids_tpu.parallel import make_mesh, shard_table
        from spark_rapids_tpu.parallel.shuffle import shuffle
        mesh = make_mesh()
        n = 64 * mesh.devices.size
        t = Table.from_pydict({"k": np.zeros(n, dtype=np.int64),
                               "v": np.arange(n)})
        dist = shard_table(t, mesh)
        monkeypatch.setenv("SRT_SHUFFLE_RETRY_MAX", "0")
        with pytest.raises(ShuffleOverflowError) as ei:
            shuffle(dist, mesh, ["k"], bucket_size=8)
        msg = str(ei.value)
        assert "occupancy" in msg and "SRT_SHUFFLE_RETRY_MAX" in msg

    @pytest.mark.skipif(not _has_shard_map(),
                        reason="jax.shard_map unavailable")
    def test_bounded_retry_recovers_from_skew(self, monkeypatch):
        from spark_rapids_tpu.parallel import collect, make_mesh, shard_table
        from spark_rapids_tpu.parallel.shuffle import shuffle
        mesh = make_mesh()
        n = 64 * mesh.devices.size
        t = Table.from_pydict({"k": np.zeros(n, dtype=np.int64),
                               "v": np.arange(n)})
        dist = shard_table(t, mesh)
        out = shuffle(dist, mesh, ["k"], bucket_size=8)
        got = collect(out)
        assert _rowset(got) == _rowset(t)


# ---------------------------------------------------------------------------
# 5. import hygiene
# ---------------------------------------------------------------------------

def test_resilience_imports_without_jax():
    """Failure-model tooling (classify, fault specs, retry policy) must
    run on hosts without the XLA stack — graft the package onto a stub
    parent and import it alone."""
    import os
    import pathlib
    pkg_dir = pathlib.Path(__file__).resolve().parents[1]
    code = (
        "import sys, types\n"
        "pkg = types.ModuleType('spark_rapids_tpu')\n"
        f"pkg.__path__ = [{str(pkg_dir / 'spark_rapids_tpu')!r}]\n"
        "sys.modules['spark_rapids_tpu'] = pkg\n"
        "import spark_rapids_tpu.resilience as res\n"
        "assert 'jax' not in sys.modules, \\\n"
        "    'importing spark_rapids_tpu.resilience pulled in jax'\n"
        "assert res.classify(MemoryError()) == 'oom'\n"
        "assert res.RetryPolicy(2, 0.0).delay(1) == 0.0\n"
        "print('jaxfree')\n"
    )
    env = dict(os.environ)
    env.pop("SRT_FAULT", None)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "jaxfree" in out.stdout


# ---------------------------------------------------------------------------
# faulted CI lane (ci/premerge-build.sh runs these with SRT_FAULT +
# SRT_METRICS exported; the tests pin their own spec so they also pass
# standalone)
# ---------------------------------------------------------------------------

@pytest.mark.faulted
class TestFaultedSmoke:
    def test_materialize_fault_golden(self, monkeypatch, metrics_on):
        t = _mk(120, seed=20)
        p = _grouped_plan()
        monkeypatch.delenv("SRT_FAULT", raising=False)
        reset_faults()
        golden = run_plan(p, t).to_pydict()
        monkeypatch.setenv("SRT_FAULT", "oom:materialize:1")
        reset_faults()
        assert run_plan(p, t).to_pydict() == golden
        rec = json.loads(last_query_metrics().to_json())["recovery"]
        assert rec["retries"] >= 1 and rec["cache_evictions"] >= 1
        snap = registry().snapshot()
        assert snap.get("recovery.retries", 0) >= 1
        assert snap.get("resilience.faults_injected", 0) >= 1

    def test_stream_fault_golden(self, monkeypatch, metrics_on):
        import jax.numpy as jnp
        t = _mk(120, seed=21)
        p = _row_local_plan()
        batches = lambda: [t.gather(jnp.arange(i, min(i + 40, 120),
                                               dtype=jnp.int32))
                           for i in range(0, 120, 40)]
        monkeypatch.delenv("SRT_FAULT", raising=False)
        reset_faults()
        golden = [x.to_pydict() for x in
                  run_plan_stream(p, batches(), combine=False)]
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:1")
        reset_faults()
        got = [x.to_pydict() for x in
               run_plan_stream(p, batches(), combine=False)]
        assert got == golden
        assert registry().snapshot().get("recovery.retries", 0) >= 1


# ---------------------------------------------------------------------------
# encoded-scan residency under the recovery ladder (SRT_ENCODED_EXEC): the
# registry is device state, so evict_device_caches must drop it (counted),
# and a fault mid-encoded-execution must recover bit-identically with the
# retry re-encoding from values
# ---------------------------------------------------------------------------

class TestEncodedScanRecovery:
    def _dict_file(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq
        n = 1200
        words = [f"w-{i}" for i in range(6)]
        at = pa.table({
            "s": pa.array([words[i % 6] for i in range(n)]),
            "v": pa.array(np.arange(n, dtype=np.float64)),
        })
        p = tmp_path / "enc.parquet"
        pq.write_table(at, p, row_group_size=400)
        return p

    def test_evict_drops_resident_encodings_counted(self):
        from spark_rapids_tpu.ops.strings import (dictionary_encode,
                                                  register_resident_encoding,
                                                  resident_encoding,
                                                  strings_from_pylist)
        from spark_rapids_tpu.resilience.recovery import evict_device_caches
        s = strings_from_pylist(["b", "a", None, "b"])
        codes, uniq = dictionary_encode(s)
        register_resident_encoding(s, codes, tuple(uniq))
        assert resident_encoding(s) is not None
        before = recovery_stats().snapshot()
        dropped = evict_device_caches()
        assert dropped >= 1
        assert resident_encoding(s) is None
        assert recovery_stats().delta(before)["cache_evictions"] == dropped

    def test_oom_mid_encoded_scan_recovers_and_reencodes(self, monkeypatch,
                                                         tmp_path):
        from spark_rapids_tpu.io.parquet_native import read_parquet_native
        from spark_rapids_tpu.ops.strings import resident_encoding
        monkeypatch.setenv("SRT_ENCODED_EXEC", "1")
        p = self._dict_file(tmp_path)
        q = plan().filter(col("v") > 100.0).groupby_agg(
            ["s"], [("v", "sum", "sv"), ("v", "count", "c")])
        oracle = _rowset(run_plan(q, read_parquet_native(p)))
        t = read_parquet_native(p)          # fresh read: residency is live
        assert resident_encoding(t["s"]) is not None
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:1")
        reset_faults()
        before = recovery_stats().snapshot()
        assert _rowset(run_plan(q, t)) == oracle
        d = recovery_stats().delta(before)
        assert d["retries"] >= 1 and d["cache_evictions"] >= 1
        # the ladder dropped the scan residency wholesale; the retried
        # attempt re-encoded from values — results never depended on it
        assert resident_encoding(t["s"]) is None


# ---------------------------------------------------------------------------
# 8. mesh fault grammar, stall watchdog, degradation knobs (jax-free units;
#    the end-to-end mesh ladder lives in test_exec_dist.py)
# ---------------------------------------------------------------------------

class TestShardTargetedFaults:
    def test_shard_selector_fires_only_on_matching_shard(self, monkeypatch):
        monkeypatch.setenv("SRT_FAULT", "oom:dist-dispatch:2:shard=3")
        reset_faults()
        fault_point("dist-dispatch", shard=0)        # other shard: clean
        fault_point("dist-dispatch", shard=2)
        fault_point("dist-dispatch")                 # no shard: clean
        with pytest.raises(InjectedFault) as ei:
            fault_point("dist-dispatch", shard=3)
        assert "shard 3" in str(ei.value)
        assert classify(ei.value) == CATEGORY_OOM
        with pytest.raises(InjectedFault):
            fault_point("dist-dispatch", shard=3)    # count=2: twice
        fault_point("dist-dispatch", shard=3)        # then exhausted

    def test_shardless_spec_matches_any_shard(self, monkeypatch):
        monkeypatch.setenv("SRT_FAULT", "oom:shuffle:1")
        reset_faults()
        with pytest.raises(InjectedFault):
            fault_point("shuffle", shard=5)

    def test_bad_shard_and_stall_specs_raise(self, monkeypatch):
        for bad in ("oom:shuffle:1:shard=-1", "oom:shuffle:1:shard=x",
                    "stall:collect"):
            monkeypatch.setenv("SRT_FAULT", bad)
            reset_faults()
            with pytest.raises(ValueError):
                fault_point("shuffle")

    def test_stall_spec_parses_and_is_released_by_reset(self, monkeypatch):
        # The stall parks the caller on an event (capped); reset_faults
        # from another thread releases it well under the cap.
        monkeypatch.setenv("SRT_FAULT", "stall:collect:1")
        reset_faults()
        t = threading.Timer(0.2, reset_faults)
        t.start()
        t0 = time.monotonic()
        fault_point("collect")                       # parks, then released
        t.join()
        assert 0.1 < time.monotonic() - t0 < 5.0


class TestDistGuard:
    def test_no_timeout_is_a_direct_call(self, monkeypatch):
        from spark_rapids_tpu.resilience import dist_guard
        monkeypatch.delenv("SRT_DIST_TIMEOUT", raising=False)
        before = threading.active_count()
        assert dist_guard("x", lambda: 41 + 1) == 42
        assert threading.active_count() == before    # no worker spawned

    def test_result_and_exception_pass_through(self, monkeypatch):
        from spark_rapids_tpu.resilience import dist_guard
        assert dist_guard("x", lambda: {"a": 1}, timeout=5.0) == {"a": 1}

        def boom():
            raise InjectedFault("oom", "x", "RESOURCE_EXHAUSTED: unit")
        with pytest.raises(InjectedFault) as ei:
            dist_guard("x", boom, timeout=5.0)
        assert classify(ei.value) == CATEGORY_OOM    # classification intact

    def test_stall_raises_named_error_fast(self):
        from spark_rapids_tpu.resilience import DistStallError, dist_guard
        ev = threading.Event()
        t0 = time.monotonic()
        with pytest.raises(DistStallError, match="SRT_DIST_TIMEOUT"):
            dist_guard("unit.wedge", lambda: ev.wait(30), timeout=0.2)
        assert time.monotonic() - t0 < 3.0
        ev.set()                                     # release the worker
        # the watchdog's error must be terminal for the ladder
        assert classify(DistStallError("x")) == CATEGORY_FATAL

    def test_env_timeout_is_picked_up(self, monkeypatch):
        from spark_rapids_tpu.resilience import DistStallError, dist_guard
        monkeypatch.setenv("SRT_DIST_TIMEOUT", "0.2")
        ev = threading.Event()
        with pytest.raises(DistStallError):
            dist_guard("unit.wedge", lambda: ev.wait(30))
        ev.set()


class TestDegradationKnobs:
    def test_dist_fallback_parsing(self, monkeypatch):
        from spark_rapids_tpu.config import dist_fallback
        monkeypatch.delenv("SRT_DIST_FALLBACK", raising=False)
        assert dist_fallback() is None
        for off in ("0", "off", "false", ""):
            monkeypatch.setenv("SRT_DIST_FALLBACK", off)
            assert dist_fallback() is None
        monkeypatch.setenv("SRT_DIST_FALLBACK", "collect")
        assert dist_fallback() == "collect"
        monkeypatch.setenv("SRT_DIST_FALLBACK", "replicate")
        with pytest.raises(ValueError):
            dist_fallback()

    def test_dist_timeout_parsing(self, monkeypatch):
        from spark_rapids_tpu.config import dist_timeout
        monkeypatch.delenv("SRT_DIST_TIMEOUT", raising=False)
        assert dist_timeout() is None
        monkeypatch.setenv("SRT_DIST_TIMEOUT", "off")
        assert dist_timeout() is None
        monkeypatch.setenv("SRT_DIST_TIMEOUT", "2.5")
        assert dist_timeout() == 2.5
        monkeypatch.setenv("SRT_DIST_TIMEOUT", "-1")
        with pytest.raises(ValueError):
            dist_timeout()
