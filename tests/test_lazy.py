"""LazyTable facade: eager-looking pipelines flushed through the plan
compiler.  Oracle: the equivalent eager ops sequence."""

import numpy as np
import pytest

from spark_rapids_tpu import Column, Table, assert_tables_equal, ops
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.exec import col, lazy


def _table(rng, n=2000):
    return Table([
        ("g", Column.from_numpy(rng.integers(0, 16, n).astype(np.int32))),
        ("v", Column.from_numpy(rng.integers(-100, 100, n).astype(np.int64),
                                validity=rng.random(n) > 0.1)),
        ("price", Column.from_numpy(
            rng.integers(100, 99999, n).astype(np.int64),
            dtype=dt.decimal64(-2))),
        ("s", Column.from_pylist(
            [None if i % 7 == 0 else
             ["promo-x", "base-y", "promo-z", "w"][i % 4]
             for i in range(n)], dt.STRING)),
    ])


class TestLazyPipelines:
    def test_filter_expr_groupby(self, rng):
        t = _table(rng)
        got = (lazy(t).filter(col("v") > 0)
               .groupby_agg(["g"], [("v", "sum", "s"), ("v", "count", "c")])
               .sort_by(["g"]).collect())
        t2 = ops.apply_boolean_mask(t, ops.binary_op(t["v"], 0, "gt"))
        want = ops.sort_by(
            ops.groupby_agg(t2, ["g"], [("v", "sum", "s"),
                                        ("v", "count", "c")]), ["g"])
        assert_tables_equal(want, got)

    def test_precomputed_mask_and_cast_expr(self, rng):
        # The q28 shape: eager LIKE mask + in-plan cast + grouped sum,
        # with NO plan() in user code and one compiled program.
        from spark_rapids_tpu.ops import strings
        t = _table(rng)
        mask = strings.like(t["s"], "promo%")
        got = (lazy(t)
               .filter(mask)
               .with_columns(pricef=col("price").cast(dt.FLOAT64))
               .groupby_agg(["g"], [("pricef", "sum", "rev"),
                                    ("pricef", "count", "n")])
               .sort_by(["g"]).collect())
        t2 = ops.apply_boolean_mask(t, mask)
        t2 = t2.with_column("pricef", ops.cast(t2["price"], dt.FLOAT64))
        want = ops.sort_by(
            ops.groupby_agg(t2, ["g"], [("pricef", "sum", "rev"),
                                        ("pricef", "count", "n")]), ["g"])
        assert_tables_equal(want, got, rtol=1e-12, atol=1e-9)
        # hidden attachments never leak into the schema
        assert not [nm for nm in got.names if nm.startswith("__")]

    def test_precomputed_column_attach(self, rng):
        t = _table(rng)
        extra = ops.cast(t["price"], dt.FLOAT64)
        got = (lazy(t).with_columns(pf=extra)
               .filter(col("pf") > 500.0)
               .select("g", "pf").collect())
        t2 = t.with_column("pf", extra)
        want = ops.apply_boolean_mask(
            t2, ops.binary_op(t2["pf"], 500.0, "gt")).select(["g", "pf"])
        assert_tables_equal(want, got)

    def test_attach_after_groupby_raises(self, rng):
        t = _table(rng)
        lt = lazy(t).groupby_agg(["g"], [("v", "sum", "s")])
        with pytest.raises(TypeError, match="row alignment"):
            lt.filter(Column.from_numpy(np.ones(16, np.bool_)))

    def test_misaligned_mask_raises(self, rng):
        t = _table(rng)
        with pytest.raises(ValueError, match="rows"):
            lazy(t).filter(Column.from_numpy(np.ones(3, np.bool_)))

    def test_cast_expr_in_plan(self, rng):
        t = _table(rng)
        got = (lazy(t)
               .with_columns(vd=col("v").cast(dt.FLOAT64) / 2.0)
               .select("vd").collect())
        want = Table([("vd", ops.binary_op(
            ops.cast(t["v"], dt.FLOAT64), 2.0, "truediv"))])
        assert_tables_equal(want, got, rtol=1e-12, atol=1e-12)

    def test_explain_and_repr(self, rng):
        t = _table(rng)
        lt = lazy(t).filter(col("v") > 0)
        assert "Filter" in lt.explain()
        assert "recorded steps" in repr(lt)


class TestLazyHygiene:
    def test_user_dunder_lazy_column_survives(self, rng):
        # A user column that happens to use the facade's hidden prefix is
        # never clobbered by an attach nor dropped at collect.
        n = 100
        t = Table([
            ("__lazy0__", Column.from_numpy(
                np.arange(n, dtype=np.int64))),
            ("v", Column.from_numpy(
                rng.integers(0, 10, n).astype(np.int64))),
        ])
        mask = Column.from_numpy(np.ones(n, np.bool_))
        out = lazy(t).filter(mask).collect()
        assert "__lazy0__" in out.names
        assert out["__lazy0__"].to_pylist() == list(range(n))

    def test_empty_source_narrow_select_then_mask(self, rng):
        # 0-row sources route through the eager fallback, whose narrow
        # select must preserve hidden attachments like the compiled path.
        t = Table([
            ("g", Column.from_numpy(np.zeros(0, np.int32))),
            ("v", Column.from_numpy(np.zeros(0, np.int64))),
        ])
        mask = Column.from_numpy(np.zeros(0, np.bool_))
        out = lazy(t).select("g").filter(mask).collect()
        assert out.num_rows == 0 and out.names == ("g",)

    def test_user_dunder_column_narrows_away(self, rng):
        # A user "__"-named column is ordinary data: an explicit narrow
        # select drops it (only ENGINE hidden names survive narrowing).
        n = 50
        t = Table([
            ("__priority", Column.from_numpy(np.arange(n, dtype=np.int64))),
            ("g", Column.from_numpy(np.zeros(n, np.int32))),
        ])
        out = lazy(t).select("g").collect()
        assert out.names == ("g",)
        out2 = (lazy(t).select("g")
                .filter(Column.from_numpy(np.ones(n, np.bool_))).collect())
        assert out2.names == ("g",)
