"""Native host bridge loader + ctypes wrappers.

Python half of the C ABI defined in native/src/bridge.cpp.  Plays the role of
the reference's ``NativeDepsLoader`` (RowConversion.java:23-25: locate the
packaged native library, load it once, lazily) with a dev-tree fallback that
builds the library on demand via g++ (the configure-once semantics of
build-libcudf.xml:22-59).

The wrappers expose the same two entry points as the reference's JNI layer
(convert to/from rows) operating on host numpy buffers, plus the layout
query.  Errors surface as Python exceptions carrying the native message (the
CATCH_STD reverse mapping).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

_LIB_NAME = "libspark_rapids_tpu_host.so"
_PKG_DIR = Path(__file__).resolve().parent
_REPO_NATIVE = _PKG_DIR.parent.parent / "native"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class NativeError(RuntimeError):
    """A C++-side failure, message propagated via srt_last_error()."""


def _build_from_source() -> Path:
    """Dev-tree fallback: compile the native library in one g++ invocation.

    CMake (native/CMakeLists.txt) is the official build; this keeps a source
    checkout self-bootstrapping, stamping the same provenance definitions.
    """
    src = _REPO_NATIVE / "src"
    if not src.is_dir():
        raise NativeError(
            f"{_LIB_NAME} not found in {_PKG_DIR} and no source tree at {src}")
    out = _PKG_DIR / _LIB_NAME
    try:
        rev = subprocess.run(["git", "rev-parse", "HEAD"], cwd=_REPO_NATIVE,
                             capture_output=True, text=True, check=False
                             ).stdout.strip() or "unknown"
    except OSError:
        rev = "unknown"
    from .. import __version__
    # Link to a process-unique temp path, then atomically publish: concurrent
    # first loads (e.g. pytest -n auto on a fresh checkout) must never dlopen
    # a partially-written ELF.
    tmp = out.with_name(f".{out.name}.{os.getpid()}.tmp")
    cmd = [
        "g++", "-std=c++17", "-O3", "-fPIC", "-shared",
        "-Wall", "-Wextra", "-Werror",
        f'-DSRT_VERSION="{__version__}"',
        f'-DSRT_GIT_REV="{rev}"',
        str(src / "row_layout.cpp"), str(src / "row_conversion.cpp"),
        str(src / "bridge.cpp"), "-pthread", "-o", str(tmp),
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
    except OSError as e:
        raise NativeError(f"native build failed: cannot run g++: {e}") from e
    if proc.returncode != 0:
        tmp.unlink(missing_ok=True)
        raise NativeError(f"native build failed:\n{proc.stderr}")
    os.replace(tmp, out)
    return out


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i32, i64 = ctypes.c_int32, ctypes.c_int64
    p = ctypes.POINTER
    lib.srt_last_error.restype = ctypes.c_char_p
    lib.srt_version.restype = ctypes.c_char_p
    lib.srt_build_info.restype = ctypes.c_char_p
    lib.srt_compute_fixed_width_layout.restype = i32
    lib.srt_compute_fixed_width_layout.argtypes = [
        i32, p(i32), p(i32), p(i32), p(i32), p(i32), p(i32), p(i32)]
    lib.srt_pack_rows.restype = i32
    lib.srt_pack_rows.argtypes = [
        i32, p(i32), p(i32), i64, p(ctypes.c_void_p), p(ctypes.c_void_p),
        ctypes.c_void_p]
    lib.srt_unpack_rows.restype = i32
    lib.srt_unpack_rows.argtypes = [
        i32, p(i32), p(i32), i64, ctypes.c_void_p, i64, p(ctypes.c_void_p),
        p(ctypes.c_void_p)]
    lib.srt_convert_to_rows.restype = i64
    lib.srt_convert_to_rows.argtypes = [
        i32, p(i32), p(i32), i64, p(ctypes.c_void_p), p(ctypes.c_void_p),
        i64, i32, p(i32), p(i32)]
    lib.srt_blobs_count.restype = i32
    lib.srt_blobs_count.argtypes = [i64]
    lib.srt_blob_num_rows.restype = i64
    lib.srt_blob_num_rows.argtypes = [i64, i32]
    lib.srt_blob_row_size.restype = i32
    lib.srt_blob_row_size.argtypes = [i64, i32]
    lib.srt_blob_data.restype = ctypes.c_void_p
    lib.srt_blob_data.argtypes = [i64, i32]
    lib.srt_blobs_free.restype = None
    lib.srt_blobs_free.argtypes = [i64]
    return lib


def _stale(lib_path: Path) -> bool:
    """True when any native source is newer than the built library."""
    src = _REPO_NATIVE / "src"
    if not src.is_dir():
        return False
    built = lib_path.stat().st_mtime
    return any(f.stat().st_mtime > built
               for f in src.iterdir() if f.suffix in (".cpp", ".hpp"))


def load() -> ctypes.CDLL:
    """Locate (or build) and load the native library, once per process.

    Resolution order: explicit ``SPARK_RAPIDS_TPU_NATIVE_LIB`` override, then
    the packaged/previously-built library (rebuilt if the native sources are
    newer — the configure-once-but-track-changes semantics of
    build-libcudf.xml:22-30), then a fresh source build.
    """
    global _lib
    with _lock:
        if _lib is None:
            env = os.environ.get("SPARK_RAPIDS_TPU_NATIVE_LIB")
            if env:
                path = Path(env)
            else:
                path = _PKG_DIR / _LIB_NAME
                if not path.exists() or _stale(path):
                    path = _build_from_source()
            _lib = _bind(ctypes.CDLL(str(path)))
        return _lib


def _check(lib: ctypes.CDLL, status: int) -> None:
    if status != 0:
        msg = lib.srt_last_error().decode()
        raise ValueError(msg) if status == 1 else NativeError(msg)


def build_info() -> dict:
    """Provenance stamped into the native artifact (build/build-info analog)."""
    lib = load()
    pairs = (kv.split("=", 1) for kv in lib.srt_build_info().decode().split(";"))
    return {k: v for k, v in pairs}


def _schema_arrays(schema) -> tuple:
    ids = np.asarray([int(dt.type_id) for dt in schema], np.int32)
    scales = np.asarray([int(getattr(dt, "scale", 0) or 0) for dt in schema],
                        np.int32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    # Keep the numpy arrays alive alongside the pointers.
    return (len(schema), ids.ctypes.data_as(i32p), scales.ctypes.data_as(i32p),
            ids, scales)


def compute_fixed_width_layout(schema) -> dict:
    """Native layout query; must agree byte-for-byte with rows/layout.py."""
    lib = load()
    ncols, ids_p, scales_p, *_keep = _schema_arrays(schema)
    starts = np.zeros(ncols, np.int32)
    sizes = np.zeros(ncols, np.int32)
    voff, vbytes, rsize = ctypes.c_int32(), ctypes.c_int32(), ctypes.c_int32()
    i32p = ctypes.POINTER(ctypes.c_int32)
    _check(lib, lib.srt_compute_fixed_width_layout(
        ncols, ids_p, scales_p, starts.ctypes.data_as(i32p),
        sizes.ctypes.data_as(i32p), ctypes.byref(voff), ctypes.byref(vbytes),
        ctypes.byref(rsize)))
    return {
        "column_starts": tuple(int(x) for x in starts),
        "column_sizes": tuple(int(x) for x in sizes),
        "validity_offset": voff.value,
        "validity_bytes": vbytes.value,
        "row_size": rsize.value,
    }


def _buffer_array(arrays: Sequence[Optional[np.ndarray]]):
    ptrs = (ctypes.c_void_p * len(arrays))()
    for i, a in enumerate(arrays):
        ptrs[i] = None if a is None else a.ctypes.data_as(ctypes.c_void_p).value
    return ptrs


def _checked_buffers(schema, datas, valids):
    """Validate + coerce caller buffers against the schema before they cross
    the FFI boundary (lengths and physical dtypes must match or native code
    would read out of bounds / pack garbage)."""
    if len(datas) != len(schema) or len(valids) != len(schema):
        raise ValueError(
            f"{len(datas)} data / {len(valids)} validity buffers for "
            f"{len(schema)} schema columns")
    num_rows = int(np.asarray(datas[0]).shape[0]) if datas else 0
    out_d, out_v = [], []
    for i, (dt, d, v) in enumerate(zip(schema, datas, valids)):
        d = np.ascontiguousarray(d)
        want = dt.np_dtype
        # Same width AND compatible kind: integer/bool buffers may view each
        # other (timestamps/decimals travel as int64), but float-for-int or
        # int-for-float of the same width is a caller bug, not a view.
        compatible = d.dtype == want or (
            d.dtype.itemsize == want.itemsize
            and d.dtype.kind in "iub" and want.kind in "iub")
        if not compatible:
            raise ValueError(
                f"column {i}: buffer dtype {d.dtype} does not match {dt!r}")
        if d.ndim != 1 or d.shape[0] != num_rows:
            raise ValueError(
                f"column {i}: expected shape ({num_rows},), got {d.shape}")
        if v is not None:
            v = np.ascontiguousarray(v, np.uint8)
            if v.ndim != 1 or v.shape[0] != num_rows:
                raise ValueError(
                    f"column {i}: validity shape {v.shape} != ({num_rows},)")
        out_d.append(d)
        out_v.append(v)
    return num_rows, out_d, out_v


def pack_rows(schema, datas: Sequence[np.ndarray],
              valids: Sequence[Optional[np.ndarray]]) -> np.ndarray:
    """Columnar numpy buffers -> one contiguous row-format byte buffer."""
    lib = load()
    ncols, ids_p, scales_p, *_keep = _schema_arrays(schema)
    # Size the output via the pure-Python layout engine (byte-identical by
    # test contract) — no extra FFI round trip on the hot path.
    from ..rows.layout import compute_fixed_width_layout as _py_layout
    row_size = _py_layout(schema).row_size
    num_rows, datas, valids = _checked_buffers(schema, datas, valids)
    # np.empty, not zeros: the native pack memsets the whole range itself
    # (its deterministic-zeros contract), so pre-zeroing is a wasted pass.
    out = np.empty(num_rows * row_size, np.uint8)
    _check(lib, lib.srt_pack_rows(
        ncols, ids_p, scales_p, num_rows, _buffer_array(datas),
        _buffer_array(valids), out.ctypes.data_as(ctypes.c_void_p)))
    return out


def unpack_rows(schema, rows: np.ndarray, num_rows: int):
    """Row-format byte buffer -> (list of column arrays, list of bool arrays).

    Validates the buffer size against the schema layout, as the reference does
    (row_conversion.cu:541).
    """
    lib = load()
    ncols, ids_p, scales_p, *_keep = _schema_arrays(schema)
    rows = np.ascontiguousarray(rows, np.uint8)
    datas = [np.zeros(num_rows, dt.np_dtype) for dt in schema]
    valids = [np.zeros(num_rows, np.uint8) for _ in schema]
    _check(lib, lib.srt_unpack_rows(
        ncols, ids_p, scales_p, num_rows, rows.ctypes.data_as(ctypes.c_void_p),
        rows.size, _buffer_array(datas), _buffer_array(valids)))
    return datas, [v.astype(np.bool_) for v in valids]


def convert_to_rows(schema, datas: Sequence[np.ndarray],
                    valids: Sequence[Optional[np.ndarray]],
                    max_batch_bytes: int = 0,
                    check_row_width: bool = True) -> list[np.ndarray]:
    """Batched conversion through the handle-based ABI.

    Applies the reference's output contract (blobs capped at 2 GB, batch row
    counts in 32-row multiples, optional 1 KB row-width gate); returns one
    byte array per blob (copies owned by Python; the native blob set is freed
    before returning, exercising the caller-owns-handle lifetime contract).
    """
    lib = load()
    ncols, ids_p, scales_p, *_keep = _schema_arrays(schema)
    num_rows, datas, valids = _checked_buffers(schema, datas, valids)
    nblobs = ctypes.c_int32()
    status = ctypes.c_int32()
    handle = lib.srt_convert_to_rows(
        ncols, ids_p, scales_p, num_rows, _buffer_array(datas),
        _buffer_array(valids), max_batch_bytes, 1 if check_row_width else 0,
        ctypes.byref(nblobs), ctypes.byref(status))
    if handle == 0:
        _check(lib, status.value or 2)
    try:
        out = []
        for i in range(nblobs.value):
            nbytes = (int(lib.srt_blob_num_rows(handle, i)) *
                      int(lib.srt_blob_row_size(handle, i)))
            addr = lib.srt_blob_data(handle, i)
            if nbytes == 0 or addr is None:
                out.append(np.zeros(0, np.uint8))
                continue
            buf = (ctypes.c_uint8 * nbytes).from_address(addr)
            out.append(np.frombuffer(buf, np.uint8).copy())
        return out
    finally:
        lib.srt_blobs_free(handle)


__all__ = [
    "NativeError",
    "build_info",
    "compute_fixed_width_layout",
    "convert_to_rows",
    "load",
    "pack_rows",
    "unpack_rows",
]
