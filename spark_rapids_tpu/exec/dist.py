"""Distributed execution of compiled plans over a device mesh.

The TPU answer to how spark-rapids runs a physical plan across executors:
instead of shuffling rows between workers over UCX, a distributed plan
runs the SAME per-shard program on every device under ``shard_map`` and
merges only the (cells,)-sized dense group-by accumulators with mesh
collectives — every merge (min/max included, via the psum-gather trick
in compile.py) is expressed as a SUM all-reduce because that is the one
collective the target TPU stack lowers — for the aggregation queries
that dominate TPC-DS, cross-device traffic is a few kilobytes riding ICI
regardless of row count, and there is no shuffle at all.

Plan-shape contract (validated at trace time):

* filter / project / broadcast join run per-shard (the build side is
  replicated to every device, exactly like a Spark broadcast);
* the first group-by must take the dense-domain path; its accumulator
  merge is the only collective.  After it, state is replicated and any
  further steps (sort, limit, more group-bys, filters on aggregates)
  run identically everywhere;
* a global sort or limit of still-sharded rows, or a sorted-fallback
  group-by of sharded rows, raises — that work needs a shuffle and
  belongs to :mod:`..parallel.dist_ops`.

Returns a materialized :class:`..table.Table` when the plan ends
replicated (aggregation plans), or a padded :class:`..parallel.mesh.
DistTable` when it ends row-sharded (pure filter/project pipelines).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from ..column import Column
from ..dtypes import BOOL8
from ..parallel.mesh import DistTable, shard_map
from ..table import Table
from .compile import _Bound, _assemble, _final_order, materialize
from .plan import GroupAggStep, JoinShuffledStep, Plan

_DIST_COMPILED: dict = {}

# live-count cache per row-mask buffer identity: the empty-input guard
# needs one host sync, but steady-state repeat runs over the same
# DistTable must stay sync-free.
_LIVE_COUNT: dict = {}


def _live_count_cached(row_mask) -> int:
    from .stats import _guarded_cache_get, _guarded_cache_put
    key = (id(row_mask),)
    hit = _guarded_cache_get(_LIVE_COUNT, key, (row_mask,))
    if hit is not None:
        return hit
    count = int(jnp.sum(row_mask))
    from ..utils.memory import record_host_sync
    record_host_sync("dist.live_count", 8)
    _guarded_cache_put(_LIVE_COUNT, key, (row_mask,), count)
    return count


def _ends_replicated(bound: _Bound) -> bool:
    return any(isinstance(s, GroupAggStep) for s in bound.steps)


def _lower_shuffled_join(plan: Plan, dist: DistTable, mesh: Mesh):
    """Execute a plan containing a shuffled join: per-shard prefix, then
    the mesh shuffle join (both sides ``all_to_all``-repartitioned by key
    hash and merge-joined per shard, parallel.dist_ops), then the suffix
    plan on the joined DistTable.

    This is the distributed big-big join of the TPC-DS q95 shape: the
    single-chip compiled form binds a probe over whole tables; across a
    mesh the equivalent data movement is the shuffle itself.
    """
    from ..parallel.dist_ops import dist_join
    from ..parallel.mesh import shard_table

    from ..parallel.mesh import collect
    from .compile import run_plan_eager

    i = next(idx for idx, s in enumerate(plan.steps)
             if isinstance(s, JoinShuffledStep))
    step: JoinShuffledStep = plan.steps[i]
    if any(isinstance(s, GroupAggStep) for s in plan.steps[:i]):
        raise TypeError(
            "shuffled join after a group-by is not supported in a "
            "distributed plan (the left side is already an aggregate); "
            "join first, then aggregate")
    if step.how not in ("inner", "left"):
        raise TypeError(
            f"distributed shuffled join supports inner/left, not "
            f"{step.how!r} (semi/anti: aggregate the right side's keys "
            f"and use join_broadcast, or run single-chip)")

    right = step.table
    if any(c.offsets is not None for c in right.columns):
        raise TypeError(
            "distributed plans operate on fixed-width columns only "
            "(dictionary-encode the right table's strings first)")
    # Align key names so both shuffles route by the same columns.
    if tuple(step.left_on) != tuple(step.right_on):
        clashes = (set(step.left_on) &
                   (set(right.names) - set(step.right_on)))
        if clashes:
            raise ValueError(
                f"renaming right keys {step.right_on} -> {step.left_on} "
                f"collides with right columns {sorted(clashes)}; rename "
                f"them first")
        right = right.rename(dict(zip(step.right_on, step.left_on)))
    pre = (run_plan_dist(Plan(plan.steps[:i]), dist, mesh)
           if i else dist)
    overlap = (set(right.names) - set(step.left_on)) & set(pre.table.names)
    if overlap:
        raise ValueError(
            f"join output column(s) {sorted(overlap)} collide with "
            f"existing columns; rename one side first")
    # Degenerate shapes (0-row right side, prefix that filtered every row)
    # break shuffle/join trace-time assumptions — finish eagerly on the
    # collected rows, then restore the documented return contract: a plan
    # that ends row-sharded must hand back a DistTable regardless of the
    # data shape that routed it here (right-side emptiness is build-side
    # data the caller does not control).
    if right.num_rows == 0 or _live_count_cached(pre.row_mask) == 0:
        result = run_plan_eager(Plan(plan.steps[i:]), collect(pre))
        if any(isinstance(s, GroupAggStep) for s in plan.steps[i:]):
            return result                     # replicated-ending: a Table
        return shard_table(result, mesh)
    rdist = shard_table(right, mesh)
    joined = dist_join(pre, rdist, mesh, on=list(step.left_on),
                       how=step.how)
    return run_plan_dist(Plan(plan.steps[i + 1:]), joined, mesh)


def run_plan_dist(plan: Plan, dist: DistTable, mesh: Mesh):
    """Execute ``plan`` against a row-sharded table on ``mesh``."""
    if _live_count_cached(dist.row_mask) == 0:
        # Degenerate shapes break trace-time assumptions (and the probe
        # under an all-False mask); mirror run_plan's eager fallback.
        # Checked before the shuffled-join dispatch so every lowering
        # path sees live rows.  The return CONTRACT is preserved: a plan
        # that ends row-sharded hands back a DistTable here too.
        from ..parallel.mesh import collect, shard_table
        from .compile import run_plan_eager
        result = run_plan_eager(plan, collect(dist))
        if any(isinstance(s, GroupAggStep) for s in plan.steps):
            return result
        return shard_table(result, mesh)
    if any(isinstance(s, JoinShuffledStep) for s in plan.steps):
        return _lower_shuffled_join(plan, dist, mesh)
    axis = mesh.axis_names[0]
    axis_size = int(mesh.shape[axis])
    table = dist.table
    bound = _Bound(plan, table, probe_mask=dist.row_mask)
    if bound.string_cols or bound.dictionaries:
        raise TypeError(
            "distributed plans operate on fixed-width columns only "
            "(dictionary-encode strings before sharding, as shard_table "
            "requires)")
    replicated_out = _ends_replicated(bound)

    # The compiled function closes over the concrete mesh via shard_map,
    # so the cache key must identify the mesh by its actual devices, not
    # just its shape.
    mesh_key = (axis, tuple(d.id for d in mesh.devices.flat))
    key = bound.signature() + (mesh_key, replicated_out)
    from ..obs import timeline as _tl
    from ..obs.metrics import counter, gauge
    fn = _DIST_COMPILED.get(key)
    counter(f"dist.compile_cache.{'miss' if fn is None else 'hit'}").inc()
    _tl.instant(f"dist.compile_cache.{'miss' if fn is None else 'hit'}",
                cat="dist", shards=axis_size)
    gauge("dist.mesh_devices").set(axis_size)
    if fn is None:
        program = _assemble(bound.assembly_steps(), tuple(bound.group_metas),
                            tuple(bound.join_metas), axis=axis,
                            axis_size=axis_size,
                            union_metas=tuple(bound.union_metas))

        def sharded_program(cols, row_mask, side):
            # Padding slots enter as dead rows via the initial selection.
            return program(cols, side, init_sel=row_mask)

        out_spec = PartitionSpec() if replicated_out else PartitionSpec(axis)
        fn = jax.jit(partial(
            shard_map,
            mesh=mesh,
            in_specs=(PartitionSpec(axis), PartitionSpec(axis),
                      PartitionSpec()),
            out_specs=(out_spec, out_spec),
            check_vma=False,
        )(sharded_program))
        _DIST_COMPILED[key] = fn

    tl_on = _tl.enabled()
    t0 = _tl.now_us() if tl_on else 0.0
    out_cols, sel = fn(bound.exec_cols, dist.row_mask, bound.side_inputs)
    if tl_on:
        # Block so the recorded interval covers device wall, then emit it
        # once per shard lane: the host cannot observe per-core device
        # timelines without the jax profiler, but the shard_map program is
        # SPMD — every shard runs the same program over the same interval,
        # and the replicated-out group-by merge is its ICI collective.
        out_cols, sel = jax.block_until_ready((out_cols, sel))
        dur = _tl.now_us() - t0
        _tl.add_complete("dist.dispatch", "dist", t0, dur, lane="dist",
                         shards=axis_size, replicated=replicated_out)
        if replicated_out:
            for s in range(axis_size):
                _tl.add_complete("ici.psum", "ici", t0, dur,
                                 lane=f"shard-{s}", shard=s,
                                 collective="psum")
    if replicated_out:
        return materialize(bound, out_cols, sel)
    order = [nm for nm in _final_order(plan.steps, bound.input_names)
             if nm in out_cols]
    order += [nm for nm in out_cols if nm not in order]
    return DistTable(table=Table([(nm, out_cols[nm]) for nm in order]),
                     row_mask=sel.astype(jnp.bool_))
