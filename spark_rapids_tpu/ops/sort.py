"""Multi-key stable sort.

TPU-first design: a single ``jax.lax.sort`` call with ``num_keys`` operands —
XLA's native lexicographic multi-key sort, which lowers to the TPU's
sort HLO — instead of the hash/radix machinery a GPU engine would use
(sort is the workhorse here: groupby and join are built on it, because
scatter-to-random-address hash tables are hostile to the TPU memory system;
see SURVEY.md §7 "Hard parts").

Null ordering is encoded as a leading rank key per sort key (0/1 before the
value), so nulls group deterministically without sentinel values; descending
order inverts integer keys bitwise (``~x``, total-order-preserving, no
overflow) and negates floats after NaN canonicalization (XLA total order then
places NaN consistently: ascending -> after +inf, Spark/cuDF semantics).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..column import Column
from ..table import Table


def _canonicalize_nan(x: jax.Array) -> jax.Array:
    if jnp.issubdtype(x.dtype, jnp.floating):
        return jnp.where(x != x, jnp.array(jnp.nan, x.dtype), x)
    return x


def _descending_key(x: jax.Array) -> jax.Array:
    if jnp.issubdtype(x.dtype, jnp.floating):
        return -x            # after NaN canonicalization: -NaN sorts first
    if x.dtype == jnp.bool_:
        return ~x
    return ~x                # bitwise complement: order-inverting for ints


def sort_operands(columns: Sequence[Column], ascending: Sequence[bool],
                  nulls_first: Sequence[bool]) -> list[jax.Array]:
    """Build the lax.sort key operands (2 per column: null rank, value;
    4 for DECIMAL128, whose (hi, lo) word pair carries the order)."""
    from .common import grouping_columns_with
    columns, ascending, nulls_first = grouping_columns_with(
        list(columns), list(ascending), list(nulls_first))
    ops: list[jax.Array] = []
    for col, asc, nf in zip(columns, ascending, nulls_first):
        valid = col.valid_mask()
        # rank 0 sorts first. nulls_first -> nulls rank 0.
        null_rank = jnp.where(valid, jnp.uint8(1 if nf else 0),
                              jnp.uint8(0 if nf else 1))
        val = _canonicalize_nan(col.data)
        if not asc:
            val = _descending_key(val)
        if col.validity is not None:
            # Null rows' payloads are undefined; mask them to a constant so
            # ordering among nulls falls through to the NEXT key (and then
            # to stability), never to garbage bytes.
            val = jnp.where(col.validity, val, jnp.zeros((), val.dtype))
        ops.append(null_rank)
        ops.append(val)
    return ops


def sorted_order(columns: Sequence[Column],
                 ascending: Optional[Sequence[bool]] = None,
                 nulls_first: Optional[Sequence[bool]] = None) -> jax.Array:
    """Stable permutation that sorts by the given key columns."""
    n = columns[0].size
    if ascending is None:
        ascending = [True] * len(columns)
    if nulls_first is None:
        # Spark default: nulls first when ascending, last when descending.
        nulls_first = [a for a in ascending]
    ops = sort_operands(columns, ascending, nulls_first)
    iota = jnp.arange(n, dtype=jnp.int32)
    out = lax.sort(ops + [iota], dimension=0, is_stable=True, num_keys=len(ops))
    return out[-1]


def sort_by(table: Table, by: Union[str, Sequence[str]],
            ascending: Optional[Sequence[bool]] = None,
            nulls_first: Optional[Sequence[bool]] = None) -> Table:
    """Sort a table by key columns (stable, multi-key, null-order aware)."""
    if isinstance(by, str):
        by = [by]
    perm = sorted_order([table[name] for name in by], ascending, nulls_first)
    return table.gather(perm)
