"""TPC-DS bank, logistics family: shipping-lag and inventory shapes.

Same conventions as :mod:`.tpcds_queries` (dimension pre-filtering,
group-by-id/decode-after, FLOAT64 money); oracle-checked in
tests/test_tpcds_logistics.py.  Imported by :mod:`.tpcds_queries` for the
registry merge; shared helpers live in :mod:`.tpcds_lib` to keep that
merge acyclic.
"""

from __future__ import annotations

import numpy as np

from ..column import Column
from ..dtypes import FLOAT64, STRING
from ..table import Table
from ..exec import col, lit, plan, when
from .tpcds import (BRANDS, CATEGORIES, DATE_SK0, SHIP_MODE_TYPES,
                    TpcdsData)
from .tpcds_lib import _dim, _lag_buckets, _vocab_map


def _ship_type_map() -> Table:
    return _vocab_map("__type_id", "sm_type", SHIP_MODE_TYPES)


def q62(d: TpcdsData) -> Table:
    """TPC-DS q62: web-sales shipping-lag distribution per (warehouse,
    ship-mode type, web site) — five CASE-summed 30-day buckets."""
    dates = _dim(d.date_dim, col("d_month_seq").between(0, 11),
                 ["d_date_sk"])
    sm = d.ship_mode.select(["sm_ship_mode_sk", "sm_type_id"])
    wh = (d.warehouse.select(["w_warehouse_sk", "w_warehouse_name"])
          .rename({"w_warehouse_sk": "__wh_sk"}))
    sites = (d.web_site.select(["web_site_sk", "web_name"])
             .rename({"web_site_sk": "__site_sk"}))
    lag = col("ws_ship_date_sk") - col("ws_sold_date_sk")
    p = (plan()
         .join_broadcast(dates, left_on="ws_ship_date_sk",
                         right_on="d_date_sk", how="semi")
         .join_broadcast(sm, left_on="ws_ship_mode_sk",
                         right_on="sm_ship_mode_sk"))
    p = (_lag_buckets(p, lag)
         .groupby_agg(["ws_warehouse_sk", "sm_type_id", "ws_web_site_sk"],
                      [("d30", "sum", "days_30"), ("d60", "sum", "days_60"),
                       ("d90", "sum", "days_90"),
                       ("d120", "sum", "days_120"),
                       ("dmore", "sum", "days_more")])
         .join_broadcast(wh, left_on="ws_warehouse_sk", right_on="__wh_sk")
         .join_broadcast(_ship_type_map(), left_on="sm_type_id",
                         right_on="__type_id")
         .join_broadcast(sites, left_on="ws_web_site_sk",
                         right_on="__site_sk")
         .sort_by(["ws_warehouse_sk", "sm_type_id", "ws_web_site_sk"])
         .limit(100))
    return p.run(d.web_sales)


def q99(d: TpcdsData) -> Table:
    """TPC-DS q99: q62's shipping-lag shape over the catalog channel per
    (warehouse, ship-mode type, call center)."""
    dates = _dim(d.date_dim, col("d_month_seq").between(0, 11),
                 ["d_date_sk"])
    sm = d.ship_mode.select(["sm_ship_mode_sk", "sm_type_id"])
    wh = (d.warehouse.select(["w_warehouse_sk", "w_warehouse_name"])
          .rename({"w_warehouse_sk": "__wh_sk"}))
    ccs = (d.call_center.select(["cc_call_center_sk", "cc_name"])
           .rename({"cc_call_center_sk": "__cc_sk"}))
    lag = col("cs_ship_date_sk") - col("cs_sold_date_sk")
    p = (plan()
         .join_broadcast(dates, left_on="cs_ship_date_sk",
                         right_on="d_date_sk", how="semi")
         .join_broadcast(sm, left_on="cs_ship_mode_sk",
                         right_on="sm_ship_mode_sk"))
    p = (_lag_buckets(p, lag)
         .groupby_agg(["cs_warehouse_sk", "sm_type_id",
                       "cs_call_center_sk"],
                      [("d30", "sum", "days_30"), ("d60", "sum", "days_60"),
                       ("d90", "sum", "days_90"),
                       ("d120", "sum", "days_120"),
                       ("dmore", "sum", "days_more")])
         .join_broadcast(wh, left_on="cs_warehouse_sk", right_on="__wh_sk")
         .join_broadcast(_ship_type_map(), left_on="sm_type_id",
                         right_on="__type_id")
         .join_broadcast(ccs, left_on="cs_call_center_sk",
                         right_on="__cc_sk")
         .sort_by(["cs_warehouse_sk", "sm_type_id", "cs_call_center_sk"])
         .limit(100))
    return p.run(d.catalog_sales)


def q21(d: TpcdsData) -> Table:
    """TPC-DS q21: per (warehouse, item) inventory totals in the 30 days
    before vs after a pivot date, kept when the after/before ratio is
    within [2/3, 3/2].  Price band widened from the spec's 0.99..1.49 to
    keep the synthetic item subset non-empty at small scales."""
    pivot = DATE_SK0 + 360
    items = _dim(d.item, col("i_current_price").between(20.0, 60.0),
                 ["i_item_sk"])
    item_ids = (d.item.select(["i_item_sk", "i_item_id"])
                .rename({"i_item_sk": "__i_sk"}))
    wh = (d.warehouse.select(["w_warehouse_sk", "w_warehouse_name"])
          .rename({"w_warehouse_sk": "__wh_sk"}))
    p = (plan()
         .join_broadcast(items, left_on="inv_item_sk",
                         right_on="i_item_sk", how="semi")
         .filter(col("inv_date_sk").between(pivot - 30, pivot + 30))
         .with_columns(
             before=when(col("inv_date_sk") < pivot,
                         col("inv_quantity_on_hand")).otherwise(0),
             after=when(col("inv_date_sk") >= pivot,
                        col("inv_quantity_on_hand")).otherwise(0))
         .groupby_agg(["inv_warehouse_sk", "inv_item_sk"],
                      [("before", "sum", "inv_before"),
                       ("after", "sum", "inv_after")])
         .filter((col("inv_before") > 0)
                 & (col("inv_after").cast(FLOAT64)
                    / col("inv_before").cast(FLOAT64))
                 .between(2.0 / 3.0, 3.0 / 2.0))
         .join_broadcast(wh, left_on="inv_warehouse_sk",
                         right_on="__wh_sk")
         .join_broadcast(item_ids, left_on="inv_item_sk",
                         right_on="__i_sk")
         .sort_by(["inv_warehouse_sk", "inv_item_sk"])
         .limit(100))
    return p.run(d.inventory)


def _in_stock_sold_items(d: TpcdsData, fact: Table, date_col: str,
                         item_col: str, price_lo: float,
                         price_hi: float, lo_d: int, hi_d: int) -> Table:
    """Shared q37/q82 shape: items in a price band with 100..500 units on
    hand during a 60-day window that also sold through ``fact``."""
    inv = (plan()
           .filter(col("inv_quantity_on_hand").between(100, 500)
                   & col("inv_date_sk").between(lo_d, hi_d))
           .select("inv_item_sk")
           .run(d.inventory))
    sold = (plan()
            .filter(col(date_col).between(lo_d, hi_d))
            .select(item_col)
            .run(fact))
    p = (plan()
         .filter(col("i_current_price").between(price_lo, price_hi))
         .join_broadcast(inv, left_on="i_item_sk",
                         right_on="inv_item_sk", how="semi")
         .join_broadcast(sold, left_on="i_item_sk",
                         right_on=item_col, how="semi")
         .select("i_item_sk", "i_item_id", "i_current_price")
         .sort_by(["i_item_sk"])
         .limit(100))
    return p.run(d.item)


def q37(d: TpcdsData) -> Table:
    """TPC-DS q37: catalog-channel items in a price band with 100..500
    units on hand during a 60-day window."""
    return _in_stock_sold_items(d, d.catalog_sales, "cs_sold_date_sk",
                                "cs_item_sk", 20.0, 50.0,
                                DATE_SK0 + 300, DATE_SK0 + 360)


def q82(d: TpcdsData) -> Table:
    """TPC-DS q82: q37's in-stock shape over the store channel."""
    return _in_stock_sold_items(d, d.store_sales, "ss_sold_date_sk",
                                "ss_item_sk", 30.0, 60.0,
                                DATE_SK0 + 60, DATE_SK0 + 120)


def q22(d: TpcdsData) -> Table:
    """TPC-DS q22: average quantity-on-hand rolled up over the product
    hierarchy for a 12-month window.  Deviation: the rollup runs over
    (i_category, i_brand) — the spec's leading i_product_name level is
    degenerate here because the product key functionally determines the
    rest of the hierarchy.  Three device group-bys (leaf, category,
    grand total) host-assembled into the rollup lattice with NULL
    grouping keys, spec-style."""
    attrs = d.item.select(["i_item_sk", "i_category_id", "i_brand_id"])
    base = (plan()
            .filter(col("inv_date_sk").between(DATE_SK0, DATE_SK0 + 330))
            .join_broadcast(attrs, left_on="inv_item_sk",
                            right_on="i_item_sk")
            .run(d.inventory))
    leaf = (plan()
            .groupby_agg(["i_category_id", "i_brand_id"],
                         [("inv_quantity_on_hand", "mean", "qoh")])
            .run(base).to_pydict())
    cat = (plan()
           .groupby_agg(["i_category_id"],
                        [("inv_quantity_on_hand", "mean", "qoh")])
           .run(base).to_pydict())
    total = (plan()
             .with_columns(one=lit(1))
             .groupby_agg(["one"],
                          [("inv_quantity_on_hand", "mean", "qoh")],
                          domains={"one": (1, 1)})
             .run(base).to_pydict())
    rows = []
    for c, b, q in zip(leaf["i_category_id"], leaf["i_brand_id"],
                       leaf["qoh"]):
        rows.append((c, b, q))
    for c, q in zip(cat["i_category_id"], cat["qoh"]):
        rows.append((c, None, q))
    for q in total["qoh"]:
        rows.append((None, None, q))
    # round the float sort key so the order (and the limit-100 cut) is
    # reproducible against an independent oracle computing the same
    # means in a different summation order
    rows.sort(key=lambda r: (round(r[2], 6) if r[2] is not None
                             else float("inf"),
                             r[0] if r[0] is not None else -1,
                             r[1] if r[1] is not None else -1))
    rows = rows[:100]
    cat_ids = [r[0] for r in rows]
    brand_ids = [r[1] for r in rows]
    return Table([
        ("i_category", Column.from_pylist(
            [None if c is None else CATEGORIES[c - 1] for c in cat_ids],
            STRING)),
        ("i_brand", Column.from_pylist(
            [None if b is None else BRANDS[b - 1] for b in brand_ids],
            STRING)),
        ("qoh", Column.from_numpy(
            np.asarray([np.nan if q is None else q for q in
                        (r[2] for r in rows)], dtype=np.float64),
            validity=np.asarray([r[2] is not None for r in rows]))),
    ])


QUERIES = {
    "q21": q21, "q22": q22, "q37": q37, "q62": q62, "q82": q82,
    "q99": q99,
}
