"""Semantic subplan cache + incremental materialized views
(spark_rapids_tpu/serve/semantic.py, spark_rapids_tpu/views/).

The contracts pinned here:

1. **Bit-identity oracle** — with ``SRT_SEMANTIC_CACHE`` off,
   ``run_table_plan`` *is* ``run_plan``; with it on, every served
   result (first compute, materializing compute, spliced cache hit)
   is bit-identical to the bare executor, including at bucket-boundary
   sizes with null keys, through the serving scheduler in every mode,
   and while the recovery ladder is rescuing an injected fault.
2. **CSE mechanics** — a shared prefix materializes on the second
   interested submission (first, when advisor-confirmed), later
   submissions splice it (hit counters move), an uncacheable prefix
   falls back to running the suffix over the in-hand result, and
   hit-rate-aware eviction reports cold evictions to the workload
   advisor (which damps future recommendations for that prefix).
3. **Views** — incremental fold + refresh is bit-identical to the
   streaming-combine executor over the same batches AND to a fresh
   view folded once; staleness/invalidate/memo-hit semantics hold;
   registration is knob-gated with a knob-named ValueError.
4. **Policy closure** — ``workload.advise()`` routes confirmed
   ``materialize_subplan`` recommendations into the semantic cache's
   confirmed set, and (``SRT_VIEWS_AUTO``) auto-registers known
   group-by plans over confirmed prefixes as ``auto:<fp>`` views.
5. **Result-cache mutation staleness** — an in-place Table mutation
   (``mark_mutated``) changes the input digest and invalidates any
   cached value holding the mutated table (regression: the cache used
   to serve the stale pre-mutation result).
6. **Observability** — bundle schema v4 carries the semantic block,
   the doctor flags hot-prefix recomputes, and the ``/views`` payload
   and ``obs views`` rendering are pure functions of the state.
"""

import numpy as np
import pytest

from spark_rapids_tpu import Column, Table, views
from spark_rapids_tpu import config
from spark_rapids_tpu.exec import col, plan, run_plan_stream
from spark_rapids_tpu.obs import registry, workload
from spark_rapids_tpu.obs import bundle as bundle_mod
from spark_rapids_tpu.obs.doctor import diagnose
from spark_rapids_tpu.resilience import recovery_stats, reset_faults
from spark_rapids_tpu.serve import (QuerySession, ResultCache, input_digest,
                                    semantic)
from spark_rapids_tpu.table import assert_tables_equal


@pytest.fixture
def semantic_on(monkeypatch):
    monkeypatch.setenv("SRT_SEMANTIC_CACHE", "1")
    monkeypatch.setenv("SRT_METRICS", "1")
    registry().reset()
    semantic.reset()
    views.reset()
    workload.reset()
    yield monkeypatch
    semantic.reset()
    views.reset()
    workload.reset()
    registry().reset()


@pytest.fixture
def views_on(semantic_on):
    semantic_on.setenv("SRT_VIEWS", "1")
    yield semantic_on


@pytest.fixture
def faults(monkeypatch):
    monkeypatch.setenv("SRT_RETRY_BACKOFF", "0")
    monkeypatch.delenv("SRT_FAULT", raising=False)
    reset_faults()
    yield monkeypatch
    monkeypatch.delenv("SRT_FAULT", raising=False)
    reset_faults()


def _mk(n, seed=0, khi=5, null_keys=False):
    r = np.random.default_rng(seed)
    kv = r.integers(0, khi, n).astype(np.int64)
    k = Column.from_numpy(kv, validity=r.random(n) > 0.15) \
        if null_keys else Column.from_numpy(kv)
    return Table({
        "k": k,
        "v": Column.from_numpy(r.integers(0, 100, n).astype(np.int64),
                               validity=r.random(n) > 0.2),
    })


def _agg_plan():
    return plan().filter(col("v") > 10).groupby_agg(
        ["k"], [("v", "sum", "s"), ("v", "count", "c")],
        domains={"k": (0, 4)})


def _etl_plan():
    return plan().filter(col("v") > 10).with_columns(w=col("v") * 2)


# ---------------------------------------------------------------------------
# 1. bit-identity oracle
# ---------------------------------------------------------------------------

class TestOracleIdentity:
    def test_off_is_pass_through(self, monkeypatch):
        monkeypatch.delenv("SRT_SEMANTIC_CACHE", raising=False)
        semantic.reset()
        t = _mk(256, seed=3)
        p = _agg_plan()
        assert_tables_equal(p.run(t), semantic.run_table_plan(p, t))
        assert semantic.stats()["enabled"] is False
        assert semantic.stats()["entries"] == 0

    def test_materialize_then_hit_is_bit_identical(self, semantic_on):
        t = _mk(1024, seed=4)
        # Sibling aggregations over the same pruned+filtered prefix —
        # the optimizer canonicalizes both to the same leading chain.
        pa = _agg_plan()
        pb = plan().filter(col("v") > 10).groupby_agg(
            ["k"], [("v", "min", "mn"), ("v", "max", "mx")],
            domains={"k": (0, 4)})
        want_a, want_b = pa.run(t), pb.run(t)
        # 1st: interest only; 2nd: materialize + splice; 3rd (sibling
        # plan, same prefix): splice from cache.
        assert_tables_equal(want_a, semantic.run_table_plan(pa, t))
        assert_tables_equal(want_a, semantic.run_table_plan(pa, t))
        assert_tables_equal(want_b, semantic.run_table_plan(pb, t))
        s = semantic.stats()
        assert s["materializations"] == 1
        assert s["hits"] >= 1
        assert s["entries"] == 1 and s["bytes"] > 0

    def test_float_sums_splice_bit_identical(self, semantic_on):
        """Float accumulation order is position-sensitive: a compacted
        prefix result re-orders the rows under the downstream sum and
        drifts the last ulp (regression — integer aggregations masked
        this).  The position-preserving splice must match the fused
        run exactly, through a broadcast join included."""
        r = np.random.default_rng(11)
        n = 257
        t = Table({
            "k": Column.from_numpy(r.integers(0, 7, n).astype(np.int64)),
            "v": Column.from_numpy(r.integers(0, 100, n).astype(np.int64)),
            "x": Column.from_numpy(r.uniform(0.0, 10.0, n)),
        })
        dim = Table({
            "k2": Column.from_numpy(np.arange(7, dtype=np.int64)),
            "w": Column.from_numpy(r.uniform(0.5, 2.0, 7)),
        })
        pa = (plan().filter(col("v") > 10)
              .join_broadcast(dim, left_on="k", right_on="k2")
              .groupby_agg(["k"], [("x", "sum", "sx"), ("w", "sum", "sw")],
                           domains={"k": (0, 6)}))
        pb = (plan().filter(col("v") > 10)
              .join_broadcast(dim, left_on="k", right_on="k2")
              .groupby_agg(["k"], [("x", "mean", "mx"), ("w", "max", "hw")],
                           domains={"k": (0, 6)}))
        want_a, want_b = pa.run(t), pb.run(t)
        for _ in range(3):
            assert_tables_equal(want_a, semantic.run_table_plan(pa, t))
            assert_tables_equal(want_b, semantic.run_table_plan(pb, t))
        s = semantic.stats()
        assert s["materializations"] == 1 and s["hits"] >= 3

    @pytest.mark.parametrize("n", [64, 65, 1, 129])
    def test_bucket_boundaries_with_null_keys(self, semantic_on, n):
        t = _mk(n, seed=n, null_keys=True)
        pa = _agg_plan()
        want = pa.run(t)
        for _ in range(3):      # full, materialize, hit
            assert_tables_equal(want, semantic.run_table_plan(pa, t))
        s = semantic.stats()
        # A tiny input can filter to an empty (uncacheable) prefix —
        # then every run is a full run, which is the oracle anyway.
        if s["materializations"]:
            assert s["hits"] >= 1

    def test_distinct_inputs_never_cross_contaminate(self, semantic_on):
        ta, tb = _mk(512, seed=7), _mk(512, seed=8)
        pa = _agg_plan()
        want_a, want_b = pa.run(ta), pa.run(tb)
        for _ in range(3):
            assert_tables_equal(want_a, semantic.run_table_plan(pa, ta))
            assert_tables_equal(want_b, semantic.run_table_plan(pa, tb))
        assert semantic.stats()["entries"] == 2

    def test_session_fanout_hits_and_matches(self, semantic_on):
        t = _mk(2048, seed=9)
        pa, pe = _agg_plan(), _etl_plan()
        want_a, want_e = pa.run(t).to_pydict(), pe.run(t).to_pydict()
        s = QuerySession(max_concurrent=3, register_queued=False)
        try:
            for _ in range(3):
                assert s.submit(pa, table=t).result(
                    timeout=300).to_pydict() == want_a
            assert s.submit(pe, table=t).result(
                timeout=300).to_pydict() == want_e
        finally:
            s.close()
        st = semantic.stats()
        assert st["hits"] > 0 and st["materializations"] >= 1

    def test_other_modes_unaffected(self, semantic_on):
        """stream submissions bypass the subplan cache entirely — and
        stay bit-identical with the knob on."""
        batches = [_mk(96, seed=20 + i) for i in range(3)]
        pe = _etl_plan()
        want = [x.to_pydict() for x in run_plan_stream(pe, list(batches))]
        s = QuerySession(max_concurrent=2, register_queued=False)
        try:
            got = s.submit(pe, list(batches)).result(timeout=300)
        finally:
            s.close()
        assert [x.to_pydict() for x in got] == want

    def test_fault_isolation(self, semantic_on, faults):
        """An injected dispatch OOM during the spliced run is rescued
        by the ladder without disturbing bit-identity — and the split
        rungs never re-resolve the cached source into duplicates."""
        t = _mk(2048, seed=11)
        pa = _agg_plan()
        want = pa.run(t)
        assert_tables_equal(want, semantic.run_table_plan(pa, t))
        assert_tables_equal(want, semantic.run_table_plan(pa, t))
        faults.setenv("SRT_FAULT", "oom:dispatch:1")
        reset_faults()
        before = recovery_stats().snapshot()
        assert_tables_equal(want, semantic.run_table_plan(pa, t))
        delta = recovery_stats().delta(before)
        assert delta["retries"] >= 1, delta
        assert semantic.stats()["hits"] >= 1


# ---------------------------------------------------------------------------
# 2. CSE mechanics
# ---------------------------------------------------------------------------

class TestCacheMechanics:
    def test_uncacheable_prefix_falls_back_bit_identically(
            self, semantic_on):
        semantic_on.setenv("SRT_SEMANTIC_CACHE_BYTES", "64")
        t = _mk(1024, seed=12)
        pa = _agg_plan()
        want = pa.run(t)
        for _ in range(3):
            assert_tables_equal(want, semantic.run_table_plan(pa, t))
        s = semantic.stats()
        assert s["entries"] == 0 and s["hits"] == 0

    def test_cold_eviction_feeds_advisor_damping(self, semantic_on):
        """Evicting a zero-hit entry reports the prefix to the workload
        advisor, which caps that prefix's future materialize_subplan
        severity."""
        from spark_rapids_tpu.serve.result_cache import result_nbytes
        t = _mk(64, seed=13)
        cache = semantic.SemanticCache(
            cap_bytes=int(1.5 * result_nbytes(t)))
        assert cache.put("fpA/d1", "fpA", t)
        assert cache.put("fpB/d2", "fpB", _mk(64, seed=14))
        assert cache.stats()["evictions"] >= 1
        cold = workload.cold_evicted_fps()
        assert "fpA" in cold
        snap = {"window_seconds": 60.0, "hotspots": [], "overlaps": [{
            "prefix_fingerprint": "fpA", "depth": 1,
            "kinds": ["Filter"], "count": 4, "plans": 2, "inflight": 0,
            "seconds_mean": 0.5, "measured": True,
            "est_result_bytes": 1000, "benefit_score": 2.0}]}
        recs = workload.recommend(snap, cold_evicted=cold)
        assert recs and recs[0]["severity"] <= workload.COLD_SEVERITY_CAP
        assert "damped" in recs[0]["reason"]
        undamped = workload.recommend(snap)
        assert undamped[0]["severity"] == 75

    def test_eviction_prefers_fewest_hits(self, semantic_on):
        from spark_rapids_tpu.serve.result_cache import result_nbytes
        ta, tb = _mk(64, seed=15), _mk(64, seed=16)
        cache = semantic.SemanticCache(
            cap_bytes=int(1.5 * result_nbytes(ta)))
        cache.put("hot/d", "hot", ta)
        assert cache.get("hot/d") is not None       # one hit
        cache.put("cold/d", "cold", tb)             # overflows the cap
        assert cache.peek("hot/d") is not None      # hot survived
        assert cache.peek("cold/d") is None

    def test_pinned_entries_never_evict(self, semantic_on):
        from spark_rapids_tpu.serve.result_cache import result_nbytes
        t = _mk(64, seed=17)
        cache = semantic.SemanticCache(
            cap_bytes=int(1.5 * result_nbytes(t)))
        cache.put("pinned/d", "p", t)
        cache.pin("pinned/d")
        cache.put("new/d", "n", _mk(64, seed=18))
        assert cache.peek("pinned/d") is not None
        cache.unpin("pinned/d")

    def test_knob_validation(self, monkeypatch):
        for knob, accessor, bad in [
                ("SRT_SEMANTIC_CACHE", config.semantic_cache_enabled,
                 "maybe"),
                ("SRT_SEMANTIC_CACHE_BYTES", config.semantic_cache_bytes,
                 "-5"),
                ("SRT_VIEWS", config.views_enabled, "2"),
                ("SRT_VIEWS_AUTO", config.views_auto, "yep")]:
            monkeypatch.setenv(knob, bad)
            with pytest.raises(ValueError, match=knob):
                accessor()
            monkeypatch.delenv(knob)


# ---------------------------------------------------------------------------
# 3. materialized views
# ---------------------------------------------------------------------------

class TestViews:
    def _batches(self):
        # Bucket-boundary sizes, an empty batch, and null keys.
        sizes = [64, 65, 1, 70]
        out = [_mk(n, seed=30 + i, null_keys=True)
               for i, n in enumerate(sizes)]
        empty = Table({
            "k": Column.from_numpy(np.empty(0, dtype=np.int64)),
            "v": Column.from_numpy(np.empty(0, dtype=np.int64)),
        })
        out.insert(2, empty)
        return out

    def test_incremental_equals_streaming_combine(self, views_on):
        batches = self._batches()
        pa = _agg_plan()
        want = list(run_plan_stream(pa, [b for b in batches],
                                    combine=True))
        assert len(want) == 1
        v = views.register("sales", pa)
        for b in batches:
            v.fold(b)
        assert_tables_equal(want[0], v.result())
        # ...and to a fresh view folded over the same history.
        v2 = views.register("sales2", pa)
        for b in batches:
            v2.fold(b)
        assert_tables_equal(v.result(), v2.result())
        assert v.input_digest == v2.input_digest

    def test_float_folds_match_streaming_combine_bits(self, views_on):
        """Float partials are association-sensitive: the view's folds
        must carry the same binomial tree as the one-shot streaming
        driver, mid-stream refreshes included (regression — a plain
        left fold re-associates the adds and drifts the last ulp;
        integer aggregations masked this)."""
        r = np.random.default_rng(21)
        batches = [Table({
            "k": Column.from_numpy(r.integers(0, 5, n).astype(np.int64)),
            "x": Column.from_numpy(r.uniform(0.0, 10.0, n)),
        }) for n in (64, 65, 1, 70, 33)]
        pf = plan().groupby_agg(
            ["k"], [("x", "sum", "sx"), ("x", "mean", "mx")],
            domains={"k": (0, 4)})
        v = views.register("fsales", pf)
        for i, b in enumerate(batches):
            v.fold(b)
            if i == 2:          # mid-stream refresh must not disturb
                v.refresh()     # the accumulator tree
        want = list(run_plan_stream(pf, list(batches), combine=True))[0]
        assert_tables_equal(want, v.result())

    def test_mid_stream_refresh_and_staleness(self, views_on):
        batches = self._batches()
        pa = _agg_plan()
        v = views.register("mid", pa)
        assert v.stale
        v.fold(batches[0])
        early = v.refresh()
        assert_tables_equal(
            early, list(run_plan_stream(pa, [batches[0]],
                                        combine=True))[0])
        assert not v.stale
        hits0 = v.snapshot()["hits"]
        assert_tables_equal(early, v.result())      # memoized
        assert v.snapshot()["hits"] == hits0 + 1
        v.fold(batches[1])
        assert v.stale
        assert_tables_equal(
            v.result(),
            list(run_plan_stream(pa, batches[:2], combine=True))[0])
        assert not v.stale

    def test_invalidate_rebuilds_from_empty(self, views_on):
        batches = self._batches()
        pa = _agg_plan()
        v = views.register("inv", pa)
        for b in batches:
            v.fold(b)
        v.result()
        v.invalidate()
        assert v.stale and v.snapshot()["batches"] == 0
        with pytest.raises(ValueError, match="inv"):
            v.refresh()
        v.fold(batches[0])
        assert_tables_equal(
            v.result(),
            list(run_plan_stream(pa, [batches[0]], combine=True))[0])

    def test_register_requires_knob(self, semantic_on):
        semantic_on.delenv("SRT_VIEWS", raising=False)
        with pytest.raises(ValueError, match="SRT_VIEWS"):
            views.register("nope", _agg_plan())

    def test_register_requires_groupby_tail(self, views_on):
        with pytest.raises(ValueError, match="group-by"):
            views.register("etl", _etl_plan())

    def test_registry_lifecycle(self, views_on):
        v = views.register("a", _agg_plan())
        with pytest.raises(ValueError, match="already registered"):
            views.register("a", _agg_plan())
        assert views.get("a") is v
        assert views.names() == ["a"]
        assert views.unregister("a") and not views.unregister("a")
        assert views.names() == []


# ---------------------------------------------------------------------------
# 4. policy closure
# ---------------------------------------------------------------------------

class TestPolicyClosure:
    def _prefix_fp(self, p):
        from spark_rapids_tpu.exec.optimize import (optimize,
                                                    prefix_step_texts)
        from spark_rapids_tpu.obs.history import subplan_fingerprint
        opt = optimize(p)
        chains = [t for t in prefix_step_texts(opt)
                  if len(t) < len(opt.steps)]
        return subplan_fingerprint(max(chains, key=len))

    def test_confirmed_prefix_materializes_first_sight(self, semantic_on):
        t = _mk(512, seed=40)
        pa = _agg_plan()
        fp = self._prefix_fp(pa)
        semantic._on_confirmed([fp])
        assert fp in semantic.confirmed_fps()
        want = pa.run(t)
        assert_tables_equal(want, semantic.run_table_plan(pa, t))
        assert semantic.stats()["materializations"] == 1
        assert_tables_equal(want, semantic.run_table_plan(pa, t))
        assert semantic.stats()["hits"] == 1

    def test_advise_routes_confirmations_to_sink(self, semantic_on):
        snap = {"window_seconds": 60.0, "queries": 4, "plans": 2,
                "step_seconds": 2.0, "hotspots": [], "overlaps": [{
                    "prefix_fingerprint": "feedbeef", "depth": 1,
                    "kinds": ["Filter"], "count": 4, "plans": 2,
                    "inflight": 0, "seconds_mean": 0.5, "measured": True,
                    "est_result_bytes": 1000, "benefit_score": 2.0}]}
        semantic_on.setattr(workload, "snapshot", lambda window_s=None: snap)
        payload = workload.advise(
            advisor=workload.Advisor(confirm=1, clear=1))
        assert any(r["action"] == "materialize_subplan:feedbeef"
                   for r in payload["recommendations"])
        assert "feedbeef" in semantic.confirmed_fps()

    def test_auto_view_registration(self, views_on):
        views_on.setenv("SRT_VIEWS_AUTO", "1")
        t = _mk(512, seed=41)
        pa = _agg_plan()
        want = pa.run(t)
        assert_tables_equal(want, semantic.run_table_plan(pa, t))
        fp = self._prefix_fp(pa)
        semantic._on_confirmed([fp])
        name = f"auto:{fp}"
        assert name in views.names()
        v = views.get(name)
        assert v.auto
        v.fold(t)
        assert_tables_equal(
            list(run_plan_stream(pa, [t], combine=True))[0], v.result())

    def test_auto_view_requires_both_knobs(self, views_on):
        views_on.delenv("SRT_VIEWS_AUTO", raising=False)
        t = _mk(256, seed=42)
        pa = _agg_plan()
        semantic.run_table_plan(pa, t)
        semantic._on_confirmed([self._prefix_fp(pa)])
        assert views.names() == []


# ---------------------------------------------------------------------------
# 5. result-cache mutation staleness (regression)
# ---------------------------------------------------------------------------

class TestMutationStaleness:
    def test_mark_mutated_changes_digest(self):
        t = _mk(128, seed=50)
        before = input_digest(t)
        assert before == input_digest(t)
        t.mark_mutated()
        assert input_digest(t) != before

    def test_stale_value_invalidated_on_get(self, semantic_on):
        c = ResultCache(cap_bytes=1 << 20)
        t = _mk(128, seed=51)
        c.put(("q",), t)
        got, hit = c.get(("q",))
        assert hit and got is t
        t.mark_mutated()            # in-place mutation after caching
        got, hit = c.get(("q",))
        assert not hit and got is None
        assert c.stats()["entries"] == 0
        snap = registry().snapshot()
        assert snap.get("serve.result_cache.stale_invalidations", 0) >= 1

    def test_generation_survives_jax_roundtrip(self):
        t = _mk(64, seed=52)
        t.mark_mutated()
        assert t.generation > 0


# ---------------------------------------------------------------------------
# 6. observability
# ---------------------------------------------------------------------------

class TestObservability:
    def _golden_schema(self):
        import json
        import os
        path = os.path.join(os.path.dirname(__file__), "golden",
                            "postmortem_bundle_schema.json")
        with open(path) as f:
            return json.load(f)

    def test_bundle_carries_semantic_block(self, semantic_on):
        t = _mk(256, seed=60)
        pa = _agg_plan()
        semantic.run_table_plan(pa, t)
        payload = bundle_mod.build("failure", query_id=1,
                                   fingerprint="fp", mode="run", plan=pa)
        assert bundle_mod.validate_bundle(
            payload, self._golden_schema()) == []
        sem = payload["semantic"]
        assert sem["enabled"] is True
        assert sem["prefix_fingerprints"]

    def test_hot_prefix_recompute_flag_and_doctor(self, semantic_on):
        t = _mk(256, seed=61)
        pa = _agg_plan()
        semantic.run_table_plan(pa, t)
        fps = semantic.bundle_block(pa)["prefix_fingerprints"]
        assert fps
        semantic._on_confirmed([fps[-1]])
        block = semantic.bundle_block(pa)
        assert block["hot_prefix_recompute"] is True
        payload = bundle_mod.build("failure", query_id=2,
                                   fingerprint="fp", mode="run", plan=pa)
        verdict = diagnose(payload, baseline=None)
        assert any("subplan prefix" in f["title"]
                   for f in verdict["findings"])

    def test_views_payload_shape(self, views_on):
        v = views.register("shape", _agg_plan())
        v.fold(_mk(64, seed=62))
        v.result()
        payload = views.views_payload()
        assert payload["schema_version"] == 1
        assert payload["views_enabled"] is True
        assert [x["name"] for x in payload["views"]] == ["shape"]
        assert payload["semantic_cache"]["enabled"] is True
        assert "events" in payload["outcomes"]

    def test_cli_views_render_and_json(self, views_on, capsys):
        from spark_rapids_tpu.obs.__main__ import main, render_views
        v = views.register("cli", _agg_plan())
        v.fold(_mk(64, seed=63))
        v.result()
        assert main(["views"]) == 0
        out = capsys.readouterr().out
        assert "cli" in out and "semantic cache" in out
        assert main(["views", "--json"]) == 0
        import json
        payload = json.loads(capsys.readouterr().out)
        assert payload["views"][0]["name"] == "cli"
        text = render_views(payload)
        assert "fresh" in text or "STALE" in text

    def test_prometheus_gauges_export(self, views_on):
        t = _mk(256, seed=64)
        pa = _agg_plan()
        for _ in range(3):
            semantic.run_table_plan(pa, t)
        v = views.register("gauge", pa)
        v.fold(t)
        v.result()
        from spark_rapids_tpu.obs import server
        text = server.prometheus_text()
        assert "srt_semantic_cache_hits" in text
        assert "srt_views_registered 1" in text
        assert 'srt_view_batches{view="gauge"} 1' in text
