/* Shared error-propagation machinery for the C ABI surface.
 *
 * The reference maps C++ exceptions to Java exceptions at the JNI boundary
 * with CATCH_STD (reference: src/main/cpp/src/RowConversionJni.cpp:40,65);
 * this is the C-ABI counterpart: exceptions become status codes plus a
 * thread-local message retrievable via srt_last_error() (bridge.cpp).
 */
#pragma once

#include <cstdint>
#include <stdexcept>
#include <string>

namespace spark_rapids_tpu {

inline thread_local std::string g_last_error;

constexpr int32_t SRT_OK = 0;
constexpr int32_t SRT_ERR_INVALID = 1;   // std::invalid_argument (CUDF_EXPECTS analog)
constexpr int32_t SRT_ERR_INTERNAL = 2;  // anything else

template <typename Fn>
int32_t guarded(Fn&& fn) noexcept {
  try {
    fn();
    return SRT_OK;
  } catch (const std::invalid_argument& e) {
    g_last_error = e.what();
    return SRT_ERR_INVALID;
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return SRT_ERR_INTERNAL;
  } catch (...) {
    g_last_error = "unknown native error";
    return SRT_ERR_INTERNAL;
  }
}

}  // namespace spark_rapids_tpu
