"""Import-time behavior contracts.

``import spark_rapids_tpu`` must not initialize the XLA backend: a
multi-host user has to be able to call ``jax.distributed.initialize``
(via ``parallel.init_cluster``) AFTER importing the package, and backend
init forecloses that (jax raises).  The persistent-compile-cache setup is
therefore import-time only for explicitly-configured accelerator
platforms and otherwise deferred to the engine's first compile.
"""

import subprocess
import sys


def test_import_does_not_initialize_backend():
    code = (
        "import jax\n"
        "import spark_rapids_tpu\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge.backends_are_initialized(), \\\n"
        "    'importing spark_rapids_tpu initialized the XLA backend'\n"
        "print('clean')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "clean" in out.stdout
