"""Live-telemetry HTTP exporter — Prometheus `/metrics` + `/queries`.

A stdlib :mod:`http.server` daemon thread (no third-party exporter
dependency) that publishes the observability state of this process while
queries are still running:

``/metrics``
    Prometheus text exposition (format 0.0.4) of the whole metrics
    registry (counters, gauges, timers — obs/metrics.py), per-query
    live gauges from the in-flight registry (obs/live.py) including
    per-shard batch progress, and the hand-rolled SLO latency
    histograms (``srt_query_seconds{mode}``,
    ``srt_query_phase_seconds{phase}``,
    ``srt_serve_queue_wait_seconds`` — fed once per completed query).
``/queries``
    JSON snapshots of in-flight and recently finished queries keyed by
    ``query_id`` + plan fingerprint (``obs.live.snapshot_all()``).
``/capacity``
    One capacity-advisor evaluation (obs/capacity.py) over the rolling
    ``SRT_CAPACITY_WINDOW_S`` window: the saturation snapshot, this
    window's raw candidates, and the hysteresis-stable recommendation
    set.  The same observables export as ``srt_capacity_*`` gauges on
    ``/metrics`` (snapshot only — scraping ``/metrics`` must not
    advance the advisor's hysteresis).
``/views``
    JSON snapshot of the semantic-cache + materialized-view state
    (views.registry.views_payload): registered views with staleness
    and hit counts, semantic subplan-cache stats, and the workload
    advisor's semantic outcome feed.  The same state exports as
    ``srt_semantic_*`` / ``srt_view_*`` gauges on ``/metrics``.
``/queries/<id>/timeline``
    Chrome-trace JSON of a *still-running* query: recorded events whose
    span args carry that ``query_id``, plus a non-destructive render of
    still-open spans marked ``incomplete`` (obs/timeline.py) — load it
    in Perfetto mid-run.

Enable with ``SRT_LIVE_SERVER=1`` (port via ``SRT_LIVE_PORT``, default
9465, ``0`` = ephemeral); the first metered query start spins the server
up (obs/live.py), or call :func:`start` directly.  Binds 127.0.0.1 —
front it with a real proxy before exposing it beyond the host.  jax-free
at import like the rest of ``obs``.
"""

from __future__ import annotations

import bisect
import json
import math
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from ..config import live_server_port, metrics_enabled

_NAME_SUB = re.compile(r"[^a-zA-Z0-9_:]")
_TIMELINE_RE = re.compile(r"^/queries/(\d+)/timeline$")


def metric_name(name: str) -> str:
    """Registry name → Prometheus metric name (``srt_`` prefixed;
    anything outside ``[a-zA-Z0-9_:]`` becomes ``_``)."""
    return "srt_" + _NAME_SUB.sub("_", name)


def escape_label_value(value: object) -> str:
    """Label-value escaping per the exposition format: backslash, double
    quote, and newline must be escaped; everything else passes through."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def format_value(value: object) -> str:
    """Sample-value rendering: ``NaN`` / ``+Inf`` / ``-Inf`` spelled the
    way Prometheus parsers expect, ints without a decimal point."""
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, float):
        if math.isnan(value):
            return "NaN"
        if math.isinf(value):
            return "+Inf" if value > 0 else "-Inf"
        return repr(value)
    return str(value)


def _render_labels(labels: Dict[str, object]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


#: family name -> (type, [(labels, value), ...]); insertion-ordered so
#: every sample of a family stays under its one ``# TYPE`` line, as the
#: exposition format requires.
_Families = Dict[str, Tuple[str, List[Tuple[Dict[str, object], object]]]]


def _add(fam: _Families, name: str, kind: str,
         labels: Dict[str, object], value: object) -> None:
    entry = fam.get(name)
    if entry is None:
        entry = fam[name] = (kind, [])
    entry[1].append((labels, value))


# -- SLO latency histograms (hand-rolled; no prometheus_client dep) ----

#: Default bucket upper bounds (seconds) — the Prometheus client's
#: latency defaults extended to one minute, since a cold XLA compile on
#: TPU legitimately lands in the tens of seconds (BASELINE.md).
LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0, 60.0)


class _Histogram:
    """One (family, label-set) histogram: per-bucket counts, sum, count.

    ``counts[i]`` is the NON-cumulative count of observations in bucket
    ``i`` (the last slot is the +Inf overflow); exposition renders the
    cumulative ``_bucket{le=...}`` series the format requires."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...] = LATENCY_BUCKETS):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, value)] += 1
        self.sum += value
        self.count += 1


_HIST_LOCK = threading.Lock()
#: (family name without srt_ prefix, sorted label items) -> _Histogram.
#: Insertion-ordered, so a family's label sets render in first-observed
#: order under one ``# TYPE`` line.
_HISTOGRAMS: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], _Histogram] = {}


def observe_hist(name: str, value: float,
                 labels: Optional[Dict[str, object]] = None) -> None:
    """Record one observation into histogram ``name`` (``srt_``-prefixed
    at exposition).  Self-gated on ``SRT_METRICS=1`` — callers pay one
    env read when metrics are off.  Called once per query (not per
    batch), so a plain lock is fine here where the flight ring is not."""
    if not metrics_enabled():
        return
    key = (name, tuple(sorted((k, str(v))
                              for k, v in (labels or {}).items())))
    with _HIST_LOCK:
        hist = _HISTOGRAMS.get(key)
        if hist is None:
            hist = _HISTOGRAMS[key] = _Histogram()
        hist.observe(float(value))


def observe_query(qm) -> None:
    """Fold one completed query into the SLO surface:
    ``srt_query_seconds{mode}`` plus the per-phase split
    ``srt_query_phase_seconds{phase}``.  Hooked from
    ``obs.query.set_last_query_metrics`` / ``set_last_stream_metrics``
    so every metered completion lands here regardless of entry point."""
    if not metrics_enabled() or qm is None:
        return
    observe_hist("query_seconds", qm.total_seconds, {"mode": qm.mode})
    for phase, seconds in (("bind", qm.bind_seconds),
                           ("compile", qm.compile_seconds),
                           ("execute", qm.execute_seconds),
                           ("materialize", qm.materialize_seconds)):
        observe_hist("query_phase_seconds", seconds, {"phase": phase})


def _bucket_le(bound: float) -> str:
    """``le`` label text: ints without a trailing ``.0``, as the
    Prometheus client renders them."""
    return str(int(bound)) if float(bound).is_integer() else repr(bound)


def histogram_text() -> List[str]:
    """Exposition lines for every histogram family: cumulative
    ``_bucket{le=...}`` series ending at ``+Inf`` (== ``_count``), then
    ``_sum`` and ``_count`` — snapshotted under the lock so a scrape
    mid-recording still reads a consistent (sum, count, buckets) triple."""
    with _HIST_LOCK:
        snap = [(name, dict(labels), hist.buckets, list(hist.counts),
                 hist.sum, hist.count)
                for (name, labels), hist in _HISTOGRAMS.items()]
    lines: List[str] = []
    seen_type = set()
    for name, labels, buckets, counts, total, count in snap:
        base = metric_name(name)
        if base not in seen_type:
            seen_type.add(base)
            lines.append(f"# TYPE {base} histogram")
        cum = 0
        for bound, n in zip(buckets, counts):
            cum += n
            lines.append(f"{base}_bucket"
                         f"{_render_labels({**labels, 'le': _bucket_le(bound)})}"
                         f" {cum}")
        lines.append(f"{base}_bucket"
                     f"{_render_labels({**labels, 'le': '+Inf'})} {count}")
        lines.append(f"{base}_sum{_render_labels(labels)} "
                     f"{format_value(total)}")
        lines.append(f"{base}_count{_render_labels(labels)} {count}")
    return lines


def reset_histograms() -> None:
    """Drop all histogram state (test isolation)."""
    with _HIST_LOCK:
        _HISTOGRAMS.clear()


def capacity_gauges(fam: _Families) -> None:
    """Fold the capacity snapshot into ``/metrics`` as ``srt_capacity_*``
    gauges.  Uses :func:`obs.capacity.snapshot` + :func:`recommend`
    directly — NOT :func:`advise` — so scrapes never advance the
    advisor's hysteresis state (only ``/capacity`` and the CLI do)."""
    from . import capacity
    from ..config import capacity_targets
    try:
        snap = capacity.snapshot()
        candidates = capacity.recommend(snap, capacity_targets())
    except Exception:       # a broken accountant must not break /metrics
        return
    busy, queue, ll = snap["busy"], snap["queue"], snap["littles_law"]
    adm, hbm = snap["admission"], snap["hbm"]
    for name, value in (
            ("window_seconds", snap["window_seconds"]),
            ("busy_fraction", busy["dispatch_fraction"]),
            ("materialize_fraction", busy["materialize_fraction"]),
            ("queue_waits", queue["waits"]),
            ("queue_wait_p95_seconds", queue["wait_p95_s"]),
            ("queue_depth", queue["depth"]),
            ("admission_hbm_waits", adm["hbm_waits"]),
            ("admission_rejected_bytes", adm["rejected_bytes"]),
            ("hbm_claimed_p95_bytes", hbm["claimed_p95_bytes"]),
            ("arrival_rate_qps", ll["arrival_rate_qps"]),
            ("effective_concurrency", ll["effective_concurrency"]),
            ("utilization_of_cap", ll["utilization_of_cap"])):
        _add(fam, f"srt_capacity_{name}", "gauge", {}, value)
    if hbm["headroom_fraction"] is not None:
        _add(fam, "srt_capacity_hbm_headroom_fraction", "gauge", {},
             hbm["headroom_fraction"])
    for cand in candidates:
        _add(fam, "srt_capacity_advice", "gauge",
             {"action": cand["action"]}, cand["severity"])


def workload_gauges(fam: _Families) -> None:
    """Fold the workload snapshot into ``/metrics`` as ``srt_workload_*``
    gauges.  Same scrape discipline as :func:`capacity_gauges`:
    snapshot() + recommend() only — NOT advise() — so scrapes never
    advance the workload advisor's hysteresis (only ``/workload`` and
    the CLI do)."""
    from . import workload
    try:
        snap = workload.snapshot()
        candidates = workload.recommend(snap)
    except Exception:           # a broken miner must not break /metrics
        return
    for name, value in (
            ("window_seconds", snap["window_seconds"]),
            ("queries", snap["queries"]),
            ("plans", snap["plans"]),
            ("step_seconds", snap["step_seconds"]),
            ("step_kinds", snap["step_kinds"]),
            ("tickets", snap["tickets"])):
        _add(fam, f"srt_workload_{name}", "gauge", {}, value)
    for h in snap["hotspots"]:
        labels = {"kind": h["kind"]}
        _add(fam, "srt_workload_hotspot_seconds", "gauge", labels,
             h["seconds"])
        _add(fam, "srt_workload_hotspot_share", "gauge", labels,
             h["share"])
        _add(fam, "srt_workload_hotspot_projected_win_seconds", "gauge",
             labels, h["projected_win_s"])
    for o in snap["overlaps"]:
        labels = {"prefix": o["prefix_fingerprint"]}
        _add(fam, "srt_workload_overlap_count", "gauge", labels,
             o["count"])
        _add(fam, "srt_workload_overlap_benefit_score", "gauge", labels,
             o["benefit_score"])
    for cand in candidates:
        _add(fam, "srt_workload_advice", "gauge",
             {"action": cand["action"]}, cand["severity"])


def semantic_gauges(fam: _Families) -> None:
    """Fold the semantic-cache and view state into ``/metrics`` as
    ``srt_semantic_*`` / ``srt_view_*`` gauges.  Reads only modules the
    process already loaded (``sys.modules``) — a scrape never imports
    the serving layer, and a process that never served stays silent."""
    import sys as _sys
    semantic = _sys.modules.get("spark_rapids_tpu.serve.semantic")
    if semantic is not None:
        try:
            s = semantic.stats()
            for name in ("entries", "bytes", "hits", "misses",
                         "materializations", "evictions"):
                _add(fam, f"srt_semantic_cache_{name}", "gauge", {},
                     s[name])
            _add(fam, "srt_semantic_cache_hit_rate", "gauge", {},
                 s["hit_rate"])
        except Exception:   # a broken cache must not break /metrics
            pass
    registry = _sys.modules.get("spark_rapids_tpu.views.registry")
    if registry is not None:
        try:
            views = registry.snapshot()
            _add(fam, "srt_views_registered", "gauge", {}, len(views))
            for v in views:
                labels = {"view": v["name"]}
                _add(fam, "srt_view_batches", "gauge", labels,
                     v["batches"])
                _add(fam, "srt_view_stale", "gauge", labels, v["stale"])
                _add(fam, "srt_view_hits", "gauge", labels, v["hits"])
                _add(fam, "srt_view_refreshes", "gauge", labels,
                     v["refreshes"])
        except Exception:   # a broken registry must not break /metrics
            pass


def prometheus_text() -> str:
    """The ``/metrics`` body: registry metrics + live-query gauges."""
    from . import live
    from .metrics import registry

    fam: _Families = {}
    for name, (kind, value) in sorted(registry().typed_snapshot().items()):
        base = metric_name(name)
        if kind == "counter":
            _add(fam, base + "_total", "counter", {}, value)
        elif kind == "timer":
            total_seconds, count = value
            _add(fam, base + "_seconds_total", "counter", {}, total_seconds)
            _add(fam, base + "_calls_total", "counter", {}, count)
        else:
            _add(fam, base, "gauge", {}, value)

    snap = live.snapshot_all()
    _add(fam, "srt_live_queries", "gauge", {}, len(snap["in_flight"]))
    _add(fam, "srt_serve_queued_queries", "gauge", {},
         len(snap.get("queued", [])))
    for q in snap["in_flight"]:
        labels = {"query_id": q["query_id"], "mode": q["mode"],
                  "fingerprint": q["fingerprint"]}
        for suffix, key in (
                ("elapsed_seconds", "elapsed_seconds"),
                ("batches_done", "batches_done"),
                ("batches_in", "batches_in"),
                ("inflight", "inflight"),
                ("rows_in", "rows_in"),
                ("rows_out", "rows_out"),
                ("live_rows", "live_rows"),
                ("rows_per_sec", "rows_per_sec"),
                ("ici_bytes", "ici_bytes"),
                ("donation_hits", "donation_hits"),
                ("recovery_rungs", None),
                ("hbm_peak_bytes", "hbm_peak_bytes")):
            value = (q["recovery"]["count"] if key is None else q[key])
            _add(fam, f"srt_live_query_{suffix}", "gauge", labels, value)
        for shard, done in q["shard_batches"].items():
            _add(fam, "srt_live_query_shard_batches", "gauge",
                 {"query_id": q["query_id"], "shard": shard}, done)
    capacity_gauges(fam)
    workload_gauges(fam)
    semantic_gauges(fam)

    lines: List[str] = []
    for name, (kind, samples) in fam.items():
        lines.append(f"# TYPE {name} {kind}")
        for labels, value in samples:
            lines.append(f"{name}{_render_labels(labels)} "
                         f"{format_value(value)}")
    lines.extend(histogram_text())
    return "\n".join(lines) + "\n"


def query_timeline(query_id: int) -> Optional[dict]:
    """Chrome-trace payload for one (possibly still-running) query.

    Recorded events filtered to span args carrying ``query_id`` (lane
    metadata kept so tids render as names), plus a *non-destructive*
    snapshot of still-open spans marked ``incomplete``.  None when the
    query left no events and the live registry has never seen it.
    """
    from . import live, timeline
    evs = timeline.events() + timeline.open_span_events()
    picked = [e for e in evs
              if e.get("ph") == "M"
              or e.get("args", {}).get("query_id") == query_id]
    if (all(e.get("ph") == "M" for e in picked)
            and live.get(query_id) is None):
        return None
    return {"displayTimeUnit": "ms", "traceEvents": picked}


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):        # no access-log noise
        pass

    def _send(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        from . import live
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._send(200, prometheus_text().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
                return
            if path == "/queries":
                body = json.dumps(live.snapshot_all(), sort_keys=True)
                self._send(200, body.encode(), "application/json")
                return
            if path == "/capacity":
                from . import capacity
                body = json.dumps(capacity.advise(), sort_keys=True)
                self._send(200, body.encode(), "application/json")
                return
            if path == "/workload":
                from . import workload
                body = json.dumps(workload.advise(), sort_keys=True)
                self._send(200, body.encode(), "application/json")
                return
            if path == "/views":
                from ..views import views_payload
                body = json.dumps(views_payload(), sort_keys=True)
                self._send(200, body.encode(), "application/json")
                return
            m = _TIMELINE_RE.match(path)
            if m:
                payload = query_timeline(int(m.group(1)))
                if payload is None:
                    self._send(404, b'{"error": "unknown query_id"}',
                               "application/json")
                    return
                self._send(200, json.dumps(payload, sort_keys=True).encode(),
                           "application/json")
                return
            self._send(404, b'{"error": "not found"}', "application/json")
        except BrokenPipeError:
            pass


class LiveTelemetryServer:
    """The exporter: a ThreadingHTTPServer on a daemon thread."""

    def __init__(self, port: Optional[int] = None, host: str = "127.0.0.1"):
        if port is None:
            port = live_server_port()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="srt-live-server",
            daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


_SERVER: Optional[LiveTelemetryServer] = None
_SERVER_LOCK = threading.Lock()


def start(port: Optional[int] = None) -> LiveTelemetryServer:
    """Start (or return) the process-global exporter."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is None:
            _SERVER = LiveTelemetryServer(port=port)
        return _SERVER


def maybe_start() -> Optional[LiveTelemetryServer]:
    """Start the exporter iff ``SRT_LIVE_SERVER=1`` — the hook query
    starts call (one flag read; idempotent once running)."""
    from ..config import live_server_enabled
    if not live_server_enabled():
        return None
    return start()


def get() -> Optional[LiveTelemetryServer]:
    """The running exporter, or None."""
    return _SERVER


def stop() -> None:
    """Stop the process-global exporter (test isolation)."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            _SERVER.stop()
            _SERVER = None
