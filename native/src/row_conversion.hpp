/* Host-side columnar <-> row-major conversion (native half).
 *
 * The C++ counterpart of spark_rapids_tpu/rows/convert.py for non-Python /
 * non-device hosts (Spark executors handing UnsafeRow-style buffers across
 * the FFI boundary).  Functional equivalent of the reference's
 * `spark_rapids_jni::convert_to_rows` / `convert_from_rows`
 * (row_conversion.cu:458-517, :519-575) with the device kernels replaced by
 * cache-blocked multi-threaded host loops; the TPU device path is the
 * JAX/Pallas implementation, this path is the byte-exact host interop /
 * fallback.
 *
 * Byte contract (shared with the JAX path; asserted by tests/test_ffi.py):
 * alignment gaps, row padding, and unused validity bits are deterministic
 * zeros; null entries' payload bytes are copied verbatim from the column
 * buffer (the engine never invents values).
 */
#pragma once

#include <cstdint>

#include "row_layout.hpp"

namespace spark_rapids_tpu {

/* Columnar -> rows.  col_data[i] points to num_rows * column_sizes[i] bytes of
 * contiguous column data; col_valid[i] is num_rows bytes of 0/1 validity, or
 * nullptr meaning all-valid (col_valid itself may be nullptr: every column
 * all-valid).  out must hold num_rows * layout.row_size bytes. */
void pack_rows(const RowLayout& layout, int64_t num_rows,
               const void* const* col_data, const uint8_t* const* col_valid,
               uint8_t* out);

/* Rows -> columnar.  rows holds num_rows * layout.row_size bytes; writes each
 * column's data into col_data[i] (num_rows * column_sizes[i] bytes) and its
 * validity into col_valid[i] (num_rows bytes of 0/1), skipping nullptr
 * destinations (either outer array may also be nullptr entirely). */
void unpack_rows(const RowLayout& layout, int64_t num_rows, const uint8_t* rows,
                 void* const* col_data, uint8_t* const* col_valid);

}  // namespace spark_rapids_tpu
