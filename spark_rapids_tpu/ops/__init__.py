"""Eager columnar ops layer (the cuDF capability-envelope equivalent).

Each op executes immediately; pure compute runs as jit-cached XLA programs
(see :mod:`.common` for the execution model).  TPU-first algorithm choices:
sort-based groupby and join (no hash tables), lax.sort multi-key sorting,
searchsorted merge probes, prefix-sum expansions.
"""

from . import datetime, reductions, regex, strings, window
from .binary import binary_op, fill_null, if_else, is_null, is_valid, unary_op
from .cast import cast
from .common import concat_columns, concat_tables

#: SQL UNION ALL over same-schema tables (an alias: the engine's
#: row-concatenation is exactly the union-all physical op).
union_all = concat_tables
from .filter import apply_boolean_mask, distinct, drop_nulls
from .groupby import groupby, groupby_agg
from .join import join
from .search import is_in, lower_bound, upper_bound
from .sort import sort_by, sorted_order

__all__ = [
    "apply_boolean_mask",
    "binary_op",
    "cast",
    "concat_columns",
    "concat_tables",
    "datetime",
    "distinct",
    "drop_nulls",
    "fill_null",
    "groupby",
    "groupby_agg",
    "if_else",
    "is_in",
    "is_null",
    "is_valid",
    "join",
    "lower_bound",
    "reductions",
    "regex",
    "sort_by",
    "sorted_order",
    "strings",
    "unary_op",
    "union_all",
    "upper_bound",
    "window",
]
