"""Columnar ↔ row-major conversion (the reference's flagship feature).

TPU-native equivalent of ``spark_rapids_jni::convert_to_rows`` /
``convert_from_rows`` (reference: row_conversion.cu:458-517, :519-575 and the
Java API RowConversion.java:101-121).  Where the reference stages row images
through CUDA shared memory with warp-cooperative validity ballots, this
implementation expresses the transpose as whole-batch vector ops — bitcasts,
concatenation along the byte axis, shift/mask validity packing — and lets XLA
tile it through VMEM.  One jitted XLA program per (schema, batch-shape),
cached, mirroring the reference's compile-once kernels.

Semantics preserved from the reference:

  * output split into multiple row blobs so no blob exceeds 2**31 bytes, with
    batch row counts in multiples of 32 (row_conversion.cu:476-479, :505-511),
  * 1 KB row-width limit (RowConversion.java:98-99) — liftable here since TPU
    has no shared-memory constraint (``check_row_width=False``),
  * ``from_rows`` validates blob size against the schema layout
    (row_conversion.cu:541: "The layout of the data appears to be off"),
  * null rows' payload bytes are copied verbatim (the engine never invents
    values), and — unlike the reference, which leaves pad/garbage bits —
    padding bytes and unused validity bits are deterministically zero.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..column import Column
from ..dtypes import DType, TypeId
from ..table import Table
from .bytes import from_bytes, pack_validity_bytes, to_bytes, unpack_validity_bytes
from .layout import (BATCH_ROW_MULTIPLE, MAX_BATCH_BYTES, MAX_ROW_WIDTH,
                     RowLayout, compute_fixed_width_layout)


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class RowBlob:
    """A batch of rows serialized to the fixed-width row format.

    Equivalent of the reference's ``LIST<INT8>`` output column
    (row_conversion.cu:405-406): ``data`` is the flat byte buffer, ``offsets``
    the int32 ``(n+1,)`` row offsets (a sequence with stride ``row_size``).
    """

    data: jax.Array        # uint8 (num_rows * row_size,)
    offsets: jax.Array     # int32 (num_rows + 1,)
    row_size: int          # static

    def tree_flatten(self):
        return (self.data, self.offsets), self.row_size

    @classmethod
    def tree_unflatten(cls, row_size, children):
        data, offsets = children
        return cls(data=data, offsets=offsets, row_size=row_size)

    @property
    def num_rows(self) -> int:
        return int(self.offsets.shape[0]) - 1

    def rows_2d(self) -> jax.Array:
        return self.data.reshape(-1, self.row_size)


# -- jitted kernels, cached per schema ---------------------------------------

@functools.lru_cache(maxsize=None)
def _packer(schema: tuple[DType, ...]):
    layout = compute_fixed_width_layout(schema)

    @jax.jit
    def pack(datas: tuple[jax.Array, ...], masks: tuple[jax.Array, ...]) -> jax.Array:
        n = datas[0].shape[0]
        pieces = []
        cursor = 0
        for dtype, start, size, data in zip(schema, layout.column_starts,
                                            layout.column_sizes, datas):
            if start > cursor:   # alignment gap -> deterministic zero padding
                pieces.append(jnp.zeros((n, start - cursor), jnp.uint8))
            pieces.append(to_bytes(data, dtype))
            cursor = start + size
        valid = jnp.stack(masks, axis=1)           # (n, num_columns) bool
        pieces.append(pack_validity_bytes(valid, layout.validity_bytes))
        cursor += layout.validity_bytes
        if layout.row_size > cursor:
            pieces.append(jnp.zeros((n, layout.row_size - cursor), jnp.uint8))
        return jnp.concatenate(pieces, axis=1).reshape(-1)

    return layout, pack


@functools.lru_cache(maxsize=None)
def _unpacker(schema: tuple[DType, ...]):
    layout = compute_fixed_width_layout(schema)

    @jax.jit
    def unpack(flat: jax.Array):
        image = flat.reshape(-1, layout.row_size)
        datas = []
        for dtype, start, size in zip(schema, layout.column_starts, layout.column_sizes):
            datas.append(from_bytes(image[:, start:start + size], dtype))
        raw_validity = image[:, layout.validity_offset:
                             layout.validity_offset + layout.validity_bytes]
        valid = unpack_validity_bytes(raw_validity, layout.num_columns)
        return tuple(datas), valid

    return layout, unpack


# -- public API ---------------------------------------------------------------

def to_rows(table: Table, *, max_batch_bytes: int = MAX_BATCH_BYTES,
            check_row_width: bool = True) -> list[RowBlob]:
    """Convert a fixed-width table to row blobs.

    Returns one :class:`RowBlob` per batch; multiple blobs only when the total
    byte size would exceed ``max_batch_bytes`` (reference contract:
    RowConversion.java:32-48).
    """
    schema = tuple(table.schema())
    layout, pack = _packer(schema)
    if check_row_width and layout.row_size > MAX_ROW_WIDTH:
        raise ValueError(
            f"Row size {layout.row_size} exceeds the {MAX_ROW_WIDTH}-byte row "
            f"format limit (pass check_row_width=False to lift)")

    num_rows = table.num_rows
    max_rows = layout.max_rows_per_batch(max_batch_bytes)
    if max_rows <= 0:
        raise ValueError("row size too large for the batch byte limit")

    def batch_blob(start: int, count: int) -> RowBlob:
        datas = tuple(c.data[start:start + count] for c in table.columns)
        masks = tuple(
            jnp.ones(count, jnp.bool_) if c.validity is None
            else c.validity[start:start + count]
            for c in table.columns)
        flat = pack(datas, masks)
        offsets = jnp.arange(count + 1, dtype=jnp.int32) * layout.row_size
        return RowBlob(data=flat, offsets=offsets, row_size=layout.row_size)

    if num_rows == 0:   # one empty blob so the round trip stays total
        return [batch_blob(0, 0)]
    return [batch_blob(start, min(max_rows, num_rows - start))
            for start in range(0, num_rows, max_rows)]


def from_rows(blobs: Sequence[RowBlob] | RowBlob, schema: Sequence[DType],
              names: Optional[Sequence[str]] = None) -> Table:
    """Convert row blobs back to a columnar table.

    ``schema`` describes the columns to extract (the caller records it at
    ``to_rows`` time, as in RowConversionTest.java:46-49).  Multiple blobs are
    concatenated in order (the reference's batched-output inverse).
    """
    if isinstance(blobs, RowBlob):
        blobs = [blobs]
    schema = tuple(schema)
    if names is None:
        names = [f"c{i}" for i in range(len(schema))]
    elif len(names) != len(schema):
        raise ValueError(f"{len(names)} names for {len(schema)} schema columns")
    layout, unpack = _unpacker(schema)
    if not blobs:
        blobs = [RowBlob(data=jnp.zeros(0, jnp.uint8),
                         offsets=jnp.zeros(1, jnp.int32),
                         row_size=layout.row_size)]

    all_datas: list[tuple] = []
    all_valid: list[jax.Array] = []
    for blob in blobs:
        if blob.data.dtype not in (jnp.uint8, jnp.int8):
            raise ValueError("Only a list of bytes is supported as input")
        num_rows = blob.num_rows
        if layout.row_size * num_rows != blob.data.size:
            raise ValueError("The layout of the data appears to be off")
        datas, valid = unpack(blob.data)
        all_datas.append(datas)
        all_valid.append(valid)

    if len(all_datas) > 1:
        datas = tuple(jnp.concatenate([d[i] for d in all_datas])
                      for i in range(len(schema)))
        valid = jnp.concatenate(all_valid, axis=0)
    else:
        datas, valid = all_datas[0], all_valid[0]

    columns = []
    for i, (name, dtype) in enumerate(zip(names, schema)):
        columns.append((name, Column(data=datas[i], validity=valid[:, i], dtype=dtype)))
    return Table(columns)
