"""Regex engine: host-compiled DFA, device-vectorized execution.

The reference envelope includes cuDF's strings/regex engine (BASELINE.json
names the TPC-DS q28/q88 string/regex suite).  A GPU engine walks an NFA per
thread with data-dependent branching — the exact shape TPU hates.  TPU-first
redesign:

  * the pattern is compiled **on host** (parse → Thompson NFA → subset-
    construction DFA over *symbol equivalence classes*, so the transition
    table is (num_states, num_classes) int32, typically tiny),
  * execution is one ``lax.scan`` over character positions of the padded
    (rows, max_len) byte matrix: every row's DFA state advances in lockstep
    via a vectorized table gather.  No per-row branching; the table lives in
    VMEM.

Anchors are first-class: the symbol alphabet is 258 wide — 256 bytes plus
virtual BOS/EOS markers processed before/after the byte stream.  ``^``/``$``
compile to classes over {BOS}/{EOS}; every DFA state implicitly *retains*
itself across BOS/EOS (assertion, not consumption), so anchors work anywhere
in the pattern, including per-alternation-branch (``^q|z$``).

Supported syntax: literals, ``.``, ``[...]`` classes (ranges, negation),
escapes ``\\d \\D \\w \\W \\s \\S \\n \\t \\r`` and escaped metachars,
``* + ? {m} {m,} {m,n}``, alternation ``|``, groups ``(...)`` (non-capturing
semantics), anchors ``^``/``$``.  UTF-8 operates at the byte level
(multi-byte literals match as byte sequences).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

NUM_BYTES = 256
BOS = 256          # virtual begin-of-string symbol
EOS = 257          # virtual end-of-string symbol
NUM_SYMBOLS = 258


def _byte_bits() -> np.ndarray:
    return np.zeros(NUM_SYMBOLS, np.bool_)


def _invert_bytes(bits: np.ndarray) -> np.ndarray:
    """Negate a class over the byte range only (anchors never match classes)."""
    out = bits.copy()
    out[:NUM_BYTES] = ~bits[:NUM_BYTES]
    out[NUM_BYTES:] = False
    return out


# -- parsing into an AST ------------------------------------------------------

class _Parser:
    """Recursive-descent parser for the supported regex subset."""

    def __init__(self, pattern: str):
        self.src = pattern
        self.pos = 0

    def error(self, msg: str):
        raise ValueError(f"regex parse error at {self.pos} in {self.src!r}: {msg}")

    def peek(self):
        return self.src[self.pos] if self.pos < len(self.src) else None

    def take(self):
        ch = self.peek()
        self.pos += 1
        return ch

    def parse(self):
        node = self.alt()
        if self.pos != len(self.src):
            self.error(f"unexpected {self.peek()!r}")
        return node

    def alt(self):
        branches = [self.concat()]
        while self.peek() == "|":
            self.take()
            branches.append(self.concat())
        return ("alt", branches) if len(branches) > 1 else branches[0]

    def concat(self):
        parts = []
        while self.peek() not in (None, "|", ")"):
            parts.append(self.repeat())
        if not parts:
            return ("empty",)
        return ("cat", parts) if len(parts) > 1 else parts[0]

    def repeat(self):
        node = self.atom()
        while True:
            ch = self.peek()
            if ch == "*":
                self.take()
                node = ("star", node)
            elif ch == "+":
                self.take()
                node = ("cat", [node, ("star", node)])
            elif ch == "?":
                self.take()
                node = ("alt", [node, ("empty",)])
            elif ch == "{":
                node = self.bounded(node)
            else:
                return node

    def bounded(self, node):
        self.take()  # '{'
        digits = ""
        while self.peek() and self.peek().isdigit():
            digits += self.take()
        if not digits:
            self.error("expected digit in {m,n}")
        lo = int(digits)
        hi = lo
        if self.peek() == ",":
            self.take()
            digits = ""
            while self.peek() and self.peek().isdigit():
                digits += self.take()
            hi = int(digits) if digits else None
        if self.take() != "}":
            self.error("expected }")
        parts = [node] * lo
        if hi is None:
            parts.append(("star", node))
        else:
            if hi < lo:
                self.error("{m,n} with n < m")
            for _ in range(hi - lo):
                parts.append(("alt", [node, ("empty",)]))
        if not parts:
            return ("empty",)
        return ("cat", parts) if len(parts) > 1 else parts[0]

    def atom(self):
        ch = self.take()
        if ch == "(":
            node = self.alt()
            if self.take() != ")":
                self.error("expected )")
            return node
        if ch == "[":
            return ("class", self.char_class())
        if ch == ".":
            bits = _byte_bits()
            bits[:NUM_BYTES] = True
            bits[ord("\n")] = False
            return ("class", bits)
        if ch == "^":
            bits = _byte_bits()
            bits[BOS] = True
            return ("class", bits)
        if ch == "$":
            bits = _byte_bits()
            bits[EOS] = True
            return ("class", bits)
        if ch == "\\":
            return ("class", self.escape(self.take()))
        if ch in "*+?{":
            self.error(f"dangling quantifier {ch!r}")
        encoded = ch.encode("utf-8")
        if len(encoded) > 1:
            # multi-byte literal: a byte *sequence*, not a class
            parts = []
            for b in encoded:
                one = _byte_bits()
                one[b] = True
                parts.append(("class", one))
            return ("cat", parts)
        bits = _byte_bits()
        bits[encoded[0]] = True
        return ("class", bits)

    def escape(self, ch):
        if ch is None:
            self.error("dangling backslash")
        bits = _byte_bits()
        if ch in ("d", "D"):
            bits[ord("0"):ord("9") + 1] = True
            return _invert_bytes(bits) if ch == "D" else bits
        if ch in ("w", "W"):
            bits[ord("a"):ord("z") + 1] = True
            bits[ord("A"):ord("Z") + 1] = True
            bits[ord("0"):ord("9") + 1] = True
            bits[ord("_")] = True
            return _invert_bytes(bits) if ch == "W" else bits
        if ch in ("s", "S"):
            for c in " \t\n\r\f\v":
                bits[ord(c)] = True
            return _invert_bytes(bits) if ch == "S" else bits
        if ch == "x":
            hexits = (self.take() or "") + (self.take() or "")
            try:
                bits[int(hexits, 16)] = True
            except ValueError:
                self.error(f"bad \\x escape {hexits!r}")
            return bits
        if ch in {"n": 1, "t": 1, "r": 1, "f": 1, "v": 1, "0": 1}:
            mapped = {"n": "\n", "t": "\t", "r": "\r", "f": "\f",
                      "v": "\v", "0": "\0"}[ch]
            bits[ord(mapped)] = True
            return bits
        if ch.isalnum():
            # \b, \B, \A, \Z, backreferences, ... : unsupported — raising is
            # better than silently matching the literal letter.
            self.error(f"unsupported escape \\{ch}")
        for b in ch.encode("utf-8"):   # escaped metachar / punctuation
            bits[b] = True
        return bits

    def _class_atom(self):
        """One class element: an int byte value (usable as a range bound) or
        a bitset (multi-byte literal or \\d-style escape)."""
        ch = self.take()
        if ch == "\\":
            nxt = self.take()
            if nxt == "x":
                hexits = (self.take() or "") + (self.take() or "")
                try:
                    return int(hexits, 16)
                except ValueError:
                    self.error(f"bad \\x escape {hexits!r}")
            single = {"n": "\n", "t": "\t", "r": "\r", "f": "\f",
                      "v": "\v", "0": "\0"}.get(nxt)
            if single is not None:
                return ord(single)
            self.pos -= 1            # rewind so escape() re-reads nxt
            return self.escape(self.take())
        encoded = ch.encode("utf-8")
        if len(encoded) > 1:
            bits = _byte_bits()
            for b in encoded:
                bits[b] = True
            return bits
        return encoded[0]

    def char_class(self):
        bits = _byte_bits()
        negate = False
        if self.peek() == "^":
            self.take()
            negate = True
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                self.error("unterminated [")
            if ch == "]" and not first:
                self.take()
                break
            first = False
            atom = self._class_atom()
            if isinstance(atom, np.ndarray):
                bits |= atom
                continue
            lo = atom
            if self.peek() == "-" and self.pos + 1 < len(self.src) \
                    and self.src[self.pos + 1] != "]":
                self.take()  # '-'
                hi = self._class_atom()
                if isinstance(hi, np.ndarray):
                    self.error("bad range bound")
                if hi < lo:
                    self.error("bad range")
                bits[lo:hi + 1] = True
            else:
                bits[lo] = True
        return _invert_bytes(bits) if negate else bits


# -- Thompson NFA -------------------------------------------------------------

class _NFA:
    def __init__(self):
        self.eps: list[list[int]] = []        # epsilon edges per state
        self.trans: list[list[tuple[np.ndarray, int]]] = []  # (symbolset, target)

    def new_state(self) -> int:
        self.eps.append([])
        self.trans.append([])
        return len(self.eps) - 1

    def build(self, node) -> tuple[int, int]:
        """Returns (start, accept) fragment for an AST node."""
        kind = node[0]
        if kind == "empty":
            s = self.new_state()
            return s, s
        if kind == "class":
            s, a = self.new_state(), self.new_state()
            self.trans[s].append((node[1], a))
            return s, a
        if kind == "cat":
            start, acc = self.build(node[1][0])
            for part in node[1][1:]:
                s2, a2 = self.build(part)
                self.eps[acc].append(s2)
                acc = a2
            return start, acc
        if kind == "alt":
            s, a = self.new_state(), self.new_state()
            for branch in node[1]:
                bs, ba = self.build(branch)
                self.eps[s].append(bs)
                self.eps[ba].append(a)
            return s, a
        if kind == "star":
            s, a = self.new_state(), self.new_state()
            bs, ba = self.build(node[1])
            self.eps[s] += [bs, a]
            self.eps[ba] += [bs, a]
            return s, a
        raise AssertionError(f"unknown AST node {kind}")

    def closure(self, states: frozenset[int]) -> frozenset[int]:
        seen = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in self.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)


# -- compiled DFA -------------------------------------------------------------

@dataclass(frozen=True)
class CompiledRegex:
    """Host-compiled DFA, ready for device execution.

    ``table_padded`` carries an extra identity "pad" class (id
    ``pad_class``) so past-end positions are a no-op transition instead of
    a select against the previous state.
    """

    pattern: str
    table: np.ndarray          # (num_states, num_classes) int32
    symbol_class: np.ndarray   # (258,) int32 — byte/BOS/EOS -> class
    accept: np.ndarray         # (num_states,) bool
    start_state: int
    table_padded: np.ndarray   # (num_states, num_classes + 1) int32
    pad_class: int             # identity class id == num_classes


@functools.lru_cache(maxsize=256)
def compile(pattern: str, full_match: bool = False) -> CompiledRegex:  # noqa: A001
    """Compile a pattern for device execution.

    ``full_match=False``: cuDF ``contains_re`` / ``re.search`` semantics
    (unanchored unless the pattern uses ^/$).  ``full_match=True``:
    ``re.fullmatch`` semantics (both ends anchored).
    """
    ast = _Parser(pattern).parse()
    nfa = _NFA()
    start, accept = nfa.build(ast)
    if not full_match:
        # implicit byte-skipping prefix: restart anywhere in the string
        pre = nfa.new_state()
        anybyte = _byte_bits()
        anybyte[:NUM_BYTES] = True
        nfa.trans[pre].append((anybyte, pre))
        nfa.eps[pre].append(start)
        start = pre

    # Symbol equivalence classes over all NFA edges.  BOS/EOS are forced
    # into their own classes (they get assertion semantics below).
    edge_sets = [bits for state_edges in nfa.trans for bits, _ in state_edges]
    sig_matrix = (np.stack(edge_sets) if edge_sets
                  else np.zeros((1, NUM_SYMBOLS), np.bool_))
    anchor_rows = np.zeros((2, NUM_SYMBOLS), np.bool_)
    anchor_rows[0, BOS] = True
    anchor_rows[1, EOS] = True
    sig_matrix = np.concatenate([sig_matrix, anchor_rows])
    sigs: dict[bytes, int] = {}
    symbol_class = np.zeros(NUM_SYMBOLS, np.int32)
    for sym in range(NUM_SYMBOLS):
        key = sig_matrix[:, sym].tobytes()
        symbol_class[sym] = sigs.setdefault(key, len(sigs))
    num_classes = len(sigs)
    class_rep = np.zeros(num_classes, np.int32)
    for sym in range(NUM_SYMBOLS - 1, -1, -1):
        class_rep[symbol_class[sym]] = sym

    # Subset construction.  BOS/EOS steps *retain* the current state set
    # (zero-width assertion) in addition to explicit anchor edges.
    start_set = nfa.closure(frozenset([start]))
    dfa_ids: dict[frozenset[int], int] = {start_set: 0}
    order: list[frozenset[int]] = [start_set]
    rows: list[np.ndarray] = []
    accepts: list[bool] = []
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        row = np.zeros(num_classes, np.int32)
        for cls in range(num_classes):
            sym = class_rep[cls]
            moved = set()
            for s in cur:
                for bits, t in nfa.trans[s]:
                    if bits[sym]:
                        moved.add(t)
            if sym >= NUM_BYTES:
                moved |= set(cur)           # assertion: survive the marker
            nxt = nfa.closure(frozenset(moved)) if moved else frozenset()
            if nxt not in dfa_ids:
                dfa_ids[nxt] = len(order)
                order.append(nxt)
            row[cls] = dfa_ids[nxt]
        rows.append(row)
        accepts.append(accept in cur)

    table = np.stack(rows).astype(np.int32)
    acc = np.array(accepts, np.bool_)
    if not full_match:
        # Sticky accept: once matched, stay matched (search semantics).
        for s in range(len(table)):
            if acc[s]:
                table[s, :] = s

    num_states = table.shape[0]
    padded_t = np.concatenate(
        [table, np.arange(num_states, dtype=np.int32).reshape(-1, 1)], axis=1)
    return CompiledRegex(pattern=pattern, table=table,
                         symbol_class=symbol_class, accept=acc, start_state=0,
                         table_padded=padded_t.astype(np.int32),
                         pad_class=num_classes)


def _onehot_lookup(table_vec: jax.Array, idx: jax.Array) -> jax.Array:
    """``table_vec[idx]`` as a one-hot matmul.

    TPU dynamic gather from a small table lowers to a scalar path that runs
    ~23M lookups/s (measured, v5e); a compare + MXU matmul with f32
    accumulation does the same lookup exactly at >10x that.  Exact because
    one-hot entries are 0/1 and table values are int32-exact in f32 (DFA
    tables are far below 2^24 states).
    """
    size = table_vec.shape[0]
    if size > 4096:
        # Wide tables would make the one-hot operand rows*size — gather is
        # slower but memory-safe for pathological DFAs.
        return jnp.take(table_vec, idx)
    oh = (idx[:, None] == jnp.arange(size, dtype=jnp.int32)[None, :])
    return jnp.matmul(oh.astype(jnp.bfloat16),
                      table_vec.astype(jnp.float32),
                      preferred_element_type=jnp.float32).astype(jnp.int32)


def run_dfa_t(rx: CompiledRegex, chars_t: jax.Array,
              lengths: jax.Array) -> jax.Array:
    """Run the DFA over a TRANSPOSED (max_len, rows) uint8 char matrix.

    Returns a bool (rows,) match mask.  BOS folds into the (uniform) start
    state on the host; EOS is applied after the scan.  Each scan step
    consumes one contiguous char row (the transposed layout keeps the lane
    dimension = rows, so nothing lane-pads) and resolves both the
    byte→class map and the transition table through one-hot MXU lookups
    (:func:`_onehot_lookup`).  Past-end positions map to the identity "pad"
    class, so no select on the state is needed.
    """
    num_classes = rx.table.shape[1]
    max_len, n = chars_t.shape
    c1 = num_classes + 1
    tbl_flat = jnp.asarray(rx.table_padded.reshape(-1))
    byte_class = jnp.asarray(rx.symbol_class[:NUM_BYTES])
    pad_cls = jnp.int32(rx.pad_class)

    # BOS transition is uniform across rows: resolve on host.
    state0 = int(rx.table[rx.start_state, rx.symbol_class[BOS]])
    state = jnp.full((n,), state0, jnp.int32)

    if max_len > 0:
        mask_t = (jnp.arange(max_len, dtype=jnp.int32)[:, None]
                  < lengths[None, :])

        def step(state, xs):
            ch, ok = xs
            cls = _onehot_lookup(byte_class, ch.astype(jnp.int32))
            cls = jnp.where(ok, cls, pad_cls)
            return _onehot_lookup(tbl_flat, state * c1 + cls), None

        state, _ = jax.lax.scan(step, state, (chars_t, mask_t))

    # EOS: per-state transition, then accept — both row-count lookups.
    eos_map = jnp.asarray(rx.table[:, rx.symbol_class[EOS]])
    state = _onehot_lookup(eos_map, state)
    accept = jnp.asarray(rx.accept.astype(np.int32))
    return _onehot_lookup(accept, state) != 0


def run_dfa(rx: CompiledRegex, padded: jax.Array, lengths: jax.Array) -> jax.Array:
    """Run the DFA over a padded (rows, max_len) uint8 matrix (compat
    wrapper over :func:`run_dfa_t`)."""
    return run_dfa_t(rx, padded.T, lengths)


@functools.lru_cache(maxsize=256)
def matcher(pattern: str, full_match: bool = False):
    """Jitted end-to-end matcher for one pattern: ``(chars_t, lengths) →
    bool mask``.  One compiled XLA program per (pattern, shape) instead of
    an eager dispatch per DFA building block."""
    rx = compile(pattern, full_match)

    @jax.jit
    def run(chars_t, lengths):
        return run_dfa_t(rx, chars_t, lengths)

    return run
