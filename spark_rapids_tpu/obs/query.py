"""Per-plan query metrics record — the Spark SQL-metrics-tab analog.

One :class:`QueryMetrics` describes one plan execution end to end: bind /
compile / execute / materialize wall times, compile-cache status, the
host-sync and device→host-byte deltas accounted by utils/memory.py, the
dictionary-encode cache hit rate, and (when produced by
``Plan.explain_analyze``) per-step measured rows in/out and timings.

Producers live in exec/compile.py (``run_plan`` when ``SRT_METRICS=1``,
and ``analyze_plan`` behind ``Plan.explain_analyze``); consumers are
:func:`last_query_metrics` (the benchmarks' second JSON line) and
:meth:`QueryMetrics.render` (the ``explain_analyze`` tree).

``to_json()`` is a STABLE schema (``schema_version`` bumps on change;
tests/golden/query_metrics_schema.json pins the key set): BENCH runs diff
these payloads across PRs, so fields are append-only.

No jax at module load (lazy-import rule, see obs/metrics.py).
"""

from __future__ import annotations

import itertools
import json
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

#: Sentinels for "not measured" (explain_analyze measures per-step rows
#: and times; the plain metered run records static step info only).
UNMEASURED_INT = -1
UNMEASURED_FLOAT = -1.0

_QUERY_IDS = itertools.count(1)
_LAST_LOCK = threading.Lock()
_LAST: Optional["QueryMetrics"] = None
_LAST_STREAM: Optional["QueryMetrics"] = None

#: Thread-local serving context (serve/scheduler.py).  A scheduler
#: worker sets this around its executor call; QueryMetrics constructed
#: on that thread pick up the serve fields AND stash themselves back
#: into the context dict (key "qm") so the worker can attach the
#: metrics object to its ticket without racing the global
#: ``set_last_*`` slots across concurrent workers.
_SERVE_TLS = threading.local()


def set_serve_context(info: Optional[dict]) -> None:
    """Install (or with None clear) this thread's serving context:
    ``{"queue_wait_seconds", "admission", "result_cache", "policy"}``."""
    _SERVE_TLS.info = info


def serve_context() -> Optional[dict]:
    return getattr(_SERVE_TLS, "info", None)


def next_query_id() -> int:
    return next(_QUERY_IDS)


@dataclass
class StepMetrics:
    """One plan step's contribution.

    ``rows_in``/``rows_out`` count LIVE rows (selection-mask semantics:
    the program keeps every slot padded; live rows are the ones a
    materialization would keep).  ``padded_out`` is the physical slot
    count after the step, and ``density`` = rows_out / padded_out — low
    density after a filter is exactly the compaction opportunity the
    selection-mask design defers to materialization."""
    index: int
    kind: str
    describe: str
    rows_in: int = UNMEASURED_INT
    rows_out: int = UNMEASURED_INT
    padded_out: int = UNMEASURED_INT
    seconds: float = UNMEASURED_FLOAT
    density: float = UNMEASURED_FLOAT

    def to_dict(self) -> dict:
        return {
            "index": self.index,
            "kind": self.kind,
            "describe": self.describe,
            "rows_in": self.rows_in,
            "rows_out": self.rows_out,
            "padded_out": self.padded_out,
            "seconds": round(self.seconds, 6),
            "density": round(self.density, 6),
        }


@dataclass
class QueryMetrics:
    """End-to-end accounting for one plan execution."""
    query_id: int = 0
    #: stable plan fingerprint (obs/history.plan_fingerprint) — the same
    #: correlation key the live registry, timeline span args, and the
    #: history sink carry, so a scrape, a trace, and a history line all
    #: join on (query_id, fingerprint).  "" when the producer had no
    #: plan in hand.
    fingerprint: str = ""
    mode: str = "run"                  # run | analyze | dist | stream
    input_rows: int = 0
    input_columns: int = 0
    output_rows: int = UNMEASURED_INT
    bind_seconds: float = 0.0
    #: wall of the first program invocation when it missed the in-process
    #: program cache — dominated by trace + XLA compile (BASELINE.md:
    #: minutes on TPU, vs ms of execute); 0.0 on a hit.
    compile_seconds: float = 0.0
    #: program invocation wall (device dispatch + compute + the blocking
    #: wait); on a compile-cache miss this equals compile_seconds.
    execute_seconds: float = 0.0
    materialize_seconds: float = 0.0
    total_seconds: float = 0.0
    compile_cache: str = "unavailable"  # hit | miss | unavailable
    host_syncs: int = 0
    d2h_bytes: int = 0
    dict_encode_hits: int = 0
    dict_encode_misses: int = 0
    steps: List[StepMetrics] = field(default_factory=list)
    #: raw registry counter deltas over the run (shuffle bytes, parquet
    #: rows, ... — whatever the layers underneath incremented).
    counters: Dict[str, int] = field(default_factory=dict)
    # -- streaming executor (exec/stream.py; zero for non-stream modes) --
    stream_batches: int = 0
    stream_inflight: int = 0            # configured window (K)
    stream_peak_inflight: int = 0       # deepest observed pipeline depth
    stream_donation_hits: int = 0       # donating dispatches reusing HBM
    stream_donation_misses: int = 0
    stream_source_seconds: float = 0.0  # decode time inside the feed
    #: decode + bind + dispatch + materialize, as if run serially; the
    #: overlap ratio is (serial - wall) / serial, > 0 when pipelining won.
    stream_serial_seconds: float = 0.0
    stream_overlap_ratio: float = 0.0
    # -- sharded streaming (exec/dist_stream.py; zero when single-chip) --
    stream_shards: int = 0              # mesh devices driving the stream
    stream_merge_collectives: int = 0   # ICI merges paid (combine: ONE)
    stream_ici_bytes: int = 0           # estimated collective traffic
    stream_syncs_avoided: int = 0       # per-batch live-count syncs saved
    # -- execution resilience (resilience/; zero on a fault-free run) ----
    recovery_retries: int = 0           # evict-and-retry rounds taken
    recovery_splits: int = 0            # batch halvings (the last rung)
    recovery_cache_evictions: int = 0   # device-cache entries dropped
    recovery_backoff_seconds: float = 0.0
    # -- mesh-ladder share of the totals above (exec/dist.py; zero on
    # single-chip runs).  A dist retry also counts in recovery_retries —
    # these isolate how much of the recovery work happened on the mesh,
    # and recovery_dist_fallbacks marks a degraded (collect-and-finish-
    # single-chip) answer.
    recovery_dist_retries: int = 0
    recovery_dist_splits: int = 0       # per-shard capacity halvings
    recovery_dist_fallbacks: int = 0    # SRT_DIST_FALLBACK=collect rungs
    recovery_dist_evictions: int = 0
    # -- out-of-core share (resilience/spill.py; zero unless SRT_SPILL
    # engaged): pages/bytes that left HBM and came back, spill files
    # written, and the wall spent paging back in.
    recovery_spill_pages_out: int = 0
    recovery_spill_pages_in: int = 0
    recovery_spill_bytes_out: int = 0
    recovery_spill_bytes_in: int = 0
    recovery_spill_files: int = 0
    recovery_spill_page_in_seconds: float = 0.0
    # -- cost ledger inputs (obs/profile.py; filled by a CostCollector
    # over the metered run, zero/empty when nothing was collected) ------
    cost_analysis_available: bool = False   # XLA cost_analysis() worked
    cost_flops: float = 0.0                 # summed over programs run
    cost_bytes_accessed: float = 0.0
    hbm_static_bytes: int = 0               # program argument footprint
    hbm_peak_bytes: int = 0                 # max allocator peak sampled
    hbm_per_device: List[dict] = field(default_factory=list)
    # -- plan optimizer (exec/optimize.py; zeroed when SRT_PLAN_OPT=0
    # or no rule fired) --------------------------------------------------
    opt_enabled: bool = False
    opt_rules: List[str] = field(default_factory=list)
    opt_rewrites: Dict[str, int] = field(default_factory=dict)
    opt_steps_before: int = 0
    opt_steps_after: int = 0
    opt_history_informed: bool = False
    # -- serving layer (serve/scheduler.py; zeroed/empty when the query
    # ran outside a QuerySession) ----------------------------------------
    serve_queue_wait_seconds: float = 0.0
    serve_admission: str = ""           # admitted | queued | rejected
    serve_result_cache: str = ""        # hit | miss | "" (uncacheable)
    serve_policy: str = ""              # rr | wfair

    def __post_init__(self) -> None:
        # Adopt the ambient serving context, if a scheduler worker set
        # one on this thread, and hand ourselves back to it.
        info = serve_context()
        if info is not None:
            self.serve_queue_wait_seconds = float(
                info.get("queue_wait_seconds", 0.0))
            self.serve_admission = str(info.get("admission", ""))
            self.serve_result_cache = str(info.get("result_cache", ""))
            self.serve_policy = str(info.get("policy", ""))
            info["qm"] = self

    def finish_counters(self, delta: Dict[str, int]) -> None:
        """Fold a registry counters-delta into the summary fields."""
        self.counters = dict(delta)
        self.host_syncs = delta.get("host.sync", 0)
        self.d2h_bytes = delta.get("host.d2h_bytes", 0)
        self.dict_encode_hits = delta.get("strings.dict_encode.hit", 0)
        self.dict_encode_misses = delta.get("strings.dict_encode.miss", 0)

    def apply_recovery(self, delta: Dict[str, float]) -> None:
        """Fold a ``RecoveryStats.delta`` (resilience/retry.py) taken over
        the run into the recovery fields."""
        self.recovery_retries = int(delta.get("retries", 0))
        self.recovery_splits = int(delta.get("splits", 0))
        self.recovery_cache_evictions = int(delta.get("cache_evictions", 0))
        self.recovery_backoff_seconds = float(
            delta.get("backoff_seconds", 0.0))
        self.recovery_dist_retries = int(delta.get("dist_retries", 0))
        self.recovery_dist_splits = int(delta.get("dist_splits", 0))
        self.recovery_dist_fallbacks = int(delta.get("dist_fallbacks", 0))
        self.recovery_dist_evictions = int(delta.get("dist_evictions", 0))
        self.recovery_spill_pages_out = int(delta.get("spill_pages_out", 0))
        self.recovery_spill_pages_in = int(delta.get("spill_pages_in", 0))
        self.recovery_spill_bytes_out = int(delta.get("spill_bytes_out", 0))
        self.recovery_spill_bytes_in = int(delta.get("spill_bytes_in", 0))
        self.recovery_spill_files = int(delta.get("spill_files", 0))
        self.recovery_spill_page_in_seconds = float(
            delta.get("spill_page_in_seconds", 0.0))

    def apply_opt(self, info) -> None:
        """Fold an optimizer record (exec/optimize.OptInfo) into the opt
        fields — the ``opt`` block of the JSON payload."""
        if info is None:
            return
        self.opt_enabled = bool(info.enabled)
        self.opt_rules = list(info.rules)
        self.opt_rewrites = {k: int(v)
                             for k, v in sorted(info.rewrites.items()) if v}
        self.opt_steps_before = int(info.steps_before)
        self.opt_steps_after = int(info.steps_after)
        self.opt_history_informed = bool(info.history_informed)

    def to_dict(self) -> dict:
        from .profile import cost_block
        return {
            # v3: added the always-present "recovery" block.
            # v4: added "recovery.dist" (the mesh-ladder share).
            # v5: added the always-present "cost" ledger block.
            # v6: "stream" gained the sharded-stream fields (shards,
            #     merge_collectives, ici_bytes, syncs_avoided).
            # v7: added "fingerprint" (the live-telemetry correlation
            #     key shared with obs/live.py and timeline span args).
            # v8: added the always-present "scan" block (statistics
            #     pruning + encoded residency: bytes/pages/row-groups
            #     skipped, encoded column count) and the "cost" ledger's
            #     "scan" sub-split (decode vs gather seconds).
            # v9: added the always-present "opt" block (plan-optimizer
            #     rewrites applied before bind/compile: per-rule
            #     counters, step counts before/after, pruned input
            #     columns, history-informed flag).
            # v10: added the always-present "serve" block (queue wait,
            #     admission outcome, result-cache status, scheduler
            #     policy — empty/zero outside a QuerySession).
            # v11: added "recovery.spill" (the out-of-core share:
            #     pages/bytes paged out of HBM and back, spill files
            #     written, page-in wall — zero unless SRT_SPILL engaged).
            "schema_version": 11,
            "metric": "query_metrics",
            "query_id": self.query_id,
            "fingerprint": self.fingerprint,
            "mode": self.mode,
            "input": {"rows": self.input_rows,
                      "columns": self.input_columns},
            "output": {"rows": self.output_rows},
            "timings": {
                "bind_seconds": round(self.bind_seconds, 6),
                "compile_seconds": round(self.compile_seconds, 6),
                "execute_seconds": round(self.execute_seconds, 6),
                "materialize_seconds": round(self.materialize_seconds, 6),
                "total_seconds": round(self.total_seconds, 6),
            },
            "compile_cache": self.compile_cache,
            "host": {"syncs": self.host_syncs,
                     "d2h_bytes": self.d2h_bytes},
            "caches": {"dict_encode_hits": self.dict_encode_hits,
                       "dict_encode_misses": self.dict_encode_misses},
            "steps": [s.to_dict() for s in self.steps],
            "counters": self.counters,
            # Always present (zeroed outside mode="stream") so the golden
            # key set stays one set across modes.
            "stream": {
                "batches": self.stream_batches,
                "inflight": self.stream_inflight,
                "peak_inflight": self.stream_peak_inflight,
                "donation_hits": self.stream_donation_hits,
                "donation_misses": self.stream_donation_misses,
                "source_seconds": round(self.stream_source_seconds, 6),
                "serial_seconds": round(self.stream_serial_seconds, 6),
                "overlap_ratio": round(self.stream_overlap_ratio, 6),
                # Sharded-stream share (zero when single-chip): one
                # merge collective per group-by stream is the design
                # invariant the bench line watches.
                "shards": self.stream_shards,
                "merge_collectives": self.stream_merge_collectives,
                "ici_bytes": self.stream_ici_bytes,
                "syncs_avoided": self.stream_syncs_avoided,
            },
            # Always present (zeroed on a fault-free run) for the same
            # one-key-set-across-modes reason as "stream".
            "recovery": {
                "retries": self.recovery_retries,
                "splits": self.recovery_splits,
                "cache_evictions": self.recovery_cache_evictions,
                "backoff_seconds": round(self.recovery_backoff_seconds, 6),
                # Mesh-ladder share (always present, zero single-chip):
                # nonzero "fallbacks" marks a degraded-but-correct answer
                # finished single-chip via SRT_DIST_FALLBACK=collect.
                "dist": {
                    "retries": self.recovery_dist_retries,
                    "splits": self.recovery_dist_splits,
                    "fallbacks": self.recovery_dist_fallbacks,
                    "cache_evictions": self.recovery_dist_evictions,
                },
                # Out-of-core share (always present, zero unless the
                # spill rung / proactive watermark engaged): nonzero
                # bytes_out with bytes_in proves pages left HBM and came
                # back — the query ran larger than memory.
                "spill": {
                    "pages_out": self.recovery_spill_pages_out,
                    "pages_in": self.recovery_spill_pages_in,
                    "bytes_out": self.recovery_spill_bytes_out,
                    "bytes_in": self.recovery_spill_bytes_in,
                    "files": self.recovery_spill_files,
                    "page_in_seconds": round(
                        self.recovery_spill_page_in_seconds, 6),
                },
            },
            # Always present (zeroed on a non-pruning run): the scan
            # pushdown ledger — what statistics pruning skipped and how
            # many columns stayed dictionary-resident (SRT_ENCODED_EXEC).
            "scan": {
                "bytes_skipped": int(
                    self.counters.get("scan.bytes_skipped", 0)),
                "pages_skipped": int(
                    self.counters.get("scan.pages_skipped", 0)),
                "row_groups_skipped": int(
                    self.counters.get("scan.row_groups_skipped", 0)),
                "encoded_cols": int(
                    self.counters.get("scan.encoded_cols", 0)),
            },
            # Always present (zeroed when the optimizer is off or no
            # rule fired): what exec/optimize.py rewrote before
            # bind/compile.
            "opt": {
                "enabled": self.opt_enabled,
                "rules": list(self.opt_rules),
                "rewrites": dict(self.opt_rewrites),
                "steps_before": self.opt_steps_before,
                "steps_after": self.opt_steps_after,
                "pruned_columns": int(
                    self.counters.get("plan.opt.pruned_columns", 0)),
                "history_informed": self.opt_history_informed,
            },
            # Always present (empty/zero outside a QuerySession): how
            # the serving layer handled this query.
            "serve": {
                "queue_wait_seconds": round(
                    self.serve_queue_wait_seconds, 6),
                "admission": self.serve_admission,
                "result_cache": self.serve_result_cache,
                "policy": self.serve_policy,
            },
            # Always present (zeroed when unmetered): wall split into
            # compute/ici/host_sync/dispatch_overhead plus the HBM
            # footprint — the regression gate's input (obs/regress.py).
            "cost": cost_block(self),
        }

    def to_json(self) -> str:
        """ONE line, stable key order — the benchmarks' second JSON line."""
        return json.dumps(self.to_dict(), sort_keys=True)

    # -- rendering ---------------------------------------------------------

    def render(self, header: str = "") -> str:
        """The ``explain_analyze`` tree: per-step lines annotated with
        measured rows/time where available."""
        lines = []
        if header:
            lines.append(header)
        lines.append(
            f"  == Analyzed ({self.mode}) == "
            f"bind={_ms(self.bind_seconds)} "
            f"compile={_ms(self.compile_seconds)} "
            f"cache={self.compile_cache} "
            f"execute={_ms(self.execute_seconds)} "
            f"materialize={_ms(self.materialize_seconds)} "
            f"total={_ms(self.total_seconds)}")
        lines.append(
            f"  host_syncs={self.host_syncs} d2h_bytes={self.d2h_bytes} "
            f"dict_encode={self.dict_encode_hits} hit"
            f"/{self.dict_encode_misses} miss")
        if self.total_seconds >= 0:
            from .profile import cost_block
            cb = cost_block(self)
            lines.append(
                f"  cost: compute={_ms(cb['compute_seconds'])} "
                f"ici={_ms(cb['ici_seconds'])} "
                f"host_sync={_ms(cb['host_sync_seconds'])} "
                f"overhead={_ms(cb['dispatch_overhead_seconds'])} "
                f"unattributed={_ms(cb['unattributed_seconds'])} "
                f"(attributed {cb['attributed_fraction']:.0%})")
            if cb["hbm"]["devices"]:
                lines.append(
                    f"  hbm: static={cb['hbm']['static_bytes']} "
                    f"peak={cb['hbm']['peak_bytes']} "
                    f"devices={cb['hbm']['devices']}")
        if self.opt_enabled and self.opt_rewrites:
            rw = " ".join(f"{k}={v}"
                          for k, v in sorted(self.opt_rewrites.items()))
            hist = " (history-informed)" if self.opt_history_informed else ""
            lines.append(
                f"  opt: steps {self.opt_steps_before} -> "
                f"{self.opt_steps_after}  {rw}{hist}")
        if self.recovery_retries or self.recovery_splits:
            lines.append(
                f"  recovery: retries={self.recovery_retries} "
                f"splits={self.recovery_splits} "
                f"cache_evictions={self.recovery_cache_evictions} "
                f"backoff={_ms(self.recovery_backoff_seconds)}")
        if (self.recovery_dist_retries or self.recovery_dist_splits
                or self.recovery_dist_fallbacks):
            lines.append(
                f"  recovery.dist: retries={self.recovery_dist_retries} "
                f"splits={self.recovery_dist_splits} "
                f"fallbacks={self.recovery_dist_fallbacks} "
                f"cache_evictions={self.recovery_dist_evictions}")
        if self.recovery_spill_pages_out:
            lines.append(
                f"  recovery.spill: pages={self.recovery_spill_pages_out}"
                f"/{self.recovery_spill_pages_in} "
                f"bytes={self.recovery_spill_bytes_out}"
                f"/{self.recovery_spill_bytes_in} "
                f"files={self.recovery_spill_files} "
                f"page_in={_ms(self.recovery_spill_page_in_seconds)}")
        n = len(self.steps)
        for i, s in enumerate(self.steps):
            branch = "└─" if i == n - 1 else "├─"
            if s.rows_in == UNMEASURED_INT:
                ann = "  [metrics unavailable: set SRT_METRICS=1]"
            else:
                ann = (f"  rows: {s.rows_in} -> {s.rows_out}"
                       f" (density {s.density:.1%}"
                       f" of {s.padded_out} slots)")
                if s.seconds != UNMEASURED_FLOAT:
                    ann += f"  {_ms(s.seconds)}"
            lines.append(f"  {branch} {s.describe}{ann}")
        out_rows = ("?" if self.output_rows == UNMEASURED_INT
                    else self.output_rows)
        lines.append(f"     Materialize -> {out_rows} rows")
        return "\n".join(lines)


def _ms(seconds: float) -> str:
    if seconds < 0:
        return "n/a"
    return f"{seconds * 1e3:.1f}ms"


def _on_query_complete(qm: QueryMetrics) -> None:
    """Every completed metered query funnels through the ``set_last_*``
    setters, so this is where the SLO surface is fed: one observation
    into the latency histograms (obs/server.py, gated on
    ``SRT_METRICS=1``) and the SLO-breach bundle check (obs/bundle.py,
    gated on ``SRT_SLO_MS`` + ``SRT_BUNDLE_DIR``)."""
    from . import capacity as _capacity
    from . import server as _server
    _server.observe_query(qm)
    _capacity.feed_completion(qm.mode, qm.total_seconds, qm.fingerprint)
    from .bundle import maybe_slo
    maybe_slo(qm)


def set_last_query_metrics(qm: QueryMetrics) -> None:
    global _LAST
    with _LAST_LOCK:
        _LAST = qm
    _on_query_complete(qm)


def last_query_metrics() -> Optional[QueryMetrics]:
    """The most recent plan execution's metrics (None before any metered
    run) — how benchmarks fetch the payload without plumbing a return
    value through ``Plan.run``."""
    with _LAST_LOCK:
        return _LAST


def set_last_stream_metrics(qm: QueryMetrics) -> None:
    global _LAST_STREAM
    with _LAST_LOCK:
        _LAST_STREAM = qm
    _on_query_complete(qm)


def last_stream_metrics() -> Optional[QueryMetrics]:
    """The most recent streaming execution's metrics (mode="stream";
    None before any stream completes).  Unlike the metered ``run`` path
    this is populated even with SRT_METRICS off — the stream's phase
    timings cost nothing extra to record, and the overlap ratio is the
    whole point of running the executor."""
    with _LAST_LOCK:
        return _LAST_STREAM


def _metrics_payload() -> dict:
    """Payload for ``bench_line("metrics")``: the last query's
    ``to_dict()`` when a metered plan ran, else the global registry
    snapshot (bench programs that never build a Plan still get their
    cache/IO/host-sync counters captured)."""
    qm = last_query_metrics()
    if qm is not None:
        return qm.to_dict()
    from .metrics import registry
    return {"metric": "srt_metrics", "counters": registry().snapshot()}


def _cache_payload() -> dict:
    """Payload for ``bench_line("cache")``: whole-plan cache hit rate,
    distinct shapes bound, and the pad-waste fraction of the
    shape-bucketing layer — the bench-trajectory view of the bucketing
    win.  Separate from the metrics payload so the golden-pinned
    QueryMetrics schema stays untouched."""
    from .metrics import registry
    snap = registry().snapshot()
    hits = int(snap.get("plan.compile_cache.hit", 0))
    misses = int(snap.get("plan.compile_cache.miss", 0))
    lookups = hits + misses
    pad_rows = int(snap.get("plan.bucket.pad_rows", 0))
    rows_total = int(snap.get("plan.bucket.rows_total", 0))
    from ..exec.bucketing import bucket_stats   # lazy: exec pulls in jax
    return {
        "metric": "compile_cache",
        "hits": hits,
        "misses": misses,
        "hit_rate": round(hits / lookups, 6) if lookups else 0.0,
        "size": int(snap.get("plan.compile_cache.size", 0)),
        "evictions": int(snap.get("plan.compile_cache.evictions", 0)),
        "bucketing": dict(bucket_stats(),
                          pad_rows=pad_rows,
                          rows_total=rows_total,
                          pad_waste_frac=(round(pad_rows / rows_total, 6)
                                          if rows_total else 0.0)),
    }


def _stream_payload() -> dict:
    """Payload for ``bench_line("stream")``: wall vs. serial phase-sum
    time, the overlap ratio, and the donation-reuse counters of the last
    ``run_plan_stream`` — the bench-trajectory view of pipeline
    efficiency.  ``{"runs": 0}`` before any stream completes."""
    qm = last_stream_metrics()
    if qm is None:
        return {"metric": "stream_exec", "runs": 0}
    return {
        "metric": "stream_exec",
        "runs": 1,
        "batches": qm.stream_batches,
        "input_rows": qm.input_rows,
        "output_rows": qm.output_rows,
        "inflight": qm.stream_inflight,
        "peak_inflight": qm.stream_peak_inflight,
        "donation_hits": qm.stream_donation_hits,
        "donation_misses": qm.stream_donation_misses,
        "wall_seconds": round(qm.total_seconds, 6),
        "serial_seconds": round(qm.stream_serial_seconds, 6),
        "source_seconds": round(qm.stream_source_seconds, 6),
        "overlap_ratio": round(qm.stream_overlap_ratio, 6),
    }


def _dist_stream_payload() -> dict:
    """Payload for ``bench_line("dist_stream")``: the sharded-stream view
    of the last streaming run — shard count, the one-merge-collective
    invariant, estimated ICI bytes, donation reuse, and the host syncs
    the device-carried live counts avoided versus per-batch
    ``run_plan_dist`` dispatch.  ``{"runs": 0}`` until a sharded stream
    (``run_plan_stream(mesh=...)``) completes."""
    qm = last_stream_metrics()
    if qm is None or qm.stream_shards == 0:
        return {"metric": "dist_stream", "runs": 0}
    return {
        "metric": "dist_stream",
        "runs": 1,
        "batches": qm.stream_batches,
        "shards": qm.stream_shards,
        "input_rows": qm.input_rows,
        "output_rows": qm.output_rows,
        "overlap_ratio": round(qm.stream_overlap_ratio, 6),
        "donation_hits": qm.stream_donation_hits,
        "donation_misses": qm.stream_donation_misses,
        "merge_collectives": qm.stream_merge_collectives,
        "ici_bytes": qm.stream_ici_bytes,
        "host_syncs": qm.host_syncs,
        "syncs_avoided": qm.stream_syncs_avoided,
        "wall_seconds": round(qm.total_seconds, 6),
    }


def _recovery_payload() -> dict:
    """Payload for ``bench_line("recovery")``: the process-lifetime
    recovery totals — retries taken, batch splits, cache evictions,
    backoff slept, faults injected — so a ``--faults`` bench run shows
    recovery actually engaging."""
    from ..resilience import recovery_stats
    snap = recovery_stats().snapshot()
    return {
        "metric": "recovery",
        "retries": int(snap["retries"]),
        "splits": int(snap["splits"]),
        "cache_evictions": int(snap["cache_evictions"]),
        "backoff_seconds": round(float(snap["backoff_seconds"]), 6),
        "faults_injected": int(snap["faults_injected"]),
        "dist": {
            "retries": int(snap["dist_retries"]),
            "splits": int(snap["dist_splits"]),
            "fallbacks": int(snap["dist_fallbacks"]),
            "cache_evictions": int(snap["dist_evictions"]),
        },
        "spill": {
            "pages_out": int(snap["spill_pages_out"]),
            "pages_in": int(snap["spill_pages_in"]),
            "bytes_out": int(snap["spill_bytes_out"]),
            "bytes_in": int(snap["spill_bytes_in"]),
            "files": int(snap["spill_files"]),
            "page_in_seconds": round(
                float(snap["spill_page_in_seconds"]), 6),
        },
    }


def _spill_payload() -> dict:
    """Payload for ``bench_line("spill")``: the process-lifetime
    out-of-core totals — pages/bytes paged out of HBM and back, spill
    files written, page-in wall.  ``bench_queries.py --spill`` merges
    its measured oracle-vs-spilled walls and parity verdict into this
    payload before emitting its one line."""
    from ..resilience import recovery_stats
    snap = recovery_stats().snapshot()
    return {
        "metric": "spill",
        "pages_out": int(snap["spill_pages_out"]),
        "pages_in": int(snap["spill_pages_in"]),
        "bytes_out": int(snap["spill_bytes_out"]),
        "bytes_in": int(snap["spill_bytes_in"]),
        "files": int(snap["spill_files"]),
        "page_in_seconds": round(float(snap["spill_page_in_seconds"]), 6),
    }


def _regress_payload() -> dict:
    """Payload for ``bench_line("regress")``: the perf-regression report
    of obs/regress.py over the ``SRT_METRICS_HISTORY`` file — per-plan
    fresh-vs-baseline breaches at ``SRT_REGRESS_TOL``.  Never raises;
    the caller (``bench_queries.py --regress``) decides the exit code
    from the ``breaches`` list."""
    from . import regress
    return regress.check_history()


def _encoded_scan_payload() -> dict:
    """Payload for ``bench_line("encoded_scan")``: the process-lifetime
    scan-pushdown view — host→device bytes actually moved vs bytes whose
    read was skipped by statistics pruning, pages/row-groups skipped,
    columns kept dictionary-resident, and the decode/gather wall split.
    ``bench_parquet.py`` emits it so ``--regress`` can watch the moved-
    bytes ratio; zero counters just mean pruning never engaged."""
    from .metrics import registry
    snap = registry().counters_snapshot()
    return {
        "metric": "encoded_scan",
        "bytes_moved": int(snap.get("io.parquet.bytes_read", 0)),
        "bytes_skipped": int(snap.get("scan.bytes_skipped", 0)),
        "pages_skipped": int(snap.get("scan.pages_skipped", 0)),
        "row_groups_skipped": int(snap.get("scan.row_groups_skipped", 0)),
        "row_groups_read": int(snap.get("io.parquet.row_groups", 0)),
        "encoded_cols": int(snap.get("scan.encoded_cols", 0)),
        "resident_hits": int(
            snap.get("strings.dict_encode.resident_hit", 0)),
        "decode_seconds": round(snap.get("scan.decode.us", 0) / 1e6, 6),
        "gather_seconds": round(snap.get("scan.gather.us", 0) / 1e6, 6),
    }


def _serving_payload() -> dict:
    """Payload for ``bench_line("serving")``: process-lifetime serving
    totals from the registry — submissions/admissions/rejections, the
    result-cache hit rate, and total queue-wait vs run time.  Latency
    percentiles and sustained qps are closed-loop-client measurements,
    so ``bench_queries.py --serving`` merges them into this payload
    before emitting its one line."""
    from .metrics import registry
    snap = registry().snapshot()
    hits = int(snap.get("serve.result_cache.hit", 0))
    misses = int(snap.get("serve.result_cache.miss", 0))
    lookups = hits + misses
    return {
        "metric": "serving",
        "submitted": int(snap.get("serve.submitted", 0)),
        "completed": int(snap.get("serve.completed", 0)),
        "admitted": int(snap.get("serve.admitted", 0)),
        "queued": int(snap.get("serve.queued", 0)),
        "rejected": int(snap.get("serve.admission.rejected", 0)),
        "hbm_waits": int(snap.get("serve.admission.hbm_waits", 0)),
        "errors": int(snap.get("serve.errors", 0)),
        "result_cache": {
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / lookups, 6) if lookups else 0.0,
            "evictions": int(snap.get("serve.result_cache.evictions", 0)),
            "bytes": int(snap.get("serve.result_cache.bytes", 0)),
        },
        "queue_wait_seconds": round(
            float(snap.get("serve.queue_wait.seconds", 0.0)), 6),
        "run_seconds": round(float(snap.get("serve.run.seconds", 0.0)), 6),
    }


def _workload_payload() -> dict:
    """Payload for ``bench_line("workload")``: the fleet-intelligence
    view of the current workload window — the top op hotspot (the next
    Pallas kernel target) and the top subplan overlap candidate (the
    next materialization target), each with its evidence.
    ``bench_queries.py --workload`` merges its measured live-vs-muted
    feed overhead into this payload before emitting its one line."""
    from . import workload
    snap = workload.snapshot()
    hotspots = snap.get("hotspots") or []
    overlaps = snap.get("overlaps") or []
    return {
        "metric": "workload",
        "queries": snap.get("queries", 0),
        "plans": snap.get("plans", 0),
        "step_seconds": snap.get("step_seconds", 0.0),
        "step_kinds": snap.get("step_kinds", 0),
        "top_hotspot": hotspots[0] if hotspots else None,
        "top_overlap": overlaps[0] if overlaps else None,
    }


_BENCH_PAYLOADS = {
    "metrics": _metrics_payload,
    "cache": _cache_payload,
    "stream": _stream_payload,
    "dist_stream": _dist_stream_payload,
    "recovery": _recovery_payload,
    "spill": _spill_payload,
    "regress": _regress_payload,
    "encoded_scan": _encoded_scan_payload,
    "serving": _serving_payload,
    "workload": _workload_payload,
}


def bench_line(kind: str) -> str:
    """One benchmark JSON line (single line, sorted keys) for ``kind``.

    Kinds: ``"metrics"`` (last QueryMetrics or registry snapshot),
    ``"cache"`` (compile cache + bucketing), ``"stream"`` (last streaming
    run), ``"dist_stream"`` (sharded-stream view of the last streaming
    run), ``"recovery"`` (process-lifetime resilience totals),
    ``"spill"`` (process-lifetime out-of-core paging totals),
    ``"regress"`` (perf-regression report vs the metrics history),
    ``"encoded_scan"`` (scan pruning / encoded-residency totals),
    ``"serving"`` (serving-layer admission/result-cache totals),
    ``"workload"`` (top op hotspot + top subplan overlap candidate).  The
    four legacy ``bench_*_line`` names are thin wrappers over this and
    emit byte-identical output.
    """
    builder = _BENCH_PAYLOADS.get(kind)
    if builder is None:
        raise ValueError(f"unknown bench line kind {kind!r} "
                         f"(have {sorted(_BENCH_PAYLOADS)})")
    return json.dumps(builder(), sort_keys=True)


def bench_metrics_line() -> str:
    """Thin wrapper: ``bench_line("metrics")`` (the benchmarks' second
    JSON line behind ``SRT_METRICS=1``)."""
    return bench_line("metrics")


def bench_cache_line() -> str:
    """Thin wrapper: ``bench_line("cache")`` (compile-cache/bucketing
    bench line)."""
    return bench_line("cache")


def bench_stream_line() -> str:
    """Thin wrapper: ``bench_line("stream")`` (streaming-pipeline bench
    line)."""
    return bench_line("stream")


def bench_recovery_line() -> str:
    """Thin wrapper: ``bench_line("recovery")`` (resilience bench line)."""
    return bench_line("recovery")
