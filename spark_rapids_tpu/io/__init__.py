"""IO layer: Arrow interop and Parquet scan/write."""

from .arrow import from_arrow, from_arrow_array, to_arrow, to_arrow_array
from .parquet import read_parquet, write_parquet

__all__ = [
    "from_arrow",
    "from_arrow_array",
    "read_parquet",
    "to_arrow",
    "to_arrow_array",
    "write_parquet",
]
