"""Word-image parity: XLA path == Pallas kernel == byte oracle.

Three independent implementations of the row format must agree bit-for-bit:
the XLA vector formulation, the Pallas TPU kernel (run here in interpret
mode on CPU; the same kernel runs compiled on TPU), and the host byte
contract checked against the native C++ packer.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.rows.image import (host_bytes_to_words, pack_words,
                                         pack_words_pallas, unpack_words,
                                         unpack_words_pallas,
                                         words_to_host_bytes)
from spark_rapids_tpu.rows.layout import compute_fixed_width_layout

SCHEMAS = {
    "mixed8": (dt.INT64, dt.FLOAT64, dt.INT32, dt.BOOL8, dt.FLOAT32, dt.INT8,
               dt.decimal32(-3), dt.decimal64(-8)),
    "narrow": (dt.INT8, dt.INT16, dt.UINT8, dt.BOOL8, dt.INT16, dt.UINT16),
    "wide": (dt.INT64, dt.UINT64, dt.FLOAT64, dt.TIMESTAMP_MICROSECONDS),
    "many": tuple([dt.INT32] * 20),          # 3 validity bytes
    "single": (dt.UINT16,),
}


def make_inputs(schema, n, rng):
    datas, masks = [], []
    for s in schema:
        np_dt = s.np_dtype
        if np_dt.kind == "f":
            vals = rng.normal(size=n).astype(np_dt)
            # Exercise special values through the software f64 bit path.
            if n >= 8 and np_dt == np.float64:
                vals[:8] = [0.0, -0.0, np.inf, -np.inf, np.nan, 1e308,
                            2.5e-308, -1.5]
        elif np_dt.kind == "b" or s == dt.BOOL8:
            vals = rng.integers(0, 2, n).astype(np_dt)
        else:
            info = np.iinfo(np_dt)
            vals = rng.integers(info.min, int(info.max) + 1, n,
                                dtype=np.int64 if info.min < 0 else np.uint64
                                ).astype(np_dt)
        datas.append(jnp.asarray(vals))
        masks.append(jnp.asarray(rng.integers(0, 4, n) > 0))
    return tuple(datas), tuple(masks)


def oracle_bytes(schema, layout, datas, masks):
    out = bytearray(layout.row_size * int(datas[0].shape[0]))
    np_datas = [np.asarray(d) for d in datas]
    np_masks = [np.asarray(m) for m in masks]
    for r in range(int(datas[0].shape[0])):
        base = r * layout.row_size
        vbits = 0
        for c, s in enumerate(schema):
            if np_masks[c][r]:
                vbits |= 1 << c
            raw = np_datas[c][r:r + 1].tobytes()
            start = base + layout.column_starts[c]
            out[start:start + layout.column_sizes[c]] = raw
        for b in range(layout.validity_bytes):
            out[base + layout.validity_offset + b] = (vbits >> (8 * b)) & 0xFF
    return bytes(out)


@pytest.mark.parametrize("name", sorted(SCHEMAS))
def test_xla_matches_oracle_bytes(name, rng):
    schema = SCHEMAS[name]
    layout = compute_fixed_width_layout(schema)
    datas, masks = make_inputs(schema, 100, rng)
    words = pack_words(layout, datas, masks)
    host = words_to_host_bytes(words, layout.row_size)
    assert host.tobytes() == oracle_bytes(schema, layout, datas, masks)


@pytest.mark.parametrize("name", sorted(SCHEMAS))
def test_pallas_matches_xla(name, rng):
    schema = SCHEMAS[name]
    layout = compute_fixed_width_layout(schema)
    datas, masks = make_inputs(schema, 300, rng)   # not a tile multiple
    ref = np.asarray(pack_words(layout, datas, masks))
    ker = np.asarray(pack_words_pallas(layout, datas, masks, interpret=True))
    np.testing.assert_array_equal(ref, ker)


@pytest.mark.parametrize("name", sorted(SCHEMAS))
def test_unpack_round_trip_both_paths(name, rng):
    schema = SCHEMAS[name]
    layout = compute_fixed_width_layout(schema)
    datas, masks = make_inputs(schema, 100, rng)
    words = pack_words(layout, datas, masks)
    for unpack in (unpack_words,
                   lambda l, w: unpack_words_pallas(l, w, interpret=True)):
        out_d, out_v = unpack(layout, words)
        for s, src, got in zip(schema, datas, out_d):
            a = np.asarray(src)
            b = np.asarray(got)
            np.testing.assert_array_equal(
                a.view(b.dtype) if a.dtype != b.dtype else a, b)
        for src_m, got_m in zip(masks, out_v):
            np.testing.assert_array_equal(np.asarray(src_m), np.asarray(got_m))


def test_host_bytes_inverse(rng):
    schema = SCHEMAS["mixed8"]
    layout = compute_fixed_width_layout(schema)
    datas, masks = make_inputs(schema, 64, rng)
    words = np.asarray(pack_words(layout, datas, masks))
    host = words_to_host_bytes(words, layout.row_size)
    back = host_bytes_to_words(host, layout.row_size)
    np.testing.assert_array_equal(words, back)


def test_native_cpp_agrees_with_device_words(rng):
    """The C++ host packer and the device word image produce the same bytes."""
    from spark_rapids_tpu import ffi
    schema = SCHEMAS["mixed8"]
    layout = compute_fixed_width_layout(schema)
    datas, masks = make_inputs(schema, 128, rng)
    device = words_to_host_bytes(pack_words(layout, datas, masks),
                                 layout.row_size)
    native = ffi.pack_rows(schema, [np.asarray(d) for d in datas],
                           [np.asarray(m) for m in masks])
    assert device.tobytes() == native.tobytes()
