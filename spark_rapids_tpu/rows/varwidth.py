"""Variable-width (string) row ↔ columnar conversion.

The reference hard-stops here: ``CUDF_FAIL("Only fixed width types are
currently supported")`` (row_conversion.cu:514-516, :573; nested-type TODO
at RowConversion.java:111).  This module EXTENDS the row-format contract to
string columns, Spark-``UnsafeRow`` style:

  * the fixed part lays out exactly as :mod:`.layout`, with each STRING
    column occupying an 8-byte slot (natural alignment 8) holding
    ``(length << 32) | offset`` — ``offset`` is the byte offset of the
    field's payload from the START of its row, ``length`` its byte count;
  * the validity tail and 8-byte row padding are unchanged (strings
    participate in the validity bits like any column);
  * after the padded fixed part comes the row's variable section: each
    string field's bytes in schema order, packed tight; the row is then
    padded to a multiple of 8.  Null strings write ``length 0`` at the
    running offset (deterministic bytes, like the fixed engine's zeroed
    padding);
  * rows therefore vary in size; a blob carries the ``int32 (n+1,)``
    row-offset sequence exactly like the cudf ``LIST<INT8>`` contract.

Device representation stays word-major-friendly: one flat ``uint32`` word
stream (rows are 8-byte aligned, so no field of the fixed part straddles a
word, and the variable section is assembled bytewise into words).  The
packing is gather-based — every output word finds its sources — because
TPU punishes scatters; per-row positions come from ``searchsorted`` over
the row offsets (log-depth, no giant cumsums, which measured minutes of
XLA compile at 4M rows on this stack).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..column import Column
from ..dtypes import INT64, STRING, DType
from ..table import Table
from .layout import RowLayout, align_offset, compute_fixed_width_layout
from .image import pack_words, unpack_words

_U32 = jnp.uint32


def _pow2(n: int) -> int:
    """Round up to a power of two — jitted pack/unpack programs are cached
    per padded size class, so a stream of different-sized batches doesn't
    recompile (minutes each on TPU) or grow the program cache unboundedly."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


@dataclass(frozen=True)
class VarLayout:
    """Static layout facts for a schema with string columns."""
    schema: tuple[DType, ...]
    fixed: RowLayout                 # strings replaced by INT64 slots
    var_cols: tuple[int, ...]        # schema indices of string columns


def _is_var(dt: DType) -> bool:
    return dt.is_string or dt.is_list


@functools.lru_cache(maxsize=None)
def compute_var_layout(schema: tuple[DType, ...]) -> VarLayout:
    for dt in schema:
        if dt.is_list and not dt.element.is_fixed_width:
            raise NotImplementedError(
                f"row format supports LIST of fixed-width elements only "
                f"(got {dt!r}); move nested payloads via Arrow interop")
        if dt.is_struct:
            raise NotImplementedError(
                "STRUCT columns have no row-format encoding (the reference "
                "punts nested types too, RowConversion.java:111); flatten "
                "fields into top-level columns or use Arrow interop")
    fixed_schema = tuple(INT64 if _is_var(dt) else dt for dt in schema)
    var_cols = tuple(i for i, dt in enumerate(schema) if _is_var(dt))
    if not var_cols:
        raise ValueError("schema has no variable-width columns; use the "
                         "fixed-width engine")
    return VarLayout(schema=tuple(schema),
                     fixed=compute_fixed_width_layout(fixed_schema),
                     var_cols=var_cols)


def _list_byte_view(c: Column) -> Column:
    """A LIST<fixed-width> column as a synthetic STRING column over its
    raw element bytes: byte offsets = element offsets * itemsize, payload
    = the flattened elements' little-endian bytes.  The var-section
    machinery then needs no list-specific kernels — the (len<<32|offset)
    slot design extends to lists for free.  (One host round trip for the
    byte view; this is the host-interop boundary anyway.)"""
    elem = c.dtype.element
    child = c.children[0]
    if child.validity is not None:
        raise NotImplementedError(
            "LIST elements with nulls have no row-format encoding yet; "
            "fill or drop element nulls first, or use Arrow interop")
    k = elem.itemsize
    host = np.ascontiguousarray(np.asarray(child.data))
    # Byte offsets in int64: an int32 multiply wraps silently once
    # element_offset * itemsize reaches 2^31 (>134M int128 elements).
    byte_offsets = np.asarray(c.offsets, dtype=np.int64) * k
    if byte_offsets.size and int(byte_offsets[-1]) >= 1 << 31:
        raise ValueError(
            f"LIST column's flattened element bytes "
            f"({int(byte_offsets[-1])}) exceed the 2 GB var-section "
            f"limit; split the batch (convert.py's batching does this "
            f"for the row path)")
    return Column(data=jnp.asarray(host.view(np.uint8).ravel()),
                  offsets=jnp.asarray(byte_offsets.astype(np.int32)),
                  validity=c.validity, dtype=STRING)


def _list_from_bytes(col: Column, dtype: DType) -> Column:
    """Inverse of :func:`_list_byte_view` at unpack time."""
    elem = dtype.element
    k = elem.itemsize
    host = np.ascontiguousarray(np.asarray(col.data))
    if elem.is_two_word:
        data = jnp.asarray(host.view(np.uint64).reshape(-1, 2))
    else:
        data = jnp.asarray(host.view(elem.np_dtype))
    child = Column(data=data, dtype=elem)
    return Column(offsets=(col.offsets // k).astype(jnp.int32),
                  validity=col.validity, dtype=dtype, children=(child,))


def _byte_view_table(table: Table) -> Table:
    """Replace LIST columns with their byte-view STRING forms (no-op for
    tables without lists)."""
    if not any(c.dtype is not None and c.dtype.is_list
               for c in table.columns):
        return table
    return Table([(nm, _list_byte_view(c)
                   if c.dtype is not None and c.dtype.is_list else c)
                  for nm, c in table.items()])


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class VarRowBlob:
    """A batch of variable-width rows.

    ``words``: flat uint32 stream of all rows back to back (8-byte-aligned
    rows); ``offsets``: int32 (n+1,) byte offsets of each row.
    """

    words: jax.Array          # uint32 (total_bytes // 4,)
    offsets: jax.Array        # int32 (n + 1,), multiples of 8

    def tree_flatten(self):
        return (self.words, self.offsets), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        words, offsets = children
        return cls(words=words, offsets=offsets)

    @property
    def num_rows(self) -> int:
        return int(self.offsets.shape[0]) - 1

    @property
    def nbytes(self) -> int:
        return int(self.words.shape[0]) * 4

    @property
    def data(self) -> np.ndarray:
        """Byte-exact host blob (little-endian word stream)."""
        return np.asarray(self.words).astype("<u4").view(np.uint8)

    @classmethod
    def from_host_bytes(cls, data: np.ndarray, offsets: np.ndarray
                        ) -> "VarRowBlob":
        arr = np.asarray(data)
        if arr.dtype not in (np.uint8, np.int8):
            raise ValueError("Only a list of bytes is supported as input")
        if arr.size % 4:
            raise ValueError("The layout of the data appears to be off")
        words = arr.view(np.uint8).view("<u4")
        return cls(words=jnp.asarray(words),
                   offsets=jnp.asarray(np.asarray(offsets, np.int32)))


def _string_cols(table: Table) -> dict[int, Column]:
    return {i: c for i, c in enumerate(table.columns)
            if c.offsets is not None}


def _row_var_geometry(layout: VarLayout, table: Table):
    """Per-row geometry (traced): field lengths, field starts (from row
    start), row sizes, row offsets."""
    n = table.num_rows
    lens = []
    for i in layout.var_cols:
        c = table.columns[i]
        ln = (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int32)
        if c.validity is not None:
            ln = jnp.where(c.validity, ln, 0)
        lens.append(ln)
    starts = []
    at = jnp.full(n, layout.fixed.row_size, jnp.int32)
    for ln in lens:
        starts.append(at)
        at = at + ln
    var_total = at - layout.fixed.row_size
    row_sizes = layout.fixed.row_size + ((var_total + 7) & ~7)
    # int64 offsets: a >2 GB total must surface for batching, not wrap
    # (int32 cumsum overflow would silently corrupt); chunked_cumsum
    # because whole-array cumsum is a compile/runtime cliff at millions of
    # rows (ops.common.chunked_cumsum docstring).
    from ..ops.common import chunked_cumsum
    row_offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int64), chunked_cumsum(row_sizes.astype(jnp.int64))])
    return lens, starts, row_sizes, row_offsets


@functools.lru_cache(maxsize=None)
def _var_packer(schema: tuple[DType, ...], total_words: int):
    """Jitted flat-word pack for one (schema, padded output size)."""
    layout = compute_var_layout(schema)
    Wf = layout.fixed.row_size // 4

    @jax.jit
    def pack(datas, valids, str_offsets, str_chars, row_offsets,
             lens, starts):
        n = row_offsets.shape[0] - 1
        # Fixed-part word image, with string slots as synthetic INT64
        # (length << 32 | offset-from-row-start) columns.
        fixed_datas = []
        masks = []
        vi = 0
        for i, dt in enumerate(schema):
            if dt.is_string:
                slot = (lens[vi].astype(jnp.uint64) << jnp.uint64(32)) | \
                    starts[vi].astype(jnp.uint64)
                fixed_datas.append(lax.bitcast_convert_type(slot, jnp.int64))
                vi += 1
            else:
                fixed_datas.append(datas[i])
            masks.append(valids[i])
        image = pack_words(layout.fixed, tuple(fixed_datas), tuple(masks))

        # Gather-assemble the flat word stream.
        word_off = row_offsets // 4                       # (n+1,)
        pos = jnp.arange(total_words, dtype=jnp.int32)
        row = jnp.clip(
            jnp.searchsorted(word_off, pos, side="right").astype(jnp.int32)
            - 1, 0, n - 1)
        wir = pos - jnp.take(word_off, row)               # word-in-row

        in_fixed = wir < Wf
        fixed_vals = image[jnp.clip(wir, 0, Wf - 1), row]

        # Variable-section bytes: 4 per word.
        base_byte = (wir - Wf) * 4                        # within var section
        acc = jnp.zeros(total_words, _U32)
        for k in range(4):
            v = base_byte + k                             # var-section offset
            byte = jnp.zeros(total_words, jnp.uint8)
            for j, i in enumerate(layout.var_cols):
                fstart = jnp.take(starts[j], row) - layout.fixed.row_size
                flen = jnp.take(lens[j], row)
                inside = (v >= fstart) & (v < fstart + flen)
                nc = str_chars[j].shape[0]
                if nc == 0:        # static: column has no characters at all
                    continue
                src = jnp.take(str_offsets[j], row) + (v - fstart)
                picked = jnp.take(str_chars[j], jnp.clip(src, 0, nc - 1))
                byte = jnp.where(inside, picked, byte)
            acc = acc | (byte.astype(_U32) << _U32(8 * k))
        out = jnp.where(in_fixed, fixed_vals, acc)
        # positions past the last row (output padding) are zero
        out = jnp.where(pos < word_off[-1], out, _U32(0))
        return out

    return layout, pack


def pack_var_rows(table: Table) -> VarRowBlob:
    """Serialize a table with string columns into one variable-width blob.

    One host sync (the total byte size — inherently data dependent, like
    the reference's batch sizing at row_conversion.cu:476-511).  Raises
    when the blob would exceed the 2**31-byte contract — batch first
    (``to_var_rows``).
    """
    from .layout import MAX_BATCH_BYTES
    compute_var_layout(tuple(table.schema()))     # validate BEFORE adapting
    table = _byte_view_table(table)
    schema = tuple(table.schema())
    layout = compute_var_layout(schema)
    if table.num_rows == 0:
        return VarRowBlob(words=jnp.zeros(0, _U32),
                          offsets=jnp.zeros(1, jnp.int32))
    lens, starts, row_sizes, row_offsets = _row_var_geometry(layout, table)
    total_bytes = int(row_offsets[-1])                # the host sync
    if total_bytes > MAX_BATCH_BYTES:
        raise ValueError(
            f"row blob would be {total_bytes} bytes (> 2**31-1); split into "
            f"batches via to_rows/to_var_rows")
    row_offsets = row_offsets.astype(jnp.int32)
    total_words = max(total_bytes // 4, 1)

    # Pad every data-dependent input shape to its pow2 class so the jitted
    # pack specializes per size class, not per batch (a batch stream must
    # not recompile — minutes each on TPU).  Padded rows have empty offset
    # ranges (repeated totals), so they contribute to no output word, and
    # the output is zeroed past the true total anyway.
    n = table.num_rows
    nb = _pow2(n)

    def pad_rows(arr, fill):
        if nb == n:
            return arr
        return jnp.concatenate([arr, jnp.full(nb - n, fill, arr.dtype)])

    _, pack = _var_packer(schema, _pow2(total_words))
    str_offsets, str_chars = [], []
    for i in layout.var_cols:
        c = table.columns[i]
        str_offsets.append(pad_rows(c.offsets[:-1].astype(jnp.int32), 0))
        cb = _pow2(max(int(c.data.shape[0]), 1))
        chars = c.data
        if chars.shape[0] < cb:
            chars = jnp.concatenate(
                [chars, jnp.zeros(cb - chars.shape[0], chars.dtype)])
        str_chars.append(chars)
    datas = tuple(pad_rows(c.data, jnp.zeros((), c.data.dtype))
                  if c.offsets is None else jnp.zeros(0, jnp.uint8)
                  for c in table.columns)
    valids = tuple(pad_rows(c.valid_mask(), False) for c in table.columns)
    ro_padded = (row_offsets if nb == n else jnp.concatenate(
        [row_offsets, jnp.full(nb - n, row_offsets[-1], jnp.int32)]))
    words = pack(datas, valids, tuple(str_offsets), tuple(str_chars),
                 ro_padded,
                 tuple(pad_rows(ln, 0) for ln in lens),
                 tuple(pad_rows(st, 0) for st in starts))
    return VarRowBlob(words=words[:total_words], offsets=row_offsets)


@functools.lru_cache(maxsize=None)
def _var_unpacker(schema: tuple[DType, ...], words_bucket: int,
                  rows_bucket: int, char_buckets: tuple[int, ...]):
    """Jitted unpack for one (schema, pow2-padded sizes) class.

    Keyed on the pow2 *row bucket*, not the exact row count, matching the
    pack side's size-class design: a stream of distinct blob sizes reuses
    one compiled program per class instead of recompiling per blob.  The
    caller pads ``row_offsets`` to the bucket (repeating the final offset)
    and passes ``row_live`` so the padded tail — whose gathered slot words
    are garbage — contributes zero string length and is sliced off on
    return.  Char buffers come back padded to their bucket; the caller
    slices to the exact counts it already synced.
    """
    layout = compute_var_layout(schema)
    Wf = layout.fixed.row_size // 4

    @jax.jit
    def unpack(words, row_offsets, row_live):
        from ..ops.common import chunked_cumsum
        word_off = row_offsets // 4
        # Fixed part: gather each row's fixed words into the (Wf, nb) image.
        idx = word_off[:-1][None, :] + jnp.arange(Wf, dtype=jnp.int32)[:, None]
        image = jnp.take(words, jnp.clip(idx, 0, max(words_bucket - 1, 0)))
        datas, valids = unpack_words(layout.fixed, image)

        # Parse string slots.
        outs = []
        for j, i in enumerate(layout.var_cols):
            slot = lax.bitcast_convert_type(datas[i], jnp.uint64)
            flen = (slot >> jnp.uint64(32)).astype(jnp.int32)
            foff = (slot & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32)
            flen = jnp.where(valids[i] & row_live, flen, 0)
            out_offsets = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 chunked_cumsum(flen)])
            # char c of the output buffer -> (row, intra) -> source byte.
            # Padded rows have zero length, so every cpos below the true
            # char total resolves to a live row.
            cpos = jnp.arange(char_buckets[j], dtype=jnp.int32)
            crow = jnp.clip(
                jnp.searchsorted(out_offsets, cpos,
                                 side="right").astype(jnp.int32) - 1,
                0, rows_bucket - 1)
            intra = cpos - jnp.take(out_offsets, crow)
            src_byte = (jnp.take(row_offsets[:-1], crow)
                        + jnp.take(foff, crow) + intra)
            w = jnp.take(words, jnp.clip(src_byte // 4, 0,
                                         max(words_bucket - 1, 0)))
            ch = ((w >> ((src_byte % 4).astype(_U32) * _U32(8)))
                  & _U32(0xFF)).astype(jnp.uint8)
            outs.append((out_offsets, ch))
        return datas, valids, outs

    return layout, unpack


def empty_var_table(schema: Sequence[DType],
                    names: Sequence[str]) -> Table:
    """A zero-row table for a (string-bearing) schema."""
    cols = []
    for name, dt in zip(names, schema):
        if dt.is_list:
            cols.append((name, Column(
                offsets=jnp.zeros(1, jnp.int32), dtype=dt,
                children=(Column(data=jnp.zeros(
                    (0, 2) if dt.element.is_two_word else 0,
                    dt.element.jnp_dtype), dtype=dt.element),))))
        elif dt.is_string:
            cols.append((name, Column(data=jnp.zeros(0, jnp.uint8),
                                      offsets=jnp.zeros(1, jnp.int32),
                                      dtype=STRING)))
        else:
            cols.append((name, Column(data=jnp.zeros(0, dt.jnp_dtype),
                                      dtype=dt)))
    return Table(cols)


def to_var_rows(table: Table, *, max_batch_bytes: int) -> list[VarRowBlob]:
    """Batched serialization: split so no blob exceeds ``max_batch_bytes``
    (reference contract RowConversion.java:32-48), in 32-row multiples
    where possible."""
    compute_var_layout(tuple(table.schema()))     # validate BEFORE adapting
    table = _byte_view_table(table)
    schema = tuple(table.schema())
    layout = compute_var_layout(schema)
    _, _, row_sizes, row_offsets = _row_var_geometry(layout, table)
    off = np.asarray(row_offsets)                    # the one host sync
    n = table.num_rows
    if n == 0 or off[-1] <= max_batch_bytes:
        return [pack_var_rows(table)]
    blobs = []
    start = 0
    while start < n:
        # widest batch from `start` under the cap, rounded to 32 rows
        end = int(np.searchsorted(off, off[start] + max_batch_bytes,
                                  side="right")) - 1
        end = max(start + 1, end)
        if end - start > 32 and end < n:
            end = start + (end - start) // 32 * 32
        idx = jnp.arange(start, min(end, n), dtype=jnp.int32)
        blobs.append(pack_var_rows(table.gather(idx)))
        start = min(end, n)
    return blobs


def unpack_var_rows(blob: VarRowBlob, schema: Sequence[DType],
                    names: Optional[Sequence[str]] = None) -> Table:
    """Rebuild a columnar table from a variable-width blob.

    Two host syncs (per-string-column char totals) — the inverse of the
    pack's size sync.
    """
    schema = tuple(schema)
    layout = compute_var_layout(schema)
    if names is None:
        names = [f"c{i}" for i in range(len(schema))]
    n = blob.num_rows
    total_words = int(blob.words.shape[0])
    if n == 0:
        return empty_var_table(schema, names)

    # Char totals per string column (host sync; data dependent).
    char_counts = []
    Wf = layout.fixed.row_size // 4
    word_off = blob.offsets // 4
    sums = []
    for j, i in enumerate(layout.var_cols):
        slot_word = layout.fixed.column_starts[i] // 4
        hi = jnp.take(blob.words,
                      jnp.clip(word_off[:-1] + slot_word + 1, 0,
                               max(total_words - 1, 0)))
        sums.append(jnp.sum(hi.astype(jnp.int64)))
    # Null rows' slots still carry length 0 (pack wrote them), so the raw
    # sums are exact.
    char_counts = tuple(int(s) for s in jax.device_get(sums)) if sums else ()

    words_bucket = _pow2(max(total_words, 1))
    rows_bucket = _pow2(n)
    char_buckets = tuple(_pow2(max(c, 1)) for c in char_counts)
    words = blob.words
    if words.shape[0] < words_bucket:
        words = jnp.concatenate(
            [words, jnp.zeros(words_bucket - words.shape[0], _U32)])
    # Pad offsets to the row bucket (repeat the final offset: empty ranges)
    # so one compiled unpack serves every blob in the size class.
    offsets = blob.offsets
    if n < rows_bucket:
        offsets = jnp.concatenate(
            [offsets, jnp.full(rows_bucket - n, offsets[-1], offsets.dtype)])
    row_live = jnp.arange(rows_bucket, dtype=jnp.int32) < jnp.int32(n)
    _, unpack = _var_unpacker(schema, words_bucket, rows_bucket, char_buckets)
    datas, valids, str_outs = unpack(words, offsets, row_live)

    columns = []
    si = 0
    for i, (name, dt) in enumerate(zip(names, schema)):
        if _is_var(dt):
            out_offsets, chars = str_outs[si]
            chars = chars[:char_counts[si]]
            si += 1
            validity = valids[i][:n]
            scol = Column(data=chars, offsets=out_offsets[:n + 1],
                          validity=validity, dtype=STRING)
            columns.append((name, _list_from_bytes(scol, dt)
                            if dt.is_list else scol))
        else:
            columns.append((name, Column(data=datas[i][:n],
                                         validity=valids[i][:n],
                                         dtype=dt)))
    return Table(columns)
