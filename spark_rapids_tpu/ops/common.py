"""Shared machinery for the eager ops layer.

The engine's execution model mirrors the reference system's (cuDF is an eager
GPU library driven by the Spark plugin): each op executes immediately, with
its pure compute expressed as jitted XLA programs cached per schema/shape.
Ops whose *output size* is data dependent (filter, join, distinct groups)
materialize one scalar count on host — the TPU analog of the reference's
host-side batching decisions (row_conversion.cu:476-511) — then run a
fixed-shape kernel.  XLA requires static shapes; recompilation is bounded by
bucketing such sizes to powers of two where it matters.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..column import Column


def pow2_bucket(n: int) -> int:
    """Round up to a power of two (minimum 1) to bound shape-recompiles."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def compact_indices(mask: jax.Array) -> jax.Array:
    """Indices of True entries, in order — the dynamic-shape boundary.

    One host sync for the count, then a stable argsort moves selected rows to
    the front (False sorts after True is arranged via key inversion).  This is
    the TPU replacement for stream-compaction scatters.
    """
    count = int(jnp.sum(mask))
    order = jnp.argsort(~mask, stable=True)
    return order[:count]


def adjacent_differs(data: jax.Array, validity=None) -> jax.Array:
    """For sorted raw arrays: mask[i] = row i differs from row i-1 (grouping
    equality: null == null, NaN == NaN per Spark/cuDF). mask[0] is True.

    Array-level form shared by the local engine and the distributed
    shard_map kernels (parallel.dist_ops) so grouping-equality semantics
    have exactly one definition."""
    neq = data[1:] != data[:-1]
    if jnp.issubdtype(data.dtype, jnp.floating):
        both_nan = (data[1:] != data[1:]) & (data[:-1] != data[:-1])
        neq = neq & ~both_nan
    if validity is not None:
        both_null = ~validity[1:] & ~validity[:-1]
        null_differs = validity[1:] != validity[:-1]
        neq = (neq & ~both_null) | null_differs
    return jnp.concatenate([jnp.ones(1, jnp.bool_), neq])


def null_safe_equal_adjacent(col: Column) -> jax.Array:
    """Column wrapper over :func:`adjacent_differs`."""
    return adjacent_differs(col.data, col.validity)


def null_safe_equal_at(ldata: jax.Array, lvalid, rdata: jax.Array, rvalid) -> jax.Array:
    """Elementwise grouping equality between two gathered key arrays
    (null == null, NaN == NaN — same semantics as :func:`adjacent_differs`)."""
    eq = ldata == rdata
    if jnp.issubdtype(ldata.dtype, jnp.floating):
        eq = eq | ((ldata != ldata) & (rdata != rdata))
    if lvalid is None and rvalid is None:
        return eq
    lv = jnp.ones(ldata.shape[0], jnp.bool_) if lvalid is None else lvalid
    rv = jnp.ones(rdata.shape[0], jnp.bool_) if rvalid is None else rvalid
    return jnp.where(lv & rv, eq, ~lv & ~rv)


def grouping_sort_operands(datas, valids) -> list[jax.Array]:
    """lax.sort key operands for GROUPING semantics (traceable).

    Two operands per key: a null rank (nulls first) and the value with
    NaNs canonicalized and null rows masked to zero — so equality among
    null rows is payload-independent (null == null) and NaN == NaN.  The
    single definition shared by the groupby and join kernels; the sort
    op's richer ordering options live in :func:`ops.sort.sort_operands`.
    """
    from .sort import _canonicalize_nan
    n = datas[0].shape[0]
    ops: list[jax.Array] = []
    for d, v in zip(datas, valids):
        rank = jnp.ones(n, jnp.uint8) if v is None else v.astype(jnp.uint8)
        val = _canonicalize_nan(d)
        if v is not None:
            val = jnp.where(v, val, jnp.zeros((), val.dtype))
        ops.append(rank)
        ops.append(val)
    return ops


def grouping_columns_with(cols: list[Column], *flag_lists):
    """:func:`grouping_columns` plus per-key flag lists (ascending,
    nulls_first, ...) kept aligned through the expansion: a key that
    expands into several columns (DECIMAL128's word pair) duplicates its
    flags onto every expanded column.  Returns
    ``(expanded_cols, *expanded_flag_lists)``."""
    out_cols: list[Column] = []
    out_flags: list[list] = [[] for _ in flag_lists]
    for i, col in enumerate(cols):
        expanded = grouping_columns([col])
        out_cols.extend(expanded)
        for j, flags in enumerate(flag_lists):
            out_flags[j].extend([flags[i]] * len(expanded))
    return (out_cols, *out_flags)


#: Rows per chunk for chunked (segmented) prefix scans.  62500 x 64
#: chunks measured best at 4M rows on v5e; shared by every scan below so
#: there is exactly one constant to retune.
SCAN_CHUNK_ROWS = 62500

_SCAN_COMBINES = {"add": jnp.add, "min": jnp.minimum, "max": jnp.maximum}


def chunked_segmented_scan(fields: dict, boundary) -> dict:
    """Inclusive segmented scan over every ``{name: (array, kind)}`` field
    (kinds: add/min/max), restarting where ``boundary`` is True;
    ``boundary=None`` statically selects the plain (unsegmented) scan —
    no boundary plumbing is traced at all.

    ONE ``lax.scan`` over row chunks carrying each field's running
    open-segment value; each chunk runs a local ``associative_scan`` and
    splices the carry in before its first boundary.  Whole-array
    ``associative_scan`` and ``jnp.cumsum`` at millions of rows measured
    minutes of XLA *compile* time (cumsum also ~435 ms/run) on v5e; the
    chunked form compiles in seconds and runs ~75 ms for four fields at
    4M rows (BASELINE.md).
    """
    kinds = {k: kind for k, (_, kind) in fields.items()}
    if boundary is None:
        return _chunked_plain_scan(fields, kinds)
    n = boundary.shape[0]
    B = min(SCAN_CHUNK_ROWS, max(n, 1))
    pad = -n % B
    npad = n + pad

    def padded(arr, fill):
        if pad == 0:
            return arr
        return jnp.concatenate([arr, jnp.full(pad, fill, arr.dtype)])

    b2 = padded(boundary, True).reshape(-1, B)
    v2 = {k: padded(arr, jnp.zeros((), arr.dtype)).reshape(-1, B)
          for k, (arr, _) in fields.items()}

    def local_op(a, b):
        va, ba = a
        vb, bb = b
        out = {k: jnp.where(bb, vb[k], _SCAN_COMBINES[kinds[k]](va[k], vb[k]))
               for k in va}
        return out, ba | bb

    def body(carry, xs):
        bc, vc = xs
        local, _ = jax.lax.associative_scan(local_op, (vc, bc))
        seen = jax.lax.associative_scan(jnp.logical_or, bc)
        out = {k: jnp.where(seen, local[k],
                            _SCAN_COMBINES[kinds[k]](carry[k], local[k]))
               for k in vc}
        return {k: out[k][-1] for k in out}, out

    init = {k: jnp.zeros((), arr.dtype) for k, (arr, _) in fields.items()}
    _, out = jax.lax.scan(body, init, (b2, v2))
    return {k: o.reshape(npad)[:n] for k, o in out.items()}


def _chunked_plain_scan(fields: dict, kinds: dict) -> dict:
    """Unsegmented variant: combine scan with one scalar carry per field."""
    n = next(iter(fields.values()))[0].shape[0]
    B = min(SCAN_CHUNK_ROWS, max(n, 1))
    pad = -n % B
    npad = n + pad

    def padded(arr):
        if pad == 0:
            return arr
        # zero is the identity for the only supported kind (add), and the
        # tail is sliced off before anyone reads it anyway
        return jnp.concatenate([arr, jnp.zeros(pad, arr.dtype)])

    v2 = {k: padded(arr).reshape(-1, B) for k, (arr, _) in fields.items()}

    def body(carry, vc):
        out = {k: _SCAN_COMBINES[kinds[k]](
            jax.lax.associative_scan(_SCAN_COMBINES[kinds[k]], vc[k]),
            carry[k]) for k in vc}
        return {k: out[k][-1] for k in out}, out

    init = {}
    for k, (arr, _) in fields.items():
        if kinds[k] == "add":
            init[k] = jnp.zeros((), arr.dtype)
        else:
            raise ValueError("unsegmented min/max scans need an identity; "
                             "pass an explicit boundary instead")
    _, out = jax.lax.scan(body, init, v2)
    return {k: o.reshape(npad)[:n] for k, o in out.items()}


def chunked_cumsum(x: jax.Array) -> jax.Array:
    """``jnp.cumsum(x)`` as the degenerate (no-boundary) chunked scan."""
    if x.shape[0] == 0:
        return x
    return chunked_segmented_scan({"s": (x, "add")}, None)["s"]


def distinct_run_heads(sorted_key_ops, sorted_val_ops, live=None):
    """(group boundary, distinct-value head) masks over rows sorted by
    (keys..., value) grouping operands.

    The single definition of nunique equality (null == null, NaN == NaN
    via the grouping operands; null VALUES excluded — cuDF default),
    shared by the eager groupby kernel and the plan compiler's sorted
    kernel.  A head is a live, valid row whose (key, value) pair differs
    from its predecessor.  ``live`` masks filtered-out rows (they must be
    sorted to the end by a leading rank operand).
    """
    n = sorted_val_ops[0].shape[0]
    key_boundary = jnp.zeros(n, jnp.bool_)
    for op in sorted_key_ops:
        key_boundary = key_boundary | adjacent_differs(op)
    if live is not None:
        key_boundary = key_boundary & live
    pair_boundary = key_boundary
    for op in sorted_val_ops:
        pair_boundary = pair_boundary | adjacent_differs(op)
    valid = sorted_val_ops[0] == 1          # value null-rank: 1 = valid
    if live is not None:
        valid = valid & live
    return key_boundary, pair_boundary & valid


def concat_columns(pieces: list[Column]) -> Column:
    """Concatenate columns of one dtype (cudf ``concatenate`` equivalent).

    Validity materializes to an explicit mask if any piece is nullable;
    string pieces concatenate char buffers and rebase offsets.
    """
    if not pieces:
        raise ValueError("concat_columns needs at least one column")
    dtype = pieces[0].dtype
    if any(p.dtype != dtype for p in pieces[1:]):
        raise TypeError(f"dtype mismatch: {[p.dtype for p in pieces]}")
    if dtype is not None and dtype.is_struct:
        validity = None
        if any(p.validity is not None for p in pieces):
            validity = jnp.concatenate([p.valid_mask() for p in pieces])
        children = tuple(
            concat_columns([p.children[i] for p in pieces])
            for i in range(len(dtype.fields)))
        return Column(validity=validity, dtype=dtype, children=children)
    if dtype is not None and dtype.is_list:
        validity = None
        if any(p.validity is not None for p in pieces):
            validity = jnp.concatenate([p.valid_mask() for p in pieces])
        child = concat_columns([p.children[0] for p in pieces])
        parts = [pieces[0].offsets]
        base = pieces[0].offsets[-1]
        for p in pieces[1:]:
            parts.append(p.offsets[1:] + base)
            base = base + p.offsets[-1]
        return Column(offsets=jnp.concatenate(parts), validity=validity,
                      dtype=dtype, children=(child,))
    if pieces[0].offsets is not None:
        from .strings import concat_columns as strings_concat
        return strings_concat(pieces)
    validity = None
    if any(p.validity is not None for p in pieces):
        validity = jnp.concatenate([p.valid_mask() for p in pieces])
    data = jnp.concatenate([p.data for p in pieces])
    return Column(data=data, validity=validity, dtype=dtype)


def concat_tables(tables: list) -> "Table":
    """Row-wise table concatenation (cudf ``concatenate(tables)``); schemas
    must match by name, order, and dtype."""
    from ..table import Table
    if not tables:
        raise ValueError("concat_tables needs at least one table")
    names = list(tables[0].names)
    for t in tables[1:]:
        if list(t.names) != names:
            raise ValueError(f"schema mismatch: {list(t.names)} vs {names}")
    return Table([(name, concat_columns([t[name] for t in tables]))
                  for name in names])


def grouping_columns(cols: list[Column]) -> list[Column]:
    """Map key columns to group/compare-friendly forms: STRING columns become
    lexicographically-ordered INT32 dictionary codes (validity preserved),
    DECIMAL128 expands into its (hi signed, lo unsigned) word pair — the
    pair's lexicographic order equals 128-bit signed order, so the
    multi-key machinery downstream needs no 128-bit compares — and
    everything else passes through.  May return MORE columns than given;
    callers use the result only as an ordered key set."""
    out = []
    for col in cols:
        if col.dtype is not None and col.dtype.is_nested:
            raise TypeError(
                f"{col.dtype!r} cannot be a grouping/sort/join key; key on "
                f"a struct field (col.field(name)) or a derived scalar "
                f"instead")
        if col.offsets is not None:
            from .strings import dictionary_encode
            codes, _ = dictionary_encode(col)
            out.append(codes)
        elif col.dtype.is_two_word:
            from .decimal128 import key_columns
            out.extend(key_columns(col))
        else:
            out.append(col)
    return out
