"""Distributed shuffle benchmark (BASELINE.json config #5 scaffolding).

Measures hash-partitioned ``all_to_all`` shuffle throughput plus the
shuffle-backed distributed group-by over a device mesh.  On a multi-chip
TPU slice the collective rides ICI; on a single-host dev box the same code
runs on the 8-device virtual CPU mesh (set SRT_BENCH_PLATFORM=cpu, the
default when only one real device exists) — numbers there are *shape*
validation, not bandwidth: the real sweep belongs on a pod slice.

Run: python benchmarks/bench_shuffle.py
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

ROWS_PER_DEV = 1_000_000
REPS = 5


def _setup_platform():
    import jax
    want = os.environ.get("SRT_BENCH_PLATFORM")
    if want is None and len(jax.devices()) < 2:
        # A 1-device mesh can't exercise all_to_all; fall back to the
        # virtual CPU mesh (must be configured before the backend spins up,
        # hence the re-exec).
        if "--reexec" not in sys.argv:
            env = dict(os.environ,
                       XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                                  " --xla_force_host_platform_device_count=8"),
                       JAX_PLATFORMS="cpu", SRT_BENCH_PLATFORM="cpu")
            os.execvpe(sys.executable,
                       [sys.executable, __file__, "--reexec"], env)
    if want:
        jax.config.update("jax_platforms", want)
    return jax


def main():
    jax = _setup_platform()
    import jax.numpy as jnp

    import spark_rapids_tpu as srt
    from spark_rapids_tpu.column import Column
    from spark_rapids_tpu.parallel.dist_ops import dist_groupby
    from spark_rapids_tpu.parallel.mesh import make_mesh, shard_table
    from spark_rapids_tpu.parallel.shuffle import shuffle

    devices = jax.devices()
    n_dev = len(devices)
    mesh = make_mesh(devices)
    n = ROWS_PER_DEV * n_dev
    rng = np.random.default_rng(3)

    table = srt.Table([
        ("key", Column.from_numpy(rng.integers(0, 1 << 20, n).astype(np.int64))),
        ("val", Column.from_numpy(rng.integers(0, 1000, n).astype(np.int64))),
    ])
    dist = shard_table(table, mesh)

    # Warm + chain through a data-dependent bump on the keys.
    out = shuffle(dist, mesh, ["key"])
    bump = int(np.asarray(out.table["key"].data).ravel()[0]) & 1
    t0 = time.perf_counter()
    for _ in range(REPS):
        shifted = shard_table(srt.Table([
            ("key", Column(data=table["key"].data + bump,
                           dtype=table["key"].dtype)),
            ("val", table["val"])]), mesh)
        out = shuffle(shifted, mesh, ["key"])
        bump = int(np.asarray(out.table["key"].data).ravel()[0]) & 1
    dt = (time.perf_counter() - t0) / REPS
    print(json.dumps({"metric": f"shuffle_all_to_all_{n_dev}dev",
                      "value": round(n / dt, 1), "unit": "rows/sec",
                      "devices": n_dev}))

    # Distributed group-by (shuffle + per-shard sorted-segment reduce).
    t0 = time.perf_counter()
    for _ in range(REPS):
        g = dist_groupby(dist, mesh, ["key"], [("val", "sum", "s"),
                                               ("val", "count", "c")])
        bump = int(np.asarray(g.table["c"].data).ravel()[0]) & 1
        dist = shard_table(srt.Table([
            ("key", Column(data=table["key"].data + bump,
                           dtype=table["key"].dtype)),
            ("val", table["val"])]), mesh)
    dt = (time.perf_counter() - t0) / REPS
    print(json.dumps({"metric": f"dist_groupby_{n_dev}dev",
                      "value": round(n / dt, 1), "unit": "rows/sec",
                      "devices": n_dev}))

    # Distributed PLAN (shuffle-free): per-shard filter + dense group-by,
    # (cells,)-sized psum merge — the exec-layer path (exec/dist.py).
    from spark_rapids_tpu.exec import col, plan
    small = srt.Table([
        ("key", Column.from_numpy(
            (np.asarray(table["key"].data) % 199).astype(np.int64))),
        ("val", table["val"]),
    ])
    p = (plan().filter(col("val") < 900)
         .groupby_agg(["key"], [("val", "sum", "s"), ("val", "count", "c")],
                      domains={"key": (0, 198)})
         .sort_by(["key"]))
    sdist = shard_table(small, mesh)
    out = p.run_dist(sdist, mesh)
    bump = int(out.to_pydict()["c"][0]) & 1
    t0 = time.perf_counter()
    for _ in range(REPS):
        sdist2 = shard_table(srt.Table([
            ("key", Column(data=small["key"].data * 1 + 0 * bump,
                           dtype=small["key"].dtype)),
            ("val", Column(data=small["val"].data + bump,
                           dtype=small["val"].dtype))]), mesh)
        out = p.run_dist(sdist2, mesh)
        bump = int(out.to_pydict()["c"][0]) & 1
    dt = (time.perf_counter() - t0) / REPS
    print(json.dumps({"metric": f"dist_plan_dense_groupby_{n_dev}dev",
                      "value": round(n / dt, 1), "unit": "rows/sec",
                      "devices": n_dev}))


if __name__ == "__main__":
    main()
