#!/bin/bash
# Nightly dependency-bump bot (shell half).
#
# The reference's submodule-sync bot bumps the vendored cudf pointer to
# remote HEAD, exits if unchanged, commits with signoff, runs the full GPU
# test suite, pushes a bot branch and hands off to the Python half which
# opens/updates a PR and squash-auto-merges only on green
# (reference: ci/submodule-sync.sh:41-100).  Here the "submodule" is the
# pinned JAX/XLA dependency surface: the runner environment is expected to
# have the candidate (latest) versions installed; this job re-pins to them,
# tests, and hands off.
#
# Env:  REF (target branch, default main), GITHUB_TOKEN, GITHUB_REPOSITORY.
set -ex

cd "$(dirname "$0")/.."
REF="${REF:-main}"
BOT_BRANCH="bot-deps-sync-${REF}"

git fetch origin "$REF"
git checkout -B "$BOT_BRANCH" "origin/$REF"

# Re-pin to the environment's installed versions; exit quietly if current.
python buildtools/pins-check --write
if git diff --quiet -- buildtools/pins.toml; then
    echo "deps-sync: pins already current; nothing to do"
    exit 0
fi

SUMMARY=$(git diff --unified=0 -- buildtools/pins.toml | grep '^[+-][a-z]' || true)
git add buildtools/pins.toml
git commit -s -m "Update dependency pins" -m "$SUMMARY"

# Full premerge suite against the new versions decides mergeability.
passed=true
./ci/premerge-build.sh || passed=false

git push -f origin "$BOT_BRANCH"
python .github/workflows/action-helper/python/deps-sync \
    --head "$BOT_BRANCH" --base "$REF" --passed "$passed" \
    --summary "$SUMMARY"
