"""TPC-DS-shaped schema and synthetic data generator.

The reference system's north-star workload is TPC-DS on Spark
(/root/repo/BASELINE.json: "distributed shuffle: full TPC-DS SF1000
99-query sweep"); the reference repo itself ships no query engine — the
queries arrive as Spark physical plans and the native library executes
their columnar fragments (SURVEY.md §0).  This module provides the data
half of that workload for the TPU engine: a scale-parameterized star
schema with TPC-DS's table shapes (three sales channels + returns facts,
conformed dimensions), realistic key skew, null fractions, and the
string/date/demographic attributes the query bank
(:mod:`.tpcds_queries`) filters on.

It is a *shape-faithful synthetic*, not dsdgen: per-table row counts
follow the spec's relative scaling but values are drawn from compact
vocabularies so that correctness oracles (pandas re-implementations in
tests/test_tpcds.py) stay tractable.  Column subsets cover what the
query bank touches; extending a query usually means adding a column
here first.

Scale parameter: ``sf_rows`` = store_sales row count.  The other tables
scale relative to it the way TPC-DS scales relative to SF.
"""

from __future__ import annotations

from dataclasses import dataclass, fields

import numpy as np

from ..column import Column
from ..dtypes import STRING
from ..table import Table

# -- vocabularies (compact stand-ins for dsdgen's) --------------------------

CATEGORIES = ("Books", "Electronics", "Home", "Jewelry", "Music",
              "Shoes", "Sports", "Women")
CLASSES = tuple(f"class{i:02d}" for i in range(16))
BRANDS = tuple(f"brand#{i:03d}" for i in range(50))
STATES = ("CA", "GA", "IL", "NY", "TX", "TN", "OH", "WA")
COUNTIES = tuple(f"{s} County {i}" for s in ("Fair", "Rich", "Walker",
                                             "Ziebach") for i in range(2))
CITIES = ("Midway", "Fairview", "Oak Grove", "Glendale", "Centerville",
          "Springdale", "Shiloh", "Pleasant Hill")
GENDERS = ("M", "F")
MARITAL = ("M", "S", "D", "W", "U")
EDUCATION = ("Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown")
BUY_POTENTIAL = (">10000", "5001-10000", "1001-5000", "501-1000",
                 "0-500", "Unknown")
DAY_NAMES = ("Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday")
FIRST_NAMES = tuple(f"First{i:03d}" for i in range(64))
LAST_NAMES = tuple(f"Last{i:03d}" for i in range(64))
COMPANIES = ("pri", "able", "ought", "eing", "bar", "cally")
SHIP_MODE_TYPES = ("EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY")
CARRIERS = ("UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU",
            "MSC", "LATVIAN", "DIAMOND")
COLORS = ("red", "green", "blue", "white", "black", "navy", "peach",
          "saddle", "ghost", "light", "powder", "dim", "smoke", "burlywood")
SIZES = ("small", "medium", "large", "extra large", "petite", "N/A")
UNITS = ("Each", "Dozen", "Case", "Pound", "Ounce", "Ton", "Gram", "Box")
CONTAINERS = ("Unknown", "Small Box", "Large Box", "Carton")
REASONS = tuple(f"reason {i}" for i in range(35))


@dataclass
class TpcdsData:
    """The generated star schema (every member is a :class:`Table`)."""

    store_sales: Table
    web_sales: Table
    catalog_sales: Table
    store_returns: Table
    web_returns: Table
    catalog_returns: Table
    inventory: Table
    date_dim: Table
    time_dim: Table
    item: Table
    store: Table
    customer: Table
    customer_address: Table
    customer_demographics: Table
    household_demographics: Table
    promotion: Table
    web_site: Table
    warehouse: Table
    ship_mode: Table
    call_center: Table
    income_band: Table
    reason: Table
    web_page: Table
    catalog_page: Table

    def names(self):
        return [f.name for f in fields(self)]


def _col_i64(rng, lo, hi, n, null_frac=0.0):
    data = rng.integers(lo, hi, n).astype(np.int64)
    validity = None if null_frac == 0 else rng.random(n) >= null_frac
    return Column.from_numpy(data, validity=validity)


def _col_f64(rng, lo, hi, n, null_frac=0.0):
    data = np.round(rng.uniform(lo, hi, n), 2)
    validity = None if null_frac == 0 else rng.random(n) >= null_frac
    return Column.from_numpy(data, validity=validity)


def _col_vocab(rng, vocab, n, null_frac=0.0, weights=None):
    idx = rng.choice(len(vocab), size=n, p=weights)
    vals = [vocab[i] for i in idx]
    if null_frac:
        nulls = rng.random(n) < null_frac
        vals = [None if dead else v for v, dead in zip(vals, nulls)]
    return Column.from_pylist(vals, STRING)


def _skewed_fk(rng, n_keys, n, null_frac=0.02):
    """Foreign keys with zipf-ish skew (hot dimension members), 1-based;
    a few percent null like dsdgen's nullable FK columns."""
    raw = rng.zipf(1.3, size=n)
    keys = ((raw - 1) % n_keys + 1).astype(np.int64)
    # blend with uniform so every key appears
    uni = rng.integers(1, n_keys + 1, n)
    take_uni = rng.random(n) < 0.5
    keys = np.where(take_uni, uni, keys)
    validity = None if null_frac == 0 else rng.random(n) >= null_frac
    return Column.from_numpy(keys, validity=validity)


#: first date_sk; date_sk walks day-by-day over two years (1998-1999),
#: mirroring the spec's Julian-style surrogate keys.
DATE_SK0 = 2450815
N_DAYS = 730


def _date_dim() -> Table:
    sk = np.arange(DATE_SK0, DATE_SK0 + N_DAYS, dtype=np.int64)
    day_index = np.arange(N_DAYS)
    year = np.where(day_index < 365, 1998, 1999).astype(np.int64)
    doy = day_index % 365
    # 12 months of 30 days + a 5-day remainder folded into December:
    # synthetic calendar, consistent across year/moy/dom/week/quarter.
    moy = np.minimum(doy // 30, 11).astype(np.int64) + 1
    dom = (doy - (moy - 1) * 30 + 1).astype(np.int64)
    dow = (day_index % 7).astype(np.int64)
    week_seq = (day_index // 7 + 1).astype(np.int64)
    qoy = ((moy - 1) // 3 + 1).astype(np.int64)
    month_seq = ((year - 1998) * 12 + moy - 1).astype(np.int64)
    return Table([
        ("d_date_sk", Column.from_numpy(sk)),
        ("d_year", Column.from_numpy(year)),
        ("d_moy", Column.from_numpy(moy)),
        ("d_dom", Column.from_numpy(dom)),
        ("d_dow", Column.from_numpy(dow)),
        ("d_qoy", Column.from_numpy(qoy)),
        ("d_week_seq", Column.from_numpy(week_seq)),
        ("d_month_seq", Column.from_numpy(month_seq)),
        ("d_day_name", Column.from_pylist(
            [DAY_NAMES[int(d)] for d in dow], STRING)),
    ])


def _time_dim() -> Table:
    # minute granularity: 1440 rows
    sk = np.arange(1440, dtype=np.int64)
    return Table([
        ("t_time_sk", Column.from_numpy(sk)),
        ("t_hour", Column.from_numpy((sk // 60).astype(np.int64))),
        ("t_minute", Column.from_numpy((sk % 60).astype(np.int64))),
    ])


def generate(sf_rows: int = 100_000, seed: int = 20260802) -> TpcdsData:
    """Generate the full schema at ``sf_rows`` store_sales rows.

    Table scaling mirrors TPC-DS's relative proportions: web/catalog
    sales at ~half the store channel, returns at ~10%, dimensions at
    spec-like cardinalities bounded below so small test scales still
    exercise every code path (all vocab members appear, every channel
    has rows)."""
    rng = np.random.default_rng(seed)

    n_ss = int(sf_rows)
    n_ws = max(n_ss // 2, 64)
    n_cs = max(n_ss // 2, 64)
    n_sr = max(n_ss // 10, 32)
    n_wr = max(n_ws // 10, 16)
    n_cr = max(n_cs // 10, 16)
    n_item = max(min(n_ss // 200, 18_000), 60)
    n_store = 12
    n_cust = max(min(n_ss // 20, 100_000), 200)
    n_addr = max(n_cust // 2, 100)
    n_cd = 7 * len(GENDERS) * len(MARITAL)       # full demographic cross
    n_hd = 7200
    n_promo = 30
    n_web = 6
    n_wh = 5
    n_sm = 20
    n_cc = 6
    n_ib = 20
    n_wp = 60
    n_cp = 60
    # inventory snapshots at monthly granularity (24 months x items x
    # warehouses); the spec's weekly cross is shape-equivalent but 4x
    # the rows for no extra query coverage
    n_inv_months = 24

    # -- dimensions --------------------------------------------------------
    date_dim = _date_dim()
    time_dim = _time_dim()

    isk = np.arange(1, n_item + 1, dtype=np.int64)
    cat_idx = rng.integers(0, len(CATEGORIES), n_item)
    brand_idx = rng.integers(0, len(BRANDS), n_item)
    class_idx = rng.integers(0, len(CLASSES), n_item)
    # id/name pairs are functionally dependent (as in dsdgen), so query
    # results can group by the compact id and attach the name after
    # aggregation with a small unique-key broadcast join.
    item = Table([
        ("i_item_sk", Column.from_numpy(isk)),
        ("i_item_id", Column.from_pylist(
            [f"ITEM{k:08d}" for k in isk], STRING)),
        ("i_brand_id", Column.from_numpy(brand_idx.astype(np.int64) + 1)),
        ("i_brand", Column.from_pylist(
            [BRANDS[i] for i in brand_idx], STRING)),
        ("i_category_id", Column.from_numpy(cat_idx.astype(np.int64) + 1)),
        ("i_category", Column.from_pylist(
            [CATEGORIES[i] for i in cat_idx], STRING)),
        ("i_class_id", Column.from_numpy(class_idx.astype(np.int64) + 1)),
        ("i_class", Column.from_pylist(
            [CLASSES[i] for i in class_idx], STRING)),
        # cyclic, not uniform-random: every manufacturer/manager id in
        # 1..99 exists at every scale, so fixed query parameters always
        # select a non-empty item subset
        ("i_manufact_id", Column.from_numpy((isk % 99 + 1).astype(np.int64))),
        ("i_manager_id", Column.from_numpy(
            ((isk * 7) % 99 + 1).astype(np.int64))),
        ("i_current_price", _col_f64(rng, 0.5, 100.0, n_item)),
        ("i_manufact", Column.from_pylist(
            [f"manufact#{int(k) % 99 + 1:03d}" for k in isk], STRING)),
        # attribute ids functionally dependent on the name columns (same
        # group-by-id-decode-after contract as brand/category/class)
        ("i_color_id", Column.from_numpy(
            ((isk * 3) % len(COLORS) + 1).astype(np.int64))),
        ("i_color", Column.from_pylist(
            [COLORS[(int(k) * 3) % len(COLORS)] for k in isk], STRING)),
        ("i_size", Column.from_pylist(
            [SIZES[int(k) % len(SIZES)] for k in isk], STRING)),
        ("i_units", Column.from_pylist(
            [UNITS[(int(k) * 5) % len(UNITS)] for k in isk], STRING)),
        ("i_container", Column.from_pylist(
            [CONTAINERS[int(k) % len(CONTAINERS)] for k in isk], STRING)),
        ("i_wholesale_cost", _col_f64(rng, 0.5, 80.0, n_item)),
    ])

    ssk = np.arange(1, n_store + 1, dtype=np.int64)
    store = Table([
        ("s_store_sk", Column.from_numpy(ssk)),
        ("s_store_id", Column.from_pylist(
            [f"STORE{k:04d}" for k in ssk], STRING)),
        ("s_store_name", Column.from_pylist(
            [f"store{k % 7}" for k in ssk], STRING)),
        ("s_state", _col_vocab(rng, STATES, n_store)),
        ("s_county", _col_vocab(rng, COUNTIES, n_store)),
        ("s_city_id", Column.from_numpy(
            (ssk % len(CITIES) + 1).astype(np.int64))),
        ("s_city", Column.from_pylist(
            [CITIES[int(k) % len(CITIES)] for k in ssk], STRING)),
        ("s_zip5", _col_i64(rng, 10_000, 99_999, n_store)),
        ("s_number_employees", _col_i64(rng, 200, 300, n_store)),
        ("s_gmt_offset", Column.from_numpy(
            rng.choice([-5.0, -6.0, -7.0, -8.0], n_store))),
    ])

    ask = np.arange(1, n_addr + 1, dtype=np.int64)
    ca_state_idx = rng.integers(0, len(STATES), n_addr)
    ca_city_idx = rng.integers(0, len(CITIES), n_addr)
    # state/city carry an id column functionally dependent on the name
    # (queries group/compare on the compact id and decode afterwards)
    customer_address = Table([
        ("ca_address_sk", Column.from_numpy(ask)),
        ("ca_state_id", Column.from_numpy(
            ca_state_idx.astype(np.int64) + 1)),
        ("ca_state", Column.from_pylist(
            [STATES[i] for i in ca_state_idx], STRING)),
        ("ca_county", _col_vocab(rng, COUNTIES, n_addr)),
        ("ca_city_id", Column.from_numpy(ca_city_idx.astype(np.int64) + 1)),
        ("ca_city", Column.from_pylist(
            [CITIES[i] for i in ca_city_idx], STRING)),
        ("ca_zip5", _col_i64(rng, 10_000, 99_999, n_addr)),
        ("ca_country", Column.from_pylist(
            ["United States"] * n_addr, STRING)),
        ("ca_gmt_offset", Column.from_numpy(
            rng.choice([-5.0, -6.0, -7.0, -8.0], n_addr))),
    ])

    csk = np.arange(1, n_cust + 1, dtype=np.int64)
    customer = Table([
        ("c_customer_sk", Column.from_numpy(csk)),
        ("c_customer_id", Column.from_pylist(
            [f"CUST{k:010d}" for k in csk], STRING)),
        ("c_current_addr_sk", _col_i64(rng, 1, n_addr + 1, n_cust)),
        ("c_current_cdemo_sk", _col_i64(rng, 1, n_cd + 1, n_cust,
                                        null_frac=0.02)),
        ("c_current_hdemo_sk", _col_i64(rng, 1, n_hd + 1, n_cust,
                                        null_frac=0.02)),
        ("c_first_name", _col_vocab(rng, FIRST_NAMES, n_cust,
                                    null_frac=0.02)),
        ("c_last_name", _col_vocab(rng, LAST_NAMES, n_cust,
                                   null_frac=0.02)),
        ("c_preferred_cust_flag", Column.from_pylist(
            ["Y" if k % 3 else "N" for k in csk], STRING)),
        ("c_birth_month", Column.from_numpy(
            (csk % 12 + 1).astype(np.int64))),
        ("c_birth_year", Column.from_numpy(
            (1930 + csk % 60).astype(np.int64))),
        ("c_salutation", Column.from_pylist(
            [("Mr.", "Mrs.", "Ms.", "Dr.", "Sir")[int(k) % 5]
             for k in csk], STRING)),
    ])

    # full cross of education x gender x marital (spec: cd is a cross
    # join of demographic attributes)
    cd_rows = [(e, g, m) for e in EDUCATION for g in GENDERS
               for m in MARITAL]
    customer_demographics = Table([
        ("cd_demo_sk", Column.from_numpy(
            np.arange(1, len(cd_rows) + 1, dtype=np.int64))),
        ("cd_education_status", Column.from_pylist(
            [r[0] for r in cd_rows], STRING)),
        ("cd_gender", Column.from_pylist([r[1] for r in cd_rows], STRING)),
        ("cd_marital_status", Column.from_pylist(
            [r[2] for r in cd_rows], STRING)),
        ("cd_purchase_estimate", Column.from_numpy(
            (np.arange(len(cd_rows)) % 10 * 1000 + 500).astype(np.int64))),
    ])

    hsk = np.arange(1, n_hd + 1, dtype=np.int64)
    household_demographics = Table([
        ("hd_demo_sk", Column.from_numpy(hsk)),
        ("hd_dep_count", Column.from_numpy((hsk % 10).astype(np.int64))),
        ("hd_vehicle_count", Column.from_numpy(
            (hsk % 6 - 1).astype(np.int64))),
        ("hd_buy_potential", Column.from_pylist(
            [BUY_POTENTIAL[int(k) % len(BUY_POTENTIAL)] for k in hsk],
            STRING)),
        ("hd_income_band_sk", Column.from_numpy(
            (hsk % n_ib + 1).astype(np.int64))),
    ])

    psk = np.arange(1, n_promo + 1, dtype=np.int64)
    promotion = Table([
        ("p_promo_sk", Column.from_numpy(psk)),
        ("p_channel_email", Column.from_pylist(
            ["N" if k % 5 else "Y" for k in psk], STRING)),
        ("p_channel_event", Column.from_pylist(
            ["N" if k % 3 else "Y" for k in psk], STRING)),
        ("p_channel_dmail", Column.from_pylist(
            ["N" if k % 2 else "Y" for k in psk], STRING)),
    ])

    wsk = np.arange(1, n_web + 1, dtype=np.int64)
    web_site = Table([
        ("web_site_sk", Column.from_numpy(wsk)),
        ("web_company_name", Column.from_pylist(
            [COMPANIES[int(k) % len(COMPANIES)] for k in wsk], STRING)),
        ("web_name", Column.from_pylist(
            [f"site_{int(k)}" for k in wsk], STRING)),
    ])

    whk = np.arange(1, n_wh + 1, dtype=np.int64)
    warehouse = Table([
        ("w_warehouse_sk", Column.from_numpy(whk)),
        ("w_state", _col_vocab(rng, STATES, n_wh)),
        ("w_warehouse_name", Column.from_pylist(
            [f"Warehouse {k}" for k in whk], STRING)),
        ("w_warehouse_sq_ft", _col_i64(rng, 50_000, 1_000_000, n_wh)),
        ("w_county", _col_vocab(rng, COUNTIES, n_wh)),
    ])

    smk = np.arange(1, n_sm + 1, dtype=np.int64)
    ship_mode = Table([
        ("sm_ship_mode_sk", Column.from_numpy(smk)),
        # sm_type_id functionally determines sm_type (group-by-id contract)
        ("sm_type_id", Column.from_numpy(
            (smk % len(SHIP_MODE_TYPES) + 1).astype(np.int64))),
        ("sm_type", Column.from_pylist(
            [SHIP_MODE_TYPES[int(k) % len(SHIP_MODE_TYPES)] for k in smk],
            STRING)),
        ("sm_carrier", Column.from_pylist(
            [CARRIERS[int(k) % len(CARRIERS)] for k in smk], STRING)),
    ])

    cck = np.arange(1, n_cc + 1, dtype=np.int64)
    call_center = Table([
        ("cc_call_center_sk", Column.from_numpy(cck)),
        ("cc_name", Column.from_pylist(
            [f"call center {k}" for k in cck], STRING)),
        ("cc_county", Column.from_pylist(
            [COUNTIES[int(k) % len(COUNTIES)] for k in cck], STRING)),
        ("cc_manager", _col_vocab(rng, LAST_NAMES, n_cc)),
    ])

    ibk = np.arange(1, n_ib + 1, dtype=np.int64)
    income_band = Table([
        ("ib_income_band_sk", Column.from_numpy(ibk)),
        ("ib_lower_bound", Column.from_numpy(
            ((ibk - 1) * 10_000).astype(np.int64))),
        ("ib_upper_bound", Column.from_numpy(
            (ibk * 10_000).astype(np.int64))),
    ])

    rk = np.arange(1, len(REASONS) + 1, dtype=np.int64)
    reason = Table([
        ("r_reason_sk", Column.from_numpy(rk)),
        ("r_reason_desc", Column.from_pylist(list(REASONS), STRING)),
    ])

    wpk = np.arange(1, n_wp + 1, dtype=np.int64)
    web_page = Table([
        ("wp_web_page_sk", Column.from_numpy(wpk)),
        ("wp_char_count", Column.from_numpy(
            (3000 + (wpk * 97) % 3000).astype(np.int64))),
    ])

    cpk = np.arange(1, n_cp + 1, dtype=np.int64)
    catalog_page = Table([
        ("cp_catalog_page_sk", Column.from_numpy(cpk)),
        ("cp_catalog_page_id", Column.from_pylist(
            [f"CPAGE{k:06d}" for k in cpk], STRING)),
    ])

    # -- facts -------------------------------------------------------------
    def sales_dates(n):
        return Column.from_numpy(
            rng.integers(DATE_SK0, DATE_SK0 + N_DAYS, n).astype(np.int64),
            validity=rng.random(n) >= 0.01)

    qty = lambda n: _col_i64(rng, 1, 100, n, null_frac=0.04)
    price = lambda n: _col_f64(rng, 1.0, 300.0, n, null_frac=0.04)

    store_sales = Table([
        ("ss_sold_date_sk", sales_dates(n_ss)),
        ("ss_sold_time_sk", _col_i64(rng, 0, 1440, n_ss, null_frac=0.01)),
        ("ss_item_sk", _skewed_fk(rng, n_item, n_ss, null_frac=0.0)),
        ("ss_customer_sk", _skewed_fk(rng, n_cust, n_ss)),
        ("ss_cdemo_sk", _skewed_fk(rng, n_cd, n_ss)),
        ("ss_hdemo_sk", _skewed_fk(rng, n_hd, n_ss)),
        ("ss_addr_sk", _skewed_fk(rng, n_addr, n_ss)),
        ("ss_store_sk", _skewed_fk(rng, n_store, n_ss)),
        ("ss_promo_sk", _skewed_fk(rng, n_promo, n_ss)),
        ("ss_ticket_number", _col_i64(rng, 1, max(n_ss // 3, 2), n_ss)),
        ("ss_quantity", qty(n_ss)),
        ("ss_sales_price", price(n_ss)),
        ("ss_list_price", price(n_ss)),
        ("ss_ext_sales_price", price(n_ss)),
        ("ss_ext_discount_amt", _col_f64(rng, 0.0, 80.0, n_ss,
                                         null_frac=0.04)),
        ("ss_ext_wholesale_cost", price(n_ss)),
        ("ss_ext_list_price", price(n_ss)),
        ("ss_ext_tax", _col_f64(rng, 0.0, 25.0, n_ss, null_frac=0.04)),
        ("ss_coupon_amt", _col_f64(rng, 0.0, 50.0, n_ss, null_frac=0.04)),
        ("ss_net_profit", _col_f64(rng, -100.0, 200.0, n_ss,
                                   null_frac=0.04)),
        ("ss_net_paid", price(n_ss)),
    ])

    web_sales = Table([
        ("ws_sold_date_sk", sales_dates(n_ws)),
        ("ws_ship_date_sk", sales_dates(n_ws)),
        ("ws_item_sk", _skewed_fk(rng, n_item, n_ws, null_frac=0.0)),
        ("ws_bill_customer_sk", _skewed_fk(rng, n_cust, n_ws)),
        ("ws_bill_addr_sk", _skewed_fk(rng, n_addr, n_ws)),
        ("ws_web_site_sk", _skewed_fk(rng, n_web, n_ws, null_frac=0.0)),
        ("ws_warehouse_sk", _skewed_fk(rng, n_wh, n_ws, null_frac=0.0)),
        ("ws_order_number", _col_i64(rng, 1, max(n_ws // 4, 2), n_ws)),
        ("ws_quantity", qty(n_ws)),
        ("ws_ext_sales_price", price(n_ws)),
        ("ws_ext_discount_amt", _col_f64(rng, 0.0, 80.0, n_ws,
                                         null_frac=0.04)),
        ("ws_ext_ship_cost", _col_f64(rng, 0.0, 60.0, n_ws,
                                      null_frac=0.04)),
        ("ws_net_profit", _col_f64(rng, -100.0, 200.0, n_ws,
                                   null_frac=0.04)),
        ("ws_net_paid", price(n_ws)),
        ("ws_sold_time_sk", _col_i64(rng, 0, 1440, n_ws, null_frac=0.01)),
        ("ws_ship_mode_sk", _skewed_fk(rng, n_sm, n_ws, null_frac=0.0)),
        ("ws_web_page_sk", _skewed_fk(rng, n_wp, n_ws, null_frac=0.0)),
        ("ws_promo_sk", _skewed_fk(rng, n_promo, n_ws)),
        ("ws_ship_customer_sk", _skewed_fk(rng, n_cust, n_ws,
                                           null_frac=0.05)),
        ("ws_ext_list_price", price(n_ws)),
        ("ws_ext_wholesale_cost", price(n_ws)),
        ("ws_sales_price", price(n_ws)),
        ("ws_list_price", price(n_ws)),
        ("ws_ship_addr_sk", _skewed_fk(rng, n_addr, n_ws)),
    ])

    catalog_sales = Table([
        ("cs_sold_date_sk", sales_dates(n_cs)),
        ("cs_item_sk", _skewed_fk(rng, n_item, n_cs, null_frac=0.0)),
        ("cs_bill_customer_sk", _skewed_fk(rng, n_cust, n_cs)),
        ("cs_bill_cdemo_sk", _skewed_fk(rng, n_cd, n_cs)),
        ("cs_promo_sk", _skewed_fk(rng, n_promo, n_cs)),
        ("cs_quantity", qty(n_cs)),
        ("cs_list_price", price(n_cs)),
        ("cs_sales_price", price(n_cs)),
        ("cs_coupon_amt", _col_f64(rng, 0.0, 50.0, n_cs, null_frac=0.04)),
        ("cs_ext_sales_price", price(n_cs)),
        ("cs_net_profit", _col_f64(rng, -100.0, 200.0, n_cs,
                                   null_frac=0.04)),
        ("cs_order_number", _col_i64(rng, 1, max(n_cs // 4, 2), n_cs)),
        ("cs_warehouse_sk", _skewed_fk(rng, n_wh, n_cs, null_frac=0.03)),
        ("cs_ship_date_sk", sales_dates(n_cs)),
        ("cs_ship_mode_sk", _skewed_fk(rng, n_sm, n_cs, null_frac=0.0)),
        ("cs_call_center_sk", _skewed_fk(rng, n_cc, n_cs, null_frac=0.0)),
        ("cs_ship_addr_sk", _skewed_fk(rng, n_addr, n_cs)),
        ("cs_bill_addr_sk", _skewed_fk(rng, n_addr, n_cs)),
        ("cs_ship_customer_sk", _skewed_fk(rng, n_cust, n_cs,
                                           null_frac=0.05)),
        ("cs_ext_discount_amt", _col_f64(rng, 0.0, 80.0, n_cs,
                                         null_frac=0.04)),
        ("cs_ext_ship_cost", _col_f64(rng, 0.0, 60.0, n_cs,
                                      null_frac=0.04)),
        ("cs_ext_list_price", price(n_cs)),
        ("cs_ext_wholesale_cost", price(n_cs)),
        ("cs_sold_time_sk", _col_i64(rng, 0, 1440, n_cs, null_frac=0.01)),
        ("cs_catalog_page_sk", _skewed_fk(rng, n_cp, n_cs, null_frac=0.0)),
        ("cs_net_paid", price(n_cs)),
    ])

    # -- returns: derived from sales rows (dsdgen's referential contract:
    # every return references a real sale, so composite joins on
    # (ticket/order, item, customer) actually match and sale-to-return
    # lags are meaningful) --------------------------------------------------

    def _take(tbl, name, idx):
        c = tbl[name]
        vals = np.asarray(c.data)[idx]
        valid = None if c.validity is None else np.asarray(c.validity)[idx]
        return vals, valid

    def _ret_dates(src_dates, src_valid, n):
        """Returned date = sold date + a 1..119-day lag (clipped to the
        calendar), nulled at the same ~1% rate as sales dates; a return
        whose source sale has a null sold date gets a null returned date
        too (dsdgen derives the return date from the sale date)."""
        lag = rng.integers(1, 120, n)
        base = (src_dates if src_valid is None
                else np.where(src_valid, src_dates, DATE_SK0))
        dates = np.minimum(base + lag, DATE_SK0 + N_DAYS - 1)
        validity = rng.random(n) >= 0.01
        if src_valid is not None:
            validity &= src_valid
        return Column.from_numpy(dates.astype(np.int64), validity=validity)

    sr_idx = rng.integers(0, n_ss, n_sr)
    sr_item, _ = _take(store_sales, "ss_item_sk", sr_idx)
    sr_tkt, _ = _take(store_sales, "ss_ticket_number", sr_idx)
    sr_cust, sr_cust_m = _take(store_sales, "ss_customer_sk", sr_idx)
    sr_store, sr_store_m = _take(store_sales, "ss_store_sk", sr_idx)
    sr_sold, sr_sold_m = _take(store_sales, "ss_sold_date_sk", sr_idx)
    store_returns = Table([
        ("sr_returned_date_sk", _ret_dates(sr_sold, sr_sold_m, n_sr)),
        ("sr_customer_sk", Column.from_numpy(sr_cust,
                                             validity=sr_cust_m)),
        ("sr_store_sk", Column.from_numpy(sr_store, validity=sr_store_m)),
        ("sr_item_sk", Column.from_numpy(sr_item)),
        ("sr_ticket_number", Column.from_numpy(sr_tkt)),
        ("sr_return_amt", _col_f64(rng, 0.5, 200.0, n_sr,
                                   null_frac=0.02)),
        ("sr_return_quantity", qty(n_sr)),
        ("sr_reason_sk", _skewed_fk(rng, len(REASONS), n_sr,
                                    null_frac=0.02)),
        ("sr_net_loss", _col_f64(rng, 0.5, 150.0, n_sr, null_frac=0.02)),
        ("sr_cdemo_sk", _skewed_fk(rng, n_cd, n_sr)),
        ("sr_return_time_sk", _col_i64(rng, 0, 1440, n_sr,
                                       null_frac=0.01)),
    ])

    wr_idx = rng.integers(0, n_ws, n_wr)
    wr_ord, _ = _take(web_sales, "ws_order_number", wr_idx)
    wr_item, _ = _take(web_sales, "ws_item_sk", wr_idx)
    wr_cust, wr_cust_m = _take(web_sales, "ws_bill_customer_sk", wr_idx)
    wr_sold, wr_sold_m = _take(web_sales, "ws_sold_date_sk", wr_idx)
    web_returns = Table([
        ("wr_order_number", Column.from_numpy(wr_ord)),
        ("wr_returned_date_sk", _ret_dates(wr_sold, wr_sold_m, n_wr)),
        ("wr_return_amt", _col_f64(rng, 0.5, 200.0, n_wr,
                                   null_frac=0.02)),
        ("wr_item_sk", Column.from_numpy(wr_item)),
        ("wr_returning_customer_sk", Column.from_numpy(
            wr_cust, validity=wr_cust_m)),
        ("wr_returning_addr_sk", _skewed_fk(rng, n_addr, n_wr)),
        ("wr_refunded_cdemo_sk", _skewed_fk(rng, n_cd, n_wr)),
        ("wr_refunded_addr_sk", _skewed_fk(rng, n_addr, n_wr)),
        ("wr_reason_sk", _skewed_fk(rng, len(REASONS), n_wr,
                                    null_frac=0.02)),
        ("wr_net_loss", _col_f64(rng, 0.5, 150.0, n_wr, null_frac=0.02)),
        ("wr_return_quantity", qty(n_wr)),
    ])

    cr_idx = rng.integers(0, n_cs, n_cr)
    cr_ord, _ = _take(catalog_sales, "cs_order_number", cr_idx)
    cr_item, _ = _take(catalog_sales, "cs_item_sk", cr_idx)
    cr_cust, cr_cust_m = _take(catalog_sales, "cs_bill_customer_sk",
                               cr_idx)
    cr_cc, cr_cc_m = _take(catalog_sales, "cs_call_center_sk", cr_idx)
    cr_page, cr_page_m = _take(catalog_sales, "cs_catalog_page_sk",
                               cr_idx)
    cr_sold, cr_sold_m = _take(catalog_sales, "cs_sold_date_sk", cr_idx)
    catalog_returns = Table([
        ("cr_order_number", Column.from_numpy(cr_ord)),
        ("cr_item_sk", Column.from_numpy(cr_item)),
        ("cr_returned_date_sk", _ret_dates(cr_sold, cr_sold_m, n_cr)),
        ("cr_return_amount", _col_f64(rng, 0.5, 200.0, n_cr,
                                      null_frac=0.02)),
        ("cr_return_quantity", qty(n_cr)),
        ("cr_net_loss", _col_f64(rng, 0.5, 150.0, n_cr, null_frac=0.02)),
        ("cr_returning_customer_sk", Column.from_numpy(
            cr_cust, validity=cr_cust_m)),
        ("cr_returning_addr_sk", _skewed_fk(rng, n_addr, n_cr)),
        ("cr_call_center_sk", Column.from_numpy(cr_cc, validity=cr_cc_m)),
        ("cr_catalog_page_sk", Column.from_numpy(cr_page,
                                                 validity=cr_page_m)),
        ("cr_reason_sk", _skewed_fk(rng, len(REASONS), n_cr,
                                    null_frac=0.02)),
    ])

    # inventory: full (month x item x warehouse) cross, snapshot on the
    # first day of each synthetic 30-day month
    inv_date = DATE_SK0 + 30 * np.arange(n_inv_months, dtype=np.int64)
    inv_d, inv_i, inv_w = np.meshgrid(
        inv_date, np.arange(1, n_item + 1, dtype=np.int64),
        np.arange(1, n_wh + 1, dtype=np.int64), indexing="ij")
    n_inv = inv_d.size
    inventory = Table([
        ("inv_date_sk", Column.from_numpy(inv_d.ravel())),
        ("inv_item_sk", Column.from_numpy(inv_i.ravel())),
        ("inv_warehouse_sk", Column.from_numpy(inv_w.ravel())),
        ("inv_quantity_on_hand", _col_i64(rng, 0, 1000, n_inv,
                                          null_frac=0.02)),
    ])

    return TpcdsData(
        store_sales=store_sales, web_sales=web_sales,
        catalog_sales=catalog_sales, store_returns=store_returns,
        web_returns=web_returns, catalog_returns=catalog_returns,
        inventory=inventory, date_dim=date_dim, time_dim=time_dim,
        item=item, store=store, customer=customer,
        customer_address=customer_address,
        customer_demographics=customer_demographics,
        household_demographics=household_demographics,
        promotion=promotion, web_site=web_site, warehouse=warehouse,
        ship_mode=ship_mode, call_center=call_center,
        income_band=income_band, reason=reason, web_page=web_page,
        catalog_page=catalog_page)
