"""``python -m spark_rapids_tpu.obs`` — console tooling over obs state.

``top``
    htop-style live query view: polls the in-process live registry
    (obs/live.py) or, with ``--url``, a remote exporter's ``/queries``
    endpoint (obs/server.py) and redraws a console table of in-flight
    queries: phase, batches done / in-flight, rows/sec, ICI bytes, last
    recovery rung, and one progress bar per shard.  ``--once`` prints a
    single frame (scripts, CI, docs); default is a 1 Hz refresh until
    Ctrl-C.
``doctor <bundle.json | fingerprint>``
    postmortem analysis (obs/doctor.py): rank what failed or got slow
    in one bundle — or a plan fingerprint's newest history record —
    against the same-fingerprint history baseline, and print the
    verdict.  Exits 0 whenever a verdict was produced.

Rendering is a pure function of the ``/queries`` JSON payload
(:func:`render_top`), so tests drive it with synthetic snapshots and the
remote and local paths share one code path.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import List, Optional

_BAR_WIDTH = 24


def _human(n: float) -> str:
    for unit in ("", "K", "M", "G", "T"):
        if abs(n) < 1000:
            return f"{n:.0f}{unit}" if unit else f"{n:.0f}"
        n /= 1000.0
    return f"{n:.0f}P"


def _bar(done: int, total: int, width: int = _BAR_WIDTH) -> str:
    if total <= 0:
        return "[" + "·" * width + "]"
    filled = min(width, int(round(width * done / total)))
    return "[" + "#" * filled + "-" * (width - filled) + "]"


def _fmt_query(q: dict) -> List[str]:
    eta = q.get("eta_seconds")
    lines = [
        "  q{qid:<5} {mode:<12} {phase:<12} {elapsed:>8.1f}s "
        "{done:>5}/{total:<5} inflight={inflight:<2} "
        "{rps:>9} rows/s  ici={ici:>6}B  hbm={hbm:>6}B{eta}".format(
            qid=q["query_id"], mode=q["mode"], phase=q["phase"],
            elapsed=q["elapsed_seconds"], done=q["batches_done"],
            total=q["total_batches"] or "?", inflight=q["inflight"],
            rps=_human(q["rows_per_sec"]), ici=_human(q["ici_bytes"]),
            hbm=_human(q["hbm_peak_bytes"]),
            eta=f"  eta={eta:.0f}s" if eta else "")]
    rung = q["recovery"]["last_rung"]
    if rung:
        lines.append(f"         recovery: {rung} "
                     f"({q['recovery']['count']} rungs)")
    shard_batches = q.get("shard_batches") or {}
    if shard_batches:
        total = max(q["batches_in"], max(shard_batches.values()), 1)
        for shard, done in sorted(shard_batches.items(),
                                  key=lambda kv: int(kv[0])):
            lines.append(f"         shard {int(shard):>2} "
                         f"{_bar(done, total)} {done}/{total}")
    return lines


def render_top(snap: dict, source: str = "local") -> str:
    """One frame of the ``top`` view from a ``/queries`` payload."""
    in_flight = snap.get("in_flight", [])
    queued = snap.get("queued", [])
    recent = snap.get("recent", [])
    ts = time.strftime("%H:%M:%S",
                       time.localtime(snap.get("unix_time", time.time())))
    lines = [f"srt top — {source} pid={snap.get('pid', '?')} {ts}  "
             f"running={len(in_flight)} queued={len(queued)} "
             f"recent={len(recent)}"]
    if in_flight:
        lines.append("in-flight:")
        for q in in_flight:
            lines.extend(_fmt_query(q))
    else:
        lines.append("in-flight: (none)")
    if queued:
        lines.append("queued:")
        for q in queued[:8]:
            lines.append(
                "  q{qid:<5} {mode:<12} {status:<8} waiting "
                "{waited:>6.1f}s  est_hbm={est} fp={fp}".format(
                    qid=q.get("query_id", "?"), mode=q.get("mode", "?"),
                    status=q.get("status", "?"),
                    waited=q.get("queued_seconds", 0.0),
                    est=q.get("estimate_hbm_bytes", 0),
                    fp=q.get("fingerprint", "")))
    if recent:
        lines.append("recent:")
        for q in recent[-8:]:
            lines.append(
                "  q{qid:<5} {mode:<12} {status:<8} {elapsed:>8.1f}s "
                "{batches:>5} batches {rows:>10} rows out".format(
                    qid=q["query_id"], mode=q["mode"], status=q["status"],
                    elapsed=q["elapsed_seconds"],
                    batches=q["batches_done"], rows=q["rows_out"]))
    return "\n".join(lines)


def _fetch(url: str) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/queries",
                                timeout=5) as resp:
        return json.loads(resp.read().decode())


def _snapshot(url: Optional[str]) -> dict:
    if url is not None:
        return _fetch(url)
    from . import live
    return live.snapshot_all()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.obs",
        description="Console views over the live-query registry.")
    sub = parser.add_subparsers(dest="command")
    top = sub.add_parser("top", help="htop-style live query table")
    top.add_argument("--url", default=None,
                     help="remote exporter base URL (e.g. "
                          "http://127.0.0.1:9465); default: the local "
                          "in-process registry")
    top.add_argument("--interval", type=float, default=1.0,
                     help="refresh period in seconds (default 1.0)")
    top.add_argument("--once", action="store_true",
                     help="print one frame and exit")
    doctor = sub.add_parser(
        "doctor", help="explain a failed/slow query from its postmortem "
                       "bundle or plan fingerprint")
    doctor.add_argument("target",
                        help="path to a postmortem bundle JSON "
                             "(SRT_BUNDLE_DIR) or a plan fingerprint "
                             "with history records")
    doctor.add_argument("--history", default=None,
                        help="metrics-history JSONL for the baseline "
                             "(default: SRT_METRICS_HISTORY)")
    args = parser.parse_args(argv)
    if args.command == "doctor":
        from .doctor import main as doctor_main
        return doctor_main(args.target, history_path=args.history)
    if args.command != "top":
        parser.print_help()
        return 2
    source = args.url or "local"
    try:
        while True:
            frame = render_top(_snapshot(args.url), source=source)
            if args.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
