"""IO layer: Arrow interop, Parquet scan/write, native page decoder."""

from .arrow import from_arrow, from_arrow_array, to_arrow, to_arrow_array
from .feed import prefetch, scan_parquet
from .parquet import read_parquet, write_parquet
from .parquet_native import read_parquet_native

__all__ = [
    "from_arrow",
    "from_arrow_array",
    "prefetch",
    "read_parquet",
    "read_parquet_native",
    "scan_parquet",
    "to_arrow",
    "to_arrow_array",
    "write_parquet",
]
