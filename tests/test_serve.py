"""Concurrent query serving layer (spark_rapids_tpu/serve/).

The contracts pinned here:

1. **Bit-identity under concurrency** — results served through
   ``QuerySession.submit`` (one-shot and streaming, mixed) are
   bit-identical to the same plans run sequentially on the bare
   executors, including while the recovery ladder is rescuing a
   fault-injected neighbor.
2. **Shared compile caches are race-free** — N threads hammering one
   signature through ``_lru_lookup`` build exactly once; concurrent
   distinct-key inserts keep size + eviction accounting exact.
3. **Live registry scrapes don't race writers** — many queries mutating
   their records while ``/queries``/``/metrics`` snapshot concurrently
   never corrupt a snapshot.
4. **Admission control** — over-budget estimates queue (then run) or are
   rejected up front through the ticket; claims release on completion.
5. **Result cache** — repeated fingerprint + identical input short-
   circuits bit-identically; iterator feeds never cache.
6. **Knob validation** — the four ``SRT_SERVE_*``/``SRT_RESULT_CACHE``
   accessors validate without jax.
"""

import threading
import time
from collections import OrderedDict

import numpy as np
import pytest

from spark_rapids_tpu import Column, Table
from spark_rapids_tpu import config
from spark_rapids_tpu.exec import col, plan, run_plan_stream
from spark_rapids_tpu.obs import live, registry, server
from spark_rapids_tpu.resilience import recovery_stats, reset_faults
from spark_rapids_tpu.serve import (AdmissionController, AdmissionRejected,
                                    QuerySession, ResultCache, input_digest)
from spark_rapids_tpu.serve.scheduler import _FairGate


@pytest.fixture
def metrics_on(monkeypatch):
    monkeypatch.setenv("SRT_METRICS", "1")
    registry().reset()
    yield
    registry().reset()


@pytest.fixture
def faults(monkeypatch):
    monkeypatch.setenv("SRT_RETRY_BACKOFF", "0")
    monkeypatch.delenv("SRT_FAULT", raising=False)
    reset_faults()
    yield monkeypatch
    monkeypatch.delenv("SRT_FAULT", raising=False)
    reset_faults()


def _mk(n, seed=0, khi=5):
    r = np.random.default_rng(seed)
    return Table({
        "k": Column.from_numpy(r.integers(0, khi, n).astype(np.int64)),
        "v": Column.from_numpy(r.integers(0, 100, n).astype(np.int64),
                               validity=r.random(n) > 0.2),
    })


def _agg_plan():
    return plan().filter(col("v") > 10).groupby_agg(
        ["k"], [("v", "sum", "s"), ("v", "count", "c")],
        domains={"k": (0, 4)})


def _etl_plan():
    return plan().filter(col("v") > 50).with_columns(w=col("v") * 2)


@pytest.fixture
def session():
    s = QuerySession(max_concurrent=3, register_queued=False)
    yield s
    s.close()


# ---------------------------------------------------------------------------
# 1. scheduler bit-identity
# ---------------------------------------------------------------------------

class TestSchedulerIdentity:
    def test_mixed_concurrent_load_matches_sequential(self, session):
        table = _mk(4096, seed=1)
        batches = [_mk(512, seed=s) for s in range(4)]
        pa, pe = _agg_plan(), _etl_plan()
        oracle_run = pa.run(table).to_pydict()
        oracle_stream = [t.to_pydict()
                         for t in run_plan_stream(pe, list(batches))]

        tickets = []
        for _ in range(4):
            tickets.append(("run", session.submit(pa, table=table)))
            tickets.append(("stream", session.submit(pe, list(batches))))
        for kind, t in tickets:
            got = t.result(timeout=300)
            if kind == "run":
                assert got.to_pydict() == oracle_run
            else:
                assert [x.to_pydict() for x in got] == oracle_stream
            assert t.status == "done" and t.done()

    def test_faulted_neighbor_does_not_disturb_others(self, session,
                                                      faults, metrics_on):
        """One query hits an injected dispatch OOM mid-load; the ladder
        recovers it while every ticket (including the faulted one) stays
        bit-identical to the fault-free sequential oracle."""
        table = _mk(4096, seed=2)
        batches = [_mk(512, seed=10 + s) for s in range(4)]
        pa, pe = _agg_plan(), _etl_plan()
        oracle_run = pa.run(table).to_pydict()
        oracle_stream = [t.to_pydict()
                         for t in run_plan_stream(pe, list(batches))]

        faults.setenv("SRT_FAULT", "oom:dispatch:2")
        reset_faults()
        before = recovery_stats().snapshot()
        tickets = [("stream", session.submit(pe, list(batches)))]
        for _ in range(3):
            tickets.append(("run", session.submit(pa, table=table)))
        for kind, t in tickets:
            got = t.result(timeout=300)
            if kind == "run":
                assert got.to_pydict() == oracle_run
            else:
                assert [x.to_pydict() for x in got] == oracle_stream
        delta = recovery_stats().delta(before)
        assert delta["retries"] >= 1, delta

    def test_submit_validates_inputs(self, session):
        p = _etl_plan()
        with pytest.raises(ValueError, match="exactly one"):
            session.submit(p)
        with pytest.raises(ValueError, match="exactly one"):
            session.submit(p, [_mk(8)], table=_mk(8))
        with pytest.raises(ValueError, match="needs mesh"):
            session.submit(p, dist=object())
        with pytest.raises(ValueError, match="weight"):
            session.submit(p, table=_mk(8), weight=0)

    def test_closed_session_refuses_submissions(self):
        s = QuerySession(max_concurrent=1, register_queued=False)
        s.close()
        with pytest.raises(RuntimeError, match="closed"):
            s.submit(_etl_plan(), table=_mk(8))

    def test_error_delivered_through_ticket(self, session):
        t = session.submit(plan().filter(col("missing") > 0),
                           table=_mk(64))
        with pytest.raises(Exception):
            t.result(timeout=120)
        assert t.status == "error"


# ---------------------------------------------------------------------------
# 2. serve block of QueryMetrics
# ---------------------------------------------------------------------------

class TestServeMetrics:
    def test_ticket_carries_metrics_with_serve_block(self, session,
                                                     metrics_on):
        t = session.submit(_agg_plan(), table=_mk(1024, seed=3))
        t.result(timeout=300)
        assert t.metrics is not None
        d = t.metrics.to_dict()
        assert d["schema_version"] == 11
        assert d["serve"]["policy"] == "rr"
        assert d["serve"]["admission"] in ("admitted", "queued")
        assert d["serve"]["queue_wait_seconds"] >= 0.0

    def test_serve_block_always_present_outside_session(self, metrics_on):
        p, t = _agg_plan(), _mk(1024, seed=4)
        p.run(t)
        from spark_rapids_tpu.obs import last_query_metrics
        d = last_query_metrics().to_dict()
        assert d["serve"] == {"queue_wait_seconds": 0.0, "admission": "",
                              "result_cache": "", "policy": ""}

    def test_queue_wait_isolated_from_run_time(self):
        """A ticket queued behind a busy pool accounts its wait in
        queue_wait_seconds, not in the executor's timings."""
        s = QuerySession(max_concurrent=1, register_queued=False)
        try:
            table = _mk(2048, seed=5)
            p = _agg_plan()
            p.run(table)                      # warm the compile cache
            t1 = s.submit(p, table=table)
            t2 = s.submit(p, table=table)
            t1.result(timeout=300)
            t2.result(timeout=300)
            assert t2.queue_wait_seconds >= 0.0
            assert t2.run_seconds >= 0.0
        finally:
            s.close()


# ---------------------------------------------------------------------------
# 3. admission control
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_over_budget_estimate_rejected_via_ticket(self, monkeypatch):
        monkeypatch.setattr(AdmissionController, "estimate",
                            staticmethod(lambda fp: 1_000_000))
        s = QuerySession(max_concurrent=2, hbm_budget=1000,
                         register_queued=False)
        try:
            t = s.submit(_etl_plan(), table=_mk(64))
            assert t.admission == "rejected" and t.status == "rejected"
            with pytest.raises(AdmissionRejected, match="exceeds"):
                t.result(timeout=5)
        finally:
            s.close()

    def test_fitting_claims_run_and_release(self, monkeypatch, metrics_on):
        monkeypatch.setattr(AdmissionController, "estimate",
                            staticmethod(lambda fp: 600))
        s = QuerySession(max_concurrent=2, hbm_budget=1000,
                        register_queued=False)
        try:
            table = _mk(1024, seed=6)
            p = _agg_plan()
            oracle = p.run(table).to_pydict()
            tickets = [s.submit(p, table=table) for _ in range(3)]
            for t in tickets:
                assert t.result(timeout=300).to_pydict() == oracle
            assert s.admission.claimed_bytes() == 0
        finally:
            s.close()

    def test_acquire_blocks_until_release(self):
        a = AdmissionController(budget=100)
        assert a.acquire(1, 60) is False
        waited = []
        th = threading.Thread(target=lambda: waited.append(a.acquire(2, 60)))
        th.start()
        time.sleep(0.15)
        assert not waited          # still parked: 60 + 60 > 100
        a.release(1)
        th.join(timeout=10)
        assert waited == [True]    # True = it had to HBM-wait
        a.release(2)
        assert a.claimed_bytes() == 0

    def test_cold_fingerprint_estimates_zero(self):
        assert AdmissionController.estimate("") == 0
        assert AdmissionController.estimate("no-such-fp") == 0


# ---------------------------------------------------------------------------
# 4. result cache
# ---------------------------------------------------------------------------

class TestResultCache:
    def test_repeat_submission_hits_bit_identically(self, metrics_on):
        s = QuerySession(max_concurrent=2, result_cache_cap=64 << 20,
                         register_queued=False)
        try:
            table = _mk(1024, seed=7)
            p = _agg_plan()
            t1 = s.submit(p, table=table)
            first = t1.result(timeout=300).to_pydict()
            assert t1.result_cache == "miss"
            t2 = s.submit(p, table=table)
            assert t2.result_cache == "hit"
            assert t2.result(timeout=5).to_pydict() == first
            assert t2.metrics is None       # never touched an executor
            snap = registry().counters_snapshot()
            assert snap.get("serve.result_cache.hit", 0) >= 1
        finally:
            s.close()

    def test_different_input_misses(self):
        s = QuerySession(max_concurrent=2, result_cache_cap=64 << 20,
                         register_queued=False)
        try:
            p = _agg_plan()
            s.submit(p, table=_mk(1024, seed=8)).result(timeout=300)
            t = s.submit(p, table=_mk(1024, seed=9))
            assert t.result_cache == "miss"
            t.result(timeout=300)
        finally:
            s.close()

    def test_iterator_feed_never_cached(self):
        s = QuerySession(max_concurrent=1, result_cache_cap=64 << 20,
                         register_queued=False)
        try:
            batches = [_mk(256, seed=s0) for s0 in range(3)]
            t = s.submit(_etl_plan(), iter(list(batches)))
            t.result(timeout=300)
            assert t.result_cache == ""     # unkeyable, not even a miss
            assert s.cache.stats()["entries"] == 0
        finally:
            s.close()

    def test_input_digest_identity(self):
        a, b = _mk(128, seed=1), _mk(128, seed=1)
        c = _mk(128, seed=2)
        assert input_digest(a) == input_digest(b)
        assert input_digest(a) != input_digest(c)
        assert input_digest([a, c]) == input_digest([b, c])
        assert input_digest(iter([a])) is None

    def test_lru_evicts_by_bytes(self):
        c = ResultCache(cap_bytes=3000)
        t = _mk(128, seed=0)        # ~128*(8+1)*2 bytes of host data
        c.put(("a",), t)
        c.put(("b",), t)
        assert c.stats()["entries"] == 1    # second put evicted the first
        got, hit = c.get(("b",))
        assert hit and got is t
        assert c.get(("a",)) == (None, False)


# ---------------------------------------------------------------------------
# 5. fairness policies
# ---------------------------------------------------------------------------

class TestFairGate:
    def test_lone_waiter_never_blocks(self):
        g = _FairGate("rr")
        g.register(1, 1.0)
        t0 = time.perf_counter()
        for _ in range(10):
            g.turn(1)
        assert time.perf_counter() - t0 < 1.0
        g.unregister(1)

    def _drive(self, gate, turns_by_tid):
        order, lock = [], threading.Lock()

        def spin(tid, n):
            for _ in range(n):
                gate.turn(tid)
                with lock:
                    order.append(tid)
                time.sleep(0.01)    # keep both threads at the gate

        threads = [threading.Thread(target=spin, args=(tid, n))
                   for tid, n in turns_by_tid.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        return order

    def test_rr_alternates_between_contenders(self):
        g = _FairGate("rr")
        g.register(1, 1.0)
        g.register(2, 1.0)
        order = self._drive(g, {1: 6, 2: 6})
        assert len(order) == 12
        # Round-robin: once both contend, no long monopoly runs.
        longest = max(len(list(run)) for _, run in
                      __import__("itertools").groupby(order))
        assert longest <= 3, order

    def test_wfair_favors_heavier_weight(self):
        g = _FairGate("wfair")
        g.register(1, 1.0)
        g.register(2, 4.0)
        order = self._drive(g, {1: 4, 2: 12})
        # The weight-4 query gets ~4 turns per turn of the weight-1
        # query while both contend: its first 8 turns complete before
        # the light query's fourth.
        assert order.index(2) <= 2, order
        assert len(order) == 16

    def test_policy_plumbed_from_config(self, monkeypatch):
        monkeypatch.setenv("SRT_SERVE_POLICY", "wfair")
        s = QuerySession(max_concurrent=1, register_queued=False)
        try:
            assert s.policy == "wfair" and s._gate.policy == "wfair"
        finally:
            s.close()


# ---------------------------------------------------------------------------
# 6. config knobs (jax-free validation is pinned in test_import_hygiene)
# ---------------------------------------------------------------------------

class TestServeKnobs:
    def test_defaults(self, monkeypatch):
        for k in ("SRT_SERVE_MAX_CONCURRENT", "SRT_SERVE_HBM_BUDGET",
                  "SRT_SERVE_POLICY", "SRT_RESULT_CACHE"):
            monkeypatch.delenv(k, raising=False)
        assert config.serve_max_concurrent() == 4
        assert config.serve_hbm_budget() is None
        assert config.serve_policy() == "rr"
        assert config.result_cache_bytes() is None

    def test_valid_values(self, monkeypatch):
        monkeypatch.setenv("SRT_SERVE_MAX_CONCURRENT", "9")
        monkeypatch.setenv("SRT_SERVE_HBM_BUDGET", "123456")
        monkeypatch.setenv("SRT_SERVE_POLICY", "wfair")
        monkeypatch.setenv("SRT_RESULT_CACHE", "1048576")
        assert config.serve_max_concurrent() == 9
        assert config.serve_hbm_budget() == 123456
        assert config.serve_policy() == "wfair"
        assert config.result_cache_bytes() == 1048576

    def test_off_values(self, monkeypatch):
        for off in ("0", "off", "false", "no"):
            monkeypatch.setenv("SRT_SERVE_HBM_BUDGET", off)
            monkeypatch.setenv("SRT_RESULT_CACHE", off)
            assert config.serve_hbm_budget() is None
            assert config.result_cache_bytes() is None

    @pytest.mark.parametrize("knob,bad", [
        ("SRT_SERVE_MAX_CONCURRENT", "0"),
        ("SRT_SERVE_MAX_CONCURRENT", "zebra"),
        ("SRT_SERVE_HBM_BUDGET", "-5"),
        ("SRT_SERVE_HBM_BUDGET", "zebra"),
        ("SRT_SERVE_POLICY", "fifo"),
        ("SRT_RESULT_CACHE", "-1"),
        ("SRT_RESULT_CACHE", "zebra"),
    ])
    def test_invalid_values_raise(self, monkeypatch, knob, bad):
        monkeypatch.setenv(knob, bad)
        accessor = {
            "SRT_SERVE_MAX_CONCURRENT": config.serve_max_concurrent,
            "SRT_SERVE_HBM_BUDGET": config.serve_hbm_budget,
            "SRT_SERVE_POLICY": config.serve_policy,
            "SRT_RESULT_CACHE": config.result_cache_bytes,
        }[knob]
        with pytest.raises(ValueError, match=knob):
            accessor()

    def test_knob_table_lists_serve_rows(self):
        table = config.knob_table()
        for k in ("SRT_SERVE_MAX_CONCURRENT", "SRT_SERVE_HBM_BUDGET",
                  "SRT_SERVE_POLICY", "SRT_RESULT_CACHE"):
            assert k in table


# ---------------------------------------------------------------------------
# 7. compile-cache thread safety (the shared-LRU hammer)
# ---------------------------------------------------------------------------

class TestCompileCacheConcurrency:
    def test_one_key_builds_exactly_once(self, metrics_on):
        from spark_rapids_tpu.exec.compile import _lru_lookup
        cache = OrderedDict()
        builds = []
        barrier = threading.Barrier(8)
        sentinel = object()

        def build():
            builds.append(1)
            time.sleep(0.05)        # widen the double-compile window
            return sentinel

        got = [None] * 8

        def worker(i):
            barrier.wait()
            fn, _ = _lru_lookup(cache, "shared-key", build, "test.hammer")
            got[i] = fn

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(builds) == 1, f"double-compiled {len(builds)}x"
        assert all(fn is sentinel for fn in got)
        snap = registry().counters_snapshot()
        assert snap.get("test.hammer.miss", 0) == 1
        assert snap.get("test.hammer.hit", 0) == 7

    def test_concurrent_inserts_keep_eviction_counts_exact(self,
                                                           metrics_on):
        from spark_rapids_tpu.exec.compile import _lru_lookup
        from spark_rapids_tpu.config import compile_cache_cap
        cache = OrderedDict()
        cap = compile_cache_cap()
        n_keys = cap + 17

        def worker(lo):
            for k in range(lo, n_keys, 4):
                _lru_lookup(cache, ("k", k), lambda: object(),
                            "test.evict")

        threads = [threading.Thread(target=worker, args=(lo,))
                   for lo in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        snap = registry().counters_snapshot()
        assert len(cache) <= cap
        assert snap.get("test.evict.miss", 0) == n_keys
        assert snap.get("test.evict.evictions", 0) == n_keys - len(cache)

    def test_concurrent_queries_share_one_compile(self, metrics_on):
        """End-to-end: many sessions' workers racing the same plan
        signature compile it once (plan.compile_cache.miss == 1 for the
        fresh signature)."""
        s = QuerySession(max_concurrent=4, register_queued=False)
        try:
            table = Table.from_pydict({
                "hammer_k": (np.arange(2048) % 7).astype(np.int64),
                "hammer_v": np.arange(2048, dtype=np.int64),
            })
            p = (plan().filter(col("hammer_v") > 100)
                 .groupby_agg(["hammer_k"],
                              [("hammer_v", "sum", "s")],
                              domains={"hammer_k": (0, 6)}))
            tickets = [s.submit(p, table=table) for _ in range(6)]
            outs = {id(t): t.result(timeout=300).to_pydict()
                    for t in tickets}
            assert len(set(map(str, outs.values()))) == 1
        finally:
            s.close()


# ---------------------------------------------------------------------------
# 8. live-registry concurrency (writers vs scrapes)
# ---------------------------------------------------------------------------

class TestLiveRegistryConcurrency:
    def test_many_writers_never_corrupt_scrapes(self, metrics_on):
        """Live records mutating container state (per-shard dicts,
        recovery rungs) at full speed must never throw inside a
        concurrent snapshot/scrape ("dictionary changed size during
        iteration" is the historical failure)."""
        stop = threading.Event()
        errors = []

        def writer(seed):
            r = np.random.default_rng(seed)
            while not stop.is_set():
                lq = live.start("dist_stream", force=True)
                lq.set_shards(8)
                for _ in range(6):
                    lq.shard_batches_done(8)
                    lq.batch_out(int(r.integers(1, 100)))
                lq.rung(f"retry#{seed}")
                lq.finish()

        def scraper():
            while not stop.is_set():
                try:
                    snap = live.snapshot_all()
                    assert isinstance(snap["in_flight"], list)
                    server.prometheus_text()
                except Exception as e:       # pragma: no cover
                    errors.append(e)
                    return

        writers = [threading.Thread(target=writer, args=(i,))
                   for i in range(4)]
        scrapers = [threading.Thread(target=scraper) for _ in range(3)]
        for t in writers + scrapers:
            t.start()
        time.sleep(1.0)
        stop.set()
        for t in writers + scrapers:
            t.join(timeout=30)
        assert not errors, errors[:3]

    def test_queued_provider_feeds_snapshot(self):
        live.set_queued_provider(
            lambda: [{"query_id": 7, "status": "queued"}])
        try:
            snap = live.snapshot_all()
            assert snap["queued"] == [{"query_id": 7, "status": "queued"}]
        finally:
            live.set_queued_provider(None)
        assert live.snapshot_all()["queued"] == []

    def test_broken_provider_degrades_to_empty(self):
        live.set_queued_provider(lambda: 1 / 0)
        try:
            assert live.snapshot_all()["queued"] == []
        finally:
            live.set_queued_provider(None)

    def test_session_registers_and_unregisters_provider(self, metrics_on):
        s = QuerySession(max_concurrent=1)      # register_queued=True
        try:
            assert live.snapshot_all()["queued"] == []
            text = server.prometheus_text()
            assert "srt_serve_queued_queries 0" in text
        finally:
            s.close()
        # close() must drop the provider so a dead session isn't scraped
        assert live.snapshot_all()["queued"] == []

    def test_top_renders_queued_pane(self):
        from spark_rapids_tpu.obs.__main__ import render_top
        snap = {"pid": 1, "unix_time": 0.0, "in_flight": [], "recent": [],
                "queued": [{"query_id": 9, "mode": "stream",
                            "status": "queued", "queued_seconds": 1.5,
                            "estimate_hbm_bytes": 0, "fingerprint": "ab"}]}
        frame = render_top(snap, source="test")
        assert "queued=1" in frame
        assert "q9" in frame and "stream" in frame
