/* RLE/bit-packed hybrid run parsing — the native half of the Parquet
 * decoder's host pass.
 *
 * The device kernels (spark_rapids_tpu/io/parquet_native.py `_expand_runs`)
 * expand run TABLES; walking run headers is inherently sequential byte work,
 * and null-dense definition-level streams can carry ~100k runs per column
 * chunk, where a Python parse loop costs hundreds of ms.  This single-pass
 * C++ walk fills the run table and (for width-1 streams) popcounts the
 * defined values in the same pass, replacing both `parse_rle_runs` and
 * `count_rle_ones` on the hot path.  The Python implementations remain as
 * the reference/fallback (tests assert parity).
 *
 * Stream grammar (Parquet spec, Encodings.md "RLE/Bit-Packed Hybrid"):
 *   run        := varint-header payload
 *   header & 1 == 0: RLE run of (header >> 1) copies of one
 *                    ceil(width/8)-byte little-endian value
 *   header & 1 == 1: (header >> 1) groups of 8 bit-packed values
 * Truncated bit-packed payloads at the stream tail read as zeros (the
 * Python word-image path pads with zero words; behavior must match).
 */
#include <algorithm>
#include <cstdint>
#include <stdexcept>
#include <string>

#include "error.hpp"

namespace {

struct RunSink {
  int32_t* out_start = nullptr;   // first output index the run covers
  int64_t* count = nullptr;       // values the run encodes
  int32_t* rle_value = nullptr;   // RLE runs only
  int64_t* bp_bit_base = nullptr; // absolute bit offset, bit-packed runs
  uint8_t* is_rle = nullptr;
  int64_t capacity = 0;
};

int popcount8(uint8_t b) {
#if defined(__GNUC__) || defined(__clang__)
  return __builtin_popcount(b);
#else
  int n = 0;
  while (b) { n += b & 1; b >>= 1; }
  return n;
#endif
}

/* One pass over the stream.  With a null sink this only counts runs; with a
 * sink it fills the table.  `ones` (optional) accumulates the number of
 * 1-values for width-1 streams, clamped to num_values. */
int64_t walk(const uint8_t* buf, int64_t len, int32_t width, int64_t num_values,
             const RunSink* sink, int64_t* ones) {
  if (width < 0 || width > 32) throw std::invalid_argument("bit width out of range");
  const int64_t vbytes = (width + 7) / 8;
  int64_t pos = 0, out = 0, runs = 0, one_count = 0;
  while (out < num_values && pos < len) {
    uint64_t header = 0;
    int shift = 0;
    while (true) {
      if (pos >= len) throw std::invalid_argument("RLE varint truncated");
      const uint8_t b = buf[pos++];
      header |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) throw std::invalid_argument("RLE varint overflow");
    }
    if (sink && runs >= sink->capacity)
      throw std::invalid_argument("run table capacity exceeded");
    if (header & 1) {                       // bit-packed groups of 8
      const int64_t groups = static_cast<int64_t>(header >> 1);
      const int64_t cnt = groups * 8;
      if (sink) {
        sink->out_start[runs] = static_cast<int32_t>(out);
        sink->count[runs] = cnt;
        sink->rle_value[runs] = 0;
        sink->bp_bit_base[runs] = pos * 8;
        sink->is_rle[runs] = 0;
      }
      if (ones && width == 1) {
        const int64_t covered = std::min(cnt, num_values - out);
        const int64_t avail_bits = std::max<int64_t>(0, (len - pos) * 8);
        const int64_t usable = std::min(covered, avail_bits);  // tail: zeros
        const int64_t full = usable / 8, rem = usable % 8;
        for (int64_t i = 0; i < full; ++i) one_count += popcount8(buf[pos + i]);
        if (rem) one_count +=
            popcount8(static_cast<uint8_t>(buf[pos + full] & ((1 << rem) - 1)));
      }
      pos += groups * width;
      out += cnt;
    } else {                                // RLE run
      const int64_t cnt = static_cast<int64_t>(header >> 1);
      uint32_t v = 0;
      for (int64_t i = 0; i < vbytes && pos + i < len; ++i)
        v |= static_cast<uint32_t>(buf[pos + i]) << (8 * i);
      if (sink) {
        sink->out_start[runs] = static_cast<int32_t>(out);
        sink->count[runs] = cnt;
        sink->rle_value[runs] = static_cast<int32_t>(v);
        sink->bp_bit_base[runs] = 0;
        sink->is_rle[runs] = 1;
      }
      if (ones && width == 1)
        one_count += std::min(cnt, num_values - out) * (v & 1);
      pos += vbytes;
      out += cnt;
    }
    ++runs;
  }
  if (out < num_values)
    throw std::invalid_argument("RLE stream exhausted at " +
                                std::to_string(out) + "/" +
                                std::to_string(num_values) + " values");
  if (ones) *ones = one_count;
  return runs;
}

}  // namespace

extern "C" {

/* Count the runs in a stream (sizes the arrays for srt_rle_parse_runs). */
int32_t srt_rle_count_runs(const uint8_t* buf, int64_t buf_len,
                           int32_t bit_width, int64_t num_values,
                           int64_t* n_runs) {
  return spark_rapids_tpu::guarded([&] {
    if (!buf && buf_len > 0) throw std::invalid_argument("buf is null");
    if (!n_runs) throw std::invalid_argument("n_runs is null");
    *n_runs = walk(buf, buf_len, bit_width, num_values, nullptr, nullptr);
  });
}

/* Fill the run table (arrays sized >= max_runs) and, for width-1 streams,
 * the defined-value popcount. */
int32_t srt_rle_parse_runs(const uint8_t* buf, int64_t buf_len,
                           int32_t bit_width, int64_t num_values,
                           int64_t max_runs, int32_t* out_start, int64_t* count,
                           int32_t* rle_value, int64_t* bp_bit_base,
                           uint8_t* is_rle, int64_t* n_runs, int64_t* ones) {
  return spark_rapids_tpu::guarded([&] {
    if (!buf && buf_len > 0) throw std::invalid_argument("buf is null");
    if (!out_start || !count || !rle_value || !bp_bit_base || !is_rle || !n_runs)
      throw std::invalid_argument("output array is null");
    RunSink sink{out_start, count, rle_value, bp_bit_base, is_rle, max_runs};
    int64_t ones_local = 0;
    *n_runs = walk(buf, buf_len, bit_width, num_values, &sink,
                   ones ? &ones_local : nullptr);
    if (ones) *ones = ones_local;
  });
}

}  // extern "C"
