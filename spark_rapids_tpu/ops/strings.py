"""String column support: Arrow-style offsets + UTF-8 char buffer.

The reference punts on variable-width types (``CUDF_FAIL("Only fixed width
types are currently supported")`` — row_conversion.cu:515) but its capability
envelope includes cuDF's strings engine (SURVEY.md §2.3).  Representation:

  * ``data``    — ``uint8`` char buffer of all strings concatenated,
  * ``offsets`` — ``int32 (n+1,)``; string *i* is ``data[offsets[i]:offsets[i+1]]``,
  * ``validity``— bool mask as for fixed-width columns (null strings have
                  zero-length payloads).

Design note: per-element byte work is hostile to the VPU's 32-bit lanes, so
compute ops (contains/regex, in :func:`contains` and :mod:`regex`) operate on
the flat char buffer with vectorized comparisons + segment logic rather than
per-string loops.  Gather materializes the output size on host (eager op —
the engine's host-driven model, see :mod:`spark_rapids_tpu.ops`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dtypes import INT32, STRING
from ..column import Column


def strings_from_pylist(values: list[Optional[str]]) -> Column:
    """Build a STRING column from Python strings (``None`` = null)."""
    n = len(values)
    offsets = np.zeros(n + 1, dtype=np.int32)
    mask = np.ones(n, dtype=np.bool_)
    chunks: list[bytes] = []
    pos = 0
    for i, v in enumerate(values):
        if v is None:
            mask[i] = False
        else:
            b = v.encode("utf-8")
            chunks.append(b)
            pos += len(b)
        offsets[i + 1] = pos
    chars = np.frombuffer(b"".join(chunks), dtype=np.uint8).copy()
    validity = None if mask.all() else jnp.asarray(mask)
    return Column(data=jnp.asarray(chars), validity=validity,
                  offsets=jnp.asarray(offsets), dtype=STRING)


def strings_to_pylist(col: Column) -> list[Optional[str]]:
    chars = np.asarray(col.data, dtype=np.uint8)
    offsets = np.asarray(col.offsets)
    mask = None if col.validity is None else np.asarray(col.validity)
    out: list[Optional[str]] = []
    for i in range(len(offsets) - 1):
        if mask is not None and not mask[i]:
            out.append(None)
        else:
            out.append(bytes(chars[offsets[i]:offsets[i + 1]]).decode("utf-8"))
    return out


def concat_columns(cols: list[Column]) -> Column:
    """Concatenate string columns row-wise (axis 0)."""
    offsets_parts = [np.asarray(cols[0].offsets)]
    base = int(offsets_parts[0][-1])
    for c in cols[1:]:
        off = np.asarray(c.offsets)
        offsets_parts.append(off[1:] + base)
        base += int(off[-1])
    offsets = jnp.asarray(np.concatenate(offsets_parts))
    chars = jnp.concatenate([c.data for c in cols])
    validity = None
    if any(c.validity is not None for c in cols):
        validity = jnp.concatenate([c.valid_mask() for c in cols])
    return Column(data=chars, validity=validity, offsets=offsets, dtype=STRING)


def dictionary_encode(col: Column) -> tuple[Column, list[str]]:
    """Factorize strings to INT32 codes whose order matches lexicographic
    (byte-wise) string order, plus the sorted unique values.

    Host-assisted (np.unique over the materialized strings): an eager op in
    the engine's host-driven model.  The codes column preserves validity, so
    sort/groupby/join can operate on codes with unchanged null semantics.
    Device-native string comparison is a planned Pallas optimization.
    """
    chars = np.asarray(col.data, dtype=np.uint8)
    offsets = np.asarray(col.offsets)
    mask = None if col.validity is None else np.asarray(col.validity)
    values = []
    for i in range(len(offsets) - 1):
        if mask is not None and not mask[i]:
            values.append(b"")          # placeholder; row is null
        else:
            values.append(chars[offsets[i]:offsets[i + 1]].tobytes())
    uniq, codes = np.unique(np.array(values, dtype=object), return_inverse=True)
    codes_col = Column(data=jnp.asarray(codes.astype(np.int32)),
                       validity=col.validity, dtype=INT32)
    return codes_col, [u.decode("utf-8") for u in uniq]


def fill_null_strings(col: Column, value: str) -> Column:
    """Replace null rows with ``value`` (cudf ``replace_nulls`` for strings).

    Device formulation: append the replacement as one extra row, then gather
    with indices redirected to it for null rows.
    """
    if col.validity is None:
        return col
    n = col.size
    extra = strings_from_pylist([value])
    widened = concat_columns([col.with_validity(None), extra])
    indices = jnp.where(col.validity, jnp.arange(n, dtype=jnp.int32), n)
    out = strings_gather(widened, indices)
    return out.with_validity(None)


def strings_gather(col: Column, indices) -> Column:
    """Row gather for string columns.

    Eager: the output char-buffer size is data dependent, so it is synced to
    host once and the char copy runs as one vectorized device gather
    (position->source map built from searchsorted over the new offsets).
    """
    indices = jnp.asarray(indices)
    offsets = col.offsets
    starts = jnp.take(offsets, indices)
    lens = jnp.take(offsets, indices + 1) - starts
    new_offsets = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                   jnp.cumsum(lens, dtype=jnp.int32)])
    total = int(new_offsets[-1])  # host sync: output size is data dependent
    if total == 0:
        chars = jnp.zeros(0, jnp.uint8)
    else:
        pos = jnp.arange(total, dtype=jnp.int32)
        row = jnp.searchsorted(new_offsets, pos, side="right") - 1
        src = jnp.take(starts, row) + (pos - jnp.take(new_offsets, row))
        chars = jnp.take(col.data, src)
    validity = None
    if col.validity is not None:
        validity = jnp.take(col.validity, indices)
    return Column(data=chars, validity=validity, offsets=new_offsets, dtype=STRING)
